"""The conservative windowed-PDES loop as a device program.

Reference semantics being reproduced (ref: SURVEY.md §3.2):
- All events inside the execution window [wstart, wend) run, one host's
  events serially in (time, src, seq) order, different hosts in
  parallel (ref: scheduler.c:359-414).
- Then a barrier; the next window starts at the global minimum pending
  event time and spans the minimum cross-host latency ("min time
  jump"), so no cross-host packet can violate causality
  (ref: master.c:450-480).

Mechanics here: the per-round worker pop loop becomes a lax.while_loop
of "micro-steps" — each micro-step pops at most one event per host
(a full [H] vector of events) and runs all handlers as masked batch
updates. The round barrier + min-reduction becomes jnp.min over queue
heads (jax.lax.pmin across shards in shadow_tpu.parallel).
"""

from __future__ import annotations

import inspect
from typing import Callable, Protocol

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime
from shadow_tpu.core.compact import (
    active_indices,
    gather_lanes,
    scatter_lanes,
)
from shadow_tpu.core.events import (
    EmitBuffer,
    EventQueue,
    Outbox,
    Popped,
    apply_emissions,
    emit_kind_bits,
    kind_census,
    pop_earliest,
    route_outbox,
)

I32 = jnp.int32
I64 = jnp.int64

# Default active-lane budget S for the sparse-window fast path: when
# the global census of rows holding any event < wend fits, the window
# fixpoint runs over a compacted [S]-lane view of the Sim instead of
# all H rows (core/compact.py). 256 holds the config-#2-shaped sparse
# TCP workloads (~28 active of 10,240) with a wide margin while staying
# a single nice tile. NetConfig.sparse_lanes overrides; 0 disables.
DEFAULT_SPARSE_LANES = 256


def resolve_sparse_lanes(cfg) -> int:
    """Effective S for a config: cfg.sparse_lanes (None -> the
    default), forced to 0 (off) when it cannot narrow anything."""
    v = getattr(cfg, "sparse_lanes", None)
    if v is None:
        v = DEFAULT_SPARSE_LANES
    v = int(v)
    if v <= 0 or v >= int(cfg.num_hosts):
        return 0
    return v

# step_fn(sim, popped, emitbuf) -> (sim, emitbuf): apply every handler
# for one micro-step's popped events ([H] lanes, masked by popped.valid).
StepFn = Callable


class SimProtocol(Protocol):
    events: EventQueue
    outbox: Outbox


@struct.dataclass
class EngineStats:
    events_processed: jax.Array  # [] i64
    micro_steps: jax.Array       # [] i64
    windows: jax.Array           # [] i64
    # Sparse-window fast path: windows drained at compact [S] width vs
    # windows that ran the full-width body (census exceeded S, or the
    # window held no live lane at all). hit + miss == windows whenever
    # the fast path is enabled; both stay 0 when it is off.
    fastpath_hit: jax.Array      # [] i64
    fastpath_miss: jax.Array     # [] i64

    @staticmethod
    def create() -> "EngineStats":
        z = jnp.zeros((), I64)
        return EngineStats(events_processed=z, micro_steps=z, windows=z,
                           fastpath_hit=z, fastpath_miss=z)

    # Host-side accumulation across attempts/rebuilds. The supervisor
    # carries totals over an escalation boundary, where the pre-trip
    # counters live in a *different* jitted program than the post-heal
    # ones — accumulate as plain ints, never mix traced arrays from
    # two builds.
    def add(self, other: "EngineStats") -> "EngineStats":
        return EngineStats(
            events_processed=self.events_processed + other.events_processed,
            micro_steps=self.micro_steps + other.micro_steps,
            windows=self.windows + other.windows,
            fastpath_hit=self.fastpath_hit + other.fastpath_hit,
            fastpath_miss=self.fastpath_miss + other.fastpath_miss,
        )

    def as_dict(self) -> dict:
        return {
            "events_processed": int(self.events_processed),
            "micro_steps": int(self.micro_steps),
            "windows": int(self.windows),
            "fastpath_hit": int(self.fastpath_hit),
            "fastpath_miss": int(self.fastpath_miss),
        }

    @staticmethod
    def from_dict(d: dict) -> "EngineStats":
        def v(k):
            return jnp.asarray(int(d.get(k, 0)), I64)
        return EngineStats(events_processed=v("events_processed"),
                           micro_steps=v("micro_steps"),
                           windows=v("windows"),
                           fastpath_hit=v("fastpath_hit"),
                           fastpath_miss=v("fastpath_miss"))


# route_fn(sim) -> sim: deliver the outbox into destination queues.
# The default is the single-shard events.route_outbox; the multi-chip
# runner substitutes the all-to-all exchange (shadow_tpu.parallel).
def _default_route(sim):
    q, out = route_outbox(sim.events, sim.outbox)
    return sim.replace(events=q, outbox=out)


# min_fn(x) -> x: reduce a per-shard scalar to the global value. The
# multi-chip runner substitutes lax.pmin over the mesh axis — the
# device form of the executeEvents barrier + min-next-event-time
# reduction (ref: scheduler.c:359-414).
def _identity(x):
    return x


def _takes_census(step_fn) -> bool:
    """Does step_fn accept the per-window kind census? Hand-written
    3-arg step functions (tests, tools) keep working unchanged."""
    try:
        return "census" in inspect.signature(step_fn).parameters
    except (TypeError, ValueError):
        return False


def window_fixpoint(sim, stats: EngineStats, step_fn: StepFn, wend,
                    emit_capacity: int = 4, lane_id=None):
    """Drain every event earlier than wend (local events only — handlers
    may keep emitting same-host events inside the window, e.g. loopback
    +1ns deliveries, ref: network_interface.c:546-554; iterate to
    fixpoint like the reference's pop-until-NULL worker loop). Purely
    shard-local: no collectives, so shards iterate independently.

    When step_fn accepts a `census` kwarg (net.step.make_step_fn), the
    loop carries the window's kind bitmask (events.kind_census): seeded
    from the queue at entry, OR-extended with each micro-step's
    emissions, so handler families whose kinds never occur this window
    are skipped for the whole window instead of re-testing the popped
    vector each micro-step."""
    wend = jnp.asarray(wend, simtime.DTYPE)
    # Zero emission template hoisted out of the loop body: one constant
    # per trace instead of a fresh EmitBuffer.create materialized every
    # micro-step.
    buf0 = EmitBuffer.create(sim.events.num_hosts, emit_capacity,
                             nwords=sim.events.words.shape[-1])
    if getattr(sim.events, "overflow_h", None) is not None:
        # lane isolation (core/lanes.py): emission overflow must carry
        # per-host attribution too, or the queue plane would drift
        # from the scalar latch at apply_emissions
        buf0 = buf0.replace(
            overflow_h=jnp.zeros((sim.events.num_hosts,), I32))
    with_census = _takes_census(step_fn)

    def cond(carry):
        return jnp.any(carry[0].events.min_time() < wend)

    def body(carry):
        if with_census:
            sim, stats, census = carry
        else:
            sim, stats = carry
        q, popped = pop_earliest(sim.events, wend)
        sim = sim.replace(events=q)
        # events_processed counts EXECUTED events: pops the CPU
        # admission gate re-queues (step._cpu_gate) are excluded via
        # the blocked-counter delta, so a repeatedly deferred event
        # still counts exactly once
        blocked0 = (jnp.sum(sim.net.ctr_cpu_blocked)
                    if hasattr(sim, "net") else jnp.zeros((), I64))
        if with_census:
            sim, buf = step_fn(sim, popped, buf0, census=census)
        else:
            sim, buf = step_fn(sim, popped, buf0)
        blocked1 = (jnp.sum(sim.net.ctr_cpu_blocked)
                    if hasattr(sim, "net") else jnp.zeros((), I64))
        if getattr(sim, "causality", None) is not None:
            # event-lineage recorder (telemetry/causality.py): must see
            # the PRE-apply next_seq so each emission's identity hash
            # matches the seq apply_emissions is about to assign. Lazy
            # import like the injection merge below — core must not
            # depend on telemetry at module load. Trace-time no-op
            # (zero compiled ops) when Sim.causality is None.
            from shadow_tpu.telemetry.causality import lineage_update
            sim = lineage_update(sim, popped, buf, lane_id)
        q, out = apply_emissions(sim.events, sim.outbox, buf, lane_id)
        sim = sim.replace(events=q, outbox=out)
        stats = stats.replace(
            events_processed=stats.events_processed
            + jnp.sum(popped.valid, dtype=I64) - (blocked1 - blocked0),
            micro_steps=stats.micro_steps + 1,
        )
        if with_census:
            return sim, stats, census | emit_kind_bits(buf)
        return sim, stats

    if with_census:
        out = jax.lax.while_loop(
            cond, body, (sim, stats, kind_census(sim.events, wend)))
    else:
        out = jax.lax.while_loop(cond, body, (sim, stats))
    return out[0], out[1]


def step_window(sim, stats: EngineStats, step_fn: StepFn, wend,
                emit_capacity: int = 4, lane_id=None,
                route_fn=_default_route, min_fn=_identity,
                bulk_fn=None, fault_fn=None, telem_fn=None, wstart=None,
                sparse_lanes: int = 0, census_fn=None, flow_fn=None,
                adv_attr=None, sentinel_fn=None):
    """One full round: drain the window, then route cross-host events
    staged in the outbox into destination queues. Returns the new global
    minimum pending time (the master's minNextEventTime,
    ref: scheduler.c:634-650).

    When `bulk_fn` is set (net.bulk.make_bulk_fn), eligible hosts'
    whole windows are consumed in one vectorized pass first; the
    fixpoint below then only iterates for leftover hosts (zero
    iterations in the steady state of bulk-friendly workloads).

    `fault_fn` (faults.apply.make_fault_fn) runs first, at the window
    boundary: it rewrites the latency/reliability tables and applies
    crash resets as a pure function of wend, so every event inside the
    window sees the post-fault network. None (the default) leaves the
    body untouched.

    `telem_fn` (telemetry.ring.make_telem_fn) records one per-window
    telemetry record after the drain and BEFORE route_fn — the outbox
    must still hold the window's staged sends (route clears it), and
    queue occupancy is measured at its end-of-drain low-water point.
    `wstart` (the window's start time) is only consumed by telemetry;
    None records a zero-length window.

    `sparse_lanes` > 0 arms the sparse-window fast path: when the
    GLOBAL count of rows holding any event < wend (census_fn reduces
    the shard-local count; lax.psum under shard_map, so every shard
    takes the same branch) fits the budget S and is nonzero, the
    fixpoint runs over a compacted [S]-lane Sim (core/compact.py) and
    scatters back — bit-identical by construction. fault_fn, bulk_fn,
    telemetry and route all run at full width on both branches, so
    fault/checkpoint boundaries are unchanged.

    `adv_attr` — a (cause, edge_a, edge_b, raw_jump) tuple from a
    window-end rule's `.explain` companion (make_wend_fn) — latches
    this window's advance attribution into Sim.causality
    (telemetry/causality.py advance_latch) after the drain. None (the
    default, and always when causality is off) latches nothing."""
    if telem_fn is not None:
        ev0 = stats.events_processed
        ms0 = stats.micro_steps
    # Open-system injection (inject/staging.py) merges FIRST, before
    # the fault rewrite and the bulk/census passes: an injected event
    # with timestamp inside this window must be census-visible and
    # drain exactly like one an application scheduled. Trace-time
    # no-op when Sim.inject is None (the default).
    inject_deltas = None
    if getattr(sim, "inject", None) is not None:
        from shadow_tpu.inject.staging import merge_staged
        sim, inj_w, drop_w, def_w = merge_staged(
            sim, 0 if wstart is None else wstart, wend, lane_id)
        inject_deltas = (inj_w, drop_w, def_w)
    if fault_fn is not None:
        sim = fault_fn(sim, wend)
    # Specialization guard (compile/specialize.py): on a
    # capability-trimmed program, evaluate one cheap predicate per
    # dropped capability right after the fault rewrite (the only
    # in-window writer of the watched tables) — a trip is latched
    # sticky and becomes a fatal health fault at gather time.
    # Trace-time no-op when Sim.guard is None (every full program).
    if getattr(sim, "guard", None) is not None:
        from shadow_tpu.compile.specialize import guard_update
        sim = guard_update(sim, wend)
    if bulk_fn is not None:
        sim, n_bulk = bulk_fn(sim, wend)
        stats = stats.replace(
            events_processed=stats.events_processed + n_bulk)

    if adv_attr is not None and getattr(sim, "causality", None) is None:
        adv_attr = None
    S = int(sparse_lanes) if sparse_lanes else 0
    n_active = None
    if S > 0 or telem_fn is not None or adv_attr is not None:
        active = sim.events.min_time() < jnp.asarray(wend, simtime.DTYPE)
        n_active = jnp.sum(active, dtype=I32)  # shard-LOCAL lane count
    fastpath = jnp.zeros((), jnp.bool_)
    if S > 0:
        n_global = (census_fn or _identity)(n_active)
        # Require at least one live lane: an all-quiet window's
        # full-width fixpoint terminates immediately, so compaction
        # would pay gather+scatter for nothing (bulk-pass workloads
        # consume whole windows before the fixpoint every round).
        hit = (n_global > 0) & (n_global <= S)

        def _full_body(op):
            fsim, fstats = op
            return window_fixpoint(
                fsim, fstats, step_fn, wend, emit_capacity, lane_id)

        if S < sim.events.num_hosts:
            def _compact_body(op):
                fsim, fstats = op
                idx = active_indices(active, S)
                lane_c = (idx if lane_id is None
                          else jnp.asarray(lane_id, I32)[idx])
                csim = gather_lanes(fsim, idx)
                csim, fstats = window_fixpoint(
                    csim, fstats, step_fn, wend, emit_capacity, lane_c)
                return scatter_lanes(fsim, csim, idx), fstats

            sim, stats = jax.lax.cond(hit, _compact_body, _full_body,
                                      (sim, stats))
        else:
            # This (shard-local) width is already <= S: there is
            # nothing to narrow, so run full width unconditionally —
            # but keep the GLOBAL hit/miss accounting below, so the
            # decision record is shard-count-invariant (a 64-host
            # serial run compacts to S=16 while its 8-shard twin runs
            # 8-wide shards as-is; both must count the same hits).
            sim, stats = _full_body((sim, stats))
        stats = stats.replace(
            fastpath_hit=stats.fastpath_hit + hit.astype(I64),
            fastpath_miss=stats.fastpath_miss + (~hit).astype(I64))
        fastpath = hit
    else:
        sim, stats = window_fixpoint(sim, stats, step_fn, wend,
                                     emit_capacity, lane_id)
    if telem_fn is not None:
        # inject_deltas is passed only when injection is live, so
        # hand-written telem_fns without the kwarg keep working
        kw = ({"inject_deltas": inject_deltas}
              if inject_deltas is not None else {})
        sim = telem_fn(sim, wend if wstart is None else wstart, wend,
                       stats.events_processed - ev0,
                       stats.micro_steps - ms0,
                       n_active, fastpath, **kw)
    if flow_fn is not None:
        # flow flight-recorder (telemetry/flows.py): samples the
        # staged outbox, so it must also run BEFORE route_fn clears it
        sim = flow_fn(sim, wend if wstart is None else wstart, wend)
    if adv_attr is not None:
        # window-advance attribution (telemetry/causality.py): the
        # census reduction makes the latched active count GLOBAL, so
        # the replicated [W] plane stays shard-identical
        from shadow_tpu.telemetry.causality import advance_latch
        cause, edge_a, edge_b, raw_jump = adv_attr
        sim = advance_latch(
            sim, wend if wstart is None else wstart, wend,
            cause, edge_a, edge_b, raw_jump,
            (census_fn or _identity)(n_active))
    sim = route_fn(sim)
    if getattr(sim, "lanes", None) is not None:
        # lane barrier (core/lanes.py): reduce the per-host latch
        # planes per lane, trip + freeze sick lanes, and — when the
        # program is resident (Sim.admission, fleet/admission.py) —
        # enforce lease horizons and keep FREE lanes empty, all at
        # this barrier. After the route so this window's deliveries
        # are attributed (and a delivery past a lease edge is flushed
        # the window it arrives), before the min so frozen/expired
        # lanes stop holding the global advance back.
        from shadow_tpu.core.lanes import window_update
        sim = window_update(sim, wend)
    if sentinel_fn is not None:
        # cross-shard integrity sentinel (parallel/elastic.py): digest
        # the replicated leaves AFTER the route barrier restored the
        # replication invariant (_replicate_scalars runs inside
        # route_fn) and the lane barrier settled — any pmax-vs-pmin
        # digest disagreement here is silent divergence, latched
        # sticky. Trace-time no-op when Sim.sentinel is None.
        sim = sentinel_fn(sim, wend)
    stats = stats.replace(windows=stats.windows + 1)
    local_min = jnp.min(sim.events.min_time())
    if getattr(sim, "inject", None) is not None:
        # staged-but-unmerged events join the advance rule: a quiet
        # queue must still jump to the next injected timestamp
        # instead of declaring the run over
        from shadow_tpu.inject.staging import staged_pending_min
        local_min = jnp.minimum(local_min,
                                staged_pending_min(sim.inject))
    next_min = min_fn(local_min)
    return sim, stats, next_min


def make_wend_fn(*, min_jump: int, end_time: int,
                 pair_mask=None, fault_times=None, table_fn=None):
    """Build the window-end rule ``wend = wend_fn(sim, wstart)`` shared
    by every chunked runner.

    Static (``pair_mask`` is None): the reference's rule — ``wstart +
    min_jump`` clamped to ``end_time + 1`` (ref: master.c:450-480),
    with the same positive floor as `run`.

    Adaptive (``pair_mask`` is a [V,V] bool array of host-bearing
    vertex pairs, see net.build.adaptive_jump_spec): advance by the
    CURRENT minimum cross-host path latency read from
    ``sim.net.latency_ns`` — the reference's lazily-recomputed min time
    jump (topology.c:1374-1385) done live, so fault plans that raise
    latencies let windows grow. Three guards keep it conservative:

    - floor at the static ``min_jump``: plan validation rejects
      negative latency deltas (faults/plan.py), so the live tables are
      always >= boot and the floor only matters for links a fault
      disabled entirely;
    - links with ``reliability == 0`` (downed by LINK_DOWN/PARTITION)
      do not constrain the jump — no packet crosses them — which is
      only sound together with:
    - ``fault_times`` (the plan's record times): wend never crosses the
      next record > wstart, so a LINK_UP/HEAL cannot revive a short
      link in the middle of a window sized without it, and every
      record materializes at a window boundary exactly (seed_wakeups
      pins a pending event at each record time, so wstart reaches it);
    - ``table_fn`` (faults.apply.make_table_fn, required whenever a
      plan is installed): the window is sized from the plan-replayed
      tables at ``wstart + 1`` — records at exactly wstart applied —
      NOT from the live ``sim.net`` tables. step_window only rewrites
      the live tables AFTER the span was chosen, so a window starting
      exactly at a latency-restore record would otherwise be sized by
      the stale (still-spiked) table: packets flying at the restored
      short latency then land inside the over-long window, out of
      conservative order.

    The returned rule carries an ``explain`` companion —
    ``wend_fn.explain(sim, wstart) -> (wend, cause, edge_a, edge_b,
    raw_jump)`` — computing the SAME wend plus its advance attribution
    (telemetry/causality.py CAUSE_* codes): which constraint bound the
    window, the binding latency-table vertex pair under adaptive jump
    (-1 otherwise), and the available lookahead before the record/end
    clamps. Clamps are attributed in a fixed priority order (floor ->
    record -> end) and only a clamp that STRICTLY lowers wend takes
    the cause, so ties are deterministic on every path.
    """
    from shadow_tpu.telemetry.causality import (
        CAUSE_ADAPTIVE_EDGE,
        CAUSE_END_TIME,
        CAUSE_FAULT_RECORD,
        CAUSE_MIN_JUMP,
    )
    if isinstance(min_jump, int) and min_jump <= 0:
        raise ValueError(f"min_jump must be positive, got {min_jump}")
    end = jnp.asarray(int(end_time), simtime.DTYPE)
    jump0 = jnp.maximum(jnp.asarray(min_jump, simtime.DTYPE), 1)
    ft_c = None
    if fault_times is not None and len(fault_times):
        ft_c = jnp.asarray(fault_times, simtime.DTYPE)
    neg1 = jnp.asarray(-1, I32)
    if pair_mask is None:
        def wend_fn(sim, wstart):
            wend = jnp.minimum(wstart + jump0, end + 1)
            # Static windows take the same clamp as adaptive ones:
            # without it a window crossing a record would apply the
            # fault EARLY (step_window rewrites for records < wend),
            # smearing fault timing by up to min_jump and making the
            # final state depend on where window boundaries happen to
            # fall. With it every record lands at a boundary exactly,
            # in every driver, under every partitioning.
            if ft_c is not None:
                nxt = jnp.min(jnp.where(ft_c > wstart, ft_c,
                                        simtime.INVALID))
                wend = jnp.minimum(wend, nxt)
            return wend

        def explain(sim, wstart):
            wend = wstart + jump0
            cause = jnp.asarray(CAUSE_MIN_JUMP, I32)
            if ft_c is not None:
                nxt = jnp.min(jnp.where(ft_c > wstart, ft_c,
                                        simtime.INVALID))
                cause = jnp.where(nxt < wend, CAUSE_FAULT_RECORD, cause)
                wend = jnp.minimum(wend, nxt)
            cause = jnp.where(end + 1 < wend, CAUSE_END_TIME, cause)
            wend = jnp.minimum(wend, end + 1)
            return wend, cause, neg1, neg1, jump0

        wend_fn.explain = explain
        return wend_fn
    mask_c = jnp.asarray(pair_mask, bool)
    V = int(mask_c.shape[0])

    def _adaptive_jump(sim, wstart):
        if table_fn is not None:
            lat, rel = table_fn(wstart + 1)
        else:
            lat, rel = sim.net.latency_ns, sim.net.reliability
        lat = jnp.asarray(lat, simtime.DTYPE)
        live = mask_c & (rel > 0)
        return jnp.where(live, lat, simtime.INVALID)

    def wend_fn(sim, wstart):
        jump = jnp.min(_adaptive_jump(sim, wstart))
        # Tables are replicated across shards (REPLICATED_FIELDS), so
        # this min is shard-invariant without a collective. The upper
        # clip keeps wstart + jump from overflowing i64 when no pair
        # constrains the window at all (mask empty or every masked
        # link down): any span is conservative then, and end + 1 ends
        # the run in one window.
        jump = jnp.clip(jump, jump0, end + 1)
        wend = wstart + jump
        if ft_c is not None:
            nxt = jnp.min(jnp.where(ft_c > wstart, ft_c, simtime.INVALID))
            wend = jnp.minimum(wend, nxt)
        return jnp.minimum(wend, end + 1)

    def explain(sim, wstart):
        masked = _adaptive_jump(sim, wstart)
        flat = masked.reshape(-1)
        k = jnp.argmin(flat)            # first min: deterministic edge
        jump_u = flat[k]
        jump = jnp.clip(jump_u, jump0, end + 1)
        # at (or below) the floor the EDGE is not the constraint
        adaptive = jump_u > jump0
        cause = jnp.where(adaptive, CAUSE_ADAPTIVE_EDGE,
                          CAUSE_MIN_JUMP).astype(I32)
        edge_a = jnp.where(adaptive, (k // V).astype(I32), neg1)
        edge_b = jnp.where(adaptive, (k % V).astype(I32), neg1)
        wend = wstart + jump
        if ft_c is not None:
            nxt = jnp.min(jnp.where(ft_c > wstart, ft_c, simtime.INVALID))
            cause = jnp.where(nxt < wend, CAUSE_FAULT_RECORD, cause)
            wend = jnp.minimum(wend, nxt)
        cause = jnp.where(end + 1 < wend, CAUSE_END_TIME, cause)
        wend = jnp.minimum(wend, end + 1)
        return wend, cause, edge_a, edge_b, jump

    wend_fn.explain = explain
    return wend_fn


def make_chunk_body(step_fn: StepFn, *, end_time: int, wend_fn,
                    chunk_windows: int, emit_capacity: int = 4,
                    lane_fn=None, route_fn=_default_route,
                    min_fn=_identity, bulk_fn=None, fault_fn=None,
                    telem_fn=None, sparse_lanes: int = 0,
                    census_fn=None, flow_fn=None, sentinel_fn=None):
    """Build ``chunk(sim, stats, wstart) -> (sim, stats, wstart')``:
    up to `chunk_windows` full window rounds as ONE device program (a
    lax.fori_loop over step_window), so host-driven loops pay one
    dispatch per K windows instead of per window.

    The window sequence is identical to `run`'s while_loop: each round
    computes ``wend = wend_fn(sim, wstart)`` (make_wend_fn) and
    advances to the min_fn-reduced next pending time. The loop is a
    lax.while_loop over ``(i < chunk_windows) & (wstart <= end)`` —
    the same shape as `run`, just bounded — so a round whose wstart
    already passed end_time (or an empty queue: next_min ==
    simtime.INVALID > end) exits immediately and a whole chunk
    dispatched past the end returns its carry unchanged. Callers may
    therefore keep one speculative chunk in flight and only
    synchronize on the *previous* chunk's wstart. (A fori_loop with a
    per-window lax.cond no-op guard is the obvious alternative; it
    shuttles the entire sim tuple through a conditional every window,
    which on some backends costs more than the window itself.)

    ``lane_fn(sim)`` supplies step_window's lane_id (None -> identity
    lanes); it is evaluated once per chunk on the carried sim — lane
    identity is static for a run. fault_fn/telem_fn/bulk_fn and the
    sparse fast path all run INSIDE the loop, per window, exactly as
    in the per-window host loop. The trip condition reads only
    replicated values (wstart is min_fn-reduced), so shards stay in
    lockstep exactly as in `run`."""
    if int(chunk_windows) < 1:
        raise ValueError(
            f"chunk_windows must be >= 1, got {chunk_windows}")
    end = jnp.asarray(int(end_time), simtime.DTYPE)
    K = int(chunk_windows)

    def chunk(sim, stats, wstart):
        wstart = jnp.asarray(wstart, simtime.DTYPE)
        lane = None if lane_fn is None else lane_fn(sim)
        # Streamed injection: no window may start at (or cross) the
        # staging horizon — the first trace event the host has NOT
        # yet staged — or that event would merge late once staged.
        # The chunk hands control back to the host there; the feeder
        # refills, horizon advances, and the loop is redispatched.
        # INVALID horizon (no feeder / whole trace staged) never
        # binds, so closed-loop runs are untouched.
        streamed = getattr(sim, "inject", None) is not None

        def cond(carry):
            i, _sim, _stats, ws = carry
            ok = (i < K) & (ws <= end)
            if streamed:
                ok = ok & (ws < _sim.inject.horizon)
            return ok

        explain = getattr(wend_fn, "explain", None)
        tracing = (getattr(sim, "causality", None) is not None
                   and explain is not None)

        def body(carry):
            i, sim, stats, ws = carry
            adv = None
            if tracing:
                from shadow_tpu.telemetry.causality import (
                    CAUSE_INJECT_HORIZON,
                )
                wend, cause, edge_a, edge_b, raw = explain(sim, ws)
                if streamed:
                    cause = jnp.where(sim.inject.horizon < wend,
                                      CAUSE_INJECT_HORIZON, cause)
                    wend = jnp.minimum(wend, sim.inject.horizon)
                adv = (cause, edge_a, edge_b, raw)
            else:
                wend = wend_fn(sim, ws)
                if streamed:
                    wend = jnp.minimum(wend, sim.inject.horizon)
            sim, stats, next_min = step_window(
                sim, stats, step_fn, wend,
                emit_capacity=emit_capacity, lane_id=lane,
                route_fn=route_fn, min_fn=min_fn, bulk_fn=bulk_fn,
                fault_fn=fault_fn, telem_fn=telem_fn, wstart=ws,
                sparse_lanes=sparse_lanes, census_fn=census_fn,
                flow_fn=flow_fn, adv_attr=adv, sentinel_fn=sentinel_fn)
            return i + 1, sim, stats, next_min

        _, sim, stats, wstart = jax.lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), sim, stats, wstart))
        return sim, stats, wstart

    return chunk


def run(
    sim,
    step_fn: StepFn,
    *,
    end_time: int,
    min_jump: int,
    start_time: int = 0,
    emit_capacity: int = 4,
    lane_id=None,
    route_fn=_default_route,
    min_fn=_identity,
    bulk_fn=None,
    fault_fn=None,
    telem_fn=None,
    sparse_lanes: int = 0,
    census_fn=None,
    fault_times=None,
    flow_fn=None,
    sentinel_fn=None,
):
    """Run the whole simulation as one device program (fast path for
    on-device application models). Window advance rule is the
    reference's: newStart = minNextEventTime, newEnd = newStart +
    minJump, clamped to end (ref: master.c:450-480). min_jump is the
    precomputed minimum cross-host path latency with the same 10ms
    floor the reference applies when unknown (ref: master.c:133-159).
    `fault_times` (the installed plan's record times) additionally
    clamps each window at the next record > wstart — the same rule as
    make_wend_fn — so faults take effect exactly at their timestamps
    instead of up to min_jump early when a window would cross one.

    Under shard_map, route_fn carries the only collectives (all-to-all
    + the pmin in min_fn), both outside the inner fixpoint loop, so the
    outer window loop runs in lockstep across shards while each shard
    drains its own window at its own pace.
    """
    if isinstance(min_jump, int) and min_jump <= 0:
        raise ValueError(f"min_jump must be positive, got {min_jump}")
    end_time = jnp.asarray(end_time, simtime.DTYPE)
    # A non-positive window length would spin the outer loop forever;
    # clamp like the reference's runahead floor (master.c:133-159).
    min_jump = jnp.maximum(jnp.asarray(min_jump, simtime.DTYPE), 1)
    ft_c = None
    if fault_times is not None and len(fault_times):
        ft_c = jnp.asarray(fault_times, simtime.DTYPE)
    stats = EngineStats.create()

    def cond(carry):
        sim, stats, wstart = carry
        return wstart <= end_time

    tracing = getattr(sim, "causality", None) is not None

    def body(carry):
        sim, stats, wstart = carry
        adv = None
        if tracing:
            # same attribution rule (and clamp-priority order) as the
            # static make_wend_fn explain — the whole-run program's
            # advance plane must be bit-identical to the chunked
            # drivers' (telemetry/causality.py)
            from shadow_tpu.telemetry.causality import (
                CAUSE_END_TIME,
                CAUSE_FAULT_RECORD,
                CAUSE_MIN_JUMP,
            )
            wend = wstart + min_jump
            cause = jnp.asarray(CAUSE_MIN_JUMP, I32)
            if ft_c is not None:
                nxt = jnp.min(jnp.where(ft_c > wstart, ft_c,
                                        simtime.INVALID))
                cause = jnp.where(nxt < wend, CAUSE_FAULT_RECORD, cause)
                wend = jnp.minimum(wend, nxt)
            cause = jnp.where(end_time + 1 < wend, CAUSE_END_TIME,
                              cause)
            wend = jnp.minimum(wend, end_time + 1)
            neg1 = jnp.asarray(-1, I32)
            adv = (cause, neg1, neg1, min_jump)
        else:
            wend = jnp.minimum(wstart + min_jump, end_time + 1)
            if ft_c is not None:
                nxt = jnp.min(jnp.where(ft_c > wstart, ft_c,
                                        simtime.INVALID))
                wend = jnp.minimum(wend, nxt)
        sim, stats, next_min = step_window(
            sim, stats, step_fn, wend, emit_capacity, lane_id,
            route_fn, min_fn, bulk_fn, fault_fn, telem_fn, wstart,
            sparse_lanes, census_fn, flow_fn, adv, sentinel_fn,
        )
        return sim, stats, next_min

    local_min = jnp.min(sim.events.min_time())
    if getattr(sim, "inject", None) is not None:
        # Whole-run programs never return to the host, so the feeder
        # must have staged the ENTIRE trace (Feeder.fill_all; horizon
        # stays INVALID). The staged minimum joins the first-window
        # rule so a trace-only run (empty queue) still starts.
        from shadow_tpu.inject.staging import staged_pending_min
        local_min = jnp.minimum(local_min,
                                staged_pending_min(sim.inject))
    first = jnp.maximum(
        min_fn(local_min),
        jnp.asarray(start_time, simtime.DTYPE),
    )
    sim, stats, _ = jax.lax.while_loop(cond, body, (sim, stats, first))
    return sim, stats
