"""Device-resident event queues.

The reference keeps one locked binary min-heap of events per host
(ref: priority_queue.c:17-40, scheduler_policy_host_single.c:20-33) with
the deterministic total order key (time, dstHostID, srcHostID,
perSourceSequence) (ref: event.c:110-153). Here each host owns one row
of fixed-capacity struct-of-arrays tensors; "pop" is a masked
lexicographic argmin over the row, so ordering is bit-identical to the
reference's heap order for any thread/shard count.

Cross-host events never target the current window (every inter-host
path latency >= the window length, which is the min path latency — ref:
master.c:450-480, scheduler_policy_host_single.c:171-184), so sends are
staged per *source* host in an Outbox (collision-free writes) and routed
to destination rows once per window by a sort-based shuffle. On a
sharded mesh that shuffle is the all-to-all exchange point.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime

I32 = jnp.int32
# Number of generic int32 payload words carried by every event. Wide
# enough for a simulated TCP header (ref: packet.h:66-86): src/dst
# ports, seq, ack, flags, window, timestamp, ts-echo, a 3-range
# selective-ack list, payload ref+len, plus the delivery-status audit
# word (packetfmt.W_STATUS; ref: packet.h:18-40).
NWORDS = 17
# Narrow width for configs without TCP state: just the
# protocol-independent words (packetfmt indices 0..5). Every pass of
# the window loop moves the whole words tensor, so UDP-only workloads
# carrying 6 instead of 17 words nearly halve per-event bytes.
# Producers may build NWORDS-wide rows; sinks fit_words() them to the
# allocated width (trailing TCP words are zero in non-TCP configs).
NWORDS_BASE = 6


def fit_words(words: jax.Array, width: int) -> jax.Array:
    """Pad (zeros) or slice the trailing word dim to `width`. Slicing
    is only sound when the dropped columns are zero — guaranteed
    because narrow queues exist only in non-TCP configs, where nothing
    writes the TCP header words."""
    w = words.shape[-1]
    if w == width:
        return words
    if w > width:
        return words[..., :width]
    pad = [(0, 0)] * (words.ndim - 1) + [(0, width - w)]
    return jnp.pad(words, pad)


class EventKind:
    """Builtin event kinds. The reference's Task is an arbitrary C
    closure (ref: task.c:13-21); on device we enumerate handler ids.
    Kinds >= USER are claimed by application models."""

    NONE = 0
    PACKET = 1          # packet arrival at dst host's upstream router
    PACKET_LOCAL = 2    # loopback delivery (ref: network_interface.c:546-554)
    TIMER = 3           # timerfd expiration (ref: timer.c)
    PROC_START = 4      # process start (ref: process.c:1326-1360)
    PROC_STOP = 5
    NIC_RECV = 6        # rx token-bucket drain retry (ref: network_interface.c:421-455)
    NIC_SEND = 7        # tx token-bucket drain retry
    TCP_RTX_TIMER = 8   # TCP retransmission timeout
    TCP_CLOSE_TIMER = 9  # TIMEWAIT 60s close timer (ref: tcp.c:604-699)
    TCP_DACK_TIMER = 10  # delayed-ACK timer
    HEARTBEAT = 11      # tracker heartbeat (ref: tracker.c:607)
    TCP_FLUSH = 12      # same-time flush continuation: one coalesced
                        # ACK can admit far more segments than one
                        # micro-step packetizes; the chain unwinds in
                        # the window fixpoint (ref: _tcp_flush's while
                        # loop, tcp.c:1121-...)
    FAULT_WAKEUP = 13   # pending no-op seeded at each fault-plan time
                        # so a window boundary lands at (or before) the
                        # fault even in sparse workloads (faults/apply)
    USER = 16


@struct.dataclass
class EventQueue:
    """Per-host event store: row h = host h's pending events.

    time == simtime.INVALID marks an empty slot. `seq` is the
    per-*source*-host sequence number that makes the total order
    deterministic (ref: event.c:29-35,110-153)."""

    time: jax.Array   # [H, K] i64
    kind: jax.Array   # [H, K] i32
    src: jax.Array    # [H, K] i32
    seq: jax.Array    # [H, K] i32
    words: jax.Array  # [H, K, NWORDS] i32
    # Per-source-host monotonic event id (ref: host_getNewEventID).
    next_seq: jax.Array   # [H] i32
    # Sticky count of events dropped because a row was full. The host
    # side checks this between windows and re-runs with a larger K
    # (the reference never drops events; neither do we silently).
    overflow: jax.Array   # [] i32
    # Optional per-host attribution plane for the same latch ([H] i32),
    # attached by core/lanes.attach for lane-isolated ensemble runs.
    # None (the default) contributes no pytree leaves, so programs and
    # checkpoints built without lane isolation stay byte-identical
    # (same contract as Sim.telem / Sim.inject). Invariant when
    # attached: overflow == sum(overflow_h) — every bump site below
    # updates both, attributing drops to the DESTINATION row.
    overflow_h: Any = None

    @property
    def num_hosts(self) -> int:
        return self.time.shape[0]

    @property
    def capacity(self) -> int:
        return self.time.shape[1]

    @staticmethod
    def create(num_hosts: int, capacity: int,
               nwords: int = NWORDS) -> "EventQueue":
        return EventQueue(
            time=jnp.full((num_hosts, capacity), simtime.INVALID, simtime.DTYPE),
            kind=jnp.zeros((num_hosts, capacity), I32),
            src=jnp.zeros((num_hosts, capacity), I32),
            seq=jnp.zeros((num_hosts, capacity), I32),
            words=jnp.zeros((num_hosts, capacity, nwords), I32),
            next_seq=jnp.zeros((num_hosts,), I32),
            overflow=jnp.zeros((), I32),
        )

    def valid(self) -> jax.Array:
        return self.time != simtime.INVALID

    def fill_count(self) -> jax.Array:
        """[H] number of occupied slots per host row."""
        return jnp.sum(self.valid(), axis=1, dtype=I32)

    def occupancy(self) -> tuple:
        """(min, max, sum) of per-host occupied slots — the telemetry
        ring's queue-occupancy probe (shard-local values; the telem
        hook reduces them across shards at the window barrier)."""
        fill = self.fill_count()
        return (jnp.min(fill), jnp.max(fill),
                jnp.sum(fill, dtype=simtime.DTYPE))

    def min_time(self) -> jax.Array:
        """[H] earliest pending event time per host (INVALID if none).
        The per-shard reduction of this is the conservative barrier's
        min-next-event-time (ref: scheduler.c:393-398)."""
        return jnp.min(self.time, axis=1)


class Popped(NamedTuple):
    """One popped event per host lane ([H]-shaped; valid=False lanes
    hold garbage and must be masked by handlers)."""

    valid: jax.Array  # [H] bool
    time: jax.Array   # [H] i64
    kind: jax.Array   # [H] i32
    src: jax.Array    # [H] i32
    seq: jax.Array    # [H] i32
    words: jax.Array  # [H, NWORDS] i32

    def word(self, i: int) -> jax.Array:
        return self.words[:, i]


def _onehot(mask: jax.Array, slot: jax.Array, width: int) -> jax.Array:
    """[H] masked slot -> [H, width] one-hot row selector. Writes via
    jnp.where(onehot, ...) instead of scatter: XLA fuses selects, while
    each scatter is a separate slow-to-compile op (this path runs every
    micro-step)."""
    return mask[:, None] & (jnp.arange(width)[None, :] == slot[:, None])


def _put(arr: jax.Array, sel: jax.Array, value) -> jax.Array:
    """Masked row write arr[H,W] (or [H,W,NWORDS] when value is
    [H,NWORDS]) under a one-hot selector."""
    value = jnp.asarray(value, arr.dtype)
    if arr.ndim == 3:
        return jnp.where(sel[:, :, None], value[:, None, :], arr)
    v = value[:, None] if value.ndim == 1 else value
    return jnp.where(sel, v, arr)


def _tie_key(src: jax.Array, seq: jax.Array) -> jax.Array:
    """Pack (srcHost, perSourceSeq) into one sortable i64 — the 3rd and
    4th keys of the reference's event order (ref: event.c:137-152).
    (dstHost, the 2nd key, is the row index here.)"""
    return (src.astype(jnp.int64) << 32) | seq.astype(jnp.uint32).astype(jnp.int64)


def pop_earliest(q: EventQueue, horizon) -> tuple[EventQueue, Popped]:
    """Pop each host's earliest event with time < horizon.

    This is the device analog of one scheduler_pop round across all
    hosts at once (ref: scheduler.c:359-377): one host's events stay
    serial (one pop per micro-step), different hosts pop in parallel.
    (Whole-window batching lives in net/bulk.py instead.)
    """
    t = q.time  # [H, K]
    # Lexicographic argmin over (time, src, seq) within each row.
    tmin = jnp.min(t, axis=1, keepdims=True)              # [H,1]
    is_tmin = t == tmin
    tie = jnp.where(is_tmin, _tie_key(q.src, q.seq), jnp.iinfo(jnp.int64).max)
    idx = jnp.argmin(tie, axis=1)                          # [H]
    rows = jnp.arange(q.num_hosts)
    ptime = t[rows, idx]
    valid = ptime < jnp.asarray(horizon, simtime.DTYPE)
    popped = Popped(
        valid=valid,
        time=ptime,
        kind=q.kind[rows, idx],
        src=q.src[rows, idx],
        seq=q.seq[rows, idx],
        words=q.words[rows, idx],
    )
    # Clear popped slots (only where valid).
    sel = _onehot(valid, idx, q.capacity)
    new_time = jnp.where(sel, simtime.INVALID, q.time)
    return q.replace(time=new_time), popped


def push_rows(
    q: EventQueue,
    mask: jax.Array,   # [H] bool — which rows receive an event
    time: jax.Array,   # [H] i64
    kind: jax.Array,   # [H] i32
    src: jax.Array,    # [H] i32
    seq: jax.Array,    # [H] i32
    words: jax.Array,  # [H, NWORDS] i32
) -> EventQueue:
    """Insert one event into each masked host row (first free slot)."""
    words = fit_words(words, q.words.shape[-1])
    free = ~q.valid()                                     # [H, K]
    has_free = jnp.any(free, axis=1)
    slot = jnp.argmax(free, axis=1)                       # first free slot
    ok = mask & has_free
    sel = _onehot(ok, slot, q.capacity)
    q = q.replace(
        time=_put(q.time, sel, time),
        kind=_put(q.kind, sel, kind),
        src=_put(q.src, sel, src),
        seq=_put(q.seq, sel, seq),
        words=_put(q.words, sel, words),
        overflow=q.overflow + jnp.sum(mask & ~has_free, dtype=I32),
    )
    if q.overflow_h is not None:
        q = q.replace(
            overflow_h=q.overflow_h + (mask & ~has_free).astype(I32))
    return q


@struct.dataclass
class Outbox:
    """Cross-host events staged per *source* host, so writes are
    collision-free inside a micro-step. Routed to destination rows once
    per window by route_outbox() (the shard-exchange point;
    ref: worker_sendPacket, worker.c:243-304 is the only place events
    cross hosts)."""

    dst: jax.Array    # [H, M] i32  (-1 = empty)
    time: jax.Array   # [H, M] i64
    kind: jax.Array   # [H, M] i32
    src: jax.Array    # [H, M] i32
    seq: jax.Array    # [H, M] i32
    words: jax.Array  # [H, M, NWORDS] i32
    count: jax.Array  # [H] i32
    overflow: jax.Array  # [] i32
    # narrow-tier telemetry (VERDICT r4 #10): how often the route /
    # exchange took the narrow vs full-width branch, and the largest
    # occupancy the gate ever measured — a new workload that silently
    # overflows the tier shows up as narrow_miss > 0 instead of an
    # invisible slow branch. Running totals survive clear_outbox.
    narrow_hit: jax.Array   # [] i32 windows on the narrow branch
    narrow_miss: jax.Array  # [] i32 windows forced to full width
    max_occupied: jax.Array  # [] i32 max occupancy the gate measured
    # sparse-window layer 3: windows whose outbox staged nothing, so
    # route_outbox skipped the insert pipeline entirely (and, sharded,
    # the all-to-all's cheap branch). Running total, like the narrow
    # counters.
    route_elided: jax.Array  # [] i32 windows with an empty exchange
    # Optional per-SOURCE-host overflow attribution ([H] i32) — same
    # opt-in / invariant contract as EventQueue.overflow_h.
    overflow_h: Any = None

    @property
    def num_hosts(self) -> int:
        return self.dst.shape[0]

    @property
    def capacity(self) -> int:
        return self.dst.shape[1]

    def occupied(self) -> jax.Array:
        """[H, M] bool: slots holding a staged entry (dst >= 0).
        `count` alone cannot answer this — the TCP bulk pass stages at
        sparse columns, so consumers (route, telemetry) must test the
        dst plane."""
        return self.dst >= 0

    @staticmethod
    def create(num_hosts: int, capacity: int,
               nwords: int = NWORDS) -> "Outbox":
        return Outbox(
            dst=jnp.full((num_hosts, capacity), -1, I32),
            time=jnp.full((num_hosts, capacity), simtime.INVALID, simtime.DTYPE),
            kind=jnp.zeros((num_hosts, capacity), I32),
            src=jnp.zeros((num_hosts, capacity), I32),
            seq=jnp.zeros((num_hosts, capacity), I32),
            words=jnp.zeros((num_hosts, capacity, nwords), I32),
            count=jnp.zeros((num_hosts,), I32),
            overflow=jnp.zeros((), I32),
            narrow_hit=jnp.zeros((), I32),
            narrow_miss=jnp.zeros((), I32),
            max_occupied=jnp.zeros((), I32),
            route_elided=jnp.zeros((), I32),
        )


def outbox_append(
    out: Outbox,
    mask: jax.Array,   # [H] bool
    dst: jax.Array,    # [H] i32
    time: jax.Array,   # [H] i64
    kind: jax.Array,   # [H] i32
    src: jax.Array,    # [H] i32
    seq: jax.Array,    # [H] i32
    words: jax.Array,  # [H, NWORDS] i32
) -> Outbox:
    words = fit_words(words, out.words.shape[-1])
    ok = mask & (out.count < out.capacity)
    sel = _onehot(ok, out.count, out.capacity)
    if out.overflow_h is not None:
        out = out.replace(
            overflow_h=out.overflow_h
            + (mask & ~(out.count < out.capacity)).astype(I32))
    return out.replace(
        dst=_put(out.dst, sel, dst),
        time=_put(out.time, sel, time),
        kind=_put(out.kind, sel, kind),
        src=_put(out.src, sel, src),
        seq=_put(out.seq, sel, seq),
        words=_put(out.words, sel, words),
        count=out.count + ok.astype(I32),
        overflow=out.overflow + jnp.sum(mask & ~(out.count < out.capacity), dtype=I32),
    )


def compact_rows(q: EventQueue) -> EventQueue:
    """Stable-partition each row so occupied slots are contiguous at the
    front. Pop order is argmin-based, so intra-row layout is free; this
    just makes free slots addressable as fill_count + rank."""
    empty = ~q.valid()
    order = jnp.argsort(empty, axis=1, stable=True)       # [H, K]
    take = lambda a: jnp.take_along_axis(a, order, axis=1)
    return q.replace(
        time=take(q.time), kind=take(q.kind), src=take(q.src), seq=take(q.seq),
        words=jnp.take_along_axis(q.words, order[..., None], axis=1),
    )


def segment_ranks(sorted_keys: jax.Array) -> jax.Array:
    """[n] rank of each element within its run of equal keys (keys must
    already be sorted)."""
    n = sorted_keys.shape[0]
    pos = jnp.arange(n)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]]
    )
    seg_start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    return pos - seg_start


# Group width for insert_flat's sort-free "count-route": cross-group
# ranks come from a scatter-add [n/G, H] count matrix + exclusive
# cumsum, within-group ranks from an [n/G, G, G] compare cube. Larger
# G shrinks the count matrix and grows the cube. (Kept for
# measurement; "sort2" superseded it as the accelerator default in r4
# — 65.7 -> 30.4 ms/window at 10k hosts on v5e.)
INSERT_GROUP = 64
# Above these element counts the count matrix / free-slot cube are
# worse than the sort path (and at 100k unsharded hosts the count
# matrix alone would be ~30 GB) — fall back to sorting.
COUNT_MATRIX_BUDGET = 400_000_000
SLOT_CUBE_BUDGET = 1_000_000_000


def _insert_impl(n: int, H: int) -> str:
    if jax.default_backend() == "cpu":
        # CPU gathers/sorts are cheap; the packed-plane co-sort and
        # padded scatter are pure waste there
        return "sort"
    # multi-operand co-sort + lexicographically sorted scatter: no
    # count matrix, no cube, no per-entry permutation gathers — and
    # no scale ceiling (the count matrix at 100k hosts would be
    # ~30 GB; sort2 is O(n log n) compare-exchange on packed planes)
    return "sort2"


def _pack_time(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """i64 -> (lo, hi) i32 words, exact for every bit pattern."""
    lo = t.astype(jnp.uint32).astype(I32)
    hi = (t >> 32).astype(I32)
    return lo, hi


def _unpack_time(lo: jax.Array, hi: jax.Array) -> jax.Array:
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.uint32).astype(
        jnp.int64)


def _free_slot_of_rank(q: EventQueue, impl: str) -> jax.Array:
    """[H, K] map: rank r (among a row's free slots, ascending slot
    order) -> slot index, K where the row has fewer than r+1 free
    slots. Insertion fills holes in place — the queue is never
    compacted (pop order is argmin-based, so intra-row layout carries
    no semantics; both impls produce identical values so plane layout
    is impl-independent)."""
    H, K = q.time.shape
    free = ~q.valid()                                      # [H,K]
    if impl == "count" and H * K * K <= SLOT_CUBE_BUDGET:
        free_rank = jnp.cumsum(free, axis=1, dtype=I32) - free
        hit = free[:, :, None] & (
            free_rank[:, :, None] == jnp.arange(K)[None, None, :])
        slot = jnp.sum(
            jnp.where(hit, jnp.arange(K)[:, None], 0), axis=1, dtype=I32)
        return jnp.where(jnp.any(hit, axis=1), slot, K)
    # row-sort mechanism, same values: free slots first, index order
    order = jnp.argsort(~free, axis=1, stable=True).astype(I32)
    n_free = jnp.sum(free, axis=1, dtype=I32)              # [H]
    return jnp.where(jnp.arange(K)[None, :] < n_free[:, None], order, K)


# Per-destination-row arrival budget of the "sort2" select sweep: when
# every destination row receives at most this many entries (measured
# 10k PHOLD: max 23), the insert needs NO per-entry scatter at all —
# a windowed gather (H index rows) plus INSERT_SWEEP dense selects.
# Rows over budget fall back to the sorted-scatter form via lax.cond.
INSERT_SWEEP = 32


def _queue_packed(q: EventQueue):
    """The queue's planes as one [H, K, 5+W] i32 tensor."""
    return jnp.concatenate(
        [jnp.stack(_pack_time(q.time), axis=2), q.kind[:, :, None],
         q.src[:, :, None], q.seq[:, :, None], q.words], axis=2)


def _queue_unpacked(q: EventQueue, packed_q, overflow_add,
                    overflow_add_h=None):
    q = q.replace(
        time=_unpack_time(packed_q[:, :, 0], packed_q[:, :, 1]),
        kind=packed_q[:, :, 2],
        src=packed_q[:, :, 3],
        seq=packed_q[:, :, 4],
        words=packed_q[:, :, 5:],
        overflow=q.overflow + overflow_add,
    )
    if q.overflow_h is not None and overflow_add_h is not None:
        q = q.replace(overflow_h=q.overflow_h + overflow_add_h)
    return q


def _insert_sorted_scatter(q: EventQueue, rowc, packed, n, H, K):
    """The "sort2" insert mechanism: co-sort the packed planes by
    destination row with one multi-operand lax.sort (the permutation
    happens inside the vectorized sort network — no per-entry plane
    gathers, which is what made the classic argsort+shuffle form slow
    on TPU), then apply the sorted stream with one of two writers:

    - select sweep (common case, every destination row receives at
      most INSERT_SWEEP entries): per-row arrival counts come from one
      single-plane sorted scatter-add; each row's arrivals are pulled
      as a contiguous [INSERT_SWEEP, P] window of the sorted stream
      with ONE gather of H index rows (per-entry gathers/scatters on
      TPU cost ~20-45 ns/row serialized — H rows instead of n is the
      whole win); arrival j then lands in the row's j-th free slot
      via INSERT_SWEEP dense masked selects, fully vectorized.
    - sorted scatter (fallback): one lexicographically sorted
      [n, P] scatter into a padded operand; rejected entries redirect
      to a pad row/column that is sliced off, so duplicate pad writes
      are discarded harmlessly.

    Values are bit-identical to the "count"/"sort" mechanisms either
    way: the stable sort preserves caller order within each row, so
    ranks and chosen free slots agree entry-for-entry."""
    P = packed.shape[1]
    cols = tuple(packed[:, j] for j in range(P))
    srt = jax.lax.sort((rowc,) + cols, num_keys=1, is_stable=True)
    row_o = srt[0]
    packed_o = jnp.stack(srt[1:], axis=1)                  # [n, P]
    valid_o = row_o < H

    # per-destination-row arrival counts (invalid entries fall in the
    # dropped bin H) and each row's start offset in the sorted stream
    cnt = jnp.zeros((H + 1,), I32).at[row_o].add(
        1, indices_are_sorted=True)[:H]
    start = jnp.cumsum(cnt, dtype=I32) - cnt               # [H] excl

    free = ~q.valid()                                      # [H, K]
    nfree = jnp.sum(free, axis=1, dtype=I32)
    packed_q = _queue_packed(q)

    Wn = INSERT_SWEEP
    # per-row overflow attribution (lane isolation): both writers drop
    # exactly the arrivals beyond a row's free slots, so the plane add
    # is max(cnt - nfree, 0) either way — computed once, outside the
    # cond, only when the plane is attached (trace-time no-op else)
    ofl_h = (jnp.maximum(cnt - nfree, 0).astype(I32)
             if q.overflow_h is not None else None)

    def _select_sweep(_):
        # each row's arrivals as a contiguous window of the stream
        pad_o = jnp.pad(packed_o, ((0, Wn), (0, 0)))
        use_pallas = False
        if jax.default_backend() == "tpu":
            from shadow_tpu.core import insert_pallas

            use_pallas = insert_pallas.mailbox_available(H)
        if use_pallas:
            # pipelined per-row HBM->VMEM DMAs instead of XLA's
            # strictly serial H-iteration gather loop. Mosaic needs
            # the DMA'd minor dim 128-aligned, so the stream is
            # padded P -> 128 (the extra bytes ride otherwise-idle
            # DMA bandwidth; the serial loop they replace was latency
            # bound, not bandwidth bound).
            wide = jnp.pad(pad_o, ((0, 0), (0, 128 - P)))
            win = insert_pallas.mailbox_gather(wide, start, Wn)[..., :P]
        else:
            dnums = jax.lax.GatherDimensionNumbers(
                offset_dims=(1, 2), collapsed_slice_dims=(),
                start_index_map=(0,))
            win = jax.lax.gather(
                pad_o, start[:, None], dnums, slice_sizes=(Wn, P),
                indices_are_sorted=True,
                mode=jax.lax.GatherScatterMode.CLIP)       # [H, Wn, P]
        f_rank = jnp.cumsum(free, axis=1, dtype=I32) - free
        acc = packed_q
        for j in range(Wn):
            take = free & (f_rank == j) & (j < cnt)[:, None]
            acc = jnp.where(take[:, :, None], win[:, j, None, :], acc)
        ofl = jnp.sum(jnp.maximum(cnt - nfree, 0), dtype=I32)
        return acc, ofl

    def _sorted_scatter(_):
        rank_o = segment_ranks(row_o)
        slot_map = _free_slot_of_rank(q, "sort")           # [H, K]
        # Keep the clipped index sequence genuinely sorted for the
        # hint: invalid entries (row H, clipped to H-1) restart
        # segment_ranks at 0, so pin their rank index to K-1 —
        # (H-1, K-1) repeated is >= every preceding (H-1, k<=K-1)
        # pair. Their cand value is unused (fits requires valid_o).
        rank_c = jnp.where(valid_o, jnp.clip(rank_o, 0, K - 1), K - 1)
        cand = slot_map.at[
            jnp.clip(row_o, 0, H - 1), rank_c].get(
            indices_are_sorted=True)
        fits = valid_o & (rank_o < K) & (cand < K)
        # (row, slot) is lexicographically non-decreasing: rows
        # ascend, and within a row fit slots ascend (rank-th free
        # slot) with the rejected suffix pinned at the pad column K.
        r = jnp.where(valid_o, row_o, H)
        s = jnp.where(fits, cand, K)
        padded = jnp.pad(packed_q, ((0, 1), (0, 1), (0, 0)))
        idx = jnp.stack([r, s], axis=1)                    # [n, 2]
        dnums = jax.lax.ScatterDimensionNumbers(
            update_window_dims=(1,), inserted_window_dims=(0, 1),
            scatter_dims_to_operand_dims=(0, 1))
        padded = jax.lax.scatter(
            padded, idx, packed_o, dnums, indices_are_sorted=True,
            unique_indices=False, mode=jax.lax.GatherScatterMode.CLIP)
        ofl = jnp.sum(valid_o & ~fits, dtype=I32)
        return padded[:H, :K], ofl

    packed_q, ofl = jax.lax.cond(
        jnp.max(cnt) <= Wn, _select_sweep, _sorted_scatter, 0)
    return _queue_unpacked(q, packed_q, ofl, ofl_h)


def insert_flat(
    q: EventQueue,
    valid: jax.Array,  # [n] bool
    row: jax.Array,    # [n] i32 *local* destination row
    time: jax.Array,   # [n] i64
    kind: jax.Array,   # [n] i32
    src: jax.Array,    # [n] i32 (global source host id)
    seq: jax.Array,    # [n] i32
    words: jax.Array,  # [n, NWORDS] i32
    impl: str | None = None,
) -> EventQueue:
    """Insert a flat batch of events into their destination rows, in
    caller order within each row (the determinism contract: caller
    order = global source order). Overflow is counted, never silent.

    Each entry's within-row rank = #earlier entries with the same row;
    its slot = the rank-th free slot of that row (holes fill in
    place). Three bit-identical mechanisms, chosen per backend by
    _insert_impl:

    - "sort2" (accelerators, the default): one multi-operand lax.sort
      co-sorting the packed planes by destination row, then a single
      lexicographically sorted scatter (_insert_sorted_scatter).
    - "sort" (CPU): stable argsort by row + segment ranks, the
      classic shuffle — cheap where gathers are cheap.
    - "count" (kept for measurement, no longer auto-selected):
      scatter-add a [n/G, H] per-group count matrix, exclusive-cumsum
      for cross-group ranks, an [n/G, G, G] within-group compare cube
      (the r2 design that beat the argsort+gather form on TPU before
      sort2 beat both; INSERT_GROUP/COUNT_MATRIX_BUDGET only matter
      when it is requested explicitly).

    All planes move through ONE packed [.., 5+W] i32 gather/scatter
    (time split into two i32 words) instead of per-plane ops."""
    n = row.shape[0]
    H = q.num_hosts
    K = q.capacity
    W = q.words.shape[-1]
    if impl is None:
        impl = _insert_impl(n, H)
    rowc = jnp.where(valid, row, H)

    tlo, thi = _pack_time(time)
    packed = jnp.concatenate(
        [tlo[:, None], thi[:, None], kind[:, None], src[:, None],
         seq[:, None], words], axis=1)                     # [n, 5+W]

    if impl == "sort2":
        return _insert_sorted_scatter(q, rowc, packed, n, H, K)

    if impl == "count":
        G = INSERT_GROUP
        pad = (-n) % G
        rowp = jnp.pad(rowc, (0, pad), constant_values=H)
        ng = rowp.shape[0] // G
        gidx = jnp.arange(ng * G) // G
        cnt = jnp.zeros((ng, H), I32).at[gidx, rowp].add(1, mode="drop")
        base_excl = jnp.cumsum(cnt, axis=0, dtype=I32) - cnt
        base = base_excl[
            jnp.clip(gidx, 0, ng - 1), jnp.clip(rowp, 0, H - 1)]
        rg = rowp.reshape(ng, G)
        earlier = jnp.arange(G)[:, None] < jnp.arange(G)[None, :]
        intra = jnp.sum(
            (rg[:, :, None] == rg[:, None, :]) & earlier[None],
            axis=1, dtype=I32).reshape(-1)
        rank = (base + intra)[:n]
        row_o, rank_o, packed_o, valid_o = rowc, rank, packed, valid
    else:
        order = jnp.argsort(rowc, stable=True)
        row_o = rowc[order]
        packed_o = packed[order]
        valid_o = row_o < H
        rank_o = segment_ranks(row_o)

    slot_map = _free_slot_of_rank(q, impl)                 # [H,K]
    cand = slot_map[
        jnp.clip(row_o, 0, H - 1), jnp.clip(rank_o, 0, K - 1)]
    fits = valid_o & (rank_o < K) & (cand < K)
    r = jnp.where(fits, row_o, H)                          # OOB -> drop
    s = jnp.where(fits, cand, K)

    packed_q = _queue_packed(q).at[r, s].set(packed_o, mode="drop")
    ofl_h = None
    if q.overflow_h is not None:
        # destination-row attribution: non-fitting valid entries
        # scatter-added onto their (clipped; masked-off when invalid)
        # destination rows
        ofl_h = jnp.zeros((H,), I32).at[jnp.clip(row_o, 0, H - 1)].add(
            (valid_o & ~fits).astype(I32))
    return _queue_unpacked(q, packed_q,
                           jnp.sum(valid_o & ~fits, dtype=I32), ofl_h)


def clear_outbox(out: Outbox) -> Outbox:
    H, M = out.dst.shape
    return out.replace(
        dst=jnp.full((H, M), -1, I32),
        time=jnp.full((H, M), simtime.INVALID, simtime.DTYPE),
        count=jnp.zeros((H,), I32),
    )


# Narrow-route tier: outbox rows are cursor-appended (left-packed), so
# when every row's count fits this width the route runs over a sliced
# [H, ROUTE_NARROW] view — the whole insert pipeline (sort/scatter,
# rank maps) scales with candidate count, and the capacity is sized
# for worst-case bursts the steady state never reaches (measured r4:
# 10k-host PHOLD load 8 stages max 23/48 per row). None disables.
ROUTE_NARROW = 24


def _route_width(q: EventQueue, out: Outbox, width: int,
                 impl: str | None) -> EventQueue:
    """Insert the first `width` outbox columns of every row."""
    H = out.dst.shape[0]
    n = H * width
    dst = out.dst[:, :width].reshape(n)
    occupied = dst >= 0
    # A dst outside [0, H) is a routing bug — count it, never silently
    # drop.
    bad_dst = occupied & (dst >= H)
    valid = occupied & ~bad_dst
    q = insert_flat(
        q, valid, dst,
        out.time[:, :width].reshape(n), out.kind[:, :width].reshape(n),
        out.src[:, :width].reshape(n), out.seq[:, :width].reshape(n),
        out.words[:, :width].reshape(n, out.words.shape[-1]),
        impl=impl,
    )
    if q.overflow_h is not None:
        # bad_dst is flattened row-major from the SOURCE rows — the
        # destination is out of range, so attribute to the sender
        q = q.replace(overflow_h=q.overflow_h + jnp.sum(
            bad_dst.reshape(H, width), axis=1, dtype=I32))
    return q.replace(overflow=q.overflow + jnp.sum(bad_dst, dtype=I32))


def route_outbox(q: EventQueue, out: Outbox, impl: str | None = None,
                 narrow: int | None = None) -> tuple[EventQueue, Outbox]:
    """Deliver all staged cross-host events into destination rows.

    Single-shard version: destination host ids are row indices
    directly. The multi-chip path runs insert_flat after an all-to-all
    keyed by dst // hosts_per_shard (see shadow_tpu.parallel.shard).
    `impl` overrides the insert mechanism ("count"/"sort"/"sort2") for
    callers whose arrays live on a different backend than
    jax.default_backend() (values are bit-identical either way; this
    is perf-only). `narrow` overrides ROUTE_NARROW.

    Bit-identity of the narrow tier: the gate is the true maximum
    OCCUPIED column (not the per-row count — the UDP bulk pass stages
    replies at sparse time-order columns, net/bulk.py ord_col, so a
    row can hold entries past its count), so slicing drops only empty
    slots, and candidate enumeration order (row-major over the slice)
    preserves the relative order of every occupied entry — ranks,
    slots and overflow accounting are unchanged.
    """
    H, M = out.dst.shape
    width = ROUTE_NARROW if narrow is None else narrow
    if width and width < M:
        occupied_width = jnp.max(
            jnp.where(out.dst >= 0, jnp.arange(M, dtype=I32)[None, :] + 1,
                      0))
        hit = occupied_width <= width
        empty = occupied_width == 0
        out = out.replace(
            narrow_hit=out.narrow_hit + hit.astype(I32),
            narrow_miss=out.narrow_miss + (~hit).astype(I32),
            max_occupied=jnp.maximum(out.max_occupied, occupied_width),
            route_elided=out.route_elided + empty.astype(I32))
        # Empty-exchange elision (sparse-window layer 3): an occupied
        # width of zero means no row staged anything, so the insert
        # pipeline is a structural no-op — skip it. occupied_width
        # counts bad-dst entries too, so empty also implies no
        # overflow accounting is owed.
        q = jax.lax.cond(
            empty,
            lambda qq: qq,
            lambda qq: jax.lax.cond(
                hit,
                lambda q2: _route_width(q2, out, width, impl),
                lambda q2: _route_width(q2, out, M, impl),
                qq),
            q)
    else:
        empty = ~jnp.any(out.dst >= 0)
        out = out.replace(
            route_elided=out.route_elided + empty.astype(I32))
        q = jax.lax.cond(
            empty,
            lambda qq: qq,
            lambda qq: _route_width(qq, out, M, impl),
            q)
    return q, clear_outbox(out)


@struct.dataclass
class EmitBuffer:
    """Per-micro-step emission staging. Handlers run sequentially (one
    masked batch per kind), each lane (= the host whose event was
    popped) appending at its private cursor — deterministic and
    collision-free. apply_emissions() then assigns per-source sequence
    numbers in slot order and moves local events into the queue and
    remote events into the Outbox."""

    dst: jax.Array    # [H, E] i32
    time: jax.Array   # [H, E] i64
    kind: jax.Array   # [H, E] i32
    words: jax.Array  # [H, E, NWORDS] i32
    count: jax.Array  # [H] i32
    overflow: jax.Array  # [] i32
    # Optional per-host overflow attribution ([H] i32) — attached by
    # window_fixpoint when the queue carries its own plane, folded into
    # EventQueue.overflow_h by apply_emissions.
    overflow_h: Any = None

    @property
    def num_hosts(self) -> int:
        return self.dst.shape[0]

    @property
    def capacity(self) -> int:
        return self.dst.shape[1]

    @staticmethod
    def create(num_hosts: int, capacity: int = 4,
               nwords: int = NWORDS) -> "EmitBuffer":
        return EmitBuffer(
            dst=jnp.full((num_hosts, capacity), -1, I32),
            time=jnp.full((num_hosts, capacity), simtime.INVALID, simtime.DTYPE),
            kind=jnp.zeros((num_hosts, capacity), I32),
            words=jnp.zeros((num_hosts, capacity, nwords), I32),
            count=jnp.zeros((num_hosts,), I32),
            overflow=jnp.zeros((), I32),
        )


def emit(
    buf: EmitBuffer,
    mask: jax.Array,          # [H] bool
    dst: jax.Array,           # [H] i32 (dst == lane index -> local)
    time: jax.Array,          # [H] i64
    kind,                     # [H] i32 or int
    words: jax.Array,         # [H, NWORDS] i32
) -> EmitBuffer:
    H = buf.num_hosts
    words = fit_words(words, buf.words.shape[-1])
    kind = jnp.broadcast_to(jnp.asarray(kind, I32), (H,))
    ok = mask & (buf.count < buf.capacity)
    sel = _onehot(ok, buf.count, buf.capacity)
    if buf.overflow_h is not None:
        buf = buf.replace(
            overflow_h=buf.overflow_h
            + (mask & ~(buf.count < buf.capacity)).astype(I32))
    return buf.replace(
        dst=_put(buf.dst, sel, dst),
        time=_put(buf.time, sel, time),
        kind=_put(buf.kind, sel, kind),
        words=_put(buf.words, sel, words),
        count=buf.count + ok.astype(I32),
        overflow=buf.overflow + jnp.sum(mask & ~(buf.count < buf.capacity), dtype=I32),
    )


def emit_words(*vals, num_hosts: int | None = None) -> jax.Array:
    """Assemble an [H, NWORDS] word array from [H] (or scalar) columns."""
    assert len(vals) <= NWORDS, f"{len(vals)} payload words > NWORDS={NWORDS}"
    cols = []
    H = num_hosts
    for v in vals:
        v = jnp.asarray(v)
        if v.ndim == 1:
            H = v.shape[0]
    assert H is not None
    for v in vals:
        v = jnp.asarray(v, I32)
        cols.append(jnp.broadcast_to(v, (H,)))
    while len(cols) < NWORDS:
        cols.append(jnp.zeros((H,), I32))
    return jnp.stack(cols[:NWORDS], axis=1)


def apply_emissions(
    q: EventQueue, out: Outbox, buf: EmitBuffer, lane_id: jax.Array | None = None
) -> tuple[EventQueue, Outbox]:
    """Move staged emissions into the local queue / cross-host outbox,
    assigning per-source sequence numbers in slot order (matching the
    reference's per-push host_getNewEventID ordering).

    `lane_id` is each local row's *global* host id ([H] i32) — the
    identity of the sharded lane. Emission dst fields are global host
    ids; dst == lane_id means a same-host event that stays in the local
    queue. Defaults to arange(H) (single-shard)."""
    H, E = buf.dst.shape
    lane = jnp.arange(H, dtype=I32) if lane_id is None else lane_id.astype(I32)
    nvalid = jnp.zeros((H,), I32)
    for e in range(E):
        v = buf.dst[:, e] >= 0
        seq = q.next_seq + nvalid
        is_local = v & (buf.dst[:, e] == lane)
        is_remote = v & (buf.dst[:, e] != lane)
        q = push_rows(
            q, is_local, buf.time[:, e], buf.kind[:, e], lane, seq, buf.words[:, e]
        )
        out = outbox_append(
            out, is_remote, buf.dst[:, e], buf.time[:, e], buf.kind[:, e],
            lane, seq, buf.words[:, e],
        )
        nvalid = nvalid + v.astype(I32)
    q = q.replace(next_seq=q.next_seq + nvalid,
                  overflow=q.overflow + buf.overflow)
    if q.overflow_h is not None and buf.overflow_h is not None:
        q = q.replace(overflow_h=q.overflow_h + buf.overflow_h)
    return q, out


# --- Window kind census (sparse-window layer 2) -------------------------
#
# One u32 bitmask per window: bit k set when any event of kind k could
# be popped before wend. Kinds >= 31 share bit 31, so the mask can only
# OVER-approximate — sound, because every handler is a masked batch
# update and an all-false mask is the identity (net/step.py documents
# the invariant). The census seeds from the queue at window entry and
# is OR-extended with each micro-step's emissions, so kinds that only
# appear mid-window (e.g. TCP_FLUSH staged by the receive path) are
# re-admitted before their events can be popped.

def _kind_bit(kind: jax.Array) -> jax.Array:
    """One-hot u32 bit per kind; kinds >= 31 collapse onto bit 31."""
    return jnp.uint32(1) << jnp.clip(kind, 0, 31).astype(jnp.uint32)


def _or_reduce(bits: jax.Array) -> jax.Array:
    return jax.lax.reduce(bits, jnp.uint32(0),
                          lambda a, b: jax.lax.bitwise_or(a, b),
                          tuple(range(bits.ndim)))


def kind_census(q: EventQueue, wend) -> jax.Array:
    """[] u32 bitmask of event kinds present in `q` before `wend`."""
    m = q.time < jnp.asarray(wend, simtime.DTYPE)
    return _or_reduce(jnp.where(m, _kind_bit(q.kind), jnp.uint32(0)))


def emit_kind_bits(buf: EmitBuffer) -> jax.Array:
    """[] u32 bitmask of event kinds staged in an EmitBuffer."""
    m = buf.dst >= 0
    return _or_reduce(jnp.where(m, _kind_bit(buf.kind), jnp.uint32(0)))


def census_mask(kinds) -> int:
    """Static u32 mask for a handler family's kind tuple (host side)."""
    m = 0
    for k in kinds:
        m |= 1 << min(int(k), 31)
    return m
