from shadow_tpu.core import simtime
from shadow_tpu.core.events import EventQueue, Outbox, Popped, EmitBuffer
