"""Simulation-time constants and helpers.

Parity with the reference's time model (ref: definitions.h:14-78):
simulated time is unsigned 64-bit nanoseconds there; here it is *signed*
int64 nanoseconds (JAX sorts/compares signed types natively), with
INVALID = int64 max as the "no event" sentinel. int64 range covers
~292 years of nanoseconds, the same practical range.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

DTYPE = jnp.int64

# Sentinel meaning "no time / empty slot" (ref: definitions.h:28).
INVALID = np.iinfo(np.int64).max
MAX = INVALID - 1
MIN = 0

ONE_NANOSECOND = 1
ONE_MICROSECOND = 1_000
ONE_MILLISECOND = 1_000_000
ONE_SECOND = 1_000_000_000
ONE_MINUTE = 60 * ONE_SECOND
ONE_HOUR = 3600 * ONE_SECOND

# Offset added to simulated time so applications observe a wall clock
# starting at 2000-01-01 00:00:00 UTC (ref: definitions.h:74-78,
# worker.c:385-390).
EMULATED_TIME_OFFSET = 946_684_800 * ONE_SECOND


def ns(x) -> jnp.ndarray:
    return jnp.asarray(x, dtype=DTYPE)


def from_seconds(s: float) -> int:
    return int(round(s * ONE_SECOND))


def from_millis(ms: float) -> int:
    return int(round(ms * ONE_MILLISECOND))


def to_seconds(t) -> float:
    return float(t) / ONE_SECOND


def emulated(t):
    """Simulated -> emulated (app-visible) time (ref: worker.c:385-390)."""
    return t + EMULATED_TIME_OFFSET
