"""Lane-scoped health latches and blast-radius containment.

An ensemble-packed program (the BENCH_REPLICAS axis / fleet packed
jobs) partitions its H host rows into R contiguous *lanes* of H/R
hosts — each lane one tenant's scenario. The global sticky latches
(EventQueue.overflow, Outbox.overflow, NetState.rq_overflow) stay
authoritative, but they cannot say WHICH tenant tripped, so one lane's
overflow would abort or escalate every tenant sharing the compiled
program.

This module makes health lane-scoped end to end, inside the jitted
window body:

- Per-host attribution planes (`overflow_h` on EventQueue/Outbox,
  `rq_overflow_h` on NetState) ride every latch bump site, invariant
  scalar == sum(plane).
- A LaneHealth struct (Sim.lanes) carries [R]-shaped latch planes —
  overflow / stall / time-regression / injection-drop counters — plus
  a lane quarantine mask.
- window_update() runs at every window barrier (core/engine.py
  step_window, after the route): it reduces the host planes per lane,
  trips sick lanes, and FREEZES a quarantined lane's hosts — their
  pending events are flushed (counted in `flushed`, never silently)
  so they pop nothing, stage nothing, and stop holding the global
  min-time advance back, while healthy lanes run to completion.

Opt-in contract (same as Sim.telem / Sim.inject): every new field
defaults to None and contributes no pytree leaves, so programs and
checkpoints built without lane isolation are byte-identical; attach()
is the explicit opt-in. Lane blocks are contiguous in host-index
order (lane of host h = h // (H/R)), matching the replica blocks
apps/phold.py peer_base/peer_span carve out — single-controller,
single-shard programs only (the fleet's packed jobs run shards=1).

Host-side consumers: faults/health.py gathers the per-lane report and
treats lane-CONTAINED capacity trips as non-fatal; faults/supervisor.py
performs checkpoint lane surgery (faults/escalate.py extract_lane) and
hands the sick lane to the fleet for requeue with salvage artifacts.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime

I32 = jnp.int32
I64 = jnp.int64

# Trip-bit vocabulary (LaneHealth.trip_bits; mirrored by
# faults/health.py diagnostics and the manifest "lanes" block).
TRIP_EVENTS = 1    # EventQueue row overflow inside the lane
TRIP_OUTBOX = 2    # Outbox overflow from one of the lane's hosts
TRIP_RQ = 4        # router-ring overflow inside the lane
TRIP_STALL = 8     # lane min-time pinned for >= stall_limit windows
TRIP_REGRESS = 16  # lane pending time behind the window barrier
TRIP_SLO = 32      # admission gate exhausted the degradation ladder
# (fleet/admission.py): quarantine by POLICY, set host-side at a
# barrier — the device freeze machinery is identical to a capacity
# trip, but the cause is an SLO breach, not corruption.

TRIP_NAMES = {
    TRIP_EVENTS: "events_overflow",
    TRIP_OUTBOX: "outbox_overflow",
    TRIP_RQ: "rq_overflow",
    TRIP_STALL: "stall",
    TRIP_REGRESS: "time_regression",
    TRIP_SLO: "slo_breach",
}


def trip_names(bits: int) -> list:
    """Human-readable names of the set trip bits."""
    return [n for b, n in sorted(TRIP_NAMES.items()) if int(bits) & b]


@struct.dataclass
class LaneHealth:
    """[R]-shaped per-lane latch planes + quarantine mask.

    The overflow planes are cumulative SNAPSHOTS (re-reduced from the
    per-host planes at each barrier, not deltas), so they equal the
    lane share of the scalar latches at every window boundary."""

    overflow_events: jax.Array   # [R] i32 lane share of events.overflow
    overflow_outbox: jax.Array   # [R] i32 lane share of outbox.overflow
    overflow_rq: jax.Array       # [R] i32 lane share of net.rq_overflow
    inj_dropped: jax.Array       # [R] i64 injected-event drops (warning)
    stall_streak: jax.Array      # [R] i32 consecutive no-progress windows
    regress: jax.Array           # [R] i32 windows with pending < barrier
    prev_min: jax.Array          # [R] i64 lane min pending at last barrier
    quarantined: jax.Array       # [R] bool sticky quarantine mask
    quarantined_at: jax.Array    # [R] i64 barrier time of the trip
    trip_bits: jax.Array         # [R] i32 OR of TRIP_* causes
    flushed: jax.Array           # [R] i64 events flushed from frozen rows
    # Windows a lane may sit with an unchanged min pending time before
    # the stall latch trips; 0 disables the stall trip (host-side
    # zero-streak supervision still applies globally).
    stall_limit: int = struct.field(pytree_node=False, default=0)

    @property
    def replicas(self) -> int:
        return self.quarantined.shape[0]

    @staticmethod
    def create(replicas: int, stall_limit: int = 0) -> "LaneHealth":
        R = int(replicas)
        return LaneHealth(
            overflow_events=jnp.zeros((R,), I32),
            overflow_outbox=jnp.zeros((R,), I32),
            overflow_rq=jnp.zeros((R,), I32),
            inj_dropped=jnp.zeros((R,), I64),
            stall_streak=jnp.zeros((R,), I32),
            regress=jnp.zeros((R,), I32),
            prev_min=jnp.full((R,), simtime.INVALID, simtime.DTYPE),
            quarantined=jnp.zeros((R,), bool),
            quarantined_at=jnp.full((R,), simtime.INVALID, simtime.DTYPE),
            trip_bits=jnp.zeros((R,), I32),
            flushed=jnp.zeros((R,), I64),
            stall_limit=int(stall_limit),
        )


@struct.dataclass
class LaneAdmission:
    """[R]-shaped lease planes for a RESIDENT program (PR 16): the
    lane population changes at window barriers without retracing.

    The host-side lease state machine (fleet/admission.py LaneLease)
    owns the transitions; these planes are the device-visible shadow
    the jitted window body enforces at every barrier:

    - a FREE lane (active=False) is kept empty — any event routed or
      resurrected into it is flushed at the next barrier (counted in
      `flushed`, never silently),
    - an active lane's events at/after its `lease_end` horizon are
      flushed, so the tenant drains within one barrier of its lease
      expiring instead of holding the global min-time advance back,
    - an active lane that ran dry latches `completed` (+ the barrier
      time), which is what the host polls to fold the lease to
      COMPLETED and return the lane to the free pool.

    Same opt-in contract as LaneHealth: Sim.admission defaults to
    None and contributes no pytree leaves; attach_admission() is the
    explicit opt-in (and requires LaneHealth attached first — the
    quarantine machinery is the degradation ladder's last step)."""

    active: jax.Array        # [R] bool lane holds a live lease
    epoch: jax.Array         # [R] i32 admissions into this lane so far
    lease_end: jax.Array     # [R] i64 lease horizon (INVALID = open)
    admitted_at: jax.Array   # [R] i64 barrier time of the live join
    completed: jax.Array     # [R] bool active lane ran dry (latched)
    completed_at: jax.Array  # [R] i64 barrier time the lane ran dry
    flushed: jax.Array      # [R] i64 events flushed by admission rules

    @property
    def replicas(self) -> int:
        return self.active.shape[0]

    @staticmethod
    def create(replicas: int) -> "LaneAdmission":
        R = int(replicas)
        return LaneAdmission(
            active=jnp.zeros((R,), bool),
            epoch=jnp.zeros((R,), I32),
            lease_end=jnp.full((R,), simtime.INVALID, simtime.DTYPE),
            admitted_at=jnp.full((R,), simtime.INVALID, simtime.DTYPE),
            completed=jnp.zeros((R,), bool),
            completed_at=jnp.full((R,), simtime.INVALID, simtime.DTYPE),
            flushed=jnp.zeros((R,), I64),
        )


def attach_admission(sim):
    """Opt a lane-isolated sim into resident admission: every lane
    starts FREE (the host-side lease table admits tenants by implant,
    fleet/admission.py). Requires core.lanes.attach() first."""
    if getattr(sim, "lanes", None) is None:
        raise ValueError(
            "attach_admission requires lane isolation (core.lanes."
            "attach) — admission is lease bookkeeping over lanes")
    return sim.replace(admission=LaneAdmission.create(sim.lanes.replicas))


def admit_all(sim, at_ns: int = 0):
    """Standalone resident mode (`shadow-tpu --resident`): mark every
    lane as holding an OPEN lease from t=at_ns. No host-side lease
    table drives churn here — the planes exist so the barrier rules,
    completion latches, and the manifest "admission" block behave
    identically to a fleet-managed resident program with a static
    population."""
    adm = sim.admission
    if adm is None:
        raise ValueError("admit_all requires attach_admission() first")
    return sim.replace(admission=adm.replace(
        active=jnp.ones_like(adm.active),
        epoch=jnp.ones_like(adm.epoch),
        admitted_at=jnp.full_like(adm.admitted_at, int(at_ns))))


def lane_sum(x: jax.Array, replicas: int) -> jax.Array:
    """Reduce an [H]-leading plane to [R] lane totals (contiguous lane
    blocks). Bool inputs are counted."""
    R = int(replicas)
    if x.dtype == jnp.bool_:
        x = x.astype(I32)
    return jnp.sum(x.reshape(R, -1, *x.shape[1:]), axis=1, dtype=x.dtype)


def lane_min(x: jax.Array, replicas: int) -> jax.Array:
    """[H] -> [R] per-lane minimum (contiguous lane blocks)."""
    return jnp.min(x.reshape(int(replicas), -1), axis=1)


def host_mask(lane_mask: jax.Array, num_hosts: int) -> jax.Array:
    """[R] bool lane mask -> [H] bool host mask."""
    R = lane_mask.shape[0]
    return jnp.repeat(lane_mask, num_hosts // R)


def lane_of_host(h, num_hosts: int, replicas: int):
    """Lane index of host row h (int or array)."""
    return h // (num_hosts // int(replicas))


def attach(sim, replicas: int, stall_limit: int = 0):
    """Opt into lane-isolated health: attach the per-host attribution
    planes and the LaneHealth struct. H must divide evenly into R
    contiguous lane blocks (the replica layout apps/phold.py packs)."""
    R = int(replicas)
    H = sim.events.num_hosts
    if R < 1 or H % R != 0:
        raise ValueError(
            f"lane isolation needs num_hosts % replicas == 0, got "
            f"H={H} R={R}")
    sim = sim.replace(
        events=sim.events.replace(overflow_h=jnp.zeros((H,), I32)),
        outbox=sim.outbox.replace(overflow_h=jnp.zeros((H,), I32)),
        net=sim.net.replace(rq_overflow_h=jnp.zeros((H,), I32)),
        lanes=LaneHealth.create(R, stall_limit),
    )
    return sim


def window_update(sim, wend):
    """The per-window lane barrier (runs inside the jitted window body,
    after route_fn delivered the outbox): reduce the per-host latch
    planes to [R], trip sick lanes, and freeze quarantined lanes by
    flushing their pending events (counted per lane in `flushed`).

    Freezing at the barrier is exact containment: inserts are per-row
    independent, so a sick lane's overflow never perturbs another
    lane's rows, and flushing removes the lane from the global
    min-time advance so healthy lanes keep running to completion."""
    lanes = sim.lanes
    R = lanes.replicas
    H = sim.events.num_hosts
    wend = jnp.asarray(wend, simtime.DTYPE)

    ev = lane_sum(sim.events.overflow_h, R)
    ob = lane_sum(sim.outbox.overflow_h, R)
    rq = lane_sum(sim.net.rq_overflow_h, R)

    lmin = lane_min(sim.events.min_time(), R)          # [R] i64
    active = lmin != simtime.INVALID
    # stall: the lane's earliest pending time survived a whole window
    # unchanged (first barrier never matches: prev_min is INVALID,
    # an active lane's min is < INVALID)
    stalled = active & (lmin == lanes.prev_min)
    streak = jnp.where(stalled, lanes.stall_streak + 1, 0)
    # time regression: pending work behind the barrier after the
    # fixpoint drained everything < wend — the conservative-order
    # safety latch, per lane
    regressed = active & (lmin < wend)
    regress = lanes.regress + regressed.astype(I32)

    trip = (jnp.where(ev > 0, TRIP_EVENTS, 0)
            | jnp.where(ob > 0, TRIP_OUTBOX, 0)
            | jnp.where(rq > 0, TRIP_RQ, 0)
            | jnp.where(regressed, TRIP_REGRESS, 0)).astype(I32)
    if lanes.stall_limit > 0:
        trip = trip | jnp.where(
            streak >= lanes.stall_limit, TRIP_STALL, 0).astype(I32)

    tripped = trip != 0
    newly = tripped & ~lanes.quarantined
    quarantined = lanes.quarantined | tripped
    quarantined_at = jnp.where(newly, wend, lanes.quarantined_at)
    trip_bits = lanes.trip_bits | trip

    # freeze: flush every quarantined lane's pending events (cross-lane
    # traffic routed into a frozen lane this window included), counted
    mask_h = host_mask(quarantined, H)                 # [H] bool
    to_flush = sim.events.valid() & mask_h[:, None]    # [H, K]
    flushed = lanes.flushed + lane_sum(
        jnp.sum(to_flush, axis=1, dtype=I64), R)
    q = sim.events.replace(
        time=jnp.where(to_flush, simtime.INVALID, sim.events.time))

    lanes = lanes.replace(
        overflow_events=ev, overflow_outbox=ob, overflow_rq=rq,
        stall_streak=streak, regress=regress,
        prev_min=jnp.where(quarantined, simtime.INVALID, lmin),
        quarantined=quarantined, quarantined_at=quarantined_at,
        trip_bits=trip_bits, flushed=flushed)
    sim = sim.replace(events=q, lanes=lanes)

    adm = getattr(sim, "admission", None)
    if adm is not None:
        # resident admission (fleet/admission.py): keep FREE lanes
        # empty and enforce each active lane's lease horizon, both at
        # this barrier — route_fn already ran, so a delivery landing
        # at/after the horizon is flushed the same window it arrives
        # (the lease edge is exact at barriers, like fault times)
        free_h = host_mask(~adm.active, H)                  # [H] bool
        lease_h = jnp.repeat(adm.lease_end, H // R)         # [H] i64
        over = q.valid() & (free_h[:, None]
                            | (q.time >= lease_h[:, None]))
        adm_flushed = adm.flushed + lane_sum(
            jnp.sum(over, axis=1, dtype=I64), R)
        q = q.replace(time=jnp.where(over, simtime.INVALID, q.time))
        # completion latch: an active, un-quarantined lane with no
        # pending events ran its lease dry — record the barrier time
        # once; the host folds the lease to COMPLETED and frees the
        # lane (a quarantined lane is the supervisor's problem, not a
        # completion)
        quiet = lane_min(q.min_time(), R) == simtime.INVALID
        newly_done = adm.active & quiet & ~adm.completed & ~quarantined
        adm = adm.replace(
            flushed=adm_flushed,
            completed=adm.completed | newly_done,
            completed_at=jnp.where(newly_done, wend, adm.completed_at))
        sim = sim.replace(events=q, admission=adm)
    return sim


def lane_events_exec(sim) -> jax.Array:
    """[R] i64 cumulative executed-event count per lane (lane share of
    net.ctr_events_exec) — the telemetry ring's per-lane plane basis."""
    return lane_sum(sim.net.ctr_events_exec, sim.lanes.replicas)


def lane_report(sim) -> list:
    """Host-side: one dict per lane for the manifest "lanes" block.
    Values are pulled once per call — call between device steps."""
    import numpy as np

    lanes = sim.lanes
    R = lanes.replicas
    ev = np.asarray(lanes.overflow_events)
    ob = np.asarray(lanes.overflow_outbox)
    rq = np.asarray(lanes.overflow_rq)
    inj = np.asarray(lanes.inj_dropped)
    stall = np.asarray(lanes.stall_streak)
    reg = np.asarray(lanes.regress)
    quar = np.asarray(lanes.quarantined)
    qat = np.asarray(lanes.quarantined_at)
    bits = np.asarray(lanes.trip_bits)
    flushed = np.asarray(lanes.flushed)
    exec_ = np.asarray(lane_events_exec(sim))
    out = []
    for r in range(R):
        d = {
            "lane": r,
            "events_overflow": int(ev[r]),
            "outbox_overflow": int(ob[r]),
            "rq_overflow": int(rq[r]),
            "inj_dropped": int(inj[r]),
            "stall_streak": int(stall[r]),
            "time_regression": int(reg[r]),
            "events_exec": int(exec_[r]),
            "quarantined": bool(quar[r]),
            "flushed": int(flushed[r]),
        }
        if bool(quar[r]):
            d["quarantined_at_ns"] = int(qat[r])
            d["trip_bits"] = int(bits[r])
            d["trip"] = trip_names(int(bits[r]))
        out.append(d)
    return out


def admission_report(sim) -> list:
    """Host-side: one dict per lane of the LaneAdmission planes —
    the device-truth half of the manifest "admission" block (the
    lease-table half comes from fleet/admission.py). Pull once per
    call, between device steps."""
    import numpy as np

    adm = sim.admission
    active = np.asarray(adm.active)
    epoch = np.asarray(adm.epoch)
    lease = np.asarray(adm.lease_end)
    at = np.asarray(adm.admitted_at)
    done = np.asarray(adm.completed)
    done_at = np.asarray(adm.completed_at)
    flushed = np.asarray(adm.flushed)
    out = []
    for r in range(adm.replicas):
        d = {
            "lane": r,
            "active": bool(active[r]),
            "epoch": int(epoch[r]),
            "completed": bool(done[r]),
            "flushed": int(flushed[r]),
        }
        if bool(active[r]):
            d["lease_end_ns"] = int(lease[r])
            d["admitted_at_ns"] = int(at[r])
        if bool(done[r]):
            d["completed_at_ns"] = int(done_at[r])
        out.append(d)
    return out


# manifest per-lane key -> Prometheus family name. One row per latch
# the lane report carries, so a new latch added to lane_report shows
# up on dashboards by adding one line here.
LANE_METRIC_KEYS = (
    ("quarantined", "lane_quarantined"),
    ("flushed", "lane_flushed"),
    ("events_exec", "lane_events_exec"),
    ("events_overflow", "lane_events_overflow"),
    ("outbox_overflow", "lane_outbox_overflow"),
    ("rq_overflow", "lane_rq_overflow"),
    ("inj_dropped", "lane_inj_dropped"),
    ("stall_streak", "lane_stall_streak"),
    ("time_regression", "lane_time_regression"),
)


def lane_metric_families(per_lane) -> dict:
    """Per-lane gauge families from the manifest's lanes.per_lane list
    (lane_report dicts), in the nested-dict shape
    telemetry.export.prometheus_text renders as
    family{key="<lane>"} value. The quarantine mask exports as 0/1 per
    lane — the tenant dashboard's liveness bit — alongside the flush
    counter, overflow shares and per-lane executed-event totals that
    previously only reached Prometheus as scalar roll-ups."""
    out: dict = {}
    for src_key, family in LANE_METRIC_KEYS:
        fam = {}
        for d in per_lane or []:
            if src_key in d:
                fam[str(d["lane"])] = int(d[src_key])
        if fam:
            out[family] = fam
    return out
