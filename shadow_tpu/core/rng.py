"""Deterministic per-host random streams.

The reference seeds one master Random, which seeds the slave, which
seeds each host in registration order (ref: master.c:417, slave.c:301,
random.c:16-60) — determinism flows from the seed hierarchy, not from
execution order. Here the hierarchy is a counter-based construction:
draw i of host h from master seed s is threefry(fold(fold(key(s), h),
counter_h)), which is independent of thread/shard interleaving by
construction.

Keys are carried as raw uint32 key data ([H, 2]) rather than key
arrays so they shard/transfer like any other tensor under shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32


def host_streams(seed: int, num_hosts: int) -> jax.Array:
    """[H, 2] u32 per-host base key data."""
    base = jax.random.key(seed)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        base, jnp.arange(num_hosts, dtype=jnp.uint32)
    )
    return jax.random.key_data(keys)


def _fold(key_data: jax.Array, counters: jax.Array) -> jax.Array:
    keys = jax.random.wrap_key_data(key_data)
    return jax.vmap(jax.random.fold_in)(keys, counters.astype(jnp.uint32))


def uniform(key_data: jax.Array, counters: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One f32 uniform [0,1) draw per host at its current counter;
    returns (values[H], counters+1)."""
    ks = _fold(key_data, counters)
    vals = jax.vmap(lambda k: jax.random.uniform(k, dtype=jnp.float32))(ks)
    return vals, counters + 1


def uniform_at(key_data: jax.Array, counters: jax.Array) -> jax.Array:
    """f32 uniform [0,1) draws at explicit counters ([H, ...] u32,
    leading dim = hosts). Bit-identical to repeated uniform() calls at
    the same counter values — the bulk window pass uses this to
    reproduce the serial path's draw stream out of order."""
    H = key_data.shape[0]
    flat = counters.reshape(H, -1)

    def one(kd, cs):
        k = jax.random.wrap_key_data(kd)
        ks = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            k, cs.astype(jnp.uint32))
        return jax.vmap(lambda kk: jax.random.uniform(kk, dtype=jnp.float32))(ks)

    vals = jax.vmap(one)(key_data, flat)
    return vals.reshape(counters.shape)


def randint(key_data: jax.Array, counters: jax.Array, maxval) -> tuple[jax.Array, jax.Array]:
    """One i32 uniform draw in [0, maxval) per host (maxval may be [H])."""
    ks = _fold(key_data, counters)
    u = jax.vmap(lambda k: jax.random.uniform(k, dtype=jnp.float32))(ks)
    vals = jnp.minimum((u * maxval).astype(I32), jnp.asarray(maxval, I32) - 1)
    return vals, counters + 1
