"""Active-lane compaction (sparse-window layer 1).

Gather the host rows that hold any event before the window end into a
compact [S]-lane view of the whole Sim, run the window fixpoint at
width S, and scatter the results back. The row-selection rules are the
SAME as the sharding specs (parallel/shard.py sim_specs): a leaf whose
leading dimension is the host dimension is gathered; replicated lookup
tables (NetState.REPLICATED_FIELDS), the telemetry ring, and scalars
pass through whole. That identity of rules is what makes compaction
sound — every handler already has to treat its row index as a local
lane (identity comes from net.lane_id and replicated tables), because
sharding imposes exactly the same contract.

Bit-identity: the gathered indices are DISTINCT real rows (argsort of
the activity mask, actives first in ascending row order), so per-row
pop order, per-source sequence numbering, and the scatter-back are
exact. Padding lanes are inactive rows whose queues hold nothing
before wend — they pop nothing, and every handler is a masked batch
update for which an all-false mask is the identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

I32 = jnp.int32


def _replicated(path, sim) -> bool:
    # Lazy import: core must not depend on net at module load.
    from shadow_tpu.net.state import REPLICATED_FIELDS, NetState

    names = [k.name for k in path if hasattr(k, "name")]
    # The telemetry ring, the injection staging buffer, and the lane
    # health latches are whole-sim replicated state: their 1-D planes
    # are ring/staging/lane slots, not host rows — gather/scatter must
    # pass them through untouched. (Per-host overflow_h planes live on
    # events/outbox/net and DO gather, keeping row attribution exact.)
    if names and names[0] in ("telem", "inject", "lanes"):
        return True
    # Causality (telemetry/causality.py): the advance-attribution
    # plane's [W] leaves are window slots, not host rows — pass
    # through. The [H, F] lineage sub-rings and their [H] counters ARE
    # host rows mutated inside the fixpoint: they gather/scatter by
    # the default leading-dim rule, keeping row attribution exact.
    if names and names[0] == "causality" and names[-1].startswith("adv_"):
        return True
    if names and names[-1] in REPLICATED_FIELDS and (
        names[-2] == "net" if len(names) > 1
        else isinstance(sim, NetState)
    ):
        return True
    return False


def gather_lanes(sim, idx: jax.Array):
    """Compact view of `sim` holding rows `idx` ([S] i32, distinct)."""
    def g(path, leaf):
        if _replicated(path, sim) or jnp.ndim(leaf) == 0:
            return leaf
        return leaf[idx]

    return jax.tree_util.tree_map_with_path(g, sim)


def scatter_lanes(full, compact, idx: jax.Array):
    """Write a compact Sim's rows back into the full-width `full`.
    Replicated/scalar leaves take the compact branch's value (they are
    whole-sim state the fixpoint may have updated, e.g. counters)."""
    def s(path, fleaf, cleaf):
        if _replicated(path, full) or jnp.ndim(fleaf) == 0:
            return cleaf
        return fleaf.at[idx].set(cleaf)

    return jax.tree_util.tree_map_with_path(s, full, compact)


def active_indices(active: jax.Array, s: int) -> jax.Array:
    """First `s` row indices with actives packed first ([S] i32,
    distinct, ascending within each group — stable partition)."""
    return jnp.argsort(~active, stable=True)[:s].astype(I32)
