"""Host-side telemetry: ring drains and wall-clock phase timers.

The Harvester pulls the device ring (telemetry/ring.py) into plain
Python records between device calls — after a whole-run program, or
per window from a host-driven loop's on_window hook (the supervisor /
pcap paths), i.e. "between supervisor checkpoints". Like the pcap
drain (utils/pcap.py), it detects overruns from the monotonic write
counter: count advancing more than `capacity` since the last drain
means records were overwritten before the host saw them; the total is
latched in `records_lost` and surfaced as a health warning
(faults/health.py), never silently.

PhaseTimers records named wall-clock spans (trace/compile, device
execute, harvest, export) on the host timeline; export.chrome_trace
draws them as per-shard wall-time tracks alongside the ring's
sim-time track.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.telemetry.causality import (
    ADVANCE_PLANES,
    LINEAGE_PLANES,
    AdvanceRecord,
    CausalityRecord,
)
from shadow_tpu.telemetry.flows import FLOW_PLANES, FlowRecord
from shadow_tpu.telemetry.ring import PLANES


@dataclass
class WindowRecord:
    """One harvested per-window record (host-side ints)."""

    index: int        # monotonic window number (ring count at write)
    wstart: int
    wend: int
    events: int
    micro_steps: int
    routed_local: int
    routed_cross: int
    drops: int
    retx: int
    qocc_min: int
    qocc_max: int
    qocc_sum: int
    active_lanes: int  # host rows live at window start (global)
    fastpath: int      # 1 = drained on the compact [S]-lane branch
    injected: int      # staged events merged this window (global)
    inj_dropped: int   # injected merges lost to full rows (global)
    inj_deferred: int  # staged, still pending beyond wend (gauge)
    # lane-isolated runs: events executed per lane this window
    # (ring.lane_events row); empty tuple when lane fan-out is off
    lane_events: tuple = ()


@dataclass
class Harvester:
    """Incremental ring drain with overrun accounting."""

    seen: int = 0                 # ring count at the last drain
    records: list = field(default_factory=list)
    records_lost: int = 0
    # escalation marks (faults/escalate.py Escalation dicts) the
    # supervisor notes on heal: the ring itself survives a transplant
    # byte-for-byte, but the heal is a host-side act the device never
    # sees — record it here so the manifest's telemetry aggregates
    # carry it next to the windows it interrupted
    escalation_marks: list = field(default_factory=list)
    # --- flow flight-recorder (telemetry/flows.py), drained in the
    # same pass as the window ring so every host loop that already
    # calls drain() (supervisor checkpoints, pcap hook, final harvest)
    # gets flow records for free. flow_enabled latches True the first
    # time a sim with a flow ring passes through drain().
    flow_enabled: bool = False
    flow_seen: int = 0            # flow ring count at the last drain
    flow_records: list = field(default_factory=list)
    flow_lost: int = 0            # ring overrun (host drained too late)
    flow_lost_clamp: int = 0      # device window-clamp loss (cumulative)
    flow_sampled: int = 0         # device cumulative sampled count
    # --- causality planes (telemetry/causality.py), drained in the
    # same pass: per-host lineage sub-rings (caus_seen is a per-host
    # count list) plus the replicated window-advance plane. Enabled
    # latches True the first time a sim with causality passes through.
    caus_enabled: bool = False
    caus_seen: list = field(default_factory=list)   # [H] per-host counts
    caus_records: list = field(default_factory=list)
    caus_lost: int = 0            # per-host ring overrun total
    caus_sampled: int = 0         # device cumulative kept (sum of counts)
    caus_emitted: int = 0         # device cumulative ALL emissions seen
    adv_seen: int = 0             # advance-plane count at the last drain
    adv_records: list = field(default_factory=list)
    adv_lost: int = 0

    def mark_escalation(self, esc) -> None:
        self.escalation_marks.append(
            esc if isinstance(esc, dict) else esc.as_dict())

    def drain(self, sim) -> int:
        """Pull records written since the last drain. Returns how many
        were taken. Tolerates a count REWIND (the supervisor resumed
        from an older checkpoint): already-harvested records past the
        restored count are discarded so replayed windows are not
        double-counted."""
        self._drain_flows(sim)
        self._drain_causality(sim)
        ring = getattr(sim, "telem", None)
        if ring is None:
            return 0
        c = int(np.asarray(ring.count))
        if c < self.seen:
            self.records = [r for r in self.records if r.index < c]
            self.seen = c
        new = c - self.seen
        if new <= 0:
            return 0
        W = ring.capacity
        lost = max(0, new - W)
        self.records_lost += lost
        take = min(new, W)
        idx = np.arange(c - take, c)
        slots = idx % W
        # one bulk ndarray->list conversion per plane, then positional
        # construction (WindowRecord fields are (index,) + PLANES in
        # order) — per-record int() conversions would make the drain
        # the dominant per-window host cost under chunked dispatch
        cols = [np.asarray(getattr(ring, name))[slots].tolist()
                for name, _ in PLANES]
        extras = []
        lane_pl = getattr(ring, "lane_events", None)
        if lane_pl is not None:
            extras.append([tuple(row) for row in
                           np.asarray(lane_pl)[slots].tolist()])
        self.records.extend(
            WindowRecord(*row)
            for row in zip(idx.tolist(), *cols, *extras))
        self.seen = c
        return take

    def _drain_flows(self, sim) -> int:
        """Flow-ring sibling of the window drain: same monotonic-count
        overrun accounting, same rewind tolerance. The device's own
        cumulative sampled/lost scalars are snapshotted as-is (they
        rewind with the checkpoint on a supervisor resume)."""
        ring = getattr(sim, "flows", None)
        if ring is None:
            return 0
        self.flow_enabled = True
        self.flow_sampled = int(np.asarray(ring.sampled))
        self.flow_lost_clamp = int(np.asarray(ring.lost))
        c = int(np.asarray(ring.count))
        if c < self.flow_seen:
            self.flow_records = [r for r in self.flow_records
                                 if r.index < c]
            self.flow_seen = c
        new = c - self.flow_seen
        if new <= 0:
            return 0
        F = ring.capacity
        lost = max(0, new - F)
        self.flow_lost += lost
        take = min(new, F)
        idx = np.arange(c - take, c)
        slots = idx % F
        cols = [np.asarray(getattr(ring, name))[slots].tolist()
                for name, _ in FLOW_PLANES]
        self.flow_records.extend(
            FlowRecord(*row) for row in zip(idx.tolist(), *cols))
        self.flow_seen = c
        return take

    def _drain_causality(self, sim) -> int:
        """Causality drain: the per-host lineage sub-rings (each host
        row is its own monotonic ring — overrun and rewind accounting
        run PER HOST) plus the replicated advance plane (a plain
        flows-style scalar-count ring). Returns total records taken."""
        ring = getattr(sim, "causality", None)
        if ring is None:
            return 0
        self.caus_enabled = True
        counts = np.asarray(ring.count)
        H = counts.shape[0]
        F = ring.capacity
        if len(self.caus_seen) != H:
            self.caus_seen = [0] * H
        self.caus_sampled = int(counts.sum())
        self.caus_emitted = int(np.asarray(ring.seen).sum())
        taken = 0
        planes = None
        for h in range(H):
            c = int(counts[h])
            if c < self.caus_seen[h]:
                self.caus_records = [
                    r for r in self.caus_records
                    if not (r.host == h and r.index >= c)]
                self.caus_seen[h] = c
            new = c - self.caus_seen[h]
            if new <= 0:
                continue
            if planes is None:
                # one device_get per plane, shared by every host row
                planes = [np.asarray(getattr(ring, name))
                          for name, _ in LINEAGE_PLANES]
            lost = max(0, new - F)
            self.caus_lost += lost
            take = min(new, F)
            idx = np.arange(c - take, c)
            slots = idx % F
            cols = [p[h][slots].tolist() for p in planes]
            self.caus_records.extend(
                CausalityRecord(h, *row)
                for row in zip(idx.tolist(), *cols))
            self.caus_seen[h] = c
            taken += take
        taken += self._drain_advance(ring)
        return taken

    def _drain_advance(self, ring) -> int:
        c = int(np.asarray(ring.adv_count))
        if c < self.adv_seen:
            self.adv_records = [r for r in self.adv_records
                                if r.index < c]
            self.adv_seen = c
        new = c - self.adv_seen
        if new <= 0:
            return 0
        W = ring.adv_capacity
        lost = max(0, new - W)
        self.adv_lost += lost
        take = min(new, W)
        idx = np.arange(c - take, c)
        slots = idx % W
        cols = [np.asarray(getattr(ring, name))[slots].tolist()
                for name, _ in ADVANCE_PLANES]
        self.adv_records.extend(
            AdvanceRecord(*row) for row in zip(idx.tolist(), *cols))
        self.adv_seen = c
        return take

    def mean_window_ns(self) -> float | None:
        """Mean harvested window span (wend - wstart) in ns, or None
        when nothing was harvested. Under adaptive_jump this is the
        manifest's evidence that windows actually grew past the static
        min_jump floor."""
        if not self.records:
            return None
        return float(np.mean(
            [r.wend - r.wstart for r in self.records]))

    def summary(self) -> dict:
        """Aggregates for the run manifest / bench line."""
        evs = np.array([r.events for r in self.records], np.int64)
        out = {
            "windows_recorded": len(self.records),
            "records_lost": self.records_lost,
        }
        if len(evs):
            out["events_per_window"] = {
                "p50": float(np.percentile(evs, 50)),
                "p90": float(np.percentile(evs, 90)),
                "p99": float(np.percentile(evs, 99)),
                "mean": float(evs.mean()),
            }
            out["micro_steps_per_window_max"] = int(
                max(r.micro_steps for r in self.records))
            out["qocc_max"] = int(max(r.qocc_max for r in self.records))
            out["fastpath_windows"] = int(
                sum(r.fastpath for r in self.records))
            out["active_lanes_max"] = int(
                max(r.active_lanes for r in self.records))
            out["window_span_ns_mean"] = self.mean_window_ns()
            # injection plane aggregates: the lint cross-checks the
            # manifest's injection.injected against injected_sum when
            # no records were lost; inj_deferred is a gauge, so only
            # the final value means anything
            out["injected_sum"] = int(
                sum(r.injected for r in self.records))
            out["inj_dropped_sum"] = int(
                sum(r.inj_dropped for r in self.records))
            out["inj_deferred_last"] = int(
                self.records[-1].inj_deferred)
            # lane-isolated runs: per-lane harvested event totals —
            # the lint cross-checks these against the manifest's
            # per-lane counters when no records were lost
            if self.records[-1].lane_events:
                R = len(self.records[-1].lane_events)
                out["lane_events_sum"] = [
                    int(sum(r.lane_events[i] for r in self.records
                            if r.lane_events)) for i in range(R)]
        if self.escalation_marks:
            out["escalations"] = len(self.escalation_marks)
        if self.flow_enabled:
            # headline flow accounting only — the full histogram /
            # traffic-matrix fan-out is the manifest's top-level
            # "flows" block (telemetry/flows.flows_manifest_block)
            out["flows_sampled"] = int(self.flow_sampled)
            out["flows_harvested"] = len(self.flow_records)
            out["flows_lost_ring"] = int(self.flow_lost)
            out["flows_lost_window_clamp"] = int(self.flow_lost_clamp)
        if self.caus_enabled:
            # headline causality accounting — the chains / binding
            # fan-out is the manifest's top-level "causality" block
            # (telemetry/causality.causality_manifest_block)
            out["causality_sampled"] = int(self.caus_sampled)
            out["causality_harvested"] = len(self.caus_records)
            out["causality_lost_ring"] = int(self.caus_lost)
            out["causality_windows_attributed"] = len(self.adv_records)
        return out


@dataclass
class Phase:
    name: str
    start_s: float     # offset from the timer origin
    dur_s: float
    shard: int | None  # None = applies to every shard


class PhaseTimers:
    """Named wall-clock spans on one origin, for the wall-time trace
    tracks. `shard=None` spans are drawn on every shard's track (the
    single-controller JAX host drives all shards through one
    timeline)."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.phases: list[Phase] = []

    @contextmanager
    def phase(self, name: str, shard: int | None = None):
        s = time.perf_counter()
        try:
            yield
        finally:
            self.phases.append(Phase(
                name=name, start_s=s - self.t0,
                dur_s=time.perf_counter() - s, shard=shard))

    def totals(self) -> dict:
        """phase name -> total seconds (merged over repeats)."""
        out: dict = {}
        for p in self.phases:
            out[p.name] = out.get(p.name, 0.0) + p.dur_s
        return out
