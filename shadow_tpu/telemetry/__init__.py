"""Window telemetry: device-resident per-window ring + host exports.

See ring.py (the on-device ring and the engine hook), flows.py (the
per-flow latency flight-recorder and its histogram/traffic-matrix
fan-out), causality.py (the event-lineage recorder, window-advance
attribution, and critical-chain reconstruction), harvest.py (the
between-calls drain + wall-clock phase timers), export.py (Chrome
trace / Prometheus text / run manifest)."""

from shadow_tpu.telemetry.ring import (  # noqa: F401
    DEFAULT_CAPACITY,
    TelemetryRing,
    attach,
    make_telem_fn,
)
from shadow_tpu.telemetry.causality import (  # noqa: F401
    CAUSE_NAMES,
    AdvanceRecord,
    CausalityRecord,
    CausalityState,
    attach_causality,
    binding_histogram,
    causality_manifest_block,
    cause_name,
    critical_chains,
)
from shadow_tpu.telemetry.flows import (  # noqa: F401
    DEFAULT_SAMPLE_PERIOD,
    FlowRecord,
    FlowRing,
    attach_flows,
    flows_manifest_block,
    latency_histograms,
    make_flow_fn,
    per_lane_latency,
    traffic_matrix,
)
from shadow_tpu.telemetry.harvest import (  # noqa: F401
    Harvester,
    PhaseTimers,
    WindowRecord,
)
from shadow_tpu.telemetry.export import (  # noqa: F401
    chrome_trace,
    metrics_from_manifest,
    prometheus_text,
    run_manifest,
    write_manifest,
    write_metrics,
    write_trace,
)
