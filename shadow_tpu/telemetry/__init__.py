"""Window telemetry: device-resident per-window ring + host exports.

See ring.py (the on-device ring and the engine hook), harvest.py (the
between-calls drain + wall-clock phase timers), export.py (Chrome
trace / Prometheus text / run manifest)."""

from shadow_tpu.telemetry.ring import (  # noqa: F401
    DEFAULT_CAPACITY,
    TelemetryRing,
    attach,
    make_telem_fn,
)
from shadow_tpu.telemetry.harvest import (  # noqa: F401
    Harvester,
    PhaseTimers,
    WindowRecord,
)
from shadow_tpu.telemetry.export import (  # noqa: F401
    chrome_trace,
    prometheus_text,
    run_manifest,
    write_manifest,
    write_metrics,
    write_trace,
)
