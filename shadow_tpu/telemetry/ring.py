"""Device-resident per-window telemetry ring.

The reference's observability is host-side counters sampled whenever
the tracker feels like it (tracker.c); on TPU every host<->device sync
stalls the window loop, so per-window visibility must be *written by
the device program itself*. This module keeps a fixed-capacity ring of
per-window records — one record per window barrier, written as pure
masked one-hot stores (the same no-scatter idiom as events._put and
the pcap capture ring) — that the host drains between device calls
(telemetry/harvest.py).

This ring answers "how did each WINDOW go"; its per-packet sibling is
telemetry/flows.py (the flow flight-recorder), which reuses the same
count-monotonic ring/overrun contract but samples individual
cross-host sends into latency records. Both drain through one
Harvester and surface through the same manifest/metrics/trace fan-out
(telemetry/export.py).

Record fields (one [W] plane each):

- wstart / wend      window bounds in sim-ns
- events             events executed inside the window (global)
- micro_steps        fixpoint iterations (max over shards — the
                     single-shard value; a psum would double-count)
- routed_local       outbox entries whose destination is on the same
                     shard (== all entries on 1 shard)
- routed_cross       outbox entries bound for another shard
- drops              packets dropped this window (all drop classes,
                     net.state.drop_total delta)
- retx               TCP segments retransmitted this window
- qocc_min/max/sum   event-queue occupancy across hosts at the end of
                     the window drain (pre-route)
- active_lanes       host rows holding any event < wend when the
                     window fixpoint started (global psum; the
                     sparse-window census input, core/engine.py)
- fastpath           1 when the window drained on the compact [S]-lane
                     fast path, 0 when it ran full width (replicated:
                     the census branch is globally decided)

Shard invariance: every field is reduced at the window barrier with
the collective that makes it *identical on every shard and equal to
the single-shard value* — psum for totals, pmax for micro_steps /
qocc_max, pmin for qocc_min. The ring is therefore replicated state
(parallel.shard.sim_specs gives the telem subtree P()), and per-window
records are bit-identical for any shard count, except that the
local/cross routing *split* is mesh-dependent (their sum is not).

Overflow: the ring never blocks the device program. `count` is
monotonic and slot = count % capacity (the pcap-ring pattern,
net/state.py cap_count); the host-side harvester detects count
advancing more than `capacity` since its last drain and latches the
lost-record total as a *warning* in faults/health.py — results stay
exact, only observability degraded.

Chunked dispatch: host-driven loops with windows_per_dispatch > 1
(utils/checkpoint.run_windows, net/build.make_chunked_runner) drain
the ring only once per K-window chunk, so size the capacity >=
windows_per_dispatch or the middle of each chunk is overwritten before
the host ever sees it. The overrun latch above is the safety net — the
loss is reported, never silent — but a ring that fits a whole chunk is
the intended configuration.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from shadow_tpu.core import simtime

I32 = jnp.int32
I64 = jnp.int64

# plane name -> dtype, in record order (harvest.py iterates this)
PLANES = (
    ("wstart", I64),
    ("wend", I64),
    ("events", I64),
    ("micro_steps", I64),
    ("routed_local", I64),
    ("routed_cross", I64),
    ("drops", I64),
    ("retx", I64),
    ("qocc_min", I32),
    ("qocc_max", I32),
    ("qocc_sum", I64),
    ("active_lanes", I64),
    ("fastpath", I32),
    # open-system injection (inject/staging.py), all zero when off:
    ("injected", I64),      # staged events merged this window (global)
    ("inj_dropped", I64),   # merges lost to full rows this window
    ("inj_deferred", I64),  # staged, pending beyond wend (replicated)
)

DEFAULT_CAPACITY = 4096


@struct.dataclass
class TelemetryRing:
    """Fixed-capacity ring of per-window records ([W] planes) plus the
    running scalars the per-window deltas are computed against."""

    wstart: jax.Array        # [W] i64
    wend: jax.Array          # [W] i64
    events: jax.Array        # [W] i64
    micro_steps: jax.Array   # [W] i64
    routed_local: jax.Array  # [W] i64
    routed_cross: jax.Array  # [W] i64
    drops: jax.Array         # [W] i64
    retx: jax.Array          # [W] i64
    qocc_min: jax.Array      # [W] i32
    qocc_max: jax.Array      # [W] i32
    qocc_sum: jax.Array      # [W] i64
    active_lanes: jax.Array  # [W] i64
    fastpath: jax.Array      # [W] i32
    injected: jax.Array      # [W] i64
    inj_dropped: jax.Array   # [W] i64
    inj_deferred: jax.Array  # [W] i64
    # monotonic windows-recorded counter; slot = count % W. The host
    # detects overruns from count jumps (never a device-side latch:
    # the whole-run device program cannot see host drains).
    count: jax.Array         # [] i64
    # cumulative counters at the previous record (for per-window deltas
    # of counters that only exist as running totals in NetState/TcpState)
    prev_drops: jax.Array    # [] i64
    prev_retx: jax.Array     # [] i64
    # --- lane-isolated runs (core/lanes.py), both None when off -----
    # Per-lane fan-out of the events plane: lane_events[w, r] is the
    # events lane r executed in window w (delta of the cumulative
    # ctr_events_exec lane share). Single-shard only, like lane
    # isolation itself. None-default: programs without lanes are
    # byte-identical.
    lane_events: Any = None      # [W, R] i64
    prev_lane_exec: Any = None   # [R] i64 cumulative at last record

    @property
    def capacity(self) -> int:
        return self.wstart.shape[0]

    @staticmethod
    def create(capacity: int = DEFAULT_CAPACITY) -> "TelemetryRing":
        if capacity < 1:
            raise ValueError(f"telemetry capacity must be >= 1, got "
                             f"{capacity}")
        planes = {name: jnp.zeros((capacity,), dt) for name, dt in PLANES}
        z = jnp.zeros((), I64)
        return TelemetryRing(count=z, prev_drops=z, prev_retx=z, **planes)


def attach(sim, capacity: int = DEFAULT_CAPACITY):
    """Return `sim` with a telemetry ring attached (no-op if one
    already is). Sim.telem defaults to None — a None field contributes
    no pytree leaves, so checkpoints and jitted programs built without
    telemetry are untouched; attaching is an explicit opt-in that
    changes the pytree structure (and therefore retraces).

    Lane-isolated sims (core/lanes.py — attach lanes FIRST) get the
    per-lane event fan-out planes sized off sim.lanes.replicas."""
    if getattr(sim, "telem", None) is not None:
        return sim
    ring = TelemetryRing.create(capacity)
    lanes = getattr(sim, "lanes", None)
    if lanes is not None:
        ring = ring.replace(
            lane_events=jnp.zeros((capacity, lanes.replicas), I64),
            prev_lane_exec=jnp.zeros((lanes.replicas,), I64))
    return sim.replace(telem=ring)


def _record(ring: TelemetryRing, vals: dict) -> TelemetryRing:
    """Masked one-hot store of one record at slot count % W."""
    W = ring.capacity
    slot = (ring.count % W).astype(I32)
    sel = jnp.arange(W, dtype=I32) == slot
    new = {
        k: jnp.where(sel, jnp.asarray(v).astype(getattr(ring, k).dtype),
                     getattr(ring, k))
        for k, v in vals.items()
    }
    return ring.replace(count=ring.count + 1, **new)


def make_telem_fn(axis: str | None = None):
    """Build the engine's telem_fn(sim, wstart, wend, ev_delta,
    ms_delta) -> sim hook. It runs inside step_window after the window
    fixpoint and BEFORE route_fn, so the outbox still holds the
    window's staged cross-host sends (route clears it).

    `axis` names the shard_map mesh axis; None compiles the
    single-shard identity reductions. All cross-shard sums ride ONE
    psum of a stacked i64 vector (plus one pmax vector and one pmin
    scalar) so telemetry adds three small collectives per window, at
    the barrier where the route all-to-all already synchronizes.

    When sim.telem is None the hook is a trace-time no-op: zero ops in
    the compiled program, so telemetry-off runs are bit-for-bit and
    cost-for-cost identical to builds without this hook."""

    if axis is None:
        def psum(x):
            return x

        pmax = pmin = psum
    else:
        def psum(x):
            return lax.psum(x, axis)

        def pmax(x):
            return lax.pmax(x, axis)

        def pmin(x):
            return lax.pmin(x, axis)

    def telem_fn(sim, wstart, wend, ev_delta, ms_delta,
                 active_lanes=None, fastpath=None, inject_deltas=None):
        """active_lanes is the SHARD-LOCAL live-lane count (psummed
        into the record below so it rides the existing collective);
        fastpath is the replicated census-branch indicator. Both
        default to zero for callers predating the sparse fast path.
        inject_deltas is the window's (injected, dropped, deferred)
        from inject.merge_staged — the first two are SHARD-LOCAL
        partials that ride the psum stack, deferred is replicated;
        the engine passes it only when injection is live."""
        ring = getattr(sim, "telem", None)
        if ring is None:
            return sim

        from shadow_tpu.net.state import drop_total

        out = sim.outbox
        occupied = out.occupied()
        lane = sim.net.lane_id
        Hl = lane.shape[0]
        base = lane[0]
        # local = destined to a host this shard owns (contiguous block
        # [base, base+Hl), parallel.shard.route_outbox_sharded); on one
        # shard every valid destination is local.
        local = occupied & (out.dst >= base) & (out.dst < base + Hl)
        n_local = jnp.sum(local, dtype=I64)
        n_cross = jnp.sum(occupied, dtype=I64) - n_local

        drops_cum = jnp.sum(drop_total(sim.net), dtype=I64)
        retx_cum = (jnp.sum(sim.tcp.retx_segs, dtype=I64)
                    if getattr(sim, "tcp", None) is not None
                    else jnp.zeros((), I64))
        # shard-local end-of-drain occupancy; reduced below
        qmin_l, qmax_l, qsum_l = sim.events.occupancy()

        active_l = (jnp.zeros((), I64) if active_lanes is None
                    else jnp.asarray(active_lanes).astype(I64))
        z64 = jnp.zeros((), I64)
        inj_l, injdrop_l, injdef = ((z64, z64, z64)
                                    if inject_deltas is None
                                    else inject_deltas)
        sums = psum(jnp.stack([
            ev_delta.astype(I64), n_local, n_cross, drops_cum, retx_cum,
            qsum_l, active_l, inj_l.astype(I64), injdrop_l.astype(I64),
        ]))
        maxes = pmax(jnp.stack([
            ms_delta.astype(I64), qmax_l.astype(I64),
        ]))
        qmin = pmin(qmin_l)

        ring = _record(ring, dict(
            wstart=jnp.asarray(wstart, simtime.DTYPE),
            wend=jnp.asarray(wend, simtime.DTYPE),
            events=sums[0],
            micro_steps=maxes[0],
            routed_local=sums[1],
            routed_cross=sums[2],
            drops=sums[3] - ring.prev_drops,
            retx=sums[4] - ring.prev_retx,
            qocc_sum=sums[5],
            qocc_min=qmin,
            qocc_max=maxes[1],
            active_lanes=sums[6],
            fastpath=(jnp.zeros((), I32) if fastpath is None
                      else jnp.asarray(fastpath).astype(I32)),
            injected=sums[7],
            inj_dropped=sums[8],
            inj_deferred=injdef.astype(I64),
        ))
        ring = ring.replace(prev_drops=sums[3], prev_retx=sums[4])

        # per-lane event fan-out (single-shard: lane isolation's
        # contract — no collective needed). Stored into the slot
        # _record just wrote (count - 1).
        lanes_st = getattr(sim, "lanes", None)
        if getattr(ring, "lane_events", None) is not None \
                and lanes_st is not None:
            from shadow_tpu.core.lanes import lane_sum

            cum = lane_sum(sim.net.ctr_events_exec,
                           lanes_st.replicas).astype(I64)
            delta = cum - ring.prev_lane_exec
            W = ring.capacity
            sel = (jnp.arange(W, dtype=I32)
                   == ((ring.count - 1) % W).astype(I32))
            ring = ring.replace(
                lane_events=jnp.where(sel[:, None], delta[None, :],
                                      ring.lane_events),
                prev_lane_exec=cum)
        return sim.replace(telem=ring)

    return telem_fn
