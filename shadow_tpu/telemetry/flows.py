"""Device-resident flow flight-recorder: per-packet latency sampling.

The telemetry ring (telemetry/ring.py) answers "how did the *window*
go"; this module answers "where did the *packets* go and how long did
they take" — the per-flow attribution ROADMAP items 1-3 need (placement
wants a cross-shard traffic matrix, the gateway arc wants per-flow
latency back out, packed multi-tenant runs want per-lane fan-out).

A FlowRing is a fixed-capacity ring of per-packet records appended at
the window barrier from the staged outbox (every cross-host send passes
through the outbox exactly once, core/events.apply_emissions; same-host
loopback deliveries never cross the fabric and are not sampled).

Record fields (one [F] plane each):

- src / dst        global host ids of the sampled send
- lane             isolation lane of the src host (0 when lane
                   isolation is off, core/lanes.lane_of_host)
- kind             event kind of the staged delivery
- flags            shard-invariant topology bits: FLAG_LOOPBACK
                   (src == dst), FLAG_CROSS_VERTEX (src and dst attach
                   to different topology vertices), FLAG_CROSS_LANE
                   (src and dst in different isolation lanes).
                   Physical cross-*shard* classification is host-side
                   (path_of_host) because it depends on the mesh, like
                   the routed local/cross split.
- t_enq            window start — the packet was staged inside
                   [wstart, wend), so wstart bounds its enqueue time
- t_route          window end: the barrier where the send crossed (or
                   would cross) the shard exchange
- t_deliver        the delivery timestamp carried by the event

Determinism / shard invariance (the non-negotiable): sampling is a
pure hash of (time, dst, src, seq) — splitmix64 finalizer, keep when
hash % sample_period == 0 — never host randomness. Append order is the
global (source host, outbox slot) order: rows are contiguous ascending
global host ids per shard, so each shard's sampled entries form a
contiguous block of the global order; the cross-shard prefix offset is
an all_gather of per-shard sampled counts. Each ring slot therefore
has exactly one writer, and the cross-shard merge is ONE psum of the
stacked plane deltas (each shard contributes its own writes, zeros
elsewhere) — records are bit-identical for {1..S} shards and any
windows-per-dispatch chunking, because the ring state threads through
the window loop unchanged.

Overflow: per-window appends are clamped to capacity. `count` is the
monotonic stored-record counter (slot = count % F, the telemetry-ring
pattern — host overruns are detected from count jumps); `sampled` and
`lost` are cumulative device scalars with the exact invariant
count + lost == sampled, which tools/telemetry_lint.py checks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from shadow_tpu.core import simtime

I32 = jnp.int32
I64 = jnp.int64
U64 = jnp.uint64

# plane name -> dtype, in record order (harvest.py drains in this
# order; FlowRecord fields are (index,) + FLOW_PLANES)
FLOW_PLANES = (
    ("src", I32),
    ("dst", I32),
    ("lane", I32),
    ("kind", I32),
    ("flags", I32),
    ("t_enq", I64),
    ("t_route", I64),
    ("t_deliver", I64),
)
_I32_PLANES = tuple(n for n, dt in FLOW_PLANES if dt == I32)
_I64_PLANES = tuple(n for n, dt in FLOW_PLANES if dt == I64)

DEFAULT_CAPACITY = 4096
DEFAULT_SAMPLE_PERIOD = 64

FLAG_LOOPBACK = 1       # src == dst (defensive: outbox is cross-host)
FLAG_CROSS_VERTEX = 2   # src/dst attach to different topology vertices
FLAG_CROSS_LANE = 4     # src/dst in different isolation lanes


@struct.dataclass
class FlowRing:
    """Fixed-capacity ring of sampled per-packet records."""

    src: jax.Array        # [F] i32
    dst: jax.Array        # [F] i32
    lane: jax.Array       # [F] i32
    kind: jax.Array       # [F] i32
    flags: jax.Array      # [F] i32
    t_enq: jax.Array      # [F] i64
    t_route: jax.Array    # [F] i64
    t_deliver: jax.Array  # [F] i64
    # monotonic stored-record counter; slot = count % F
    count: jax.Array      # [] i64
    # cumulative sampled (stored + clamped); count + lost == sampled
    sampled: jax.Array    # [] i64
    lost: jax.Array       # [] i64
    # keep 1-in-N when hash(time,dst,src,seq) % N == 0; static so the
    # sampling constant folds into the compiled program
    sample_period: int = struct.field(pytree_node=False, default=64)

    @property
    def capacity(self) -> int:
        return self.src.shape[0]

    @staticmethod
    def create(capacity: int = DEFAULT_CAPACITY,
               sample_period: int = DEFAULT_SAMPLE_PERIOD) -> "FlowRing":
        if capacity < 1:
            raise ValueError(
                f"flow ring capacity must be >= 1, got {capacity}")
        if sample_period < 1:
            raise ValueError(
                f"flow sample period must be >= 1, got {sample_period}")
        planes = {n: jnp.zeros((capacity,), dt) for n, dt in FLOW_PLANES}
        z = jnp.zeros((), I64)
        return FlowRing(count=z, sampled=z, lost=z,
                        sample_period=int(sample_period), **planes)


def attach_flows(sim, sample_period: int = DEFAULT_SAMPLE_PERIOD,
                 capacity: int = DEFAULT_CAPACITY):
    """Return `sim` with a flow ring attached (no-op if one already
    is). Sim.flows defaults to None — the same opt-in contract as
    sim.telem: a None field contributes no pytree leaves, so programs,
    checkpoints and results built without flow tracing are byte-for-
    byte untouched; attaching changes the pytree and retraces."""
    if getattr(sim, "flows", None) is not None:
        return sim
    return sim.replace(flows=FlowRing.create(capacity, sample_period))


def _mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer (u64 wrap-around arithmetic)."""
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def sample_hash(time, dst, src, seq) -> jax.Array:
    """Deterministic u64 sampling key over the flow identity. Pure
    function of simulated state — the same packet hashes the same on
    any mesh, which is what makes sampling shard-invariant."""
    k = time.astype(U64)
    k = k ^ dst.astype(U64) * jnp.uint64(0x9E3779B97F4A7C15)
    k = k ^ src.astype(U64) * jnp.uint64(0xC2B2AE3D27D4EB4F)
    k = k ^ seq.astype(U64) * jnp.uint64(0x165667B19E3779F9)
    return _mix64(k)


def make_flow_fn(axis: str | None = None):
    """Build the engine's flow_fn(sim, wstart, wend) -> sim hook. Runs
    inside step_window right after telem_fn — after the window fixpoint
    and BEFORE route_fn, so the outbox still holds the window's staged
    sends (route clears it).

    `axis` names the shard_map mesh axis; None compiles single-shard
    identity reductions (no collectives at all). Sharded, the hook adds
    three collectives per window at the barrier the route all-to-all
    already synchronizes: one all_gather of the per-shard sampled
    counts (the append-prefix offsets), and one psum each for the
    stacked i32 / i64 plane deltas.

    When sim.flows is None the hook is a trace-time no-op: zero ops in
    the compiled program."""

    def flow_fn(sim, wstart, wend):
        ring = getattr(sim, "flows", None)
        if ring is None:
            return sim

        out = sim.outbox
        Hl, M = out.dst.shape
        F = ring.capacity
        P = ring.sample_period

        occupied = out.occupied()
        keep = occupied & (sample_hash(out.time, out.dst, out.src,
                                       out.seq) % jnp.uint64(P)
                           == jnp.uint64(0))
        # flatten in (row, slot) order: rows are ascending global host
        # ids, so the local order is a contiguous block of the global
        # (source host, outbox slot) append order
        keep_f = keep.reshape(-1)
        csum = jnp.cumsum(keep_f.astype(I64))
        cnt = csum[-1]

        if axis is None:
            offset = jnp.zeros((), I64)
            total = cnt
        else:
            counts = lax.all_gather(cnt, axis)        # [S], shard order
            sidx = lax.axis_index(axis)
            S = counts.shape[0]
            offset = jnp.sum(
                jnp.where(jnp.arange(S) < sidx, counts, 0), dtype=I64)
            total = jnp.sum(counts, dtype=I64)

        # Scatter-free append: invert the slot map. The local rank-r
        # kept entry lands in ring slot (count + offset + r) % F when
        # offset + r < F (the capacity clamp; the excess is counted,
        # never silently dropped). So for each ring slot s there is at
        # most one writing rank r = (s - count - offset) mod F, and the
        # flattened outbox index of rank r is the first position whose
        # keep-cumsum reaches r+1 — a searchsorted. Everything below is
        # gathers over [F] + elementwise selects: no scatter at all,
        # which is the whole point (XLA lowers a [Hl*M]-update scatter
        # to a serial per-update loop on CPU and a slow path on TPU —
        # the scatter form cost ~46% of end-to-end throughput at 256
        # hosts; this form is in the noise).
        s = jnp.arange(F, dtype=I64)
        r = jnp.mod(s - ring.count - offset, jnp.asarray(F, I64))
        valid = (r < cnt) & ((offset + r) < F)
        i = jnp.clip(jnp.searchsorted(csum, r + 1), 0, Hl * M - 1)

        # gather the F candidate records, then derive lane/flags on the
        # compacted [F] width (not the full outbox width)
        src = out.src.reshape(-1)[i]
        dst = out.dst.reshape(-1)[i]
        kind = out.kind.reshape(-1)[i]
        t_del = out.time.reshape(-1)[i]
        GH = sim.net.vertex_of_host.shape[0]
        lanes_st = getattr(sim, "lanes", None)
        if lanes_st is not None:
            from shadow_tpu.core.lanes import lane_of_host

            R = lanes_st.replicas
            lane_src = lane_of_host(src, GH, R).astype(I32)
            lane_dst = lane_of_host(dst, GH, R).astype(I32)
        else:
            lane_src = jnp.zeros_like(src)
            lane_dst = lane_src
        # gather against replicated topology tables (clamped indexing
        # tolerates the dst == -1 empties; those rows are never valid)
        vsrc = sim.net.vertex_of_host[jnp.clip(src, 0, GH - 1)]
        vdst = sim.net.vertex_of_host[jnp.clip(dst, 0, GH - 1)]
        flags = ((src == dst).astype(I32) * FLAG_LOOPBACK
                 + (vsrc != vdst).astype(I32) * FLAG_CROSS_VERTEX
                 + (lane_src != lane_dst).astype(I32) * FLAG_CROSS_LANE)

        vals = {
            "src": src, "dst": dst, "lane": lane_src, "kind": kind,
            "flags": flags,
            "t_enq": jnp.broadcast_to(
                jnp.asarray(wstart, simtime.DTYPE), (F,)),
            "t_route": jnp.broadcast_to(
                jnp.asarray(wend, simtime.DTYPE), (F,)),
            "t_deliver": t_del,
        }
        new = {
            n: jnp.where(valid, v.astype(getattr(ring, n).dtype),
                         getattr(ring, n))
            for n, v in vals.items()
        }
        if axis is not None:
            # each slot has exactly one writing shard; merge by summing
            # the plane deltas (zeros where this shard did not write)
            d32 = jnp.stack([new[n] - getattr(ring, n)
                             for n in _I32_PLANES])
            d64 = jnp.stack([new[n] - getattr(ring, n)
                             for n in _I64_PLANES])
            d32 = lax.psum(d32, axis)
            d64 = lax.psum(d64, axis)
            new = {n: getattr(ring, n) + d32[i]
                   for i, n in enumerate(_I32_PLANES)}
            new.update({n: getattr(ring, n) + d64[i]
                        for i, n in enumerate(_I64_PLANES)})

        appended = jnp.minimum(total, jnp.asarray(F, I64))
        ring = ring.replace(
            count=ring.count + appended,
            sampled=ring.sampled + total,
            lost=ring.lost + (total - appended),
            **new)
        return sim.replace(flows=ring)

    return flow_fn


# --- host side: records -> histograms / percentiles / traffic matrix --

@dataclass
class FlowRecord:
    """One harvested flow sample (host-side ints). Field order is
    (index,) + FLOW_PLANES — the harvester constructs positionally."""

    index: int      # monotonic append position (ring count at write)
    src: int
    dst: int
    lane: int
    kind: int
    flags: int
    t_enq: int
    t_route: int
    t_deliver: int

    @property
    def latency_ns(self) -> int:
        """Staging-to-delivery latency: the observable the histograms
        bucket. t_enq is the window start, so this over-approximates
        the true enqueue->deliver span by < one window."""
        return self.t_deliver - self.t_enq


def path_of_host(h: int, num_hosts: int, path_shards: int) -> int:
    """Contiguous-block shard of a host — the same decomposition the
    mesh uses (parallel/shard.py: shard s owns [s*Hl, (s+1)*Hl)).
    `path_shards` is a host-side choice: pass the run's physical shard
    count for "where did traffic cross THIS mesh", or a candidate count
    to evaluate a placement before running it."""
    if path_shards <= 1 or num_hosts <= 0:
        return 0
    block = max(1, num_hosts // path_shards)
    return min(h // block, path_shards - 1)


def _pct_sorted(vals: list, q: float) -> int:
    """Nearest-rank percentile over a pre-sorted int list — pure
    integer selection, bit-reproducible across platforms (no float
    interpolation)."""
    if not vals:
        return 0
    i = min(len(vals) - 1, max(0, round(q / 100 * (len(vals) - 1))))
    return vals[i]


def _log2_bucket_lo(lat: int) -> int:
    """Lower bound of the log2 latency bucket holding `lat` ns: bucket
    [2^b, 2^(b+1)) for lat >= 1; the degenerate lat <= 0 lands in
    bucket 0."""
    if lat < 1:
        return 0
    return 1 << (int(lat).bit_length() - 1)


def latency_histograms(records, *, num_hosts: int, path_shards: int = 1
                       ) -> dict:
    """Log-bucketed latency histograms keyed by
    "lane<r>/<srcshard>-><dstshard>/k<kind>". Each value carries the
    sample count, nearest-rank p50/p95/p99 latency, and the sparse
    bucket map {bucket_lo_ns: count} with keys ascending. Keyed by the
    *host-side* path decomposition (path_of_host) so histograms are
    identical for any physical mesh that harvested the same records."""
    lats: dict[str, list] = {}
    for r in records:
        key = (f"lane{r.lane}/"
               f"{path_of_host(r.src, num_hosts, path_shards)}->"
               f"{path_of_host(r.dst, num_hosts, path_shards)}/"
               f"k{r.kind}")
        lats.setdefault(key, []).append(r.latency_ns)
    out = {}
    for key in sorted(lats):
        vs = sorted(lats[key])
        buckets: dict[str, int] = {}
        for v in vs:
            lo = str(_log2_bucket_lo(v))
            buckets[lo] = buckets.get(lo, 0) + 1
        out[key] = {
            "count": len(vs),
            "p50_ns": _pct_sorted(vs, 50),
            "p95_ns": _pct_sorted(vs, 95),
            "p99_ns": _pct_sorted(vs, 99),
            "buckets": {k: buckets[k]
                        for k in sorted(buckets, key=int)},
        }
    return out


def per_lane_latency(records) -> dict:
    """{lane: {count, p50_ns, p95_ns, p99_ns}} — the per-lane metric
    families and Perfetto track summaries."""
    lats: dict[int, list] = {}
    for r in records:
        lats.setdefault(int(r.lane), []).append(r.latency_ns)
    out = {}
    for lane in sorted(lats):
        vs = sorted(lats[lane])
        out[str(lane)] = {
            "count": len(vs),
            "p50_ns": _pct_sorted(vs, 50),
            "p95_ns": _pct_sorted(vs, 95),
            "p99_ns": _pct_sorted(vs, 99),
        }
    return out


def traffic_matrix(records, *, num_hosts: int, path_shards: int) -> list:
    """[S][S] sampled-send counts between contiguous host blocks — the
    placement pass's objective input (minimize off-diagonal mass).
    Multiply by the sample period for an unbiased traffic estimate."""
    S = max(1, path_shards)
    mat = [[0] * S for _ in range(S)]
    for r in records:
        mat[path_of_host(r.src, num_hosts, S)][
            path_of_host(r.dst, num_hosts, S)] += 1
    return mat


def flows_manifest_block(harvester, *, num_hosts: int, shards: int = 1,
                         sample_period: int | None = None) -> dict | None:
    """Build the manifest's top-level "flows" block from a harvester
    that drained a flow ring. None when no flow tracing ran.
    tools/telemetry_lint.py checks: recorded + lost_window_clamp ==
    sampled, harvested + lost_ring <= recorded, histogram bucket sums
    == harvested, traffic-matrix total == harvested."""
    if harvester is None or not getattr(harvester, "flow_enabled", False):
        return None
    recs = harvester.flow_records
    S = max(1, int(shards))
    block = {
        "sample_period": (int(sample_period)
                          if sample_period is not None else None),
        "sampled": int(harvester.flow_sampled),
        "recorded": int(harvester.flow_seen),
        "harvested": len(recs),
        "lost_ring": int(harvester.flow_lost),
        "lost_window_clamp": int(harvester.flow_lost_clamp),
        "path_shards": S,
        "histograms": latency_histograms(
            recs, num_hosts=num_hosts, path_shards=S),
        "per_lane": per_lane_latency(recs),
        "traffic_matrix": traffic_matrix(
            recs, num_hosts=num_hosts, path_shards=S),
    }
    return block
