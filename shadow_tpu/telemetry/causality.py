"""Causal critical-path profiling: event lineage + advance attribution.

Two device-resident planes answer the two "why is it slow" questions a
conservative windowed PDES has (ref: master.c:450-480 — wallclock is
governed by which latency edge binds each window and which causal
event chains serialize hosts):

- An **event-lineage recorder**: inside the window fixpoint, every
  emitted event is sampled by the same pure splitmix64 hash discipline
  as the flow flight-recorder (flows.sample_hash over the event's
  (time, dst, src, seq) identity — a pure function of simulated state,
  so the SAME emissions are kept on any mesh and any chunking) and
  appended scatter-free into a per-HOST sub-ring together with its
  PARENT event key (the popped event whose handler emitted it), host,
  kind and depth. Appends are row-local, so the planes are
  bit-identical across shard counts with zero collectives — unlike the
  flow ring, which needs an all_gather + psum barrier merge.
  Host-side, (parent key -> record key) joins reconstruct the longest
  causal chains: the serialization structure the Pallas arc needs to
  aim at the right ops.

- A **window-advance attribution plane**: once per window, the chunked
  drivers latch WHICH constraint bound wend (min-jump floor, adaptive
  latency edge (a, b), fault-record clamp, injection-horizon clamp,
  end-time), the realized jump vs the available lookahead
  (jump-utilization), and the global active-lane census. The plane is
  [W]-replicated like the telemetry ring: every shard latches the same
  replicated values, so no merge is needed.

Opt-in exactly like Sim.telem / Sim.flows: Sim.causality defaults to
None and contributes no pytree leaves — causality-off runs stay
byte-identical to pre-causality pytrees; attach_causality() retraces.

Coverage note: lineage records emissions made by the window FIXPOINT
(handler micro-steps). Events consumed by a bulk pass (net/bulk.py)
never enter the fixpoint and are not recorded — bulk-dominated
workloads see only the fixpoint residue, which is exactly the part
that serializes micro-steps and so the part worth profiling.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core.events import _onehot, _put
from shadow_tpu.telemetry.flows import (
    _pct_sorted,
    path_of_host,
    sample_hash,
)

I32 = jnp.int32
I64 = jnp.int64
U64 = jnp.uint64

DEFAULT_CAPACITY = 64          # lineage records per HOST sub-ring
DEFAULT_ADV_CAPACITY = 4096    # advance-attribution window records
DEFAULT_SAMPLE_PERIOD = 64     # keep 1-in-N emissions (same as flows)

# Window-advance binding causes, in clamp-priority order: each clamp
# that STRICTLY lowers wend overwrites the cause, so ties report the
# earlier (weaker) constraint — deterministic on every path.
CAUSE_MIN_JUMP = 0        # static floor (or adaptive jump at the floor)
CAUSE_ADAPTIVE_EDGE = 1   # live latency table min over pair_mask
CAUSE_FAULT_RECORD = 2    # clamped to the next fault-plan record time
CAUSE_INJECT_HORIZON = 3  # clamped to the injection staging horizon
CAUSE_END_TIME = 4        # clamped to end_time + 1

CAUSE_NAMES = ("min_jump_floor", "adaptive_edge", "fault_record",
               "inject_horizon", "end_time")


def cause_name(code: int) -> str:
    return (CAUSE_NAMES[code] if 0 <= code < len(CAUSE_NAMES)
            else f"unknown_{code}")


# lineage plane name -> dtype, in record order (harvest.py drains in
# this order; CausalityRecord fields are (host, index) + LINEAGE_PLANES)
LINEAGE_PLANES = (
    ("key", U64),
    ("parent", U64),
    ("dst", I32),
    ("kind", I32),
    ("depth", I64),
    ("t_emit", I64),
    ("t_due", I64),
)

# advance plane name -> dtype (AdvanceRecord fields are (index,) + these)
ADVANCE_PLANES = (
    ("adv_wstart", I64),
    ("adv_wend", I64),
    ("adv_raw", I64),
    ("adv_cause", I32),
    ("adv_edge_a", I32),
    ("adv_edge_b", I32),
    ("adv_active", I64),
)


@struct.dataclass
class CausalityState:
    """Per-host lineage sub-rings + the replicated advance plane."""

    # --- lineage: [H, F] row-local planes; appends never leave the row
    key: jax.Array      # [H, F] u64  sample_hash of the emitted event
    parent: jax.Array   # [H, F] u64  sample_hash of the popped parent
    dst: jax.Array      # [H, F] i32  destination host
    kind: jax.Array     # [H, F] i32  emitted event kind
    depth: jax.Array    # [H, F] i64  events executed on this host so far
    t_emit: jax.Array   # [H, F] i64  parent execution time
    t_due: jax.Array    # [H, F] i64  emitted event timestamp
    count: jax.Array    # [H] i64  monotonic per-host; slot = count % F
    seen: jax.Array     # [H] i64  ALL emissions observed (sampling base)
    execs: jax.Array    # [H] i64  events executed per host (depth source)
    # --- advance attribution: [W] replicated (identical on every shard)
    adv_wstart: jax.Array  # [W] i64
    adv_wend: jax.Array    # [W] i64
    adv_raw: jax.Array     # [W] i64  available lookahead before clamps
    adv_cause: jax.Array   # [W] i32  CAUSE_* code
    adv_edge_a: jax.Array  # [W] i32  binding vertex pair (adaptive), -1
    adv_edge_b: jax.Array  # [W] i32
    adv_active: jax.Array  # [W] i64  GLOBAL active-lane census
    adv_count: jax.Array   # [] i64  monotonic; slot = adv_count % W
    # static so the sampling constant folds into the compiled program
    sample_period: int = struct.field(pytree_node=False,
                                      default=DEFAULT_SAMPLE_PERIOD)

    @property
    def capacity(self) -> int:
        return self.key.shape[1]

    @property
    def adv_capacity(self) -> int:
        return self.adv_wstart.shape[0]

    @property
    def num_hosts(self) -> int:
        return self.key.shape[0]

    @staticmethod
    def create(num_hosts: int, capacity: int = DEFAULT_CAPACITY,
               sample_period: int = DEFAULT_SAMPLE_PERIOD,
               adv_capacity: int = DEFAULT_ADV_CAPACITY
               ) -> "CausalityState":
        if capacity < 1:
            raise ValueError(
                f"causality ring capacity must be >= 1, got {capacity}")
        if sample_period < 1:
            raise ValueError(
                f"causality sample period must be >= 1, got "
                f"{sample_period}")
        if adv_capacity < 1:
            raise ValueError(
                f"causality advance capacity must be >= 1, got "
                f"{adv_capacity}")
        H = int(num_hosts)
        lineage = {n: jnp.zeros((H, capacity), dt)
                   for n, dt in LINEAGE_PLANES}
        adv = {n: jnp.zeros((adv_capacity,), dt)
               for n, dt in ADVANCE_PLANES}
        zh = jnp.zeros((H,), I64)
        return CausalityState(
            count=zh, seen=zh, execs=zh,
            adv_count=jnp.zeros((), I64),
            sample_period=int(sample_period), **lineage, **adv)


def attach_causality(sim, sample_period: int = DEFAULT_SAMPLE_PERIOD,
                     capacity: int = DEFAULT_CAPACITY,
                     adv_capacity: int = DEFAULT_ADV_CAPACITY):
    """Return `sim` with causality tracing attached (no-op if it
    already is). Sim.causality defaults to None — the same opt-in
    contract as sim.telem / sim.flows: a None field contributes no
    pytree leaves, so programs, checkpoints and results built without
    causality are byte-for-byte untouched; attaching retraces."""
    if getattr(sim, "causality", None) is not None:
        return sim
    return sim.replace(causality=CausalityState.create(
        int(sim.events.num_hosts), capacity, sample_period,
        adv_capacity))


def lineage_update(sim, popped, buf, lane_id=None):
    """Record this micro-step's sampled emissions — called from
    window_fixpoint after step_fn and BEFORE apply_emissions, because
    each emission's per-source seq must be recomputed exactly as
    apply_emissions will assign it (q.next_seq + #valid earlier slots
    in the same row; events.py). The emitted event's identity
    (time, dst, src, seq) then hashes to the SAME key its execution
    will hash to as a parent — that equality is the host-side join.

    All writes are row-local one-hot selects over [H, F] planes: no
    scatter, no collectives, bit-identical under sharding/compaction
    because compacted/sharded rows ARE the global rows."""
    cz = sim.causality
    q = sim.events
    H, E = buf.dst.shape
    F = cz.capacity
    P = jnp.uint64(cz.sample_period)
    lane = (jnp.arange(H, dtype=I32) if lane_id is None
            else jnp.asarray(lane_id, I32))
    # depth = events executed on this host INCLUDING the parent whose
    # handler just ran — so a same-host child always records a strictly
    # greater depth than its parent did (lint monotonicity)
    execs = cz.execs + popped.valid.astype(I64)
    parent = jnp.where(
        popped.valid,
        sample_hash(popped.time, lane, popped.src, popped.seq),
        jnp.zeros((), U64))
    key_p, par_p = cz.key, cz.parent
    dst_p, kind_p = cz.dst, cz.kind
    dep_p, te_p, td_p = cz.depth, cz.t_emit, cz.t_due
    count, seen = cz.count, cz.seen
    nvalid = jnp.zeros((H,), I32)
    for e in range(E):
        v = buf.dst[:, e] >= 0
        seq = q.next_seq + nvalid          # apply_emissions' assignment
        k = sample_hash(buf.time[:, e], buf.dst[:, e], lane, seq)
        keep = v & (k % P == jnp.uint64(0))
        sel = _onehot(keep, (count % F).astype(I32), F)
        key_p = _put(key_p, sel, k)
        par_p = _put(par_p, sel, parent)
        dst_p = _put(dst_p, sel, buf.dst[:, e])
        kind_p = _put(kind_p, sel, buf.kind[:, e])
        dep_p = _put(dep_p, sel, execs)
        te_p = _put(te_p, sel, popped.time)
        td_p = _put(td_p, sel, buf.time[:, e])
        count = count + keep.astype(I64)
        seen = seen + v.astype(I64)
        nvalid = nvalid + v.astype(I32)
    return sim.replace(causality=cz.replace(
        key=key_p, parent=par_p, dst=dst_p, kind=kind_p, depth=dep_p,
        t_emit=te_p, t_due=td_p, count=count, seen=seen, execs=execs))


def advance_latch(sim, wstart, wend, cause, edge_a, edge_b, raw_jump,
                  n_active):
    """Latch one window's advance attribution — called once per window
    from step_window. Every input is replicated under sharding (wstart
    and wend come off the lockstep outer loop, the cause/edge/raw come
    from replicated tables, n_active is the census_fn-reduced GLOBAL
    count), so the [W] plane stays identical on every shard."""
    cz = sim.causality
    W = cz.adv_capacity
    sel = jnp.arange(W, dtype=I64) == (cz.adv_count % W)

    def put(plane, val):
        return jnp.where(sel, jnp.asarray(val, plane.dtype), plane)

    cz = cz.replace(
        adv_wstart=put(cz.adv_wstart, wstart),
        adv_wend=put(cz.adv_wend, wend),
        adv_raw=put(cz.adv_raw, raw_jump),
        adv_cause=put(cz.adv_cause, cause),
        adv_edge_a=put(cz.adv_edge_a, edge_a),
        adv_edge_b=put(cz.adv_edge_b, edge_b),
        adv_active=put(cz.adv_active,
                       -1 if n_active is None else n_active),
        adv_count=cz.adv_count + 1)
    return sim.replace(causality=cz)


# ---------------------------------------------------------------- host

@dataclasses.dataclass
class CausalityRecord:
    """One harvested lineage record (host-side ints). `key` is the
    emitted event's identity hash; `parent` the identity hash of the
    event whose handler emitted it. A chain edge exists where some
    record's key equals another's parent AND the times agree
    (child.t_emit == parent.t_due) — the time check screens out the
    astronomically-unlikely 64-bit hash collision."""

    host: int
    index: int     # per-host monotonic ring index
    key: int
    parent: int
    dst: int
    kind: int
    depth: int
    t_emit: int
    t_due: int


@dataclasses.dataclass
class AdvanceRecord:
    """One harvested window-advance attribution record."""

    index: int
    wstart: int
    wend: int
    raw: int       # available lookahead (ns) before record/end clamps
    cause: int     # CAUSE_* code
    edge_a: int    # binding vertex pair under adaptive jump, else -1
    edge_b: int
    active: int    # global active-lane census at window start, -1 n/a

    @property
    def jump(self) -> int:
        return self.wend - self.wstart

    @property
    def utilization_pct(self) -> int | None:
        """Realized jump as an integer percentage of the available
        lookahead (None when raw is degenerate)."""
        if self.raw <= 0:
            return None
        return max(0, min(100, (self.jump * 100) // self.raw))


def critical_chains(records, top_k: int = 5, max_events: int = 32
                    ) -> list:
    """Reconstruct the longest causal chains from harvested lineage
    records by walking (record.parent -> record.key) joins. Chains only
    link where the parent emission was ITSELF sampled (probability 1/P
    per edge at period P; P=1 records every emission and recovers full
    lineage). Returns up to `top_k` chain dicts, longest first, each
    with per-host / per-kind composition and at most `max_events`
    events (tail-truncated towards the chain head)."""
    by_key: dict = {}
    for r in records:
        # duplicate keys (ring wrap re-harvest or a true collision):
        # keep the first — joins stay deterministic
        by_key.setdefault(r.key, r)

    length: dict = {}
    link: dict = {}

    def resolve(rec):
        # iterative parent walk with memoization; a visited set breaks
        # the (collision-only) possibility of a key cycle
        stack, seen_keys = [], set()
        cur = rec
        while True:
            if cur.key in length:
                break
            par = by_key.get(cur.parent)
            ok = (par is not None and par.key != cur.key
                  and par.key not in seen_keys
                  and par.t_due == cur.t_emit)
            if not ok:
                length[cur.key] = 1
                link[cur.key] = None
                break
            stack.append(cur)
            seen_keys.add(cur.key)
            cur = par
        while stack:
            child = stack.pop()
            par = by_key[child.parent]
            length[child.key] = length[par.key] + 1
            link[child.key] = par.key

    for r in by_key.values():
        resolve(r)

    heads = sorted(by_key.values(),
                   key=lambda r: (-length[r.key], r.t_due, r.host,
                                  r.index))
    chains = []
    used = set()
    for head in heads:
        if len(chains) >= top_k:
            break
        if head.key in used:
            continue
        path = []
        k = head.key
        while k is not None:
            rec = by_key[k]
            path.append(rec)
            used.add(k)
            k = link[k]
        path.reverse()     # root first
        per_host: dict = {}
        per_kind: dict = {}
        for rec in path:
            per_host[str(rec.host)] = per_host.get(str(rec.host), 0) + 1
            per_kind[str(rec.kind)] = per_kind.get(str(rec.kind), 0) + 1
        chains.append({
            "length": len(path),
            "span_ns": int(path[-1].t_due - path[0].t_emit),
            "hosts": len(per_host),
            "per_host": per_host,
            "per_kind": per_kind,
            "events": [{
                "key": int(rec.key), "host": int(rec.host),
                "dst": int(rec.dst), "kind": int(rec.kind),
                "depth": int(rec.depth), "t_emit": int(rec.t_emit),
                "t_due": int(rec.t_due),
            } for rec in path[-max_events:]],
        })
    return chains


def binding_histogram(adv_records) -> dict:
    """{cause name: window count} over harvested advance records."""
    out: dict = {}
    for r in adv_records:
        n = cause_name(r.cause)
        out[n] = out.get(n, 0) + 1
    return out


def binding_edges(adv_records) -> dict:
    """Per-edge binding counts for adaptive windows: how often each
    latency-table vertex pair (a, b) was THE constraint that sized the
    window — binding frequency, the weight the placement pass wants
    (ROADMAP item 1), as opposed to traffic volume."""
    out: dict = {}
    for r in adv_records:
        if r.cause == CAUSE_ADAPTIVE_EDGE and r.edge_a >= 0:
            k = f"v{r.edge_a}->v{r.edge_b}"
            out[k] = out.get(k, 0) + 1
    return out


def lineage_traffic_matrix(records, *, num_hosts: int,
                           path_shards: int) -> list:
    """[S][S] cross-host sampled-emission counts by (src path, dst
    path) — the causality twin of flows.traffic_matrix. Built from the
    same hash-sampled identities, so with equal sample periods and
    zero losses on both sides the two matrices are EQUAL (the lint
    cross-checks this when both blocks are present)."""
    S = max(1, int(path_shards))
    m = [[0] * S for _ in range(S)]
    for r in records:
        if r.dst == r.host:
            continue
        a = path_of_host(r.host, num_hosts, S)
        b = path_of_host(r.dst, num_hosts, S)
        m[a][b] += 1
    return m


def causality_manifest_block(harvester, *, num_hosts: int,
                             shards: int = 1,
                             sample_period: int | None = None,
                             path_shards: int = 1,
                             top_k: int = 5) -> dict | None:
    """Build the manifest's top-level "causality" block from a
    Harvester's drained lineage + advance records. None when the run
    carried no causality state. tools/telemetry_lint.py reconciles
    harvested + lost_ring against sampled, the binding-cause counts
    against the attributed window count, chain time/depth monotonicity,
    and the traffic matrix against the flows block when both are
    present (tools/critpath.py then reads this block for the
    speed-of-light report)."""
    if not getattr(harvester, "caus_enabled", False):
        return None
    recs = harvester.caus_records
    advs = harvester.adv_records
    cross = [r for r in recs if r.dst != r.host]
    out = {
        "sampled": int(harvester.caus_sampled),
        "emitted": int(harvester.caus_emitted),
        "harvested": len(recs),
        "lost_ring": int(harvester.caus_lost),
        "cross_host_harvested": len(cross),
        "windows_attributed": len(advs),
        "windows_lost": int(harvester.adv_lost),
        "path_shards": max(1, int(path_shards)),
    }
    if sample_period is not None:
        out["sample_period"] = int(sample_period)
    out["chains"] = critical_chains(recs, top_k=top_k)
    out["causes"] = binding_histogram(advs)
    out["edges"] = binding_edges(advs)
    # the per-window record list (bounded by the adv ring capacity):
    # tools/trace_view.py draws the jump sparkline from it and
    # tools/critpath.py groups its window cohorts by cause
    out["advances"] = [{
        "wstart": int(r.wstart), "jump": int(r.jump),
        "raw": int(r.raw), "cause": cause_name(r.cause),
        **({"edge": f"v{r.edge_a}->v{r.edge_b}"} if r.edge_a >= 0
           else {}),
        **({"utilization_pct": r.utilization_pct}
           if r.utilization_pct is not None else {}),
        **({"active": int(r.active)} if r.active >= 0 else {}),
    } for r in advs]
    utils = sorted(u for u in (r.utilization_pct for r in advs)
                   if u is not None)
    if utils:
        out["jump_utilization_pct"] = {
            "p50": _pct_sorted(utils, 50),
            "p95": _pct_sorted(utils, 95),
            "p99": _pct_sorted(utils, 99),
            "mean": int(sum(utils) // len(utils)),
        }
    H = max(1, int(num_hosts))
    idles = sorted(max(0, min(100, ((H - r.active) * 100) // H))
                   for r in advs if r.active >= 0)
    if idles:
        out["idle_lane_pct"] = {
            "p50": _pct_sorted(idles, 50),
            "p95": _pct_sorted(idles, 95),
            "p99": _pct_sorted(idles, 99),
        }
    out["traffic_matrix"] = lineage_traffic_matrix(
        cross, num_hosts=num_hosts, path_shards=path_shards)
    return out
