"""Telemetry export: Chrome-trace JSON, Prometheus text, run manifest.

Three host-side views over the harvested ring + phase timers:

- chrome_trace(): the Trace Event Format JSON that chrome://tracing
  and Perfetto load. One "sim-time" process track of per-window
  complete ("X") events whose ts/dur are *simulated* microseconds,
  plus one wall-time track per shard carrying the phase-timer spans
  (trace/compile vs device execute vs harvest/export overhead).
- prometheus_text(): the text exposition format, final counter values
  as gauges/counters — scrape-file style for dashboards.
- run_manifest(): the run's identity + outcome in one JSON object:
  config hash, seed, shard count, fault-plan digest, final counters,
  health verdict, telemetry summary. bench.py embeds it in its JSON
  line and the CLI writes it next to the trace.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json


def _us(ns: int) -> float:
    return ns / 1000.0


def chrome_trace(records, timers=None, num_shards: int = 1,
                 flow_records=None, adv_records=None,
                 chains=None, elastic=None) -> dict:
    """Build a Trace Event Format object (dict; json.dump it).

    Sim-time track: pid 0, one "X" event per window record, ts/dur in
    simulated µs (the format's native unit), counters in args.
    Wall-time tracks: pid 1, tid = shard id, phase spans in wall µs
    from the timer origin. Both Chrome and Perfetto accept mixed
    timelines as separate process groups.

    `flow_records` (harvested telemetry/flows.FlowRecord list) adds a
    third process group, pid 2: per-LANE flow tracks on the sim-time
    axis — one thread per isolation lane, one "X" span per sampled
    packet from its staging window to its delivery timestamp, so a
    packed multi-tenant run reads as side-by-side per-tenant latency
    timelines in Perfetto.

    `adv_records` / `chains` (harvested telemetry/causality.py
    AdvanceRecord list and critical_chains() dicts) add pid 3, the
    critical-path group: one thread per top-K causal chain drawing its
    events as spans on the sim-time axis, plus "C" counter tracks for
    jump-utilization and the window binding cause — so "why can't this
    run go faster" reads directly off the trace."""
    events = []
    if elastic:
        # elastic recovery (parallel/elastic.py): one instant event per
        # mesh transition on the sim-time axis, pinned at the verified
        # resume point — the trace shows exactly where the run shrank
        for step in elastic.get("mesh_transitions") or ():
            events.append({
                "ph": "i", "pid": 0, "tid": 0, "s": "g",
                "name": (f"mesh {step.get('from')}->{step.get('to')} "
                         f"({step.get('cause')})"),
                "ts": _us(int(step.get("resume_time_ns", 0) or 0)),
                "args": {"action": step.get("action"),
                         "cause": step.get("cause"),
                         "shard": step.get("shard"),
                         "from_shards": step.get("from"),
                         "to_shards": step.get("to")},
            })
    events.append({"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
                   "args": {"name": "sim-time (simulated µs)"}})
    events.append({"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
                   "args": {"name": "windows"}})
    for r in records:
        events.append({
            "ph": "X", "pid": 0, "tid": 0,
            "name": f"window {r.index}",
            "ts": _us(r.wstart),
            # zero-duration complete events render invisibly; clamp at
            # 1 ns worth of µs so degenerate windows stay clickable
            "dur": max(_us(r.wend - r.wstart), 0.001),
            "args": {
                "events": r.events, "micro_steps": r.micro_steps,
                "routed_local": r.routed_local,
                "routed_cross": r.routed_cross,
                "drops": r.drops, "retx": r.retx,
                "queue_occupancy": {
                    "min": r.qocc_min, "max": r.qocc_max,
                    "sum": r.qocc_sum},
                "active_lanes": r.active_lanes,
                "fastpath": r.fastpath,
                "injected": r.injected,
                "inj_dropped": r.inj_dropped,
                "inj_deferred": r.inj_deferred,
            },
        })
    if timers is not None:
        events.append({"ph": "M", "name": "process_name", "pid": 1,
                       "tid": 0, "args": {"name": "wall-time (µs)"}})
        for s in range(max(num_shards, 1)):
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": s, "args": {"name": f"shard {s}"}})
        for p in timers.phases:
            shards = ([p.shard] if p.shard is not None
                      else range(max(num_shards, 1)))
            for s in shards:
                events.append({
                    "ph": "X", "pid": 1, "tid": s, "name": p.name,
                    "ts": p.start_s * 1e6, "dur": p.dur_s * 1e6,
                    "args": {},
                })
    if flow_records:
        events.append({"ph": "M", "name": "process_name", "pid": 2,
                       "tid": 0,
                       "args": {"name": "flows per-lane (simulated µs)"}})
        for lane in sorted({r.lane for r in flow_records}):
            events.append({"ph": "M", "name": "thread_name", "pid": 2,
                           "tid": lane,
                           "args": {"name": f"lane {lane}"}})
        for r in flow_records:
            events.append({
                "ph": "X", "pid": 2, "tid": r.lane,
                "name": f"{r.src}->{r.dst} k{r.kind}",
                "ts": _us(r.t_enq),
                "dur": max(_us(r.t_deliver - r.t_enq), 0.001),
                "args": {
                    "src": r.src, "dst": r.dst, "kind": r.kind,
                    "flags": r.flags,
                    "latency_ns": r.t_deliver - r.t_enq,
                    "t_route": r.t_route,
                },
            })
    if adv_records or chains:
        events.append({"ph": "M", "name": "process_name", "pid": 3,
                       "tid": 0,
                       "args": {"name":
                                "critical path (simulated µs)"}})
        for rank, ch in enumerate(chains or ()):
            events.append({"ph": "M", "name": "thread_name", "pid": 3,
                           "tid": rank,
                           "args": {"name": f"chain {rank} "
                                            f"(len {ch['length']})"}})
            for ev in ch.get("events", ()):
                events.append({
                    "ph": "X", "pid": 3, "tid": rank,
                    "name": f"h{ev['host']}->h{ev['dst']} k{ev['kind']}",
                    "ts": _us(ev["t_emit"]),
                    "dur": max(_us(ev["t_due"] - ev["t_emit"]), 0.001),
                    "args": {"depth": ev["depth"], "key": ev["key"]},
                })
        for r in (adv_records or ()):
            util = r.utilization_pct
            args = {"cause": r.cause}
            if util is not None:
                args["jump_utilization_pct"] = util
            events.append({
                "ph": "C", "pid": 3, "tid": 0,
                "name": "window_advance",
                "ts": _us(r.wstart),
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def prometheus_text(counters: dict, prefix: str = "shadow_tpu") -> str:
    """Flatten a {name: number} dict into Prometheus text exposition
    lines. Nested dicts become labeled samples
    (name{key="sub"} value)."""
    lines = []
    for name, val in sorted(counters.items()):
        metric = f"{prefix}_{name}"
        if isinstance(val, dict):
            lines.append(f"# TYPE {metric} gauge")
            for k, v in sorted(val.items()):
                lines.append(f'{metric}{{key="{k}"}} {_num(v)}')
        else:
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {_num(val)}")
    return "\n".join(lines) + "\n"


def _num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(int(v))


def config_hash(cfg) -> str:
    """sha256 of the canonicalized NetConfig — two runs with the same
    hash ran the same simulation parameters."""
    d = dataclasses.asdict(cfg)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def fault_plan_digest(plan) -> str | None:
    """sha256 over the compiled plan's record columns (None = no plan
    installed)."""
    if plan is None:
        return None
    cols = [plan.t_ns, plan.kind, plan.a, plan.b, plan.value]
    blob = json.dumps([[int(x) for x in c] for c in cols])
    return hashlib.sha256(blob.encode()).hexdigest()


def final_counters(sim, stats=None) -> dict:
    """Final device counter totals for the manifest / metrics file."""
    import numpy as np

    from shadow_tpu.net.state import drop_total

    net = sim.net
    out = {
        "drops_total": int(np.asarray(drop_total(net)).sum()),
        # broken out so the lint can pin a loss-trimmed program's
        # reliability drops at exactly zero (compile/specialize.py —
        # the trimmed counter is structurally never written)
        "drops_reliability_total": int(
            np.asarray(net.ctr_drop_reliability).sum()),
        "tx_packets_total": int(np.asarray(net.ctr_tx_packets).sum()),
        "rx_packets_total": int(np.asarray(net.ctr_rx_packets).sum()),
        "tx_bytes_total": int(np.asarray(net.ctr_tx_bytes).sum()),
        "rx_bytes_total": int(np.asarray(net.ctr_rx_bytes).sum()),
        "retx_bytes_total": int(np.asarray(net.ctr_tx_retx_bytes).sum()),
        "events_overflow": int(np.asarray(sim.events.overflow)),
        "outbox_overflow": int(np.asarray(sim.outbox.overflow)),
        "rq_overflow": int(np.asarray(net.rq_overflow)),
        "route_elided": int(np.asarray(sim.outbox.route_elided)),
    }
    if getattr(sim, "tcp", None) is not None:
        out["retx_segments_total"] = int(
            np.asarray(sim.tcp.retx_segs).sum())
    if stats is not None:
        out["events_processed"] = int(stats.events_processed)
        out["micro_steps"] = int(stats.micro_steps)
        out["windows"] = int(stats.windows)
        out["fastpath_hit"] = int(stats.fastpath_hit)
        out["fastpath_miss"] = int(stats.fastpath_miss)
    return out


def lanes_manifest_block(health, incidents=()) -> dict | None:
    """Build the manifest's top-level "lanes" block for a lane-isolated
    (packed) run: per-lane counters from the health gather, with each
    quarantined lane carrying its salvage pointer + requeue context
    from the supervisor's LaneIncident records. None when the run
    carried no lane isolation. tools/telemetry_lint.py checks that the
    per-lane overflow counts sum to the run totals and that every
    quarantined lane names its salvage artifact."""
    if health is None or not getattr(health, "lanes_total", 0):
        return None
    inc_dicts = [i if isinstance(i, dict) else i.as_dict()
                 for i in (incidents or ())]
    by_lane = {d["lane"]: d for d in inc_dicts}
    per = []
    for d in health.lanes:
        d = dict(d)
        inc = by_lane.get(d["lane"])
        if inc is not None:
            d["salvage"] = inc.get("salvage")
            d["requeue"] = {"regrow": dict(inc.get("regrow") or {}),
                            "salvaged_from": inc.get("salvaged_from")}
        per.append(d)
    out = {
        "replicas": int(health.lanes_total),
        "quarantined": [int(r) for r in health.lanes_quarantined],
        "contained": bool(health.lane_contained),
        "per_lane": per,
    }
    if inc_dicts:
        out["incidents"] = inc_dicts
    return out


def admission_manifest_block(health) -> dict | None:
    """Build the manifest's top-level "admission" block for a
    STANDALONE resident run (`shadow-tpu --resident`): every lane is
    admitted at boot and holds an open lease, so the lease-count
    conservation the lint checks (admitted == completed + evicted +
    quarantined + resident) folds directly from the device planes —
    there is no host-side lease table in this mode. Fleet-managed
    resident programs build their block from fleet/admission.py's
    LeaseTable instead. None when the run carried no admission
    planes."""
    if health is None or not getattr(health, "resident", False):
        return None
    per = [dict(d) for d in health.admission]
    quarantined = {int(r) for r in
                   getattr(health, "lanes_quarantined", ())}
    completed = sum(1 for d in per
                    if d.get("completed") and d["lane"] not in quarantined)
    return {
        "admitted": len(per),
        "completed": completed,
        "evicted": 0,
        "quarantined": len(quarantined),
        "resident": len(per) - completed - len(quarantined),
        "deferred": 0,
        "per_lane": per,
    }


def run_manifest(*, cfg, seed: int, shards: int, sim, stats=None,
                 health=None, fault_plan=None, harvester=None,
                 timers=None, wall_seconds: float | None = None,
                 compile_s: float | None = None,
                 compile_fresh: bool | None = None,
                 conformance: dict | None = None,
                 run_id: str | None = None,
                 resume_of: str | None = None,
                 escalations=None,
                 preempted: bool | None = None,
                 dispatch: dict | None = None,
                 injection: dict | None = None,
                 lanes: dict | None = None,
                 compile_info: dict | None = None,
                 flows: dict | None = None,
                 admission: dict | None = None,
                 profile: dict | None = None,
                 causality: dict | None = None,
                 specialization: dict | None = None,
                 elastic: dict | None = None) -> dict:
    """The run's identity + outcome (see module docstring).
    `compile_s` is the wall time of the first (compiling) device call;
    `compile_fresh` says whether it actually compiled (True) or was
    served from the persistent compilation cache (False). `run_id` /
    `resume_of` chain preemption-split runs (--resume); `escalations`
    lists the supervisor's healed capacity trips (Escalation records
    or their dicts). `dispatch` records the chunked window loop's
    shape: {"windows_per_dispatch": K, "dispatches": N, "windows":
    [per-dispatch executed-window counts], "adaptive_jump_mean_ns":
    mean harvested window span} — the "windows" list, when present,
    must sum to counters.windows (tools/telemetry_lint.py)."""
    man = {
        "config_hash": config_hash(cfg),
        "seed": int(seed),
        "shards": int(shards),
        "num_hosts": int(cfg.num_hosts),
        "end_time_ns": int(cfg.end_time),
        "fault_plan_digest": fault_plan_digest(fault_plan),
        "counters": final_counters(sim, stats),
    }
    if wall_seconds is not None:
        man["wall_seconds"] = round(float(wall_seconds), 3)
    if compile_s is not None:
        man["compile_s"] = round(float(compile_s), 3)
    if compile_fresh is not None:
        man["compile_fresh"] = bool(compile_fresh)
    if health is not None:
        man["health"] = health.failure_report()
        man["health"]["verdict"] = "fatal" if health.fatal else (
            "warnings" if health.diagnostics() else "clean")
    tel = {"windows_recorded": 0, "records_lost": 0}
    if harvester is not None:
        tel = harvester.summary()
    man["telemetry"] = tel
    if timers is not None:
        man["wall_phases_s"] = {
            k: round(v, 6) for k, v in timers.totals().items()}
    if conformance is not None:
        # dual-mode verdicts (hostrun/runner.py:conformance_block):
        # which workloads ran both backends, and whether they agreed
        man["conformance"] = conformance
    if run_id is not None:
        man["run_id"] = run_id
    if resume_of is not None:
        man["resume_of"] = resume_of
    if escalations:
        man["escalations"] = [
            e if isinstance(e, dict) else e.as_dict()
            for e in escalations]
    if preempted is not None:
        man["preempted"] = bool(preempted)
    if dispatch is not None:
        man["dispatch"] = dispatch
    if injection is not None:
        # open-system event injection (inject/__init__.py
        # manifest_block): device latches + feeder accounting; the
        # lint reconciles injected+dropped+deferred == trace_events
        man["injection"] = injection
    if lanes is not None:
        # lane-isolated packed run (lanes_manifest_block): per-lane
        # counters, quarantine verdicts, salvage/requeue pointers
        man["lanes"] = lanes
    if compile_info is not None:
        # warm-program serving (compile/): program key, bucket plan,
        # hit/miss, and the compile-path timing (load_s on a hit,
        # lower_s+compile_s on a miss). tools/telemetry_lint.py
        # checks key format, hit/timing consistency, and that every
        # bucketed capacity >= its requested value
        man["compile"] = dict(compile_info)
    if flows is not None:
        # per-flow latency tracing (telemetry/flows.py
        # flows_manifest_block): sampling accounting, per-(lane, path,
        # kind) latency histograms, per-lane percentiles, and the
        # cross-shard traffic matrix the placement pass consumes.
        # tools/telemetry_lint.py reconciles recorded + lost ==
        # sampled and the bucket sums
        man["flows"] = flows
    if admission is not None:
        # resident program (fleet/admission.py manifest_block or the
        # CLI's standalone block): lease-count conservation, program-
        # key stability across admission events, degradation-ladder
        # history, per-lane lease planes. tools/telemetry_lint.py
        # checks admitted == completed + evicted + quarantined +
        # resident and the SLO verdicts against the flow percentiles
        man["admission"] = admission
    if profile is not None:
        # jax.profiler capture (--profile-dir / BENCH_PROFILE_DIR):
        # where the TPU trace artifact landed, so the manifest is the
        # one pointer from a run to every artifact it produced
        man["profile"] = dict(profile)
    if causality is not None:
        # causal critical-path profiling (telemetry/causality.py
        # causality_manifest_block): lineage sampling accounting,
        # top-K critical chains, binding-cause histogram, per-edge
        # binding counts, jump-utilization percentiles.
        # tools/telemetry_lint.py reconciles harvested + lost against
        # sampled, the cause counts against the attributed windows,
        # and the traffic matrix against the flows block;
        # tools/critpath.py derives the speed-of-light report from it
        man["causality"] = causality
    if specialization is not None:
        # compile-time capability trimming (compile/specialize.py
        # specialization_block): the derived capability vector, the
        # dropped-capability list baked into this program, and the
        # guard-latch counters proving no dead capability fired.
        # tools/telemetry_lint.py checks vector/dropped consistency,
        # that dropped capabilities' drop counters stayed zero, and
        # that a tripped guard was reported fatal
        man["specialization"] = specialization
    if elastic is not None:
        # elastic degraded-mesh recovery (parallel/elastic.py +
        # faults/supervisor.py _elastic_block): policy, initial/final
        # shard widths, every device loss and divergence record, the
        # ladder steps taken and the mesh transitions among them.
        # tools/telemetry_lint.py checks transition monotonicity
        # (pow2-down or serial), losses + divergences == ladder steps,
        # and the verified-window stamps against the checkpoints
        man["elastic"] = elastic
    return man


def metrics_from_manifest(man: dict) -> dict:
    """Flatten the manifest into the {name: number-or-dict} shape
    prometheus_text() takes."""
    out = dict(man["counters"])
    out["seed"] = man["seed"]
    out["shards"] = man["shards"]
    out["num_hosts"] = man["num_hosts"]
    tel = man.get("telemetry", {})
    out["telemetry_windows_recorded"] = tel.get("windows_recorded", 0)
    out["telemetry_records_lost"] = tel.get("records_lost", 0)
    if "events_per_window" in tel:
        out["events_per_window"] = tel["events_per_window"]
    if "health" in man:
        out["health_fatal"] = bool(man["health"]["fatal"])
    if "compile_s" in man:
        out["compile_seconds"] = man["compile_s"]
        if "compile_fresh" in man:
            out["compile_fresh"] = bool(man["compile_fresh"])
    if "compile" in man:
        c = man["compile"]
        if "hit" in c:
            out["compile_program_hit"] = bool(c["hit"])
        for k in ("load_s", "compile_s", "lower_s"):
            if c.get(k) is not None:
                out[f"compile_program_{k}"] = c[k]
    if "wall_phases_s" in man:
        out["wall_phase_seconds"] = man["wall_phases_s"]
    if "conformance" in man:
        out["conformance_agree"] = man["conformance"].get("agree", 0)
        out["conformance_diverge"] = man["conformance"].get("diverge", 0)
    if "escalations" in man:
        esc = man["escalations"]
        out["escalations_total"] = len(esc)
        # final capacity per grown knob — the dashboard's "what is
        # this run actually sized at now" gauge
        out["escalated_capacity"] = {
            e["knob"]: e["to"] for e in esc if "knob" in e}
    if "preempted" in man:
        out["preempted"] = bool(man["preempted"])
    if "dispatch" in man:
        d = man["dispatch"]
        out["windows_per_dispatch"] = d.get("windows_per_dispatch", 1)
        out["dispatches"] = d.get("dispatches", 0)
        if "adaptive_jump_mean_ns" in d:
            out["adaptive_jump_mean_ns"] = d["adaptive_jump_mean_ns"]
    if "injection" in man:
        inj = man["injection"]
        for k in ("injected", "dropped", "late", "backpressure"):
            if inj.get(k) is not None:
                out[f"inject_{k}"] = inj[k]
    if "lanes" in man:
        from shadow_tpu.core.lanes import lane_metric_families

        ln = man["lanes"]
        out["lanes_replicas"] = ln.get("replicas", 0)
        out["lanes_quarantined_total"] = len(ln.get("quarantined", []))
        out["lanes_contained"] = bool(ln.get("contained", False))
        # per-lane gauge families for every latch the lane report
        # carries (quarantine mask, flush counter, overflow shares,
        # per-lane events) — the scalar roll-ups above say "something
        # tripped", these say WHICH tenant
        out.update(lane_metric_families(ln.get("per_lane", [])))
    if "flows" in man:
        fl = man["flows"]
        for k in ("sampled", "recorded", "harvested", "lost_ring",
                  "lost_window_clamp"):
            if fl.get(k) is not None:
                out[f"flow_{k}"] = fl[k]
        if fl.get("sample_period"):
            out["flow_sample_period"] = fl["sample_period"]
        per_lane = fl.get("per_lane") or {}
        for stat in ("p50_ns", "p95_ns", "p99_ns"):
            fam = {lane: v[stat] for lane, v in sorted(per_lane.items())
                   if stat in v}
            if fam:
                out[f"flow_latency_{stat}"] = fam
        fam = {lane: v["count"] for lane, v in sorted(per_lane.items())
               if "count" in v}
        if fam:
            out["flow_lane_samples"] = fam
    if "admission" in man:
        adm = man["admission"]
        for k in ("admitted", "completed", "evicted", "quarantined",
                  "resident", "deferred"):
            if adm.get(k) is not None:
                out[f"admission_{k}"] = adm[k]
        if "program_key_stable" in adm:
            out["admission_program_key_stable"] = bool(
                adm["program_key_stable"])
        if adm.get("admission_events") is not None:
            out["admission_events"] = adm["admission_events"]
        if adm.get("retraces") is not None:
            out["admission_retraces"] = adm["retraces"]
        if adm.get("degrade_level") is not None:
            out["admission_degrade_level"] = adm["degrade_level"]
        # per-lane lease planes: which tenant occupies which lane, and
        # whether its lease is live — churn debugging needs the lane
        # attribution, not just the scalar counts above
        per = adm.get("per_lane") or []
        for stat, key in (("active", "active"),
                          ("epoch", "epoch"),
                          ("completed", "completed")):
            fam = {str(d["lane"]): int(d[key]) for d in per
                   if key in d}
            if fam:
                out[f"admission_lane_{stat}"] = fam
    if "causality" in man:
        cz = man["causality"]
        for k in ("sampled", "emitted", "harvested", "lost_ring",
                  "cross_host_harvested", "windows_attributed",
                  "windows_lost"):
            if cz.get(k) is not None:
                out[f"causality_{k}"] = cz[k]
        if cz.get("sample_period"):
            out["causality_sample_period"] = cz["sample_period"]
        # binding-cause histogram: one counter per clamp that decided
        # a window end (min_jump_floor / adaptive_edge / fault_record
        # / inject_horizon / end_time) — the dashboard's "what is the
        # simulator waiting on" breakdown
        if cz.get("causes"):
            out["window_binding_cause"] = dict(cz["causes"])
        if cz.get("edges"):
            out["window_binding_edge"] = dict(cz["edges"])
        for key, name in (("jump_utilization_pct",
                           "window_jump_utilization_pct"),
                          ("idle_lane_pct",
                           "causality_idle_lane_pct")):
            fam = cz.get(key) or {}
            if fam:
                out[name] = {k: v for k, v in sorted(fam.items())}
        chains = cz.get("chains") or []
        if chains:
            out["critical_chain_count"] = len(chains)
            out["critical_chain_len_max"] = max(
                c.get("length", 0) for c in chains)
            out["critical_chain_span_ns_max"] = max(
                c.get("span_ns", 0) for c in chains)
    if "elastic" in man:
        # elastic recovery counters: how many devices this run lost,
        # how many integrity trips it took, and how many times the
        # mesh shrank — the dashboard's "how degraded is this run"
        el = man["elastic"]
        out["device_lost_total"] = len(el.get("losses") or ())
        out["shard_divergence_total"] = len(el.get("divergences") or ())
        out["mesh_shrink_total"] = len(el.get("mesh_transitions") or ())
        if el.get("initial_shards") is not None:
            out["elastic_initial_shards"] = int(el["initial_shards"])
        if el.get("final_shards") is not None:
            out["elastic_final_shards"] = int(el["final_shards"])
    hl = man.get("health") or {}
    if hl.get("sentinel"):
        # cross-shard integrity sentinel: barrier checks performed and
        # the verified-state frontier (0 trips => frontier == end time)
        st = hl["sentinel"]
        out["sentinel_checks_total"] = int(st.get("checks", 0) or 0)
        out["sentinel_verified_through_ns"] = int(
            st.get("verified_through_ns", 0) or 0)
    return out


def write_trace(path: str, records, timers=None, num_shards: int = 1,
                flow_records=None, adv_records=None, chains=None):
    with open(path, "w") as f:
        json.dump(chrome_trace(records, timers, num_shards,
                               flow_records=flow_records,
                               adv_records=adv_records, chains=chains), f)
    return path


def write_metrics(path: str, manifest: dict):
    with open(path, "w") as f:
        f.write(prometheus_text(metrics_from_manifest(manifest)))
    return path


def write_manifest(path: str, manifest: dict):
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return path
