"""Build a runnable simulation from a parsed ShadowConfig — the
device-era analog of master's load-configuration + register-plugins +
register-hosts path (ref: master.c:161-398).
"""

from __future__ import annotations

import pathlib
from dataclasses import dataclass
from typing import Callable

import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.xmlconfig import ShadowConfig, kv_arguments
from shadow_tpu.core import simtime
from shadow_tpu.net.build import HostSpec, SimBundle, build
from shadow_tpu.net import tcp_cong
from shadow_tpu.net.state import NetConfig, QDisc, RouterQ

# plugin name -> configure(bundle, assignments) -> handlers tuple.
# assignments: list of (host_index, ProcessSpec). configure must set
# bundle.sim (app state installed) and return the app handler(s).
# An optional `hints(assignments) -> dict of NetConfig overrides` lets
# a model size the fixed-capacity rings before the build (e.g. PHOLD's
# event population is load-proportional; the reference's heaps grow
# dynamically, ours are static shapes that must be provisioned).
_REGISTRY: dict[str, Callable] = {}


def register_plugin(name: str, configure: Callable, hints: Callable = None):
    if hints is not None:
        configure.hints = hints
    _REGISTRY[name] = configure


def plugin_names():
    return sorted(_REGISTRY)


def _configure_phold(bundle: SimBundle, assignments):
    from shadow_tpu.apps import phold

    load = 25
    port = 9000
    for _, spec in assignments:
        kv = kv_arguments(spec.arguments)
        load = int(kv.get("load", load))
        port = int(kv.get("port", port))
    bundle.sim = phold.setup(bundle.sim, load=load, port=port)
    bundle.app_bulk = phold.BULK
    return (phold.handler,)


def _configure_pingpong(bundle: SimBundle, assignments):
    from shadow_tpu.apps import pingpong

    H = bundle.cfg.num_hosts
    client = np.zeros(H, bool)
    server = np.zeros(H, bool)
    server_name = None
    port, count, size = 5000, 10, 64
    for hi, spec in assignments:
        kv = kv_arguments(spec.arguments)
        mode = kv.get("mode", "client")
        port = int(kv.get("port", port))
        count = int(kv.get("count", count))
        size = int(kv.get("size", size))
        if mode == "server":
            server[hi] = True
        else:
            client[hi] = True
            server_name = kv.get("server", server_name)
    if server_name is None:
        si = int(np.argmax(server))
        server_ip = int(bundle.dns.host_ips(H)[si])
    else:
        server_ip = bundle.ip_of(server_name)
    bundle.sim = pingpong.setup(
        bundle.sim, client_mask=jnp.asarray(client),
        server_mask=jnp.asarray(server), server_ip=server_ip,
        server_port=port, count=count, size=size)
    return (pingpong.handler,)


def _configure_bulk(bundle: SimBundle, assignments):
    from shadow_tpu.apps import bulk

    H = bundle.cfg.num_hosts
    client = np.zeros(H, bool)
    server = np.zeros(H, bool)
    server_name = None
    port, nbytes = 8080, 1 << 20
    for hi, spec in assignments:
        kv = kv_arguments(spec.arguments)
        mode = kv.get("mode", "client")
        port = int(kv.get("port", port))
        nbytes = int(kv.get("bytes", nbytes))
        if mode == "server":
            server[hi] = True
        else:
            client[hi] = True
            server_name = kv.get("server", server_name)
    if server_name is None:
        si = int(np.argmax(server))
        server_ip = int(bundle.dns.host_ips(H)[si])
    else:
        server_ip = bundle.ip_of(server_name)
    bundle.sim = bulk.setup(
        bundle.sim, client_mask=jnp.asarray(client),
        server_mask=jnp.asarray(server), server_ip=server_ip,
        server_port=port, total_bytes=nbytes)
    return (bulk.handler,)


def _phold_hints(assignments):
    load = 25
    for _, spec in assignments:
        kv = kv_arguments(spec.arguments)
        load = int(kv.get("load", load))
    # random targeting makes per-host event populations bursty; 4x the
    # mean in-flight count keeps overflow at zero in practice (and
    # overflow is counted, never silent, if it ever isn't)
    cap = max(32, 4 * load)
    return {"event_capacity": cap, "outbox_capacity": cap,
            "router_ring": cap, "in_ring": max(16, 2 * load),
            "tcp": False}


_configure_phold.hints = _phold_hints

register_plugin("phold", _configure_phold)
register_plugin("shadow-plugin-test-phold", _configure_phold)
def _tcp_stream_hints(assignments, n_clients=None):
    # a conservative window can deliver a full receive window of
    # in-flight segments at once (rcvbuf/MSS ~ 122 at the default
    # 174760 B buffer), and a fan-in server absorbs bursts from MANY
    # concurrent senders (whose windows autotune toward the path BDP
    # and, under cubic, overshoot reno's growth) — provision the event
    # rows / outbox / router ring for the aggregate burst
    # (SURVEY.md §7.4.6 capacity policy; overflow is counted, never
    # silent, if these still prove small).
    # sockets_per_host: a many-client server needs listener + active
    # child + a full accept backlog of spawned children at once
    # (ACCEPT_QUEUE=4); 8 slots covers that with headroom, and SYN
    # retry backpressure handles anything beyond it.
    # tcp True: in a mixed config (e.g. bulk + pingpong) the
    # max-merge over plugin hints must keep the TCP machine
    if n_clients is None:
        n_clients = sum(
            1 for _, spec in assignments
            if kv_arguments(spec.arguments).get("mode", "client")
            != "server")
    cap = min(4096, max(256, 64 * max(n_clients, 1)))
    return {"event_capacity": cap, "outbox_capacity": cap,
            "router_ring": cap, "sockets_per_host": 8, "tcp": True}


_configure_bulk.hints = _tcp_stream_hints

def _udp_only_hints(assignments):
    # pingpong is UDP-only: skip building + inlining the TCP machine
    # (an order-of-magnitude smaller device program)
    return {"tcp": False}


_configure_pingpong.hints = _udp_only_hints

def _configure_tgen(bundle: SimBundle, assignments):
    """Open-system traffic endpoints (apps/tgen.py): every host binds
    the tgen UDP socket; the send schedule itself comes from the
    config's <traffic> elements (or --inject-trace), not from here."""
    from shadow_tpu.apps import tgen

    port = 9100
    for _, spec in assignments:
        kv = kv_arguments(spec.arguments)
        port = int(kv.get("port", port))
    bundle.sim = tgen.setup(bundle.sim, port=port)
    return (tgen.handler,)


_configure_tgen.hints = _udp_only_hints
register_plugin("tgen", _configure_tgen)


def _configure_testtcp(bundle: SimBundle, assignments):
    """The reference's dual-mode tcp test plugin (shd-test-tcp):
    positional arguments `<iomode> server` / `<iomode> client
    <server-hostname>` with iomode in blocking / nonblocking-poll /
    nonblocking-epoll / nonblocking-select / iov (test_tcp.c:28
    USAGE). All io modes share one wire behavior — a 20,000-byte
    echo — so they map onto the one device model (apps/echo.py)."""
    from shadow_tpu.apps import echo

    H = bundle.cfg.num_hosts
    client = np.zeros(H, bool)
    server = np.zeros(H, bool)
    server_name = None
    for hi, spec in assignments:
        args = list(spec.arguments)
        mode = args[1] if len(args) > 1 else "server"
        if mode == "server":
            server[hi] = True
        else:
            client[hi] = True
            if len(args) > 2:
                server_name = args[2]
    if server_name in ("localhost", "127.0.0.1"):
        # the loopback configs run client and server on ONE host
        # (tcp-*-loopback.test.shadow.config.xml); 127.0.0.1 rides the
        # 1 ns loopback path (ref: network_interface.c:546-554)
        server_ip = 0x7F000001
    elif server_name is not None:
        server_ip = bundle.ip_of(server_name)
    else:
        si = int(np.argmax(server))
        server_ip = int(bundle.dns.host_ips(H)[si])
    # the reference announces an ephemeral port over a message queue
    # (test_tcp.c:197-206); a fixed well-known port is the same wire
    port = 9999
    bundle.sim = echo.setup(
        bundle.sim, client_mask=jnp.asarray(client),
        server_mask=jnp.asarray(server), server_ip=server_ip,
        server_port=port)
    return (echo.handler,)


def _testtcp_hints(assignments):
    # client/server is the SECOND positional argument here, not a kv
    # "mode"; specs too short to say are servers, matching
    # _configure_testtcp
    n_clients = sum(1 for _, spec in assignments
                    if (list(spec.arguments) + ["server", "server"])[1]
                    != "server")
    return _tcp_stream_hints(assignments, n_clients=n_clients)


_configure_testtcp.hints = _testtcp_hints

register_plugin("testtcp", _configure_testtcp)
register_plugin("shadow-plugin-test-tcp", _configure_testtcp)
register_plugin("libshadow-plugin-test-tcp.so", _configure_testtcp)
def _configure_testudp(bundle: SimBundle, assignments):
    """The reference's udp test plugin (test-udp): positional
    arguments `client <port>` / `server <port>`; the client sends one
    datagram to the server's port and the server echoes it back
    (test_udp.c test_sendto_one_byte) — the pingpong model with
    count=1, size=1."""
    from shadow_tpu.apps import pingpong

    H = bundle.cfg.num_hosts
    client = np.zeros(H, bool)
    server = np.zeros(H, bool)
    port = 5678
    for hi, spec in assignments:
        args = list(spec.arguments)
        mode = args[0] if args else "server"
        if len(args) > 1 and args[1].isdigit():
            port = int(args[1])
        if mode == "server":
            server[hi] = True
        else:
            client[hi] = True
    si = int(np.argmax(server))
    server_ip = int(bundle.dns.host_ips(H)[si])
    bundle.sim = pingpong.setup(
        bundle.sim, client_mask=jnp.asarray(client),
        server_mask=jnp.asarray(server), server_ip=server_ip,
        server_port=port, count=1, size=1)
    return (pingpong.handler,)


def _configure_testdeterminism(bundle: SimBundle, assignments):
    """The reference's determinism fixture plugin
    (shadow-plugin-test-determinism): every host dumps values from
    the simulated random sources and clocks; two runs must be
    byte-identical (determinism1_compare.cmake). Maps to the
    randdump model over the per-host counter streams."""
    from shadow_tpu.apps import randdump

    bundle.sim = randdump.setup(bundle.sim)
    return (randdump.handler,)


register_plugin("testdeterminism", _configure_testdeterminism)
register_plugin("shadow-plugin-test-determinism",
                _configure_testdeterminism)
# the reference's random test plugin dumps simulated-random values for
# the determinism byte-compare (test_random.c reads rand()/urandom —
# all interposed onto the host Random); randdump is the same surface
register_plugin("testrandom", _configure_testdeterminism)
register_plugin("shadow-plugin-test-random", _configure_testdeterminism)


def _vproc_entry(bundle: "SimBundle", hi: int, p, main_fn):
    """One virtual-process registration tuple — the SINGLE place
    defining the plugin env contract and the start/stop mapping
    (stoptime absent OR "0" = run to sim end: the reference maps
    unset to 0, master.c:300, and only schedules a stop when
    stopTime > 0, process.c:1348)."""
    env = {
        "host": bundle.host_names[hi],
        "host_index": hi,
        "args": list(p.arguments),
        "resolve": bundle.ip_of,
        "hosts": bundle.host_names,
        "cfg": bundle.cfg,
    }
    return (
        hi,
        (lambda _h, m=main_fn, e=env: m(e)),
        p.starttime or 0,
        p.stoptime if p.stoptime else -1,
    )


def _vproc_plugin(main_fn, hints=None):
    """Adapt a reftests-style generator into a registry plugin: each
    assigned process becomes a virtual process (the same shape the
    .py-plugin path produces), so the reference's syscall-test configs
    run verbatim (ref: SURVEY.md §4 dual-mode plugins)."""

    def configure(bundle: SimBundle, assignments):
        extra = getattr(bundle, "extra_vprocs", None)
        if extra is None:
            extra = []
            bundle.extra_vprocs = extra
        for hi, p in assignments:
            extra.append(_vproc_entry(bundle, hi, p, main_fn))
        return ()

    if hints is not None:
        configure.hints = hints
    return configure


def _register_reftests():
    from shadow_tpu.apps import reftests as rt

    no_tcp = lambda assignments: {"tcp": False}  # noqa: E731
    stream = lambda assignments: _tcp_stream_hints(  # noqa: E731
        assignments, n_clients=1)
    for names, fn, hints in (
        (("testbind", "libshadow-plugin-test-bind.so"), rt.bind_main, None),
        (("testepoll", "libshadow-plugin-test-epoll.so"),
         rt.epoll_main, no_tcp),
        (("test_epoll_writeable",
          "libshadow-plugin-test-epoll-writeable.so"),
         rt.epoll_writeable_main, stream),
        (("testpoll", "libshadow-plugin-test-poll.so"),
         rt.poll_main, no_tcp),
        (("testsockbuf", "libshadow-plugin-test-sockbuf.so"),
         rt.sockbuf_main, None),
        (("testtimerfd", "libshadow-plugin-test-timerfd.so"),
         rt.timerfd_main, no_tcp),
        (("testsleep", "libshadow-plugin-test-sleep.so"),
         rt.sleep_main, no_tcp),
        (("testshutdown", "libshadow-plugin-test-shutdown.so"),
         rt.shutdown_main, stream),
        # r5 surface breadth (VERDICT r4 #4)
        (("testfile", "libshadow-plugin-test-file.so"),
         rt.file_main, no_tcp),
        (("testrandom", "shadow-plugin-test-random"),
         rt.random_main, no_tcp),
        (("testsignal", "libshadow-plugin-test-signal.so"),
         rt.signal_main, no_tcp),
        (("testpthreads", "libshadow-plugin-test-pthreads.so"),
         rt.pthreads_main, no_tcp),
        (("test-unistd", "testunistd"), rt.unistd_main, no_tcp),
    ):
        cfgfn = _vproc_plugin(fn, hints)
        for name in names:
            register_plugin(name, cfgfn)


_register_reftests()
register_plugin("testudp", _configure_testudp)
register_plugin("test-udp", _configure_testudp)
register_plugin("pingpong", _configure_pingpong)
register_plugin("tgen-ping", _configure_pingpong)
register_plugin("bulk", _configure_bulk)
register_plugin("tgen-bulk", _configure_bulk)
register_plugin("filetransfer", _configure_bulk)


@dataclass
class LoadedSim:
    bundle: SimBundle
    handlers: tuple
    config: ShadowConfig
    # virtual-process coroutines from .py plugins:
    # (host_index, proc_fn(host)->generator, start_ns, stop_ns)
    vprocs: tuple = ()
    # <traffic> elements compiled to an injection trace
    # (apps/tgen.py compile_trace; feed to inject.Feeder)
    inject_events: tuple = ()


def load(config: ShadowConfig, *, seed: int = 1,
         overrides: dict | None = None,
         base_dir: str | None = None) -> LoadedSim:
    """ShadowConfig -> built SimBundle + app handlers. `overrides`
    carries CLI-level settings (qdisc, buffers, runahead — the
    reference's Options-beats-XML precedence is inverted for host
    element attributes, matching master.c:355-364)."""
    overrides = overrides or {}
    # captured before hint-merging mutates the dict: the rebuild
    # closure below replays the CALLER's overrides, then layers the
    # escalation's capacity bumps on top (so they beat plugin hints
    # the same way CLI flags do)
    caller_overrides = dict(overrides)

    def _resolve(path: str) -> str:
        # a relative <topology path> / <plugin path> is relative to
        # the CONFIG FILE (the reference resolves the same way)
        if base_dir and not pathlib.Path(path).is_absolute():
            return str(pathlib.Path(base_dir) / path)
        return path

    if config.topology_text is not None:
        graphml = config.topology_text
    else:
        with open(_resolve(config.topology_path)) as f:
            graphml = f.read()

    host_specs: list[HostSpec] = []
    assignments: dict[str, list] = {}
    sndbuf = overrides.get("socket_send_buffer", 131072)
    rcvbuf = overrides.get("socket_recv_buffer", 174760)
    for idx, (name, he) in enumerate(config.expanded_hosts()):
        start = min((p.starttime for p in he.processes), default=None)
        stops = [p.stoptime for p in he.processes if p.stoptime]
        # one device app per host: it stops when the last of the
        # host's processes stops (ref: <process stoptime>,
        # process.c:1286-1324); no stoptime = runs to sim end
        stop = max(stops) if stops and len(stops) == len(he.processes) \
            else None
        host_specs.append(HostSpec(
            name=name,
            ip=he.iphint if he.quantity == 1 else None,
            citycode=he.citycodehint,
            countrycode=he.countrycodehint,
            geocode=he.geocodehint,
            type=he.typehint,
            bandwidthdown=he.bandwidthdown,
            bandwidthup=he.bandwidthup,
            proc_start_time=start,
            proc_stop_time=stop,
        ))
        if he.socketsendbuffer:
            sndbuf = he.socketsendbuffer
        if he.socketrecvbuffer:
            rcvbuf = he.socketrecvbuffer
        for p in he.processes:
            if p.plugin not in config.plugins:
                raise ValueError(f"process references unknown plugin "
                                 f"'{p.plugin}'")
            model = config.plugins[p.plugin].path
            assignments.setdefault(model, []).append((idx, p))

    # <traffic> elements compile BEFORE the build: host indices
    # follow expanded_hosts() order (the same order host_specs was
    # filled in above), and the trace length sizes the default
    # staging width the same way plugin hints size the rings
    inject_events: tuple = ()
    if config.traffics:
        from shadow_tpu.apps import tgen

        name_to_index = {name: i for i, (name, _)
                         in enumerate(config.expanded_hosts())}
        inject_events = tuple(tgen.compile_trace(
            config.traffics, name_to_index,
            end_time=config.stoptime))
        overrides.setdefault("inject_lanes",
                             tgen.lanes_for(len(inject_events)))

    # model-provided capacity hints (CLI overrides still win)
    hinted: dict = {}
    for model, asg in assignments.items():
        h = getattr(_REGISTRY.get(model), "hints", None)
        if h is not None:
            for k, v in h(asg).items():
                hinted[k] = max(hinted.get(k, 0), v)
    for k, v in hinted.items():
        overrides.setdefault(k, v)

    qdisc_name = overrides.get("interface_qdisc", "fifo")
    rq_name = overrides.get("router_qdisc", "codel")
    # any <host logpcap="true"> turns the capture ring on
    # (ref: configuration logpcap attr -> pcap hooks,
    # network_interface.c:337-373)
    want_pcap = bool(overrides.get("pcap", False)) or any(
        he.logpcap for _, he in config.expanded_hosts())
    cfg = NetConfig(
        num_hosts=len(host_specs),
        end_time=config.stoptime,
        bootstrap_end=config.bootstraptime,
        seed=seed,
        qdisc=QDisc.RR if qdisc_name == "rr" else QDisc.FIFO,
        router_qdisc={"codel": RouterQ.CODEL, "single": RouterQ.SINGLE,
                      "static": RouterQ.STATIC}[rq_name],
        pcap=want_pcap,
        tcp_cong=tcp_cong.NAMES[
            overrides.get("tcp_congestion_control", "reno")],
        sndbuf=sndbuf,
        rcvbuf=rcvbuf,
        **{k: v for k, v in overrides.items()
           if k in ("sockets_per_host", "event_capacity", "outbox_capacity",
                    "router_ring", "in_ring", "out_ring", "timers_per_host",
                    "emit_capacity", "nic_drain", "tcp", "tcp_ssthresh",
                    "tcp_windows", "cpu_threshold_ns",
                    "cpu_precision_ns", "track_paths",
                    "windows_per_dispatch", "adaptive_jump",
                    "inject_lanes")},
    )
    # Validate plugin references BEFORE the expensive device build: a
    # config typo should fail in milliseconds, not after a multi-minute
    # state build/compile at scale.
    py_modules: dict = {}
    for model in assignments:
        if not model.endswith(".py"):
            if model not in _REGISTRY:
                raise ValueError(
                    f"unknown plugin model '{model}' (registered: "
                    f"{plugin_names()}, or a path to a .py plugin "
                    f"file); register_plugin() to extend")
            continue
        path = _resolve(model)
        # Python-file plugin: the virtual-process form of the
        # reference's plugin .so loading (SURVEY §7.1 — apps are
        # coroutines against the simulated-syscall surface
        # instead of interposed binaries). The module defines
        #   def main(env): ... yield vproc.<syscall>() ...
        # env: host (name), host_index, args (the <process>
        # arguments), resolve(name) -> ip, cfg.
        import importlib.util
        import os
        import sys

        if not os.path.isfile(path):
            raise ValueError(
                f"plugin file '{path}' not found (paths resolve "
                f"relative to the config file)")
        # full-path hash in the name: two plugins may share a basename
        # (clients/app.py vs servers/app.py) and must not collide
        import hashlib

        digest = hashlib.sha1(path.encode()).hexdigest()[:8]
        modname = f"shadow_tpu_plugin_{pathlib.Path(path).stem}_{digest}"
        spec_ = importlib.util.spec_from_file_location(modname, path)
        mod = importlib.util.module_from_spec(spec_)
        # register before exec so pickling / get_type_hints machinery
        # can find the module by name (the documented importlib recipe)
        sys.modules[modname] = mod
        spec_.loader.exec_module(mod)
        # callable is the runtime contract (main(env) must return a
        # generator, but a plain wrapper delegating to one is fine)
        if not callable(getattr(mod, "main", None)):
            raise ValueError(
                f"plugin '{path}' defines no callable main(env) "
                f"(it must return a generator yielding vproc syscalls)")
        py_modules[model] = mod

    bundle = build(cfg, graphml, host_specs)
    if "runahead" in overrides and overrides["runahead"]:
        bundle.min_jump = int(overrides["runahead"]
                              * simtime.ONE_MILLISECOND)

    handlers: list = []
    vprocs: list = []
    for model, asg in assignments.items():
        if model.endswith(".py"):
            mod = py_modules[model]
            for hi, p in asg:
                vprocs.append(_vproc_entry(bundle, hi, p, mod.main))
            continue
        handlers.extend(_REGISTRY[model](bundle, asg))
        # registry plugins may register virtual processes instead of
        # (or alongside) device handlers (_vproc_plugin)
        extra = getattr(bundle, "extra_vprocs", None)
        if extra:
            vprocs.extend(extra)
            bundle.extra_vprocs = []

    if config.faults:
        # Resolve names -> indices against the placed bundle and
        # install the compiled plan + wakeup events. Must happen after
        # plugin configure (which may replace bundle.sim wholesale).
        from shadow_tpu import faults as faults_mod

        if vprocs:
            raise ValueError(
                "fault plans require the on-device window loop; "
                ".py-plugin virtual processes are host-driven and "
                "cannot honor the schedule deterministically")
        records = faults_mod.records_from_config(config, bundle)
        faults_mod.install(bundle, records)

    if config.traffics:
        from shadow_tpu.apps import tgen

        if vprocs:
            raise ValueError(
                "<traffic> injection requires the on-device window "
                "loop; .py-plugin virtual processes are host-driven "
                "and cannot consume injected device events")
        if not handlers:
            # traffic-only config: tgen IS the app
            bundle.sim = tgen.setup(bundle.sim,
                                    port=config.traffics[0].port)
            handlers.append(tgen.handler)
        elif not any(h is tgen.handler for h in handlers):
            raise ValueError(
                "<traffic> elements compile to tgen events, but "
                "another device app owns the app state; run the "
                "traffic hosts under the 'tgen' plugin or drop the "
                "<traffic> elements")

    def _rebuild(new_overrides: dict) -> SimBundle:
        # Full reload — topology placement, app setup, fault install —
        # at the merged capacities. Everything but the overridden
        # shapes is a pure function of (config, seed), so the rebuilt
        # boot state matches the original wherever shapes agree; the
        # escalation transplanter relies on that.
        merged = dict(caller_overrides)
        merged.update(new_overrides)
        return load(config, seed=seed, overrides=merged,
                    base_dir=base_dir).bundle

    bundle.rebuild = _rebuild
    return LoadedSim(bundle=bundle, handlers=tuple(handlers),
                     config=config, vprocs=tuple(vprocs),
                     inject_events=inject_events)
