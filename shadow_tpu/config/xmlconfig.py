"""shadow.config.xml parser — format-compatible with the reference's
GMarkup Configuration (ref: configuration.c, configuration.h:24-108),
covering both element generations the reference accepts:
`<node>`/`<application>` (1.x configs, e.g.
src/test/phold/phold.test.shadow.config.xml) and
`<host>`/`<process>`, plus `<kill time="..."/>` and the
`<shadow stoptime bootstraptime>` attributes.

Plugins cannot be ELF .so paths on a TPU (SURVEY.md §7.1): the
`path` of a `<plugin>` names an app model from the plugin registry
(builtin: phold, pingpong, bulk/tgen; extendable via
register_plugin). `arguments` strings are passed through to the
model's configure hook, split shell-style.
"""

from __future__ import annotations

import shlex
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class PluginSpec:
    id: str
    path: str                      # model name (see plugins registry)


@dataclass
class ProcessSpec:
    plugin: str
    starttime: int                 # ns
    stoptime: Optional[int]        # ns
    arguments: list[str] = field(default_factory=list)


@dataclass
class HostElem:
    """One <host>/<node> element (pre-quantity expansion)
    (ref: configuration.h:62-101)."""

    id: str
    quantity: int = 1
    iphint: Optional[str] = None
    citycodehint: Optional[str] = None
    countrycodehint: Optional[str] = None
    geocodehint: Optional[str] = None
    typehint: Optional[str] = None
    bandwidthdown: Optional[int] = None    # KiB/s
    bandwidthup: Optional[int] = None
    socketrecvbuffer: Optional[int] = None
    socketsendbuffer: Optional[int] = None
    interfacebuffer: Optional[int] = None
    qdisc: Optional[str] = None
    loglevel: Optional[str] = None
    heartbeatfrequency: Optional[int] = None  # seconds
    logpcap: bool = False
    processes: list[ProcessSpec] = field(default_factory=list)


@dataclass
class FaultSpec:
    """One <fault> element — an entry in the run's deterministic fault
    schedule (shadow-tpu extension; the reference only has static
    per-path reliability). `a`/`b` are host *names* (resolved to host
    or attachment-vertex indices by faults.plan.records_from_config
    once placement is known) or raw indices. `value` is a loss
    probability (kind="loss") or seconds of added latency
    (kind="latency").

      <fault time="1.5" kind="linkdown" a="client" b="server"/>
      <fault time="2.0" kind="loss"     a="client" b="server" value="0.05"/>
      <fault time="3.0" kind="crash"    a="relay"/>
      <fault time="4.0" kind="restart"  a="relay"/>
    """

    time_ns: int
    kind: str
    a: str
    b: Optional[str] = None
    value: Optional[float] = None


@dataclass
class TrafficPhase:
    """One phase of a <traffic> element's open-loop schedule. Which
    fields mean anything depends on `kind`:

    - stream: `rate` events/s for `count` events or `duration`
      seconds (whichever is given; count wins when both are).
    - pause: silence for `duration` seconds.
    - markov: a two-state on/off chain sampled per send slot at
      `rate` — in ON the slot emits, then flips OFF with p_off; in
      OFF it stays silent, then flips ON with p_on. `seed` makes the
      sampled trace reproducible (and part of the config, so two
      runs of one config inject identical events).
    """

    kind: str                      # stream | pause | markov
    rate: float = 1.0              # events/s (stream, markov)
    count: Optional[int] = None    # stream: stop after N events
    duration_ns: Optional[int] = None
    size: int = 64                 # payload bytes carried per event
    p_on: float = 0.5              # markov OFF->ON per slot
    p_off: float = 0.5             # markov ON->OFF per slot
    seed: int = 0                  # markov sampling stream


@dataclass
class TrafficSpec:
    """One <traffic> element — a tgen-style open-system workload
    (shadow-tpu extension): an external source drives `host` on a
    declarative phase schedule, compiled by apps/tgen.py into an
    injection trace that streams in through inject/feeder.py instead
    of living in the closed-loop event population.

      <traffic id="crowd" host="client" dst="server" start="1.0">
        <stream rate="2000" count="500" size="512"/>
        <pause duration="0.5"/>
        <markov rate="4000" duration="2.0" p_on="0.2" p_off="0.6"/>
      </traffic>

    `host`/`dst` are host names (indices resolved once placement is
    known, like FaultSpec); `dst` defaults to `host` itself (self-
    directed work, the PHOLD shape).
    """

    id: str
    host: str
    dst: Optional[str] = None
    start_ns: int = 0
    port: int = 9100               # UDP dst port tgen sends to
    phases: list[TrafficPhase] = field(default_factory=list)


@dataclass
class ShadowConfig:
    stoptime: int                  # ns
    bootstraptime: int             # ns
    topology_text: Optional[str]   # inline GraphML
    topology_path: Optional[str]
    plugins: dict[str, PluginSpec]
    hosts: list[HostElem]
    faults: list[FaultSpec] = field(default_factory=list)
    traffics: list[TrafficSpec] = field(default_factory=list)

    def expanded_hosts(self):
        """Yield (name, HostElem) with quantity stamped out the way the
        reference does (hostname, hostname2, hostname3, ...; ref:
        master.c host registration loop)."""
        for h in self.hosts:
            for i in range(h.quantity):
                name = h.id if i == 0 else f"{h.id}{i + 1}"
                yield name, h


_SECONDS = 1_000_000_000


def _seconds_attr(elem, *names, default=None):
    for n in names:
        v = elem.get(n)
        if v is not None:
            return int(float(v) * _SECONDS)
    return default


def _int_attr(elem, *names, default=None):
    for n in names:
        v = elem.get(n)
        if v is not None:
            return int(v)
    return default


def parse_config(text: str) -> ShadowConfig:
    root = ET.fromstring(text)
    if root.tag != "shadow":
        raise ValueError(f"root element must be <shadow>, got <{root.tag}>")

    stoptime = _seconds_attr(root, "stoptime", default=None)
    bootstraptime = _seconds_attr(root, "bootstraptime", default=0)

    topology_text = None
    topology_path = None
    plugins: dict[str, PluginSpec] = {}
    hosts: list[HostElem] = []
    faults: list[FaultSpec] = []
    traffics: list[TrafficSpec] = []

    for child in root:
        if child.tag == "kill":
            stoptime = _seconds_attr(child, "time", default=stoptime)
        elif child.tag == "topology":
            topology_path = child.get("path")
            if child.text and child.text.strip():
                topology_text = child.text
        elif child.tag == "plugin":
            pid = child.get("id")
            if pid is None:
                raise ValueError("<plugin> requires id")
            plugins[pid] = PluginSpec(id=pid, path=child.get("path", pid))
        elif child.tag in ("host", "node"):
            hid = child.get("id")
            if hid is None:
                raise ValueError(f"<{child.tag}> requires id")
            he = HostElem(
                id=hid,
                quantity=_int_attr(child, "quantity", default=1),
                iphint=child.get("iphint") or child.get("ip"),
                citycodehint=child.get("citycodehint"),
                countrycodehint=child.get("countrycodehint"),
                geocodehint=child.get("geocodehint"),
                typehint=child.get("typehint"),
                bandwidthdown=_int_attr(child, "bandwidthdown"),
                bandwidthup=_int_attr(child, "bandwidthup"),
                socketrecvbuffer=_int_attr(child, "socketrecvbuffer"),
                socketsendbuffer=_int_attr(child, "socketsendbuffer"),
                interfacebuffer=_int_attr(child, "interfacebuffer"),
                qdisc=child.get("interfacequeue") or child.get("qdisc"),
                loglevel=child.get("loglevel"),
                heartbeatfrequency=_int_attr(child, "heartbeatfrequency"),
                logpcap=child.get("logpcap", "false").lower() == "true",
            )
            for sub in child:
                if sub.tag in ("process", "application"):
                    plugin = sub.get("plugin")
                    if plugin is None:
                        raise ValueError(f"<{sub.tag}> requires plugin")
                    he.processes.append(ProcessSpec(
                        plugin=plugin,
                        starttime=_seconds_attr(sub, "starttime", "time",
                                                default=0),
                        stoptime=_seconds_attr(sub, "stoptime"),
                        arguments=shlex.split(sub.get("arguments", "")),
                    ))
            hosts.append(he)
        elif child.tag == "fault":
            t = _seconds_attr(child, "time", default=None)
            if t is None:
                raise ValueError("<fault> requires time")
            kind = child.get("kind")
            a = child.get("a")
            if kind is None or a is None:
                raise ValueError("<fault> requires kind and a")
            v = child.get("value")
            faults.append(FaultSpec(
                time_ns=t, kind=kind, a=a, b=child.get("b"),
                value=None if v is None else float(v)))
        elif child.tag == "traffic":
            hid = child.get("host") or child.get("src")
            if hid is None:
                raise ValueError("<traffic> requires host")
            phases = []
            for sub in child:
                if sub.tag == "stream":
                    phases.append(TrafficPhase(
                        kind="stream",
                        rate=float(sub.get("rate", "1")),
                        count=_int_attr(sub, "count"),
                        duration_ns=_seconds_attr(sub, "duration"),
                        size=_int_attr(sub, "size", default=64)))
                elif sub.tag == "pause":
                    phases.append(TrafficPhase(
                        kind="pause",
                        duration_ns=_seconds_attr(
                            sub, "duration", default=_SECONDS)))
                elif sub.tag == "markov":
                    phases.append(TrafficPhase(
                        kind="markov",
                        rate=float(sub.get("rate", "1")),
                        duration_ns=_seconds_attr(
                            sub, "duration", default=_SECONDS),
                        size=_int_attr(sub, "size", default=64),
                        p_on=float(sub.get("p_on", "0.5")),
                        p_off=float(sub.get("p_off", "0.5")),
                        seed=_int_attr(sub, "seed", default=0)))
                else:
                    raise ValueError(
                        f"<traffic> phase <{sub.tag}> unknown "
                        f"(stream | pause | markov)")
            if not phases:
                raise ValueError(
                    f"<traffic host={hid!r}> has no phases")
            traffics.append(TrafficSpec(
                id=child.get("id", hid), host=hid,
                dst=child.get("dst"),
                start_ns=_seconds_attr(child, "start", default=0),
                port=_int_attr(child, "port", default=9100),
                phases=phases))
        # unknown elements are ignored (forward compatible)

    if stoptime is None:
        raise ValueError("config must set <shadow stoptime> or <kill time>")
    if topology_text is None and topology_path is None:
        raise ValueError("config must provide a <topology>")
    return ShadowConfig(
        stoptime=stoptime,
        bootstraptime=bootstraptime,
        topology_text=topology_text,
        topology_path=topology_path,
        plugins=plugins,
        hosts=hosts,
        faults=sorted(faults, key=lambda f: f.time_ns),
        traffics=traffics,
    )


def kv_arguments(args: list[str]) -> dict[str, str]:
    """The reference's phold-style `key=value` argument convention
    (test_phold.c argument parsing)."""
    out = {}
    for a in args:
        if "=" in a:
            k, v = a.split("=", 1)
            out[k] = v
    return out
