"""Built-in example configuration behind `--test` (ref: examples.c —
the reference bakes in a 1000-client filetransfer XML; the same
1000-client bulk-download over one network vertex here, with
--test-clients to scale it down for quick smoke runs)."""

EXAMPLE_GRAPHML = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="packetloss" attr.type="double" for="edge" id="d4" />
  <key attr.name="latency" attr.type="double" for="edge" id="d3" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="d2" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="d1" />
  <graph edgedefault="undirected">
    <node id="poi-1">
      <data key="d1">10240</data>
      <data key="d2">10240</data>
    </node>
    <edge source="poi-1" target="poi-1">
      <data key="d3">50.0</data>
      <data key="d4">0.0</data>
    </edge>
  </graph>
</graphml>"""


def example_body(clients: int, kib: int, server_attrs: str = "",
                 client_attrs: str = "") -> str:
    """The plugin + hosts of the canonical bulk-download example —
    the single source of truth shared by `--test` (inline topology)
    and tools/generate_example_config.py (path topology +
    attachment-hint attrs)."""
    return f"""  <plugin id="filex" path="bulk"/>
  <host id="server" bandwidthdown="102400" bandwidthup="102400"{server_attrs}>
    <process plugin="filex" starttime="1" arguments="mode=server port=80"/>
  </host>
  <host id="client" quantity="{clients}"{client_attrs}>
    <process plugin="filex" starttime="2"
      arguments="mode=client server=server port=80 bytes={kib * 1024}"/>
  </host>"""


def example_config(clients: int = 1000, kib: int = 330,
                   stoptime: int = 60) -> str:
    """ref: example_getTestContents (examples.c:10-30)."""
    return f"""<shadow stoptime="{stoptime}">
  <topology><![CDATA[{EXAMPLE_GRAPHML}]]></topology>
{example_body(clients, kib)}
</shadow>"""
