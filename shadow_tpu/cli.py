"""Command-line entry point — the analog of the reference's bootstrap
+ Options layer (ref: main.c:734-802, options.c). No TLS/relaunch
dance (SURVEY.md §7.5): parse flags, load the XML config, build device
state, run, report.

Flag parity with options.c (flags whose mechanism has no TPU analog
are accepted and mapped or no-op'd, so reference invocations keep
working):
  --workers       -> number of mesh shards (device axis size)
  --scheduler-policy -> accepted; all policies map to the one device
                     scheduler (ref policies are pthread shardings)
  --seed, --runahead, --bootstrap-end, --interface-qdisc,
  --socket-recv-buffer, --socket-send-buffer, --log-level,
  --heartbeat-frequency, --tcp-congestion-control (reno only)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow-tpu",
        description="TPU-native parallel discrete-event network simulator",
    )
    p.add_argument("config", nargs="?", help="shadow.config.xml path")
    p.add_argument("--test", action="store_true",
                   help="run the built-in example config (ref: --test)")
    p.add_argument("--test-clients", type=int, default=100)
    p.add_argument("-w", "--workers", type=int, default=1,
                   help="device shards (ref: worker threads)")
    p.add_argument("-s", "--seed", type=int, default=1)
    p.add_argument("--scheduler-policy", default="device",
                   choices=["device", "host", "steal", "thread",
                            "threadXthread", "threadXhost"],
                   help="accepted for config compatibility; one device "
                        "scheduler implements the window semantics")
    p.add_argument("--runahead", type=int, default=0,
                   help="minimum window (ms), 0 = derive from topology "
                        "min latency (ref: master.c:133-159)")
    p.add_argument("--bootstrap-end", type=int, default=0,
                   help="unlimited-bandwidth bootstrap period (s)")
    p.add_argument("--interface-qdisc", default="fifo",
                   choices=["fifo", "rr"])
    p.add_argument("--socket-recv-buffer", type=int, default=174760)
    p.add_argument("--socket-send-buffer", type=int, default=131072)
    p.add_argument("--tcp-congestion-control", default="reno",
                   choices=["reno"])
    p.add_argument("-l", "--log-level", default="message",
                   choices=["error", "critical", "warning", "message",
                            "info", "debug"])
    p.add_argument("--heartbeat-frequency", type=int, default=60,
                   help="tracker heartbeat interval (s)")
    p.add_argument("--heartbeat-log-level", default="message")
    p.add_argument("-d", "--data-directory", default="shadow.data")
    p.add_argument("--sockets-per-host", type=int, default=4)
    p.add_argument("--event-capacity", type=int, default=32)
    p.add_argument("--version", action="version",
                   version="shadow-tpu 0.1 (capability target: shadow 1.x)")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)

    from shadow_tpu.config.examples import example_config
    from shadow_tpu.config.loader import load
    from shadow_tpu.config.xmlconfig import parse_config
    from shadow_tpu.utils.shadowlog import SimLogger, level_from_name

    if args.test:
        text = example_config(clients=args.test_clients)
    elif args.config:
        with open(args.config) as f:
            text = f.read()
    else:
        print("error: provide a config path or --test", file=sys.stderr)
        return 1

    logger = SimLogger(level=level_from_name(args.log_level))
    cfg = parse_config(text)
    loaded = load(cfg, seed=args.seed, overrides={
        "interface_qdisc": args.interface_qdisc,
        "socket_recv_buffer": args.socket_recv_buffer,
        "socket_send_buffer": args.socket_send_buffer,
        "runahead": args.runahead,
        "sockets_per_host": args.sockets_per_host,
        "event_capacity": args.event_capacity,
    })
    b = loaded.bundle
    logger.message(0, "shadow-tpu", f"built {b.cfg.num_hosts} hosts, "
                   f"min window {b.min_jump} ns, "
                   f"end {b.cfg.end_time} ns")

    t0 = time.time()
    if args.workers > 1:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from shadow_tpu.parallel.shard import run_sharded

        devs = jax.devices()[:args.workers]
        mesh = Mesh(np.array(devs), ("hosts",))
        sim, stats = run_sharded(b, mesh, app_handlers=loaded.handlers)
    else:
        from shadow_tpu.net.build import run

        sim, stats = run(b, app_handlers=loaded.handlers)
    wall = time.time() - t0

    ev = int(stats.events_processed)
    sim_s = b.cfg.end_time / 1e9
    report = {
        "events": ev,
        "windows": int(stats.windows),
        "wall_seconds": round(wall, 3),
        "events_per_second": round(ev / wall, 1) if wall > 0 else None,
        "simulated_seconds_per_wall_second":
            round(sim_s / wall, 3) if wall > 0 else None,
        "overflow": int(sim.events.overflow) + int(sim.outbox.overflow)
        + int(sim.net.rq_overflow),
    }
    logger.message(b.cfg.end_time, "shadow-tpu", "simulation complete "
                   + json.dumps(report))
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
