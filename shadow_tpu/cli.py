"""Command-line entry point — the analog of the reference's bootstrap
+ Options layer (ref: main.c:734-802, options.c). No TLS/relaunch
dance (SURVEY.md §7.5): parse flags, load the XML config, build device
state, run, report.

Flag parity with options.c (flags whose mechanism has no TPU analog
are accepted and mapped or no-op'd, so reference invocations keep
working):
  --workers       -> number of mesh shards (device axis size)
  --scheduler-policy -> accepted; all policies map to the one device
                     scheduler (ref policies are pthread shardings)
  --seed, --runahead, --bootstrap-end, --interface-qdisc,
  --socket-recv-buffer, --socket-send-buffer, --log-level,
  --heartbeat-frequency, --tcp-congestion-control (reno only)
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time

import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow-tpu",
        description="TPU-native parallel discrete-event network simulator",
    )
    p.add_argument("config", nargs="?", help="shadow.config.xml path")
    p.add_argument("--test", action="store_true",
                   help="run the built-in example config (ref: --test)")
    p.add_argument("--test-clients", type=int, default=1000,
                   help="clients in the built-in --test config; the "
                        "reference bakes in 1000 (examples.c:10-12)")
    p.add_argument("-w", "--workers", type=int, default=1,
                   help="device shards (ref: worker threads)")
    p.add_argument("-s", "--seed", type=int, default=1)
    p.add_argument("--scheduler-policy", default="device",
                   choices=["device", "host", "steal", "thread",
                            "threadXthread", "threadXhost"],
                   help="accepted for config compatibility; one device "
                        "scheduler implements the window semantics")
    p.add_argument("--runahead", type=int, default=0,
                   help="minimum window (ms), 0 = derive from topology "
                        "min latency (ref: master.c:133-159)")
    p.add_argument("--bootstrap-end", type=int, default=0,
                   help="unlimited-bandwidth bootstrap period (s)")
    p.add_argument("--interface-qdisc", default="fifo",
                   choices=["fifo", "rr"])
    p.add_argument("--router-qdisc", default="codel",
                   choices=["codel", "single", "static"],
                   help="upstream router queue manager (ref: the "
                        "QueueManagerHooks vtable, router.c; CoDel "
                        "default per host.c:205)")
    p.add_argument("--socket-recv-buffer", type=int, default=174760)
    p.add_argument("--socket-send-buffer", type=int, default=131072)
    p.add_argument("--tcp-congestion-control", default="reno",
                   choices=["reno", "aimd", "cubic"],
                   help="congestion algorithm (ref: the tcp_cong.h "
                        "hook vtable; the reference implements only "
                        "reno, the vtable was designed for all three)")
    p.add_argument("--tcp-ssthresh", type=int, default=0,
                   help="initial slow-start threshold in packets, "
                        "0 = discover via loss (ref: options.c:137)")
    p.add_argument("--tcp-windows", type=int, default=0,
                   help="pin the initial congestion window in packets, "
                        "0 = protocol default (ref: options.c:138)")
    p.add_argument("--cpu-threshold", type=int, default=-1,
                   help="virtual-CPU blocking threshold in microseconds, "
                        "negative disables the CPU model "
                        "(ref: options.c:130)")
    p.add_argument("--cpu-precision", type=int, default=200,
                   help="round CPU delays to this many microseconds "
                        "(ref: options.c:129)")
    p.add_argument("-l", "--log-level", default="message",
                   choices=["error", "critical", "warning", "message",
                            "info", "debug"])
    p.add_argument("--heartbeat-frequency", type=int, default=60,
                   help="tracker heartbeat interval (s)")
    p.add_argument("--heartbeat-log-level", default="message")
    p.add_argument("-i", "--heartbeat-log-info",
                   default="node,socket,ram",
                   help="comma list of heartbeat sections "
                        "('node','socket','ram'); the reference "
                        "defaults to 'node' alone (options.c:92)")
    # Accepted for reference-invocation compatibility; their mechanism
    # has no analog here (no native binaries to preload or debug, no
    # data template tree, interface batching is the fixed 1 ms
    # token-bucket refill) — see the module docstring.
    for flag in ("--preload", "--data-template"):
        p.add_argument(flag, default=None, help=argparse.SUPPRESS)
    for flag in ("--gdb", "--valgrind"):
        p.add_argument(flag, action="store_true", help=argparse.SUPPRESS)
    for flag in ("--interface-batch", "--interface-buffer"):
        p.add_argument(flag, type=int, default=None,
                       help=argparse.SUPPRESS)
    p.add_argument("-d", "--data-directory", default="shadow.data")
    # default None = let the plugin capacity hints size these
    # (loader.py hints; an explicit value always wins, matching the
    # reference's Options-beats-everything precedence)
    p.add_argument("--sockets-per-host", type=int, default=None)
    p.add_argument("--platform", default="auto",
                   help="JAX backend to run on ('auto' = honor "
                        "JAX_PLATFORMS / plugin default; 'cpu' forces "
                        "the CPU backend — the reliable way to run "
                        "without the TPU, since a global sitecustomize "
                        "may re-export JAX_PLATFORMS)")
    p.add_argument("--track-paths", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="count packets per (src,dst) topology vertex "
                        "pair, logged at shutdown (ref: topology.c "
                        "per-path counters); works serial and sharded "
                        "(per-shard partials psum at the barrier); "
                        "--no-track-paths overrides a config that "
                        "enables it")
    p.add_argument("--event-capacity", type=int, default=None)
    p.add_argument("--outbox-capacity", type=int, default=None)
    p.add_argument("--router-ring", type=int, default=None)
    # --- open-system injection (shadow_tpu/inject) -------------------
    p.add_argument("--inject-trace", default=None, metavar="PATH",
                   help="stream an injection trace (newline-JSON or "
                        "binary, see docs/9-injection.md) into the "
                        "simulated hosts; overrides a config's "
                        "<traffic> elements. The injected kinds must "
                        "have a device handler (the tgen plugin, or "
                        "tools/trace_gen.py targeting one)")
    p.add_argument("--inject-lanes", type=int, default=None,
                   help="device staging lanes for injection "
                        "(power of two; default sized from the trace "
                        "length, capped at 1024 — longer traces "
                        "stream through a host-driven loop)")
    # --- window telemetry (shadow_tpu/telemetry) ---------------------
    p.add_argument("--trace-out", default=None,
                   help="write a Chrome-trace/Perfetto JSON of "
                        "per-window telemetry records (sim-time track) "
                        "plus wall-clock phase spans; enables the "
                        "device-resident telemetry ring")
    p.add_argument("--metrics-out", default=None,
                   help="write final counters as Prometheus text "
                        "exposition; enables the telemetry ring")
    p.add_argument("--telemetry-capacity", type=int, default=None,
                   help="telemetry ring capacity in window records "
                        "(default 4096); overruns are latched as a "
                        "health warning, never silently")
    p.add_argument("--flow-sample", type=int, default=0, metavar="N",
                   help="sample 1-in-N cross-host packets into the "
                        "per-flow latency flight recorder "
                        "(telemetry/flows.py): deterministic "
                        "(time,dst,src,seq)-hash sampling, per-lane "
                        "latency histograms and a cross-shard traffic "
                        "matrix in the manifest. 0 (default) = off, "
                        "byte-identical to builds without the recorder")
    p.add_argument("--flow-capacity", type=int, default=None,
                   help="flow ring capacity in sampled records "
                        "(default 4096); window-clamp and overrun "
                        "losses are accounted, never silent")
    p.add_argument("--causality-sample", type=int, default=0, metavar="N",
                   help="sample 1-in-N emitted events into the causal "
                        "lineage recorder (telemetry/causality.py): "
                        "parent/child event keys, window-advance "
                        "attribution (which clamp decided every window "
                        "end), top-K critical chains and a binding-"
                        "cause histogram in the manifest, a critical-"
                        "path track in --trace-out, and the input "
                        "tools/critpath.py turns into a speed-of-light "
                        "report. 0 (default) = off, byte-identical to "
                        "builds without the recorder")
    p.add_argument("--causality-capacity", type=int, default=None,
                   help="per-host lineage sub-ring capacity in sampled "
                        "events (default 64); overruns are accounted "
                        "in the manifest, never silently")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the window "
                        "loop into DIR (view with TensorBoard / "
                        "Perfetto); the artifact path is recorded in "
                        "run_manifest.json")
    # --- run supervisor (faults/supervisor.py) -----------------------
    p.add_argument("--host-kernel", choices=("run", "diff"), default=None,
                   help="execute the config's .py-plugin processes on "
                        "the REAL host kernel (hostrun backend): 'run' "
                        "executes there only; 'diff' runs both backends "
                        "and diffs normalized syscall traces, writing a "
                        "conformance block into run_manifest.json "
                        "(exit 4 on divergence; docs/7-conformance.md)")
    p.add_argument("--host-time-scale", type=float, default=0.05,
                   help="host-kernel backend: simulated seconds -> real "
                        "seconds for sleeps/timers (default 0.05)")
    p.add_argument("--supervise", action="store_true",
                   help="host-driven window loop with health latches, "
                        "periodic checkpoints, and checkpoint-backed "
                        "retry on a latch trip (exit 3 + structured "
                        "failure report when retries are exhausted)")
    p.add_argument("--chunk-windows", type=int, default=None,
                   metavar="K",
                   help="windows per device dispatch for the "
                        "supervised/host-driven loop: K window rounds "
                        "run on device between host barriers, "
                        "amortizing dispatch overhead when windows are "
                        "small (health checks, harvest and checkpoint "
                        "cadence then run per chunk; default 1)")
    p.add_argument("--adaptive-jump", action="store_true", default=None,
                   help="derive each window's span from the LIVE "
                        "latency/reliability tables instead of the "
                        "static precomputed minimum — fault plans that "
                        "raise latencies let windows grow (fewer "
                        "windows, same final state; supervised/"
                        "host-driven loop only)")
    p.add_argument("--checkpoint-every-windows", type=int, default=64,
                   help="supervisor snapshot cadence in windows")
    p.add_argument("--checkpoint-path", default=None,
                   help="snapshot path prefix (default: "
                        "<data-directory>/checkpoint)")
    p.add_argument("--max-retries", type=int, default=2,
                   help="resume attempts after a latch trip before "
                        "giving up")
    p.add_argument("--retry-backoff", type=float, default=0.25,
                   help="base seconds of exponential backoff between "
                        "retries")
    p.add_argument("--max-run-wallclock", type=float, default=None,
                   metavar="SECONDS",
                   help="supervised runs: per-run wallclock deadline "
                        "— when a round barrier finds it spent, take "
                        "the preemption-style final snapshot, latch a "
                        "'deadline' health fault, and exit 3 with the "
                        "snapshot path in the report (--resume "
                        "continues); the in-process counterpart of "
                        "the fleet watchdog (docs/8-fleet.md)")
    p.add_argument("--stall-windows", type=int, default=512,
                   help="consecutive zero-event windows before the "
                        "stall latch trips")
    p.add_argument("--lane-isolation", type=int, default=None,
                   metavar="R",
                   help="partition the hosts into R contiguous lanes "
                        "with lane-scoped health latches "
                        "(core/lanes.py): a capacity trip quarantines "
                        "only the tripped lane — its hosts freeze at "
                        "the window barrier while healthy lanes run to "
                        "completion (blast-radius containment for "
                        "packed ensemble runs; supervised runs salvage "
                        "the sick lane's slice from the last clean "
                        "checkpoint). Lanes must not exchange traffic "
                        "for healthy-lane bit-exactness; single-shard "
                        "only (docs/6-robustness.md)")
    p.add_argument("--resident", action="store_true",
                   help="attach resident-admission lease planes to a "
                        "lane-isolated run (requires --lane-isolation; "
                        "core/lanes.py LaneAdmission): every lane "
                        "boots with an open lease, barriers enforce "
                        "free-lane flush + completion latching, and "
                        "the manifest gains an 'admission' block. "
                        "This is the static-population twin of "
                        "`fleet run --resident`, whose lease table "
                        "churns lanes at barriers (docs/8-fleet.md)")
    p.add_argument("--auto-grow", action="store_true",
                   help="supervisor escalation: a fatal capacity "
                        "overflow (event queue / outbox / router ring) "
                        "doubles the tripped knob, rebuilds at the "
                        "grown shapes, and transplants the last clean "
                        "checkpoint instead of consuming a retry "
                        "(faults/escalate.py)")
    p.add_argument("--max-grow", type=int, default=8,
                   help="escalation budget: total capacity doublings "
                        "allowed across the run (chain-wide)")
    p.add_argument("--specialize", choices=("auto", "off"),
                   default="auto",
                   help="compile-time program specialization "
                        "(compile/specialize.py): auto (default) "
                        "proves capabilities statically dead for this "
                        "build (all-ones reliability table with no "
                        "fault plan touching it; no handler that can "
                        "arm a host timer) and trims their subgraphs "
                        "out of the traced program, keying the "
                        "variant separately in the warm program "
                        "store; a device guard latch turns any "
                        "violated assumption into a fatal health "
                        "fault. off always runs the full program")
    p.add_argument("--resume", default=None, metavar="PATH",
                   help="continue a previous run from its checkpoint: "
                        "a snapshot file, a checkpoint path prefix, or "
                        "a data directory (newest snapshot wins). "
                        "Implies --supervise; capacities recorded in "
                        "the snapshot metadata are applied "
                        "automatically, and a different --workers "
                        "count is fine (snapshots are global-layout)")
    p.add_argument("--version", action="version",
                   version="shadow-tpu 0.1 (capability target: shadow 1.x)")
    return p


def overrides_from_args(args) -> dict:
    """Map parsed CLI flags onto config-loader overrides (None values
    mean "keep the config/default"). Reference units: the CPU knobs
    are microseconds (options.c:129-130), negative threshold = CPU
    model disabled."""
    overrides = {
        "tcp_ssthresh": args.tcp_ssthresh or None,
        "tcp_windows": args.tcp_windows or None,
        "cpu_threshold_ns": (args.cpu_threshold * 1000
                             if args.cpu_threshold >= 0 else None),
        "cpu_precision_ns": (args.cpu_precision * 1000
                             if args.cpu_precision >= 0 else None),
        "interface_qdisc": args.interface_qdisc,
        "router_qdisc": args.router_qdisc,
        "socket_recv_buffer": args.socket_recv_buffer,
        "socket_send_buffer": args.socket_send_buffer,
        "tcp_congestion_control": args.tcp_congestion_control,
        "runahead": args.runahead,
        "sockets_per_host": args.sockets_per_host,
        "event_capacity": args.event_capacity,
        "outbox_capacity": args.outbox_capacity,
        "router_ring": args.router_ring,
        "track_paths": args.track_paths,
        "windows_per_dispatch": args.chunk_windows,
        "adaptive_jump": args.adaptive_jump,
        "inject_lanes": args.inject_lanes,
    }
    return {k: v for k, v in overrides.items() if v is not None}


def _resolve_resume(path: str) -> str | None:
    """--resume accepts a snapshot file, a checkpoint prefix, or a
    data directory; returns the newest matching snapshot path."""
    import os

    from shadow_tpu.utils import checkpoint as ckpt

    if os.path.isdir(path):
        return ckpt.latest_checkpoint(os.path.join(path, "checkpoint"))
    if os.path.isfile(path):
        return path
    return ckpt.latest_checkpoint(path)


def _host_kernel_mode(args, b, loaded, logger) -> int:
    """--host-kernel: execute the config's virtual processes on the
    real OS (hostrun backend). 'diff' additionally runs the simulation
    and compares normalized syscall traces — the dual-mode conformance
    check (docs/7-conformance.md). Exit codes: 0 agree/ran, 2 sandbox
    has no bindable localhost ports, 4 divergence."""
    import os

    from shadow_tpu import hostrun
    from shadow_tpu.hostrun.trace import TraceRecorder

    try:
        hostrun.PortAllocator.preflight()
    except hostrun.PortsUnavailable as e:
        print(f"error: host-kernel backend unavailable: {e}",
              file=sys.stderr)
        return 2

    ip_names = {int(b.ip_of(n)): n for n in b.host_names}
    host_rec = TraceRecorder(ip_names=ip_names)
    ex = hostrun.HostKernelExecutor(
        b, time_scale=args.host_time_scale, trace=host_rec)
    for hi, fn, st, sp in loaded.vprocs:
        ex.spawn(hi, fn, start_time=st, stop_time=sp)
    t0 = time.time()
    ex.run()
    wall = time.time() - t0
    logger.message(0, "shadow-tpu",
                   f"host-kernel run complete: {len(ex.procs)} "
                   f"process(es), {wall:.2f}s wall")
    if args.host_kernel == "run":
        print(json.dumps({"mode": "host-kernel-run",
                          "processes": len(ex.procs),
                          "wall_seconds": round(wall, 3)}))
        return 0

    # diff: the same generators through the simulation, then compare
    from shadow_tpu import telemetry
    from shadow_tpu.process.vproc import ProcessRuntime

    sim_rec = TraceRecorder(ip_names=ip_names)
    rt = ProcessRuntime(b, app_handlers=loaded.handlers)
    rt.trace = sim_rec
    for hi, fn, st, sp in loaded.vprocs:
        rt.spawn(hi, fn, start_time=st, stop_time=sp)
    sim, stats = rt.run()
    res = hostrun.diff_traces(sim_rec.normalized(), host_rec.normalized())
    print(hostrun.render(res))
    name = os.path.basename(args.config) if args.config else "config"
    conf = {"workloads": {name: "agree" if res.agree else "diverge"},
            "agree": int(res.agree), "diverge": int(not res.agree),
            "total": 1}
    man = telemetry.run_manifest(
        cfg=b.cfg, seed=args.seed, shards=1, sim=sim, stats=stats,
        fault_plan=b.fault_plan, conformance=conf)
    os.makedirs(args.data_directory, exist_ok=True)
    mpath = telemetry.write_manifest(
        os.path.join(args.data_directory, "run_manifest.json"), man)
    logger.message(0, "shadow-tpu", f"run manifest -> {mpath}")
    print(json.dumps({"mode": "host-kernel-diff", "agree": res.agree,
                      "manifest": mpath}))
    return 0 if res.agree else 4


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "fleet":
        # `shadow-tpu fleet ...` is its own sub-CLI (fleet/cli.py);
        # delegate before the single-run parser sees the argv
        from shadow_tpu.fleet.cli import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "sweep":
        # `shadow-tpu sweep ...` — the counterfactual sweep engine
        # (sweep/cli.py); same delegation rule as fleet
        from shadow_tpu.sweep.cli import main as sweep_main

        return sweep_main(argv[1:])
    args = make_parser().parse_args(argv)

    # persist compiled device programs across CLI invocations (the
    # netstack step compiles in minutes cold; seconds warm)
    import jax

    from shadow_tpu.utils.compcache import enable_compile_cache

    enable_compile_cache()
    # select the backend through jax.config (an out-of-tree platform
    # plugin's get_backend hook can ignore the env var but the lazy
    # backend init honors the config; must run before backend touch).
    # --platform beats the env var: a global sitecustomize may
    # re-export JAX_PLATFORMS, making the env var unreliable as an
    # expression of user intent.
    import os

    if args.platform != "auto":
        jax.config.update("jax_platforms", args.platform)
    else:
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)

    from shadow_tpu.config.examples import example_config
    from shadow_tpu.config.loader import load
    from shadow_tpu.config.xmlconfig import parse_config
    from shadow_tpu.utils.shadowlog import SimLogger, level_from_name

    if args.test:
        text = example_config(clients=args.test_clients)
    elif args.config:
        with open(args.config) as f:
            text = f.read()
    else:
        print("error: provide a config path or --test", file=sys.stderr)
        return 1

    logger = SimLogger(level=level_from_name(args.log_level))
    # jax.profiler capture state (--profile-dir): started just before
    # the run branch, stopped at convergence and again (idempotently)
    # in the finally so an abort never leaves the tracer running
    _prof = {"on": False}

    def _stop_profile():
        if _prof["on"]:
            _prof["on"] = False
            with contextlib.suppress(Exception):
                jax.profiler.stop_trace()

    # flush on every exit path so a mid-run failure still
    # surfaces the buffered sim log (the reference flushes
    # each round, slave.c:446-450)
    try:
        cfg = parse_config(text)
        # --resume: find the snapshot BEFORE building, because its
        # recorded capacities must size the build (a post-escalation
        # snapshot is larger than the config says; a mismatch is
        # diagnosed by name either way, never resumed into garbage)
        resume_ckpt = None
        resume_meta = None
        overrides = overrides_from_args(args)
        if args.resume:
            resume_ckpt = _resolve_resume(args.resume)
            if resume_ckpt is None:
                print(f"error: no checkpoint found at {args.resume}",
                      file=sys.stderr)
                return 1
            args.supervise = True
            from shadow_tpu.utils import checkpoint as ckpt_mod

            resume_meta = ckpt_mod.peek_meta(resume_ckpt)
            for k, v in (resume_meta.get("capacities") or {}).items():
                if k in ("event_capacity", "outbox_capacity",
                         "router_ring"):
                    overrides[k] = max(int(overrides.get(k) or 0), int(v))
        if args.inject_trace and "inject_lanes" not in overrides:
            # size the staging buffer from the trace before the build
            # (the same default the loader applies to <traffic>
            # elements); one extra sequential read of the file is
            # cheap next to the device build
            from shadow_tpu.apps.tgen import lanes_for
            from shadow_tpu.inject import read_trace

            n_ev = sum(1 for _ in read_trace(args.inject_trace))
            overrides["inject_lanes"] = lanes_for(n_ev)
        # relative <topology path> / <plugin path="*.py"> entries are
        # relative to the CONFIG FILE, not the cwd (the reference
        # resolves the same way) — load() handles both via base_dir
        loaded = load(cfg, seed=args.seed,
                      overrides=overrides,
                      base_dir=os.path.dirname(os.path.abspath(args.config))
                      if args.config else None)
        b = loaded.bundle
        if resume_meta is not None and resume_meta.get("config_digest"):
            from shadow_tpu.telemetry.export import config_hash

            if resume_meta["config_digest"] != config_hash(b.cfg):
                logger.warning(
                    0, "shadow-tpu",
                    "resume snapshot was taken under a different "
                    "config digest — continuing, but the runs are "
                    "not the same simulation")
        logger.message(0, "shadow-tpu", f"built {b.cfg.num_hosts} hosts, "
                       f"min window {b.min_jump} ns, "
                       f"end {b.cfg.end_time} ns")

        # open-system injection: an explicit --inject-trace beats the
        # config's compiled <traffic> trace (the CLI-beats-XML
        # precedence every other knob follows)
        feeder = None
        if args.inject_trace or loaded.inject_events:
            from shadow_tpu.inject import Feeder

            if loaded.vprocs:
                print("error: event injection needs the on-device "
                      "window loop; .py-plugin virtual processes "
                      "cannot consume injected events",
                      file=sys.stderr)
                logger.flush()
                return 1
            if args.inject_trace and loaded.inject_events:
                logger.warning(
                    0, "shadow-tpu",
                    "--inject-trace overrides the config's <traffic> "
                    "elements")
            feeder = Feeder(args.inject_trace
                            or list(loaded.inject_events))
            logger.message(
                0, "shadow-tpu",
                f"injection staging: {b.sim.inject.lanes} lanes, "
                f"source "
                f"{args.inject_trace or '<traffic> elements'}")

        t0 = time.time()

        # periodic run-time progress records (the reference's per-round
        # heartbeat, slave.c:390-411, feeding plot-shadow's tick plot).
        # Host-driven window loops call this per window; the whole-run
        # device path reports a single final tick instead (a per-window
        # host callback would forfeit its on-device speed).
        prog_state = {"last": -1}

        def progress_hook(s, wend):
            sec = int(wend) // 10**9
            bucket = sec // max(args.heartbeat_frequency, 1)
            if bucket > prog_state["last"]:
                prog_state["last"] = bucket
                logger.message(
                    int(wend), "shadow-tpu", "[shadow-progress] "
                    + json.dumps({
                        "sim_seconds": round(int(wend) / 1e9, 3),
                        "wall_seconds": round(time.time() - t0, 3)}))

        # lane-isolated health (core/lanes.py): attach BEFORE the
        # telemetry ring — the ring sizes its per-lane fan-out planes
        # off sim.lanes. Single-shard, on-device window loop only.
        if args.lane_isolation:
            if loaded.vprocs:
                logger.warning(0, "shadow-tpu",
                               "--lane-isolation is unavailable with "
                               ".py plugins (ProcessRuntime window "
                               "loop); ignored")
            elif args.workers > 1:
                logger.warning(0, "shadow-tpu",
                               "--lane-isolation is single-shard only; "
                               f"--workers {args.workers} wins, lane "
                               "isolation disabled")
            else:
                from shadow_tpu.core import lanes as lanes_mod

                try:
                    b.sim = lanes_mod.attach(b.sim, args.lane_isolation)
                except ValueError as e:
                    print(f"error: --lane-isolation: {e}",
                          file=sys.stderr)
                    logger.flush()
                    return 1
                logger.message(
                    0, "shadow-tpu",
                    f"lane isolation: {args.lane_isolation} lanes x "
                    f"{b.cfg.num_hosts // args.lane_isolation} hosts")
                if args.resident:
                    # static-population resident planes: all lanes
                    # admitted at t=0 with open leases; the window
                    # barrier now also enforces the admission rules
                    # (free-lane flush, completion latch) and the
                    # manifest carries the lease-conservation block
                    b.sim = lanes_mod.admit_all(
                        lanes_mod.attach_admission(b.sim))
                    logger.message(
                        0, "shadow-tpu",
                        f"resident admission: "
                        f"{args.lane_isolation} lanes admitted with "
                        f"open leases")
        if args.resident and getattr(b.sim, "admission", None) is None:
            logger.warning(0, "shadow-tpu",
                           "--resident requires --lane-isolation "
                           "(admission is lease bookkeeping over "
                           "lanes); ignored")

        # window telemetry (shadow_tpu/telemetry): attach the on-device
        # ring BEFORE any run path branches so checkpoint templates,
        # the supervisor's resume template, and the compiled programs
        # all see the same pytree. A None ring costs literally zero
        # compiled ops (make_telem_fn is a trace-time no-op), so runs
        # without these flags are untouched.
        telem_on = bool(args.trace_out or args.metrics_out
                        or args.telemetry_capacity)
        flows_on = bool(args.flow_sample and args.flow_sample > 0)
        caus_on = bool(args.causality_sample
                       and args.causality_sample > 0)
        harvester = None
        timers = None
        if (telem_on or flows_on or caus_on) and loaded.vprocs:
            logger.warning(0, "shadow-tpu",
                           "window telemetry is unavailable with .py "
                           "plugins (ProcessRuntime drives its own "
                           "window loop); --trace-out/--metrics-out/"
                           "--flow-sample/--causality-sample ignored")
            telem_on = False
            flows_on = False
            caus_on = False
        if telem_on:
            from shadow_tpu import telemetry

            b.sim = telemetry.attach(
                b.sim,
                capacity=args.telemetry_capacity
                or telemetry.DEFAULT_CAPACITY)
        if flows_on:
            # flow flight-recorder (telemetry/flows.py): deterministic
            # 1-in-N packet sampling at the window barrier; drained by
            # the same harvester as the window ring
            from shadow_tpu import telemetry
            from shadow_tpu.telemetry import flows as flows_mod

            try:
                b.sim = telemetry.attach_flows(
                    b.sim, sample_period=args.flow_sample,
                    capacity=args.flow_capacity
                    or flows_mod.DEFAULT_CAPACITY)
            except ValueError as e:
                print(f"error: --flow-sample: {e}", file=sys.stderr)
                logger.flush()
                return 1
            logger.message(
                0, "shadow-tpu",
                f"flow tracing: 1-in-{args.flow_sample} packet "
                f"sampling, ring capacity "
                f"{args.flow_capacity or flows_mod.DEFAULT_CAPACITY}")
        if caus_on:
            # causal lineage recorder (telemetry/causality.py): the
            # same deterministic hash sampling discipline as the flow
            # recorder, plus per-window advance attribution at the
            # barrier; drained by the same harvester
            from shadow_tpu import telemetry
            from shadow_tpu.telemetry import causality as caus_mod

            try:
                b.sim = telemetry.attach_causality(
                    b.sim, sample_period=args.causality_sample,
                    capacity=args.causality_capacity
                    or caus_mod.DEFAULT_CAPACITY)
            except ValueError as e:
                print(f"error: --causality-sample: {e}",
                      file=sys.stderr)
                logger.flush()
                return 1
            logger.message(
                0, "shadow-tpu",
                f"causality tracing: 1-in-{args.causality_sample} "
                f"event sampling, per-host lineage capacity "
                f"{args.causality_capacity or caus_mod.DEFAULT_CAPACITY}")
        if telem_on or flows_on or caus_on:
            from shadow_tpu import telemetry

            harvester = telemetry.Harvester()
            timers = telemetry.PhaseTimers()

        # compile-time program specialization (compile/specialize.py):
        # derive the capability vector from the CONCRETE build — after
        # every optional attachment, so the analysis sees the final
        # sim composition — and trim statically-dead subgraphs from
        # the trace. The guard latch attached here turns a violated
        # assumption into a fatal health fault (exit 3), never silent
        # drift. .py-plugin runtimes arm host timers outside the
        # handler declaration surface, so they run the full program.
        from shadow_tpu.compile import specialize

        if loaded.vprocs or args.host_kernel:
            b = specialize.apply(b, mode="off")
        else:
            b = specialize.apply(b, loaded.handlers,
                                 app_bulk=b.app_bulk,
                                 mode=args.specialize)
        if b.caps is not None and b.caps.dropped():
            logger.message(
                0, "shadow-tpu",
                "specialization: trimmed "
                + ",".join(b.caps.dropped())
                + f" (program-key extra {b.caps.key_extra()!r}; "
                  f"guard latch armed)")

        cap = None
        if b.cfg.pcap:
            # pcap capture needs a host-driven window loop to drain
            # the ring (ref: per-interface PCapWriter, pcap_writer.c)
            from shadow_tpu.utils.pcap import CaptureSession

            cap = CaptureSession(b, args.data_directory)
        mesh = None
        sup_result = None  # set by the --supervise branch
        # warm-program serving (compile/serve.py): every run path
        # hands this dict to its runner; the manifest records the
        # realized {key, hit, load_s|compile_s} block from it (the
        # supervised path uses the supervisor's own copy instead)
        cinfo: dict = {}
        # --profile-dir: bracket the device work with a jax.profiler
        # trace; the manifest's "profile" block records where the
        # artifact landed so tooling can find it without guessing
        profile_info = None
        if args.profile_dir:
            try:
                os.makedirs(args.profile_dir, exist_ok=True)
                jax.profiler.start_trace(args.profile_dir)
                _prof["on"] = True
                profile_info = {"dir": os.path.abspath(args.profile_dir),
                                "tool": "jax.profiler"}
            except Exception as e:  # profiler backend is optional
                logger.warning(0, "shadow-tpu",
                               f"--profile-dir: capture unavailable "
                               f"({e}); continuing without profile")
        # track_paths no longer forces serial: shard-local [V,V]
        # partials are psummed at the window barrier
        # (parallel/shard.py _replicate_scalars)
        if args.workers > 1 and b.cfg.pcap:
            logger.warning(0, "shadow-tpu",
                           f"logpcap forces the serial window loop; "
                           f"--workers {args.workers} ignored")
        elif args.workers > 1:
            from jax.sharding import Mesh

            # contiguous-block sharding needs hosts % shards == 0; the
            # reference accepts any worker count for any host count
            # (scheduler.c round-robins), so adapt rather than error:
            # largest divisor of H within both the request and the
            # device count (clamping FIRST keeps the result a divisor,
            # and bounds the search for absurd --workers values)
            wmax = min(args.workers, len(jax.devices()), b.cfg.num_hosts)
            w = max(d for d in range(1, wmax + 1)
                    if b.cfg.num_hosts % d == 0)
            if w != args.workers:
                logger.warning(
                    0, "shadow-tpu",
                    f"--workers {args.workers} does not divide "
                    f"{b.cfg.num_hosts} hosts (or exceeds the device "
                    f"count); using {w}")
            if w > 1:
                mesh = Mesh(np.array(jax.devices()[:w]), ("hosts",))
        if args.host_kernel:
            if not loaded.vprocs:
                print("error: --host-kernel needs a config with .py "
                      "plugins (virtual processes)", file=sys.stderr)
                logger.flush()
                return 1
            code = _host_kernel_mode(args, b, loaded, logger)
            logger.flush()
            return code
        if loaded.vprocs:
            # .py plugins: coroutine processes over the simulated
            # syscall surface — the config-reachable form of the
            # reference's plugin loading (SURVEY §7.1). Composes with
            # pcap: the runtime's window loop drains the capture ring.
            from shadow_tpu.process.vproc import ProcessRuntime

            if b.app_bulk is not None:
                # ProcessRuntime's window loop has no bulk-pass hook
                # yet; a mixed .py-plugin + bulk-capable-app config
                # falls back to per-event micro-steps.
                logger.warning(0, "shadow-tpu",
                               "bulk window pass unavailable with .py "
                               "plugins; using per-event micro-steps")
            rt = ProcessRuntime(b, app_handlers=loaded.handlers,
                                mesh=mesh)
            for hi, fn, st, sp in loaded.vprocs:
                rt.spawn(hi, fn, start_time=st, stop_time=sp)
            def vproc_hook(s, wend, _cap=cap):
                if _cap is not None:
                    _cap.drain(s)
                progress_hook(s, wend)

            sim, stats = rt.run(on_window=vproc_hook)
        elif args.supervise:
            import signal

            from shadow_tpu.faults.escalate import EscalationPolicy
            from shadow_tpu.faults.supervisor import run_supervised
            from shadow_tpu.telemetry.export import config_hash

            ckpt_prefix = args.checkpoint_path or os.path.join(
                args.data_directory, "checkpoint")
            os.makedirs(os.path.dirname(os.path.abspath(ckpt_prefix)),
                        exist_ok=True)

            def sup_hook(s, wend, _cap=cap):
                if _cap is not None:
                    _cap.drain(s)
                progress_hook(s, wend)

            # preemption safety: the first SIGTERM/SIGINT asks the
            # supervisor for a final atomic snapshot at the next window
            # barrier (exit 5); the handler restores the previous
            # disposition immediately, so a second signal kills a hung
            # run the ordinary way
            stop_flag = {"v": False}
            prev_handlers = {}

            def _on_signal(signum, frame):
                stop_flag["v"] = True
                signal.signal(signum, prev_handlers[signum])

            for _sg in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[_sg] = signal.signal(_sg, _on_signal)
                except ValueError:
                    pass  # not the main thread (embedded use)

            nshards = mesh.shape["hosts"] if mesh is not None else 1
            try:
                with (timers.phase("supervised-run") if timers is not None
                      else contextlib.nullcontext()):
                    result = run_supervised(
                        b, app_handlers=loaded.handlers,
                        checkpoint_path=ckpt_prefix,
                        checkpoint_every_windows=(
                            args.checkpoint_every_windows),
                        max_retries=args.max_retries,
                        backoff_s=args.retry_backoff,
                        stall_windows=args.stall_windows,
                        escalation=(EscalationPolicy(
                            max_grow=args.max_grow)
                            if args.auto_grow else None),
                        stop=lambda: stop_flag["v"],
                        resume_from=resume_ckpt,
                        max_run_wallclock=args.max_run_wallclock,
                        mesh=mesh,
                        config_digest=config_hash(b.cfg),
                        log=lambda m: logger.message(0, "shadow-tpu", m),
                        on_window=sup_hook, harvester=harvester,
                        feeder=feeder)
            finally:
                for _sg, _h in prev_handlers.items():
                    with contextlib.suppress(ValueError, TypeError):
                        signal.signal(_sg, _h)
            sup_result = result

            def _sup_manifest(sim_, health_, stats_=None):
                from shadow_tpu import telemetry

                harvester.drain(sim_)
                wpd = max(1, int(getattr(b.cfg, "windows_per_dispatch",
                                         1) or 1))
                disp = {"windows_per_dispatch": wpd,
                        "dispatches": result.dispatches}
                # the per-dispatch window list only equals the chain's
                # window total for a clean single-attempt run (retries
                # replay dispatches; resumes offset the counters) —
                # omit it otherwise so the lint invariant stays exact
                if (wpd > 1 and result.dispatch_windows
                        and result.attempts == 1
                        and result.resume_of is None):
                    disp["windows"] = list(result.dispatch_windows)
                if getattr(b.cfg, "adaptive_jump", False):
                    m = harvester.mean_window_ns()
                    if m is not None:
                        disp["adaptive_jump_mean_ns"] = m
                inj_blk = None
                if feeder is not None:
                    from shadow_tpu import inject as inject_mod

                    inj_blk = inject_mod.manifest_block(sim_, feeder)
                from shadow_tpu.telemetry.export import (
                    admission_manifest_block,
                    lanes_manifest_block,
                )
                from shadow_tpu.telemetry.flows import \
                    flows_manifest_block
                from shadow_tpu.telemetry.causality import \
                    causality_manifest_block

                caus_blk = causality_manifest_block(
                    harvester, num_hosts=b.cfg.num_hosts,
                    shards=nshards,
                    sample_period=args.causality_sample or None)
                man = telemetry.run_manifest(
                    cfg=b.cfg, seed=args.seed, shards=nshards,
                    sim=sim_, stats=stats_, health=health_,
                    fault_plan=b.fault_plan,
                    harvester=harvester, timers=timers,
                    run_id=result.run_id, resume_of=result.resume_of,
                    escalations=result.escalations,
                    preempted=result.preempted or None,
                    dispatch=disp, injection=inj_blk,
                    compile_info=result.compile_info,
                    lanes=lanes_manifest_block(
                        health_, result.lane_incidents),
                    flows=flows_manifest_block(
                        harvester, num_hosts=b.cfg.num_hosts,
                        shards=nshards,
                        sample_period=args.flow_sample or None),
                    admission=admission_manifest_block(health_),
                    profile=profile_info,
                    causality=caus_blk,
                    specialization=specialize.specialization_block(
                        getattr(b, "caps", None), sim_,
                        mode=args.specialize))
                os.makedirs(args.data_directory, exist_ok=True)
                telemetry.write_manifest(
                    os.path.join(args.data_directory,
                                 "run_manifest.json"), man)
                if args.trace_out:
                    telemetry.write_trace(
                        args.trace_out, harvester.records, timers,
                        nshards,
                        flow_records=harvester.flow_records,
                        adv_records=harvester.adv_records or None,
                        chains=(caus_blk or {}).get("chains"))
                if args.metrics_out:
                    telemetry.write_metrics(args.metrics_out, man)
                return man

            if result.preempted:
                # interrupted, not failed: the final snapshot is on
                # disk and `--resume <data-directory>` continues the
                # run (distinct exit code so wrappers can requeue)
                report = {
                    "preempted": True,
                    "checkpoint": result.final_checkpoint,
                    "run_id": result.run_id,
                    "escalations": len(result.escalations),
                    "resume": f"--resume {args.data_directory}",
                }
                if (telem_on or flows_on or caus_on) \
                        and result.sim is not None:
                    report["manifest"] = _sup_manifest(
                        result.sim, None, result.stats)
                logger.message(0, "shadow-tpu", "run preempted "
                               + json.dumps(report))
                logger.flush()
                print(json.dumps(report))
                return 5
            if not result.ok:
                failure = result.failure_report()
                # critical, not error: SimLogger.error raises (the
                # abort path); here we must keep control to emit the
                # structured report and choose the exit code.
                for _, msg in result.health.diagnostics():
                    logger.critical(0, "shadow-tpu", msg)
                report = {"failure": failure,
                          "attempts": result.attempts}
                if result.deadline_exceeded:
                    # not a corruption: the final snapshot is clean
                    # and --resume continues the chain
                    report["checkpoint"] = result.final_checkpoint
                    report["resume"] = f"--resume {args.data_directory}"
                # the trip carries the sim, so the shutdown
                # diagnostics the success path prints still run:
                # object accounting (ref: slave.c:237-241) and the
                # run manifest — a failed run is exactly when you
                # want them
                if result.sim is not None:
                    from shadow_tpu.utils import objcount

                    oc = objcount.gather(result.sim)
                    logger.message(0, "shadow-tpu", oc.format())
                    logger.message(0, "shadow-tpu", oc.format_diff())
                    if telem_on or flows_on or caus_on:
                        report["manifest"] = _sup_manifest(
                            result.sim, result.health)
                logger.flush()
                print(json.dumps(report))
                return 3
            sim, stats = result.sim, result.stats
        elif b.cfg.pcap:
            from shadow_tpu.utils import checkpoint as ckpt

            def pcap_hook(s, wend):
                cap.drain(s)
                if harvester is not None:
                    # the host already regains control every window
                    # here; draining per window keeps ring loss at zero
                    harvester.drain(s)
                progress_hook(s, wend)

            with (timers.phase("window-loop") if timers is not None
                  else contextlib.nullcontext()):
                sim, stats, _ = ckpt.run_windows(
                    b, app_handlers=loaded.handlers, on_window=pcap_hook,
                    feeder=feeder, compile_info=cinfo)
        elif mesh is not None:
            from shadow_tpu.parallel.shard import run_sharded

            if feeder is not None:
                # whole-run jitted path: the entire trace must fit the
                # staging lanes (fill_all errors with the streaming
                # alternative spelled out when it does not)
                b.sim = feeder.fill_all(b.sim)
            if timers is not None:
                with timers.phase("device-execute"):
                    sim, stats = run_sharded(
                        b, mesh, app_handlers=loaded.handlers,
                        app_bulk=b.app_bulk, compile_info=cinfo)
                    jax.block_until_ready(sim)
            else:
                sim, stats = run_sharded(
                    b, mesh, app_handlers=loaded.handlers,
                    app_bulk=b.app_bulk, compile_info=cinfo)
        else:
            if feeder is not None:
                b.sim = feeder.fill_all(b.sim)
            if timers is not None:
                # split trace+compile from device execution so the
                # wall-time trace track shows where a cold start went
                from shadow_tpu.net.build import make_runner

                runner = make_runner(b, app_handlers=loaded.handlers,
                                     app_bulk=b.app_bulk,
                                     compile_info=cinfo)
                with timers.phase("trace-compile"):
                    # a warm-serving runner (compile/serve.WarmFn)
                    # resolves load-or-compile here via its lower()
                    # adapter, so a store hit shows up as a short
                    # trace-compile phase
                    compiled = runner.lower(b.sim).compile()
                with timers.phase("device-execute"):
                    sim, stats = compiled(b.sim)
                    jax.block_until_ready(sim)
            else:
                from shadow_tpu.net.build import run

                sim, stats = run(b, app_handlers=loaded.handlers,
                                 app_bulk=b.app_bulk)
        _stop_profile()
        if cap is not None:
            cap.drain(sim)
            cap.close()
            if cap.dropped:
                logger.warning(b.cfg.end_time, "shadow-tpu",
                               f"pcap ring overran: {cap.dropped} records "
                               f"lost (raise NetConfig.pcap_ring)")
        wall = time.time() - t0

        # end-of-run heartbeat + object accounting (ref: the tracker
        # heartbeat subsystem, tracker.c:419-607, and the shutdown object
        # counter dump, slave.c:237-241)
        from shadow_tpu.utils import objcount
        from shadow_tpu.utils.tracker import Tracker

        tracker = Tracker(
            logger, b.host_names,
            interval_s=args.heartbeat_frequency,
            level=level_from_name(args.heartbeat_log_level),
            sections=tuple(
                x.strip() for x in args.heartbeat_log_info.split(",")
                if x.strip()))
        tracker.heartbeat(sim, b.cfg.end_time)
        oc = objcount.gather(sim, stats=stats)
        logger.message(b.cfg.end_time, "shadow-tpu", oc.format())
        logger.message(b.cfg.end_time, "shadow-tpu", oc.format_diff())

        # per-host executed-event lines (ref: the per-host execution
        # timer logged at shutdown, host.c:314-317) + per-path packet
        # counts (ref: topology.c:2053-2063), info level
        exec_h = np.asarray(sim.net.ctr_events_exec)
        for hi in np.argsort(-exec_h)[: min(len(exec_h), 10)]:
            if exec_h[hi] > 0:
                logger.info(b.cfg.end_time, b.host_names[hi],
                            f"executed {int(exec_h[hi])} events")
        if b.cfg.track_paths:
            mat = np.asarray(sim.net.ctr_path_packets)
            vs, vd = np.nonzero(mat)
            for a, c in zip(vs, vd):
                logger.message(
                    b.cfg.end_time, "shadow-tpu",
                    f"path {a}->{c}: {int(mat[a, c])} packets")

        # health-latch enforcement (faults/health.py): the sticky
        # overflow counters stop being silent integers — every run
        # ends with an explicit verdict, and a fatal latch means a
        # non-zero exit with a structured failure report instead of
        # corrupted-but-plausible results.
        from shadow_tpu.faults import health as health_mod

        if harvester is not None:
            with timers.phase("harvest"):
                harvester.drain(sim)
        run_health = health_mod.gather(
            sim,
            telemetry_lost=(harvester.records_lost
                            + getattr(harvester, "flow_lost", 0)
                            if harvester is not None else 0))
        # critical, not error: SimLogger.error raises, and the fatal
        # path below must still print the structured report + exit 3.
        for sev, msg in run_health.diagnostics():
            if sev == "fatal":
                logger.critical(b.cfg.end_time, "shadow-tpu", msg)
            else:
                logger.warning(b.cfg.end_time, "shadow-tpu", msg)

        ev = int(stats.events_processed)
        sim_s = b.cfg.end_time / 1e9
        report = {
            "events": ev,
            "windows": int(stats.windows),
            "sim_seconds": round(sim_s, 3),
            # verification hook (ref: the reference's example config
            # downloads are verified by their sizes): the app's own rcvd
            # units — bytes for bulk, replies for pingpong
            **({"app_rcvd": int(np.asarray(sim.app.rcvd).sum())}
               if getattr(sim, "app", None) is not None
               and hasattr(sim.app, "rcvd") else {}),
            "wall_seconds": round(wall, 3),
            "events_per_second": round(ev / wall, 1) if wall > 0 else None,
            "simulated_seconds_per_wall_second":
                round(sim_s / wall, 3) if wall > 0 else None,
            "overflow": int(sim.events.overflow) + int(sim.outbox.overflow)
            + int(sim.net.rq_overflow),
        }
        inj_blk = None
        if feeder is not None:
            from shadow_tpu import inject as inject_mod

            inj_blk = inject_mod.manifest_block(sim, feeder)
            if inj_blk is not None:
                report["injection"] = inj_blk
        if sup_result is not None:
            if sup_result.escalations:
                report["escalations"] = [
                    e.as_dict() for e in sup_result.escalations]
            if sup_result.resume_of:
                report["resume_of"] = sup_result.resume_of
        if telem_on or flows_on or caus_on:
            from shadow_tpu import telemetry

            nshards = mesh.shape["hosts"] if mesh is not None else 1
            with timers.phase("export"):
                disp = None
                if sup_result is not None:
                    wpd = max(1, int(getattr(
                        b.cfg, "windows_per_dispatch", 1) or 1))
                    disp = {"windows_per_dispatch": wpd,
                            "dispatches": sup_result.dispatches}
                    # only a clean single-attempt run's per-dispatch
                    # list sums to the chain's window counter — see
                    # _sup_manifest
                    if (wpd > 1 and sup_result.dispatch_windows
                            and sup_result.attempts == 1
                            and sup_result.resume_of is None):
                        disp["windows"] = list(
                            sup_result.dispatch_windows)
                    if (getattr(b.cfg, "adaptive_jump", False)
                            and harvester is not None):
                        m = harvester.mean_window_ns()
                        if m is not None:
                            disp["adaptive_jump_mean_ns"] = m
                from shadow_tpu.telemetry.export import (
                    admission_manifest_block,
                    lanes_manifest_block,
                )
                from shadow_tpu.telemetry.flows import \
                    flows_manifest_block
                from shadow_tpu.telemetry.causality import \
                    causality_manifest_block

                caus_blk = causality_manifest_block(
                    harvester, num_hosts=b.cfg.num_hosts,
                    shards=nshards,
                    sample_period=args.causality_sample or None)
                man = telemetry.run_manifest(
                    cfg=b.cfg, seed=args.seed, shards=nshards, sim=sim,
                    stats=stats, health=run_health,
                    fault_plan=b.fault_plan, harvester=harvester,
                    timers=timers, wall_seconds=wall,
                    injection=inj_blk,
                    compile_info=(sup_result.compile_info
                                  if sup_result is not None
                                  else (cinfo or None)),
                    lanes=lanes_manifest_block(
                        run_health,
                        sup_result.lane_incidents
                        if sup_result is not None else ()),
                    flows=flows_manifest_block(
                        harvester, num_hosts=b.cfg.num_hosts,
                        shards=nshards,
                        sample_period=args.flow_sample or None),
                    admission=admission_manifest_block(run_health),
                    profile=profile_info,
                    causality=caus_blk,
                    specialization=specialize.specialization_block(
                        b.caps, sim, mode=args.specialize),
                    **({} if sup_result is None else {
                        "run_id": sup_result.run_id,
                        "resume_of": sup_result.resume_of,
                        "escalations": sup_result.escalations,
                        "dispatch": disp}))
                os.makedirs(args.data_directory, exist_ok=True)
                mpath = telemetry.write_manifest(
                    os.path.join(args.data_directory,
                                 "run_manifest.json"), man)
                logger.message(b.cfg.end_time, "shadow-tpu",
                               f"run manifest -> {mpath}")
                if args.trace_out:
                    telemetry.write_trace(
                        args.trace_out, harvester.records, timers,
                        nshards,
                        flow_records=harvester.flow_records,
                        adv_records=harvester.adv_records or None,
                        chains=(caus_blk or {}).get("chains"))
                    logger.message(b.cfg.end_time, "shadow-tpu",
                                   f"trace -> {args.trace_out} (load in "
                                   f"chrome://tracing or ui.perfetto.dev)")
                if args.metrics_out:
                    telemetry.write_metrics(args.metrics_out, man)
            report["telemetry"] = man["telemetry"]
        if run_health.fatal:
            report["failure"] = run_health.failure_report()
            logger.critical(b.cfg.end_time, "shadow-tpu",
                            "simulation FAILED " + json.dumps(report))
            logger.flush()
            print(json.dumps(report))
            return 3
        logger.message(b.cfg.end_time, "shadow-tpu", "simulation complete "
                       + json.dumps(report))
        logger.flush()
        print(json.dumps(report))
        return 0
    finally:
        _stop_profile()
        logger.flush()


if __name__ == "__main__":
    sys.exit(main())
