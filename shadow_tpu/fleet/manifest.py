"""fleet_manifest.json — the roll-up of every job's verdict.

Rewritten atomically after every terminal transition (not just at
exit), so a fleet killed mid-run still leaves an accurate partial
manifest next to the journal that supersedes it. `tools/
telemetry_lint.py --fleet-manifest` validates the schema: attempt
histories monotone, every terminal job carries a verdict, every
quarantined job carries its salvage pointers.
"""

from __future__ import annotations

import json
import os

from shadow_tpu.fleet import journal as journal_mod
from shadow_tpu.fleet import state as state_mod

SCHEMA = "shadow-tpu-fleet-manifest"
SCHEMA_VERSION = 1

_VERDICTS = {state_mod.DONE: "ok",
             state_mod.FAILED: "failed",
             state_mod.QUARANTINED: "quarantined"}


def _job_entry(queue, j) -> dict:
    jid = j.spec.id
    rel = os.path.join("jobs", jid)
    entry = {
        "status": j.status,
        "kind": j.spec.kind,
        "seed": j.spec.seed,
        "spec_digest": j.spec.digest(),
        "attempts": j.attempts,
        "executions": j.execs,
        "worker_losses": j.worker_losses,
        "device_losses": j.device_losses,
        "attempt_history": list(j.attempt_history),
        "backoff_history": [round(b, 6) for b in j.backoff_history],
        "verdict": _VERDICTS.get(j.status),
        "dir": rel,
        "result": j.result,
        "failure": j.failure,
        "quarantine_reason": j.quarantine_reason,
    }
    # bucket-affinity fields (fleet/affinity.py): the spec-derived
    # scheduling key, plus the realized program key once the job's
    # run_manifest reported one — equal affinity_keys must map to
    # equal program_keys (the lint's consistency check)
    from shadow_tpu.fleet.affinity import affinity_key

    entry["affinity_key"] = affinity_key(j.spec)
    if j.result and j.result.get("program_key"):
        entry["program_key"] = j.result["program_key"]
    if getattr(j.spec, "replicas", 1) > 1:
        # packed job: surface the per-lane verdicts + requeue children
        # at the entry level so the lint (and the operator) need not
        # dig through result — lane children carry lane_of back-links
        entry["replicas"] = int(j.spec.replicas)
        if j.result and j.result.get("lanes"):
            entry["lanes"] = j.result["lanes"]
    if getattr(j.spec, "lane_of", None):
        entry["lane_of"] = j.spec.lane_of
    if j.result and j.result.get("flows"):
        # per-flow latency summary (telemetry/flows.py): the job-level
        # copy is the roll-up input for the fleet "flows" block
        entry["flows"] = j.result["flows"]
    if j.result and j.result.get("causality"):
        # causality accounting (telemetry/causality.py): the job-level
        # copy is the roll-up input for the fleet "causality" block
        entry["causality"] = j.result["causality"]
    if j.result and j.result.get("elastic"):
        # elastic recovery record (parallel/elastic.py): the job-level
        # copy is the roll-up input for the fleet "elastic" block
        entry["elastic"] = j.result["elastic"]
    if j.result and j.result.get("device_lease"):
        entry["device_lease"] = j.result["device_lease"]
    if j.shards_override:
        entry["shards_override"] = int(j.shards_override)
    run_man = os.path.join(queue.job_dir(jid), "run_manifest.json")
    if os.path.isfile(run_man):
        entry["run_manifest"] = os.path.join(rel, "run_manifest.json")
    if j.status == state_mod.QUARANTINED:
        from shadow_tpu.utils import checkpoint as ckpt

        entry["salvage"] = {
            "dir": rel,
            "checkpoint": j.checkpoint or ckpt.latest_checkpoint(
                os.path.join(queue.job_dir(jid), "ck")),
            "run_manifest": entry.get("run_manifest"),
            "result": (os.path.join(rel, "result.json")
                       if os.path.isfile(os.path.join(
                           queue.job_dir(jid), "result.json"))
                       else None),
        }
    return entry


def fleet_manifest(queue, *, workers_alive: int = 0,
                   preempted: bool = False, stalled: bool = False,
                   complete: bool = False,
                   admission: dict | None = None,
                   sweep: dict | None = None) -> dict:
    """`admission` is the resident-program block
    (fleet/admission.py ResidentProgram.manifest_block): lease-count
    conservation, program-key stability, the degradation ladder's
    history and the per-lane device planes. tools/telemetry_lint.py
    validates it (admitted == completed + evicted + quarantined +
    resident; SLO verdicts consistent with flow percentiles).

    `sweep` is the sweep roll-up block (sweep/driver.py sweep_block)
    when this fleet is one sweep's execution substrate: lattice
    conservation, the distinct-program census vs the prewarm log, and
    the per-round rankings. The lint validates that block too."""
    counts: dict[str, int] = {}
    jobs = {}
    for jid in sorted(queue.jobs):
        j = queue.jobs[jid]
        counts[j.status] = counts.get(j.status, 0) + 1
        jobs[jid] = _job_entry(queue, j)
    # flows roll-up: sum every flow-traced job's counters, and fold
    # the per-lane (per-tenant) sample counts into one table — the
    # lint checks these totals against the per-job entries
    flows_tot = None
    for jid, entry in jobs.items():
        fl = entry.get("flows")
        if not fl:
            continue
        if flows_tot is None:
            flows_tot = {"jobs": 0, "sampled": 0, "recorded": 0,
                         "harvested": 0, "lost_ring": 0,
                         "lost_window_clamp": 0, "lane_samples": {}}
        flows_tot["jobs"] += 1
        for k in ("sampled", "recorded", "harvested", "lost_ring",
                  "lost_window_clamp"):
            flows_tot[k] += int(fl.get(k, 0) or 0)
        for lane, summ in (fl.get("per_lane") or {}).items():
            flows_tot["lane_samples"][lane] = (
                flows_tot["lane_samples"].get(lane, 0)
                + int(summ.get("count", 0) or 0))
    # causality roll-up: sum every causality-traced job's lineage
    # accounting and fold the binding-cause histograms fleet-wide —
    # "what is the FLEET waiting on" (the lint checks these totals
    # against the per-job entries)
    caus_tot = None
    for jid, entry in jobs.items():
        cz = entry.get("causality")
        if not cz:
            continue
        if caus_tot is None:
            caus_tot = {"jobs": 0, "sampled": 0, "harvested": 0,
                        "lost_ring": 0, "windows_attributed": 0,
                        "windows_lost": 0, "causes": {}}
        caus_tot["jobs"] += 1
        for k in ("sampled", "harvested", "lost_ring",
                  "windows_attributed", "windows_lost"):
            caus_tot[k] += int(cz.get(k, 0) or 0)
        for cause, n in (cz.get("causes") or {}).items():
            caus_tot["causes"][cause] = (
                caus_tot["causes"].get(cause, 0) + int(n or 0))
    # elastic roll-up: sum every elastic job's loss/divergence/shrink
    # accounting fleet-wide — "how degraded is the FLEET" (the lint
    # checks these totals against the per-job entries)
    elastic_tot = None
    for jid, entry in jobs.items():
        el = entry.get("elastic")
        dlosses = int(entry.get("device_losses", 0) or 0)
        if not el and not dlosses:
            continue
        if elastic_tot is None:
            elastic_tot = {"jobs": 0, "device_lost": 0,
                           "shard_divergence": 0, "mesh_shrinks": 0,
                           "ladder_steps": 0, "fleet_requeues": 0}
        elastic_tot["jobs"] += 1
        elastic_tot["fleet_requeues"] += dlosses
        if el:
            elastic_tot["device_lost"] += len(el.get("losses") or ())
            elastic_tot["shard_divergence"] += len(
                el.get("divergences") or ())
            elastic_tot["mesh_shrinks"] += len(
                el.get("mesh_transitions") or ())
            elastic_tot["ladder_steps"] += len(
                el.get("ladder_steps") or ())
    return {
        "schema": SCHEMA,
        "schema_version": SCHEMA_VERSION,
        "policy": queue.policy.as_dict(),
        "preempted": bool(preempted),
        "stalled": bool(stalled),
        "complete": bool(complete),
        "workers_alive": workers_alive,
        "journal_events": queue.events,
        # idempotent-fold refusals (fleet/state.py): duplicate
        # terminal frames a crashed writer left behind — surfaced, not
        # swallowed, so an operator can audit what replay ignored
        "journal_warnings": list(queue.fold_warnings),
        "counts": counts,
        **({"flows": flows_tot} if flows_tot else {}),
        **({"causality": caus_tot} if caus_tot else {}),
        **({"elastic": elastic_tot} if elastic_tot else {}),
        **({"admission": admission} if admission else {}),
        **({"sweep": sweep} if sweep else {}),
        "jobs": jobs,
    }


def write_fleet_manifest(path: str, man: dict) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    journal_mod.fsync_dir(os.path.dirname(os.path.abspath(path)))
    return path
