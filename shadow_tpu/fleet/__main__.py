import sys

from shadow_tpu.fleet.cli import main

if __name__ == "__main__":
    sys.exit(main())
