"""Fleet worker process: lease one job at a time, run it, report.

Spawned (never forked — JAX state does not survive a fork) by the
fleet runner with one duplex pipe. Protocol, worker side:

  recv ("job", spec_dict, job_dir, resume_from, attempt)
  send ("running", job_id, attempt)
  send ("heartbeat", job_id, {"wstart": ns, "checkpoint": path})  (many)
  send ("result", job_id, attempt, result_dict)                   (one)
  recv ("shutdown",)  ->  exit 0

SIGTERM (the fleet's graceful-drain signal) sets a stop flag the
in-flight supervised run polls at every round barrier: the run takes
its preemption-style final snapshot, the worker reports the result
(`preempted: true`, checkpoint path inside) and exits — the runner
requeues the job as a continuation. SIGKILL obviously reports
nothing; the runner detects the dead process and requeues from the
job dir's newest checkpoint (heartbeats carried it). Either way the
job resumes where it left off, not from scratch.

Crash-safety of the report itself: run_job also writes result.json
into the job dir before the pipe send, so a worker that dies between
finishing a job and reporting it still leaves a salvageable verdict.
"""

from __future__ import annotations

import os
import signal
import sys


def worker_main(worker_id: str, fleet_dir: str, conn) -> int:
    # Workers are independent JAX processes: CPU platform unless the
    # fleet says otherwise, sharing the repo-local compile cache so
    # job N's compile is job N+1's (and every sibling worker's) hit.
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    from shadow_tpu.utils.compcache import enable_compile_cache

    enable_compile_cache()

    from shadow_tpu.fleet.scenario import run_job
    from shadow_tpu.fleet.spec import JobSpec

    stop = {"v": False}

    def _on_term(signum, frame):
        stop["v"] = True

    signal.signal(signal.SIGTERM, _on_term)

    log_path = os.path.join(fleet_dir, f"worker.{worker_id}.log")
    logf = open(log_path, "a", buffering=1)

    def log(msg):
        logf.write(f"{msg}\n")

    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return 0             # runner died; nothing useful to do
        if not msg or msg[0] == "shutdown":
            return 0
        assert msg[0] == "job", msg
        _, spec_dict, job_dir, resume_from, attempt = msg
        spec = JobSpec.from_dict(spec_dict)
        try:
            conn.send(("running", spec.id, attempt))
        except (BrokenPipeError, OSError):
            return 0

        def heartbeat(info, _id=spec.id):
            try:
                conn.send(("heartbeat", _id, info))
            except (BrokenPipeError, OSError):
                pass             # runner gone; finish the job anyway

        result = run_job(spec, job_dir, resume_from=resume_from,
                         stop=lambda: stop["v"], heartbeat=heartbeat,
                         log=log)
        try:
            conn.send(("result", spec.id, attempt, result))
        except (BrokenPipeError, OSError):
            return 0
        if stop["v"]:
            return 0             # drained: one preempted result, out


def _entry(worker_id: str, fleet_dir: str, conn):
    sys.exit(worker_main(worker_id, fleet_dir, conn))
