"""Fault-tolerant scenario-fleet runner (docs/8-fleet.md).

Runs heterogeneous scenarios (config x seed x fault plan, declared
in a JSON jobs file) across a pool of worker processes, surviving
worker SIGKILL/OOM/hangs and fleet-level SIGTERM without losing or
re-running work:

- journal:  append-only CRC-framed state journal (the durable queue)
- spec:     jobs-file parsing, JobSpec, FleetPolicy
- state:    the job state machine folded over the journal
- scenario: per-job execution (reuses faults.run_supervised,
            utils/checkpoint, telemetry manifests)
- worker:   the worker process main loop
- runner:   scheduler + watchdog + graceful degradation
- manifest: fleet_manifest.json roll-up
- cli:      `shadow-tpu fleet run/status`
"""

from shadow_tpu.fleet.spec import (  # noqa: F401
    FleetPolicy,
    JobSpec,
    load_jobs_file,
    parse_jobs_obj,
)
from shadow_tpu.fleet.state import FleetQueue, backoff_delay  # noqa: F401
from shadow_tpu.fleet.runner import (  # noqa: F401
    EXIT_FAILURES,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_STALLED,
    FleetRunner,
)
