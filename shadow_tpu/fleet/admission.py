"""Continuous lane admission: tenant leases over a resident program.

A *resident* program is one compiled packed program (compile/
buckets.py pow2 shapes, PR 12 warm serving) whose lane population
changes at window barriers WITHOUT retracing — the LLM-serving
continuous-batching shape, for sims. Everything here is host-side
orchestration over machinery that already exists:

- heterogeneous lanes: every tenant's scenario pads up to the shared
  pow2 lane bucket (compile.buckets.lane_bucket; apps/phold.py
  active_hosts occupies the prefix) and packs as one lane of the
  shared program (fleet/scenario.py build_resident_shell /
  build_tenant_donor);
- lane leases: a LaneLease state machine
  (FREE -> ADMITTED -> RUNNING -> {COMPLETED, EVICTED, QUARANTINED}
  -> FREE) journaled through fleet/journal.py frames, so `--resume`
  reconstructs the resident population exactly by replay;
- join = implant the tenant's donor state into the lane's host rows
  at the next barrier (events time-shifted to the join barrier),
  leave = flush-and-salvage (faults/escalate.py extract_lane) with
  the lane returned to the free pool;
- SLO-aware admission: an AdmissionGate fed by per-lane flow p99s
  (telemetry/flows.py, PR 15) and lane health latches (core/lanes.py,
  PR 9) defers/rejects joins and degrades in EXPLICIT ordered steps
  (raise SLO-evaluation stride -> defer admissions -> evict
  best-effort -> quarantine) instead of tripping fatal latches.

The robustness invariant (the churn containment oracle,
tools/chaos_soak.py --churn): healthy resident lanes are
byte-identical to an undisturbed run regardless of churn in other
lanes, and the program key is identical before and after every
admission event — joins and leaves mutate runtime data, never shapes.

Single-controller, single-shard (shards=1) programs only, like the
fleet's packed jobs.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

from shadow_tpu.fleet import journal as journal_mod

# --- LaneLease state machine -----------------------------------------

FREE = "free"
ADMITTED = "admitted"
RUNNING = "running"
COMPLETED = "completed"
EVICTED = "evicted"
QUARANTINED = "quarantined"

LEASE_TERMINAL = (COMPLETED, EVICTED, QUARANTINED)

# legal transitions, keyed by current state. A terminal lease must
# fold through FREE before the lane takes another tenant — except a
# QUARANTINED lane, which stays parked (its trip bits are latched on
# device; only a program restart clears them).
LEASE_LEGAL = {
    FREE: (ADMITTED,),
    ADMITTED: (RUNNING,),
    RUNNING: LEASE_TERMINAL,
    COMPLETED: (FREE,),
    EVICTED: (FREE,),
    QUARANTINED: (FREE,),
}


class LaneLease:
    """One lane's current lease (host-side record; the device shadow
    is core/lanes.LaneAdmission)."""

    __slots__ = ("lane", "state", "job", "epoch", "t_join", "lease_end",
                 "tenant_class", "slo_p99_ms", "ended_at", "digest",
                 "salvage", "reason")

    def __init__(self, lane: int):
        self.lane = int(lane)
        self.state = FREE
        self.job: Optional[str] = None
        self.epoch = 0
        self.t_join: Optional[int] = None
        self.lease_end: Optional[int] = None
        self.tenant_class = "best_effort"
        self.slo_p99_ms: Optional[float] = None
        self.ended_at: Optional[int] = None
        self.digest: Optional[str] = None
        self.salvage: Optional[str] = None
        self.reason: Optional[str] = None

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class LeaseTable:
    """Journaled lease state machine over R lanes — the fleet
    journal's framing (fleet/journal.py) with a lease-specific fold.
    record() appends one frame then folds it; replay and live share
    the fold, so a resumed table cannot disagree with a live one.

    Idempotent-fold hardening (same contract as FleetQueue._apply): a
    duplicate or conflicting terminal transition for a lane whose
    lease is already settled — a crash between effect and ack can
    journal one — keeps the FIRST terminal state and warns instead of
    crashing or flipping the verdict."""

    def __init__(self, path: str, lanes: int, *, fsync: bool = True,
                 resume: bool = False):
        self.path = path
        self.lease = [LaneLease(r) for r in range(int(lanes))]
        self.seq = 0
        self.admitted_total = 0
        self.completed_total = 0
        self.evicted_total = 0
        self.quarantined_total = 0
        self.deferred_total = 0
        self.degrade_level = 0
        self.degrade_history: list = []
        self.fold_warnings: list = []
        self.history: list = []          # terminal lease records
        if resume:
            for rec in journal_mod.replay(path)[0]:
                self._apply(rec)
        elif os.path.exists(path) and journal_mod.replay(path)[0]:
            raise FileExistsError(
                f"{path} already holds a lease journal — resume it or "
                f"use a fresh directory")
        self.journal = journal_mod.Journal(path, fsync=fsync)

    # -- fold ---------------------------------------------------------
    def record(self, rec: dict) -> dict:
        self.seq += 1
        rec = dict(rec, seq=self.seq)
        self.journal.append(rec)
        self._apply(rec)
        return rec

    def _apply(self, rec: dict) -> None:
        self.seq = max(self.seq, int(rec.get("seq", 0)))
        ev = rec.get("ev")
        if ev == "degrade":
            self.degrade_level = int(rec.get("level", 0))
            self.degrade_history.append(
                {k: rec.get(k) for k in ("level", "step", "why", "t")})
            return
        if ev == "defer":
            self.deferred_total += 1
            return
        if ev != "lease":
            return
        lane = int(rec.get("lane", -1))
        if not 0 <= lane < len(self.lease):
            self.fold_warnings.append(
                f"lease journal: frame for lane {lane} out of range "
                f"(lanes={len(self.lease)}); ignored")
            return
        cur = self.lease[lane]
        st = rec.get("state")
        if st not in LEASE_LEGAL.get(cur.state, ()):
            if st in LEASE_TERMINAL and cur.state in LEASE_TERMINAL:
                self.fold_warnings.append(
                    f"lease journal: duplicate terminal '{st}' for "
                    f"lane {lane} (job {rec.get('job')}) — lease "
                    f"already {cur.state}; keeping the first verdict")
            else:
                self.fold_warnings.append(
                    f"lease journal: illegal transition "
                    f"{cur.state} -> {st} for lane {lane}; ignored")
            return
        if st == ADMITTED:
            cur.state = ADMITTED
            cur.job = rec.get("job")
            cur.epoch = int(rec.get("epoch", cur.epoch + 1))
            cur.t_join = rec.get("t_join")
            cur.lease_end = rec.get("lease_end")
            cur.tenant_class = rec.get("tenant_class", "best_effort")
            cur.slo_p99_ms = rec.get("slo_p99_ms")
            cur.digest = cur.salvage = cur.reason = None
            cur.ended_at = None
            self.admitted_total += 1
        elif st == RUNNING:
            cur.state = RUNNING
        elif st in LEASE_TERMINAL:
            cur.state = st
            cur.ended_at = rec.get("t_end")
            cur.digest = rec.get("digest")
            cur.salvage = rec.get("salvage")
            cur.reason = rec.get("reason")
            self.history.append(cur.as_dict())
            if st == COMPLETED:
                self.completed_total += 1
            elif st == EVICTED:
                self.evicted_total += 1
            else:
                self.quarantined_total += 1
        elif st == FREE:
            self.lease[lane] = LaneLease(lane)
            self.lease[lane].epoch = cur.epoch

    # -- queries ------------------------------------------------------
    def resident(self) -> list:
        """Leases currently holding a lane (ADMITTED or RUNNING)."""
        return [l for l in self.lease if l.state in (ADMITTED, RUNNING)]

    def population(self) -> dict:
        """{lane: (job, state, epoch)} of the resident set — the
        thing `--resume` must reconstruct exactly."""
        return {l.lane: (l.job, l.state, l.epoch)
                for l in self.resident()}

    def free_lanes(self) -> list:
        return [l.lane for l in self.lease if l.state == FREE]

    def counts(self) -> dict:
        return {
            "lanes": len(self.lease),
            "admitted": self.admitted_total,
            "completed": self.completed_total,
            "evicted": self.evicted_total,
            "quarantined": self.quarantined_total,
            "resident": len(self.resident()),
            "deferred": self.deferred_total,
        }

    def close(self) -> None:
        self.journal.close()


# --- SLO-aware admission gate ----------------------------------------

# the degradation ladder, in order. Each step is strictly less
# destructive than tripping a fatal latch — the whole point is that a
# protected tenant's SLO breach degrades service for best-effort
# tenants instead of aborting anybody.
LADDER = ("nominal", "stride", "defer", "evict", "quarantine")


class AdmissionGate:
    """SLO evaluation + the degradation ladder, host-side.

    Inputs per barrier: the flow records drained since the last
    evaluation (telemetry/flows.py FlowRecord, each carrying .lane)
    and the lease table. A lane breaches when its p99 flow latency
    exceeds its tenant's slo_p99_ms; `sustained` consecutive breached
    evaluations make the breach actionable:

    - a best-effort tenant breaching its OWN SLO is evicted at that
      barrier (shedding — its salvage artifact survives);
    - a protected tenant's sustained breach climbs the ladder one
      step per barrier: (1) raise the SLO-evaluation stride — note
      the device flow ring's sample_period is a static shape field,
      so the stride relief is host-side evaluation cadence, never a
      retrace — (2) defer admissions, (3) evict the worst best-effort
      lane, (4) quarantine the breaching lane (core/lanes TRIP_SLO).
      `sustained` clear evaluations walk the ladder back down."""

    def __init__(self, *, sustained: int = 2, eval_stride: int = 1,
                 max_stride: int = 8):
        self.sustained = max(1, int(sustained))
        self.base_stride = max(1, int(eval_stride))
        self.stride = self.base_stride
        self.max_stride = max(self.base_stride, int(max_stride))
        self.level = 0                 # index into LADDER
        self.streak: dict = {}         # lane -> consecutive breaches
        self.clear_streak = 0          # protected all-clear evals
        self._tick = 0
        self.last_p99: dict = {}       # lane -> p99_ns at last eval
        self.breached_jobs: dict = {}  # job -> worst breach ratio

    @property
    def defer_admissions(self) -> bool:
        return self.level >= LADDER.index("defer")

    def evaluate(self, new_records, table: LeaseTable) -> list:
        """-> list of actions: ("evict", lane, why) |
        ("quarantine", lane, why). Ladder moves are reflected in
        self.level / self.stride; the caller journals them."""
        self._tick += 1
        if (self._tick - 1) % self.stride:
            return []                  # stride relief: skip this eval
        from shadow_tpu.telemetry.flows import per_lane_latency

        p99 = {int(k): v["p99_ns"]
               for k, v in per_lane_latency(new_records).items()}
        self.last_p99.update(p99)
        actions = []
        protected_breach = None
        for lease in table.resident():
            if lease.state != RUNNING or lease.slo_p99_ms is None:
                continue
            lane = lease.lane
            if lane not in p99:
                continue               # no fresh samples: no verdict
            slo_ns = float(lease.slo_p99_ms) * 1e6
            if p99[lane] > slo_ns:
                self.streak[lane] = self.streak.get(lane, 0) + 1
                self.breached_jobs[lease.job] = max(
                    self.breached_jobs.get(lease.job, 0.0),
                    p99[lane] / slo_ns)
            else:
                self.streak[lane] = 0
            if self.streak.get(lane, 0) < self.sustained:
                continue
            why = (f"p99 {p99[lane]}ns > slo {int(slo_ns)}ns for "
                   f"{self.streak[lane]} evaluations")
            if lease.tenant_class == "best_effort":
                actions.append(("evict", lane, f"slo breach: {why}"))
                self.streak[lane] = 0
            elif protected_breach is None:
                protected_breach = (lane, why)
        if protected_breach is not None:
            self.clear_streak = 0
            lane, why = protected_breach
            if self.level < len(LADDER) - 1:
                self.level += 1
            step = LADDER[self.level]
            if step == "stride":
                self.stride = min(self.stride * 2, self.max_stride)
            elif step == "evict":
                victim = self._worst_best_effort(table, p99)
                if victim is not None:
                    actions.append((
                        "evict", victim,
                        f"shed for protected lane {lane}: {why}"))
            elif step == "quarantine":
                actions.append((
                    "quarantine", lane,
                    f"slo breach exhausted the ladder: {why}"))
        else:
            self.clear_streak += 1
            if self.clear_streak >= self.sustained and self.level > 0:
                self.level -= 1
                self.clear_streak = 0
                if LADDER[self.level + 1] == "stride":
                    self.stride = self.base_stride
        return actions

    def _worst_best_effort(self, table: LeaseTable, p99: dict):
        cands = [l for l in table.resident()
                 if l.state == RUNNING and l.tenant_class == "best_effort"]
        if not cands:
            return None
        return max(cands,
                   key=lambda l: p99.get(l.lane, -1)).lane


# --- host-side lane surgery helpers ----------------------------------

_NON_TENANT_PREFIXES = (".lanes", ".admission", ".telem", ".flows",
                        ".inject")


def lane_digest(sim, lane: int, replicas: int) -> str:
    """sha256 over one lane's share of every [H]-leading leaf — the
    tenant's result fingerprint. Lane-health/lease planes, telemetry
    and flow rings are whole-program observability state and are
    excluded, exactly like tools/chaos_soak.py's containment oracle:
    this digest must be byte-identical between a churned and an
    undisturbed run for every healthy lane."""
    import jax
    import numpy as np

    H = int(sim.events.num_hosts)
    hs = H // int(replicas)
    lo, hi = int(lane) * hs, (int(lane) + 1) * hs
    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(sim)[0]:
        key = jax.tree_util.keystr(path)
        if key.startswith(_NON_TENANT_PREFIXES):
            continue
        a = np.asarray(jax.device_get(leaf))
        if a.ndim == 0 or a.shape[0] != H:
            continue
        h.update(key.encode())
        h.update(np.ascontiguousarray(a[lo:hi]).tobytes())
    return h.hexdigest()


def _implant_lane(sim, donor_leaves: dict, lane: int, width: int,
                  t_join: int):
    """Seed one lane's host rows from a tenant donor build: every
    [H]-leading leaf's lane block is overwritten with the donor's SAME
    rows (the donor is a full-shape build, so identity planes — lane
    ids, IPs, peer bases — are already correct for this lane), and
    the donor's boot events are time-shifted to the join barrier.
    Pure data movement at fixed shapes/dtypes: the dispatch program
    never retraces."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.core import simtime

    H = int(sim.events.num_hosts)
    lo, hi = int(lane) * int(width), (int(lane) + 1) * int(width)
    shift = jnp.asarray(int(t_join), simtime.DTYPE)

    def merge(path, a):
        key = jax.tree_util.keystr(path)
        if key.startswith(_NON_TENANT_PREFIXES):
            return a
        if not hasattr(a, "ndim") or a.ndim == 0 or a.shape[0] != H:
            return a
        b = donor_leaves.get(key)
        if b is None:
            return a                 # attach-time plane the donor lacks
        blk = jnp.asarray(b[lo:hi])
        if key == ".events.time":
            blk = jnp.where(blk == simtime.INVALID,
                            jnp.asarray(simtime.INVALID, simtime.DTYPE),
                            blk + shift)
        return a.at[lo:hi].set(blk.astype(a.dtype))

    return jax.tree_util.tree_map_with_path(merge, sim)


def _flush_lane(sim, lane: int, width: int):
    """Host-side flush of one lane's pending events (leave/evict):
    the device-side admission barrier would catch them next window,
    but flushing AT the decision point means a pending fault wakeup
    or stale delivery can never execute between the decision and the
    next barrier."""
    from shadow_tpu.core import simtime

    lo, hi = int(lane) * int(width), (int(lane) + 1) * int(width)
    t = sim.events.time.at[lo:hi].set(simtime.INVALID)
    return sim.replace(events=sim.events.replace(time=t))


def _set_lease_planes(sim, lane: int, *, active: bool,
                      lease_end=None, t_join=None, bump_epoch=False):
    """Update the device LaneAdmission planes for one lane (host-side,
    between dispatches — fixed shapes/dtypes, no retrace)."""
    import jax.numpy as jnp

    from shadow_tpu.core import simtime

    adm = sim.admission
    r = int(lane)
    inv = jnp.asarray(simtime.INVALID, simtime.DTYPE)
    adm = adm.replace(
        active=adm.active.at[r].set(bool(active)),
        lease_end=adm.lease_end.at[r].set(
            inv if lease_end is None
            else jnp.asarray(int(lease_end), simtime.DTYPE)),
        admitted_at=adm.admitted_at.at[r].set(
            inv if t_join is None
            else jnp.asarray(int(t_join), simtime.DTYPE)),
        completed=adm.completed.at[r].set(False),
        completed_at=adm.completed_at.at[r].set(inv),
        epoch=(adm.epoch.at[r].add(1) if bump_epoch else adm.epoch),
    )
    return sim.replace(admission=adm)


def _quarantine_lane(sim, lane: int, at_ns: int):
    """Host-side quarantine (the ladder's last step): latch the lane's
    quarantine mask + TRIP_SLO so the device freeze takes over at the
    next barrier, exactly as if a capacity latch had tripped — but by
    explicit policy, not by corruption."""
    import jax.numpy as jnp

    from shadow_tpu.core import simtime
    from shadow_tpu.core.lanes import TRIP_SLO

    lanes = sim.lanes
    r = int(lane)
    lanes = lanes.replace(
        quarantined=lanes.quarantined.at[r].set(True),
        quarantined_at=lanes.quarantined_at.at[r].set(
            jnp.asarray(int(at_ns), simtime.DTYPE)),
        trip_bits=lanes.trip_bits.at[r].set(
            lanes.trip_bits[r] | TRIP_SLO))
    return sim.replace(lanes=lanes)


# --- the resident program --------------------------------------------

class ResidentProgram:
    """One warm packed program + a lease table + an admission gate:
    the host loop that makes the lane population continuous.

    Lifecycle per barrier (one dispatch = `chunk_windows` windows; 1
    by default, which is what bounds admission latency — the SET-style
    runahead bound — to a single window barrier):

        dispatch -> fold completions/quarantines -> drain flows ->
        gate.evaluate -> evictions -> admissions -> checkpoint

    All mutations between dispatches are runtime data at fixed
    shapes; compile.serve.live_cache_size proves zero retraces and
    the recomputed program key proves the key never moved."""

    def __init__(self, specs, *, workdir: str, lanes: int,
                 horizon_s: int, chunk_windows: int = 1,
                 flow_sample: int = 1, gate: AdmissionGate | None = None,
                 checkpoint_every_events: int = 1, seed: int = 0,
                 fsync: bool = True, log=None, resume: bool = False):
        import jax.numpy as jnp  # noqa: F401  (fail early off-device)

        from shadow_tpu.compile.buckets import lane_bucket
        from shadow_tpu.core import simtime
        from shadow_tpu.fleet import scenario as scen

        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.log = log or (lambda m: None)
        self.specs = {s.id: s for s in specs}
        for s in self.specs.values():
            _check_tenant(s)
        self.lanes = int(lanes)
        self.width = lane_bucket([s.hosts for s in self.specs.values()])
        self.horizon_ns = int(horizon_s) * simtime.ONE_SECOND
        self.caps = scen.resident_caps(self.specs.values())
        self.flow_sample = int(flow_sample)
        self.gate = gate if gate is not None else AdmissionGate()
        self.checkpoint_every_events = max(0, int(checkpoint_every_events))
        self._ck_prefix = os.path.join(workdir, "ck")
        self._donors: dict = {}
        self.table = LeaseTable(os.path.join(workdir, "leases.log"),
                                self.lanes, fsync=fsync, resume=resume)
        self._bundle = scen.build_resident_shell(
            width=self.width, lanes=self.lanes, caps=self.caps,
            horizon_ns=self.horizon_ns, seed=seed,
            flow_sample=self.flow_sample)
        self.sim = self._bundle.sim
        self._setup_dispatch(chunk_windows)
        if self._one_window is not None:
            # the per-window dispatch donates its sim argument — the
            # bundle's pytree must survive (run_windows does the same)
            import jax

            self.sim = jax.tree_util.tree_map(jnp.copy, self.sim)
        self.frontier = 0
        self.windows = 0
        self.events = 0
        self.dispatches = 0
        self._flow_cursor = 0
        self._events_since_ck = 0
        self.admission_events = 0
        self.results: dict = {}        # job -> terminal record dict
        from shadow_tpu import telemetry

        self.harvester = (telemetry.Harvester()
                          if self.flow_sample > 0 else None)

    # -- dispatch machinery ------------------------------------------
    def _setup_dispatch(self, chunk_windows: int):
        from shadow_tpu.apps import phold
        from shadow_tpu.compile import serve
        from shadow_tpu.utils import checkpoint as ckpt

        self._handlers = (phold.handler,)
        self._plan = ckpt._resolve_loop(
            self._bundle, self._handlers, end_time=self.horizon_ns,
            fault_fn=None, mesh=None, mesh_axis="hosts",
            windows_per_dispatch=max(1, int(chunk_windows)),
            adaptive_jump=False)
        warm = serve.warm_enabled(default=False)
        self._compile_info: dict = {}
        (self._chunk_fn, self._one_window, key, self._raw,
         _example) = ckpt._make_dispatch_fns(
            self._bundle, self._plan, self.sim, self._handlers,
            mesh=None, mesh_axis=None, exchange_capacity=None,
            warm=warm, compile_info=self._compile_info)
        self.program_key = key if key is not None else self._recompute_key()
        self.program_keys = {self.program_key}
        self.retraces_seen = 0

    def _recompute_key(self):
        from shadow_tpu.utils import checkpoint as ckpt

        return ckpt._program_key_for(
            self._bundle, self._plan, self.sim, self._handlers,
            sharded=False, exchange_capacity=None)

    def _note_admission_event(self):
        """Zero-retrace bookkeeping after every admission event: the
        program key must not move and the live trace cache must not
        grow past one entry."""
        from shadow_tpu.compile import serve

        self.admission_events += 1
        self.program_keys.add(self._recompute_key())
        fn = self._chunk_fn if self._chunk_fn is not None else self._one_window
        n = serve.live_cache_size(fn)
        if n is not None and n > 1:
            self.retraces_seen = max(self.retraces_seen, n - 1)
        self._events_since_ck += 1

    @property
    def program_key_stable(self) -> bool:
        return len(self.program_keys) == 1 and self.retraces_seen == 0

    def _dispatch_once(self, wstart: int):
        import jax
        import jax.numpy as jnp

        from shadow_tpu.core import simtime
        from shadow_tpu.core.engine import EngineStats

        ws = jnp.asarray(int(wstart), simtime.DTYPE)
        if self._chunk_fn is not None:
            sim, stats, nm = self._chunk_fn(self.sim,
                                            EngineStats.create(), ws)
            self.windows += int(jax.device_get(stats.windows))
        else:
            # same clamp as run_windows: end + 1 so events AT the
            # horizon still execute
            wend = min(int(wstart) + self._plan.min_jump,
                       self.horizon_ns + 1)
            sim, stats, nm = self._one_window(
                self.sim, ws, jnp.asarray(wend, simtime.DTYPE))
            self.windows += 1
        self.events += int(jax.device_get(stats.events_processed))
        self.sim = sim
        self.dispatches += 1
        return int(jax.device_get(nm))

    # -- lease operations --------------------------------------------
    def _donor(self, spec):
        from shadow_tpu.fleet import scenario as scen
        from shadow_tpu.utils import checkpoint as ckpt

        key = (spec.id, spec.seed, spec.hosts, spec.load)
        if key not in self._donors:
            donor = scen.build_tenant_donor(
                spec, width=self.width, lanes=self.lanes,
                caps=self.caps, horizon_ns=self.horizon_ns)
            self._donors[key] = ckpt._leaf_dict(donor.sim)
        return self._donors[key]

    def admit(self, job_id: str, *, lane: int | None = None,
              force: bool = False):
        """Admit a tenant at the current frontier. Returns the lane,
        or None when deferred (no free lane, gate deferring, or the
        lease would outrun the program horizon)."""
        from shadow_tpu.core import simtime

        spec = self.specs[job_id]
        if self.gate.defer_admissions and not force:
            self.table.record({"ev": "defer", "job": job_id,
                               "why": f"ladder at "
                                      f"{LADDER[self.gate.level]}"})
            self.log(f"admission deferred for {job_id} (ladder)")
            return None
        free = self.table.free_lanes()
        if lane is None:
            lane = free[0] if free else None
        elif lane not in free:
            raise ValueError(f"lane {lane} is not free")
        if lane is None:
            self.table.record({"ev": "defer", "job": job_id,
                               "why": "no free lane"})
            return None
        t_join = max(self.frontier, 0)
        lease_end = t_join + int(spec.sim_s) * simtime.ONE_SECOND
        if lease_end > self.horizon_ns:
            self.table.record({"ev": "defer", "job": job_id,
                               "why": "lease outruns horizon"})
            return None
        self._implant(spec, lane, t_join, lease_end)
        return lane

    def _implant(self, spec, lane: int, t_join: int, lease_end: int):
        epoch = self.table.lease[lane].epoch + 1
        self.table.record({
            "ev": "lease", "lane": lane, "state": ADMITTED,
            "job": spec.id, "epoch": epoch, "t_join": int(t_join),
            "lease_end": int(lease_end),
            "tenant_class": spec.tenant_class,
            "slo_p99_ms": spec.slo_p99_ms,
        })
        self.sim = _flush_lane(self.sim, lane, self.width)
        self.sim = _implant_lane(self.sim, self._donor(spec), lane,
                                 self.width, t_join)
        self.sim = _set_lease_planes(self.sim, lane, active=True,
                                     lease_end=lease_end, t_join=t_join,
                                     bump_epoch=True)
        self.table.record({"ev": "lease", "lane": lane,
                           "state": RUNNING, "job": spec.id,
                           "epoch": epoch})
        self._note_admission_event()
        self.log(f"lane {lane}: admitted {spec.id} at t={t_join} "
                 f"(lease_end={lease_end}, epoch={epoch})")

    def evict(self, job_id: str, *, reason: str = "operator") -> bool:
        lease = next((l for l in self.table.resident()
                      if l.job == job_id), None)
        if lease is None:
            return False
        self._end_lease(lease, EVICTED, reason=reason, salvage=True)
        self._note_admission_event()
        return True

    def _salvage(self, lease) -> str | None:
        from shadow_tpu.faults.escalate import extract_lane
        from shadow_tpu.utils import checkpoint as ckpt

        try:
            leaves = ckpt._leaf_dict(self.sim)
            meta = {"time_ns": int(self.frontier),
                    "capacities": ckpt.capacities_of_sim(self.sim),
                    "extra": {"job": lease.job, "epoch": lease.epoch,
                              "t_join": lease.t_join,
                              "lease_end": lease.lease_end,
                              "reason": lease.reason}}
            out, lane_meta = extract_lane(leaves, meta, lease.lane,
                                          self.lanes)
            path = os.path.join(
                self.workdir,
                f"salvage.{lease.job}.lane{lease.lane}"
                f".e{lease.epoch}.npz")
            return ckpt.save_salvage(path, out, lane_meta)
        except Exception as e:  # noqa: BLE001 — salvage is best-effort
            self.log(f"salvage failed for {lease.job}: {e}")
            return None

    def _end_lease(self, lease, state: str, *, reason: str = "",
                   salvage: bool = False, quarantine: bool = False):
        lease.reason = reason or None
        digest = lane_digest(self.sim, lease.lane, self.lanes)
        salvage_path = self._salvage(lease) if salvage else None
        rec = {"ev": "lease", "lane": lease.lane, "state": state,
               "job": lease.job, "epoch": lease.epoch,
               "t_end": int(self.frontier), "digest": digest}
        if reason:
            rec["reason"] = reason
        if salvage_path:
            rec["salvage"] = salvage_path
        self.table.record(rec)
        self.results[lease.job] = dict(rec, tenant_class=lease.tenant_class)
        if quarantine:
            self.sim = _quarantine_lane(self.sim, lease.lane,
                                        self.frontier)
            # quarantined lanes stay parked: no "free" frame
        else:
            self.sim = _flush_lane(self.sim, lease.lane, self.width)
            self.table.record({"ev": "lease", "lane": lease.lane,
                               "state": FREE, "job": lease.job,
                               "epoch": lease.epoch})
        self.sim = _set_lease_planes(self.sim, lease.lane, active=False)
        self.log(f"lane {lease.lane}: {lease.job} -> {state}"
                 + (f" ({reason})" if reason else ""))

    # -- the barrier fold --------------------------------------------
    def _fold_barrier(self):
        """Process one barrier: completions and quarantines from the
        device planes, then flow drain + SLO gate actions."""
        import numpy as np

        adm = self.sim.admission
        done = np.asarray(adm.completed)
        quar = np.asarray(self.sim.lanes.quarantined)
        for lease in list(self.table.resident()):
            if lease.state != RUNNING:
                continue
            if bool(quar[lease.lane]):
                lease.reason = "lane quarantined"
                self._end_lease(lease, QUARANTINED,
                                reason="lane health trip",
                                salvage=True, quarantine=True)
                self._note_admission_event()
            elif bool(done[lease.lane]):
                self._end_lease(lease, COMPLETED, salvage=False)
                self._note_admission_event()
        if self.harvester is None:
            return
        self.harvester.drain(self.sim)
        fresh = self.harvester.flow_records[self._flow_cursor:]
        self._flow_cursor = len(self.harvester.flow_records)
        level_before = self.gate.level
        for act, lane, why in self.gate.evaluate(fresh, self.table):
            lease = self.table.lease[lane]
            if lease.state != RUNNING:
                continue
            if act == "evict":
                self._end_lease(lease, EVICTED, reason=why,
                                salvage=True)
            else:
                self._end_lease(lease, QUARANTINED, reason=why,
                                salvage=True, quarantine=True)
            self._note_admission_event()
        if self.gate.level != level_before:
            self.table.record({
                "ev": "degrade", "level": self.gate.level,
                "step": LADDER[self.gate.level],
                "why": f"ladder {'up' if self.gate.level > level_before else 'down'} "
                       f"(stride={self.gate.stride})"})
            self.log(f"degradation ladder -> "
                     f"{LADDER[self.gate.level]}")

    def _maybe_checkpoint(self):
        from shadow_tpu.utils import checkpoint as ckpt

        if (self.checkpoint_every_events
                and self._events_since_ck >= self.checkpoint_every_events):
            self._events_since_ck = 0
            ckpt.save(f"{self._ck_prefix}.{int(self.frontier)}",
                      self.sim, time_ns=int(self.frontier),
                      extra={"lease_seq": self.table.seq,
                             "kind": "resident"})

    # -- driving ------------------------------------------------------
    def advance(self, *, until_ns: int | None = None,
                max_dispatches: int = 100000) -> int:
        """Run dispatches (folding every barrier) until the frontier
        reaches `until_ns` (or the resident set drains). Returns the
        frontier."""
        import numpy as np

        from shadow_tpu.core import simtime

        target = (self.horizon_ns if until_ns is None
                  else min(int(until_ns), self.horizon_ns))
        for _ in range(max_dispatches):
            self._fold_barrier()
            self._maybe_checkpoint()
            if self.frontier >= target:
                break
            nm = int(np.min(np.asarray(
                __import__("jax").device_get(self.sim.events.min_time()))))
            if nm == simtime.INVALID:
                # nothing pending anywhere: the frontier jumps to the
                # target (idle time costs zero dispatches)
                self.frontier = target
                self._fold_barrier()
                break
            wstart = max(nm, 0)
            if wstart >= target:
                self.frontier = min(wstart, target)
                continue
            nxt = self._dispatch_once(wstart)
            self.frontier = (nxt if nxt != simtime.INVALID
                             else min(wstart + self._plan.min_jump,
                                      target))
        return self.frontier

    def drain(self, *, max_dispatches: int = 100000) -> int:
        """Run until every resident lease reaches a terminal state."""
        import numpy as np

        from shadow_tpu.core import simtime

        for _ in range(max_dispatches):
            self._fold_barrier()
            self._maybe_checkpoint()
            if not self.table.resident():
                break
            nm = int(np.min(np.asarray(
                __import__("jax").device_get(self.sim.events.min_time()))))
            if nm == simtime.INVALID:
                # resident but quiet: the next fold collects them
                self.frontier = max(
                    self.frontier,
                    max((l.lease_end or 0)
                        for l in self.table.resident()))
                self._fold_barrier()
                break
            nxt = self._dispatch_once(max(nm, 0))
            self.frontier = (nxt if nxt != simtime.INVALID
                             else self.frontier + self._plan.min_jump)
        return self.frontier

    # -- manifest / teardown -----------------------------------------
    def manifest_block(self) -> dict:
        from shadow_tpu.core.lanes import admission_report

        blk = dict(self.table.counts())
        blk.update({
            "program_key": self.program_key,
            "program_key_stable": bool(self.program_key_stable),
            "admission_events": int(self.admission_events),
            "retraces": int(self.retraces_seen),
            "lane_width": int(self.width),
            "degrade_level": int(self.gate.level),
            "degrade_step": LADDER[self.gate.level],
            "degrade_history": list(self.table.degrade_history),
            "per_lane": admission_report(self.sim),
            "slo": {
                "eval_stride": int(self.gate.stride),
                "sustained": int(self.gate.sustained),
                "breached_jobs": {
                    k: round(v, 3)
                    for k, v in self.gate.breached_jobs.items()},
                "last_p99_ns": {str(k): int(v) for k, v in
                                sorted(self.gate.last_p99.items())},
            },
            "lease_warnings": list(self.table.fold_warnings),
        })
        return blk

    def close(self) -> None:
        self.table.close()

    # -- resume -------------------------------------------------------
    @classmethod
    def resume(cls, specs, *, workdir: str, lanes: int, horizon_s: int,
               **kw):
        """Reconstruct a resident program after a crash: replay the
        lease journal (torn tail truncated by the framing), load the
        newest checkpoint, and re-apply any lease frame newer than
        the checkpoint's recorded lease_seq — joins re-implant their
        donors at the journaled t_join, terminal frames re-flush. The
        resident population is then EXACTLY the journal's fold, which
        is the acceptance contract."""
        from shadow_tpu.utils import checkpoint as ckpt

        rp = cls(specs, workdir=workdir, lanes=lanes,
                 horizon_s=horizon_s, resume=True, **kw)
        ck = ckpt.latest_checkpoint(rp._ck_prefix)
        ck_seq = 0
        if ck is not None:
            leaves, meta = ckpt.load_leaves(ck)
            rp.sim = _sim_from_leaves(rp.sim, leaves)
            rp.frontier = int(meta.get("time_ns", 0))
            ck_seq = int((meta.get("extra") or {}).get("lease_seq", 0))
        # re-apply the journal tail the checkpoint missed
        tail = [r for r in journal_mod.replay(rp.table.path)[0]
                if r.get("ev") == "lease"
                and int(r.get("seq", 0)) > ck_seq]
        for rec in tail:
            lane, st = int(rec["lane"]), rec.get("state")
            if st == ADMITTED:
                spec = rp.specs[rec["job"]]
                rp.sim = _flush_lane(rp.sim, lane, rp.width)
                rp.sim = _implant_lane(rp.sim, rp._donor(spec), lane,
                                       rp.width, int(rec["t_join"]))
                rp.sim = _set_lease_planes(
                    rp.sim, lane, active=True,
                    lease_end=int(rec["lease_end"]),
                    t_join=int(rec["t_join"]), bump_epoch=True)
            elif st in LEASE_TERMINAL or st == FREE:
                rp.sim = _flush_lane(rp.sim, lane, rp.width)
                rp.sim = _set_lease_planes(rp.sim, lane, active=False)
        return rp


def _sim_from_leaves(template, leaves: dict):
    """Rebuild a same-shape Sim from checkpoint leaves (keystr-keyed,
    utils/checkpoint.py layout). Leaves absent from the snapshot keep
    the template's value; shape mismatches are refused by name."""
    import jax
    import jax.numpy as jnp

    def pick(path, a):
        key = jax.tree_util.keystr(path)
        b = leaves.get(key)
        if b is None:
            return a
        if hasattr(a, "shape") and tuple(a.shape) != tuple(b.shape):
            raise ValueError(
                f"resume: leaf {key} shape {b.shape} != template "
                f"{tuple(a.shape)}")
        # jnp.array (copy=True), NOT asarray: on CPU, asarray can
        # alias the snapshot's numpy memory zero-copy, and the
        # dispatch DONATES these leaves — donating a buffer numpy
        # still owns corrupts the heap
        return jnp.array(b, dtype=a.dtype)

    return jax.tree_util.tree_map_with_path(pick, template)


def _check_tenant(spec) -> None:
    if spec.kind != "scenario":
        raise ValueError(f"tenant {spec.id}: resident programs take "
                         f"kind 'scenario' jobs, got {spec.kind!r}")
    if int(getattr(spec, "replicas", 1)) != 1:
        raise ValueError(f"tenant {spec.id}: a tenant occupies ONE "
                         f"lane (replicas must be 1)")
    if spec.inject_trace is not None:
        raise ValueError(f"tenant {spec.id}: trace injection is not "
                         f"supported in resident lanes")
    if spec.faults:
        raise ValueError(
            f"tenant {spec.id}: per-tenant fault plans would bake "
            f"into the shared program (kind_census) — resident "
            f"tenants must not carry faults")


# --- fleet integration -----------------------------------------------

def run_resident_fleet(fleet_dir: str, policy, specs, *,
                       lanes: int | None = None,
                       horizon_s: int | None = None,
                       resume: bool = False, log=None,
                       gate: AdmissionGate | None = None,
                       flow_sample: int = 1, fsync: bool = True) -> dict:
    """`fleet run --resident`: execute every job as a tenant lease of
    ONE resident program instead of one worker process per job. The
    fleet queue keeps its journal/manifest contract (leases map to
    leased/running frames, terminal leases to done/requeued/
    quarantined), and the lease journal + admission block ride next
    to them. Returns the fleet manifest dict."""
    from shadow_tpu.fleet import manifest as manifest_mod
    from shadow_tpu.fleet.state import FleetQueue

    say = log or (lambda m: None)
    queue = FleetQueue(fleet_dir, policy, specs, resume=resume,
                       fsync=fsync)
    tenants = [j.spec for j in queue.jobs.values()]
    if lanes is None:
        lanes = max(2, len(tenants))
    if horizon_s is None:
        horizon_s = 4 * max(int(s.sim_s) for s in tenants) * max(
            2, len(tenants))
    rp_cls = (ResidentProgram.resume if resume else ResidentProgram)
    rp = rp_cls(tenants, workdir=os.path.join(fleet_dir, "resident"),
                lanes=int(lanes), horizon_s=int(horizon_s),
                gate=gate, flow_sample=flow_sample, fsync=fsync,
                log=say)
    resident_jobs = {l.job for l in rp.table.resident()}
    for jid, j in queue.jobs.items():
        # resumed leases keep running; everything else non-terminal
        # queues for admission
        if jid in resident_jobs and j.status != "running":
            queue.record({"ev": "leased", "job": jid,
                          "worker": "resident", "attempt":
                          max(1, j.attempts)})
            queue.record({"ev": "running", "job": jid,
                          "worker": "resident",
                          "attempt": max(1, j.attempts)})

    def _write_manifest(complete=False):
        man = manifest_mod.fleet_manifest(queue, workers_alive=0,
                                          complete=complete,
                                          admission=rp.manifest_block())
        manifest_mod.write_fleet_manifest(
            os.path.join(fleet_dir, "fleet_manifest.json"), man)
        return man

    guard = 0
    while True:
        guard += 1
        if guard > 1000:
            say("resident fleet: progress guard tripped")
            break
        settled = {jid for jid, j in queue.jobs.items() if j.terminal}
        # admit every ready job a free lane will take
        for j in queue.ready(queue.now()):
            if rp.table.free_lanes() and not rp.gate.defer_admissions:
                lane = rp.admit(j.spec.id)
                if lane is not None:
                    queue.lease(j.spec.id, "resident")
                    queue.mark_running(j.spec.id, "resident")
        if not rp.table.resident():
            if all(j.terminal for j in queue.jobs.values()):
                break
            if not queue.ready(queue.now()):
                break              # only backed-off/deferred jobs left
            continue
        rp.drain(max_dispatches=10000)
        for job_id, rec in list(rp.results.items()):
            rp.results.pop(job_id, None)   # consume: a later lease of
            # this job must not re-fold a stale verdict
            if job_id in settled or queue.jobs[job_id].terminal:
                continue
            st = rec.get("state")
            if st == COMPLETED:
                queue.complete(job_id, {
                    "ok": True, "digest": rec.get("digest"),
                    "lease": rec, "program_key": rp.program_key})
            elif st == EVICTED:
                # shedding is not the tenant's fault: requeue, don't
                # burn the failure budget
                queue.record({"ev": "requeued", "job": job_id,
                              "resume_from": None,
                              "cause": f"evicted: {rec.get('reason')}"})
            elif st == QUARANTINED:
                queue.quarantine(job_id,
                                 f"lane quarantined: {rec.get('reason')}",
                                 {"lease": rec})
        _write_manifest()
    man = _write_manifest(
        complete=all(j.terminal for j in queue.jobs.values()))
    rp.close()
    queue.close()
    return man
