"""Durable fleet queue: the job state machine over the journal.

Life of a job (docs/8-fleet.md §state machine):

    queued -> leased -> running -> done
                             \\-> failed   (non-retryable)
                             \\-> (fail)   -> backoff -> queued ...
                                             attempts exhausted
                                             -> quarantined
    worker lost / lease expired / fleet preempted
        -> requeued (same attempt, resume_from = last checkpoint)
           requeue budget exhausted -> quarantined

Terminal states and what they mean:

- **done**: the scenario completed with a clean (or self-healed)
  verdict.
- **failed**: non-retryable — the worker classified the error as
  deterministic at spec/build level (bad spec, build exception);
  retrying would reproduce it.
- **quarantined**: the job exhausted its attempt budget (or its
  worker-loss requeue budget) and is *parked*: its last checkpoint,
  run manifest, and failure report stay salvaged in its spec dir and
  the fleet manifest records why — the job stops poisoning the queue
  but loses nothing.

Attempt accounting: `attempts` counts failure retries (1-based,
bounded by max_attempts); a worker-loss requeue re-executes the SAME
attempt from its checkpoint (bounded separately by requeue_budget) —
crashing workers must not burn a job's failure budget, and a resumed
execution is a continuation, not a do-over.

Every transition is one journal frame; the whole struct rebuilds by
replay (`FleetQueue(..., resume=True)`), which is exactly what
`fleet run --resume` does: done/failed/quarantined stick, leased and
running jobs come back as queued with their recorded resume point.

Deterministic backoff: see backoff_delay() — seeded by
(backoff_seed, job id, attempt), so two runs of the same fleet
produce the same schedule (reproducible fleet logs).
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Optional

import numpy as np

from shadow_tpu.fleet import journal as journal_mod
from shadow_tpu.fleet.spec import FleetPolicy, JobSpec

QUEUED = "queued"
LEASED = "leased"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
QUARANTINED = "quarantined"

TERMINAL = (DONE, FAILED, QUARANTINED)


def backoff_delay(policy: FleetPolicy, job_id: str,
                  attempt: int) -> float:
    """Deterministic exponential backoff with seeded jitter. The
    jitter RNG is keyed by (fleet backoff seed, job id, attempt), so
    the delay for sweep-07's attempt 2 is the same number in every
    run of the fleet — reproducible logs — while still de-phasing
    jobs from each other (the point of jitter)."""
    base = min(policy.backoff_cap_s,
               policy.backoff_base_s * (2.0 ** max(attempt - 1, 0)))
    rng = np.random.default_rng(
        [policy.backoff_seed & 0xFFFFFFFF,
         zlib.crc32(job_id.encode()), attempt])
    return float(base * (1.0 + 0.25 * rng.random()))


class JobState:
    def __init__(self, spec: JobSpec):
        self.spec = spec
        self.status = QUEUED
        self.attempts = 0            # failure attempts started
        self.execs = 0               # executions incl. requeues
        self.worker_losses = 0
        self.device_losses = 0       # DEVICE_LOST requeues (elastic)
        self.shards_override: Optional[int] = None  # degraded width
        self.worker: Optional[str] = None
        self.lease_expires: Optional[float] = None
        self.deadline_at: Optional[float] = None
        self.last_heartbeat: Optional[float] = None
        self.backoff_until: float = 0.0
        self.backoff_history: list = []   # seconds per failure retry
        self.attempt_history: list = []   # attempt no. per execution
        self.resume_from: Optional[str] = None
        self.continuation = False    # next lease resumes, not retries
        self.checkpoint: Optional[str] = None  # latest known
        self.result: Optional[dict] = None
        self.failure: Optional[dict] = None
        self.quarantine_reason: Optional[str] = None

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL


class FleetQueue:
    """Single-writer queue (the fleet supervisor owns the journal).
    All transitions go through record(): append one frame, then fold
    it into the in-memory state — replay and live execution share the
    same fold, so a resumed queue cannot disagree with a live one."""

    def __init__(self, fleet_dir: str, policy: FleetPolicy,
                 specs=None, *, resume: bool = False,
                 fsync: bool = True, now=time.time):
        os.makedirs(fleet_dir, exist_ok=True)
        self.fleet_dir = fleet_dir
        self.policy = policy
        self.now = now
        self.jobs: dict[str, JobState] = {}
        self.events = 0
        # duplicate/conflicting frames the fold refused (idempotent
        # replay hardening): a journal whose tail carries a second
        # terminal transition for a settled job — a crash between a
        # worker's result landing and the supervisor's ack can write
        # one — must replay to the FIRST verdict, warn, and not crash.
        # The fleet manifest surfaces these (journal_warnings).
        self.fold_warnings: list = []
        jpath = os.path.join(fleet_dir, "journal.log")
        if resume:
            old, _ = journal_mod.replay(jpath)
            if not old and specs is None:
                raise FileNotFoundError(
                    f"--resume: no journal at {jpath}")
            for spec in self._specs_from_dirs():
                self.jobs[spec.id] = JobState(spec)
            for rec in old:
                self._apply(rec)
            self._requeue_inflight()
        elif os.path.exists(jpath) and journal_mod.replay(jpath)[0]:
            raise FileExistsError(
                f"{jpath} already holds a fleet journal — pass "
                f"--resume to continue it or point --fleet-dir at a "
                f"fresh directory")
        self.journal = journal_mod.Journal(jpath, fsync=fsync)
        if specs is not None:
            for spec in specs:
                if spec.id not in self.jobs:
                    self._add_job(spec)

    # -- spec dirs ----------------------------------------------------
    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.fleet_dir, "jobs", job_id)

    def _specs_from_dirs(self) -> list:
        import json as _json

        out = []
        root = os.path.join(self.fleet_dir, "jobs")
        if not os.path.isdir(root):
            return out
        for name in sorted(os.listdir(root)):
            p = os.path.join(root, name, "spec.json")
            if os.path.isfile(p):
                with open(p) as f:
                    out.append(JobSpec.from_dict(_json.load(f)))
        return out

    def _add_job(self, spec: JobSpec) -> None:
        import json as _json

        d = self.job_dir(spec.id)
        os.makedirs(d, exist_ok=True)
        sp = os.path.join(d, "spec.json")
        tmp = sp + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(spec.as_dict(), f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, sp)
        journal_mod.fsync_dir(d)
        self.jobs[spec.id] = JobState(spec)
        self.record({"ev": "job_added", "job": spec.id,
                     "spec_digest": spec.digest()})

    def add_job(self, spec: JobSpec) -> bool:
        """Backfill a job mid-run (the packed-job lane-requeue path):
        journal a job_added frame and write its spec dir, exactly like
        boot-time enqueue. Idempotent by id — on --resume the spec-dir
        scan restores the child spec and the replayed journal keeps
        its state, so a crash between requeue and lease loses
        nothing."""
        if spec.id in self.jobs:
            return False
        self._add_job(spec)
        return True

    # -- journal fold -------------------------------------------------
    def record(self, rec: dict) -> dict:
        rec.setdefault("t", round(self.now(), 3))
        self.journal.append(rec)
        self._apply(rec)
        return rec

    # events that (re)write a job's status — once a job is terminal,
    # folding another of these would overwrite its verdict, so the
    # fold keeps the FIRST terminal state and warns instead (the
    # journal is append-only; a dead writer's retry or a result that
    # raced a worker_lost can legitimately leave such frames).
    # worker_lost/heartbeat stay foldable: they only touch counters.
    _STATUS_EVENTS = ("leased", "running", "done", "failed",
                      "requeued", "quarantined")

    def _apply(self, rec: dict) -> None:
        self.events += 1
        ev = rec.get("ev")
        j = self.jobs.get(rec.get("job", ""))
        if j is not None and j.terminal and ev in self._STATUS_EVENTS:
            self.fold_warnings.append(
                f"journal: '{ev}' frame for job {j.spec.id} ignored — "
                f"job already terminal ({j.status}); keeping the "
                f"first verdict")
            return
        if ev == "leased" and j is not None:
            j.status = LEASED
            j.worker = rec.get("worker")
            j.attempts = max(j.attempts, int(rec.get("attempt", 1)))
            j.execs += 1
            j.attempt_history.append(int(rec.get("attempt", 1)))
            j.resume_from = rec.get("resume_from")
            j.lease_expires = rec.get("t", 0) + self.policy.lease_timeout_s
            j.last_heartbeat = rec.get("t")
            mw = j.spec.max_wallclock_s
            j.deadline_at = (rec.get("t", 0)
                             + mw * self.policy.deadline_grace
                             if mw else None)
        elif ev == "running" and j is not None:
            j.status = RUNNING
        elif ev == "heartbeat" and j is not None:
            j.last_heartbeat = rec.get("t")
            j.lease_expires = rec.get("t", 0) + self.policy.lease_timeout_s
            if rec.get("checkpoint"):
                j.checkpoint = rec["checkpoint"]
        elif ev == "done" and j is not None:
            j.status = DONE
            j.worker = None
            j.result = rec.get("result")
        elif ev == "failed" and j is not None:
            j.failure = rec.get("failure")
            if rec.get("final"):
                j.status = FAILED
                j.worker = None
            else:
                j.status = QUEUED
                j.worker = None
                j.backoff_until = rec.get("t", 0) + rec.get("backoff_s", 0)
                j.backoff_history.append(rec.get("backoff_s", 0))
                j.resume_from = None   # a failed attempt restarts clean
                j.continuation = False
        elif ev == "requeued" and j is not None:
            j.status = QUEUED
            j.worker = None
            j.resume_from = rec.get("resume_from")
            j.continuation = True
        elif ev == "worker_lost" and j is not None:
            j.worker_losses += 1
        elif ev == "device_lost" and j is not None:
            j.device_losses += 1
            if rec.get("new_shards"):
                j.shards_override = int(rec["new_shards"])
        elif ev == "quarantined" and j is not None:
            j.status = QUARANTINED
            j.worker = None
            j.quarantine_reason = rec.get("reason")
            j.failure = rec.get("failure", j.failure)

    def _requeue_inflight(self) -> None:
        """Resume fold-up: anything the dead fleet left leased or
        running comes back queued, resuming from its last recorded
        checkpoint (heartbeats carry them) or whatever the job dir
        scan finds."""
        from shadow_tpu.utils import checkpoint as ckpt

        for j in self.jobs.values():
            if j.status in (LEASED, RUNNING):
                j.status = QUEUED
                j.worker = None
                j.resume_from = j.checkpoint or ckpt.latest_checkpoint(
                    os.path.join(self.job_dir(j.spec.id), "ck"))
                j.continuation = True
                j.backoff_until = 0.0

    # -- scheduler queries --------------------------------------------
    def ready(self, now: float) -> list:
        """QUEUED jobs whose backoff has elapsed, FIFO by job order."""
        return [j for j in self.jobs.values()
                if j.status == QUEUED and j.backoff_until <= now]

    def pending(self) -> list:
        return [j for j in self.jobs.values() if not j.terminal]

    def in_flight(self) -> list:
        return [j for j in self.jobs.values()
                if j.status in (LEASED, RUNNING)]

    def next_wakeup(self, now: float) -> float:
        """Seconds until the earliest backoff expiry (for the
        scheduler's poll timeout); 0 when something is ready."""
        waits = [max(0.0, j.backoff_until - now)
                 for j in self.jobs.values() if j.status == QUEUED]
        return min(waits) if waits else 0.0

    # -- transitions --------------------------------------------------
    def lease(self, job_id: str, worker: str) -> dict:
        j = self.jobs[job_id]
        assert j.status == QUEUED, (job_id, j.status)
        attempt = (j.attempts if j.continuation and j.attempts
                   else j.attempts + 1)
        return self.record({
            "ev": "leased", "job": job_id, "worker": worker,
            "attempt": attempt, "resume_from": j.resume_from})

    def mark_running(self, job_id: str, worker: str) -> None:
        self.record({"ev": "running", "job": job_id, "worker": worker,
                     "attempt": self.jobs[job_id].attempts})

    def heartbeat(self, job_id: str, *, checkpoint=None,
                  journal_it: bool = True) -> None:
        rec = {"ev": "heartbeat", "job": job_id,
               "checkpoint": checkpoint}
        if journal_it:
            self.record(rec)
        else:                       # lease refresh without a frame
            rec["t"] = self.now()
            self._apply(rec)

    def complete(self, job_id: str, result: dict) -> None:
        self.record({"ev": "done", "job": job_id,
                     "attempt": self.jobs[job_id].attempts,
                     "result": result})

    def fail(self, job_id: str, failure: dict, *,
             fatal: bool = False) -> str:
        """Returns the resulting status (queued/failed/quarantined)."""
        j = self.jobs[job_id]
        budget = j.spec.max_attempts or self.policy.max_attempts
        if fatal:
            self.record({"ev": "failed", "job": job_id,
                         "attempt": j.attempts, "failure": failure,
                         "final": True})
            return FAILED
        if j.attempts >= budget:
            self.quarantine(job_id, f"attempts exhausted "
                            f"({j.attempts}/{budget})", failure)
            return QUARANTINED
        delay = backoff_delay(self.policy, job_id, j.attempts)
        self.record({"ev": "failed", "job": job_id,
                     "attempt": j.attempts, "failure": failure,
                     "backoff_s": round(delay, 6)})
        return QUEUED

    def worker_lost(self, worker: str, job_id: Optional[str],
                    reason: str) -> str:
        """A worker died or its lease expired. Requeue its job (same
        attempt, resume from checkpoint) unless the job has burned
        its requeue budget. Returns the job's resulting status
        ('' when the worker held no job)."""
        self.record({"ev": "worker_lost", "worker": worker,
                     "job": job_id, "reason": reason})
        if job_id is None:
            return ""
        from shadow_tpu.utils import checkpoint as ckpt

        j = self.jobs[job_id]
        if j.terminal:              # result raced the loss; keep it
            return j.status
        if j.worker_losses > self.policy.requeue_budget:
            self.quarantine(job_id, f"requeue budget exhausted "
                            f"({j.worker_losses} worker losses)",
                            {"reason": reason})
            return QUARANTINED
        resume = j.checkpoint or ckpt.latest_checkpoint(
            os.path.join(self.job_dir(job_id), "ck"))
        self.record({"ev": "requeued", "job": job_id,
                     "resume_from": resume, "cause": reason})
        return QUEUED

    def device_lost(self, job_id: str, *, lost_shard: int,
                    new_shards: int, cause: str = "") -> str:
        """A device in a leased shard set died mid-run (the in-run
        elastic ladder exhausted its meshes, or the worker surfaced a
        DEVICE_LOST verdict). Requeue the job as a continuation of the
        SAME attempt at the degraded width — device loss is
        environment, not the job's fault, so it must not burn the
        failure budget — bounded by the shared requeue budget. The
        degraded width sticks (shards_override) so the next lease
        dispatches the shrunk spec; checkpoints hold global layout, so
        the shrunk mesh resumes the same run. Returns the job's
        resulting status."""
        self.record({"ev": "device_lost", "job": job_id,
                     "lost_shard": lost_shard,
                     "new_shards": int(new_shards), "cause": cause})
        from shadow_tpu.utils import checkpoint as ckpt

        j = self.jobs[job_id]
        if j.terminal:              # result raced the loss; keep it
            return j.status
        if (j.worker_losses + j.device_losses
                > self.policy.requeue_budget):
            self.quarantine(job_id, f"requeue budget exhausted "
                            f"({j.device_losses} device losses, "
                            f"{j.worker_losses} worker losses)",
                            {"fault": "DEVICE_LOST", "cause": cause})
            return QUARANTINED
        resume = j.checkpoint or ckpt.latest_checkpoint(
            os.path.join(self.job_dir(job_id), "ck"))
        self.record({"ev": "requeued", "job": job_id,
                     "resume_from": resume,
                     "cause": f"device lost (shard {lost_shard}): "
                              f"{cause}" if cause else
                              f"device lost (shard {lost_shard})"})
        return QUEUED

    def quarantine(self, job_id: str, reason: str,
                   failure: Optional[dict] = None) -> None:
        j = self.jobs[job_id]
        self.record({"ev": "quarantined", "job": job_id,
                     "attempt": j.attempts, "reason": reason,
                     "failure": failure})

    def close(self) -> None:
        self.journal.close()
