"""Append-only, CRC-framed state journal — the fleet queue's source
of truth (docs/8-fleet.md).

Every queue transition (job added, leased, running, heartbeat, done,
failed, requeued, quarantined, worker lost, fleet preempted) is one
frame appended to `journal.log`. A frame is

    magic   2 bytes  b"SJ"   (catches "this is not a journal" early)
    length  4 bytes  u32 LE  payload byte count
    crc32   4 bytes  u32 LE  over the payload bytes
    payload N bytes  JSON (UTF-8), one record object
    newline 1 byte   b"\\n"  (debuggability: `strings journal.log`
                              reads roughly like JSON lines)

Durability contract (the fleet's analog of utils/checkpoint.py's
torn-snapshot rule): each append is a single write() of the whole
frame followed by flush + fsync, and the journal's parent directory
is fsynced when the file is first created — so acknowledged frames
survive power loss, not just process death. A frame torn by a crash
mid-write (short frame, bad CRC, bad magic) can only be the LAST
frame; replay() stops at the first bad frame and reports the byte
offset of the good prefix, and Journal() opened for append truncates
the file back to that offset so the torn tail can never corrupt
later frames. tests/test_fleet.py::test_journal_torn_write proves
the truncate-and-replay round trip.

Single writer by design: only the fleet supervisor process appends.
Workers report through their pipes and their per-job dirs; the
supervisor serializes everything into this one ordered record, which
is what makes `fleet run --resume` a pure replay.

Idempotent-fold contract: replay() returns frames verbatim — it is
the FOLDS over them that must be idempotent against duplicates. A
crash between an effect landing and its ack can journal the same
terminal transition twice (a second `done`/`failed`/`quarantined`
for a settled job, a second terminal lease frame for a settled
lane); both consumers keep the FIRST terminal state and warn instead
of crashing or flipping the verdict (fleet/state.py FleetQueue._apply
for job frames, fleet/admission.py LeaseTable._apply for lane-lease
frames). tests/test_fleet.py and tests/test_admission.py cover the
duplicate-terminal and torn-tail cases for both frame families.
"""

from __future__ import annotations

import json
import os
import struct
import zlib

MAGIC = b"SJ"
_HEADER = struct.Struct("<2sII")   # magic, length, crc32


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/create inside it is durable
    (POSIX: the atomic rename in checkpoint.save and the journal
    create both reach the disk only when their directory entry does).
    Best-effort on filesystems that refuse O_RDONLY dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def encode_frame(record: dict) -> bytes:
    payload = json.dumps(record, separators=(",", ":"),
                         sort_keys=True).encode()
    return (_HEADER.pack(MAGIC, len(payload), zlib.crc32(payload))
            + payload + b"\n")


def replay(path: str) -> tuple[list, int]:
    """Read every intact frame. Returns (records, good_bytes) where
    good_bytes is the offset just past the last intact frame — a torn
    or corrupt tail (short header, short payload, CRC mismatch, bad
    magic) ends the replay there instead of raising: the tail can
    only be the frame the dying writer never finished."""
    records: list = []
    good = 0
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return records, good
    off = 0
    n = len(data)
    while off + _HEADER.size <= n:
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != MAGIC:
            break
        end = off + _HEADER.size + length + 1   # +1 newline
        if end > n:
            break
        payload = data[off + _HEADER.size:end - 1]
        if data[end - 1:end] != b"\n" or zlib.crc32(payload) != crc:
            break
        try:
            records.append(json.loads(payload))
        except ValueError:
            break
        off = end
        good = off
    return records, good


class Journal:
    """Append handle. Opening truncates any torn tail (see replay)
    and fsyncs the parent directory if the file was just created."""

    def __init__(self, path: str, *, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        existed = os.path.exists(path)
        _, good = replay(path) if existed else ([], 0)
        self._f = open(path, "ab")
        if existed and self._f.tell() > good:
            self._f.truncate(good)
            self._f.seek(good)
        if not existed:
            self._f.flush()
            os.fsync(self._f.fileno())
            fsync_dir(os.path.dirname(os.path.abspath(path)))

    def append(self, record: dict) -> None:
        self._f.write(encode_frame(record))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
