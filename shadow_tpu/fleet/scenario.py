"""The per-job engine: one scenario, run under the self-healing
supervisor, inside a worker process.

The fleet layer deliberately reuses the single-run machinery rather
than reimplementing any of it: `faults.run_supervised` is the engine
(health latches, escalation, preemption snapshots, the new wallclock
deadline), `utils/checkpoint.py` is the resume mechanism (a job
requeued after a worker SIGKILL continues from its own supervisor
checkpoint, under a different worker process — snapshots are
process-portable the same way they are shard-count-portable), and
`telemetry/export.py` writes the per-job `run_manifest.json` the
fleet manifest rolls up.

Determinism: run_job(spec) is a pure function of the spec — the
checkpoint contract (run(0->T) == run(0->C) + resume(C->T)) makes
the result independent of how many times the job was killed and
requeued, which is what the fleet's bit-identity acceptance test
asserts (tests/test_fleet_recovery.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

from shadow_tpu.fleet.spec import JobSpec


def sim_digest(sim) -> str:
    """sha256 over every leaf's bytes (keyed by leaf path) — the
    bit-identity fingerprint the fleet compares against a clean
    serial run of the same spec."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(sim)[0]:
        h.update(jax.tree_util.keystr(path).encode())
        h.update(np.ascontiguousarray(
            np.asarray(jax.device_get(leaf))).tobytes())
    return h.hexdigest()


def _write_json(path: str, obj) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# the PHOLD-on-one-vertex soak topology every scenario job runs on
# (shared with tools/chaos_soak.py and the resident-program builders
# below — one graph, so heterogeneous tenants differ only in their
# per-lane host count, load, seed and lease terms)
SOAK_GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="lat" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
  <graph edgedefault="undirected">
    <node id="v0"><data key="up">102400</data><data key="dn">102400</data>
    </node>
    <edge source="v0" target="v0"><data key="lat">50.0</data></edge>
  </graph>
</graphml>"""


def _build_scenario(spec: JobSpec, caps: dict):
    """chaos_soak's PHOLD-on-one-vertex scenario surface, sized by
    the spec (undersized caps + auto_grow exercises escalation;
    undersized caps without auto_grow is the deterministic-failure /
    quarantine vector)."""
    from shadow_tpu.apps import phold
    from shadow_tpu.core import simtime
    from shadow_tpu.net.build import HostSpec, build
    from shadow_tpu.net.state import NetConfig

    from shadow_tpu import faults

    graph = SOAK_GRAPH
    lanes = 0
    if spec.inject_trace:
        # lane count must be stable across rebuilds/requeues — the
        # checkpoint's .inject leaves are [lanes]-shaped
        if spec.inject_lanes:
            lanes = int(spec.inject_lanes)
        else:
            from shadow_tpu.apps.tgen import lanes_for
            from shadow_tpu.inject import read_trace

            lanes = lanes_for(sum(1 for _ in
                                  read_trace(spec.inject_trace)))
    # packed job: R lane copies of the scenario in one program —
    # `hosts` is per-lane, the build carries hosts*replicas rows with
    # contiguous lane blocks (apps/phold.py replica_size) and lane-
    # isolated health attached below
    R = max(1, int(getattr(spec, "replicas", 1)))
    H = spec.hosts * R
    cfg = NetConfig(num_hosts=H, tcp=False,
                    end_time=spec.sim_s * simtime.ONE_SECOND,
                    seed=spec.seed,
                    event_capacity=caps["event_capacity"],
                    outbox_capacity=caps["outbox_capacity"],
                    router_ring=caps["router_ring"],
                    in_ring=max(8, 2 * spec.load),
                    inject_lanes=lanes)
    # quantize every shape-bearing knob to its power-of-two bucket so
    # jobs of nearby sizes share one compiled program (and one AOT
    # store entry). Padding is behavior-neutral until the first
    # overflow, so the run is bit-identical to the exact-capacity
    # build at the same bucket (compile/buckets.py; the lint checks
    # the recorded plan). The plan rides the bundle for the manifest.
    from shadow_tpu.compile.buckets import bucket_config

    cfg, bucket_plan = bucket_config(cfg)
    hosts = [HostSpec(name=f"p{i}", proc_start_time=0)
             for i in range(H)]
    b = build(cfg, graph, hosts)
    b.bucket_plan = bucket_plan
    b.sim = phold.setup(b.sim, load=spec.load,
                        replica_size=spec.hosts if R > 1 else None)
    if R > 1:
        from shadow_tpu.core import lanes as lanes_mod

        b.sim = lanes_mod.attach(b.sim, R)
    if spec.faults:
        from shadow_tpu.faults.plan import records_from_json

        faults.install(b, records_from_json({"faults":
                                             list(spec.faults)}))
    if int(getattr(spec, "flow_sample", 0) or 0) > 0:
        # per-flow latency tracing: the flow ring rides the sim pytree,
        # so rebuilds/escalations re-attach it the same way the app
        # state is re-set-up
        from shadow_tpu import telemetry

        b.sim = telemetry.attach_flows(
            b.sim, sample_period=int(spec.flow_sample))
    if int(getattr(spec, "causality_sample", 0) or 0) > 0:
        # causal lineage recorder + window-advance attribution
        # (telemetry/causality.py): rides the sim pytree the same way
        from shadow_tpu import telemetry

        b.sim = telemetry.attach_causality(
            b.sim, sample_period=int(spec.causality_sample))
    if getattr(spec, "sentinel", False):
        # cross-shard integrity sentinel (parallel/elastic.py): the
        # digest/latch subtree rides the sim pytree like flows and
        # causality, so checkpoints carry the verified-state ledger
        # and silent divergence latches instead of corrupting results
        from shadow_tpu.parallel import elastic as elastic_mod

        b.sim = elastic_mod.attach_sentinel(b.sim)
    # compile-time specialization LAST — the analysis reads the final
    # sim composition (attachments above) and the installed fault
    # plan. A lossless no-timer job serves the trimmed variant from
    # the warm store under its own key; a faulted job serves the full
    # program; the guard latch makes a violated assumption a fatal
    # health fault, never silent drift (compile/specialize.py).
    from shadow_tpu.compile import specialize as specialize_mod

    b = specialize_mod.apply(b, (phold.handler,),
                             app_bulk=getattr(b, "app_bulk", None),
                             mode=getattr(spec, "specialize", "auto"))
    return b


def resident_caps(specs) -> dict:
    """Shared capacity envelope for a heterogeneous tenant set: every
    shape-bearing knob takes the max any tenant asked for (then the
    shell build quantizes to pow2 buckets). Padding is behavior-
    neutral until the first overflow (compile/buckets.py), so the
    small tenant runs bit-identically at the big tenant's caps — the
    price of sharing one resident program."""
    specs = list(specs)
    if not specs:
        raise ValueError("resident_caps needs at least one tenant")
    return {
        "event_capacity": max(int(s.event_capacity) for s in specs),
        "outbox_capacity": max(int(s.outbox_capacity) for s in specs),
        "router_ring": max(int(s.router_ring) for s in specs),
        "in_ring": max(8, 2 * max(int(s.load) for s in specs)),
    }


def _resident_cfg(*, width: int, lanes: int, caps: dict,
                  horizon_ns: int, seed: int):
    """One NetConfig rule for the shell AND every tenant donor — the
    donor must build at bit-identical shapes/dtypes or the implant
    (fleet/admission.py) would be transplanting across programs."""
    from shadow_tpu.compile.buckets import bucket_config
    from shadow_tpu.net.state import NetConfig

    cfg = NetConfig(num_hosts=int(width) * int(lanes), tcp=False,
                    end_time=int(horizon_ns), seed=int(seed),
                    event_capacity=caps["event_capacity"],
                    outbox_capacity=caps["outbox_capacity"],
                    router_ring=caps["router_ring"],
                    in_ring=caps["in_ring"])
    return bucket_config(cfg)


def build_resident_shell(*, width: int, lanes: int, caps: dict,
                         horizon_ns: int, seed: int = 0,
                         flow_sample: int = 1):
    """The resident program's bundle: R FREE lanes of `width` hosts,
    lane health + admission + (optionally) flow tracing attached, and
    NO pending events — build() seeds every host's PROC_START, but a
    FREE lane must be empty BEFORE the first window or the boot
    events would execute ahead of the device-side free-lane flush.
    Tenants enter by implant (fleet/admission.py), never by running
    the shell's own boot."""
    import jax.numpy as jnp

    from shadow_tpu.apps import phold
    from shadow_tpu.core import lanes as lanes_mod
    from shadow_tpu.core import simtime
    from shadow_tpu.net.build import HostSpec, build

    cfg, bucket_plan = _resident_cfg(width=width, lanes=lanes,
                                     caps=caps, horizon_ns=horizon_ns,
                                     seed=seed)
    hosts = [HostSpec(name=f"p{i}", proc_start_time=0)
             for i in range(cfg.num_hosts)]
    b = build(cfg, SOAK_GRAPH, hosts)
    b.bucket_plan = bucket_plan
    # load=0: the injector arms nobody (remaining == 0 everywhere) —
    # the shell is an inert vessel with the full PHOLD handler set
    # traced in, so any tenant's implanted chains execute
    b.sim = phold.setup(b.sim, load=0, replica_size=int(width))
    b.sim = lanes_mod.attach(b.sim, int(lanes))
    b.sim = lanes_mod.attach_admission(b.sim)
    if int(flow_sample) > 0:
        from shadow_tpu import telemetry

        b.sim = telemetry.attach_flows(
            b.sim, sample_period=int(flow_sample))
    # flush the boot PROC_STARTs explicitly (host-side, before any
    # dispatch): every lane starts FREE and empty
    b.sim = b.sim.replace(events=b.sim.events.replace(
        time=jnp.full_like(b.sim.events.time, simtime.INVALID)))
    return b


def build_tenant_donor(spec: JobSpec, *, width: int, lanes: int,
                       caps: dict, horizon_ns: int):
    """A tenant's donor build: the SAME shapes as the resident shell
    (same cfg rule, same pow2 buckets) but seeded and loaded as the
    tenant's scenario — `spec.hosts` active hosts occupy each lane's
    prefix (apps/phold.py active_hosts), padding rows idle forever.

    The donor is never dispatched: fleet/admission.py slices ONE lane
    block out of its leaves and implants it into the warm program at
    the join barrier. Building at full H keeps every per-host identity
    plane (rng keys, IPs, lane ids) correct for whichever lane the
    tenant lands in — the donor's lane-r rows ARE lane-r rows."""
    from shadow_tpu.apps import phold
    from shadow_tpu.net.build import HostSpec, build

    active = int(spec.hosts)
    if active > int(width):
        raise ValueError(
            f"tenant {spec.id}: hosts={active} exceeds the resident "
            f"lane width {width}")
    cfg, _ = _resident_cfg(width=width, lanes=lanes, caps=caps,
                           horizon_ns=horizon_ns, seed=spec.seed)
    hosts = [HostSpec(name=f"p{i}", proc_start_time=0)
             for i in range(cfg.num_hosts)]
    b = build(cfg, SOAK_GRAPH, hosts)
    b.sim = phold.setup(
        b.sim, load=int(spec.load), replica_size=int(width),
        active_hosts=active if active < int(width) else None)
    return b


def slo_verdict(spec: JobSpec, flows_blk) -> dict | None:
    """The per-job "slo" result block: compare the run's worst
    per-lane flow p99 against the spec's objective. None when the
    spec carries no SLO or no flow data exists — the lint
    (tools/telemetry_lint.py) cross-checks the verdict against the
    manifest's flow percentiles."""
    if spec.slo_p99_ms is None or not flows_blk:
        return None
    per_lane = flows_blk.get("per_lane") or {}
    p99s = [int(v.get("p99_ns", 0)) for v in per_lane.values()
            if v.get("count")]
    if not p99s:
        return None
    worst = max(p99s)
    objective_ns = int(float(spec.slo_p99_ms) * 1e6)
    return {"objective_p99_ms": float(spec.slo_p99_ms),
            "p99_ns": worst,
            "met": worst <= objective_ns,
            "tenant_class": spec.tenant_class}


def _run_scenario(spec: JobSpec, job_dir: str, *, resume_from,
                  stop, heartbeat, log) -> dict:
    from shadow_tpu import faults, telemetry
    from shadow_tpu.apps import phold
    from shadow_tpu.compile import specialize as specialize_mod
    from shadow_tpu.utils import checkpoint as ckpt

    caps = {"event_capacity": spec.event_capacity,
            "outbox_capacity": spec.outbox_capacity,
            "router_ring": spec.router_ring}
    if resume_from:
        # a post-escalation snapshot is larger than the spec says;
        # its recorded capacities size the rebuild (same rule as the
        # CLI's --resume)
        meta = ckpt.peek_meta(resume_from)
        for k, v in (meta.get("capacities") or {}).items():
            if k in caps:
                caps[k] = max(caps[k], int(v))

    built = {"b": None}   # last-built bundle: cfg/plan for the manifest

    def make_bundle():
        built["b"] = _build_scenario(spec, caps)
        return built["b"]

    def rebuild(overrides):
        caps.update(overrides)
        return make_bundle()

    prefix = os.path.join(job_dir, "ck")
    hb_state = {"last": 0.0}

    def on_round(sim, wstats, wstart, wend, next_min):
        if spec.round_sleep_s:
            time.sleep(spec.round_sleep_s)
        now = time.monotonic()
        if heartbeat is not None and now - hb_state["last"] >= 0.05:
            hb_state["last"] = now
            heartbeat({"wstart": int(wstart),
                       "checkpoint": ckpt.latest_checkpoint(prefix)})

    # a fresh Feeder per attempt is correct even on resume: the window
    # loop syncs it to the snapshot's trace cursor before the first
    # refill, so a requeued job replays nothing and drops nothing
    feeder = None
    if spec.inject_trace:
        from shadow_tpu.inject import Feeder

        feeder = Feeder(spec.inject_trace)

    # flow/causality tracing needs a harvester so checkpoint-time
    # drains keep ring loss bounded (telemetry/harvest.py drains
    # flows + lineage + windows through the same choke point)
    harvester = (telemetry.Harvester()
                 if int(getattr(spec, "flow_sample", 0) or 0) > 0
                 or int(getattr(spec, "causality_sample", 0) or 0) > 0
                 else None)

    # Elastic degraded-mesh execution (parallel/elastic.py): the
    # worker leases an explicit device set of the spec's width (a
    # degraded requeue arrives with `shards` already shrunk by the
    # fleet) and arms the in-run degradation ladder — device loss
    # retries, then shrinks to survivors, then falls serial, resuming
    # each rung from the last verified checkpoint.
    mesh = None
    elastic_policy = None
    device_lease = None
    want = max(1, int(getattr(spec, "shards", 1)))
    if want > 1 or getattr(spec, "sentinel", False):
        from shadow_tpu.parallel import elastic as elastic_mod
        elastic_policy = elastic_mod.ElasticPolicy()
    if want > 1:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        avail = jax.devices()
        n = min(want, elastic_mod.next_pow2_down(len(avail)))
        if n > 1:
            leased = avail[:n]
            mesh = Mesh(np.array(leased), ("hosts",))
            device_lease = {"requested": want, "leased": n,
                            "devices": [str(d) for d in leased]}
        else:
            device_lease = {"requested": want, "leased": 1,
                            "devices": [str(avail[0])] if avail else []}

    t0 = time.monotonic()
    res = faults.run_supervised(
        make_bundle(), app_handlers=(phold.handler,),
        checkpoint_path=prefix,
        checkpoint_every_windows=spec.checkpoint_every_windows,
        max_retries=spec.max_retries,
        escalation=(faults.EscalationPolicy(max_grow=spec.max_grow)
                    if spec.auto_grow else None),
        rebuild=rebuild, stop=stop, resume_from=resume_from,
        max_run_wallclock=spec.max_wallclock_s,
        on_round=on_round, log=log, sleep=lambda s: None,
        feeder=feeder, harvester=harvester,
        mesh=mesh, elastic=elastic_policy,
        # fleets live on repeated shapes: serve dispatch programs from
        # the persistent AOT store by default (compile/serve.py;
        # SHADOW_WARM_PROGRAMS=0 / SHADOW_NO_COMPILE_CACHE opt out)
        warm_start=True)
    wall_s = time.monotonic() - t0

    result = {
        "ok": bool(res.ok),
        "preempted": bool(res.preempted),
        "deadline": bool(res.deadline_exceeded),
        # wallclock of this attempt only (a requeued continuation
        # reports its own) — feeds the sweep reducer's events_per_sec
        # objective, the one deliberately machine-dependent metric
        "wall_s": round(wall_s, 3),
        "run_id": res.run_id,
        "resume_of": res.resume_of,
        "supervisor_attempts": res.attempts,
        "escalation_restarts": res.escalation_restarts,
        "final_capacities": dict(caps),
        "checkpoint": res.final_checkpoint,
    }
    elastic_blk = getattr(res, "elastic", None)
    final_shards = (int(elastic_blk["final_shards"])
                    if elastic_blk else
                    (mesh.shape["hosts"] if mesh is not None else 1))
    if device_lease is not None:
        device_lease["final_shards"] = final_shards
        result["device_lease"] = device_lease
    if elastic_blk is not None:
        result["elastic"] = elastic_blk
    hl = getattr(res, "health", None)
    if (not res.ok and hl is not None
            and int(getattr(hl, "device_lost", 0) or 0) > 0):
        # the in-run ladder exhausted on device loss: hand the fleet a
        # degraded-requeue verdict — next-pow2-down width, same attempt
        # (runner._fold_result routes this through queue.device_lost)
        if final_shards > 1:
            nxt = max(1, final_shards // 2)
            result["device_lost"] = {
                "lost_shard": int(getattr(hl, "lost_shard", -1) or -1),
                "new_shards": nxt,
                "cause": str(getattr(hl, "device_lost_cause", "")
                             or "device lost"),
            }
    incidents = tuple(getattr(res, "lane_incidents", ()) or ())
    if incidents:
        # packed job: each quarantined lane becomes a standalone
        # replicas=1 requeue spec at the regrown capacities its trip
        # bits name — the runner backfills these into the queue
        requeues = []
        for inc in incidents:
            child = spec.as_dict()
            child.update({"id": f"{spec.id}.lane{inc.lane}",
                          "replicas": 1, "lane_of": spec.id})
            for knob, val in (inc.regrow or {}).items():
                child[knob] = max(int(child.get(knob) or 0), int(val))
            requeues.append(child)
        result["lanes"] = {
            "replicas": int(getattr(spec, "replicas", 1)),
            "quarantined": [int(i.lane) for i in incidents],
            "incidents": [i.as_dict() for i in incidents],
            "requeues": requeues,
        }
    if res.sim is not None:
        bundle = built["b"]
        from shadow_tpu import inject as inject_mod
        from shadow_tpu.telemetry.export import lanes_manifest_block
        from shadow_tpu.telemetry.flows import flows_manifest_block

        cinfo = dict(res.compile_info or {})
        plan = getattr(bundle, "bucket_plan", None)
        if plan is not None:
            cinfo["buckets"] = plan.as_dict()
        result["program_key"] = cinfo.get("key")
        flows_blk = None
        caus_blk = None
        if harvester is not None:
            harvester.drain(res.sim)
            flows_blk = flows_manifest_block(
                harvester, num_hosts=bundle.cfg.num_hosts, shards=1,
                sample_period=int(spec.flow_sample))
            from shadow_tpu.telemetry.causality import \
                causality_manifest_block

            caus_blk = causality_manifest_block(
                harvester, num_hosts=bundle.cfg.num_hosts, shards=1,
                sample_period=int(getattr(spec, "causality_sample", 0)
                                 or 0) or None)
        man = telemetry.run_manifest(
            cfg=bundle.cfg, seed=spec.seed, shards=final_shards,
            sim=res.sim,
            stats=res.stats, health=res.health,
            fault_plan=bundle.fault_plan,
            elastic=elastic_blk,
            run_id=res.run_id, resume_of=res.resume_of,
            escalations=res.escalations,
            preempted=res.preempted or None,
            injection=inject_mod.manifest_block(res.sim, feeder),
            lanes=lanes_manifest_block(res.health, incidents),
            flows=flows_blk,
            causality=caus_blk,
            compile_info=cinfo or None,
            specialization=specialize_mod.specialization_block(
                getattr(bundle, "caps", None), res.sim,
                mode=getattr(spec, "specialize", "auto")))
        result["manifest"] = telemetry.write_manifest(
            os.path.join(job_dir, "run_manifest.json"), man)
        result["counters"] = man["counters"]
        # roll-up copies the sweep reducer (sweep/reduce.py) ranks on:
        # the health verdict gates eligibility, events/wallclock is
        # the throughput objective
        result["health_verdict"] = (man.get("health") or {}).get(
            "verdict")
        ev = (man["counters"] or {}).get("events_processed")
        if ev is not None and wall_s > 0:
            result["events_per_sec"] = round(int(ev) / wall_s, 3)
        if flows_blk is not None:
            # the roll-up copy: histogram keys stay in the job
            # manifest; the fleet manifest only needs the summaries
            result["flows"] = {
                k: flows_blk[k] for k in
                ("sample_period", "sampled", "recorded", "harvested",
                 "lost_ring", "lost_window_clamp", "per_lane")
                if k in flows_blk}
        if caus_blk is not None:
            # roll-up copy: the chains and traffic matrix stay in the
            # job manifest; the fleet manifest folds the accounting
            # and the binding-cause histogram fleet-wide
            result["causality"] = {
                k: caus_blk[k] for k in
                ("sample_period", "sampled", "harvested", "lost_ring",
                 "windows_attributed", "windows_lost", "causes")
                if k in caus_blk}
        # the same spec file serves resident and per-process execution:
        # a standalone run of a tenant spec still records its SLO
        # verdict (the admission gate is the resident-path consumer)
        verdict = slo_verdict(spec, flows_blk)
        if verdict is not None:
            result["slo"] = verdict
        if res.ok:
            result["digest"] = sim_digest(res.sim)
    if not res.ok and not res.preempted:
        result["failure"] = res.failure_report()
    return result


def _run_chaos_trial(spec: JobSpec, job_dir: str, *, heartbeat,
                     log) -> dict:
    """One tools/chaos_soak.py trial (the --jobs dogfood path). The
    trial owns its own kill/heal machinery; the fleet only provides
    the workdir, the lease, and the salvage."""
    import importlib.util
    import pathlib

    tools = pathlib.Path(__file__).resolve().parents[2] / "tools"
    mod_spec = importlib.util.spec_from_file_location(
        "chaos_soak", tools / "chaos_soak.py")
    chaos = importlib.util.module_from_spec(mod_spec)
    mod_spec.loader.exec_module(chaos)
    if heartbeat is not None:
        heartbeat({"wstart": 0, "checkpoint": None})
    rep = chaos.run_trial(
        spec.seed, hosts=spec.hosts, load=spec.load,
        sim_s=spec.sim_s, kills=spec.kills, max_grow=spec.max_grow,
        workdir=job_dir, verify=spec.verify, log=log)
    # the trial's product is its report, pass or fail: a trial that
    # RAN is a done job (retrying a deterministic verdict would just
    # reproduce it); only an exception is a job failure
    return {"ok": True, "trial_ok": bool(rep["ok"]), "report": rep,
            "preempted": False, "deadline": False}


def run_job(spec: JobSpec, job_dir: str, *,
            resume_from: Optional[str] = None, stop=None,
            heartbeat=None, log=None) -> dict:
    """Execute one job attempt (or continuation). Always leaves
    `result.json` in the job dir — the crash-safe copy the supervisor
    salvages if the worker's pipe dies with the worker."""
    os.makedirs(job_dir, exist_ok=True)
    try:
        if spec.kind == "chaos_trial":
            result = _run_chaos_trial(spec, job_dir,
                                      heartbeat=heartbeat, log=log)
        else:
            result = _run_scenario(spec, job_dir,
                                   resume_from=resume_from, stop=stop,
                                   heartbeat=heartbeat, log=log)
    except Exception as e:  # noqa: BLE001 — worker must not die on a job
        result = {"ok": False, "preempted": False, "deadline": False,
                  "error": f"{type(e).__name__}: {e}"}
    _write_json(os.path.join(job_dir, "result.json"), result)
    return result
