"""Job specs and the jobs file (docs/8-fleet.md §jobs file).

A fleet executes heterogeneous *scenarios*: each job is a
(config x seed x fault plan) declaration plus its own robustness
budget (attempts, wallclock deadline, escalation policy). The jobs
file is JSON:

    {
      "fleet": {            # defaults, all optional
        "max_attempts": 3, "lease_timeout_s": 60.0,
        "backoff_base_s": 0.25, "backoff_cap_s": 30.0,
        "backoff_seed": 1, "requeue_budget": 8
      },
      "jobs": [
        {"id": "sweep-00", "kind": "scenario", "seed": 3,
         "hosts": 8, "load": 2, "sim_s": 1,
         "event_capacity": 32, "outbox_capacity": 32,
         "router_ring": 32,
         "faults": [{"time_s": 0.3, "kind": "loss",
                     "a": 0, "b": 0, "value": 0.02}],
         "auto_grow": true, "max_grow": 8,
         "max_attempts": 3, "max_wallclock_s": 300.0},
        ...
      ]
    }

Kinds:
- "scenario": a seeded PHOLD run on the single-vertex soak topology
  (the chaos-soak scenario surface) under the self-healing supervisor
  — undersized capacities + auto_grow exercise escalation; undersized
  capacities withOUT auto_grow fail deterministically (the quarantine
  path's test vector).
- "chaos_trial": one tools/chaos_soak.py run_trial, parameterized by
  the same knobs the soak CLI takes (chaos_soak --jobs dogfoods the
  fleet through this kind).

Every enqueued job gets a spec dir `jobs/<id>/` holding `spec.json`
(the durable copy — `fleet run --resume` reloads specs from these,
so the jobs file is not needed to resume), its supervisor
checkpoints, its run manifest, and its result.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Any, Optional

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


@dataclasses.dataclass
class FleetPolicy:
    """Fleet-wide defaults a job spec may override (attempts,
    deadline). Backoff is deterministic: delay for (job, attempt) is
    base * 2^(attempt-1) * (1 + jitter) with jitter drawn from a
    counter RNG seeded by (backoff_seed, job id, attempt) — two runs
    of the same fleet produce the same backoff schedule, so fleet
    logs are reproducible."""

    max_attempts: int = 3
    # heartbeats only flow once the engine is stepping rounds, so the
    # lease timeout must cover a cold XLA compile of the window program
    lease_timeout_s: float = 60.0
    backoff_base_s: float = 0.25
    backoff_cap_s: float = 30.0
    backoff_seed: int = 1
    requeue_budget: int = 8        # worker-loss requeues before parking
    deadline_grace: float = 1.5    # watchdog kills at deadline * grace

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FleetPolicy":
        known = {f.name for f in dataclasses.fields(cls)}
        bad = sorted(set(d) - known)
        if bad:
            raise ValueError(f"unknown fleet policy key(s): {bad}")
        return cls(**d)


@dataclasses.dataclass
class JobSpec:
    id: str
    kind: str = "scenario"         # "scenario" | "chaos_trial"
    seed: int = 1
    # scenario shape (chaos_soak's PHOLD surface)
    hosts: int = 8
    load: int = 2
    sim_s: int = 1
    event_capacity: int = 32
    outbox_capacity: int = 32
    router_ring: int = 32
    faults: tuple = ()             # JSON fault records (plan.py schema)
    # per-job robustness budget
    auto_grow: bool = True
    max_grow: int = 8
    max_retries: int = 0           # in-run supervisor retries; the
    # fleet owns the retry budget, so in-run retries default off
    checkpoint_every_windows: int = 8
    max_attempts: Optional[int] = None      # None = fleet default
    max_wallclock_s: Optional[float] = None  # per-job deadline
    # open-system injection (shadow_tpu/inject/): a trace file the
    # job streams into the scenario. The staging buffer's lane count
    # sizes from the trace unless pinned; resume-after-kill continues
    # the trace from the checkpoint's cursor without replay (the
    # feeder syncs to the snapshot), so injected jobs keep the fleet's
    # bit-identity contract.
    inject_trace: Optional[str] = None
    inject_lanes: Optional[int] = None
    # Packed ensemble job (docs/8-fleet.md §packed jobs): replicas > 1
    # runs R copies of the scenario in ONE compiled program — `hosts`
    # is the per-lane host count, the program carries hosts*replicas
    # rows — with lane-isolated health (core/lanes.py) attached. A
    # lane that trips is quarantined on device (healthy lanes finish
    # bit-identically), salvaged from the last clean checkpoint, and
    # requeued by the fleet as a standalone replicas=1 job at regrown
    # capacities; `lane_of` records that provenance on the child.
    replicas: int = 1
    lane_of: Optional[str] = None  # parent packed-job id (requeues)
    # per-flow latency tracing (telemetry/flows.py): sample 1-in-N
    # cross-host packets into the device flow ring; 0 = off. The job
    # manifest grows a "flows" block and the fleet manifest rolls the
    # per-lane latency summaries up per tenant.
    flow_sample: int = 0
    # causal critical-path profiling (telemetry/causality.py): sample
    # 1-in-N emitted events into the lineage recorder and latch which
    # clamp decided every window end; 0 = off. The job manifest grows
    # a "causality" block (critical chains, binding-cause histogram)
    # and the fleet manifest rolls the cause counts up fleet-wide.
    causality_sample: int = 0
    # Tenant lease terms (fleet/admission.py, resident programs):
    # `tenant_class` ranks the job for SLO-aware shedding —
    # "protected" tenants are never evicted by the admission gate and
    # their SLO breaches drive the degradation ladder; "best_effort"
    # tenants are the shedding pool. `slo_p99_ms` is the per-flow p99
    # latency objective (telemetry/flows.py per-lane percentiles feed
    # the gate); None = no SLO. Both also annotate standalone runs'
    # results (scenario.py records an "slo" verdict), so the same
    # spec file serves resident and per-process execution.
    tenant_class: str = "best_effort"
    slo_p99_ms: Optional[float] = None
    # compile-time program specialization (compile/specialize.py):
    # "auto" trims capabilities the build proves statically dead
    # (reliability loss draws, the timer handler family) out of the
    # traced program; the trimmed variant keys separately in the warm
    # AOT store, so a fleet of lossless jobs serves the lean program
    # while faulted jobs serve the full one. "off" always runs the
    # full program.
    specialize: str = "auto"
    # Elastic degraded-mesh execution (parallel/elastic.py): shards > 1
    # runs the scenario shard_map'd over that many devices — the worker
    # leases an explicit device set of this width, and a DEVICE_LOST
    # requeue re-enqueues the job at the next-pow2-down width (a
    # continuation, not a new attempt: checkpoints hold global layout,
    # so the shrunk mesh resumes the same run). `sentinel` attaches the
    # cross-shard integrity sentinel so checkpoints carry the
    # verified-state ledger and silent divergence latches as
    # SHARD_DIVERGENCE instead of corrupting results.
    shards: int = 1
    sentinel: bool = False
    # chaos_trial knobs (chaos_soak.run_trial)
    kills: int = 2
    verify: bool = False
    # test/chaos lever: sleep this long at every round barrier —
    # stretches a run's wallclock without touching its simulation
    # (worker-loss and deadline tests need a window to land a kill in)
    round_sleep_s: float = 0.0

    def __post_init__(self):
        if not _ID_RE.match(self.id):
            raise ValueError(
                f"job id {self.id!r} must match {_ID_RE.pattern} "
                f"(it names a directory)")
        if self.kind not in ("scenario", "chaos_trial"):
            raise ValueError(f"job {self.id}: unknown kind "
                             f"{self.kind!r}")
        self.faults = tuple(
            f if isinstance(f, dict) else dict(f) for f in self.faults)
        if self.inject_trace is not None and self.kind != "scenario":
            raise ValueError(f"job {self.id}: inject_trace only "
                             f"applies to kind 'scenario'")
        if int(self.replicas) < 1:
            raise ValueError(f"job {self.id}: replicas must be >= 1")
        if self.replicas > 1 and self.kind != "scenario":
            raise ValueError(f"job {self.id}: packed jobs (replicas > "
                             f"1) only apply to kind 'scenario'")
        if self.replicas > 1 and self.inject_trace is not None:
            raise ValueError(
                f"job {self.id}: inject_trace addresses a single "
                f"scenario's host ids — packed jobs can't stream it")
        if self.inject_lanes is not None:
            n = int(self.inject_lanes)
            if n <= 0 or n & (n - 1):
                raise ValueError(f"job {self.id}: inject_lanes must "
                                 f"be a positive power of two")
        if int(self.flow_sample) < 0:
            raise ValueError(f"job {self.id}: flow_sample must be "
                             f">= 0 (0 disables flow tracing)")
        if int(self.causality_sample) < 0:
            raise ValueError(f"job {self.id}: causality_sample must "
                             f"be >= 0 (0 disables causality tracing)")
        if self.specialize not in ("auto", "off"):
            raise ValueError(
                f"job {self.id}: specialize must be 'auto' or 'off', "
                f"got {self.specialize!r}")
        if self.tenant_class not in ("protected", "best_effort"):
            raise ValueError(
                f"job {self.id}: tenant_class must be 'protected' or "
                f"'best_effort', got {self.tenant_class!r}")
        if self.slo_p99_ms is not None and float(self.slo_p99_ms) <= 0:
            raise ValueError(f"job {self.id}: slo_p99_ms must be > 0 "
                             f"(None disables the SLO)")
        n = int(self.shards)
        if n < 1 or n & (n - 1):
            raise ValueError(f"job {self.id}: shards must be a "
                             f"positive power of two, got {self.shards}")
        if n > 1 and self.kind != "scenario":
            raise ValueError(f"job {self.id}: shards > 1 only applies "
                             f"to kind 'scenario'")
        if n > 1 and (self.hosts * max(int(self.replicas), 1)) % n:
            raise ValueError(
                f"job {self.id}: total host rows "
                f"({self.hosts}x{self.replicas}) must divide by "
                f"shards ({n})")

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["faults"] = list(self.faults)
        return d

    def digest(self) -> str:
        blob = json.dumps(self.as_dict(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        bad = sorted(set(d) - known)
        if bad:
            raise ValueError(f"job {d.get('id', '?')}: unknown "
                             f"key(s): {bad}")
        return cls(**d)


def parse_jobs_obj(obj: Any) -> tuple[FleetPolicy, list]:
    """Parse a loaded jobs-file object -> (policy, [JobSpec])."""
    if not isinstance(obj, dict) or "jobs" not in obj:
        raise ValueError('jobs file must be an object with a "jobs" '
                         'array')
    policy = FleetPolicy.from_dict(obj.get("fleet", {}) or {})
    jobs = [JobSpec.from_dict(j) for j in obj["jobs"]]
    if not jobs:
        raise ValueError("jobs file declares zero jobs")
    seen = set()
    for j in jobs:
        if j.id in seen:
            raise ValueError(f"duplicate job id {j.id!r}")
        seen.add(j.id)
    return policy, jobs


def load_jobs_file(path: str) -> tuple[FleetPolicy, list]:
    with open(path) as f:
        return parse_jobs_obj(json.load(f))
