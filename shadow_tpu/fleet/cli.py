"""`shadow-tpu fleet` — run and inspect scenario fleets.

    shadow-tpu fleet run --jobs-file sweep.json --fleet-dir out/ \
        --workers 4
    shadow-tpu fleet run --fleet-dir out/ --resume
    shadow-tpu fleet status --fleet-dir out/

Exit codes (docs/8-fleet.md §exit codes):
  0  fleet complete; every job done (quarantined jobs are parked
     with their salvage, which is success in salvage mode)
  1  unsalvaged failures (a non-retryable job, or any quarantine
     under --no-salvage)
  2  usage error
  5  preempted (SIGTERM): in-flight jobs checkpointed and requeued;
     rerun with --resume
  6  stalled: jobs remain but every worker (and the respawn budget)
     is gone
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from shadow_tpu.fleet.spec import FleetPolicy, load_jobs_file

_POLICY_FILE = "fleet_policy.json"


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shadow-tpu fleet",
        description="fault-tolerant scenario-fleet runner")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="execute a fleet of jobs")
    r.add_argument("--jobs-file",
                   help="JSON jobs file (optional with --resume: "
                        "specs reload from the fleet dir)")
    r.add_argument("--fleet-dir", required=True,
                   help="durable fleet state: journal, job dirs, "
                        "fleet_manifest.json")
    r.add_argument("--workers", type=int, default=2)
    r.add_argument("--resume", action="store_true",
                   help="replay the journal; completed jobs are not "
                        "re-run")
    r.add_argument("--no-salvage", action="store_true",
                   help="treat quarantined jobs as fleet failure "
                        "(exit 1) instead of parked successes")
    r.add_argument("--drain-timeout", type=float, default=60.0,
                   help="seconds to wait for preempted jobs to "
                        "checkpoint on SIGTERM")
    r.add_argument("--no-fsync", action="store_true",
                   help="skip journal fsyncs (tests only; forfeits "
                        "power-loss durability)")
    r.add_argument("--resident", action="store_true",
                   help="continuous lane admission: run every job as "
                        "a tenant lease of ONE resident packed "
                        "program (fleet/admission.py) instead of one "
                        "worker process per job; joins/leaves happen "
                        "at window barriers with zero retraces")
    r.add_argument("--resident-lanes", type=int, default=None,
                   help="lane count of the resident program "
                        "(default: max(2, number of jobs))")
    r.add_argument("--resident-horizon-s", type=int, default=None,
                   help="simulated horizon of the resident program "
                        "in seconds (default: sized from the jobs)")
    r.add_argument("--slo-sustained", type=int, default=2,
                   help="consecutive breached SLO evaluations before "
                        "the admission gate acts")
    r.add_argument("--slo-stride", type=int, default=1,
                   help="evaluate per-lane flow p99s every Nth "
                        "barrier (the degradation ladder raises this "
                        "host-side stride as its first relief step)")
    r.add_argument("--flow-sample", type=int, default=1,
                   help="resident flow-sampling period feeding the "
                        "SLO gate (0 disables the gate's p99 input)")

    s = sub.add_parser("status", help="summarize a fleet dir "
                                      "(read-only)")
    s.add_argument("--fleet-dir", required=True)
    return p


def _cmd_run(args) -> int:
    from shadow_tpu.fleet.runner import FleetRunner

    policy_path = os.path.join(args.fleet_dir, _POLICY_FILE)
    specs = None
    if args.jobs_file:
        policy, specs = load_jobs_file(args.jobs_file)
    elif args.resume and os.path.isfile(policy_path):
        with open(policy_path) as f:
            policy = FleetPolicy.from_dict(json.load(f))
    elif args.resume:
        policy = FleetPolicy()
    else:
        print("error: fleet run needs --jobs-file (or --resume "
              "with an existing fleet dir)", file=sys.stderr)
        return 2
    os.makedirs(args.fleet_dir, exist_ok=True)
    tmp = policy_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(policy.as_dict(), f, indent=1, sort_keys=True)
    os.replace(tmp, policy_path)

    if args.resident:
        from shadow_tpu.fleet.admission import (
            AdmissionGate,
            run_resident_fleet,
        )

        man = run_resident_fleet(
            args.fleet_dir, policy, specs,
            lanes=args.resident_lanes,
            horizon_s=args.resident_horizon_s,
            resume=args.resume, fsync=not args.no_fsync,
            gate=AdmissionGate(sustained=args.slo_sustained,
                               eval_stride=args.slo_stride),
            flow_sample=args.flow_sample,
            log=lambda m: print(m, file=sys.stderr))
        counts = man["counts"]
        bad = counts.get("failed", 0) + (
            counts.get("quarantined", 0) if args.no_salvage else 0)
        rc = 1 if bad else (0 if man["complete"] else 6)
        print(json.dumps({"exit": rc, "counts": counts,
                          "admission": {
                              k: man["admission"][k] for k in
                              ("admitted", "completed", "evicted",
                               "quarantined", "resident", "deferred",
                               "program_key_stable")},
                          "manifest": os.path.join(
                              args.fleet_dir, "fleet_manifest.json")}))
        return rc
    runner = FleetRunner(
        args.fleet_dir, policy, specs, workers=args.workers,
        resume=args.resume, fsync=not args.no_fsync,
        salvage=not args.no_salvage,
        drain_timeout_s=args.drain_timeout,
        log=lambda m: print(m, file=sys.stderr))
    rc = runner.run(install_signals=True)
    man_path = os.path.join(args.fleet_dir, "fleet_manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    print(json.dumps({"exit": rc, "counts": man["counts"],
                      "preempted": man["preempted"],
                      "stalled": man["stalled"],
                      "manifest": man_path}))
    return rc


def fold_job_status(records) -> tuple[dict, dict]:
    """Pure fold of replayed journal frames -> (job status map,
    checkpoint map). Shared by `fleet status` and the sweep status
    paths (sweep/cli.py), which join these statuses against the
    sweep journal's rounds."""
    status: dict = {}
    checkpoints: dict = {}
    for rec in records:
        job = rec.get("job")
        ev = rec.get("ev")
        if not job:
            continue
        if ev in ("job_added",):
            status.setdefault(job, "queued")
        elif ev in ("leased", "running"):
            status[job] = "leased" if ev == "leased" else "running"
        elif ev == "done":
            status[job] = "done"
        elif ev == "failed":
            status[job] = "failed" if rec.get("final") else "queued"
        elif ev == "requeued":
            status[job] = "queued"
        elif ev == "quarantined":
            status[job] = "quarantined"
        if ev == "heartbeat" and rec.get("checkpoint"):
            checkpoints[job] = rec["checkpoint"]
    return status, checkpoints


def _cmd_status(args) -> int:
    """Read-only: never touches the journal (a live fleet owns it)."""
    from shadow_tpu.fleet import journal as journal_mod

    jpath = os.path.join(args.fleet_dir, "journal.log")
    records, good = journal_mod.replay(jpath)
    status, checkpoints = fold_job_status(records)
    counts: dict = {}
    for st in status.values():
        counts[st] = counts.get(st, 0) + 1
    out = {"journal_events": len(records), "journal_bytes": good,
           "counts": counts, "jobs": status,
           "checkpoints": checkpoints}
    lease_log = os.path.join(args.fleet_dir, "resident", "leases.log")
    if os.path.isfile(lease_log):
        # resident fleet: fold the lease journal read-only
        # (fleet/admission.py LeaseTable shares this replay)
        lrecs, _ = journal_mod.replay(lease_log)
        pop: dict = {}
        for rec in lrecs:
            if rec.get("ev") != "lease":
                continue
            lane, st = rec.get("lane"), rec.get("state")
            if st in ("admitted", "running"):
                pop[lane] = {"job": rec.get("job"), "state": st,
                             "epoch": rec.get("epoch")}
            else:
                pop.pop(lane, None)
        out["resident"] = {"lease_frames": len(lrecs),
                           "population": {str(k): v for k, v
                                          in sorted(pop.items())}}
    sweep_log = os.path.join(args.fleet_dir, "sweep.log")
    if os.path.isfile(sweep_log):
        # this fleet dir is a sweep's execution substrate: fold the
        # sweep journal read-only into per-round progress (points
        # done / failed / pruned per round) instead of leaving only
        # the flat job counts above (sweep/driver.py shares the fold)
        from shadow_tpu.sweep import driver as sweep_driver

        frames, _ = journal_mod.replay(sweep_log)
        if frames:
            try:
                out["sweep"] = sweep_driver.fold_sweep_status(
                    frames, status)
            except Exception as e:  # noqa: BLE001 — status stays up
                out["sweep"] = {"error": f"{type(e).__name__}: {e}"}
    man_path = os.path.join(args.fleet_dir, "fleet_manifest.json")
    if os.path.isfile(man_path):
        out["manifest"] = man_path
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.cmd == "run":
        return _cmd_run(args)
    return _cmd_status(args)


if __name__ == "__main__":
    sys.exit(main())
