"""The fleet supervisor: worker pool + scheduler + watchdog.

One process owns the journal (single writer) and the pool of
spawn-context worker processes; everything else is message folding:

    dispatch: ready job + idle worker -> lease frame -> pipe send
    fold:     running / heartbeat / result messages -> state frames
    watchdog: missed heartbeats past the lease timeout, or a job
              past its wallclock deadline * grace (the supervisor's
              own in-run deadline should fire first — the watchdog
              is the backstop for a hung device call that never
              reaches a round barrier) -> SIGKILL -> worker_lost
    reap:     dead worker processes (killed by us, the OOM killer,
              or a test) -> worker_lost -> requeue from checkpoint

Graceful degradation: a lost worker shrinks the pool and its job is
requeued onto the survivors; only when the pool hits zero with work
remaining does the runner respawn a fresh worker (bounded — a
machine that eats every worker ends the fleet `stalled`, exit 6,
rather than looping forever).

Preemption (SIGTERM / stop()): dispatch halts, every worker gets
SIGTERM, each in-flight supervised run takes its preemption-style
final snapshot (PR 5 machinery) and reports a `preempted` result;
the runner journals those checkpoints as requeue frames, writes the
fleet manifest with `"preempted": true`, and exits 5. `fleet run
--resume` replays the journal and re-runs nothing that finished.

Exit codes: 0 fleet complete (salvage mode: quarantined jobs are
parked-with-artifacts, not failures) / 1 unsalvaged failures /
5 preempted / 6 stalled.
"""

from __future__ import annotations

import multiprocessing as mp
import multiprocessing.connection as mpc
import os
import signal
import time
from typing import Optional

from shadow_tpu.fleet import manifest as manifest_mod
from shadow_tpu.fleet import state as state_mod
from shadow_tpu.fleet.spec import FleetPolicy
from shadow_tpu.fleet.state import FleetQueue

_FATAL_ERRORS = ("ValueError", "TypeError", "KeyError",
                 "FileNotFoundError", "AssertionError")

EXIT_OK = 0
EXIT_FAILURES = 1
EXIT_PREEMPTED = 5
EXIT_STALLED = 6


def _is_fatal(result: dict) -> bool:
    """Deterministic spec/build-level errors re-raise identically on
    retry; burn no attempts on them."""
    err = result.get("error") or ""
    return any(err.startswith(t + ":") for t in _FATAL_ERRORS)


class FleetRunner:
    def __init__(self, fleet_dir: str, policy: FleetPolicy,
                 specs=None, *, workers: int = 2,
                 resume: bool = False, fsync: bool = True,
                 salvage: bool = True, drain_timeout_s: float = 60.0,
                 respawn_budget: int = 4, on_event=None, log=None,
                 now=time.time):
        os.makedirs(fleet_dir, exist_ok=True)
        self.fleet_dir = fleet_dir
        self.policy = policy
        self.queue = FleetQueue(fleet_dir, policy, specs,
                                resume=resume, fsync=fsync, now=now)
        self.now = now
        self.salvage = salvage
        self.drain_timeout_s = drain_timeout_s
        self.on_event = on_event
        self.log = log or (lambda m: None)
        self.workers: dict[str, dict] = {}
        self._ctx = mp.get_context("spawn")
        self._nworkers = max(1, workers)
        self._next_wid = 0
        self._respawns_left = respawn_budget
        self._stop = False
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._stalled = False
        self._hb_journaled: dict[str, float] = {}
        # worker id -> affinity key of its last-leased job: the
        # bucket-affinity pairing state (fleet/affinity.py). In-memory
        # only — after a runner restart every worker process is new,
        # so stale affinity would be wrong anyway.
        self._worker_last_key: dict[str, str] = {}
        # sweep integration (sweep/driver.py): a callable(queue) ->
        # dict producing the manifest's "sweep" roll-up block, so
        # every terminal-transition rewrite carries current sweep
        # progress — a fleet killed mid-sweep leaves an accurate one
        self.sweep_block_fn = None

    # -- events -------------------------------------------------------
    def _emit(self, ev: str, **payload) -> None:
        self.log(f"fleet: {ev} "
                 + " ".join(f"{k}={v}" for k, v in payload.items()))
        if self.on_event is not None:
            self.on_event(self, {"ev": ev, **payload})

    # -- pool ---------------------------------------------------------
    def _spawn_worker(self) -> str:
        from shadow_tpu.fleet.worker import _entry

        wid = f"w{self._next_wid}"
        self._next_wid += 1
        parent, child = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_entry, args=(wid, self.fleet_dir, child),
            name=f"fleet-{wid}", daemon=True)
        proc.start()
        child.close()
        self.workers[wid] = {"proc": proc, "conn": parent,
                             "job": None, "attempt": 0}
        self._emit("worker_spawned", worker=wid, pid=proc.pid)
        return wid

    def worker_pid(self, wid: str) -> Optional[int]:
        w = self.workers.get(wid)
        return w["proc"].pid if w else None

    def _busy(self):
        return [wid for wid, w in self.workers.items() if w["job"]]

    def _drop_worker(self, wid: str, reason: str, *,
                     kill: bool = False) -> None:
        """Remove a worker from the pool; requeue whatever it held."""
        w = self.workers.pop(wid, None)
        self._worker_last_key.pop(wid, None)
        if w is None:
            return
        if kill and w["proc"].is_alive():
            w["proc"].kill()
        w["proc"].join(timeout=10)
        try:
            w["conn"].close()
        except OSError:
            pass
        job = w["job"]
        if job is not None:
            st = self.queue.worker_lost(wid, job, reason)
            self._emit("worker_lost", worker=wid, job=job,
                       reason=reason, job_status=st)
            if self.queue.jobs[job].terminal:
                self.write_manifest()
        else:
            self._emit("worker_exit", worker=wid, reason=reason)

    # -- scheduling ---------------------------------------------------
    def _dispatch(self, now: float) -> None:
        if self._draining:
            return
        from shadow_tpu.fleet import affinity

        idle = [wid for wid, w in self.workers.items()
                if w["job"] is None and w["proc"].is_alive()]
        # bucket-affinity pairing (fleet/affinity.py): a worker that
        # just ran a job takes the first ready job sharing its program
        # key — the compiled program is still warm in that process —
        # while everything else keeps plain FIFO order
        pairs = affinity.assign(
            self.queue.ready(now), idle, self._worker_last_key,
            key_of=lambda j: affinity.affinity_key(j.spec))
        for wid, j in pairs:
            rec = self.queue.lease(j.spec.id, wid)
            self._worker_last_key[wid] = affinity.affinity_key(j.spec)
            w = self.workers[wid]
            w["job"] = j.spec.id
            w["attempt"] = rec["attempt"]
            self._hb_journaled[j.spec.id] = now
            # a device-loss requeue re-leases at the degraded width:
            # the dispatched spec carries the shrunk shard count while
            # the durable spec dir keeps the original ask
            spec_d = j.spec.as_dict()
            if j.shards_override:
                spec_d["shards"] = int(j.shards_override)
            try:
                w["conn"].send(("job", spec_d,
                                self.queue.job_dir(j.spec.id),
                                j.resume_from, rec["attempt"]))
            except (BrokenPipeError, OSError):
                self._drop_worker(wid, "pipe closed on dispatch")
                continue
            self._emit("leased", job=j.spec.id, worker=wid,
                       attempt=rec["attempt"],
                       resume_from=rec["resume_from"])

    def _fold(self, wid: str, msg) -> None:
        kind = msg[0]
        if kind == "running":
            _, job, attempt = msg
            self.queue.mark_running(job, wid)
            self._emit("running", job=job, worker=wid,
                       attempt=attempt, pid=self.worker_pid(wid))
        elif kind == "heartbeat":
            _, job, info = msg
            j = self.queue.jobs.get(job)
            if j is None or j.terminal:
                return
            ck = info.get("checkpoint")
            now = self.now()
            fresh_ck = ck is not None and ck != j.checkpoint
            stale = now - self._hb_journaled.get(job, 0.0) >= 2.0
            self.queue.heartbeat(job, checkpoint=ck,
                                 journal_it=fresh_ck or stale)
            if fresh_ck or stale:
                self._hb_journaled[job] = now
            self._emit("heartbeat", job=job, worker=wid,
                       checkpoint=ck)
        elif kind == "result":
            _, job, attempt, result = msg
            w = self.workers.get(wid)
            if w is not None and w["job"] == job:
                w["job"] = None
            self._fold_result(job, result)

    def _fold_result(self, job: str, result: dict) -> None:
        j = self.queue.jobs[job]
        if j.terminal:          # a watchdog verdict raced it; keep that
            return
        if result.get("ok"):
            self.queue.complete(job, result)
            fl = result.get("flows") or {}
            cz = result.get("causality") or {}
            self._emit("done", job=job,
                       **({"flows_sampled": fl.get("sampled"),
                           "flows_harvested": fl.get("harvested")}
                          if fl else {}),
                       **({"causality_sampled": cz.get("sampled"),
                           "causality_windows":
                           cz.get("windows_attributed")}
                          if cz else {}))
            self._backfill_lanes(job, result)
        elif result.get("device_lost"):
            # DEVICE_LOST with headroom left: the in-run elastic ladder
            # exhausted but the mesh can still shrink — requeue the
            # SAME attempt at the degraded width (device loss is
            # environment, not the job's fault; it must not burn the
            # failure budget). Bounded by the shared requeue budget.
            dl = result["device_lost"]
            st = self.queue.device_lost(
                job, lost_shard=int(dl.get("lost_shard", -1)),
                new_shards=int(dl.get("new_shards", 1)),
                cause=str(dl.get("cause", "")))
            self._emit("device_lost", job=job, status=st,
                       lost_shard=dl.get("lost_shard"),
                       new_shards=dl.get("new_shards"))
        elif result.get("preempted") and not result.get("deadline"):
            # graceful drain: the run snapshotted and yielded — park it
            # back in the queue as a continuation of the same attempt
            self.queue.record({"ev": "requeued", "job": job,
                               "resume_from": result.get("checkpoint"),
                               "cause": "fleet preempted"})
            self._emit("requeued", job=job,
                       resume_from=result.get("checkpoint"))
        else:
            failure = dict(result.get("failure")
                           or {"error": result.get("error",
                                                   "unknown failure")})
            if result.get("deadline"):
                # in-run wallclock deadline: a failure that consumes an
                # attempt (a continuation would loop on the same
                # deadline forever); the snapshot stays for forensics
                failure.setdefault("verdict", "deadline")
                failure["checkpoint"] = result.get("checkpoint")
            st = self.queue.fail(job, failure,
                                 fatal=_is_fatal(result))
            self._emit("failed", job=job, status=st,
                       error=failure.get("error",
                                         failure.get("verdict")))
        if j.terminal:
            self.write_manifest()

    def _backfill_lanes(self, job: str, result: dict) -> None:
        """A completed packed job may carry lane-requeue specs for
        its quarantined lanes (fleet/scenario.py): enqueue each as a
        standalone child job — the freed lane slots backfill into the
        normal scheduler, with the usual attempt/backoff/quarantine
        accounting applying to the children."""
        from shadow_tpu.fleet.spec import JobSpec

        for child in (result.get("lanes") or {}).get("requeues", []):
            try:
                spec = JobSpec.from_dict(child)
            except (ValueError, TypeError) as e:
                self._emit("lane_requeue_rejected", job=job,
                           error=str(e))
                continue
            if self.queue.add_job(spec):
                self._emit("lane_requeued", job=job, child=spec.id,
                           lane_of=spec.lane_of)

    def _poll(self, timeout: float) -> None:
        conns = {w["conn"]: wid for wid, w in self.workers.items()}
        if not conns:
            time.sleep(min(timeout, 0.2))
            return
        for conn in mpc.wait(list(conns), timeout=timeout):
            wid = conns[conn]
            try:
                while conn.poll():
                    self._fold(wid, conn.recv())
            except (EOFError, OSError):
                self._drop_worker(wid, "pipe closed")

    def _watchdog(self, now: float) -> None:
        for wid in list(self._busy()):
            w = self.workers.get(wid)
            if w is None:
                continue
            j = self.queue.jobs[w["job"]]
            if j.deadline_at is not None and now > j.deadline_at:
                self._drop_worker(
                    wid, f"deadline watchdog "
                    f"(>{j.spec.max_wallclock_s}s * grace)", kill=True)
            elif j.lease_expires is not None and now > j.lease_expires:
                self._drop_worker(
                    wid, f"lease expired (no heartbeat for "
                    f"{self.policy.lease_timeout_s}s)", kill=True)

    def _reap(self) -> None:
        for wid in list(self.workers):
            w = self.workers[wid]
            if not w["proc"].is_alive():
                # drain any result that beat the death to the pipe
                try:
                    while w["conn"].poll():
                        self._fold(wid, w["conn"].recv())
                except (EOFError, OSError):
                    pass
                self._drop_worker(
                    wid, f"worker process died "
                    f"(exit {w['proc'].exitcode})")

    def _maybe_respawn(self) -> None:
        if (not self.workers and not self._draining
                and self.queue.pending() and self._respawns_left > 0):
            self._respawns_left -= 1
            self._spawn_worker()

    # -- preemption ---------------------------------------------------
    def stop(self) -> None:
        """Request a graceful drain (idempotent, signal-safe)."""
        self._stop = True

    def _begin_drain(self) -> None:
        self._draining = True
        self._drain_deadline = self.now() + self.drain_timeout_s
        for wid, w in self.workers.items():
            if w["proc"].is_alive():
                w["proc"].terminate()      # SIGTERM -> stop flag
        self._emit("draining", busy=len(self._busy()))

    # -- manifest -----------------------------------------------------
    def write_manifest(self, *, final: bool = False) -> str:
        man = manifest_mod.fleet_manifest(
            self.queue, workers_alive=len(self.workers),
            preempted=self._draining, stalled=self._stalled,
            complete=final and not self.queue.pending(),
            sweep=(self.sweep_block_fn(self.queue)
                   if self.sweep_block_fn is not None else None))
        return manifest_mod.write_fleet_manifest(
            os.path.join(self.fleet_dir, "fleet_manifest.json"), man)

    # -- main loop ----------------------------------------------------
    def run(self, *, install_signals: bool = False) -> int:
        prev = None
        if install_signals:
            prev = signal.signal(signal.SIGTERM,
                                 lambda s, f: self.stop())
        try:
            for _ in range(min(self._nworkers,
                               max(1, len(self.queue.pending())))):
                self._spawn_worker()
            self.write_manifest()
            while True:
                now = self.now()
                if self._stop and not self._draining:
                    self._begin_drain()
                self._dispatch(now)
                if not self.queue.pending():
                    break
                if self._draining:
                    if not self._busy():
                        break
                    if now > (self._drain_deadline or now):
                        for wid in list(self._busy()):
                            self._drop_worker(
                                wid, "drain timeout", kill=True)
                        break
                self._poll(0.2)
                self._watchdog(self.now())
                self._reap()
                self._maybe_respawn()
                if (self.queue.pending() and not self.workers
                        and self._respawns_left <= 0
                        and not self._draining):
                    self._stalled = True
                    self._emit("stalled",
                               pending=len(self.queue.pending()))
                    break
        finally:
            for wid, w in list(self.workers.items()):
                if w["job"] is None:
                    try:
                        w["conn"].send(("shutdown",))
                    except (BrokenPipeError, OSError):
                        pass
                    w["proc"].join(timeout=5)
            for wid in list(self.workers):
                self._drop_worker(wid, "fleet shutdown", kill=True)
            self.write_manifest(final=True)
            self.queue.close()
            if install_signals and prev is not None:
                signal.signal(signal.SIGTERM, prev)
        return self.exit_code()

    def exit_code(self) -> int:
        if self._draining:
            return EXIT_PREEMPTED
        if self._stalled or self.queue.pending():
            return EXIT_STALLED
        sts = [j.status for j in self.queue.jobs.values()]
        if state_mod.FAILED in sts:
            return EXIT_FAILURES
        if state_mod.QUARANTINED in sts and not self.salvage:
            return EXIT_FAILURES
        return EXIT_OK
