"""Bucket-affinity scheduling: run jobs that share a compiled program
consecutively on the same worker.

A worker process that just finished a job holds that job's traced and
compiled dispatch program in process memory (and, warm serving on,
the AOT store's deserialized executable). Handing it another job with
the SAME program key makes the compile path free; handing it a
different shape pays a fresh trace. The queue stays a FIFO — this
module only changes which idle worker takes which ready job:

- phase 1: every idle worker that has a last-program key takes the
  FIRST ready job with a matching key (FIFO within the key group);
- phase 2: remaining workers take the remaining jobs in plain FIFO
  order, so a job with a cold key is never starved — it waits exactly
  as long as it would have without affinity, minus the jobs that
  jumped onto already-warm workers.

The affinity key is computed from the spec alone (no build, no trace
read): every spec field that shapes the compiled program, with the
capacity knobs quantized to the same power-of-two buckets the
scenario build applies (fleet/scenario.py / compile/buckets.py). Two
jobs with equal affinity keys build equal NetConfigs and therefore
hit the same AOT store entry; the per-job manifest's `compile.key`
is the ground truth the fleet manifest records next to it."""

from __future__ import annotations

import hashlib
import json

from shadow_tpu.compile.buckets import quantize_pow2

AFFINITY_PREFIX = "ak"

# JobSpec fields that do NOT shape the compiled program: identity,
# runtime data (seed — the RNG counter rides in arrays), retry/budget
# policy, host-side pacing, and lease terms (tenant class / SLO are
# admission-gate inputs evaluated on the host — the resident
# program's shape must NOT change when a tenant's SLO does, or every
# lease renegotiation would retrace). Everything else is
# program-shaping.
_NON_PROGRAM_FIELDS = frozenset({
    "id", "seed", "max_retries", "max_attempts", "max_wallclock_s",
    "checkpoint_every_windows", "lane_of", "kills", "verify",
    "round_sleep_s", "auto_grow", "max_grow", "tenant_class",
    "slo_p99_ms",
})


def affinity_key(spec) -> str:
    """Deterministic program-affinity key for a job spec: "ak" + 16
    hex over the program-shaping spec fields with capacities
    bucketed. The inject trace PATH stands in for the lane count when
    `inject_lanes` is unset — reading the trace here would put file
    I/O on the scheduling path; same path => same trace => same
    derived lane count."""
    d = spec.as_dict() if hasattr(spec, "as_dict") else dict(spec)
    shaped = {k: v for k, v in d.items()
              if k not in _NON_PROGRAM_FIELDS}
    for knob in ("event_capacity", "outbox_capacity", "router_ring",
                 "inject_lanes"):
        if shaped.get(knob):
            shaped[knob] = quantize_pow2(int(shaped[knob]))
    blob = json.dumps(shaped, sort_keys=True, default=str)
    return AFFINITY_PREFIX + hashlib.sha256(
        blob.encode()).hexdigest()[:16]


def assign(ready, idle, last_key: dict, key_of=affinity_key):
    """Pair ready jobs with idle workers, affinity first.

    `ready` is the FIFO-ordered ready list (fleet/state.py), `idle`
    the idle worker ids in a deterministic order, `last_key` maps
    worker id -> affinity key of its last job. Returns [(worker_id,
    job)] — deterministic in its inputs (tests assert this), every
    pair consuming one worker and one job."""
    remaining = list(ready)
    picked: dict = {}
    for wid in idle:
        k = last_key.get(wid)
        if k is None or not remaining:
            continue
        match = next((j for j in remaining if key_of(j) == k), None)
        if match is not None:
            picked[wid] = match
            remaining.remove(match)
    for wid in idle:
        if wid in picked or not remaining:
            continue
        picked[wid] = remaining.pop(0)
    return [(wid, picked[wid]) for wid in idle if wid in picked]
