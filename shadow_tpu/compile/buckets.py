"""Shape-bucket planner: quantize capacity knobs, derive program keys.

Every capacity knob that sizes a device array shape-specializes the
compiled program — two runs that differ only in `event_capacity=24`
vs `25` compile two distinct executables even though the second is
behaviorally a superset of the first. Quantizing every shape-bearing
capacity UP to its power-of-two bucket collapses that continuum onto
a small lattice: runs land on shared programs, the persistent AOT
store (compile/store.py) gets hits instead of bespoke shapes, and a
capacity escalation that regrows to the *next bucket*
(faults/escalate.py) resumes on a program somebody already compiled.

Why padding is free: capacity only changes behavior at the first
overflow (the escalation transplant's exactness argument,
faults/escalate.py module doc). A run that never fills 24 slots
executes bit-identically with 32 — same event stream, same latches,
same conservation ledgers — so bucketing is a pure compile-sharing
transform. tests/test_compile_cache.py asserts this bit-identity.

The **program key** is the canonical identity of one compiled
program: the bucketed shape vector plus every trace-time constant
that is baked into the executable (shard count, chunk K, adaptive
flag, end time, min_jump, the kind-census digest of the app/fault
composition, code version, machine fingerprint). Two runs with equal
keys may share a serialized executable; the AOT store additionally
checks the example arguments' avals before serving, so an under-keyed
collision degrades to a fresh compile, never a wrong program.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib

# NetConfig knobs quantized by bucket_config: each sizes a device
# array axis and obeys the first-overflow invariant (padding slots are
# behavior-neutral until the first drop, which is counted either way).
# 0 means "feature off" for sparse_lanes/inject_lanes and must stay 0
# — quantizing it to 1 would silently enable the feature.
BUCKET_KNOBS = (
    "event_capacity",
    "outbox_capacity",
    "router_ring",
    "in_ring",
    "out_ring",
    "sparse_lanes",
    "inject_lanes",
)

# Capacity-override keys (loader / escalation vocabulary) that the
# fleet quantizes before building a scenario (fleet/scenario.py) and
# that escalation regrows bucket-to-bucket (faults/escalate.py).
CAPACITY_KEYS = ("event_capacity", "outbox_capacity", "router_ring")

KEY_PREFIX = "pk"
KEY_HEX = 16


def quantize_pow2(n: int) -> int:
    """Smallest power of two >= n. 0 stays 0 ("off" knobs must stay
    off) and negatives are rejected — a negative capacity is a bug,
    not a bucket."""
    n = int(n)
    if n < 0:
        raise ValueError(f"cannot bucket a negative capacity: {n}")
    if n <= 1:
        return n
    return 1 << (n - 1).bit_length()


def quantize_caps(caps: dict) -> dict:
    """Quantize a {knob: value} capacity-override dict (the fleet /
    escalation vocabulary). Unknown keys pass through untouched."""
    return {k: (quantize_pow2(v) if k in BUCKET_KNOBS else v)
            for k, v in caps.items()}


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """What the planner did: per-knob requested vs bucketed values.
    Rides the run manifest's `compile` block so every banked line is
    auditable (tools/telemetry_lint.py checks bucketed >= requested
    and bucketed is a power of two)."""

    requested: dict
    bucketed: dict

    @property
    def changed(self) -> dict:
        return {k: self.bucketed[k] for k, v in self.requested.items()
                if self.bucketed[k] != v}

    def as_dict(self) -> dict:
        return {k: {"requested": int(self.requested[k]),
                    "bucketed": int(self.bucketed[k])}
                for k in sorted(self.requested)}


def bucket_config(cfg):
    """Quantize every BUCKET_KNOB of a NetConfig to its power-of-two
    bucket. Returns (new_cfg, BucketPlan). Knobs left at None
    (sparse_lanes' engine default, derived emit_capacity) stay None —
    the default is already a bucket."""
    requested, bucketed, overrides = {}, {}, {}
    for knob in BUCKET_KNOBS:
        v = getattr(cfg, knob, None)
        if v is None:
            continue
        q = quantize_pow2(v)
        requested[knob] = int(v)
        bucketed[knob] = q
        if q != v:
            overrides[knob] = q
    new_cfg = dataclasses.replace(cfg, **overrides) if overrides else cfg
    return new_cfg, BucketPlan(requested=requested, bucketed=bucketed)


def shape_vector(cfg, *, telem_capacity: int | None = None,
                 lane_replicas: int | None = None,
                 inject_lanes: int | None = None) -> dict:
    """Every shape-bearing knob of a build, bucketed knobs and
    semantic axes alike — the program key's first component. The
    attach-time shapes (telemetry ring capacity, lane-isolation R,
    staged injection lanes) are not NetConfig fields, so callers that
    attached them pass the live values."""
    vec = {knob: int(getattr(cfg, knob))
           for knob in BUCKET_KNOBS if getattr(cfg, knob, None) is not None}
    vec["num_hosts"] = int(cfg.num_hosts)
    vec["sockets_per_host"] = int(cfg.sockets_per_host)
    vec["timers_per_host"] = int(cfg.timers_per_host)
    vec["emit_capacity"] = int(cfg.emit_capacity)
    vec["nic_drain"] = int(getattr(cfg, "nic_drain", 0))
    vec["tcp"] = bool(cfg.tcp)
    if telem_capacity is not None:
        vec["telem_capacity"] = int(telem_capacity)
    if lane_replicas is not None:
        vec["lane_replicas"] = int(lane_replicas)
    if inject_lanes is not None:
        vec["inject_lanes"] = int(inject_lanes)
    return vec


def shape_vector_for_sim(cfg, sim) -> dict:
    """shape_vector with the attach-time shapes read off a live Sim
    (telemetry ring / lane latches / injection staging are attached
    post-build, so the cfg alone understates the program's shapes)."""
    telem = getattr(sim, "telem", None)
    lanes = getattr(sim, "lanes", None)
    inject = getattr(sim, "inject", None)
    flows = getattr(sim, "flows", None)
    vec = shape_vector(
        cfg,
        telem_capacity=int(telem.capacity) if telem is not None else None,
        lane_replicas=int(lanes.replicas) if lanes is not None else None,
        inject_lanes=int(inject.lanes) if inject is not None else None)
    if flows is not None:
        vec["flow_capacity"] = int(flows.capacity)
        vec["flow_sample_period"] = int(flows.sample_period)
    if getattr(sim, "admission", None) is not None:
        # resident program (core/lanes.LaneAdmission): the lease
        # planes add pytree leaves, so a resident program is a
        # different executable from a lanes-only program of the same
        # shapes — key it as such. The flag is the ONLY admission
        # contribution: lease values are runtime data, which is
        # exactly why joins/leaves never change the program key.
        vec["resident"] = True
    return vec


def lane_bucket(host_counts) -> int:
    """Shared power-of-two lane width for a set of heterogeneous
    tenants: every tenant's per-lane topology pads UP to this bucket
    (apps/phold.py active_hosts occupies the prefix; padding rows are
    idle forever, so padding is behavior-neutral the same way
    capacity padding is). One width for all lanes keeps the resident
    program's host partition uniform — lane of host h stays
    h // width — which is what lets the lane population change
    without changing any shape."""
    counts = [int(h) for h in host_counts]
    if not counts:
        raise ValueError("lane_bucket needs at least one tenant")
    if min(counts) < 2:
        raise ValueError(
            f"every tenant needs >= 2 hosts, got {sorted(counts)}")
    return max(2, quantize_pow2(max(counts)))


def kind_census(app_handlers=(), app_bulk=None, *, fault_plan_digest=None,
                extra: dict | None = None) -> str:
    """Digest of the event-kind composition traced into a program:
    which app handlers (by qualified name), which bulk pass, and the
    installed fault plan's record digest — the plan's constants are
    baked into the executable (faults/apply.py closes over them), so
    two plans with equal shapes are still two programs."""
    names = []
    for h in app_handlers or ():
        names.append(f"{getattr(h, '__module__', '?')}."
                     f"{getattr(h, '__qualname__', repr(h))}")
    bulk = None
    if app_bulk is not None:
        bulk = (f"{type(app_bulk).__module__}."
                f"{type(app_bulk).__qualname__}")
    blob = json.dumps({"handlers": names, "bulk": bulk,
                       "fault_plan": fault_plan_digest,
                       "extra": extra or {}}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


_CODE_VERSION: str | None = None


def code_version() -> str:
    """Digest of every shadow_tpu source file's bytes. A code change
    anywhere invalidates persisted executables (the step function,
    engine, and netstack all trace into every program — tracking
    per-module dependencies is not worth a stale-program bug).
    Computed once per process."""
    global _CODE_VERSION
    if _CODE_VERSION is None:
        root = pathlib.Path(__file__).resolve().parents[1]
        h = hashlib.sha256()
        for p in sorted(root.rglob("*.py")):
            h.update(str(p.relative_to(root)).encode())
            try:
                h.update(p.read_bytes())
            except OSError:
                pass
        _CODE_VERSION = h.hexdigest()[:16]
    return _CODE_VERSION


def program_key(shapes: dict, *, shards: int = 1, chunk_windows: int = 1,
                adaptive: bool = False, census: str = "",
                end_time: int | None = None, min_jump: int | None = None,
                exchange_capacity: int | None = None,
                extra: dict | None = None) -> str:
    """Canonical program key: "pk" + 16 hex chars over the canonical
    JSON of (shape vector, shard count, chunk K, adaptive flag, the
    trace-time scalar constants, kind-census digest, code version,
    machine fingerprint, jax version). Everything that changes the
    compiled artifact is in here; everything that is runtime data
    (seeds, event payloads, table values) is not — that is what makes
    the key shareable across a sweep."""
    import jax

    from shadow_tpu.utils.compcache import machine_fingerprint

    blob = json.dumps({
        "shapes": {k: shapes[k] for k in sorted(shapes)},
        "shards": int(shards),
        "chunk_windows": int(chunk_windows),
        "adaptive": bool(adaptive),
        "end_time": None if end_time is None else int(end_time),
        "min_jump": None if min_jump is None else int(min_jump),
        "exchange_capacity": (None if exchange_capacity is None
                              else int(exchange_capacity)),
        "census": census,
        "code": code_version(),
        "machine": machine_fingerprint(),
        "jax": jax.__version__,
        "extra": extra or {},
    }, sort_keys=True)
    return KEY_PREFIX + hashlib.sha256(
        blob.encode()).hexdigest()[:KEY_HEX]


def is_program_key(key) -> bool:
    """Format check for manifests and the lint: pk + 16 lowercase hex."""
    return (isinstance(key, str) and len(key) == len(KEY_PREFIX) + KEY_HEX
            and key.startswith(KEY_PREFIX)
            and all(c in "0123456789abcdef"
                    for c in key[len(KEY_PREFIX):]))
