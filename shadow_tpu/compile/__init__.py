"""Shape-bucketed AOT program cache and warm-start serving.

Three pieces (docs/4-performance.md has the measured numbers):

- `buckets`: quantize every shape-bearing capacity knob to its
  power-of-two bucket and derive the canonical program key that
  identifies one compiled executable across runs and processes.
- `store`: the persistent on-disk map from program key to serialized
  compiled executable, with sidecar manifests, atomic writes,
  corruption/version fallback, and LRU gc.
- `serve`: the lazy warm wrapper dispatch paths use instead of
  calling `jax.jit(...)` results directly, plus the `prewarm` entry
  point that populates the store ahead of a run.

The supervised loop (utils/checkpoint.py run_windows), the whole-run
factories (net/build.py), the sharded harness (parallel/shard.py) and
the fleet (fleet/scenario.py, which also orders ready jobs by program
key — fleet/affinity.py) all dispatch through here when warm serving
is enabled.
"""

from shadow_tpu.compile.buckets import (  # noqa: F401
    BUCKET_KNOBS,
    BucketPlan,
    bucket_config,
    code_version,
    is_program_key,
    kind_census,
    program_key,
    quantize_caps,
    quantize_pow2,
    shape_vector,
    shape_vector_for_sim,
)
from shadow_tpu.compile.serve import (  # noqa: F401
    maybe_warm,
    prewarm,
    warm_enabled,
)
from shadow_tpu.compile.store import (  # noqa: F401
    ProgramStore,
    default_store,
)
