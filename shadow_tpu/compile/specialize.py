"""Compile-time program specialization: capability-trimmed variants.

The step/bulk passes are traced for the *general* network — every
window pays for a Bernoulli loss draw per send and a timer-handler
family gate even when the concrete build can prove neither can ever
fire (reliability table all-ones and no fault plan touching it; no
handler that can arm a host timer). ROADMAP item 4(a) measured that
generality at ~11% on lossless topologies.

This module closes the gap statically:

- `derive(bundle, ...)` computes a `Capabilities` vector from the
  CONCRETE build inputs (the boot reliability table, the installed
  fault plan's record kinds, the app handlers' declared emit-kind
  sets, the attached optional subsystems).
- `apply(bundle, ...)` attaches the vector to the bundle; the runner
  factories (net/build.py) thread it into make_step_fn /
  make_bulk_fn / make_tcp_bulk_fn, which then *omit* the dead
  subgraphs from the trace instead of lax.cond-gating them.
- The vector folds into the program key (compile/buckets.py `extra`)
  ONLY when something was actually dropped, so a scenario with
  nothing trimmable produces a byte-identical program under the SAME
  key as an unspecialized build, while trimmed variants coexist in
  the warm store next to their full twins.

Safety is load-bearing: dropping a capability attaches a `GuardState`
to the Sim — one cheap device predicate per dropped capability,
evaluated once per window at the fault boundary (core/engine.py
step_window). If a provably-dead capability would have fired anyway
(a checkpoint restored a lossy reliability table into a loss-trimmed
program; an external path staged a TIMER event into a timer-trimmed
one), the latch trips a FATAL health fault (faults/health.py) —
specialization can never silently change results. The trimmed values
are bit-identical by construction wherever the capabilities hold:
the loss trim advances the RNG counters by exactly the amount the
skipped draw would have (rng.uniform returns counters+1,
data-independently), and an omitted handler family is the identity
on every micro-step where its kinds cannot appear.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import simtime
from shadow_tpu.core.events import EventKind

I64 = jnp.int64

# The capabilities this pass can trim out of the trace. `tcp` and
# `faults` are recorded in the vector for the manifest/operators but
# are already structurally elided by older machinery (cfg.tcp gates
# the TCP handler families; a None fault_fn skips the table-rewrite
# plumbing) and already keyed (cfg/tcp in the shape vector, the plan
# digest in the kind census) — only the trims below change the traced
# program beyond what the key already sees.
TRIMMABLE = ("loss", "timers")


@dataclasses.dataclass(frozen=True)
class Capabilities:
    """Static capability vector of one built scenario. True = the
    capability is LIVE (traced in full); a False trimmable capability
    is OMITTED from the trace and watched by the guard latch."""

    loss: bool = True      # any send can be reliability-dropped
    timers: bool = True    # a TIMER event can ever enter the queue
    tcp: bool = True       # cfg.tcp (recorded; trimmed by cfg already)
    faults: bool = True    # a fault plan is installed (recorded)
    # statically-known optional attachments (None-contributes-no-leaves
    # contract, net/state.py Sim) — recorded so operators can read a
    # stored program's full composition off the store sidecar
    telemetry: bool = False
    lanes: bool = False
    inject: bool = False
    flows: bool = False
    admission: bool = False
    causality: bool = False

    def dropped(self) -> tuple:
        """Names of the capabilities this pass trimmed out of the
        trace (subset of TRIMMABLE), sorted."""
        return tuple(sorted(n for n in TRIMMABLE if not getattr(self, n)))

    def key_extra(self) -> str | None:
        """Program-key contribution: a stable token per dropped
        capability, None when nothing was dropped — so an untrimmed
        specialized build keys identically to an unspecialized one."""
        d = self.dropped()
        return "-".join("no_" + n for n in d) if d else None

    def as_dict(self) -> dict:
        """Manifest / store-sidecar block."""
        return {
            "capabilities": {f.name: bool(getattr(self, f.name))
                             for f in dataclasses.fields(self)},
            "dropped": list(self.dropped()),
            "key_extra": self.key_extra(),
        }


def _plan_touches_reliability(plan) -> bool:
    """True when any record of the installed fault plan can rewrite
    the reliability table (mirror of faults/apply.py rel_kinds)."""
    if plan is None or not getattr(plan, "n", 0):
        return False
    from shadow_tpu.faults.plan import FaultKind

    k = np.asarray(plan.kind)
    return bool(np.isin(k, (FaultKind.LINK_DOWN, FaultKind.LINK_UP,
                            FaultKind.LOSS, FaultKind.PARTITION,
                            FaultKind.HEAL)).any())


def _timers_statically_dead(bundle, app_handlers) -> bool:
    """TIMER events are emitted only by net/timers.timer_set, which on
    the device side is reached only through handlers that arm host
    timers. A handler opts into the analysis by declaring
    `specialize_kinds` (a frozenset of the EventKind ints it can
    emit); every handler must declare, and none may declare TIMER.
    Injection staging can stage arbitrary kinds, so an attached
    inject lane keeps timers live. The guard latch backstops the
    declaration: a queue-resident TIMER on a timer-trimmed program is
    a fatal health fault, never a silent no-op."""
    if getattr(bundle.sim, "inject", None) is not None:
        return False
    for h in app_handlers or ():
        kinds = getattr(h, "specialize_kinds", None)
        if kinds is None or int(EventKind.TIMER) in kinds:
            return False
    return True


def derive(bundle, app_handlers=(), app_bulk=None,
           app_tcp_bulk=None) -> Capabilities:
    """Derive the capability vector from one built bundle's concrete
    inputs. Pure analysis — attaches nothing; see apply()."""
    rel = np.asarray(bundle.sim.net.reliability)
    plan = getattr(bundle, "fault_plan", None)
    lossless = bool((rel >= 1.0).all()) and not _plan_touches_reliability(plan)
    sim = bundle.sim
    return Capabilities(
        loss=not lossless,
        timers=not _timers_statically_dead(bundle, app_handlers),
        tcp=bool(bundle.cfg.tcp),
        faults=plan is not None,
        telemetry=getattr(sim, "telem", None) is not None,
        lanes=getattr(sim, "lanes", None) is not None,
        inject=getattr(sim, "inject", None) is not None,
        flows=getattr(sim, "flows", None) is not None,
        admission=getattr(sim, "admission", None) is not None,
        causality=getattr(sim, "causality", None) is not None,
    )


@struct.dataclass
class GuardState:
    """Device-side guard latch for a specialized program: one sticky
    trip counter per dropped capability, bumped once per window at the
    fault boundary (engine.step_window). The watch flags are static
    (pytree_node=False) so an unwatched predicate contributes nothing
    to the trace; the counters are scalar leaves, so shard_map's
    generic delta-psum aggregates them (parallel/shard.py
    _replicate_scalars) and lane compaction passes them through
    untouched (core/compact.py)."""

    watch_loss: bool = struct.field(pytree_node=False, default=False)
    watch_timers: bool = struct.field(pytree_node=False, default=False)
    loss_trips: jax.Array = None    # [] i64
    timer_trips: jax.Array = None   # [] i64

    def watched(self) -> tuple:
        return tuple(n for n, w in (("loss", self.watch_loss),
                                    ("timers", self.watch_timers)) if w)


def make_guard(caps: Capabilities) -> GuardState | None:
    """Guard for a capability vector; None when nothing was dropped
    (no dropped capability -> no guard -> no extra pytree leaves ->
    byte-identical program to the unspecialized build)."""
    d = caps.dropped()
    if not d:
        return None
    return GuardState(
        watch_loss="loss" in d,
        watch_timers="timers" in d,
        loss_trips=jnp.zeros((), I64),
        timer_trips=jnp.zeros((), I64),
    )


def guard_update(sim, wend):
    """Per-window guard evaluation, called from engine.step_window
    right after the fault rewrite (the only in-window writer of the
    watched tables). Each watched predicate asks "could the dropped
    capability fire?" and bumps its sticky counter; faults/health.py
    gather() folds a nonzero counter into a FATAL verdict."""
    g = sim.guard
    if g.watch_loss:
        trip = jnp.any(sim.net.reliability < 1.0)
        g = g.replace(loss_trips=g.loss_trips + trip.astype(I64))
    if g.watch_timers:
        q = sim.events
        pending = ((q.time != simtime.INVALID)
                   & (q.kind == EventKind.TIMER))
        g = g.replace(
            timer_trips=g.timer_trips + jnp.any(pending).astype(I64))
    return sim.replace(guard=g)


def apply(bundle, app_handlers=(), app_bulk=None, app_tcp_bulk=None,
          mode: str = "auto"):
    """Specialize a built bundle: derive the capability vector and,
    when anything is trimmable, return a new bundle carrying the
    vector (SimBundle.caps — the runner factories read it) with the
    guard attached to its Sim. mode="off" returns the bundle
    unchanged with caps=None (the --specialize off escape hatch).
    Returns the (possibly new) bundle; read `bundle.caps` for the
    vector (None = unspecialized)."""
    if mode == "off":
        return (dataclasses.replace(bundle, caps=None)
                if getattr(bundle, "caps", None) is not None else bundle)
    if mode != "auto":
        raise ValueError(f"--specialize must be auto|off, got {mode!r}")
    caps = derive(bundle, app_handlers, app_bulk, app_tcp_bulk)
    sim = bundle.sim
    guard = make_guard(caps)
    if guard is not None:
        sim = sim.replace(guard=guard)
    return dataclasses.replace(bundle, sim=sim, caps=caps)


def loss_trimmed(caps) -> bool:
    """True when the loss capability was dropped — the send paths use
    this one predicate so every draw site trims under the same rule."""
    return caps is not None and not caps.loss


def timers_trimmed(caps) -> bool:
    return caps is not None and not caps.timers


def specialization_block(caps, sim=None, *, mode: str = "auto") -> dict | None:
    """run_manifest.json block for a specialized run (None when the
    run was not specialized): the capability vector, the dropped list,
    the key contribution, and — when the final sim is given — the
    guard-latch counters proving no dead capability fired.
    tools/telemetry_lint.py validates this block."""
    if caps is None:
        return None
    block = {"mode": mode, **caps.as_dict()}
    g = guard_report(sim) if sim is not None else None
    if g is not None:
        block["guard"] = g
    return block


def guard_report(sim) -> dict | None:
    """Host-side snapshot of the guard counters (None when the sim
    carries no guard) — consumed by health.gather and the manifest."""
    g = getattr(sim, "guard", None)
    if g is None:
        return None
    return {
        "watched": list(g.watched()),
        "loss_trips": int(np.asarray(g.loss_trips)),
        "timer_trips": int(np.asarray(g.timer_trips)),
    }
