"""Warm-start serving: the adapter between live `jax.jit` callables
and the persistent AOT store.

A dispatch path (utils/checkpoint.py run_windows, net/build.py whole
runners, parallel/shard.py sharded runners) builds its jitted function
exactly as before, then wraps it in `maybe_warm(jitted, key)`. The
wrapper is lazy: the FIRST call resolves against the program store
using the actual call arguments as the AOT example — a hit loads the
serialized executable (milliseconds, no retrace), a miss compiles
through the live jit object and persists for next time. Either way
the wrapper's `info` dict ends up holding the manifest `compile`
block (key, hit, load_s/compile_s) the caller records.

Fallback discipline: a loaded executable that rejects its arguments
(avals drift the sidecar digest missed, donation mismatch) triggers
ONE fallback to the live jitted function, recorded in info — a stale
cache entry may cost a recompile, never a crash. When serving is
disabled (`warm_enabled()` false) `maybe_warm` returns the jitted
callable untouched: zero overhead, identical semantics.
"""

from __future__ import annotations

import os

from shadow_tpu.compile.store import default_store

ENV_FLAG = "SHADOW_WARM_PROGRAMS"


def warm_enabled(default: bool = False) -> bool:
    """Is warm-program serving on? SHADOW_WARM_PROGRAMS=1/0 wins;
    unset falls back to the caller's default (fleet scenarios default
    on — repeated shapes are their whole workload; ad-hoc runs default
    off). SHADOW_NO_COMPILE_CACHE=1 disables unconditionally — it is
    the master opt-out for every persistent-compile artifact."""
    if os.environ.get("SHADOW_NO_COMPILE_CACHE"):
        return False
    v = os.environ.get(ENV_FLAG)
    if v is None:
        return bool(default)
    return v.strip().lower() not in ("0", "", "false", "no")


class WarmFn:
    """Lazy warm wrapper: behaves like the wrapped jitted callable,
    resolves hit-or-compile against the store at first call. `key`
    may be a callable (args, kwargs) -> key for factories whose
    program shapes are only known from the first call's arguments
    (net/build.py runners take any telemetry/lane-attached sim)."""

    def __init__(self, jitted, key, *, store=None, meta=None,
                 info=None):
        self._jitted = jitted
        self._key = key
        self._store = store
        self._meta = meta
        self._compiled = None
        # shared, caller-visible: run_windows hands this dict to the
        # supervisor/manifest, the wrapper fills it at first dispatch
        self.info = info if info is not None else {}
        if isinstance(key, str):
            self.info.setdefault("key", key)
        self.info.setdefault("warm", True)

    def _resolve(self, args, kwargs):
        key = self._key
        if callable(key):
            try:
                key = key(args, kwargs)
            except Exception as e:
                self.info.update(
                    {"hit": False, "fallback": f"key:{type(e).__name__}"})
                return self._jitted
        if key is None:
            self.info.update({"warm": False, "hit": False})
            return self._jitted
        self.info["key"] = key
        store = self._store if self._store is not None else default_store()
        try:
            compiled, info = store.get_or_compile(
                key, self._jitted, args, kwargs, meta=self._meta)
        except Exception as e:
            # AOT machinery itself failed (serialization unsupported on
            # this backend, unreadable store root, ...): serve the live
            # jit — correctness must not depend on the cache.
            self.info.update({"hit": False,
                              "fallback": f"store:{type(e).__name__}"})
            return self._jitted
        self.info.update(info)
        return compiled

    def _ensure(self, args, kwargs):
        if self._compiled is None:
            self._compiled = self._resolve(args, kwargs)

    def lower(self, *args, **kwargs):
        """Keep the `fn.lower(*args).compile()` protocol alive through
        the wrapper (cli.py uses it to split trace+compile from device
        execution in the wall-time trace): compile() resolves
        load-or-compile against the store — so the load/compile cost
        lands in the caller's compile phase — and returns the WarmFn
        itself, preserving the stale-executable fallback discipline of
        __call__."""
        return _WarmLowered(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        self._ensure(args, kwargs)
        try:
            return self._compiled(*args, **kwargs)
        except Exception as e:
            if self._compiled is self._jitted:
                raise
            # a loaded executable rejected the call — demote to the
            # live jit permanently and re-execute (argument buffers are
            # intact: the rejection happens before execution)
            self.info["fallback"] = f"call:{type(e).__name__}"
            self.info["hit"] = False
            self._compiled = self._jitted
            return self._jitted(*args, **kwargs)


class _WarmLowered:
    """Adapter returned by WarmFn.lower(): .compile() forces the
    store resolution with the lowering arguments as the AOT example
    and hands back the (now-resolved) WarmFn."""

    def __init__(self, warm, args, kwargs):
        self._warm = warm
        self._args = args
        self._kwargs = kwargs

    def compile(self):
        self._warm._ensure(self._args, self._kwargs)
        return self._warm


def maybe_warm(jitted, key: str | None, *, enabled: bool,
               store=None, meta=None, info=None):
    """Wrap `jitted` for warm serving when enabled and keyed;
    otherwise return it untouched (and mark info warm=False so the
    manifest still records that serving was off)."""
    if not enabled or key is None:
        if info is not None:
            info.setdefault("warm", False)
            # lazy key factories (net/build.py) stay unresolved when
            # serving is off — a callable must never leak into the
            # manifest's compile block
            if isinstance(key, str):
                info.setdefault("key", key)
        return jitted
    return WarmFn(jitted, key, store=store, meta=meta, info=info)


def live_cache_size(fn):
    """Trace count of the live jitted callable behind `fn` (a WarmFn
    or a bare jax.jit function) — the resident program's zero-retrace
    proof (fleet/admission.py): after any number of admission events
    the dispatch function's trace cache must still hold exactly one
    entry, because joins/leaves mutate runtime data, never shapes.
    Returns None when the callable exposes no cache (a loaded AOT
    executable cannot retrace by construction)."""
    j = getattr(fn, "_jitted", fn)
    try:
        return int(j._cache_size())
    except Exception:
        return None


def prewarm(bundle, app_handlers=(), *, end_time=None,
            mesh=None, mesh_axis: str = "hosts",
            exchange_capacity=None, windows_per_dispatch=None,
            adaptive_jump=None, store=None, log=None) -> dict:
    """Compile (or confirm warm) the supervised-loop program for a
    built bundle's shape, populating the store so the NEXT run of
    this shape starts dispatching instead of compiling. Constructs
    the exact dispatch function run_windows would use and forces it
    through the store with example arguments (the bundle's own sim) —
    the persisted program IS the one a later run_windows loads.
    Returns the compile-info block ({key, hit, ...}); callers who
    want bucket sharing build the bundle from a bucketed config
    (compile.buckets.bucket_config) first."""
    from shadow_tpu.utils import checkpoint

    say = log or (lambda m: None)
    info = checkpoint.prewarm_dispatch(
        bundle, app_handlers, end_time=end_time, mesh=mesh,
        mesh_axis=mesh_axis, exchange_capacity=exchange_capacity,
        windows_per_dispatch=windows_per_dispatch,
        adaptive_jump=adaptive_jump, store=store)
    say(f"prewarm {info.get('key')}: "
        + ("hit" if info.get("hit") else
           f"compiled in {info.get('compile_s', 0.0):.1f}s"))
    return info
