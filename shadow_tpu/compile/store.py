"""Persistent AOT program store: compile once per program key, serve
serialized executables on every later run.

One entry per program key (compile/buckets.py): `<key>.bin` holds the
pickled `jax.experimental.serialize_executable.serialize(...)` payload
and `<key>.json` a human-readable sidecar (avals digest, code/jax
versions, machine fingerprint, sizes, timings). The store lives under
the claimed compile-cache directory (utils/compcache.py), so the same
machine-fingerprint claim/redirect discipline that protects JAX's own
persistent cache protects the AOT entries: a host with different CPU
features is redirected to its own namespace and never loads foreign
XLA:CPU AOT code.

Safety over speed, always: any corruption, version skew, avals
mismatch, or deserialization error degrades to a fresh
`lower().compile()` — a broken cache entry may cost one compile,
never a crash and never a wrong program. Writes are atomic
(tmp + os.replace), so a killed worker leaves no torn entries.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import pickle
import time

from shadow_tpu.compile import buckets

STORE_VERSION = 1


def _avals_digest(args, kwargs=None) -> str:
    """Digest of the example call's abstract values (shape/dtype
    tree). The program key should already pin these; the digest is the
    backstop that turns an under-keyed collision into a miss instead
    of a wrongly-served program."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    parts = [str(treedef)]
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append(f"{tuple(leaf.shape)}:{leaf.dtype}")
        else:
            # python scalar: weak-typed at trace time — tag it so a
            # scalar arg and a committed array arg never alias
            parts.append(f"py:{type(leaf).__name__}:"
                         f"{np.asarray(leaf).dtype}")
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _compile_outside_xla_cache(lowered):
    """lowered.compile() with jax's persistent compilation cache
    bypassed for this one call. An executable SERVED from that cache
    serializes into a payload whose fusion symbols cannot be re-linked
    at deserialize time (XLA:CPU "Symbols not found"), which would
    poison the store: every save after the first would overwrite a
    good entry with an unloadable one. On this path the AOT store IS
    the persistence layer, so bypassing the XLA cache costs only the
    one fresh compile the store exists to amortize.

    Nulling the config dir alone is NOT enough: the cache module
    latches an is-cache-used bit and the cache object itself at first
    use, so a process that already compiled anything keeps serving
    from the old dir. reset_cache() drops the latch; a second reset
    in the finally re-latches with the restored dir for every later
    ordinary compile in this process."""
    import jax

    try:
        from jax._src import compilation_cache as _cc
    except Exception:
        _cc = None

    prev = jax.config.jax_compilation_cache_dir
    if not prev:
        return lowered.compile()
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        if _cc is not None:
            _cc.reset_cache()
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        if _cc is not None:
            _cc.reset_cache()


def default_root() -> pathlib.Path:
    """Store root: $SHADOW_AOT_DIR, else `aot/` inside the claimed
    compile-cache dir — claim/redirect included, so foreign-featured
    hosts get their own namespace exactly like the JAX cache."""
    env = os.environ.get("SHADOW_AOT_DIR")
    if env:
        return pathlib.Path(env)
    from shadow_tpu.utils.compcache import (_claim_or_redirect,
                                            machine_fingerprint)
    cache = pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"
    return _claim_or_redirect(cache, machine_fingerprint(),
                              log=lambda m: None) / "aot"


class ProgramStore:
    """On-disk map: program key -> serialized compiled executable."""

    def __init__(self, root: os.PathLike | str | None = None):
        self.root = pathlib.Path(root) if root is not None else default_root()

    # -- paths ---------------------------------------------------------
    def bin_path(self, key: str) -> pathlib.Path:
        return self.root / f"{key}.bin"

    def meta_path(self, key: str) -> pathlib.Path:
        return self.root / f"{key}.json"

    # -- read side -----------------------------------------------------
    def read_meta(self, key: str) -> dict | None:
        try:
            meta = json.loads(self.meta_path(key).read_text())
        except (OSError, ValueError):
            return None
        return meta if isinstance(meta, dict) else None

    def _loadable(self, key: str, avals: str) -> dict | None:
        """Sidecar gate: entry exists, store/code/jax/machine versions
        match this process, avals match the caller's example args."""
        import jax

        from shadow_tpu.utils.compcache import machine_fingerprint

        meta = self.read_meta(key)
        if meta is None or not self.bin_path(key).exists():
            return None
        if meta.get("store_version") != STORE_VERSION:
            return None
        if meta.get("code") != buckets.code_version():
            return None
        if meta.get("jax") != jax.__version__:
            return None
        if meta.get("machine") != machine_fingerprint():
            return None
        if meta.get("avals") != avals:
            return None
        return meta

    def load(self, key: str, avals: str):
        """Deserialize the stored executable for `key`, or None on any
        mismatch/corruption (the caller falls back to compiling)."""
        from jax.experimental import serialize_executable

        if self._loadable(key, avals) is None:
            return None
        try:
            payload, in_tree, out_tree = pickle.loads(
                self.bin_path(key).read_bytes())
            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception:
            return None
        # LRU touch for gc(): served entries are the ones worth keeping.
        try:
            now = time.time()
            os.utime(self.bin_path(key), (now, now))
        except OSError:
            pass
        return compiled

    # -- write side ----------------------------------------------------
    def save(self, key: str, compiled, avals: str,
             meta: dict | None = None) -> bool:
        """Serialize and persist atomically. Returns False (and leaves
        no partial files) on any failure — persistence is best-effort,
        the in-memory compiled program is already usable."""
        import jax
        from jax.experimental import serialize_executable

        from shadow_tpu.utils.compcache import machine_fingerprint

        try:
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = self.bin_path(key).with_suffix(".bin.tmp")
            tmp.write_bytes(blob)
            os.replace(tmp, self.bin_path(key))
            sidecar = {
                "key": key,
                "store_version": STORE_VERSION,
                "avals": avals,
                "code": buckets.code_version(),
                "jax": jax.__version__,
                "machine": machine_fingerprint(),
                "nbytes": len(blob),
            }
            sidecar.update(meta or {})
            tmp = self.meta_path(key).with_suffix(".json.tmp")
            tmp.write_text(json.dumps(sidecar, sort_keys=True) + "\n")
            os.replace(tmp, self.meta_path(key))
            return True
        except Exception:
            for p in (self.bin_path(key).with_suffix(".bin.tmp"),
                      self.meta_path(key).with_suffix(".json.tmp")):
                try:
                    p.unlink()
                except OSError:
                    pass
            return False

    # -- the one entry point dispatch paths use ------------------------
    def get_or_compile(self, key: str, jitted, args, kwargs=None,
                       meta: dict | None = None):
        """Serve `key` warm if stored, else lower+compile `jitted` on
        the example `args` and persist. Returns (compiled, info) where
        info is the manifest `compile` block payload: {key, hit,
        load_s} on a hit, {key, hit, lower_s, compile_s} on a miss."""
        avals = _avals_digest(args, kwargs)
        t0 = time.perf_counter()
        compiled = self.load(key, avals)
        if compiled is not None:
            return compiled, {"key": key, "hit": True,
                              "load_s": time.perf_counter() - t0}
        t0 = time.perf_counter()
        lowered = jitted.lower(*args, **(kwargs or {}))
        t1 = time.perf_counter()
        compiled = _compile_outside_xla_cache(lowered)
        t2 = time.perf_counter()
        info = {"key": key, "hit": False,
                "lower_s": t1 - t0, "compile_s": t2 - t1}
        info["stored"] = self.save(key, compiled, avals, meta)
        if info["stored"] and self.load(key, avals) is None:
            # an entry that cannot be served back is worse than no
            # entry — every later run would miss through it forever
            self.drop(key)
            info["stored"] = False
        return compiled, info

    # -- maintenance (tools/compcache_ctl.py) --------------------------
    def ls(self) -> list[dict]:
        """Every entry, oldest-served first: [{key, nbytes, mtime,
        ...sidecar}]."""
        out = []
        try:
            bins = sorted(self.root.glob("*.bin"))
        except OSError:
            return out
        for b in bins:
            key = b.stem
            meta = self.read_meta(key) or {"key": key}
            try:
                st = b.stat()
                meta["nbytes"] = st.st_size
                meta["mtime"] = st.st_mtime
            except OSError:
                continue
            out.append(meta)
        out.sort(key=lambda m: m.get("mtime", 0.0))
        return out

    def stats(self) -> dict:
        entries = self.ls()
        return {
            "root": str(self.root),
            "entries": len(entries),
            "total_bytes": sum(int(m.get("nbytes", 0)) for m in entries),
            "code_versions": sorted({m.get("code") for m in entries
                                     if m.get("code")}),
            # capability-trimmed variants (compile/specialize.py) — a
            # specialized entry's sidecar carries the vector its
            # program was trimmed under
            "specialized": sum(
                1 for m in entries
                if (m.get("specialization") or {}).get("dropped")),
        }

    def drop(self, key: str) -> None:
        for p in (self.bin_path(key), self.meta_path(key)):
            try:
                p.unlink()
            except OSError:
                pass

    def gc(self, max_bytes: int) -> dict:
        """Evict least-recently-served entries until the store fits in
        `max_bytes`. Entries from other code versions go first — they
        can never be served again."""
        entries = self.ls()
        stale = [m for m in entries if m.get("code") != buckets.code_version()]
        fresh = [m for m in entries if m.get("code") == buckets.code_version()]
        dropped, total = [], sum(int(m.get("nbytes", 0)) for m in entries)
        for m in stale + fresh:
            if total <= max_bytes:
                break
            self.drop(m["key"])
            total -= int(m.get("nbytes", 0))
            dropped.append(m["key"])
        return {"dropped": dropped, "remaining_bytes": total}


_DEFAULT: ProgramStore | None = None


def default_store() -> ProgramStore:
    """Process-wide store rooted at default_root(). Re-rooted when
    SHADOW_AOT_DIR changes (tests point it at tmpdirs)."""
    global _DEFAULT
    root = default_root()
    if _DEFAULT is None or _DEFAULT.root != root:
        _DEFAULT = ProgramStore(root)
    return _DEFAULT
