"""Per-window conservation invariants — the chaos harness's oracle.

Every event that ever receives a (source, seq) identity bumps exactly
one `next_seq` counter (boot seeding in net/build.py, fault wakeups in
faults/apply.py, window emissions in core/events.py apply_emissions),
and every identified event is, at any window barrier, in exactly one
place: already processed, still queued, staged in the outbox, or
loudly dropped. That gives the ledger

    sum(next_seq) == events_processed + sum(fill_count)
                     + sum(outbox.count) [ + drops ]

EXACT when the overflow latches are zero — which is every healed run,
since any nonzero overflow is a fatal latch the supervisor escalates
on. With nonzero overflow the right side brackets the left instead
(EmitBuffer drops never received a seq, so `q.overflow` mixes
seq-carrying and seq-less drops): the checker degrades to a bounds
check rather than lying about exactness.

CRASH faults flush a host's event row non-conservatively by design
(the reference drops a dead host's events too), so chaos plans that
want the exact ledger exclude crash/restart kinds.

The clock half: window starts must be strictly increasing and each
round's next_min may never precede its window start (runahead legally
schedules *inside* the current window — `next_min < wend` is fine;
`next_min < wstart` is corruption, the same rule the supervisor
latches as time_regression).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class WindowSample:
    """The ledger at one window barrier (host-side ints — samples
    survive process kills and program rebuilds by construction)."""

    wstart: int
    wend: int
    next_min: int
    pushed: int       # sum(events.next_seq): identities ever assigned
    processed: int    # cumulative events_processed (incl. resume base)
    queued: int       # sum(events.fill_count())
    outboxed: int     # sum(outbox.count) (0 after route clears it)
    drops: int        # events.overflow + outbox.overflow (rq spill
                      # drops packets, not identified events)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def sample(sim, *, wstart: int, wend: int, next_min: int,
           processed_total: int) -> WindowSample:
    """Read the ledger off the device at a window barrier.
    `processed_total` is the harness's cumulative processed count —
    cumulative across resumes/escalations, which per-attempt engine
    stats are not."""
    q = sim.events
    return WindowSample(
        wstart=int(wstart), wend=int(wend), next_min=int(next_min),
        pushed=int(np.sum(np.asarray(q.next_seq, dtype=np.int64))),
        processed=int(processed_total),
        queued=int(np.sum(np.asarray(q.fill_count()))),
        outboxed=int(np.sum(np.asarray(sim.outbox.count))),
        drops=int(q.overflow) + int(sim.outbox.overflow),
    )


def check(samples) -> list[str]:
    """Validate a run's sample sequence; returns human-readable
    violation strings (empty == conserved). Deliberately side-effect
    free and picky — tests corrupt counters to prove it catches."""
    errors: list[str] = []
    prev = None
    for i, s in enumerate(samples):
        where = f"window[{i}] (wstart={s.wstart})"
        if s.wend <= s.wstart:
            errors.append(f"{where}: wend={s.wend} <= wstart")
        if s.next_min < s.wstart:
            errors.append(f"{where}: clock regressed — next_min="
                          f"{s.next_min} < wstart={s.wstart}")
        if prev is not None and s.wstart <= prev.wstart:
            errors.append(
                f"{where}: window starts not strictly increasing "
                f"(previous wstart={prev.wstart})")
        if prev is not None and s.pushed < prev.pushed:
            errors.append(
                f"{where}: pushed count went backwards "
                f"({prev.pushed} -> {s.pushed}) — next_seq is "
                f"monotone by construction")
        if prev is not None and s.processed < prev.processed:
            errors.append(
                f"{where}: processed count went backwards "
                f"({prev.processed} -> {s.processed})")
        accounted = s.processed + s.queued + s.outboxed
        if s.drops == 0:
            if s.pushed != accounted:
                errors.append(
                    f"{where}: conservation violated — pushed="
                    f"{s.pushed} != processed={s.processed} + queued="
                    f"{s.queued} + outboxed={s.outboxed}")
        else:
            # drops mix seq-carrying and seq-less losses: bounds only
            if not (accounted <= s.pushed <= accounted + s.drops):
                errors.append(
                    f"{where}: pushed={s.pushed} outside "
                    f"[{accounted}, {accounted + s.drops}] "
                    f"(drops={s.drops})")
        prev = s
    return errors


@dataclasses.dataclass(frozen=True)
class LaneWindowSample:
    """The ledger at one window barrier, split per lane (lane-isolated
    packed runs, core/lanes.py). Packed ensembles carry no cross-lane
    traffic (each lane is an independent replica; apps/phold.py keeps
    peers inside the replica block), so every term of the global
    ledger decomposes by contiguous lane block — plus one new term:
    `flushed`, the quarantine freeze's loudly-discarded pending events
    (they carried identities, so they stay on the books)."""

    wstart: int
    wend: int
    pushed: tuple      # [R] lane sums of next_seq
    processed: tuple   # [R] lane shares of ctr_events_exec (cumulative)
    queued: tuple      # [R] lane sums of fill_count
    outboxed: tuple    # [R] lane sums of outbox.count
    drops: tuple       # [R] lane shares of events+outbox overflow
    flushed: tuple     # [R] quarantine-flush counts (lanes.flushed)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def lane_sample(sim, *, wstart: int, wend: int) -> LaneWindowSample:
    """Read the per-lane ledger off a lane-isolated sim at a window
    barrier. Requires the attribution planes (core.lanes.attach) —
    drops cannot be attributed per lane from the scalars alone."""
    lanes = sim.lanes
    R = lanes.replicas
    q = sim.events

    def ls(x):
        return tuple(int(v) for v in
                     np.asarray(x, dtype=np.int64).reshape(R, -1).sum(1))

    ev_h = np.asarray(q.overflow_h, np.int64)
    ob_h = np.asarray(sim.outbox.overflow_h, np.int64)
    return LaneWindowSample(
        wstart=int(wstart), wend=int(wend),
        pushed=ls(q.next_seq),
        processed=ls(sim.net.ctr_events_exec),
        queued=ls(q.fill_count()),
        outboxed=ls(sim.outbox.count),
        drops=tuple(int(a + b) for a, b in
                    zip(ev_h.reshape(R, -1).sum(1),
                        ob_h.reshape(R, -1).sum(1))),
        flushed=tuple(int(v) for v in np.asarray(lanes.flushed)),
    )


def lane_check(samples) -> list[str]:
    """Validate a per-lane sample sequence: the global check()'s
    conservation rules applied to every lane independently, with the
    flushed term on the accounted side. A healthy lane must stay EXACT
    even while a neighbor lane overflows and is quarantined — that is
    the blast-radius containment oracle."""
    errors: list[str] = []
    prev = None
    for i, s in enumerate(samples):
        where = f"window[{i}] (wstart={s.wstart})"
        R = len(s.pushed)
        for r in range(R):
            lw = f"{where} lane[{r}]"
            if prev is not None and s.pushed[r] < prev.pushed[r]:
                errors.append(
                    f"{lw}: pushed count went backwards "
                    f"({prev.pushed[r]} -> {s.pushed[r]})")
            if prev is not None and s.processed[r] < prev.processed[r]:
                errors.append(
                    f"{lw}: processed count went backwards "
                    f"({prev.processed[r]} -> {s.processed[r]})")
            accounted = (s.processed[r] + s.queued[r] + s.outboxed[r]
                         + s.flushed[r])
            if s.drops[r] == 0:
                if s.pushed[r] != accounted:
                    errors.append(
                        f"{lw}: conservation violated — pushed="
                        f"{s.pushed[r]} != processed={s.processed[r]} "
                        f"+ queued={s.queued[r]} + outboxed="
                        f"{s.outboxed[r]} + flushed={s.flushed[r]}")
            else:
                # same degradation as check(): drops mix seq-carrying
                # and seq-less losses, so bounds only
                if not (accounted <= s.pushed[r]
                        <= accounted + s.drops[r]):
                    errors.append(
                        f"{lw}: pushed={s.pushed[r]} outside "
                        f"[{accounted}, {accounted + s.drops[r]}] "
                        f"(drops={s.drops[r]})")
        prev = s
    return errors


def stitch(before: list, after: list, resume_time: int) -> list:
    """Splice sample sequences across a kill/heal boundary: the resumed
    attempt replays from its checkpoint, so `before` samples at or
    past the resume point are superseded by the replay (bit-identical
    by the checkpoint contract — but the replayed copies carry the
    post-resume cumulative counters, so keep exactly one copy)."""
    kept = [s for s in before if s.wstart < resume_time]
    return kept + list(after)
