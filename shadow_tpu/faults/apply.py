"""Window-boundary fault application — the device half of faults/.

Design rule: every fault effect is a *pure function of (compiled plan
constants, wend)*. Each window boundary replays all records with
`t_ns < wend` over the pristine boot tables; host liveness is the
count of crash records minus restart records so far. No cursor, no
sticky fault state in Sim — which is exactly what makes the three
determinism contracts free:

- checkpoint/resume: nothing to save. The restored sim's (possibly
  fault-mutated) tables are overwritten from base on the very next
  boundary, so a resume inside a fault window is bit-identical.
- sharding: the plan and base tables are replicated constants and the
  wend sequence is identical on every shard, so every chip computes
  the same replicated tables without any collective.
- no plan -> no cost: make_fault_fn returns None and the engine's
  window body is unchanged.

Replay is O(records) scatter work per *window boundary* (not per
packet, not per micro-step); plans are human-written schedules of a
handful to a few hundred records, so this is noise next to the window
body itself.

Exactness: effects materialize when a window boundary passes the
record time. seed_wakeups pins a pending event at every record time,
so the conservative advance rule (next window starts at the min
pending event time) guarantees a boundary lands at or before each
fault — a fault is never skipped by a sparse-workload window jump,
and in dense workloads it quantizes to at most one window early
(documented in docs/6-robustness.md).

Crash semantics: while a host's crash count exceeds its restart
count, every boundary (idempotently) flushes its event row — sparing
PROC_START and FAULT_WAKEUP so the seeded restart survives — and
restores its per-host netstack/app/TCP rows to their boot values
(fresh process image, boot-time binds recreated exactly as app setup
made them). RNG state and observability counters are deliberately
*not* rolled back: a restarted host continues its random stream and
keeps its lifetime drop/byte counts, like a rebooted machine behind
the same NIC counters.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.events import EventKind
from shadow_tpu.net.state import NetState, REPLICATED_FIELDS
from shadow_tpu.faults.plan import (
    FaultKind,
    FaultPlan,
    HOST_KINDS,
    PPM,
    compile_plan,
    validate_records,
)

I32 = jnp.int32
F32 = jnp.float32

# NetState per-host fields that survive a crash. Everything else with
# a leading host dim is restored to its boot value while the host is
# down (static config fields are equal to boot, so restoring them is
# the identity).
_CRASH_KEEP = frozenset(REPLICATED_FIELDS) | {
    "lane_id", "rng_keys", "rng_ctr", "rq_overflow", "rq_overflow_h",
    "last_drop_status",
}


def _crash_keep(name: str) -> bool:
    return (name in _CRASH_KEEP or name.startswith("ctr_")
            or name.startswith("cap_"))


def _down_mask(leaf, down):
    """Broadcast down [H] bool against a [H, ...] leaf."""
    return down.reshape(down.shape + (1,) * (leaf.ndim - 1))


def _boot_rows(boot_leaf, lane_id):
    """Local boot rows of a replicated [GH, ...] boot capture (gather
    through lane_id so the same constant serves serial and shard_map
    bodies alike — identity gather when unsharded)."""
    return jnp.asarray(boot_leaf)[lane_id]


def make_table_fn(plan: FaultPlan, boot_sim):
    """Compile just `plan`'s latency/reliability table replay into
    ``table_fn(t) -> (lat, rel)``: the [V,V] tables with every record
    ``t_ns < t`` applied (later records win; ties in plan order). A
    pure function of the plan and the boot tables — no live sim state.
    make_fault_fn builds its rewrite on it; the adaptive window rule
    (engine.make_wend_fn) calls it at ``wstart + 1`` so a window that
    starts exactly at a record time is sized from the POST-record
    tables (the live sim tables are only rewritten inside step_window,
    after the window span was already chosen). Returns None for an
    empty plan."""
    if plan is None or plan.n == 0:
        return None

    base_lat = np.asarray(boot_sim.net.latency_ns)
    base_rel = np.asarray(boot_sim.net.reliability)
    V = base_rel.shape[0]
    if plan.num_vertices and plan.num_vertices != V:
        raise ValueError(f"plan compiled for {plan.num_vertices} vertices, "
                         f"topology has {V}")

    t_c = jnp.asarray(plan.t_ns)
    k_c = jnp.asarray(plan.kind)
    a_c = jnp.asarray(plan.a)
    b_c = jnp.asarray(plan.b)
    v_c = jnp.asarray(plan.value)
    lat0 = jnp.asarray(base_lat)
    rel0 = jnp.asarray(base_rel)
    ri = jnp.arange(V, dtype=I32)[:, None]
    ci = jnp.arange(V, dtype=I32)[None, :]

    def table_fn(wend):
        def body(i, tables):
            lat, rel = tables
            act = t_c[i] < wend
            k, a, b, v = k_c[i], a_c[i], b_c[i], v_c[i]
            # b is -1 for single-endpoint kinds, so on_ab is all-false
            # for them (ri/ci are >= 0) and the update is a no-op.
            on_ab = ((ri == a) & (ci == b)) | ((ri == b) & (ci == a))
            on_cross = (ri == a) | (ci == a)
            is_vertex = (k == FaultKind.PARTITION) | (k == FaultKind.HEAL)
            touch = act & (k != FaultKind.LATENCY) & jnp.where(
                is_vertex, on_cross, on_ab)
            new_rel = jnp.select(
                [(k == FaultKind.LINK_DOWN) | (k == FaultKind.PARTITION),
                 (k == FaultKind.LINK_UP) | (k == FaultKind.HEAL),
                 k == FaultKind.LOSS],
                [jnp.zeros_like(rel), rel0,
                 jnp.full_like(rel, 1.0 - v.astype(F32) / PPM)],
                rel)
            rel = jnp.where(touch, new_rel, rel)
            lat = jnp.where(act & (k == FaultKind.LATENCY) & on_ab,
                            lat0 + v, lat)
            return lat, rel

        lat, rel = jax.lax.fori_loop(0, plan.n, body, (lat0, rel0))
        return lat, rel

    return table_fn


def make_fault_fn(plan: FaultPlan, boot_sim):
    """Compile `plan` against the *boot* sim (the bundle's pristine
    state — never a restored checkpoint, whose tables may already be
    fault-mutated) into `fault_fn(sim, wend) -> sim`, applied by
    core.engine.step_window before each window. Returns None for an
    empty plan so the engine body is untouched."""
    if plan is None or plan.n == 0:
        return None

    base_rel = np.asarray(boot_sim.net.reliability)
    GH = int(boot_sim.net.host_ip.shape[0])
    V = base_rel.shape[0]

    k_np = plan.kind
    rel_kinds = np.isin(k_np, (FaultKind.LINK_DOWN, FaultKind.LINK_UP,
                               FaultKind.LOSS, FaultKind.PARTITION,
                               FaultKind.HEAL))
    lat_kinds = k_np == FaultKind.LATENCY
    has_crash = bool(np.isin(k_np, HOST_KINDS).any())

    t_c = jnp.asarray(plan.t_ns)
    k_c = jnp.asarray(plan.kind)

    _replay_tables = make_table_fn(plan, boot_sim)

    # Boot captures for the crash reset — replicated constants whose
    # local rows are gathered through lane_id inside the (possibly
    # shard_map'd) body.
    if has_crash:
        a_c = jnp.asarray(plan.a)
        boot_net = {
            f.name: jnp.asarray(getattr(boot_sim.net, f.name))
            for f in dataclasses.fields(NetState)
            if not _crash_keep(f.name)
            and getattr(boot_sim.net, f.name) is not None
        }
        boot_app = jax.tree.map(jnp.asarray, boot_sim.app)
        boot_tcp = jax.tree.map(jnp.asarray, boot_sim.tcp)
        crash_idx_base = jnp.where(k_c == FaultKind.CRASH, a_c, GH)
        restart_idx_base = jnp.where(k_c == FaultKind.RESTART, a_c, GH)

    def _down_vector(wend):
        """down[h] = more crashes than restarts with t < wend."""
        act = t_c < wend
        crashes = jnp.zeros((GH + 1,), I32).at[
            jnp.where(act, crash_idx_base, GH)].add(1)[:GH]
        restarts = jnp.zeros((GH + 1,), I32).at[
            jnp.where(act, restart_idx_base, GH)].add(1)[:GH]
        return crashes > restarts

    def _crash_reset(sim, down):
        lane = sim.net.lane_id
        q = sim.events
        adm = getattr(sim, "admission", None)
        if adm is not None:
            # resident program (core/lanes.LaneAdmission): a crash or
            # restart landing in a FREE lane must be a no-op — sparing
            # its PROC_START and restoring boot rows would resurrect a
            # lane the lease table already returned to the pool (the
            # boot image carries live app state). Only hosts in leased
            # lanes reset; free-lane rows stay flushed/stale until the
            # next implant overwrites them. The admission planes
            # themselves ride untouched, like rq_overflow_h: they are
            # lease bookkeeping, not per-host state.
            from shadow_tpu.core.lanes import host_mask

            down = down & host_mask(adm.active, q.time.shape[0])
        spare = ((q.kind == EventKind.PROC_START)
                 | (q.kind == EventKind.FAULT_WAKEUP))
        keep = ~down[:, None] | spare
        q = q.replace(
            time=jnp.where(keep, q.time, simtime.INVALID),
            kind=jnp.where(keep, q.kind, 0),
            src=jnp.where(keep, q.src, 0),
            seq=jnp.where(keep, q.seq, 0),
            words=jnp.where(keep[:, :, None], q.words, 0),
        )
        net_upd = {}
        for name, boot in boot_net.items():
            cur = getattr(sim.net, name)
            fresh = _boot_rows(boot, lane)
            net_upd[name] = jnp.where(_down_mask(cur, down), fresh, cur)

        def _reset_tree(cur_tree, boot_tree):
            if cur_tree is None:
                return None
            def leaf(cur, boot):
                if cur.ndim == 0 or boot.shape[0] != GH:
                    return cur
                fresh = _boot_rows(boot, lane)
                return jnp.where(_down_mask(cur, down), fresh, cur)
            return jax.tree.map(leaf, cur_tree, boot_tree)

        return sim.replace(
            events=q,
            net=sim.net.replace(**net_upd),
            app=_reset_tree(sim.app, boot_app),
            tcp=_reset_tree(sim.tcp, boot_tcp),
        )

    def fault_fn(sim, wend):
        if rel_kinds.any() or lat_kinds.any():
            lat, rel = _replay_tables(wend)
            net = sim.net
            if lat_kinds.any():
                net = net.replace(latency_ns=lat)
            if rel_kinds.any():
                net = net.replace(reliability=rel)
            sim = sim.replace(net=net)
        if has_crash:
            down_g = _down_vector(wend)
            down_l = down_g[sim.net.lane_id]
            sim = jax.lax.cond(jnp.any(down_g),
                               lambda s: _crash_reset(s, down_l),
                               lambda s: s, sim)
        return sim

    return fault_fn


def seed_wakeups(sim, records, vertex_of_host):
    """Push one pending event per fault record so a window boundary
    lands at (or before) every fault time. CRASH/link/partition kinds
    seed an inert FAULT_WAKEUP; RESTART seeds a real PROC_START at the
    restarted host so its app re-runs its start handler (fresh boot
    image courtesy of the crash reset). Link-level records wake the
    first host attached to vertex `a` (any host pins the global window
    sequence; host 0 if the vertex is unattached)."""
    from shadow_tpu.core.events import emit_words, push_rows

    vertex_of_host = np.asarray(vertex_of_host)
    H = int(vertex_of_host.shape[0])
    for r in records:
        if r.kind == FaultKind.RESTART:
            host, kind = int(r.a), EventKind.PROC_START
        elif r.kind == FaultKind.CRASH:
            host, kind = int(r.a), EventKind.FAULT_WAKEUP
        else:
            att = np.flatnonzero(vertex_of_host == r.a)
            host = int(att[0]) if att.size else 0
            kind = EventKind.FAULT_WAKEUP
        mask = np.zeros(H, bool)
        mask[host] = True
        m = jnp.asarray(mask)
        q = push_rows(
            sim.events,
            m,
            jnp.full((H,), r.t_ns, simtime.DTYPE),
            jnp.full((H,), kind, I32),
            jnp.arange(H, dtype=I32),
            sim.events.next_seq,
            emit_words(0, num_hosts=H),
        )
        q = q.replace(next_seq=q.next_seq + m.astype(I32))
        sim = sim.replace(events=q)
    return sim


def install(bundle, records):
    """Attach a fault schedule to a built SimBundle: validate +
    compile the plan, seed the wakeup events into bundle.sim, and
    stash the plan on the bundle for fault_fn_for / runners. Call
    before the first window runs (loader does this at load time)."""
    records = list(records)
    GH = int(bundle.sim.net.host_ip.shape[0])
    V = int(np.asarray(bundle.sim.net.reliability).shape[0])
    plan = compile_plan(records, num_hosts=GH, num_vertices=V)
    errors, _ = validate_records(records, num_hosts=GH, num_vertices=V,
                                 min_jump_ns=bundle.min_jump)
    if errors:  # compile_plan already raised; belt and braces
        raise ValueError("\n".join(errors))
    bundle.sim = seed_wakeups(bundle.sim, records,
                              bundle.sim.net.vertex_of_host)
    bundle.fault_plan = plan
    return plan


def fault_fn_for(bundle):
    """fault_fn for a bundle previously passed through install(), or
    None when it carries no plan. Must be given the *boot* bundle —
    base tables are captured from bundle.sim before any window ran."""
    if getattr(bundle, "fault_plan", None) is None:
        return None
    return make_fault_fn(bundle.fault_plan, bundle.sim)
