"""Deterministic fault injection + run health + supervised recovery.

- plan: record types, validation, JSON/config parsing, compilation
  (numpy-only — safe for offline tools).
- apply: compiles a plan into the window-boundary fault_fn the engine
  runs (stateless table replay; crash resets).
- health: RunHealth latches folded from the engine's sticky counters.
- supervisor: checkpointed retry loop the CLI's --supervise uses,
  with capacity escalation and preemption-safe resume chains.
- escalate: latch -> capacity-knob mapping, grow policy, and the
  checkpoint-into-grown-shapes transplanter.
- conserve: per-window conservation-invariant checker (the chaos
  soak harness's oracle).
"""

from shadow_tpu.faults.plan import (  # noqa: F401
    FaultKind,
    FaultPlan,
    FaultRecord,
    compile_plan,
    records_from_config,
    records_from_json,
    validate_records,
)
from shadow_tpu.faults.apply import (  # noqa: F401
    fault_fn_for,
    install,
    make_fault_fn,
    seed_wakeups,
)
from shadow_tpu.faults.health import RunHealth, gather  # noqa: F401
from shadow_tpu.faults.supervisor import (  # noqa: F401
    DeadlineExceeded,
    LatchTrip,
    Preempted,
    SupervisorResult,
    run_supervised,
)
from shadow_tpu.faults.escalate import (  # noqa: F401
    Escalation,
    EscalationPolicy,
    GrowBudgetExceeded,
    transplant,
)
from shadow_tpu.faults import conserve  # noqa: F401
