"""Capacity escalation: turn a fatal overflow latch into a bigger run.

The reference never overflows — its heaps grow (shadow's C event queue
is a dynamic splay tree); our static device shapes trade that away for
compiled-program speed, so an undersized capacity is a *fatal* latch
(faults/health.py). This module closes the loop the way an elastic
trainer regrows its mesh: map the tripped latch to the capacity knob
that sizes it, double the knob (bounded by a grow budget), rebuild the
bundle at the new shapes, and TRANSPLANT the last clean pre-trip
checkpoint into the grown arrays.

Why transplanting is exact and not best-effort: the supervisor gathers
health BEFORE saving a snapshot, so every snapshot on disk predates
the first dropped event — its contents are a prefix the larger
capacity would have produced bit-for-bit (capacity only changes
behavior at the first drop). Padding that prefix with empty slots on
the grown axis therefore reproduces, byte for byte on every logical
slot, the state of a from-scratch run at the grown capacity — modulo
one *layout* (not content) freedom: the router ring's modular head
addressing, which transplant() canonicalizes to head 0.

Empty-slot encodings (must match core/events.py create() and
net/state.py make_net_state): `.time` planes are simtime.INVALID,
`.dst` planes are -1, everything else zero-fills.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime

# fatal overflow latch (faults/health.py RunHealth field) -> the
# NetConfig capacity knob that sizes the overflowed array. The knob
# names are loader override keys, so a rebuild is just
# bundle.rebuild({knob: new}).
LATCH_KNOBS = {
    "events_overflow": "event_capacity",
    "outbox_overflow": "outbox_capacity",
    "rq_overflow": "router_ring",
}


class GrowBudgetExceeded(RuntimeError):
    """The escalation policy ran out of doublings — the run falls back
    to the plain retry path (and then to the structured failure
    report naming the knob)."""


@dataclasses.dataclass(frozen=True)
class Escalation:
    """One healed capacity trip, recorded in checkpoint extras and the
    run manifest (`escalations` block)."""

    time_ns: int   # window start the heal resumed from
    latch: str     # RunHealth field that tripped
    knob: str      # NetConfig knob grown
    old: int
    new: int

    def as_dict(self) -> dict:
        return {"time_ns": self.time_ns, "latch": self.latch,
                "knob": self.knob, "from": self.old, "to": self.new}

    @staticmethod
    def from_dict(d: dict) -> "Escalation":
        return Escalation(time_ns=int(d["time_ns"]), latch=d["latch"],
                          knob=d["knob"], old=int(d["from"]),
                          new=int(d["to"]))


@dataclasses.dataclass(frozen=True)
class EscalationPolicy:
    """Geometric regrowth: each trip doubles the tripped knob(s).
    `max_grow` bounds the total number of doublings across the whole
    run (including a resumed chain's earlier heals) — HBM is finite
    and a workload that keeps outrunning doubling capacity needs an
    operator, not another doubling."""

    factor: int = 2
    max_grow: int = 8


def overflowed_latches(health) -> list[str]:
    """Which capacity latches tripped, in LATCH_KNOBS order (stable:
    escalation records and grown knobs are deterministic)."""
    return [k for k in LATCH_KNOBS if int(getattr(health, k)) > 0]


def plan_growth(health, capacities: dict, policy: EscalationPolicy,
                grows_used: int, *, time_ns: int,
                ) -> tuple[dict, list[Escalation]]:
    """Map tripped latches to capacity overrides. `capacities` is the
    current build's knob values (utils.checkpoint.capacities_of_sim).
    Raises GrowBudgetExceeded when the doublings would exceed
    policy.max_grow, and ValueError when no *capacity* latch tripped
    (stall/regression trips are not healable by growing anything)."""
    latches = overflowed_latches(health)
    if not latches:
        raise ValueError("no capacity latch tripped — escalation "
                         "cannot heal this failure")
    if grows_used + len(latches) > policy.max_grow:
        raise GrowBudgetExceeded(
            f"healing {latches} needs {len(latches)} more doubling(s) "
            f"but {grows_used}/{policy.max_grow} of the grow budget "
            f"is spent (--max-grow)")
    overrides: dict = {}
    events: list[Escalation] = []
    from shadow_tpu.compile.buckets import quantize_pow2

    for latch in latches:
        knob = LATCH_KNOBS[latch]
        old = int(capacities[knob])
        # grow to the NEXT POWER-OF-TWO BUCKET at or above old*factor:
        # a pow2 capacity doubles exactly as before, a bespoke one
        # (say 24 -> 48 -> 64) lands on a bucket the AOT program
        # store has likely already compiled (compile/buckets.py), so
        # the heal restarts on a warm program instead of paying a
        # bespoke-shape trace
        new = quantize_pow2(old * policy.factor)
        overrides[knob] = new
        events.append(Escalation(time_ns=int(time_ns), latch=latch,
                                 knob=knob, old=old, new=new))
    return overrides, events


# LaneHealth trip bit (core/lanes.py TRIP_*) -> the capacity knob a
# lane-local regrow doubles when the fleet requeues the lane as a
# standalone job. Stall/regression bits map to no knob (not healable
# by growing anything — the requeue retries at the same shapes).
TRIP_BIT_KNOBS = {
    1: "event_capacity",   # TRIP_EVENTS
    2: "outbox_capacity",  # TRIP_OUTBOX
    4: "router_ring",      # TRIP_RQ
}


def plan_lane_regrow(trip_bits: int, capacities: dict,
                     factor: int = 2) -> dict:
    """Capacity overrides for requeuing a quarantined lane as its own
    job: every capacity knob named by the lane's trip bits, doubled —
    the lane-local analog of plan_growth, without the shared program's
    grow budget (the requeued job budgets its own attempts)."""
    from shadow_tpu.compile.buckets import quantize_pow2

    overrides = {}
    for bit, knob in TRIP_BIT_KNOBS.items():
        if int(trip_bits) & bit:
            # next-bucket regrow, same rule as plan_growth: the
            # requeued lane-job lands on a warm program bucket
            overrides[knob] = quantize_pow2(
                int(capacities[knob]) * int(factor))
    return overrides


def extract_lane(leaves: dict, meta: dict, lane: int,
                 replicas: int) -> tuple[dict, dict]:
    """Checkpoint lane surgery: slice one lane's share out of a packed
    snapshot's leaves (utils.checkpoint.load_leaves format).

    Every leaf with a leading host axis is cut to the lane's
    contiguous host block; [R]-shaped lane-health planes (".lanes.")
    are cut to the lane's entry; replicated whole-sim state (telem /
    inject planes, [V,V] tables, scalars) rides along whole.

    The result is a salvage ARTIFACT: post-mortem evidence plus the
    requeue context the fleet needs (what tripped, at which time, at
    what shapes). It is NOT a bit-resumable standalone checkpoint —
    per-host identity state (rng keys, IPs, lane_id) is seeded by
    global host index, so the requeued job re-runs the scenario fresh
    at regrown capacities instead of resuming the slice."""
    R = int(replicas)
    lane = int(lane)
    if not 0 <= lane < R:
        raise ValueError(f"lane {lane} out of range for replicas={R}")
    caps = dict(meta.get("capacities") or {})
    H = caps.get("num_hosts")
    if H is None:
        hk = next((k for k in leaves if k.endswith(".rq_head")), None)
        H = leaves[hk].shape[0] if hk is not None else None
    if H is None or H % R != 0:
        raise ValueError(
            f"cannot slice lane {lane}/{R} out of num_hosts={H}")
    hs = H // R
    lo, hi = lane * hs, (lane + 1) * hs
    out = {}
    for key, arr in leaves.items():
        a = np.asarray(arr)
        if key.startswith((".telem", ".inject", ".flows")):
            # whole-sim rings (flow ring rows are samples, not hosts —
            # its capacity could collide with H, so never host-slice)
            out[key] = a
        elif key.startswith((".lanes", ".admission")):
            # [R]-shaped lane-health / lease planes: the lane's entry
            out[key] = a[lane:lane + 1] if a.ndim else a
        elif a.ndim and a.shape[0] == H:
            out[key] = a[lo:hi]
        else:
            out[key] = a
    caps["num_hosts"] = hs
    lane_meta = {
        "time_ns": int(meta.get("time_ns", 0)),
        "capacities": caps,
        "lane": lane,
        "replicas": R,
        "packed_num_hosts": int(H),
        "extra": dict(meta.get("extra") or {}),
    }
    return out, lane_meta


def _fill_for(key: str):
    """Empty-slot encoding for a padded region of leaf `key`."""
    if key.endswith(".time"):
        return simtime.INVALID
    if key.endswith(".dst"):
        return -1
    return 0


def _rotate_router_ring(leaves: dict) -> dict:
    """Canonicalize the router ring to head 0 before tail-padding.

    rq slots address as (head + i) % R; growing R re-maps every
    wrapped slot, so naive tail-padding would interleave live and
    empty entries. Rotating each row so logical slot 0 sits at
    physical 0 (and zeroing rq_head) preserves the ring's *content*
    exactly while making tail-padding correct. rq_count is modular-
    address independent and stays put."""
    keys = {k: k for k in leaves}
    src_k = next((k for k in keys if k.endswith(".rq_src")), None)
    head_k = next((k for k in keys if k.endswith(".rq_head")), None)
    if src_k is None or head_k is None:
        return leaves
    head = leaves[head_k]
    if not np.any(head):
        return leaves  # already canonical
    R = leaves[src_k].shape[1]
    idx = (head[:, None] + np.arange(R)[None, :]) % R  # [H, R]
    out = dict(leaves)
    for k in keys:
        if k.endswith((".rq_src", ".rq_enq_ts", ".rq_words")):
            arr = leaves[k]
            out[k] = np.take_along_axis(
                arr, idx.reshape(idx.shape + (1,) * (arr.ndim - 2)),
                axis=1)
    out[head_k] = np.zeros_like(head)
    return out


def transplant(leaves: dict, meta: dict, template_sim):
    """Embed a snapshot's leaves into a (possibly larger) template.

    For every template leaf: identical shape -> the checkpoint bytes,
    verbatim; a grown trailing region -> checkpoint contents at the
    leading corner over an empty-slot canvas. Anything else — shrunk
    axis, dtype change, rank change, missing leaf — refuses loudly,
    naming the exact leaf. Returns (sim, time_ns, extra) exactly like
    checkpoint.load()."""
    import jax

    caps = meta.get("capacities") or {}
    flat, _ = jax.tree_util.tree_flatten_with_path(template_sim)
    tmap = {jax.tree_util.keystr(p): np.asarray(l) for p, l in flat}

    # the host axis never grows: events re-key by host index, so a
    # different H is a different simulation, not a bigger one
    th = next((l.shape[0] for k, l in tmap.items()
               if k.endswith(".rq_head")), None)
    if caps.get("num_hosts") is not None and th is not None \
            and caps["num_hosts"] != th:
        raise ValueError(
            f"snapshot has num_hosts={caps['num_hosts']}, template "
            f"has {th} — the host axis cannot be transplanted")

    ring_grew = (caps.get("router_ring") is not None and th is not None
                 and any(k.endswith(".rq_src")
                         and l.shape[1] > caps["router_ring"]
                         for k, l in tmap.items()))
    if ring_grew:
        leaves = _rotate_router_ring(leaves)

    out = []
    for pth, tleaf in flat:
        key = jax.tree_util.keystr(pth)
        if key not in leaves:
            raise ValueError(f"snapshot missing leaf {key} "
                             f"(config mismatch?)")
        arr = np.asarray(leaves[key])
        t = np.asarray(tleaf)
        if arr.dtype != t.dtype or arr.ndim != t.ndim:
            raise ValueError(
                f"cannot transplant leaf {key}: snapshot is "
                f"{arr.shape}/{arr.dtype}, template is "
                f"{t.shape}/{t.dtype}")
        if arr.shape == t.shape:
            out.append(jnp.asarray(arr))
            continue
        if any(a > b for a, b in zip(arr.shape, t.shape)):
            raise ValueError(
                f"cannot transplant leaf {key}: snapshot axis "
                f"{arr.shape} exceeds template {t.shape} — capacities "
                f"only grow (resuming into a shrunken config loses "
                f"state)")
        canvas = np.full(t.shape, _fill_for(key), dtype=t.dtype)
        canvas[tuple(slice(0, s) for s in arr.shape)] = arr
        out.append(jnp.asarray(canvas))
    treedef = jax.tree_util.tree_structure(template_sim)
    sim = jax.tree_util.tree_unflatten(treedef, out)
    return sim, meta["time_ns"], meta.get("extra", {})
