"""Fault plans — deterministic dynamic-failure schedules.

The reference Shadow only models *static* per-path reliability
(topology.c:1442-1460 -> routing/topology.py); real long-running
workloads are defined by *dynamic* failure: links flapping, loss and
latency changing, hosts crashing and rejoining. A fault plan is a
time-sorted, fixed-shape array of records `(t_ns, kind, a, b, value)`
compiled once per run and applied at window boundaries (faults/apply.py)
by rewriting the replicated latency/reliability tables the NIC already
reads — no per-packet branching, zero cost when the plan is empty.

This module is the host-side half: record types, validation, JSON
round-trip, and compilation to the fixed numpy arrays apply.py embeds
as device constants. It deliberately imports no jax so offline tooling
(tools/faultplan_lint.py) stays light.

Index vocabulary (the compiled form):
- link-level kinds (LINK_DOWN/LINK_UP/LOSS/LATENCY) address a pair of
  topology *vertices* (a, b) — the same [V,V] coordinates as
  NetState.latency_ns / reliability;
- PARTITION/HEAL address a single vertex `a` (its whole row+column);
- CRASH/RESTART address a *host* index `a`.
Config-level names (XML <fault> elements) are resolved to these
indices by records_from_config once the bundle placement is known.

`value` encoding is integral so one i64 column serves every kind:
LOSS carries loss probability in parts-per-million; LATENCY carries
the *added* latency in ns (0 restores the base path latency —
negative deltas are rejected: shrinking a path below the precomputed
minimum would invalidate the conservative window).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import json

import numpy as np


class FaultKind:
    NONE = 0
    LINK_DOWN = 1   # (a,b) path reliability -> 0, both directions
    LINK_UP = 2     # (a,b) path reliability -> base, both directions
    LOSS = 3        # (a,b) loss override; value = loss ppm
    LATENCY = 4     # (a,b) latency spike; value = added ns (0 = base)
    CRASH = 5       # host a: queue flush + socket reset while down
    RESTART = 6     # host a rejoins; seeds a PROC_START at t
    PARTITION = 7   # vertex a isolated (row+col reliability -> 0)
    HEAL = 8        # vertex a's row+col reliability -> base


KIND_NAMES = {
    "linkdown": FaultKind.LINK_DOWN, "link_down": FaultKind.LINK_DOWN,
    "link-down": FaultKind.LINK_DOWN,
    "linkup": FaultKind.LINK_UP, "link_up": FaultKind.LINK_UP,
    "link-up": FaultKind.LINK_UP,
    "loss": FaultKind.LOSS,
    "latency": FaultKind.LATENCY,
    "crash": FaultKind.CRASH,
    "restart": FaultKind.RESTART,
    "partition": FaultKind.PARTITION,
    "heal": FaultKind.HEAL,
}

NAME_OF_KIND = {
    FaultKind.LINK_DOWN: "linkdown", FaultKind.LINK_UP: "linkup",
    FaultKind.LOSS: "loss", FaultKind.LATENCY: "latency",
    FaultKind.CRASH: "crash", FaultKind.RESTART: "restart",
    FaultKind.PARTITION: "partition", FaultKind.HEAL: "heal",
}

LINK_KINDS = (FaultKind.LINK_DOWN, FaultKind.LINK_UP,
              FaultKind.LOSS, FaultKind.LATENCY)
VERTEX_KINDS = (FaultKind.PARTITION, FaultKind.HEAL)
HOST_KINDS = (FaultKind.CRASH, FaultKind.RESTART)

PPM = 1_000_000


@dataclass
class FaultRecord:
    t_ns: int
    kind: int
    a: int
    b: int = -1
    value: int = 0


@dataclass
class FaultPlan:
    """Compiled, time-sorted plan: parallel numpy columns, fixed shape.
    apply.make_fault_fn embeds these as device constants."""

    t_ns: np.ndarray    # [N] i64
    kind: np.ndarray    # [N] i32
    a: np.ndarray       # [N] i32
    b: np.ndarray       # [N] i32
    value: np.ndarray   # [N] i64
    num_hosts: int = 0
    num_vertices: int = 0

    @property
    def n(self) -> int:
        return int(self.t_ns.shape[0])


def validate_records(records, *, num_hosts=None, num_vertices=None,
                     min_jump_ns=None):
    """Offline plan validation. Returns (errors, warnings) as lists of
    strings; compile_plan raises on any error, tools/faultplan_lint.py
    prints both. Range checks run only when the bound is known."""
    errors: list[str] = []
    warnings: list[str] = []
    last_t = None
    down: dict[int, int] = {}   # host -> index of the unmatched crash
    for i, r in enumerate(records):
        where = f"record {i} (t={r.t_ns})"
        if r.t_ns < 0:
            errors.append(f"{where}: negative time")
        if last_t is not None and r.t_ns < last_t:
            errors.append(f"{where}: times not sorted "
                          f"(previous was {last_t})")
        last_t = r.t_ns
        if r.kind not in NAME_OF_KIND:
            errors.append(f"{where}: unknown kind {r.kind}")
            continue
        if min_jump_ns and r.t_ns % min_jump_ns:
            warnings.append(
                f"{where}: not aligned to the {min_jump_ns} ns window; "
                f"the engine clamps the enclosing window to END at the "
                f"record (exact fault timing), at the cost of one "
                f"shortened window per record")
        if r.kind in LINK_KINDS:
            if r.b < 0:
                errors.append(f"{where}: {NAME_OF_KIND[r.kind]} needs "
                              f"both endpoints a and b")
            for end in (r.a, r.b):
                if num_vertices is not None and not (
                        0 <= end < num_vertices):
                    errors.append(f"{where}: vertex {end} out of range "
                                  f"[0, {num_vertices})")
        elif r.kind in VERTEX_KINDS:
            if num_vertices is not None and not (0 <= r.a < num_vertices):
                errors.append(f"{where}: vertex {r.a} out of range "
                              f"[0, {num_vertices})")
        else:  # HOST_KINDS
            if num_hosts is not None and not (0 <= r.a < num_hosts):
                errors.append(f"{where}: host {r.a} out of range "
                              f"[0, {num_hosts})")
            if r.kind == FaultKind.CRASH:
                if r.a in down:
                    errors.append(f"{where}: host {r.a} crashed again "
                                  f"at record {down[r.a]} without a "
                                  f"restart in between")
                down[r.a] = i
            else:
                if r.a not in down:
                    errors.append(f"{where}: restart of host {r.a} "
                                  f"without a preceding crash")
                down.pop(r.a, None)
        if r.kind == FaultKind.LOSS and not (0 <= r.value <= PPM):
            errors.append(f"{where}: loss value {r.value} ppm outside "
                          f"[0, {PPM}]")
        if r.kind == FaultKind.LATENCY and r.value < 0:
            errors.append(
                f"{where}: negative latency delta {r.value} ns would "
                f"shrink a path below the precomputed minimum and "
                f"break the conservative window")
    return errors, warnings


def compile_plan(records, *, num_hosts: int,
                 num_vertices: int) -> FaultPlan:
    """Validate and freeze records into the fixed-shape columns. The
    input order is kept (validation enforces time-sortedness, and a
    stable order is part of the determinism contract: records at equal
    times apply in plan order on every shard)."""
    records = list(records)
    errors, _ = validate_records(records, num_hosts=num_hosts,
                                 num_vertices=num_vertices)
    if errors:
        raise ValueError("invalid fault plan:\n  " + "\n  ".join(errors))
    return FaultPlan(
        t_ns=np.array([r.t_ns for r in records], np.int64),
        kind=np.array([r.kind for r in records], np.int32),
        a=np.array([r.a for r in records], np.int32),
        b=np.array([r.b for r in records], np.int32),
        value=np.array([r.value for r in records], np.int64),
        num_hosts=num_hosts, num_vertices=num_vertices,
    )


def _value_raw(kind: int, value) -> int:
    """JSON/XML `value` is human-scaled (loss as a probability,
    latency in seconds); the record column is integral."""
    if value is None:
        return 0
    if kind == FaultKind.LOSS:
        return int(round(float(value) * PPM))
    if kind == FaultKind.LATENCY:
        return int(round(float(value) * 1e9))
    return int(value)


def records_from_json(obj) -> list[FaultRecord]:
    """Parse the standalone JSON plan format (bench.py --faults,
    tools/faultplan_lint.py):

      {"faults": [{"time_s": 1.5, "kind": "linkdown", "a": 0, "b": 1},
                  {"t_ns": 2500000000, "kind": "loss", "a": 0, "b": 1,
                   "value": 0.05}, ...]}

    a/b are vertex indices for link kinds, host indices for
    crash/restart. `value` is a loss probability or seconds of added
    latency."""
    if isinstance(obj, (str, bytes)):
        obj = json.loads(obj)
    out = []
    for e in obj.get("faults", []):
        kname = str(e.get("kind", "")).lower()
        if kname not in KIND_NAMES:
            raise ValueError(f"unknown fault kind '{kname}' "
                             f"(known: {sorted(set(KIND_NAMES))})")
        kind = KIND_NAMES[kname]
        if "t_ns" in e:
            t = int(e["t_ns"])
        elif "time_s" in e:
            t = int(round(float(e["time_s"]) * 1e9))
        else:
            raise ValueError(f"fault entry {e} has neither t_ns nor time_s")
        out.append(FaultRecord(
            t_ns=t, kind=kind, a=int(e["a"]), b=int(e.get("b", -1)),
            value=_value_raw(kind, e.get("value"))))
    return out


def records_from_config(config, bundle) -> list[FaultRecord]:
    """Resolve the XML <fault> elements (config/xmlconfig.FaultSpec —
    endpoints are host *names*) against a built bundle: host name ->
    host index, and for link-level kinds on to the host's attachment
    vertex. Raw integers are accepted where a name does not resolve
    (vertex index for link kinds, host index for crash kinds)."""
    vertex_of_host = np.asarray(bundle.sim.net.vertex_of_host)

    def _host(tok, where):
        if tok in bundle.name_to_index:
            return bundle.name_to_index[tok]
        try:
            return int(tok)
        except (TypeError, ValueError):
            raise ValueError(f"{where}: '{tok}' is not a known host name "
                             f"or index") from None

    def _vertex(tok, where):
        if tok in bundle.name_to_index:
            return int(vertex_of_host[bundle.name_to_index[tok]])
        try:
            return int(tok)
        except (TypeError, ValueError):
            raise ValueError(f"{where}: '{tok}' is not a known host name "
                             f"or vertex index") from None

    out = []
    for i, spec in enumerate(config.faults):
        where = f"<fault> {i} (t={spec.time_ns})"
        kname = spec.kind.lower()
        if kname not in KIND_NAMES:
            raise ValueError(f"{where}: unknown kind '{spec.kind}' "
                             f"(known: {sorted(set(KIND_NAMES))})")
        kind = KIND_NAMES[kname]
        if kind in HOST_KINDS:
            a, b = _host(spec.a, where), -1
        elif kind in VERTEX_KINDS:
            a, b = _vertex(spec.a, where), -1
        else:
            if spec.b is None:
                raise ValueError(f"{where}: {kname} needs both a and b")
            a, b = _vertex(spec.a, where), _vertex(spec.b, where)
        out.append(FaultRecord(t_ns=spec.time_ns, kind=kind, a=a, b=b,
                               value=_value_raw(kind, spec.value)))
    return out
