"""Run-health latches — fold the engine's sticky failure counters
into one struct with a verdict, instead of leaving them as silent
integers in the final report.

Three of the latches already exist in device state (they are sticky
by construction — counters only ever increase):

- EventQueue.overflow: a host row was full when push_rows needed a
  slot. The dropped event is *gone*; everything after it is suspect.
- Outbox.overflow: same, for the cross-host staging buffer.
- NetState.rq_overflow: upstream router ring wrapped.

Two more are computed host-side by the supervisor loop from window
telemetry it already has:

- stall: K consecutive windows advanced with zero events processed —
  the advance rule should make this impossible (windows start at the
  min pending event time), so it indicates a wedged clock.
- time_regression: a window's next start preceded the current window
  *start* (< wstart, not < wend: runahead overrides legally schedule
  into the current window).

Severity: the five above are fatal — state is corrupt or the clock is
broken; rerun with bigger capacities (the diagnostics name the knob).
Outbox.narrow_miss is a *warning*: the narrow exchange tier fell back
to full width, which is a perf regression, never corruption.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class RunHealth:
    """Host-side snapshot of the latches after (part of) a run."""

    events_overflow: int = 0
    outbox_overflow: int = 0
    rq_overflow: int = 0
    narrow_miss: int = 0
    stalled_windows: int = 0      # longest zero-event streak observed
    stall_limit: int = 0          # K that makes the streak fatal (0 = off)
    time_regression: bool = False
    # window telemetry records overwritten before the host drained them
    # (telemetry/harvest.py) — observability loss only, results exact
    telemetry_lost: int = 0
    # the supervisor's per-run wallclock deadline passed
    # (faults/supervisor.py max_run_wallclock): the run was stopped
    # with a preemption-style final snapshot instead of hanging — the
    # state is healthy, the budget is not
    deadline_exceeded: bool = False
    # open-system injection (inject/staging.py): injected events
    # dropped because the destination row was full. A WARNING, not
    # fatal — external load that was refused is accounted (the
    # injected+dropped+deferred reconciliation still closes), but the
    # results are missing those trace events.
    inject_dropped: int = 0
    # injected events whose window had already run when they merged —
    # the feeder's horizon contract makes this impossible, so any
    # nonzero count means timestamps were perturbed (clamped up)
    inject_late: int = 0
    # torn-tail truncation messages from the binary trace reader
    # (inject/trace.py): the tail frame a dying writer never finished
    # was dropped — a WARNING; everything before it was read intact
    trace_warnings: tuple = ()
    # context for diagnostics
    window_start: Optional[int] = None   # wstart when gathered
    suspect_hosts: tuple = ()            # rows at capacity (global ids)
    # --- lane-isolated runs (core/lanes.py) --------------------------
    # lanes_total > 0 means the sim carried LaneHealth: `lanes` is the
    # per-lane report (core.lanes.lane_report dicts), lanes_quarantined
    # the tripped lane indices, and lane_contained says every capacity
    # / regression trip is attributed to a quarantined lane — the
    # blast radius held, so those trips DEGRADE the run (sick lanes
    # are frozen + requeued) instead of aborting the healthy tenants.
    lanes_total: int = 0
    lanes: tuple = ()
    lanes_quarantined: tuple = ()
    lane_contained: bool = False
    # --- resident programs (core/lanes.py LaneAdmission) -------------
    # resident=True means the sim carried lease planes: `admission` is
    # the per-lane device report (core.lanes.admission_report dicts).
    # A FREE lane is EXPECTED to be empty/idle — supervision must not
    # read an inactive lane's silence as a stall or incident.
    resident: bool = False
    admission: tuple = ()
    # --- specialized programs (compile/specialize.py GuardState) -----
    # guard_watched non-empty means the sim ran as a capability-
    # trimmed variant: the named capabilities were PROVEN dead at
    # build time and omitted from the trace. A nonzero trip counter
    # means a dead capability would have fired anyway (e.g. a
    # restored snapshot carried a lossy reliability table into a
    # loss-trimmed program) — the results are INVALID, always fatal:
    # specialization must never silently change results.
    guard_watched: tuple = ()
    guard_loss_trips: int = 0
    guard_timer_trips: int = 0
    # --- cross-shard integrity sentinel (parallel/elastic.py) --------
    # sentinel_checks > 0 means the sim carried a SentinelState: every
    # window barrier compared a digest of the replicated leaves
    # pmax-vs-pmin across shards. A nonzero trip count is SILENT
    # DIVERGENCE (an SDC, a bad collective, a flipped replicated bit)
    # — always FATAL: results after tripped_at cannot be trusted;
    # resume from a checkpoint whose time <= sentinel_verified_through.
    sentinel_checks: int = 0
    shard_divergence_trips: int = 0
    divergent_shard: int = -1            # offender of the FIRST trip
    sentinel_tripped_at: int = 0
    sentinel_verified_through: int = 0
    # --- device loss (parallel/elastic.py DeviceLossError) -----------
    # A machine fault, not a sim fault: set host-side by the
    # supervisor when a dispatch classified as DEVICE_LOST — the
    # degradation ladder (retry -> shrink -> serial) owns recovery;
    # fatal only if the ladder is exhausted (the supervisor then
    # re-raises, so a RunHealth that still carries it IS the verdict).
    device_lost: int = 0
    lost_shard: int = -1
    device_lost_cause: Optional[str] = None

    @property
    def guard_tripped(self) -> bool:
        return bool(self.guard_loss_trips or self.guard_timer_trips)

    @property
    def shard_divergence(self) -> bool:
        return bool(self.shard_divergence_trips)

    @property
    def fatal(self) -> bool:
        cap_trip = bool(
            self.events_overflow or self.outbox_overflow
            or self.rq_overflow or self.time_regression)
        if cap_trip and self.lanes_total and self.lane_contained:
            # contained trips are survivable — unless no healthy lane
            # remains, in which case the program serves nobody
            cap_trip = len(self.lanes_quarantined) >= self.lanes_total
        return bool(
            cap_trip or self.deadline_exceeded or self.guard_tripped
            or self.shard_divergence or self.device_lost
            or (self.stall_limit and self.stalled_windows >= self.stall_limit))

    def diagnostics(self) -> list:
        """Human-readable findings: (severity, message) pairs, fatal
        first. Empty when the run is clean."""
        out = []
        where = (f" at window t={self.window_start}"
                 if self.window_start is not None else "")
        hosts = (f" (suspect host rows at capacity: "
                 f"{list(self.suspect_hosts)})" if self.suspect_hosts else "")
        # lane-contained capacity trips degrade instead of abort: the
        # sick lanes are frozen + requeued, healthy lanes' results are
        # exact — report as warnings, with per-lane attribution below
        contained = bool(
            self.lanes_total and self.lane_contained
            and len(self.lanes_quarantined) < self.lanes_total)
        cap_sev = "warning" if contained else "fatal"
        cap_sfx = (" [contained: attributed to quarantined lane(s) "
                   f"{list(self.lanes_quarantined)}; healthy lanes "
                   "unaffected]" if contained else "")
        if self.events_overflow:
            out.append((cap_sev,
                        f"event queue overflow x{self.events_overflow}"
                        f"{where}{hosts}: events were dropped — results "
                        f"are invalid; rerun with a larger "
                        f"--event-capacity{cap_sfx}"))
        if self.outbox_overflow:
            out.append((cap_sev,
                        f"outbox overflow x{self.outbox_overflow}{where}: "
                        f"cross-host sends were dropped; rerun with a "
                        f"larger emit/exchange capacity{cap_sfx}"))
        if self.rq_overflow:
            out.append((cap_sev,
                        f"router ring overflow x{self.rq_overflow}{where}: "
                        f"upstream packets were dropped un-modelled; grow "
                        f"the router ring (config router_ring){cap_sfx}"))
        for d in self.lanes:
            if d.get("quarantined"):
                out.append((
                    "fatal" if not contained else "warning",
                    f"lane {d['lane']} quarantined at "
                    f"t={d.get('quarantined_at_ns')} "
                    f"(trip={d.get('trip', [])}): {d.get('flushed', 0)} "
                    f"pending event(s) flushed — the lane's results are "
                    f"discarded; salvage + fleet requeue apply"))
        if (self.lanes_total
                and len(self.lanes_quarantined) >= self.lanes_total):
            out.append(("fatal",
                        f"all {self.lanes_total} lanes quarantined"
                        f"{where}: no healthy tenant remains"))
        if self.time_regression:
            out.append(("fatal",
                        f"simulated time regressed{where}: a window "
                        f"started before its predecessor — engine "
                        f"invariant broken, results invalid"))
        if self.stall_limit and self.stalled_windows >= self.stall_limit:
            out.append(("fatal",
                        f"engine stalled: {self.stalled_windows} "
                        f"consecutive windows processed zero events"
                        f"{where}"))
        if self.deadline_exceeded:
            out.append(("fatal",
                        f"run wallclock deadline exceeded{where}: a "
                        f"final snapshot was taken — state is healthy "
                        f"but the time budget is spent; --resume "
                        f"continues it, or raise --max-run-wallclock"))
        if self.guard_loss_trips:
            out.append(("fatal",
                        f"specialization guard tripped x"
                        f"{self.guard_loss_trips}{where}: the loss "
                        f"capability was trimmed from this program but "
                        f"the reliability table went below 1.0 at "
                        f"runtime — drops were NOT modelled, results "
                        f"are invalid; rerun with --specialize off"))
        if self.guard_timer_trips:
            out.append(("fatal",
                        f"specialization guard tripped x"
                        f"{self.guard_timer_trips}{where}: the timer "
                        f"capability was trimmed from this program but "
                        f"a TIMER event entered the queue — it would "
                        f"never be handled, results are invalid; rerun "
                        f"with --specialize off"))
        if self.shard_divergence:
            out.append(("fatal",
                        f"SHARD_DIVERGENCE: replicated-state digest "
                        f"disagreed across shards x"
                        f"{self.shard_divergence_trips}, first at "
                        f"t={self.sentinel_tripped_at} (suspect shard "
                        f"{self.divergent_shard}) — silent data "
                        f"corruption; results after the trip are "
                        f"invalid, resume from a checkpoint at or "
                        f"before t={self.sentinel_verified_through}"))
        if self.device_lost:
            out.append(("fatal",
                        f"DEVICE_LOST x{self.device_lost}"
                        f"{where}: a mesh device failed underneath the "
                        f"run (shard {self.lost_shard}, cause "
                        f"{self.device_lost_cause}) — the degradation "
                        f"ladder (same-mesh retry -> shrink to "
                        f"survivors -> serial) resumes from the last "
                        f"verified checkpoint"))
        if self.narrow_miss:
            out.append(("warning",
                        f"narrow exchange tier missed {self.narrow_miss} "
                        f"window(s) (full-width fallback): perf only, "
                        f"results remain exact — raise the narrow width "
                        f"if this persists"))
        if self.telemetry_lost:
            out.append(("warning",
                        f"telemetry ring overran: {self.telemetry_lost} "
                        f"window record(s) lost before the host drained "
                        f"them — results remain exact, the trace has "
                        f"gaps; raise --telemetry-capacity or drain "
                        f"more often"))
        if self.inject_dropped:
            out.append(("warning",
                        f"injection drops x{self.inject_dropped}{where}: "
                        f"injected events were refused by full host "
                        f"rows — accounted, but the results are missing "
                        f"those trace events; raise --event-capacity or "
                        f"thin the trace"))
        if self.inject_late:
            out.append(("warning",
                        f"late injections x{self.inject_late}: events "
                        f"merged after their window had run and were "
                        f"clamped forward — the feeder's horizon "
                        f"contract was violated (file a bug); "
                        f"timestamps are perturbed, not lost"))
        for w in self.trace_warnings:
            out.append(("warning", w))
        return out

    def failure_report(self) -> dict:
        """Structured failure payload for the CLI's final JSON."""
        return {
            "fatal": self.fatal,
            "events_overflow": self.events_overflow,
            "outbox_overflow": self.outbox_overflow,
            "rq_overflow": self.rq_overflow,
            "narrow_miss": self.narrow_miss,
            "stalled_windows": self.stalled_windows,
            "stall_limit": self.stall_limit,
            "time_regression": self.time_regression,
            "telemetry_lost": self.telemetry_lost,
            "deadline_exceeded": self.deadline_exceeded,
            "inject_dropped": self.inject_dropped,
            "inject_late": self.inject_late,
            "trace_warnings": list(self.trace_warnings),
            "window_start": self.window_start,
            "suspect_hosts": [int(h) for h in self.suspect_hosts],
            "diagnostics": [m for _, m in self.diagnostics()],
            **({"lanes": {
                "replicas": self.lanes_total,
                "quarantined": [int(r) for r in self.lanes_quarantined],
                "contained": bool(self.lane_contained),
                "per_lane": [dict(d) for d in self.lanes],
            }} if self.lanes_total else {}),
            **({"admission": {
                "per_lane": [dict(d) for d in self.admission],
            }} if self.resident else {}),
            **({"guard": {
                "watched": list(self.guard_watched),
                "loss_trips": self.guard_loss_trips,
                "timer_trips": self.guard_timer_trips,
                "tripped": self.guard_tripped,
            }} if self.guard_watched else {}),
            **({"sentinel": {
                "checks": self.sentinel_checks,
                "trips": self.shard_divergence_trips,
                "shard": self.divergent_shard,
                "tripped_at_ns": self.sentinel_tripped_at,
                "verified_through_ns": self.sentinel_verified_through,
            }} if self.sentinel_checks or self.shard_divergence_trips
               else {}),
            **({"device_lost": {
                "count": self.device_lost,
                "shard": self.lost_shard,
                "cause": self.device_lost_cause,
            }} if self.device_lost else {}),
        }


def gather(sim, *, window_start=None, stalled_windows=0, stall_limit=0,
           time_regression=False, telemetry_lost=0,
           trace_warnings=(), max_suspects=8) -> RunHealth:
    """Pull the device latches into a RunHealth. Cheap (a handful of
    scalars plus one fill_count) — fine to call once per checkpoint
    interval and after every run."""
    suspects = ()
    ev = int(np.asarray(sim.events.overflow))
    if ev:
        fill = np.asarray(sim.events.fill_count())
        full = np.flatnonzero(fill >= sim.events.capacity)
        lane = np.asarray(sim.net.lane_id)
        suspects = tuple(int(lane[h]) for h in full[:max_suspects])
    inj = getattr(sim, "inject", None)
    lanes_total, lane_rep, quar, contained = 0, (), (), False
    if getattr(sim, "lanes", None) is not None:
        from shadow_tpu.core.lanes import lane_report

        lane_rep = tuple(lane_report(sim))
        lanes_total = len(lane_rep)
        quar = tuple(d["lane"] for d in lane_rep if d["quarantined"])
        # contained: no un-quarantined lane carries a latched trip —
        # window_update trips at the same barrier the latch bumps, so
        # by host-gather time this holds whenever isolation worked
        contained = not any(
            d["events_overflow"] or d["outbox_overflow"]
            or d["rq_overflow"] or d["time_regression"]
            for d in lane_rep if not d["quarantined"])
    resident, adm_rep = False, ()
    if getattr(sim, "admission", None) is not None:
        from shadow_tpu.core.lanes import admission_report

        resident = True
        adm_rep = tuple(admission_report(sim))
    g_watched, g_loss, g_timer = (), 0, 0
    if getattr(sim, "guard", None) is not None:
        from shadow_tpu.compile.specialize import guard_report

        g = guard_report(sim)
        g_watched = tuple(g["watched"])
        g_loss, g_timer = g["loss_trips"], g["timer_trips"]
    s_checks, s_trips, s_shard, s_at, s_ver = 0, 0, -1, 0, 0
    if getattr(sim, "sentinel", None) is not None:
        from shadow_tpu.parallel.elastic import sentinel_report

        sr = sentinel_report(sim)
        s_checks, s_trips = sr["checks"], sr["trips"]
        s_shard, s_at = sr["shard"], sr["tripped_at_ns"]
        s_ver = sr["verified_through_ns"]
    return RunHealth(
        sentinel_checks=s_checks,
        shard_divergence_trips=s_trips,
        divergent_shard=s_shard,
        sentinel_tripped_at=s_at,
        sentinel_verified_through=s_ver,
        guard_watched=g_watched,
        guard_loss_trips=g_loss,
        guard_timer_trips=g_timer,
        lanes_total=lanes_total,
        lanes=lane_rep,
        lanes_quarantined=quar,
        lane_contained=contained,
        resident=resident,
        admission=adm_rep,
        events_overflow=ev,
        outbox_overflow=int(np.asarray(sim.outbox.overflow)),
        rq_overflow=int(np.asarray(sim.net.rq_overflow)),
        narrow_miss=int(np.asarray(sim.outbox.narrow_miss)),
        stalled_windows=int(stalled_windows),
        stall_limit=int(stall_limit),
        time_regression=bool(time_regression),
        telemetry_lost=int(telemetry_lost),
        inject_dropped=(0 if inj is None
                        else int(np.asarray(inj.dropped))),
        inject_late=0 if inj is None else int(np.asarray(inj.late)),
        trace_warnings=tuple(trace_warnings),
        window_start=None if window_start is None else int(window_start),
        suspect_hosts=suspects,
    )
