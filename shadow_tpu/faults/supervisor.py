"""Run supervisor: window loop + health latches + checkpoint-backed
recovery + capacity escalation + preemption-safe resume chains.

The CLI's `--supervise` mode runs the simulation through here instead
of the one-shot jitted runner. At every dispatch barrier — one window,
or one K-window chunk when cfg.windows_per_dispatch > 1 (the chunked
loop in checkpoint.run_windows) — the supervisor inspects the sticky
latches (faults/health.py) plus its own stall / time-regression
telemetry; every N *windows* it snapshots the sim
(utils/checkpoint.py — atomic + checksummed, so a trip mid-save can
never leave a poisoned resume point). Recovery has three distinct
paths, accounted separately:

- **escalation** (`escalation=EscalationPolicy(...)`): a fatal
  *capacity* latch (event queue / outbox / router ring overflow) is
  healed, not retried — the tripped knob doubles, the bundle rebuilds
  at the grown shapes (bundle.rebuild, installed by config/loader),
  and the last clean pre-trip snapshot transplants into the padded
  arrays (faults/escalate.py). Escalation restarts do NOT consume the
  retry budget and do not back off: the restart is a fix, not a
  gamble.
- **retry**: everything else (stall, regression, exhausted grow
  budget, no rebuild hook) restores the last good snapshot, backs off
  exponentially, and retries up to max_retries before giving up with
  a structured failure report. Retrying a *deterministic* trip
  reproduces it — the budget exists for host-process crashes and
  transient device loss.
- **preemption** (`stop=callable`): when the flag reads true at a
  round barrier the supervisor takes one final atomic checkpoint and
  raises out with `preempted=True` — the CLI maps it to its own exit
  code and a manifest carrying the `resume_of` chain id, and
  `--resume` continues the chain later, under any shard count
  (snapshots hold global-layout arrays).

Checkpoint cadence is counted in windows, not sim-ns: window length
tracks min_jump, so N windows is a stable amount of device work
regardless of the topology's latency floor. Engine-stat totals ride
every snapshot's `extra` (escalation-aware carryover: the pre-trip
counters live in a different compiled program than the post-heal
ones), so a resumed chain reports cumulative work, not the last
attempt's slice.
"""

from __future__ import annotations

import dataclasses
import time as _time
import uuid
from typing import Optional

import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.faults import escalate as escalate_mod
from shadow_tpu.faults import health as health_mod
from shadow_tpu.parallel import elastic as elastic_mod
from shadow_tpu.utils import checkpoint as ckpt


class LatchTrip(RuntimeError):
    """A fatal health latch fired mid-run. Carries the sim state at the
    trip so the failure path can still dump diagnostics (object counts,
    final counters for the run manifest)."""

    def __init__(self, health: health_mod.RunHealth, sim=None):
        self.health = health
        self.sim = sim
        msgs = "; ".join(m for s, m in health.diagnostics() if s == "fatal")
        super().__init__(msgs or "health latch tripped")


class Preempted(RuntimeError):
    """The stop flag was set at a round barrier; a final checkpoint
    was taken before raising."""

    def __init__(self, path: str, time_ns: int, sim=None):
        self.path = path
        self.time_ns = time_ns
        self.sim = sim
        super().__init__(f"preempted at t={time_ns}, checkpoint {path}")


class DeadlineExceeded(Preempted):
    """The per-run wallclock deadline (max_run_wallclock) passed at a
    round barrier: same final-snapshot discipline as preemption, but
    latched as a `deadline` health fault — the run did not hang, it
    ran out of budget. The fleet watchdog (shadow_tpu/fleet) is the
    out-of-process counterpart for runs wedged *inside* a device call,
    where no round barrier ever comes back to the host."""

    def __init__(self, path: str, time_ns: int, sim=None,
                 elapsed_s: float = 0.0):
        super().__init__(path, time_ns, sim)
        self.elapsed_s = elapsed_s


@dataclasses.dataclass(frozen=True)
class LaneIncident:
    """One quarantined lane, detected at a chunk barrier of a packed
    (lane-isolated) run. Carries the blast-radius evidence plus the
    requeue context the fleet consumes (fleet/scenario.py packed
    jobs): which capacity knobs the trip bits say to regrow, and
    where the lane's salvage slice landed."""

    lane: int
    time_ns: int          # window barrier the device quarantined at
    detected_ns: int      # chunk barrier the host noticed it at
    trip_bits: int
    trip: tuple           # TRIP_* names (core.lanes.trip_names)
    flushed: int          # pending events flushed when frozen
    salvage: Optional[str] = None       # lane-surgery artifact path
    salvaged_from: Optional[str] = None  # snapshot the slice came from
    regrow: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"lane": self.lane, "time_ns": self.time_ns,
                "detected_ns": self.detected_ns,
                "trip_bits": self.trip_bits, "trip": list(self.trip),
                "flushed": self.flushed, "salvage": self.salvage,
                "salvaged_from": self.salvaged_from,
                "regrow": dict(self.regrow)}


@dataclasses.dataclass
class SupervisorResult:
    ok: bool
    sim: object
    stats: object                      # EngineStats, cumulative chain
    health: health_mod.RunHealth       # final latch snapshot
    attempts: int = 1
    resumed_from: Optional[str] = None  # snapshot path of the last resume
    checkpoints: tuple = ()            # (path, time_ns) saved, all attempts
    # accounting split (the --retries budget must not be consumed by
    # successful self-healing):
    retries_used: int = 0              # failure retries, <= max_retries
    escalation_restarts: int = 0       # heals; unbounded by max_retries
    escalations: tuple = ()            # Escalation records, chain-wide
    preempted: bool = False
    deadline_exceeded: bool = False    # max_run_wallclock fired
    final_checkpoint: Optional[str] = None  # preemption's last snapshot
    run_id: Optional[str] = None
    resume_of: Optional[str] = None    # run_id of the chain predecessor
    # Dispatch accounting for the FINAL attempt (chunked window loop):
    # how many device dispatches the loop issued and how many windows
    # each executed. sum(dispatch_windows) == stats.windows for a
    # clean single-attempt, non-resumed run — the invariant
    # tools/telemetry_lint.py checks when a manifest embeds the list.
    dispatches: int = 0
    dispatch_windows: tuple = ()
    # Lane-isolated runs: every lane quarantined across the chain,
    # with salvage pointers — the fleet's requeue feed.
    lane_incidents: tuple = ()
    # Manifest `compile` block for the FINAL attempt's dispatch
    # program (compile/serve.py): {key, warm, hit, load_s|compile_s}.
    # None when the loop never dispatched or warm accounting was off.
    compile_info: Optional[dict] = None
    # Elastic degraded-mesh recovery (parallel/elastic.py): losses,
    # divergences, the ladder steps taken and the mesh transitions —
    # the manifest's `elastic` block. None when no ElasticPolicy was
    # installed and nothing tripped.
    elastic: Optional[dict] = None

    def failure_report(self) -> dict:
        rep = self.health.failure_report() if self.health is not None \
            else {"verdict": "preempted", "fatal": []}
        rep["attempts"] = self.attempts
        rep["resumed_from"] = self.resumed_from
        rep["retries_used"] = self.retries_used
        rep["escalation_restarts"] = self.escalation_restarts
        if self.escalations:
            rep["escalations"] = [e.as_dict() for e in self.escalations]
        if self.lane_incidents:
            rep["lane_incidents"] = [i.as_dict()
                                     for i in self.lane_incidents]
        if self.preempted:
            rep["verdict"] = "preempted"
            rep["final_checkpoint"] = self.final_checkpoint
        if self.deadline_exceeded:
            rep["verdict"] = "deadline"
            rep["final_checkpoint"] = self.final_checkpoint
        if self.elastic is not None:
            rep["elastic"] = dict(self.elastic)
        return rep


def _stats_get(wstats) -> dict:
    """Per-round EngineStats as host ints (one device_get)."""
    import jax

    s = jax.device_get(wstats)
    return {k: int(getattr(s, k)) for k in
            ("events_processed", "micro_steps", "windows",
             "fastpath_hit", "fastpath_miss")}


def run_supervised(bundle, app_handlers=(), *, fault_fn=None,
                   end_time=None, checkpoint_path,
                   checkpoint_every_windows: int = 64,
                   max_retries: int = 2, backoff_s: float = 0.25,
                   stall_windows: int = 512,
                   log=None, on_window=None, on_round=None,
                   harvester=None, sleep=_time.sleep,
                   escalation: escalate_mod.EscalationPolicy | None = None,
                   rebuild=None, stop=None, resume_from=None,
                   max_run_wallclock: float | None = None,
                   run_id: str | None = None,
                   mesh=None, mesh_axis: str = "hosts",
                   exchange_capacity: int | None = None,
                   config_digest: str | None = None,
                   windows_per_dispatch: int | None = None,
                   adaptive_jump: bool | None = None,
                   feeder=None,
                   on_lane_quarantine=None,
                   warm_start: bool | None = None,
                   elastic: elastic_mod.ElasticPolicy | None = None,
                   dispatch_wrap=None,
                   on_mesh_change=None,
                   ) -> SupervisorResult:
    """Run bundle to end_time under supervision (host-driven window
    loop; serial by default, shard_map'd over `mesh` when given — the
    host regains control at every window barrier either way).

    `escalation` turns capacity trips into heals (see module doc);
    `rebuild(overrides) -> SimBundle` defaults to bundle.rebuild (set
    by config/loader.load). When escalation rebuilds, an explicitly
    passed `fault_fn` is dropped and re-resolved from the rebuilt
    bundle's installed plan — a closure over the old shapes would
    poison the new program. `stop()` is polled at every round barrier
    (preemption flag, set from a signal handler); `resume_from` is a
    snapshot path to continue a previous run's chain (grown-capacity
    snapshots transplant automatically). `max_run_wallclock` is a
    per-run wallclock budget in seconds, chain-wide (attempts and
    heals share it): when a round barrier finds it spent, the
    supervisor takes the preemption-style final snapshot and returns
    with `deadline_exceeded=True` plus a latched `deadline` health
    fault instead of running forever — a wedge *inside* a device call
    never reaches a barrier, which is what the fleet watchdog's
    out-of-process SIGKILL path is for. `on_round(sim, wstats,
    wstart, wend, next_min)` runs after the health check at each
    round barrier — the chaos harness samples its conservation ledger
    there. `log` is a callable taking one message string; `sleep` is
    injectable for tests.

    Lane-isolated runs (core/lanes.py attached): a CONTAINED lane
    quarantine is not fatal (faults/health.py), so the run keeps going
    while the supervisor performs checkpoint lane surgery at the
    detecting barrier — the sick lane's slice is cut out of the last
    clean snapshot (faults/escalate.py extract_lane) and written as a
    salvage artifact next to the checkpoints; `on_lane_quarantine`
    (callable taking one LaneIncident) fires once per lane, chain-wide
    — the fleet's requeue hook.

    `windows_per_dispatch` / `adaptive_jump` (default: the bundle
    cfg's knobs) select the chunked window loop
    (checkpoint.run_windows): at K windows per dispatch the
    supervisor's barrier — health latches, harvest, checkpoint
    cadence, stop/deadline polls, on_round — runs once per CHUNK on
    per-chunk aggregate stats plus the ring records. Streak and
    checkpoint cadences are counted in executed windows either way,
    so `checkpoint_every_windows` and `stall_windows` keep their
    meaning, quantized up to a chunk boundary; a chunk whose windows
    all processed zero events extends the stall streak by the whole
    chunk, but a mixed chunk resets it — pick stall_windows >= a few
    chunks.

    `elastic` (parallel/elastic.ElasticPolicy) arms degraded-mesh
    recovery: every dispatch is wrapped in guard_dispatch, so a dead
    chip (XLA device/transfer error, or a dispatch overrunning
    `dispatch_deadline_s`) surfaces as a typed DeviceLossError and
    steps the degradation ladder — retry the same mesh
    (`same_mesh_retries`), then shrink to the next-pow2-down survivor
    mesh (checkpoints hold global layout, so the snapshot replans with
    a digest-verified restamp), then fall back to serial — always
    resuming from the last VERIFIED checkpoint (saved with sentinel
    trips == 0, or pre-sentinel and therefore health-clean). A
    SHARD_DIVERGENCE latch (the cross-shard integrity sentinel,
    attach_sentinel) steps the SAME ladder: a shard whose replica of
    the replicated state diverged is treated like a failing chip.
    Ladder steps consume no failure retries (like escalation heals —
    the sim did nothing wrong) and are bounded by `max_losses`.
    `dispatch_wrap` composes INSIDE the guard (chaos poison injection
    sees the dispatch first, the classifier sees its error);
    `on_mesh_change(old_shards, new_shards, cause)` fires on every
    shrink/serial transition — the fleet's degraded-requeue hook."""

    def say(msg):
        if log is not None:
            log(msg)

    rebuild_fn = rebuild if rebuild is not None \
        else getattr(bundle, "rebuild", None)
    run_id = run_id or uuid.uuid4().hex[:12]
    t_chain0 = _time.monotonic()   # max_run_wallclock origin
    # Elastic recovery makes the mesh MUTABLE chain state: a ladder
    # step may shrink it (or drop to serial) between attempts.
    cur_mesh = mesh
    cur_shards = mesh.shape[mesh_axis] if mesh is not None else 1
    shards0 = cur_shards
    losses: list = []              # DeviceLossError records, chain-wide
    divergences: list = []         # sentinel trips, chain-wide
    ladder_steps: list = []        # one per loss/divergence handled
    same_mesh_used: dict = {}      # mesh width -> same-mesh retries spent

    total_saved = []
    attempt = 0
    retries_used = 0
    escalation_restarts = 0
    escalations: list = []
    grows_used = 0
    resume_sim = None
    resume_time = 0
    resumed_from = None
    resume_of = None
    base_stats = {}                    # chain totals at the resume point
    lane_incidents: list = []          # chain-wide, one per lane
    lanes_seen: set = set()            # lanes already surgeried

    if resume_from is not None:
        leaves, meta = ckpt.load_leaves(resume_from)
        resume_sim, resume_time, extra = escalate_mod.transplant(
            leaves, meta, bundle.sim)
        base_stats = dict(extra.get("stats", {}))
        resume_of = extra.get("run_id")
        escalations = [escalate_mod.Escalation.from_dict(d)
                       for d in extra.get("escalations", [])]
        grows_used = len(escalations)
        resumed_from = resume_from
        say(f"supervisor: resuming chain {resume_of or '?'} from "
            f"{resume_from} (t={resume_time})")

    def _ckpt_extra(acc: dict) -> dict:
        stats = {k: base_stats.get(k, 0) + acc.get(k, 0)
                 for k in ("events_processed", "micro_steps", "windows",
                           "fastpath_hit", "fastpath_miss")}
        return {"stats": stats, "run_id": run_id,
                "escalations": [e.as_dict() for e in escalations]}

    def _lane_surgery(h, detected_ns):
        """Record newly quarantined lanes (once per lane, chain-wide)
        and cut each lane's slice out of the last clean snapshot —
        every snapshot predates the trip (health precedes every save),
        so the salvage is the lane's best pre-corruption evidence."""
        if not h.lanes_total:
            return
        caps = ckpt.capacities_of_sim(bundle.sim)
        # resident programs (core/lanes.LaneAdmission): a lane with no
        # live lease holds no tenant — there is nothing to salvage or
        # requeue, and the lease table (fleet/admission.py) owns the
        # lane's lifecycle; raising an incident for it would fabricate
        # a tenant failure out of an empty vessel
        inactive = {d["lane"] for d in getattr(h, "admission", ())
                    if not d.get("active")}
        for d in h.lanes:
            if not d.get("quarantined") or d["lane"] in lanes_seen:
                continue
            if d["lane"] in inactive:
                lanes_seen.add(d["lane"])
                continue
            lanes_seen.add(d["lane"])
            bits = int(d.get("trip_bits", 0))
            salvage, src = None, None
            if total_saved:
                src = total_saved[-1][0]
                try:
                    leaves, meta = ckpt.load_leaves(src)
                    ll, lm = escalate_mod.extract_lane(
                        leaves, meta, d["lane"], h.lanes_total)
                    lm["trip_bits"] = bits
                    lm["trip"] = list(d.get("trip", []))
                    lm["quarantined_at_ns"] = d.get("quarantined_at_ns")
                    salvage = ckpt.save_salvage(
                        f"{checkpoint_path}.lane{d['lane']}.salvage",
                        ll, lm)
                except (OSError, ValueError, KeyError) as e:
                    say(f"supervisor: lane {d['lane']} salvage "
                        f"failed: {e}")
            inc = LaneIncident(
                lane=int(d["lane"]),
                time_ns=int(d.get("quarantined_at_ns") or 0),
                detected_ns=int(detected_ns), trip_bits=bits,
                trip=tuple(d.get("trip", ())),
                flushed=int(d.get("flushed", 0)),
                salvage=salvage, salvaged_from=src,
                regrow=escalate_mod.plan_lane_regrow(bits, caps))
            lane_incidents.append(inc)
            say(f"supervisor: lane {inc.lane} quarantined at "
                f"t={inc.time_ns} (trip={list(inc.trip)}), "
                f"{inc.flushed} event(s) flushed"
                + (f"; salvage {salvage}" if salvage
                   else "; no snapshot to salvage"))
            if on_lane_quarantine is not None:
                on_lane_quarantine(inc)

    def _verified_snapshot(limit_ns: int | None = None):
        """Newest checkpoint the elastic ladder may resume from:
        its elastic stamp (utils/checkpoint.elastic_meta) shows zero
        sentinel trips — or predates the sentinel entirely, in which
        case the health check that preceded the save is the verifier.
        `limit_ns` (a divergence's verified_through) additionally caps
        the resume time. Returns (path, time_ns, meta) or None."""
        for path, t in reversed(total_saved):
            if limit_ns is not None and t > limit_ns:
                continue
            try:
                _, meta = ckpt.load_leaves(path)
            except (OSError, ValueError, KeyError) as e:
                say(f"supervisor: skipping unreadable snapshot "
                    f"{path}: {e}")
                continue
            el = meta.get("elastic")
            rep = el.get("sentinel") if isinstance(el, dict) else None
            if rep and rep.get("trips"):
                continue
            return path, t, meta
        return None

    def _elastic_block():
        if elastic is None and not losses and not divergences:
            return None
        return {
            "policy": elastic.as_dict() if elastic is not None else None,
            "initial_shards": shards0,
            "final_shards": cur_shards,
            "losses": [dict(d) for d in losses],
            "divergences": [dict(d) for d in divergences],
            "ladder_steps": [dict(s) for s in ladder_steps],
            "mesh_transitions": [dict(s) for s in ladder_steps
                                 if s["from"] != s["to"]],
        }

    def _elastic_step(cause: str, shard: int, limit_ns=None):
        """One rung of the degradation ladder. Decides retry / shrink /
        serial, finds the verified resume point (replanning its shard
        stamp when the width changes), and mutates the chain's mesh
        state. Returns True when the chain should continue, False when
        the ladder is exhausted."""
        nonlocal cur_mesh, cur_shards, resume_sim, resume_time
        nonlocal resumed_from, base_stats
        if len(losses) + len(divergences) > elastic.max_losses:
            say(f"supervisor: elastic budget exhausted "
                f"({elastic.max_losses} losses)")
            return False
        # --- decide the rung ---------------------------------------
        if same_mesh_used.get(cur_shards, 0) < elastic.same_mesh_retries:
            same_mesh_used[cur_shards] = \
                same_mesh_used.get(cur_shards, 0) + 1
            action, new_mesh, new_shards = "retry", cur_mesh, cur_shards
        elif (elastic.allow_shrink and cur_mesh is not None
                and cur_shards > max(elastic.min_shards, 1)):
            new_mesh, new_shards = elastic_mod.survivor_mesh(
                cur_mesh, mesh_axis, shard)
            if new_mesh is None or new_shards < elastic.min_shards:
                if not elastic.allow_serial:
                    say("supervisor: survivors cannot carry a mesh and "
                        "serial fallback is disabled")
                    return False
                action, new_mesh, new_shards = "serial", None, 1
            else:
                action = "shrink"
        elif elastic.allow_serial and cur_mesh is not None:
            action, new_mesh, new_shards = "serial", None, 1
        else:
            say(f"supervisor: ladder exhausted at {cur_shards} "
                f"shard(s) ({cause})")
            return False
        # --- verified resume point ---------------------------------
        found = _verified_snapshot(limit_ns)
        if found is not None:
            path, t, _meta = found
            if new_shards != cur_shards:
                try:
                    # digest-verified restamp: recomputes the per-shard
                    # sha256 ledger at the OLD width against the stamp,
                    # then restamps at the NEW width
                    path = ckpt.replan_shards(path, new_shards,
                                              template_sim=bundle.sim)
                except (ValueError, OSError, KeyError) as e:
                    say(f"supervisor: replan of {path} failed ({e}); "
                        f"rebooting at {new_shards} shard(s)")
                    path = None
            if path is not None:
                resume_sim, resume_time, extra = ckpt.load(path,
                                                           bundle.sim)
                base_stats = dict(extra.get("stats", {}))
                resumed_from = path
            else:
                resume_sim, resume_time, base_stats = None, 0, {}
                t = 0
        else:
            say("supervisor: no verified snapshot, rebooting from t=0")
            resume_sim, resume_time, base_stats = None, 0, {}
            t = 0
        ladder_steps.append({
            "action": action, "cause": cause, "shard": int(shard),
            "from": cur_shards, "to": new_shards,
            "resume_time_ns": int(t), "attempt": attempt,
        })
        say(f"supervisor: elastic {action} ({cause}, shard {shard}): "
            f"{cur_shards} -> {new_shards} shard(s), resuming at "
            f"t={int(t)}")
        if new_shards != cur_shards and on_mesh_change is not None:
            on_mesh_change(cur_shards, new_shards, cause)
        cur_mesh, cur_shards = new_mesh, new_shards
        return True

    def _wrap_dispatch(fn):
        """Compose the caller's dispatch_wrap (chaos poison — it must
        see the dispatch first so its injected error reaches the
        classifier) inside the device-loss guard."""
        if dispatch_wrap is not None:
            fn = dispatch_wrap(fn)
        if elastic is not None:
            fn = elastic_mod.guard_dispatch(
                fn, shards=cur_shards,
                deadline_s=elastic.dispatch_deadline_s)
        return fn

    while True:
        attempt += 1
        # Per-attempt telemetry the chunk closure mutates.
        tele = {"zero_streak": 0, "worst_streak": 0, "regressed": False,
                "wstart": None, "since_ckpt": 0, "acc": {},
                "dispatch_windows": []}
        # Filled by run_windows' warm wrapper at the first dispatch of
        # this attempt; the FINAL attempt's block lands in the result
        # (an escalation restart compiles a new program — that is the
        # one the manifest should report).
        cinfo: dict = {}

        def _on_chunk(sim, wstats, wstart, wend, next_min):
            tele["wstart"] = wstart
            ws = _stats_get(wstats)
            for k, v in ws.items():
                tele["acc"][k] = tele["acc"].get(k, 0) + v
            tele["dispatch_windows"].append(ws["windows"])
            # Streaks count executed WINDOWS (not dispatches), so the
            # stall limit keeps its meaning at any chunk size — a
            # whole-chunk zero extends the streak by the chunk's
            # window count.
            if ws["events_processed"] == 0:
                tele["zero_streak"] += ws["windows"]
                tele["worst_streak"] = max(tele["worst_streak"],
                                           tele["zero_streak"])
            else:
                tele["zero_streak"] = 0
            # Runahead may legally schedule inside the current window
            # (next_min < wend); only a start-regression is corrupt.
            if next_min < wstart:
                tele["regressed"] = True
            if harvester is not None:
                harvester.drain(sim)
            h = _gather(sim)
            # Lane surgery BEFORE the fatal check: even the
            # all-lanes-quarantined abort should leave salvage behind.
            _lane_surgery(h, wend)
            if h.fatal:
                # before the user hooks on purpose: a tripped round's
                # state is corrupt and will be replayed after the heal
                # — observers should never see it as a completed round
                raise LatchTrip(h, sim)
            # Health precedes every save: snapshots are always clean,
            # which is what makes escalation transplants exact.
            tele["since_ckpt"] += ws["windows"]
            if (tele["since_ckpt"] >= checkpoint_every_windows
                    and next_min < simtime.INVALID):
                # Healthy at this barrier: snapshot resumes at next_min.
                p = ckpt.save(f"{checkpoint_path}.{next_min}", sim,
                              time_ns=next_min, shards=cur_shards,
                              config_digest=config_digest,
                              extra=_ckpt_extra(tele["acc"]))
                total_saved.append((p, next_min))
                tele["since_ckpt"] = 0
            if on_round is not None:
                on_round(sim, wstats, wstart, wend, next_min)
            if on_window is not None:
                on_window(sim, wend)
            # Preemption polls LAST: the round is complete and every
            # observer has seen it — the final snapshot's resume point
            # starts the next round, so a hook that never saw this one
            # would double- or under-count across the kill boundary.
            if stop is not None and stop() and next_min < simtime.INVALID:
                p = ckpt.save(f"{checkpoint_path}.{next_min}", sim,
                              time_ns=next_min, shards=cur_shards,
                              config_digest=config_digest,
                              extra=_ckpt_extra(tele["acc"]))
                total_saved.append((p, next_min))
                raise Preempted(p, next_min, sim)
            # The wallclock deadline uses the same final-snapshot
            # discipline as preemption (round complete, observers
            # saw it, state healthy) but latches as a health fault:
            # the caller learns the budget was the problem, and
            # --resume continues the chain.
            if max_run_wallclock is not None \
                    and next_min < simtime.INVALID:
                el = _time.monotonic() - t_chain0
                if el >= max_run_wallclock:
                    p = ckpt.save(f"{checkpoint_path}.{next_min}", sim,
                                  time_ns=next_min, shards=cur_shards,
                                  config_digest=config_digest,
                                  extra=_ckpt_extra(tele["acc"]))
                    total_saved.append((p, next_min))
                    raise DeadlineExceeded(p, next_min, sim,
                                           elapsed_s=el)

        def _gather(sim):
            return health_mod.gather(
                sim,
                window_start=tele["wstart"],
                stalled_windows=tele["worst_streak"],
                stall_limit=stall_windows,
                time_regression=tele["regressed"],
                # flow-ring overruns ride the same observability-
                # degraded warning: results stay exact, the flight
                # recorder has gaps (telemetry/flows.py)
                telemetry_lost=(harvester.records_lost
                                + getattr(harvester, "flow_lost", 0)
                                if harvester is not None else 0),
                trace_warnings=tuple(
                    getattr(feeder, "warnings", ()) or ()),
            )

        def _result(ok, sim, h, **kw):
            return SupervisorResult(
                ok=ok, sim=sim, health=h, attempts=attempt,
                resumed_from=resumed_from,
                checkpoints=tuple(total_saved),
                retries_used=retries_used,
                escalation_restarts=escalation_restarts,
                escalations=tuple(escalations),
                run_id=run_id, resume_of=resume_of,
                dispatches=len(tele["dispatch_windows"]),
                dispatch_windows=tuple(tele["dispatch_windows"]),
                lane_incidents=tuple(lane_incidents),
                compile_info=(dict(cinfo) if cinfo else None),
                elastic=_elastic_block(), **kw)

        from shadow_tpu.core.engine import EngineStats

        try:
            sim, stats, _ = ckpt.run_windows(
                bundle, app_handlers,
                end_time=end_time,
                start_time=resume_time,
                sim=resume_sim,
                fault_fn=fault_fn,
                on_chunk=_on_chunk,
                stats0=(EngineStats.from_dict(base_stats)
                        if base_stats else None),
                mesh=cur_mesh, mesh_axis=mesh_axis,
                exchange_capacity=exchange_capacity,
                windows_per_dispatch=windows_per_dispatch,
                adaptive_jump=adaptive_jump,
                feeder=feeder,
                warm_start=warm_start,
                compile_info=cinfo,
                dispatch_wrap=(_wrap_dispatch
                               if (dispatch_wrap is not None
                                   or elastic is not None) else None),
            )
            if harvester is not None:
                harvester.drain(sim)
            h = _gather(sim)
            _lane_surgery(h, tele["wstart"] or 0)
            if h.fatal:
                raise LatchTrip(h, sim)
            return _result(True, sim, h, stats=stats)
        except DeadlineExceeded as d:
            say(f"supervisor: wallclock deadline after "
                f"{d.elapsed_s:.1f}s: {d}")
            h = dataclasses.replace(_gather(d.sim),
                                    deadline_exceeded=True)
            return _result(
                False, d.sim, h,
                stats=EngineStats.from_dict(
                    _ckpt_extra(tele["acc"])["stats"]),
                deadline_exceeded=True, final_checkpoint=d.path)
        except Preempted as p:
            say(f"supervisor: {p}")
            # the preempting round passed its health check before the
            # final save — report that healthy snapshot, not a guess
            return _result(
                False, p.sim, _gather(p.sim),
                stats=EngineStats.from_dict(
                    _ckpt_extra(tele["acc"])["stats"]),
                preempted=True, final_checkpoint=p.path)
        except elastic_mod.DeviceLossError as loss:
            say(f"supervisor: device loss on attempt {attempt}: {loss}")
            if elastic is None:
                raise
            losses.append(dict(loss.as_dict(), attempt=attempt,
                               mesh=cur_shards))
            if _elastic_step("device_lost", loss.shard):
                continue  # a ladder step consumes no retry, no backoff
            h = health_mod.RunHealth(
                device_lost=len(losses),
                lost_shard=loss.shard,
                device_lost_cause=loss.cause)
            return _result(False, None, h, stats=None)
        except LatchTrip as trip:
            say(f"supervisor: latch trip on attempt {attempt}: {trip}")
            if elastic is not None and trip.health.shard_divergence:
                # the sentinel's SDC screen: a shard whose replica of
                # the replicated state diverged is a failing chip —
                # step the SAME ladder, but the resume point must also
                # predate the trip's verified_through (nothing after it
                # is trusted)
                divergences.append({
                    "fault": "SHARD_DIVERGENCE",
                    "shard": int(trip.health.divergent_shard),
                    "tripped_at_ns": int(trip.health.sentinel_tripped_at),
                    "verified_through_ns":
                        int(trip.health.sentinel_verified_through),
                    "attempt": attempt, "mesh": cur_shards,
                })
                if _elastic_step(
                        "shard_divergence", trip.health.divergent_shard,
                        limit_ns=trip.health.sentinel_verified_through):
                    continue
                return _result(False, trip.sim, trip.health, stats=None)
            healed = False
            if escalation is not None and rebuild_fn is not None:
                try:
                    caps = ckpt.capacities_of_sim(bundle.sim)
                    t0 = total_saved[-1][1] if total_saved else 0
                    grow, events = escalate_mod.plan_growth(
                        trip.health, caps, escalation, grows_used,
                        time_ns=t0)
                    healed = True
                except (ValueError, escalate_mod.GrowBudgetExceeded) as e:
                    say(f"supervisor: escalation unavailable: {e}")
            if healed:
                for ev in events:
                    say(f"supervisor: escalating {ev.knob} "
                        f"{ev.old} -> {ev.new} ({ev.latch})")
                    if harvester is not None:
                        harvester.mark_escalation(ev)
                old_telem = getattr(bundle.sim, "telem", None)
                old_inject = getattr(bundle.sim, "inject", None)
                old_lanes = getattr(bundle.sim, "lanes", None)
                old_caps = getattr(bundle, "caps", None)
                bundle = rebuild_fn(grow)
                if old_lanes is not None:
                    # re-attach lane isolation at the grown shapes
                    # FIRST (the telemetry ring sizes its per-lane
                    # planes off sim.lanes) so the transplant finds
                    # matching .lanes / overflow-plane leaves and
                    # containment survives the heal
                    from shadow_tpu.core import lanes as lanes_mod

                    bundle.sim = lanes_mod.attach(
                        bundle.sim, old_lanes.replicas,
                        stall_limit=old_lanes.stall_limit)
                if old_telem is not None:
                    from shadow_tpu.telemetry.ring import attach

                    bundle.sim = attach(bundle.sim,
                                        capacity=old_telem.capacity)
                if old_inject is not None:
                    # keep the staging buffer across the heal (same
                    # lane count) so the snapshot transplant below
                    # finds matching .inject leaves and the feeder's
                    # sync() resumes the trace without replay
                    from shadow_tpu.inject.staging import attach as \
                        inject_attach

                    bundle.sim = inject_attach(bundle.sim,
                                               old_inject.lanes)
                if old_caps is not None:
                    # re-derive the capability vector at the grown
                    # shapes (capacity growth cannot change it — the
                    # reliability table and handler set are capacity-
                    # independent) so the transplant below finds the
                    # snapshot's guard leaves in the template and the
                    # healed program stays trimmed under the same key
                    # discipline (compile/specialize.py)
                    from shadow_tpu.compile import specialize as \
                        specialize_mod

                    bundle = specialize_mod.apply(
                        bundle, app_handlers,
                        app_bulk=getattr(bundle, "app_bulk", None))
                # a caller-supplied fault_fn closes over the OLD
                # shapes; drop it — run_windows re-resolves from the
                # rebuilt bundle's installed plan
                fault_fn = None
                escalations.extend(events)
                grows_used += len(events)
                escalation_restarts += 1
                if total_saved:
                    path, t = total_saved[-1]
                    say(f"supervisor: transplanting {path} (t={t}) "
                        f"into grown shapes")
                    leaves, meta = ckpt.load_leaves(path)
                    resume_sim, resume_time, extra = \
                        escalate_mod.transplant(leaves, meta, bundle.sim)
                    base_stats = dict(extra.get("stats", {}))
                    resumed_from = path
                else:
                    say("supervisor: no snapshot yet, rebooting at "
                        "grown capacity")
                    resume_sim, resume_time = None, 0
                    base_stats = {}
                continue  # a heal consumes no retry and sleeps never
            if retries_used >= max_retries:
                # carry the tripped sim so the caller can still report
                # (object counts, manifest counters) from it
                return _result(False, trip.sim, trip.health, stats=None)
            retries_used += 1
            if total_saved:
                path, t = total_saved[-1]
                say(f"supervisor: resuming from {path} (t={t}) after "
                    f"backoff")
                resume_sim, resume_time, extra = ckpt.load(path, bundle.sim)
                base_stats = dict(extra.get("stats", {}))
                resumed_from = path
            else:
                say("supervisor: no snapshot yet, restarting from boot")
                resume_sim, resume_time = None, 0
                resumed_from = None
                base_stats = {}
            sleep(backoff_s * (2 ** (retries_used - 1)))
