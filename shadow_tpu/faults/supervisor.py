"""Run supervisor: window loop + health latches + checkpoint-backed
recovery.

The CLI's `--supervise` mode runs the simulation through here instead
of the one-shot jitted runner. Every round the supervisor inspects
the sticky latches (faults/health.py) plus its own stall /
time-regression telemetry; every N *windows* it snapshots the sim
(utils/checkpoint.py — atomic + checksummed, so a trip mid-save can
never leave a poisoned resume point). When a fatal latch trips it
restores the last good snapshot, backs off exponentially, and retries
up to max_retries before giving up with a structured failure report.

Retrying after a *deterministic* trip only helps when the operator's
knobs differ between attempts (the retry hook bumps nothing itself —
determinism is the whole point), but crashes of the host process,
preemptions, and transient device loss are exactly what the
checkpoint chain is for; the bounded retry covers those while the
structured report covers the deterministic case.

Checkpoint cadence is counted in windows, not sim-ns: window length
tracks min_jump, so N windows is a stable amount of device work
regardless of the topology's latency floor.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Optional

import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.faults import health as health_mod
from shadow_tpu.utils import checkpoint as ckpt


class LatchTrip(RuntimeError):
    """A fatal health latch fired mid-run. Carries the sim state at the
    trip so the failure path can still dump diagnostics (object counts,
    final counters for the run manifest)."""

    def __init__(self, health: health_mod.RunHealth, sim=None):
        self.health = health
        self.sim = sim
        msgs = "; ".join(m for s, m in health.diagnostics() if s == "fatal")
        super().__init__(msgs or "health latch tripped")


@dataclasses.dataclass
class SupervisorResult:
    ok: bool
    sim: object
    stats: object                      # EngineStats totals (last attempt)
    health: health_mod.RunHealth       # final latch snapshot
    attempts: int = 1
    resumed_from: Optional[str] = None  # snapshot path of the last resume
    checkpoints: tuple = ()            # (path, time_ns) saved, all attempts

    def failure_report(self) -> dict:
        rep = self.health.failure_report()
        rep["attempts"] = self.attempts
        rep["resumed_from"] = self.resumed_from
        return rep


def run_supervised(bundle, app_handlers=(), *, fault_fn=None,
                   end_time=None, checkpoint_path,
                   checkpoint_every_windows: int = 64,
                   max_retries: int = 2, backoff_s: float = 0.25,
                   stall_windows: int = 512,
                   log=None, on_window=None, harvester=None,
                   sleep=_time.sleep) -> SupervisorResult:
    """Run bundle to end_time under supervision. Serial runner only
    (the host must regain control at every window barrier); the CLI
    routes --supervise to it. `log` is a callable taking one message
    string; `sleep` is injectable for tests. `harvester`
    (telemetry.Harvester) is drained every round — "between supervisor
    checkpoints" — and its loss count rides the health snapshot as a
    warning; its rewind handling keeps resumed attempts from
    double-counting replayed windows."""

    def say(msg):
        if log is not None:
            log(msg)

    total_saved = []
    attempt = 0
    resume_sim = None
    resume_time = 0
    resumed_from = None

    while True:
        attempt += 1
        # Per-attempt telemetry the on_round closure mutates.
        tele = {"zero_streak": 0, "worst_streak": 0, "regressed": False,
                "wstart": None, "since_ckpt": 0}

        def on_round(sim, wstats, wstart, wend, next_min):
            tele["wstart"] = wstart
            if int(np.asarray(wstats.events_processed)) == 0:
                tele["zero_streak"] += 1
                tele["worst_streak"] = max(tele["worst_streak"],
                                           tele["zero_streak"])
            else:
                tele["zero_streak"] = 0
            # Runahead may legally schedule inside the current window
            # (next_min < wend); only a start-regression is corrupt.
            if next_min < wstart:
                tele["regressed"] = True
            if harvester is not None:
                harvester.drain(sim)
            h = _gather(sim)
            if h.fatal:
                raise LatchTrip(h, sim)
            tele["since_ckpt"] += 1
            if (tele["since_ckpt"] >= checkpoint_every_windows
                    and next_min < simtime.INVALID):
                # Healthy at this barrier: snapshot resumes at next_min.
                p = ckpt.save(f"{checkpoint_path}.{next_min}", sim,
                              time_ns=next_min)
                total_saved.append((p, next_min))
                tele["since_ckpt"] = 0
            if on_window is not None:
                on_window(sim, wend)

        def _gather(sim):
            return health_mod.gather(
                sim,
                window_start=tele["wstart"],
                stalled_windows=tele["worst_streak"],
                stall_limit=stall_windows,
                time_regression=tele["regressed"],
                telemetry_lost=(harvester.records_lost
                                if harvester is not None else 0),
            )

        try:
            sim, stats, _ = ckpt.run_windows(
                bundle, app_handlers,
                end_time=end_time,
                start_time=resume_time,
                sim=resume_sim,
                fault_fn=fault_fn,
                on_round=on_round,
            )
            if harvester is not None:
                harvester.drain(sim)
            h = _gather(sim)
            if h.fatal:
                raise LatchTrip(h, sim)
            return SupervisorResult(
                ok=True, sim=sim, stats=stats, health=h,
                attempts=attempt, resumed_from=resumed_from,
                checkpoints=tuple(total_saved))
        except LatchTrip as trip:
            say(f"supervisor: latch trip on attempt {attempt}: {trip}")
            if attempt > max_retries:
                # carry the tripped sim so the caller can still report
                # (object counts, manifest counters) from it
                return SupervisorResult(
                    ok=False, sim=trip.sim, stats=None, health=trip.health,
                    attempts=attempt, resumed_from=resumed_from,
                    checkpoints=tuple(total_saved))
            if total_saved:
                path, t = total_saved[-1]
                say(f"supervisor: resuming from {path} (t={t}) after "
                    f"backoff")
                resume_sim, resume_time, _ = ckpt.load(path, bundle.sim)
                resumed_from = path
            else:
                say("supervisor: no snapshot yet, restarting from boot")
                resume_sim, resume_time = None, 0
                resumed_from = None
            sleep(backoff_s * (2 ** (attempt - 1)))
