"""Build a runnable simulation from topology + host specs.

This is the device-era analog of the reference's startup path
(ref: master.c:161-398 / slave.c:296-336): load + validate topology,
register every host with DNS, attach hosts to vertices via the hint
rules, derive the conservative window from the minimum path latency,
and initialize the struct-of-arrays device state. Process starts are
seeded as PROC_START events (ref: process.c:1326-1360).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import (
    EngineStats,
    _default_route,
    make_chunk_body,
    make_wend_fn,
    resolve_sparse_lanes,
)
from shadow_tpu.core.engine import run as engine_run
from shadow_tpu.core.events import EventKind, emit_words, push_rows
from shadow_tpu.parallel.elastic import make_sentinel_fn
from shadow_tpu.telemetry.flows import make_flow_fn
from shadow_tpu.telemetry.ring import make_telem_fn
from shadow_tpu.net.state import (
    NetConfig,
    NetState,
    Sim,
    make_net_state,
    make_sim,
)
from shadow_tpu.net.step import make_step_fn
from shadow_tpu.routing.dns import DNS
from shadow_tpu.routing.graphml import parse_graphml
from shadow_tpu.routing.topology import Topology


@dataclass
class HostSpec:
    """One virtual host (ref: <host> config element,
    configuration.h:62-101)."""

    name: str
    ip: str | None = None            # requested IP hint
    citycode: str | None = None
    countrycode: str | None = None
    geocode: str | None = None
    type: str | None = None
    bandwidthdown: int | None = None  # KiB/s override
    bandwidthup: int | None = None
    cpufrequency_khz: int | None = None  # virtual CPU speed (ref:
                                         # host cpufrequency attr)
    proc_start_time: int | None = None  # PROC_START event time (ns)
    proc_stop_time: int | None = None   # PROC_STOP event time (ns)
                                        # (ref: <process stoptime>,
                                        # process.c:1286-1324)

    def hints(self) -> dict:
        out: dict = {}
        for k in ("ip", "citycode", "countrycode", "geocode", "type"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        for k in ("bandwidthdown", "bandwidthup"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


@dataclass
class SimBundle:
    cfg: NetConfig
    sim: Sim
    topology: Topology
    dns: DNS
    min_jump: int
    host_names: list[str]
    name_to_index: dict[str, int] = field(default_factory=dict)
    # Optional net.bulk.AppBulk installed by the configured app model
    # (config/loader.py): turns on the bulk window pass wherever the
    # bundle is run (CLI serial, sharded, bench).
    app_bulk: Any = None
    # Optional faults.plan.FaultPlan attached by faults.install():
    # runners derive the window-boundary fault_fn from it (and the
    # boot sim) via faults.fault_fn_for(bundle).
    fault_plan: Any = None
    # Optional rebuild(overrides: dict) -> SimBundle installed by
    # config/loader.py: re-run the whole load (topology, app setup,
    # fault install) with capacity overrides merged in. This is the
    # escalation path's lever (faults/escalate.py) — a grown capacity
    # needs a fresh Sim AND fresh step/fault closures, because every
    # compiled function shape-specializes on the boot arrays.
    rebuild: Any = None
    # Optional compile/specialize.Capabilities attached by
    # specialize.apply(): the runner factories below thread it into
    # the step/bulk builders (dead subgraphs are omitted from the
    # trace) and fold it into the program key when anything was
    # dropped. None = full (unspecialized) program. Escalation regrow
    # must re-derive it (a rebuilt bundle starts unspecialized).
    caps: Any = None

    def ip_of(self, name: str) -> int:
        return self.dns.resolve_name(name).ip

    def host_of(self, name: str) -> int:
        return self.name_to_index[name]


def build(cfg: NetConfig, graphml_text: str, hosts: Sequence[HostSpec],
          app: Any = None) -> SimBundle:
    if len(hosts) != cfg.num_hosts:
        raise ValueError(f"cfg.num_hosts={cfg.num_hosts} != {len(hosts)} specs")
    top = Topology(parse_graphml(graphml_text))
    dns = DNS()
    names = []
    for i, h in enumerate(hosts):
        dns.register(i, h.name, requested_ip=h.ip)
        names.append(h.name)

    # attach draws come from the deterministic seed hierarchy
    # (ref: master.c:417 -> slave.c:301): one uniform per host in
    # registration order.
    draws = np.random.default_rng(cfg.seed).random(len(hosts))
    placement = top.attach_hosts([h.hints() for h in hosts], draws)
    min_jump = top.min_jump_ns(placement)

    # Sequentially-allocated IPs (no config pinned an address out of
    # order) unlock the arithmetic IP fast path in the bulk passes
    # (state.ip_of_hosts) — detected here where the table is still
    # host-side numpy, and threaded through the bundle's cfg so every
    # step/bulk function built from it agrees.
    host_ips = dns.host_ips(cfg.num_hosts)
    if cfg.num_hosts and np.array_equal(
            host_ips, host_ips[0] + np.arange(cfg.num_hosts)):
        from dataclasses import replace as _dc_replace
        cfg = _dc_replace(cfg, ip_affine_base=int(host_ips[0]))

    net = make_net_state(
        cfg,
        host_ips=host_ips,
        bw_up_kibps=placement.bw_up_kibps,
        bw_down_kibps=placement.bw_down_kibps,
        vertex_of_host=placement.vertex,
        latency_ns=top.latency_ns,
        reliability=top.reliability,
        cpu_freq_khz=np.array(
            [h.cpufrequency_khz or 0 for h in hosts], np.int64),
    )
    sim = make_sim(cfg, net, app=app)

    # seed PROC_START / PROC_STOP events (ref: host_boot ->
    # process_schedule, process.c:1326-1360)
    H = cfg.num_hosts
    for attr, kind in ((lambda h: h.proc_start_time, EventKind.PROC_START),
                       (lambda h: h.proc_stop_time, EventKind.PROC_STOP)):
        times = np.full(cfg.num_hosts, -1, dtype=np.int64)
        for i, h in enumerate(hosts):
            t = attr(h)
            if t is not None:
                times[i] = t
        m = times >= 0
        if m.any():
            q = push_rows(
                sim.events,
                jnp.asarray(m),
                jnp.asarray(np.where(m, times, 0), simtime.DTYPE),
                jnp.full((H,), kind, jnp.int32),
                jnp.arange(H, dtype=jnp.int32),
                sim.events.next_seq,
                emit_words(0, num_hosts=H),
            )
            q = q.replace(next_seq=q.next_seq + jnp.asarray(m, jnp.int32))
            sim = sim.replace(events=q)

    return SimBundle(
        cfg=cfg, sim=sim, topology=top, dns=dns, min_jump=min_jump,
        host_names=names, name_to_index={n: i for i, n in enumerate(names)},
    )


def _resolve_bulk_fn(bundle: SimBundle, app_bulk, app_tcp_bulk,
                     tcp_bulk_lossless: bool = False, caps=None):
    """One bulk-pass selection rule for every runner flavor (the UDP
    bulk wins when both are given; make_bulk_fn's order_impl is a
    separate knob with its own vocabulary, not forwarded).
    tcp_bulk_lossless compiles the narrow loss-free TCP pass — see
    make_tcp_bulk_fn (bit-identical for any workload; faster when the
    workload is genuinely artifact-free). `caps` is the bundle's
    capability vector (compile/specialize.py) — the bulk builders trim
    their reliability-draw subgraphs under it."""
    if app_bulk is not None:
        from shadow_tpu.net.bulk import make_bulk_fn

        fn = make_bulk_fn(bundle.cfg, app_bulk, caps=caps)
        if fn is not None:
            return fn
    if app_tcp_bulk is not None:
        from shadow_tpu.net.tcp_bulk import make_tcp_bulk_fn

        return make_tcp_bulk_fn(bundle.cfg, app_tcp_bulk,
                                lossless=tcp_bulk_lossless, caps=caps)
    return None


def _resolve_caps(bundle: SimBundle, caller_fault_fn):
    """The capability vector a runner may trim under. An explicit
    caller fault_fn is OPAQUE — its closure could rewrite any table
    (e.g. re-introduce loss) invisibly to the static analysis — so it
    disables specialization exactly like it disables warm serving
    (_whole_run_key_fn). The installed-plan path (bundle.fault_plan)
    stays trimmable: derive() already folded the plan's record kinds
    into the vector."""
    caps = getattr(bundle, "caps", None)
    if caller_fault_fn is not None:
        if caps is not None and caps.dropped():
            # the specialized sim already carries the guard latch —
            # running it under a full (untrimmed) program would turn
            # any table rewrite by this opaque fault_fn into a false
            # fatal. Refuse loudly instead of mis-reporting.
            raise ValueError(
                "explicit fault_fn on a specialized bundle: an opaque "
                "fault rule defeats the static capability analysis — "
                "rebuild with specialize.apply(mode='off') or install "
                "the plan via faults.install()")
        return None
    return caps


def _caps_meta(caps):
    """Store-sidecar block for a trimmed program (compcache_ctl ls
    shows it next to the bucket plan); None when nothing was dropped
    so untrimmed sidecars are unchanged."""
    if caps is None or not caps.dropped():
        return None
    return {"specialization": caps.as_dict()}


def _resolve_fault_fn(bundle: SimBundle, fault_fn):
    """Every runner flavor applies a bundle's installed fault plan by
    default — a config-driven schedule must hold wherever the bundle
    runs (serial, chunked, sharded, bench). An explicit fault_fn
    overrides."""
    if fault_fn is not None:
        return fault_fn
    if getattr(bundle, "fault_plan", None) is not None:
        from shadow_tpu.faults.apply import fault_fn_for

        return fault_fn_for(bundle)
    return None


def adaptive_jump_spec(bundle: SimBundle):
    """Constants for the adaptive time jump (engine.make_wend_fn):
    ``(pair_mask, fault_times)``.

    pair_mask is the [V,V] bool set of vertex pairs that constrain the
    conservative window — ordered pairs of distinct host-bearing
    vertices, plus the self-path of any vertex carrying >= 2 hosts —
    exactly topology.min_jump_ns's pair rules, but evaluated on device
    against the LIVE latency/reliability tables each window instead of
    once at boot. fault_times is the installed plan's record times
    (None when no plan): wend clamps to the next record so every fault
    still materializes at a window boundary."""
    voh = np.asarray(bundle.sim.net.vertex_of_host)
    V = int(np.asarray(bundle.sim.net.latency_ns).shape[0])
    mask = np.zeros((V, V), dtype=bool)
    if voh.size:
        verts, counts = np.unique(voh, return_counts=True)
        mask[np.ix_(verts, verts)] = True
        mask[np.arange(V), np.arange(V)] = False
        for v, c in zip(verts, counts):
            if c >= 2:
                mask[v, v] = True
    return mask, plan_times(bundle)


def plan_times(bundle: SimBundle):
    """The installed fault plan's unique record times (None without a
    plan) — the wend clamp every window rule shares so records land at
    window boundaries exactly (engine.make_wend_fn / engine.run)."""
    plan = getattr(bundle, "fault_plan", None)
    if plan is not None and getattr(plan, "n", 0):
        return np.unique(np.asarray(plan.t_ns, np.int64))
    return None


def resolve_wend_fn(bundle: SimBundle, end_time: int, adaptive: bool,
                    fault_fn=None):
    """One window-end rule for every chunked runner: the reference's
    static ``wstart + min_jump`` (adaptive=False), or the live-table
    adaptive jump. `fault_fn` is the rule the runner resolved (post
    _resolve_fault_fn): adaptive mode needs the fault schedule's
    record times to stay conservative, so an opaque fault_fn with no
    installed plan is rejected — it could revive a short link in the
    middle of a window that was sized without it. Both modes clamp
    wend at the next record time so faults apply exactly on schedule
    and the executed event stream is invariant to the window
    partitioning (static vs adaptive, any windows_per_dispatch)."""
    if not adaptive:
        return make_wend_fn(min_jump=bundle.min_jump, end_time=end_time,
                            fault_times=plan_times(bundle))
    if fault_fn is not None and getattr(bundle, "fault_plan", None) is None:
        raise ValueError(
            "adaptive_jump requires the fault plan's record times "
            "(faults.install) — cannot bound an opaque fault_fn's "
            "table rewrites")
    mask, ft = adaptive_jump_spec(bundle)
    tf = None
    if getattr(bundle, "fault_plan", None) is not None:
        from shadow_tpu.faults.apply import make_table_fn

        # Size windows from the plan replay at wstart + 1, never the
        # live sim tables: step_window rewrites those only after the
        # span is chosen, so a window starting exactly at a restore
        # record would see the stale pre-restore latency (see
        # make_wend_fn's guard list).
        tf = make_table_fn(bundle.fault_plan, bundle.sim)
    return make_wend_fn(min_jump=bundle.min_jump, end_time=end_time,
                        pair_mask=mask, fault_times=ft, table_fn=tf)


def _whole_run_key_fn(bundle: SimBundle, app_handlers, *, end, path,
                      chunk_windows, adaptive, fault_fn, app_bulk,
                      app_tcp_bulk, tcp_bulk_lossless=False,
                      route_impl=None, shards=1,
                      exchange_capacity=None, caps=None):
    """Lazy program-key rule for the whole-run factories (compile/):
    the shape vector comes from the FIRST call's sim (telemetry /
    lane / injection attachments change the traced pytree, and the
    factory's callable accepts any of them), everything else is fixed
    at factory time. Returns None — warm serving disabled — when the
    caller passed an opaque fault_fn: its closure constants are baked
    into the trace but invisible to the key."""
    if fault_fn is not None:
        return None

    def _key(args, kwargs):
        from shadow_tpu.compile import buckets
        from shadow_tpu.telemetry.export import fault_plan_digest

        fp = getattr(bundle, "fault_plan", None)
        extra = {"path": path, "route_impl": route_impl,
                 "tcp_bulk_lossless": bool(tcp_bulk_lossless),
                 "tcp_bulk": (type(app_tcp_bulk).__name__
                              if app_tcp_bulk is not None else None)}
        if caps is not None and caps.key_extra() is not None:
            # trimmed variants are DIFFERENT executables — key them
            # apart so they coexist in the store next to their full
            # twins. Untrimmed specialized builds contribute nothing:
            # their program is byte-identical to the unspecialized one
            # and must share its key (and its warm artifacts).
            extra["caps"] = caps.key_extra()
        census = buckets.kind_census(
            app_handlers, app_bulk,
            fault_plan_digest=(fault_plan_digest(fp)
                               if fp is not None else None))
        shapes = buckets.shape_vector_for_sim(bundle.cfg, args[0])
        return buckets.program_key(
            shapes, shards=int(shards), chunk_windows=chunk_windows,
            adaptive=adaptive, census=census, end_time=int(end),
            min_jump=bundle.min_jump,
            exchange_capacity=exchange_capacity, extra=extra)

    return _key


def make_runner(bundle: SimBundle, app_handlers=(),
                end_time: int | None = None, app_bulk=None,
                app_tcp_bulk=None,
                route_impl: str | None = None,
                tcp_bulk_lossless: bool = False,
                fault_fn=None, warm_start: bool | None = None,
                compile_info: dict | None = None):
    """Build a jitted sim -> (sim, stats) callable for the whole run.
    Reuse it across calls: tracing the full netstack in Python costs
    seconds per call at this op count; a reused jitted callable pays
    it once and then hits the C++ dispatch fast path (this is what a
    benchmark's timed iteration must call).

    `app_bulk` (a net.bulk.AppBulk) turns on the bulk window pass:
    eligible hosts' whole windows are consumed in one vectorized pass
    per window instead of one micro-step per event, bit-identically
    (see net/bulk.py).

    `route_impl` ("sort"/"count") overrides the outbox-insert
    mechanism when the arrays live on a different backend than
    jax.default_backend() — e.g. CPU-pinned state on a TPU host
    (values are bit-identical either way; perf-only, mirrors
    make_bulk_fn's order_impl). "sort2" is also accepted but must NOT
    be used as an off-backend override on a TPU host: its Pallas
    mailbox kernel is gated on jax.default_backend() at trace time
    (array placement is unknowable under jit), so tracing it against
    CPU-pinned state would compile the TPU-only kernel. Use "sort"
    for CPU-pinned overrides.

    `warm_start` serves the program from the persistent AOT store
    (compile/) — a stored program for this shape loads without
    retracing the netstack; SHADOW_WARM_PROGRAMS overrides, and
    `compile_info` (a dict) receives the {key, hit, load_s|compile_s}
    block at the first call."""
    caller_fault_fn = fault_fn
    caps = _resolve_caps(bundle, caller_fault_fn)
    step = make_step_fn(bundle.cfg, app_handlers, caps=caps)
    end = end_time if end_time is not None else bundle.cfg.end_time
    bulk_fn = _resolve_bulk_fn(bundle, app_bulk, app_tcp_bulk,
                               tcp_bulk_lossless, caps=caps)
    fault_fn = _resolve_fault_fn(bundle, fault_fn)
    route_fn = _default_route
    if route_impl is not None:
        from shadow_tpu.core.events import route_outbox

        def route_fn(sim):
            q, out = route_outbox(sim.events, sim.outbox, impl=route_impl)
            return sim.replace(events=q, outbox=out)

    # trace-time no-ops unless telemetry.attach()ed /
    # telemetry.attach_flows()ed to the input sim
    telem_fn = make_telem_fn()
    flow_fn = make_flow_fn()

    def _go(sim):
        return engine_run(
            sim, step, end_time=end, min_jump=bundle.min_jump,
            emit_capacity=bundle.cfg.emit_capacity,
            lane_id=sim.net.lane_id,
            route_fn=route_fn,
            bulk_fn=bulk_fn,
            fault_fn=fault_fn,
            telem_fn=telem_fn,
            flow_fn=flow_fn,
            sparse_lanes=resolve_sparse_lanes(bundle.cfg),
            fault_times=plan_times(bundle),
            # serial identity sentinel: never trips, but advances the
            # verified-through ledger (trace-time no-op when off)
            sentinel_fn=make_sentinel_fn(None),
        )

    from shadow_tpu.compile import serve

    return serve.maybe_warm(
        jax.jit(_go),
        _whole_run_key_fn(bundle, app_handlers, end=end, path="whole",
                          chunk_windows=0, adaptive=False,
                          fault_fn=caller_fault_fn, app_bulk=app_bulk,
                          app_tcp_bulk=app_tcp_bulk,
                          tcp_bulk_lossless=tcp_bulk_lossless,
                          route_impl=route_impl, caps=caps),
        enabled=serve.warm_enabled(default=bool(warm_start)),
        meta=_caps_meta(caps),
        info=compile_info)


def make_chunked_runner(bundle: SimBundle, app_handlers=(),
                        end_time: int | None = None, app_bulk=None,
                        app_tcp_bulk=None, chunk_windows: int = 256,
                        tcp_bulk_lossless: bool = False,
                        fault_fn=None, adaptive_jump: bool = False,
                        warm_start: bool | None = None,
                        compile_info: dict | None = None):
    """make_runner variant that executes `chunk_windows` windows per
    device call with a host-side outer loop — window-for-window the
    SAME sequence engine.run's single while_loop produces (advance
    rule newStart = minNext, master.c:450-480), so results are
    bit-identical.

    Why it exists: one device call covering a whole long simulation
    (the real-topology regime: 200 windows per sim-second) can exceed
    a backend's per-execution limits (observed on the tunneled v5e:
    relay runs on the reference topology die with UNAVAILABLE while
    the identical computation split into shorter calls completes).
    Chunking bounds single-call execution time at a few hundred
    windows and costs one dispatch per chunk.

    The host loop is pipelined: one speculative chunk is always in
    flight, and the loop only synchronizes on the PREVIOUS chunk's
    wstart while the next executes (a chunk dispatched past the end is
    a no-op — make_chunk_body guards every window on wstart <= end).
    The sim pytree is donated to each dispatch, so steady-state device
    allocation is one sim regardless of chunk count; the caller's
    input sim is copied once at entry and stays intact.

    `adaptive_jump` swaps the static min_jump window for the
    live-table rule (resolve_wend_fn / engine.make_wend_fn): window
    boundaries then differ from the static run wherever a fault plan
    raised latencies, but the final state is reachable-event
    identical — the conservative window invariant makes results
    independent of the partition into windows."""
    if chunk_windows < 1:
        raise ValueError(
            f"chunk_windows must be >= 1, got {chunk_windows} "
            "(0 iterations would spin the host loop forever)")

    caller_fault_fn = fault_fn
    caps = _resolve_caps(bundle, caller_fault_fn)
    step = make_step_fn(bundle.cfg, app_handlers, caps=caps)
    end = int(end_time if end_time is not None else bundle.cfg.end_time)
    bulk_fn = _resolve_bulk_fn(bundle, app_bulk, app_tcp_bulk,
                               tcp_bulk_lossless, caps=caps)
    fault_fn = _resolve_fault_fn(bundle, fault_fn)
    telem_fn = make_telem_fn()
    wend_fn = resolve_wend_fn(bundle, end, adaptive_jump, fault_fn)

    chunk = make_chunk_body(
        step, end_time=end, wend_fn=wend_fn,
        chunk_windows=int(chunk_windows),
        emit_capacity=bundle.cfg.emit_capacity,
        lane_fn=lambda s: s.net.lane_id,
        bulk_fn=bulk_fn, fault_fn=fault_fn, telem_fn=telem_fn,
        sparse_lanes=resolve_sparse_lanes(bundle.cfg),
        flow_fn=make_flow_fn(), sentinel_fn=make_sentinel_fn(None))
    from shadow_tpu.compile import serve

    k_windows = serve.maybe_warm(
        jax.jit(chunk, donate_argnums=(0,)),
        _whole_run_key_fn(bundle, app_handlers, end=end,
                          path="whole_chunk",
                          chunk_windows=int(chunk_windows),
                          adaptive=bool(adaptive_jump),
                          fault_fn=caller_fault_fn, app_bulk=app_bulk,
                          app_tcp_bulk=app_tcp_bulk,
                          tcp_bulk_lossless=tcp_bulk_lossless,
                          caps=caps),
        enabled=serve.warm_enabled(default=bool(warm_start)),
        meta=_caps_meta(caps),
        info=compile_info)

    def go(sim):
        # Donation consumes the sim argument buffers; copy once so the
        # caller's (usually bundle.sim) survives repeated go() calls.
        sim = jax.tree_util.tree_map(jnp.copy, sim)
        stats = EngineStats.create()
        wstart = jnp.min(sim.events.min_time())
        sim, stats, wstart = k_windows(sim, stats, wstart)
        while True:
            # Keep one chunk in flight: dispatch i+1 on chunk i's
            # as-yet-unresolved outputs, then block on chunk i's
            # wstart alone — the old loop's device_get(wstart) barrier
            # between every chunk left the device idle for a full host
            # round-trip per chunk.
            nsim, nstats, nwstart = k_windows(sim, stats, wstart)
            if int(wstart) > end:
                # Chunk i already ran past the end, so the speculative
                # chunk was a pure no-op: its outputs ARE chunk i's.
                return nsim, nstats
            sim, stats, wstart = nsim, nstats, nwstart

    return go


def run(bundle: SimBundle, app_handlers=(), end_time: int | None = None,
        app_bulk=None):
    """Run the whole simulation on device; returns (sim, stats)."""
    return make_runner(bundle, app_handlers, end_time,
                       app_bulk=app_bulk)(bundle.sim)
