"""Socket table operations.

The reference's descriptor table + per-interface bound-socket hash
(ref: host.c:696-767, network_interface.c:255-308) become row scans
over the [H,S] socket arrays: a "bind" writes the (ip,port) columns, a
delivery "lookup" is a vectorized match over the row, preferring the
general (peer-less) association first exactly like the reference
(network_interface.c:388-403).
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core.events import fit_words
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net.rings import (
    gather_hs,
    ring_advance_push,
    ring_push_at,
    set_hs,
    set_ring,
)
from shadow_tpu.net.state import NetState, SocketFlags, SocketType

I32 = jnp.int32
MIN_RANDOM_PORT = 10000  # ref: definitions.h:94


def set_writable(net: NetState, mask, slot, on):
    """Set/clear WRITABLE for (lane, slot), bumping the out-readiness
    generation on the not-writable -> writable transition (the edge ET
    epoll watches key off; ref: descriptor_adjustStatus ->
    epoll.c:583). The single helper keeps the NIC-drain, TCP-ACK, and
    enqueue-full call sites consistent."""
    fl = gather_hs(net.sk_flags, slot)
    on = jnp.broadcast_to(jnp.asarray(on, bool), mask.shape)
    edge = mask & on & ((fl & SocketFlags.WRITABLE) == 0)
    return net.replace(
        sk_flags=set_hs(
            net.sk_flags, mask, slot,
            jnp.where(on, fl | SocketFlags.WRITABLE,
                      fl & ~SocketFlags.WRITABLE)),
        sk_out_gen=set_hs(net.sk_out_gen, edge, slot,
                          gather_hs(net.sk_out_gen, slot) + 1),
    )


def sk_enqueue_out(net: NetState, mask, slot, words):
    """Push one fully-formed packet ([H, NWORDS]) onto (lane, slot)'s
    output ring, charging W_LEN payload bytes against the send buffer
    (ref: socket_addToOutputBuffer, socket.h:47-78) and stamping the
    per-host app-ordering priority (ref: host.c packet priority
    counter). Returns (net, ok[H]) — ok False when the ring or send
    buffer lacks space (the EWOULDBLOCK condition)."""
    H = mask.shape[0]
    lane = jnp.arange(H)
    BO = net.out_words.shape[2]
    words = fit_words(words, net.out_words.shape[-1])
    length = words[:, pf.W_LEN]

    space_ok = (gather_hs(net.out_bytes, slot) + length) <= gather_hs(
        net.sk_sndbuf, slot
    )
    ok, pos = ring_push_at(net.out_head, net.out_count, BO, mask & space_ok, slot)
    net = net.replace(
        out_words=set_ring(net.out_words, ok, slot, pos, words),
        out_priority=set_ring(net.out_priority, ok, slot, pos,
                              net.priority_ctr),
        priority_ctr=net.priority_ctr + ok.astype(net.priority_ctr.dtype),
    )
    _, count = ring_advance_push(net.out_head, net.out_count, mask, slot, ok)
    ob = gather_hs(net.out_bytes, slot)
    net = net.replace(
        out_count=count,
        out_bytes=set_hs(net.out_bytes, ok, slot, ob + length),
    )
    # Writable status tracks output capacity for datagram sockets
    # (ref: descriptor_adjustStatus WRITABLE): clear when the ring or
    # byte budget is exhausted — including when THIS enqueue failed (or
    # an EPOLLOUT waiter livelocks retrying) — and let the NIC drain
    # restore it. TCP sockets are excluded: their app-visible
    # writability is STREAM-buffer room, managed by tcp_send / the ACK
    # path; this ring is internal segment staging there (pure ACKs
    # piling up during a token stall must not eat the app's WRITABLE,
    # which no TCP path would ever restore for a data-less socket).
    full = mask & (gather_hs(net.sk_type, slot) != SocketType.TCP) \
        & (~ok
           | (gather_hs(net.out_count, slot) >= BO)
           | (gather_hs(net.out_bytes, slot)
              >= gather_hs(net.sk_sndbuf, slot)))
    net = set_writable(net, full, slot, False)
    return net, ok


def sk_create(net: NetState, mask, stype):
    """Allocate one socket per masked lane (first free slot). Returns
    (net, slot[H] — -1 where full/unmasked)."""
    free = net.sk_type == SocketType.NONE  # [H,S]
    has = jnp.any(free, axis=1)
    slot = jnp.argmax(free, axis=1)
    ok = mask & has
    slot = jnp.where(ok, slot, -1)
    stype_b = jnp.broadcast_to(jnp.asarray(stype, I32), mask.shape)
    net = net.replace(
        sk_type=set_hs(net.sk_type, ok, slot, stype_b),
        sk_flags=set_hs(
            net.sk_flags, ok, slot,
            jnp.full(mask.shape, SocketFlags.ACTIVE | SocketFlags.WRITABLE, I32),
        ),
        # object accounting (ref: object_counter.c new counts)
        ctr_sk_alloc=net.ctr_sk_alloc + ok.astype(jnp.int64),
    )
    return net, slot


def sk_bind(net: NetState, mask, slot, ip, port):
    """Bind masked lanes' socket `slot` to (ip, port); port 0 draws an
    ephemeral port (counter-based analog of the reference's random
    free-port search, host.c:1058-1110 — deterministic per host)."""
    eph = MIN_RANDOM_PORT + net.port_ctr
    use_eph = mask & (jnp.asarray(port) == 0)
    port = jnp.where(use_eph, eph, port)
    net = net.replace(
        port_ctr=net.port_ctr + use_eph.astype(I32),
        sk_bound_ip=set_hs(net.sk_bound_ip, mask, slot,
                           jnp.asarray(ip, net.sk_bound_ip.dtype)),
        sk_bound_port=set_hs(net.sk_bound_port, mask, slot,
                             jnp.asarray(port, I32)),
    )
    return net, port


def sk_connect_peer(net: NetState, mask, slot, peer_ip, peer_port):
    """Set the peer association (UDP connect / TCP connect initiation).
    Auto-binds an ephemeral port if unbound (ref: host.c:1193-1230)."""
    bport = gather_hs(net.sk_bound_port, slot)
    net, _ = sk_bind(net, mask & (bport == 0), slot, 0, 0)
    net = net.replace(
        sk_peer_ip=set_hs(net.sk_peer_ip, mask, slot,
                          jnp.asarray(peer_ip, net.sk_peer_ip.dtype)),
        sk_peer_port=set_hs(net.sk_peer_port, mask, slot,
                            jnp.asarray(peer_port, I32)),
    )
    return net


def sk_set_flag(net: NetState, mask, slot, flag: int, on):
    cur = gather_hs(net.sk_flags, slot)
    new = jnp.where(on, cur | flag, cur & ~flag)
    return net.replace(sk_flags=set_hs(net.sk_flags, mask, slot, new))


def lookup_socket(net: NetState, mask, proto, dst_ip, dst_port, src_ip, src_port):
    """Find the receiving socket slot per lane ([H] -> slot or -1).

    The (peer ip, peer port)-specific association wins over the
    general (peer-less) one, so packets for an established TCP child
    reach the child and only unmatched SYNs reach the listener (ref:
    network_interface.c:375-419 + tcp.c's child multiplexing keyed by
    hash(peerIP,peerPort), tcp.c:91-113,1822-1852 — here children are
    their own socket slots instead of sub-objects of the server)."""
    S = net.sk_type.shape[1]
    pr = jnp.asarray(proto)[:, None]
    dip = jnp.asarray(dst_ip)[:, None]
    dpt = jnp.asarray(dst_port)[:, None]
    sip = jnp.asarray(src_ip)[:, None]
    spt = jnp.asarray(src_port)[:, None]

    base = (
        mask[:, None]
        & (net.sk_type == pr)
        & ((net.sk_flags & SocketFlags.CLOSED) == 0)
        & (net.sk_bound_port == dpt)
        & ((net.sk_bound_ip == 0) | (net.sk_bound_ip == dip))
    )
    general = base & (net.sk_peer_port == 0)
    specific = base & (net.sk_peer_ip == sip) & (net.sk_peer_port == spt)

    def first_slot(m):
        has = jnp.any(m, axis=1)
        return jnp.where(has, jnp.argmax(m, axis=1), -1)

    g = first_slot(general)
    s = first_slot(specific)
    return jnp.where(s >= 0, s, g)
