"""TCP bulk window pass: consume a host's whole window of steady-state
TCP traffic without running the full micro-step pipeline per event.

The serialization floor of TCP workloads is arrival serialization: one
host's K in-window segments take K micro-steps, and each micro-step
pays the WHOLE handler pipeline — pop, the complete TCP receive
machine, NIC drain, queue insertion (docs/4-performance.md "TCP: the
serialization floor"; the reference's per-event cost is one cheap
tcp_processPacket call, tcp.c:1777-2100). This pass replaces those K
full-pipeline micro-steps with K iterations of a ~100x smaller body: a
lax.while_loop whose body pops one event per host from a *candidate*
queue and applies only the reduced steady-state semantics:

  - in-order data-bearing segments (seq == rcv_nxt, flags == ACK):
    router-ring cycle, token charge, rcv_nxt/app_rbytes advance,
    READABLE + in-gen edge, delayed-ACK scheduling
    (ref: tcp.c:1777-2100 in-order path + tcp.c:2066-2091);
  - the app's synchronous consume-and-forward (tcp_recv semantics
    incl. Linux-DRS autotune, then tcp_send + flush on the forward
    socket — the TcpAppBulk contract);
  - pure ACKs: snd_wnd update, RTT/RTO (Karn/Jacobson incl. the first
    sample's BDP buffer sizing), congestion growth via the SAME
    cong.ca_update the serial path calls, snd_una advance,
    send-buffer autotune, RTO re-arm, and the flush of newly
    admissible segments (ref: tcp.c ACK path);
  - flush bursts of ANY length: one flush call packetizes up to
    FLUSH_SEGMENTS segments and chains a same-time TCP_FLUSH
    continuation into the candidate queue, which a later scan
    iteration pops in the exact (time, src, seq) interleaving the
    serial fixpoint would use (ref: tcp.c:1121 drain-while-sendable);
  - segment wiring: out-ring cycle, priority stamps, wire-time header
    stamps (stamp_at_wire parity), per-packet reliability draws at
    the exact serial RNG counters, outbox entries with the exact
    per-source sequence numbers the serial path would assign;
  - delayed-ACK timer fires (incl. stale-generation no-ops), with the
    pure ACK's wire trip;
  - RTX timer fires: stale die, disarmed clear, pending re-emit, and
    a DUE deadline runs the full timeout machinery — slow-start
    collapse, backoff, go-back-N retransmit, re-arm (the r5
    loss-aware widening);
  - the LOSS REGIME (r5, ref: tcp.c:854-1027 + tcp.c:84-89 — the
    steady state of the reference's marquee lossy-topology configs):
    old segments re-ACK; out-of-order segments park in the
    reassembly ranges and elicit an immediate SACK-bearing dup-ACK;
    in-order arrivals merge parked ranges and deliver the full gain;
    arriving SACK blocks replace the sender scoreboard; dup-ACKs
    count up to fast retransmit (3rd dup-ACK: ssthresh/cwnd from the
    configured algorithm, recovery entry, snd_una segment re-sent
    with the sack_clip_len decision rule); partial ACKs re-send;
    full ACKs exit recovery; every outgoing packet carries the
    stamp_at_wire SACK advertisement.

Commit/abort: the pass runs on ALL hosts against candidate state and
raises a per-host `bad` flag the moment anything outside the reduced
model appears — SYN/RST, handshake states, a FIN at the wrong seq or
after a peer FIN (teardown-under-loss stays serial), window-update
ACKs, buffer/token shortfalls, persist conditions, zero-window
probes. Hosts flagged bad DISCARD their
candidate state and fall back to the serial window fixpoint untouched
— exactly like UDP bulk ineligibility (net/bulk.py). For committed
hosts the final state is bit-identical to the serial path by
construction; tests/test_tcp_bulk.py asserts full-sim equality.

Like net/bulk.py this multiplies throughput only when most hosts
commit most windows — the lossless steady state of relay/Tor-shaped
workloads (BASELINE config #3), where handshakes and teardowns are a
few serial windows bracketing thousands of eligible ones.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.events import EventKind, _onehot, _put, _tie_key
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net import tcp_cong as cong
from shadow_tpu.net.rings import gather_hs, set_hs, set_ring
from shadow_tpu.net.sockets import lookup_socket
from shadow_tpu.net.state import (
    NetConfig,
    QDisc,
    RouterQ,
    SocketFlags,
    host_of_ip,
)
from shadow_tpu.net.state import ip_of_hosts
from shadow_tpu.net.tcp import (
    DACK_QUICK_LIMIT,
    DACK_QUICK_NS,
    DACK_SLOW_NS,
    FLUSH_SEGMENTS,
    MAX_BACKOFF,
    MSS,
    RESTART_CWND,
    RTO_MAX_MS,
    RTO_MIN_MS,
    SNDMEM_SKB,
    TCP_WMEM_MAX,
    TCP_RMEM_MAX,
    TcpSt,
    _ms,
    sack_advert,
    sack_clip_len,
)

I32 = jnp.int32
I64 = jnp.int64


class TcpAppBulk:
    """App contract for the TCP bulk pass.

    The serial app handler runs on every micro-step and reacts to
    readiness; the bulk pass instead calls `on_data` once per
    delivered in-order segment (per scan iteration) and expects the
    app to behave like the steady-state relay/server pattern: consume
    everything available synchronously, optionally submit bytes on a
    forward socket at the same instant. Anything richer (accepts,
    connects, closes, partial reads, sends not triggered by this
    delivery) must be excluded by precheck — those hosts take the
    serial path."""

    def precheck(self, cfg: NetConfig, sim) -> jax.Array:
        """[H] bool — hosts whose app is in the steady consume/forward
        state this pass models."""
        raise NotImplementedError

    def on_data(self, cfg: NetConfig, app, mask, slot, nread, now):
        """One in-order delivery on (lane, slot) at `now`: `nread` is
        EVERYTHING available — the arriving segment's fresh bytes plus
        any reassembly-range merge gain — which the pass is about to
        hand to the app in full (the serial tcp_recv return). Apps
        whose single read is bounded below that (partial reads) must
        return ok False. Returns
        (app', ok[H], fwd_mask[H], fwd_slot[H], fwd_bytes[H]):
        ok False where the app would NOT read this socket fully right
        now (host falls back to serial); fwd_* request a tcp_send of
        fwd_bytes on fwd_slot at the same instant (the relay
        store-and-forward)."""
        raise NotImplementedError

    def on_eof(self, cfg: NetConfig, app, mask, slot, now):
        """Peer FIN consumed on (lane, slot) at `now` — the app's
        tcp_recv would report EOF this micro-step. Returns
        (app', ok[H], c1_mask, c1_slot, c2_mask, c2_slot): up to two
        sockets the app tcp_close()s at this instant, in call order
        (the relay closes down_sock then up_conn). ok False falls the
        host back to serial. Default: any EOF is out of model."""
        H = mask.shape[0]
        z = jnp.zeros((H,), bool)
        zi = jnp.zeros((H,), jnp.int32)
        return app, ~mask, z, zi, z, zi


def _gate(pred, fn, ops):
    """lax.cond-skip a section of the scan body when no lane needs it
    (the kind-gated-pipeline trick, net/step.py): every section is a
    masked batch update, so all-false-mask == identity and the skip is
    value-identical. Teardown/timer/push sections run in a tiny
    minority of iterations but would otherwise cost their full op
    graphs every iteration."""
    import jax

    return jax.lax.cond(pred, fn, lambda o: o, ops)


def _flag(bad, why, cond, bit):
    """Raise the abort flag and record WHICH model boundary was hit.
    Bits are assigned in source order; tools/tcp_bulk_debug decodes
    them. The why mask costs one [H] OR per site and is the difference
    between 'the pass doesn't engage' and knowing what to widen next."""
    return bad | cond, why | jnp.where(cond, jnp.int64(bit), 0)


class _Carry(NamedTuple):
    sim: Any
    bad: jax.Array       # [H] bool — host fell out of the model
    why: jax.Array       # [H] i64 — abort-reason bitmask (_flag sites)
    seq_ctr: jax.Array   # [H] i32 — candidate next_seq
    it: jax.Array        # [] i32 iteration guard


def _pop_masked(q, wend, allow):
    """pop_earliest with a per-host allow mask (bad hosts must stop
    popping or the loop never terminates)."""
    t = q.time
    tmin = jnp.min(t, axis=1, keepdims=True)
    is_tmin = t == tmin
    tie = jnp.where(is_tmin, _tie_key(q.src, q.seq),
                    jnp.iinfo(jnp.int64).max)
    idx = jnp.argmin(tie, axis=1)
    rows = jnp.arange(q.num_hosts)
    ptime = t[rows, idx]
    valid = allow & (ptime < jnp.asarray(wend, simtime.DTYPE))
    sel = _onehot(valid, idx, q.capacity)
    q = q.replace(time=jnp.where(sel, simtime.INVALID, q.time))
    from shadow_tpu.core.events import Popped

    return q, Popped(valid=valid, time=ptime, kind=q.kind[rows, idx],
                     src=q.src[rows, idx], seq=q.seq[rows, idx],
                     words=q.words[rows, idx])


def _push_local(q, mask, time, kind, words, lane, seq):
    """push_rows with an explicit seq (the serial path's apply_emissions
    assigns per-source seqs at emission; the scan carries the counter)."""
    from shadow_tpu.core.events import push_rows

    # push_rows assigns first-free slot — identical allocation rule
    return push_rows(q, mask, time,
                     jnp.broadcast_to(jnp.asarray(kind, I32), mask.shape),
                     lane, seq, words)


def make_tcp_bulk_fn(cfg: NetConfig, app_bulk: TcpAppBulk,
                     debug: bool = False,
                     lossless: bool = False,
                     caps=None) -> Callable | None:
    """Build the TCP bulk window pass, or None when the config cannot
    support it (static preconditions — mirrors bulk.make_bulk_fn).
    debug=True makes bulk_fn return a third value: a dict with the
    per-host eligibility/commit masks and the why bitmask (engine
    callers must use debug=False).

    lossless=True compiles the r4-style narrow pass: every loss
    artifact (SACK arrival, out-of-order seq, dup-ACK, recovery
    state, due RTO) STOPS the lane instead of being modeled, and the
    loss machinery's per-iteration cost (scoreboard replacement, OO
    merge scans, retransmit regeneration, SACK stamping) is not even
    traced. Bit-identity holds for ANY workload — prefix-commit hands
    stopped lanes to the serial fixpoint — so this is purely a perf
    knob for workloads that are genuinely artifact-free (fast
    loss-free links); workloads with retransmissions run SLOWER under
    it (their windows go serial). The NIC ring (token-limited) path
    is kept either way: slow links are orthogonal to loss."""
    if not cfg.tcp:
        return None
    if cfg.qdisc != QDisc.FIFO or cfg.router_qdisc != RouterQ.CODEL:
        return None
    if cfg.pcap or cfg.track_paths:
        return None
    if cfg.cpu_threshold_ns >= 0:
        return None
    if cfg.nic_drain != FLUSH_SEGMENTS:
        # the FAST (fused) wire path models one flush burst + one full
        # drain as a single step; its lane-mode decision (`overbound`)
        # and the static wire unroll are sized on this equality. Other
        # drain bounds would need the unrolls re-derived — the ring
        # path could handle them, but the fast path is the common case
        return None
    if cfg.out_ring <= FLUSH_SEGMENTS:
        # one burst must fit the ring with room to spare or even the
        # ring path stops on every flush (ek & ~okp); serial instead
        # STALLS the remainder inside tcp_flush — a regime this pass
        # does not model
        return None

    R = cfg.router_ring
    BO = cfg.out_ring
    alg = cfg.tcp_cong
    # Capability trim (compile/specialize.py): a dropped loss
    # capability removes the per-wire reliability Bernoulli draws from
    # the trace. Distinct from `lossless` above: that knob narrows the
    # TCP *artifact* model (SACK/recovery/RTO stop lanes); this one
    # elides the wire drop draw itself. uniform_at is a pure counter
    # query — the draw bookkeeping (`drawn`, j_ctr) is kept, so every
    # surviving draw site sees identical counters.
    rel_dead = caps is not None and not caps.loss

    def _sack_stamps(tcp, at_slot):
        """The SACK advertisement for a departing packet — identically
        zero in the lossless model (no reassembly parking exists, and
        lanes with carried-in parked state stop before wiring)."""
        if lossless:
            z = jnp.zeros(at_slot.shape, I32)
            return ((z, z), (z, z), (z, z))
        return sack_advert(tcp, at_slot)

    def bulk_fn(sim, wend):
        net0 = sim.net
        q0 = sim.events
        H, K = q0.time.shape
        S = net0.sk_type.shape[1]
        GH = net0.host_ip.shape[0]
        lane = net0.lane_id
        rows = jnp.arange(H)
        wend64 = jnp.asarray(wend, simtime.DTYPE)

        # ---- host-level static eligibility ---------------------------
        inwin0 = q0.time < wend64
        nonboot = jnp.all(~inwin0 | (q0.time >= cfg.bootstrap_end), axis=1)
        # send-side NIC backlog (queued output ring + a pending
        # NIC_SEND covering event) is IN model since r5 — the steady
        # state of token-limited (slow-link) senders. The receive side
        # (router queue, rx drain retries) is not (yet): those hosts
        # stay serial.
        out_backlog = jnp.sum(net0.out_count, axis=1) > 0
        send_consistent = ~out_backlog | net0.nic_send_pending
        quiesced = (
            (net0.rq_count == 0)
            & ~net0.nic_recv_pending
            & ~net0.nic_send_now
            & send_consistent
            & (jnp.sum(net0.in_count, axis=1) == 0)
            & ~net0.proc_stopped)
        codel_ok = ~net0.codel_dropping & (net0.codel_interval_expire == 0)
        app_ok = app_bulk.precheck(cfg, sim)
        has_work = jnp.any(inwin0, axis=1)
        if lossless:
            # the narrow pass neither models nor STAMPS parked
            # reassembly/scoreboard state (its SACK advertisement is
            # identically zero), so a host carrying any such state in
            # from a serial window is ineligible OUTRIGHT — otherwise
            # a wire on an unrelated slot of the same host (delayed
            # ACK, app flush, dual close) would silently advertise an
            # empty list where the serial engine stamps the parked
            # ranges
            no_parked = ~(jnp.any(sim.tcp.oo_r > sim.tcp.oo_l,
                                  axis=(1, 2))
                          | jnp.any(sim.tcp.sack_r > sim.tcp.sack_l,
                                    axis=(1, 2)))
            app_ok = app_ok & no_parked
        # kind_ok is NOT part of eligibility (r5 prefix-commit): a
        # non-TCP kind mid-window just STOPS that host's scan there —
        # the processed prefix commits and the serial fixpoint takes
        # the tail. Window-level invariants (quiesced NIC/router, app
        # steady state, bootstrap, codel idle) must still hold at
        # window start for the per-iteration model to be sound at all.
        elig = nonboot & quiesced & codel_ok & app_ok & has_work
        # precheck failures land in the top why bits for the debug view
        why0 = (jnp.where(~nonboot, jnp.int64(1) << 57, 0)
                | jnp.where(~quiesced, jnp.int64(1) << 58, 0)
                | jnp.where(~codel_ok, jnp.int64(1) << 59, 0)
                | jnp.where(~app_ok, jnp.int64(1) << 60, 0)
                | jnp.where(~has_work, jnp.int64(1) << 61, 0))

        def _whole_pass(sim):
            # ---- per-socket per-window constants -------------------------
            # peer host / latency / reliability (ip->host once per window)
            peer_h = host_of_ip(net0, net0.sk_peer_ip)          # [H,S]
            peer_hc = jnp.clip(peer_h, 0, GH - 1)
            vsrc = net0.vertex_of_host[lane][:, None]            # [H,1]
            vdst = net0.vertex_of_host[peer_hc]                  # [H,S]
            lat_s = net0.latency_ns[vsrc, vdst]                  # [H,S]
            lat_rev_s = net0.latency_ns[vdst, vsrc]              # [H,S]
            rel_s = net0.reliability[vsrc, vdst]                 # [H,S]
            peer_up_s = net0.bw_up_kibps[peer_hc]                # [H,S]
            peer_down_s = net0.bw_down_kibps[peer_hc]            # [H,S]

            # ---- the reduced per-event scan ------------------------------
            def cond(c):
                live = ~c.bad & jnp.any(c.sim.events.time < wend64, axis=1)
                return jnp.any(live) & (c.it < 4 * K + 8)

            def body(c):
                sim, bad, why, seq_ctr, it = c
                # prefix-commit snapshot: a lane whose event turns out
                # to be out of model REVERTS to this iteration-start
                # state (its event stays queued), so every lane always
                # carries a clean serial-reachable prefix
                sim_prev, seq_prev, bad_prev = sim, seq_ctr, bad
                net, tcp, app = sim.net, sim.tcp, sim.app
                q, p = _pop_masked(sim.events, wend64, ~bad & elig)
                W = q.words.shape[-1]
                v = p.valid
                t = p.time
                words = p.words
                is_pkt = v & (p.kind == EventKind.PACKET)
                is_dk = v & (p.kind == EventKind.TCP_DACK_TIMER)
                is_fl = v & (p.kind == EventKind.TCP_FLUSH)
                is_rtx = v & (p.kind == EventKind.TCP_RTX_TIMER)
                is_ns = v & (p.kind == EventKind.NIC_SEND)
                bad, why = _flag(bad, why,
                                 (v & ~(is_pkt | is_dk | is_fl | is_rtx
                                        | is_ns)), 1)

                # ===== packet classification =============================
                proto = pf.proto_of(words)
                flags = pf.tcp_flags_of(words)
                bad, why = _flag(bad, why, (is_pkt & (proto != pf.PROTO_TCP)), 2)
                finp = is_pkt & (flags == (pf.TCPF_FIN | pf.TCPF_ACK))
                bad, why = _flag(bad, why, (is_pkt & (flags != pf.TCPF_ACK)
                                            & ~finp), 4)
                # a FIN carrying data is out of model (this stack emits
                # dataless FINs, including retransmitted ones —
                # _retransmit_one regenerates the FIN at length 0)
                bad, why = _flag(bad, why,
                                 (finp & (words[:, pf.W_LEN] != 0)), 1 << 44)

                src_port, dst_port = pf.ports_of(words)
                dst_ip = words[:, pf.W_DSTIP].astype(jnp.uint32).astype(I64)
                src_ip = ip_of_hosts(cfg, net, p.src)
                slot = lookup_socket(net, is_pkt, jnp.full((H,), pf.PROTO_TCP,
                                                           I32),
                                     dst_ip, dst_port, src_ip, src_port)
                bad, why = _flag(bad, why, (is_pkt & (slot < 0)), 16)
                slot = jnp.where(slot >= 0, slot, 0)
                st = gather_hs(tcp.st, slot)
                # teardown states are in model; handshake (LISTEN/SYN_*),
                # TIME_WAIT stragglers, and recycled slots are not
                bad, why = _flag(bad, why, (is_pkt & ~(
                    (st == TcpSt.ESTABLISHED) | (st == TcpSt.FIN_WAIT_1)
                    | (st == TcpSt.FIN_WAIT_2) | (st == TcpSt.CLOSING)
                    | (st == TcpSt.CLOSE_WAIT) | (st == TcpSt.LAST_ACK))), 32)
                pkt = is_pkt & ~bad
                finp = finp & ~bad

                seqno = words[:, pf.W_SEQ]
                ackno = words[:, pf.W_ACK]
                length = words[:, pf.W_LEN]
                peer_win = words[:, pf.W_WIN]
                tsval = words[:, pf.W_TSVAL]
                tsecho = words[:, pf.W_TSECHO]
                is_data = pkt & (length > 0) & ~finp
                is_ack = pkt & (length == 0) & ~finp
                # data only reaches sockets in the serial has_data states
                bad, why = _flag(bad, why, (is_data & ~(
                    (st == TcpSt.ESTABLISHED) | (st == TcpSt.FIN_WAIT_1)
                    | (st == TcpSt.FIN_WAIT_2))), 1 << 45)
                is_data = is_data & ~bad

                # loss artifacts are IN model (old data, out-of-order
                # parking + SACK, dup-ACKs, fast retransmit, recovery,
                # RTO) — the reference's steady state on lossy paths
                # (ref: tcp.c:854-1027 retransmit machinery,
                # tcp.c:84-89 recovery states). Out of model: a FIN at
                # the wrong seq (teardown-under-loss stays serial).
                rcv_nxt = gather_hs(tcp.rcv_nxt, slot)
                bad, why = _flag(bad, why, (finp & (seqno != rcv_nxt)),
                                 1 << 46)
                sc = jnp.clip(slot, 0, S - 1)
                # pure ACKs to a socket whose peer already FINed are fine
                # (the final ACK of our FIN in LAST_ACK/CLOSING); data or a
                # re-FIN after the peer's FIN are not (deferred FIN
                # consumption on later arrivals stays serial)
                bad, why = _flag(bad, why, ((is_data | finp)
                                            & gather_hs(tcp.fin_rcvd, slot)),
                                 256)
                pkt = pkt & ~bad
                is_data = is_data & ~bad
                is_ack = is_ack & ~bad

                # ===== router ring cycle + rx token charge ================
                # (ref: router.c:104-125 + network_interface.c:421-455; the
                # ring is empty between events in the eligible regime, so
                # enqueue position == head and the packet dequeues in the
                # same micro-step, leaving head advanced and the written
                # planes behind)
                wl_in = pf.wire_length(proto, length).astype(I64)
                # ring-plane contents below head are dead storage (the
                # bit-identity convention of tests/test_bulk.py excludes
                # them); only the head advance is live state
                net = net.replace(
                    rq_head=jnp.where(pkt, (net.rq_head + 1) % R, net.rq_head),
                )
                # analytic refill at the arrival instant, then the charge
                dq = jnp.maximum(t // simtime.ONE_MILLISECOND - net.tb_quantum,
                                 0)
                # a popped NIC_SEND refills at entry exactly like the
                # serial handler (refill_tokens, nic.py:64-77)
                refresh = (pkt | is_ns) & (dq > 0)
                recv_tok = jnp.minimum(net.tb_recv_refill + pf.MTU,
                                       net.tb_recv_tokens
                                       + dq * net.tb_recv_refill)
                send_tok0 = jnp.minimum(net.tb_send_refill + pf.MTU,
                                        net.tb_send_tokens
                                        + dq * net.tb_send_refill)
                net = net.replace(
                    tb_recv_tokens=jnp.where(refresh, recv_tok,
                                             net.tb_recv_tokens),
                    tb_send_tokens=jnp.where(refresh, send_tok0,
                                             net.tb_send_tokens),
                    tb_quantum=jnp.where(refresh, t // simtime.ONE_MILLISECOND,
                                         net.tb_quantum),
                )
                bad, why = _flag(bad, why, (pkt & (net.tb_recv_tokens < pf.MTU)), 2048)
                net = net.replace(
                    tb_recv_tokens=jnp.maximum(
                        net.tb_recv_tokens - jnp.where(pkt, wl_in, 0), 0))

                net = net.replace(
                    ctr_rx_packets=net.ctr_rx_packets + pkt.astype(I64),
                    ctr_rx_bytes=net.ctr_rx_bytes + jnp.where(pkt, wl_in, 0),
                    ctr_rx_data_bytes=net.ctr_rx_data_bytes
                    + jnp.where(pkt, length, 0).astype(I64),
                )

                # ===== reduced tcp_packet_in ==============================
                # ts_recent (in-window: seq <= rcv_nxt holds for both kinds)
                tsr = gather_hs(tcp.ts_recent, slot)
                tcp = tcp.replace(ts_recent=set_hs(
                    tcp.ts_recent, pkt & (seqno <= rcv_nxt) & (tsval >= tsr),
                    slot, tsval))

                # snd_wnd + SACK scoreboard replacement (ref: tcp.c ACK
                # path; scoreboard = the advertised list, an empty list
                # clears it — tcp.py:962-975). Under lossless, an
                # arriving SACK block is an upstream loss artifact:
                # stop instead of modeling.
                wnd_prev = gather_hs(tcp.snd_wnd, slot)
                tcp = tcp.replace(snd_wnd=set_hs(tcp.snd_wnd, pkt, slot,
                                                 peer_win))
                if lossless:
                    sack_any = (
                        (words[:, pf.W_SACKL] != 0)
                        | (words[:, pf.W_SACKR] != 0)
                        | (words[:, pf.W_SACKL2] != 0)
                        | (words[:, pf.W_SACKR2] != 0)
                        | (words[:, pf.W_SACKL3] != 0)
                        | (words[:, pf.W_SACKR3] != 0))
                    bad, why = _flag(bad, why, (is_pkt & sack_any),
                                     1 << 32)
                    pkt = pkt & ~bad
                    is_data = is_data & ~bad
                    is_ack = is_ack & ~bad
                else:
                    sack_l3 = jnp.stack(
                        [words[:, pf.W_SACKL], words[:, pf.W_SACKL2],
                         words[:, pf.W_SACKL3]], axis=1)
                    sack_r3 = jnp.stack(
                        [words[:, pf.W_SACKR], words[:, pf.W_SACKR2],
                         words[:, pf.W_SACKR3]], axis=1)
                    sel_sk = pkt[:, None] & (
                        jnp.arange(S)[None, :] == slot[:, None])
                    tcp = tcp.replace(
                        sack_l=jnp.where(sel_sk[..., None],
                                         sack_l3[:, None, :], tcp.sack_l),
                        sack_r=jnp.where(sel_sk[..., None],
                                         sack_r3[:, None, :], tcp.sack_r),
                    )

                una = gather_hs(tcp.snd_una, slot)
                nxt = gather_hs(tcp.snd_nxt, slot)
                smax = gather_hs(tcp.snd_max, slot)
                new_ack = pkt & (ackno > una) & (ackno <= smax)
                bad, why = _flag(bad, why, (pkt & (ackno > smax)), 4096)
                # healing ACK past a rewound snd_nxt: those bytes arrived
                # from the pre-rewind transmission — jump forward
                # (ref: tcp.py:979-983); rewinds only exist with RTOs
                if lossless:
                    bad, why = _flag(bad, why, (new_ack & (ackno > nxt)),
                                     8192)
                    new_ack = new_ack & ~bad
                else:
                    heal = new_ack & (ackno > nxt)
                    tcp = tcp.replace(snd_nxt=set_hs(tcp.snd_nxt, heal,
                                                     slot, ackno))
                    nxt = jnp.where(heal, ackno, nxt)
                dup_ack = pkt & (ackno == una) & (una < nxt) & (length == 0) \
                    & (peer_win == wnd_prev) & ~finp   # ~f_fin per RFC 5681
                # a DATA segment whose embedded ack also advances our send
                # side (bidirectional stream on one socket) would need two
                # flush targets in one iteration — out of model
                bad, why = _flag(bad, why, (pkt & (length > 0)
                                            & (ackno > una)), 1 << 43)
                new_ack = new_ack & ~bad

                # RTT / RTO (ref: tcp.c:991-1026)
                rtt = jnp.maximum(_ms(t) - tsecho, 1)
                srtt = gather_hs(tcp.srtt_ms, slot)
                sample = new_ack & (tsecho > 0)
                first = sample & (srtt < 0)
                rttvar = gather_hs(tcp.rttvar_ms, slot)
                srtt_n = jnp.where(first, rtt, srtt + (rtt - srtt) // 8)
                rttvar_n = jnp.where(first, rtt // 2,
                                     (3 * rttvar + jnp.abs(srtt - rtt)) // 4)
                rto_n = jnp.clip(srtt_n + jnp.maximum(4 * rttvar_n, 1),
                                 RTO_MIN_MS, RTO_MAX_MS)
                tcp = tcp.replace(
                    srtt_ms=set_hs(tcp.srtt_ms, sample, slot, srtt_n),
                    rttvar_ms=set_hs(tcp.rttvar_ms, sample, slot, rttvar_n),
                    rto_ms=set_hs(tcp.rto_ms, sample, slot, rto_n),
                    backoff=set_hs(tcp.backoff, new_ack, slot,
                                   jnp.zeros((H,), I32)),
                )

                # congestion hooks — same code path as the serial engine
                # incl. fast-recovery transitions (ref: tcp.py:1011-1047).
                # Under lossless, recovery state (carried in from a
                # serial window) and dup-ACKs stop the lane instead.
                in_rec = gather_hs(tcp.in_recovery, slot)
                if lossless:
                    bad, why = _flag(bad, why, (pkt & in_rec), 1024)
                    bad, why = _flag(
                        bad, why,
                        (pkt & (gather_hs(tcp.dup_acks, slot) > 0)),
                        1 << 33)
                    bad, why = _flag(bad, why, dup_ack, 16384)
                    pkt = pkt & ~bad
                    is_data = is_data & ~bad
                    is_ack = is_ack & ~bad
                    new_ack = new_ack & ~bad
                recover = gather_hs(tcp.recover, slot)
                cwnd = gather_hs(tcp.cwnd, slot)
                ssth = gather_hs(tcp.ssthresh, slot)
                ca = gather_hs(tcp.ca_acc, slot)
                n_acked = jnp.where(new_ack, (ackno - una + MSS - 1) // MSS, 0)
                if lossless:
                    full_rec = jnp.zeros((H,), bool)
                    partial = jnp.zeros((H,), bool)
                    normal = new_ack
                else:
                    full_rec = new_ack & in_rec & (ackno >= recover)
                    partial = new_ack & in_rec & (ackno < recover)
                    normal = new_ack & ~in_rec
                ss = normal & (cwnd < ssth)
                grown = cwnd + n_acked
                spill = ss & (grown >= ssth)
                cwnd1 = jnp.where(ss, jnp.minimum(grown, ssth), cwnd)
                # leaving fast recovery deflates to ssthresh
                cwnd1 = jnp.where(full_rec, ssth, cwnd1)
                ca_in = jnp.where(spill, grown - ssth,
                                  jnp.where(full_rec | (normal & ~ss),
                                            n_acked, 0))
                in_ca = (normal & ~ss) | spill | full_rec
                ca_base = jnp.where(spill | full_rec, 0, ca)
                cwnd1, ca1, epoch1 = cong.ca_update(
                    alg, in_ca, cwnd1, jnp.where(in_ca, ca_base, ca), ca_in,
                    gather_hs(tcp.cub_wmax, slot),
                    gather_hs(tcp.cub_epoch_ms, slot), _ms(t))
                tcp = tcp.replace(
                    cwnd=set_hs(tcp.cwnd, new_ack, slot, cwnd1),
                    ca_acc=set_hs(tcp.ca_acc, new_ack, slot, ca1),
                    cub_epoch_ms=set_hs(tcp.cub_epoch_ms, in_ca, slot, epoch1),
                    in_recovery=set_hs(tcp.in_recovery, full_rec, slot,
                                       False),
                    dup_acks=set_hs(tcp.dup_acks, new_ack, slot,
                                    jnp.zeros((H,), I32)),
                    snd_una=set_hs(tcp.snd_una, new_ack, slot, ackno),
                )
                una2 = jnp.where(new_ack, ackno, una)

                # initial buffer sizing on the FIRST RTT sample (ref:
                # tcp.c:1007-1009 + _tcp_tuneInitialBufferSizes): BDP from
                # the topology's true two-way latency and the bottleneck of
                # local/peer interface bandwidth, x1.25
                from shadow_tpu.net.tcp import (
                    RECV_BUFFER_MIN, SEND_BUFFER_MIN)

                at_init = first & ~gather_hs(tcp.at_init_done, slot)

                def _at_init_sec(ops):
                    net, tcp = ops
                    peer_ip_sl = gather_hs(net.sk_peer_ip, slot)
                    self_ip = net.host_ip[lane]
                    is_loop = (peer_ip_sl == self_ip) | ((peer_ip_sl >> 24) == 127)
                    rtt_topo_ms = jnp.maximum(
                        (gather_hs(lat_s, slot) + gather_hs(lat_rev_s, slot))
                        // simtime.ONE_MILLISECOND, 1)
                    my_up = net.bw_up_kibps[lane]
                    my_down = net.bw_down_kibps[lane]
                    bdp_snd = rtt_topo_ms * jnp.minimum(
                        my_up, gather_hs(peer_down_s, slot)) * 1280 // 1000
                    bdp_rcv = rtt_topo_ms * jnp.minimum(
                        my_down, gather_hs(peer_up_s, slot)) * 1280 // 1000
                    init_snd = jnp.where(
                        is_loop, TCP_WMEM_MAX,
                        jnp.clip(bdp_snd, SEND_BUFFER_MIN, TCP_WMEM_MAX)
                    ).astype(I32)
                    init_rcv = jnp.where(
                        is_loop, TCP_RMEM_MAX,
                        jnp.clip(bdp_rcv, RECV_BUFFER_MIN, TCP_RMEM_MAX)
                    ).astype(I32)
                    net = net.replace(
                        sk_sndbuf=set_hs(net.sk_sndbuf,
                                         at_init & net.autotune_snd, slot,
                                         init_snd),
                        sk_rcvbuf=set_hs(net.sk_rcvbuf,
                                         at_init & net.autotune_rcv, slot,
                                         init_rcv))
                    tcp = tcp.replace(at_init_done=set_hs(
                        tcp.at_init_done, at_init, slot, True))
                    return net, tcp

                net, tcp = _gate(jnp.any(at_init), _at_init_sec,
                                 (net, tcp))

                my_up = net.bw_up_kibps[lane]
                # send-buffer autotune growth (ref: tcp.c:566-592)
                srtt_now = jnp.maximum(jnp.where(sample, srtt_n, srtt),
                                       0).astype(I64)
                max_wmem = jnp.clip(my_up * 1024 * srtt_now // 1000,
                                    TCP_WMEM_MAX, 10 * TCP_WMEM_MAX)
                want_snd = jnp.minimum(I64(SNDMEM_SKB) * 2 * cwnd1.astype(I64),
                                       max_wmem).astype(I32)
                cur_snd = gather_hs(net.sk_sndbuf, slot)
                net = net.replace(sk_sndbuf=set_hs(
                    net.sk_sndbuf,
                    new_ack & net.autotune_snd & (want_snd > cur_snd),
                    slot, want_snd))
                # ACK progress reopened stream room -> WRITABLE (edge helper)
                wroom = new_ack & (
                    gather_hs(net.sk_sndbuf, slot)
                    - (gather_hs(tcp.snd_end, slot) - ackno) > 0)
                from shadow_tpu.net.sockets import set_writable

                net = set_writable(net, wroom, slot, True)

                # dup-ack counting / fast retransmit entry (ref:
                # tcp.py:1110-1129 — ssthresh/entry cwnd from the
                # configured algorithm). The retransmission itself is
                # wired FIRST in the wire stage below (serial emission
                # order: _retransmit_one precedes the flush).
                def _dupack_sec(ops):
                    tcp, _ = ops
                    da = gather_hs(tcp.dup_acks, slot) + 1
                    tcp = tcp.replace(dup_acks=set_hs(
                        tcp.dup_acks, dup_ack, slot, da))
                    enter_fr = dup_ack & (da == 3) & ~in_rec
                    ssth_fr = cong.ssthresh_on_loss(alg, cwnd)
                    tcp = tcp.replace(
                        ssthresh=set_hs(tcp.ssthresh, enter_fr, slot,
                                        ssth_fr),
                        cwnd=set_hs(tcp.cwnd, enter_fr, slot,
                                    cong.cwnd_on_recovery_entry(alg,
                                                                ssth_fr)))
                    wmax1, ep1 = cong.on_loss_event(
                        alg, enter_fr, cwnd, gather_hs(tcp.cub_wmax, slot),
                        gather_hs(tcp.cub_epoch_ms, slot))
                    tcp = tcp.replace(
                        cub_wmax=set_hs(tcp.cub_wmax, enter_fr, slot, wmax1),
                        cub_epoch_ms=set_hs(tcp.cub_epoch_ms, enter_fr, slot,
                                            ep1),
                        in_recovery=set_hs(tcp.in_recovery, enter_fr, slot,
                                           True),
                        recover=set_hs(tcp.recover, enter_fr, slot, nxt),
                        fr_entries=tcp.fr_entries + enter_fr.astype(I64))
                    if alg != cong.AIMD:
                        # window inflation while in recovery (entry
                        # iteration excluded — in_rec is the pre-entry
                        # value, matching serial)
                        inflate = dup_ack & in_rec
                        tcp = tcp.replace(cwnd=set_hs(
                            tcp.cwnd, inflate, slot,
                            gather_hs(tcp.cwnd, slot) + 1))
                    return tcp, enter_fr

                if lossless:
                    enter_fr = jnp.zeros((H,), bool)
                else:
                    tcp, enter_fr = _gate(jnp.any(dup_ack), _dupack_sec,
                                          (tcp, jnp.zeros((H,), bool)))
                # the segment at snd_una re-sends on recovery entry and
                # on every partial ACK (ref: tcp.py:1132)
                retx_ack = (enter_fr | partial) & ~bad

                # RTO deadline after progress (ref: tcp.c ACK path)
                still_out = new_ack & (ackno < smax)
                done_ack = new_ack & (ackno >= smax)
                rto_ns = gather_hs(tcp.rto_ms, slot).astype(I64) \
                    * simtime.ONE_MILLISECOND
                tcp = tcp.replace(
                    rtx_expire=set_hs(tcp.rtx_expire, still_out, slot,
                                      t + rto_ns),
                    )
                tcp = tcp.replace(rtx_expire=set_hs(
                    tcp.rtx_expire, done_ack, slot,
                    jnp.full((H,), simtime.INVALID, I64)))

                # ===== ACK of our FIN: teardown transitions ===============
                # (ref: tcp.c teardown + tcp_bulk ordering note: serial
                # runs this after its ACK-path flush; with the flush moved
                # later the values are unchanged because a fin_acked lane
                # never has data left to flush — all bytes incl. the FIN
                # are acked.) LAST_ACK frees the socket via the REAL
                # _free_socket so the recycled-slot reset is by definition
                # identical.
                from shadow_tpu.net.tcp import (
                    TIMEWAIT_NS, _free_socket as _tcp_free)

                fin_ever_any = pkt & gather_hs(tcp.fin_pending, slot)

                def _fin_acked_sec(ops):
                    net, tcp, q, seq_ctr, bad, why = ops
                    smax_fa = gather_hs(tcp.snd_max, slot)
                    fin_ever_fa = gather_hs(tcp.fin_pending, slot) & (
                        smax_fa == gather_hs(tcp.snd_end, slot) + 1)
                    fin_acked = pkt & fin_ever_fa & (ackno == smax_fa)
                    st_fa = gather_hs(tcp.st, slot)
                    tcp = tcp.replace(st=set_hs(
                        tcp.st, fin_acked & (st_fa == TcpSt.FIN_WAIT_1), slot,
                        jnp.full((H,), TcpSt.FIN_WAIT_2, I32)))
                    tw1 = fin_acked & (st_fa == TcpSt.CLOSING)
                    tcp = tcp.replace(st=set_hs(
                        tcp.st, tw1, slot,
                        jnp.full((H,), TcpSt.TIME_WAIT, I32)))
                    closed_now = fin_acked & (st_fa == TcpSt.LAST_ACK)
                    sim_fs = sim.replace(net=net, tcp=tcp)
                    sim_fs = _tcp_free(cfg, sim_fs, closed_now, slot)
                    net, tcp = sim_fs.net, sim_fs.tcp
                    tww = jnp.zeros((H, W), I32).at[:, 0].set(
                        slot.astype(I32))
                    free_tw = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, tw1 & ~free_tw, 1 << 47)
                    tw1e = tw1 & ~bad
                    q = _push_local(q, tw1e, t + TIMEWAIT_NS,
                                    EventKind.TCP_CLOSE_TIMER, tww, lane,
                                    seq_ctr)
                    seq_ctr = seq_ctr + tw1e.astype(I32)
                    return net, tcp, q, seq_ctr, bad, why

                net, tcp, q, seq_ctr, bad, why = _gate(
                    jnp.any(fin_ever_any), _fin_acked_sec,
                    (net, tcp, q, seq_ctr, bad, why))

                # ===== data receive (ref: tcp.py:1174-1247) ===============
                # old segments re-ACK; fresh segments that fit deliver
                # in order (merging parked reassembly ranges) or park
                # out of order; overfull segments drop + re-ACK — the
                # serial data path in full, minus TIME_WAIT stragglers.
                # Under lossless, any non-exact seq / parked state /
                # overfull buffer stops the lane instead.
                seg_end = seqno + length
                if lossless:
                    bad, why = _flag(bad, why,
                                     (is_data & (seqno != rcv_nxt)), 64)
                    # no parked reassembly/scoreboard state can exist
                    # here: hosts carrying any were ineligible at the
                    # window gate, and this mode never parks
                    is_data = is_data & ~bad
                    pkt = pkt & ~bad
                    freeb = gather_hs(net.sk_rcvbuf, slot) \
                        - gather_hs(tcp.app_rbytes, slot)
                    bad, why = _flag(bad, why,
                                     (is_data & (length > freeb)), 65536)
                    is_data = is_data & ~bad
                    old_d = jnp.zeros((H,), bool)
                    fresh = is_data
                    fits = is_data
                    inorder = is_data
                    adv = jnp.where(inorder, length, 0)
                    rcv1 = rcv_nxt + adv
                    rb0 = gather_hs(tcp.app_rbytes, slot)
                    rbytes = rb0 + adv
                else:
                    old_d = is_data & (seg_end <= rcv_nxt)
                    fresh = is_data & ~old_d
                    oo_bytes = jnp.sum(
                        tcp.oo_r[rows, sc] - tcp.oo_l[rows, sc],
                        axis=1, dtype=I32)
                    freeb = gather_hs(net.sk_rcvbuf, slot) \
                        - gather_hs(tcp.app_rbytes, slot) - oo_bytes
                    fits = fresh & (length <= freeb)
                    tcp = tcp.replace(drop_rwin=tcp.drop_rwin
                                      + (fresh & ~fits).astype(I64))
                    inorder = fits & (seqno <= rcv_nxt)
                    adv = jnp.where(inorder, seg_end - rcv_nxt, 0)
                    rcv1 = rcv_nxt + adv
                    rb0 = gather_hs(tcp.app_rbytes, slot)
                    rbytes = rb0 + adv

                def _oo_sec(ops):
                    tcp, rcv1, rbytes, _ = ops
                    # merge any reassembly range now contiguous
                    # (unrolled bounded scan, ref: tcp.py:1198-1212)
                    NR = tcp.oo_l.shape[2]
                    for _i in range(NR):
                        ool = tcp.oo_l[rows, sc]          # [H,NR]
                        oor = tcp.oo_r[rows, sc]
                        hit = (ool <= rcv1[:, None]) & (oor > ool)
                        take = jnp.any(hit & inorder[:, None], axis=1)
                        pick = jnp.argmax(hit, axis=1)
                        new_r = oor[rows, pick]
                        gain = jnp.where(take & (new_r > rcv1),
                                         new_r - rcv1, 0)
                        rcv1 = rcv1 + gain
                        rbytes = rbytes + gain
                        tcp = tcp.replace(
                            oo_l=set_ring(tcp.oo_l, take & inorder, slot,
                                          pick, 0),
                            oo_r=set_ring(tcp.oo_r, take & inorder, slot,
                                          pick, 0),
                        )
                    # out-of-order: park [seq, seg_end) in a range
                    # (ref: tcp.py:1217-1236)
                    ooseg = fits & (seqno > rcv_nxt)
                    ool = tcp.oo_l[rows, sc]
                    oor = tcp.oo_r[rows, sc]
                    overlap = (seqno[:, None] <= oor) \
                        & (seg_end[:, None] >= ool) & (oor > ool)
                    mergeable = jnp.any(overlap, axis=1)
                    mpick = jnp.argmax(overlap, axis=1)
                    empty_rng = oor <= ool
                    has_empty = jnp.any(empty_rng, axis=1)
                    epick = jnp.argmax(empty_rng, axis=1)
                    do_merge = ooseg & mergeable
                    do_new = ooseg & ~mergeable & has_empty
                    dropped_oo = ooseg & ~mergeable & ~has_empty
                    tcp = tcp.replace(drop_oo_full=tcp.drop_oo_full
                                      + dropped_oo.astype(I64))
                    pick = jnp.where(do_merge, mpick, epick)
                    nl = jnp.where(do_merge,
                                   jnp.minimum(ool[rows, pick], seqno), seqno)
                    nr = jnp.where(do_merge,
                                   jnp.maximum(oor[rows, pick], seg_end),
                                   seg_end)
                    tcp = tcp.replace(
                        oo_l=set_ring(tcp.oo_l, do_merge | do_new, slot,
                                      pick, nl),
                        oo_r=set_ring(tcp.oo_r, do_merge | do_new, slot,
                                      pick, nr),
                    )
                    return tcp, rcv1, rbytes, ooseg

                if lossless:
                    ooseg = jnp.zeros((H,), bool)
                else:
                    tcp, rcv1, rbytes, ooseg = _gate(
                        jnp.any(fits & (seqno > rcv_nxt))
                        | jnp.any((oo_bytes > 0) & inorder),
                        _oo_sec, (tcp, rcv1, rbytes,
                                  jnp.zeros((H,), bool)))
                tcp = tcp.replace(
                    rcv_nxt=set_hs(tcp.rcv_nxt, inorder, slot, rcv1),
                    app_rbytes=set_hs(tcp.app_rbytes, inorder, slot,
                                      rbytes),
                )
                readable = inorder & (gather_hs(tcp.app_rbytes, slot) > 0)
                fl_r = gather_hs(net.sk_flags, slot)
                net = net.replace(
                    sk_flags=set_hs(net.sk_flags, readable, slot,
                                    fl_r | SocketFlags.READABLE),
                    sk_in_gen=set_hs(net.sk_in_gen, readable, slot,
                                     gather_hs(net.sk_in_gen, slot) + 1),
                )
                # loss-signalling ACKs go out immediately with the SACK
                # advertisement (ref: tcp.py:1289-1297 `immediate`)
                imm_ack = (old_d | ooseg | (fresh & ~fits)) & ~bad

                # ===== peer FIN (ref: tcp.c FIN processing) ===============
                # in-order only (seq == rcv_nxt checked above), so the FIN
                # consumes immediately: rcv_nxt+1, state transition, EOF
                # readability edge; FIN_WAIT_2 arms the TIME_WAIT reaper
                fin_now = finp & ~bad

                def _peer_fin_sec(ops):
                    net, tcp, q, seq_ctr, bad, why = ops
                    st_fp = gather_hs(tcp.st, slot)
                    tcp = tcp.replace(
                        fin_rcvd=set_hs(tcp.fin_rcvd, fin_now, slot, True),
                        fin_rseq=set_hs(tcp.fin_rseq, fin_now, slot, seqno),
                    )
                    tcp = tcp.replace(rcv_nxt=set_hs(
                        tcp.rcv_nxt, fin_now, slot,
                        gather_hs(tcp.rcv_nxt, slot) + 1))
                    to_cw = fin_now & (st_fp == TcpSt.ESTABLISHED)
                    to_closing = fin_now & (st_fp == TcpSt.FIN_WAIT_1)
                    to_tw = fin_now & (st_fp == TcpSt.FIN_WAIT_2)
                    bad, why = _flag(bad, why,
                                     fin_now & ~(to_cw | to_closing | to_tw),
                                     1 << 48)
                    tcp = tcp.replace(st=set_hs(
                        tcp.st, to_cw, slot,
                        jnp.full((H,), TcpSt.CLOSE_WAIT, I32)))
                    tcp = tcp.replace(st=set_hs(
                        tcp.st, to_closing, slot,
                        jnp.full((H,), TcpSt.CLOSING, I32)))
                    tcp = tcp.replace(st=set_hs(
                        tcp.st, to_tw, slot,
                        jnp.full((H,), TcpSt.TIME_WAIT, I32)))
                    tw2 = to_tw & ~bad
                    free_tw2 = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, tw2 & ~free_tw2, 1 << 49)
                    tw2 = tw2 & ~bad
                    tww2 = jnp.zeros((H, W), I32).at[:, 0].set(
                        slot.astype(I32))
                    q = _push_local(q, tw2, t + TIMEWAIT_NS,
                                    EventKind.TCP_CLOSE_TIMER, tww2, lane,
                                    seq_ctr)
                    seq_ctr = seq_ctr + tw2.astype(I32)
                    fl_f = gather_hs(net.sk_flags, slot)
                    net = net.replace(
                        sk_flags=set_hs(net.sk_flags, fin_now, slot,
                                        fl_f | SocketFlags.READABLE),
                        sk_in_gen=set_hs(net.sk_in_gen, fin_now, slot,
                                         gather_hs(net.sk_in_gen, slot) + 1),
                    )
                    return net, tcp, q, seq_ctr, bad, why

                net, tcp, q, seq_ctr, bad, why = _gate(
                    jnp.any(fin_now), _peer_fin_sec,
                    (net, tcp, q, seq_ctr, bad, why))

                # delayed-ACK scheduling (ref: tcp.c:2066-2091) — the push
                # is the FIRST emission of this micro-step's ACK-generation
                # stage (seq order); a consumed FIN coalesces its ACK like
                # in-order data (tcp.c:2066-2091 `delayed = inorder|fin`)
                ackable = inorder | (fin_now & ~bad)
                cnt = gather_hs(tcp.dack_counter, slot) + 1
                tcp = tcp.replace(dack_counter=set_hs(
                    tcp.dack_counter, ackable, slot, cnt))
                sched = ackable & ~gather_hs(tcp.dack_scheduled, slot)
                nq = gather_hs(tcp.quick_acks, slot)
                quick = nq < DACK_QUICK_LIMIT
                ddelay = jnp.where(quick, DACK_QUICK_NS, DACK_SLOW_NS)
                tcp = tcp.replace(
                    quick_acks=set_hs(tcp.quick_acks, sched & quick, slot,
                                      nq + 1),
                    dack_scheduled=set_hs(tcp.dack_scheduled, sched, slot,
                                          True))
                def _dack_push(ops):
                    q, seq_ctr, bad, why = ops
                    dkw = jnp.zeros((H, W), I32)
                    dkw = dkw.at[:, 0].set(slot.astype(I32))
                    dkw = dkw.at[:, 1].set(gather_hs(tcp.dack_gen, slot))
                    free_before = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, (sched & ~free_before), 131072)
                    q = _push_local(q, sched & ~bad, t + ddelay,
                                    EventKind.TCP_DACK_TIMER, dkw, lane,
                                    seq_ctr)
                    seq_ctr = seq_ctr + (sched & ~bad).astype(I32)
                    return q, seq_ctr, bad, why

                q, seq_ctr, bad, why = _gate(jnp.any(sched), _dack_push,
                                             (q, seq_ctr, bad, why))

                # ===== app consume + forward ==============================
                # tcp_recv semantics: read EVERYTHING available — the
                # delivered amount includes any merged reassembly gain,
                # exactly the serial tcp_recv return
                avail = gather_hs(tcp.app_rbytes, slot)
                win_before = gather_hs(net.sk_rcvbuf, slot) - avail
                app, app_okm, fwd_mask, fwd_slot, fwd_bytes = app_bulk.on_data(
                    cfg, app, inorder, slot, avail, t)
                bad, why = _flag(bad, why, (inorder & ~app_okm), 262144)
                inorder = inorder & ~bad
                fwd_mask = fwd_mask & inorder
                tcp = tcp.replace(app_rbytes=set_hs(
                    tcp.app_rbytes, inorder, slot, jnp.zeros((H,), I32)))
                # Linux-DRS receive autotune (ref: tcp.c:535-564)
                at_on = inorder & net.autotune_rcv
                copied = gather_hs(tcp.at_copied, slot) + avail
                space = jnp.maximum(2 * copied, gather_hs(tcp.at_space, slot))
                cur_r = gather_hs(net.sk_rcvbuf, slot)
                srtt2 = gather_hs(tcp.srtt_ms, slot)
                my_down = net.bw_down_kibps[lane]
                max_rmem = jnp.clip(
                    my_down * 1024 * jnp.maximum(srtt2, 0).astype(I64) // 1000,
                    TCP_RMEM_MAX, 10 * TCP_RMEM_MAX)
                growing = at_on & (space > cur_r)
                tcp = tcp.replace(at_space=set_hs(tcp.at_space, growing, slot,
                                                  space))
                new_size = jnp.minimum(space.astype(I64), max_rmem).astype(I32)
                net = net.replace(sk_rcvbuf=set_hs(
                    net.sk_rcvbuf, growing & (new_size > cur_r), slot,
                    new_size))
                tcp = tcp.replace(at_copied=set_hs(tcp.at_copied, at_on, slot,
                                                   copied))
                last = gather_hs(tcp.at_last, slot)
                tcp = tcp.replace(at_last=set_hs(
                    tcp.at_last, at_on & (last == 0), slot, t))
                rtt_ns2 = jnp.maximum(srtt2, 0).astype(I64) \
                    * simtime.ONE_MILLISECOND
                reset = at_on & (last > 0) & (srtt2 > 0) & (t - last > rtt_ns2)
                tcp = tcp.replace(
                    at_last=set_hs(tcp.at_last, reset, slot, t),
                    at_copied=set_hs(tcp.at_copied, reset, slot,
                                     jnp.zeros((H,), I32)))
                # drained -> clear READABLE (no EOF in the eligible regime)
                fl_d = gather_hs(net.sk_flags, slot)
                net = net.replace(sk_flags=set_hs(
                    net.sk_flags, inorder, slot,
                    fl_d & ~SocketFlags.READABLE))
                # receiver silly-window update ACK => out of model
                win_after = gather_hs(net.sk_rcvbuf, slot)
                bad, why = _flag(bad, why, (inorder & (win_before < 2 * MSS) & (win_after - win_before >= MSS)), 524288)

                # ===== app EOF: the teardown cascade ======================
                # The serial app observes eof in its tcp_recv on the FIN's
                # own micro-step and issues its closes right there (relay
                # handler: server closes up_conn; a drained relay closes
                # down_sock then up_conn). The hook returns up to two close
                # targets in that order; tcp_close semantics
                # (ref: tcp.c:604-699) applied inline, FIN rides via the
                # flush below.
                zb = jnp.zeros((H,), bool)
                zi32 = jnp.zeros((H,), I32)

                def _eof_sec(ops):
                    app, tcp, bad, why, _, _, _, _ = ops
                    app, eof_ok, c1_mask, c1_slot, c2_mask, c2_slot = \
                        app_bulk.on_eof(cfg, app, fin_now & ~bad, slot, t)
                    bad, why = _flag(bad, why, (fin_now & ~eof_ok), 1 << 50)
                    c1_mask = c1_mask & fin_now & ~bad
                    c2_mask = c2_mask & fin_now & ~bad
                    c1_slot = jnp.asarray(c1_slot, I32)
                    c2_slot = jnp.asarray(c2_slot, I32)

                    def close_transitions(tcp, bad, why, cm, cs, bit):
                        cst = gather_hs(tcp.st, cs)
                        to_fw1 = cm & ((cst == TcpSt.ESTABLISHED)
                                       | (cst == TcpSt.SYN_RCVD))
                        to_la = cm & (cst == TcpSt.CLOSE_WAIT)
                        # other close paths (deferred SYN_SENT, direct
                        # frees, re-close) are out of model
                        bad, why = _flag(bad, why, cm & ~(to_fw1 | to_la),
                                         bit)
                        tcp = tcp.replace(st=set_hs(
                            tcp.st, to_fw1 & ~bad, cs,
                            jnp.full((H,), TcpSt.FIN_WAIT_1, I32)))
                        tcp = tcp.replace(st=set_hs(
                            tcp.st, to_la & ~bad, cs,
                            jnp.full((H,), TcpSt.LAST_ACK, I32)))
                        tcp = tcp.replace(fin_pending=set_hs(
                            tcp.fin_pending, cm & ~bad, cs, True))
                        return tcp, bad, why

                    tcp, bad, why = close_transitions(tcp, bad, why,
                                                      c1_mask, c1_slot,
                                                      1 << 51)
                    tcp, bad, why = close_transitions(tcp, bad, why,
                                                      c2_mask, c2_slot,
                                                      1 << 52)
                    return (app, tcp, bad, why, c1_mask & ~bad, c1_slot,
                            c2_mask & ~bad, c2_slot)

                (app, tcp, bad, why, c1_mask, c1_slot, c2_mask,
                 c2_slot) = _gate(
                    jnp.any(fin_now), _eof_sec,
                    (app, tcp, bad, why, zb, zi32, zb, zi32))

                # tcp_send semantics on the forward socket (full accept or
                # abort; ref: tcp_sendUserData, tcp.c:2126-2190)
                fsl = jnp.where(fwd_mask, fwd_slot, 0)
                fst = gather_hs(tcp.st, fsl)
                can_send = fwd_mask & (
                    (fst == TcpSt.ESTABLISHED) | (fst == TcpSt.CLOSE_WAIT)
                    | (fst == TcpSt.SYN_SENT) | (fst == TcpSt.SYN_RCVD))
                bad, why = _flag(bad, why, (fwd_mask & ~can_send), 1048576)
                f_una = gather_hs(tcp.snd_una, fsl)
                f_end = gather_hs(tcp.snd_end, fsl)
                f_sndbuf = gather_hs(net.sk_sndbuf, fsl)
                room = jnp.maximum(f_sndbuf - (f_end - f_una), 0)
                bad, why = _flag(bad, why, (can_send & (room < fwd_bytes)), 2097152)
                bad, why = _flag(bad, why, (can_send & (room - fwd_bytes <= 0)), 4194304)
                can_send = can_send & ~bad
                tcp = tcp.replace(snd_end=set_hs(tcp.snd_end, can_send, fsl,
                                                 f_end + fwd_bytes))

                # ===== flush of admissible segments =======================
                # data arrivals flush the forward socket; ACKs flush the
                # arrival socket; popped TCP_FLUSH continuations flush
                # their own slot (ref: _tcp_flush via tcp_send / the ACK
                # path / handle_tcp_flush)
                flslot = jnp.where(is_fl, p.word(0), 0)
                tcp = tcp.replace(flush_pending=set_hs(
                    tcp.flush_pending, is_fl, flslot, False))
                reopened = is_ack & (wnd_prev == 0) & (peer_win > 0)
                fl_mask = can_send | new_ack | reopened | is_fl | c1_mask
                fslot = jnp.where(can_send, fsl,
                                  jnp.where(is_fl, flslot,
                                            jnp.where(c1_mask, c1_slot,
                                                      slot)))
                g_una = gather_hs(tcp.snd_una, fslot)
                g_nxt = gather_hs(tcp.snd_nxt, fslot)
                g_end = gather_hs(tcp.snd_end, fslot)
                g_st = gather_hs(tcp.st, fslot)
                g_cwnd = gather_hs(tcp.cwnd, fslot)
                g_wnd = jnp.minimum(g_cwnd * MSS, gather_hs(tcp.snd_wnd, fslot))
                can_data = fl_mask & (
                    (g_st == TcpSt.ESTABLISHED) | (g_st == TcpSt.CLOSE_WAIT)
                    | (g_st == TcpSt.FIN_WAIT_1) | (g_st == TcpSt.LAST_ACK))
                A = jnp.clip(jnp.minimum(g_end - g_nxt, g_una + g_wnd - g_nxt),
                             0)
                A = jnp.where(can_data, A, 0)
                # one flush call packetizes at most FLUSH_SEGMENTS segments;
                # the remainder chains a same-time TCP_FLUSH continuation
                # exactly like the serial path (its pop order among other
                # same-instant events follows the same (time, src, seq)
                # comparator, so the scan replays the interleaving)
                A_now = jnp.minimum(A, FLUSH_SEGMENTS * MSS)
                n_seg = (A_now + MSS - 1) // MSS
                rest = A - A_now
                fl_mask = fl_mask & ~bad
                n_seg = jnp.where(fl_mask, n_seg, 0)
                A_now = jnp.where(fl_mask, A_now, 0)
                # the FIN rides once all data is packetized (ref: tcp_flush
                # FIN tail; self-guarding — after it, snd_nxt = end + 1)
                fin1 = fl_mask & gather_hs(tcp.fin_pending, fslot) \
                    & (g_nxt + A_now == g_end) & (rest == 0)
                nxt_after = g_nxt + A_now + fin1.astype(I32)
                tcp = tcp.replace(
                    snd_nxt=set_hs(tcp.snd_nxt, fl_mask, fslot, nxt_after),
                    snd_max=set_hs(tcp.snd_max, fl_mask, fslot,
                                   jnp.maximum(gather_hs(tcp.snd_max, fslot),
                                               nxt_after)))
                # the serial chain decision also requires ring + sndbuf
                # room AT THIS POINT of the micro-step — i.e. counting
                # the backlog plus the packets this event has enqueued
                # so far (the retransmit and this flush's burst;
                # ref: tcp_flush room2, tcp.py:729-734). The retransmit
                # length is not yet clipped here, so when the room
                # verdict depends on it (a 1..MSS-byte uncertainty,
                # only possible on a near-full send buffer) the lane
                # conservatively stops.
                seg2 = jnp.minimum(
                    jnp.minimum(g_end - nxt_after, MSS),
                    g_una + g_wnd - nxt_after)
                ob_cnt0 = gather_hs(net.out_count, fslot)
                ob_byt0 = gather_hs(net.out_bytes, fslot)
                sb0 = gather_hs(net.sk_sndbuf, fslot)
                cnt_extra = retx_ack.astype(I32) + n_seg + fin1.astype(I32)
                room_no_rt = (ob_cnt0 + cnt_extra < BO) \
                    & (ob_byt0 + A_now + seg2 <= sb0)
                room_max_rt = (ob_cnt0 + cnt_extra < BO) \
                    & (ob_byt0 + A_now + jnp.where(retx_ack, MSS, 0)
                       + seg2 <= sb0)
                bad, why = _flag(bad, why,
                                 fl_mask & (rest > 0)
                                 & (room_no_rt != room_max_rt), 1 << 39)
                chain = fl_mask & (rest > 0) & room_max_rt & ~bad \
                    & ~gather_hs(tcp.flush_pending, fslot)

                def _chain_push(ops):
                    tcp, q, seq_ctr, bad, why = ops
                    tcp = tcp.replace(flush_pending=set_hs(
                        tcp.flush_pending, chain, fslot, True))
                    cw_ = jnp.zeros((H, W), I32).at[:, 0].set(
                        fslot.astype(I32))
                    free_c = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, chain & ~free_c, 1 << 42)
                    ch = chain & ~bad
                    q = _push_local(q, ch, t, EventKind.TCP_FLUSH, cw_,
                                    lane, seq_ctr)
                    seq_ctr = seq_ctr + ch.astype(I32)
                    return tcp, q, seq_ctr, bad, why

                tcp, q, seq_ctr, bad, why = _gate(
                    jnp.any(chain), _chain_push, (tcp, q, seq_ctr, bad, why))

                # RTO arm after flush (ref: tcp_flush tail + _arm_rtx)
                h_una = gather_hs(tcp.snd_una, fslot)
                h_nxt = gather_hs(tcp.snd_nxt, fslot)
                # persist condition (zero window, unsent data waiting) — the
                # serial path would arm a probe timer (out of model)
                bad, why = _flag(bad, why, (fl_mask & (h_una == h_nxt) & (gather_hs(tcp.snd_end, fslot) > h_nxt) & (gather_hs(tcp.snd_wnd, fslot) == 0)), 33554432)
                fl_mask = fl_mask & ~bad
                outstanding = fl_mask & (h_una < h_nxt)
                need = outstanding & (
                    gather_hs(tcp.rtx_expire, fslot) == simtime.INVALID)

                def _arm_sec(ops):
                    tcp, q, seq_ctr, bad, why = ops
                    rto_arm = (gather_hs(tcp.rto_ms, fslot).astype(I64)
                               << jnp.minimum(gather_hs(tcp.backoff, fslot),
                                              MAX_BACKOFF).astype(I64)) \
                        * simtime.ONE_MILLISECOND
                    rto_arm = jnp.minimum(
                        rto_arm, I64(RTO_MAX_MS) * simtime.ONE_MILLISECOND)
                    deadline = t + rto_arm
                    tcp = tcp.replace(rtx_expire=set_hs(
                        tcp.rtx_expire, need, fslot, deadline))
                    in_flight = gather_hs(tcp.rtx_event, fslot)
                    earlier = need & in_flight & (
                        deadline < gather_hs(tcp.rtx_fire, fslot))
                    # (an in-window deadline is fine: the pushed event
                    # pops later in this scan and the RTX fire section
                    # handles pending/due alike)
                    need_event = (need & ~in_flight) | earlier
                    gen = gather_hs(tcp.rtx_gen, fslot) + 1
                    tcp = tcp.replace(
                        rtx_gen=set_hs(tcp.rtx_gen, need_event, fslot, gen),
                        rtx_event=set_hs(tcp.rtx_event, need_event, fslot,
                                         True),
                        rtx_fire=set_hs(tcp.rtx_fire, need_event, fslot,
                                        deadline))
                    rw = jnp.zeros((H, W), I32)
                    rw = rw.at[:, 0].set(fslot.astype(I32))
                    rw = rw.at[:, 1].set(gen)
                    free_b = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, (need_event & ~free_b),
                                     134217728)
                    q = _push_local(q, need_event & ~bad, deadline,
                                    EventKind.TCP_RTX_TIMER, rw, lane,
                                    seq_ctr)
                    seq_ctr = seq_ctr + (need_event & ~bad).astype(I32)
                    return tcp, q, seq_ctr, bad, why

                tcp, q, seq_ctr, bad, why = _gate(
                    jnp.any(need), _arm_sec, (tcp, q, seq_ctr, bad, why))

                # ===== secondary close (relay dual-close, tcp_close #2) ===
                # up_conn: no stream data, so its flush reduces to the FIN
                # + the RTO arm (ref: tcp_close -> tcp_flush on a drained
                # CLOSE_WAIT socket)
                g2_nxt = gather_hs(tcp.snd_nxt, c2_slot)

                def _c2_sec(ops):
                    tcp, q, seq_ctr, bad, why, _ = ops
                    g2_end = gather_hs(tcp.snd_end, c2_slot)
                    bad, why = _flag(bad, why,
                                     (c2_mask & (g2_end != g2_nxt)), 1 << 53)
                    fin2 = c2_mask & ~bad & gather_hs(tcp.fin_pending,
                                                      c2_slot)
                    tcp = tcp.replace(
                        snd_nxt=set_hs(tcp.snd_nxt, fin2, c2_slot,
                                       g2_nxt + 1),
                        snd_max=set_hs(tcp.snd_max, fin2, c2_slot,
                                       jnp.maximum(
                                           gather_hs(tcp.snd_max, c2_slot),
                                           g2_nxt + 1)))
                    need2 = fin2 & (gather_hs(tcp.rtx_expire, c2_slot)
                                    == simtime.INVALID)
                    rto2 = (gather_hs(tcp.rto_ms, c2_slot).astype(I64)
                            << jnp.minimum(gather_hs(tcp.backoff, c2_slot),
                                           MAX_BACKOFF).astype(I64)) \
                        * simtime.ONE_MILLISECOND
                    rto2 = jnp.minimum(
                        rto2, I64(RTO_MAX_MS) * simtime.ONE_MILLISECOND)
                    dl2 = t + rto2
                    tcp = tcp.replace(rtx_expire=set_hs(
                        tcp.rtx_expire, need2, c2_slot, dl2))
                    inflt2 = gather_hs(tcp.rtx_event, c2_slot)
                    earl2 = need2 & inflt2 & (
                        dl2 < gather_hs(tcp.rtx_fire, c2_slot))
                    nev2 = (need2 & ~inflt2) | earl2
                    gen2 = gather_hs(tcp.rtx_gen, c2_slot) + 1
                    tcp = tcp.replace(
                        rtx_gen=set_hs(tcp.rtx_gen, nev2, c2_slot, gen2),
                        rtx_event=set_hs(tcp.rtx_event, nev2, c2_slot, True),
                        rtx_fire=set_hs(tcp.rtx_fire, nev2, c2_slot, dl2))
                    rw2 = (jnp.zeros((H, W), I32)
                           .at[:, 0].set(c2_slot.astype(I32))
                           .at[:, 1].set(gen2))
                    free_2 = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, nev2 & ~free_2, 1 << 55)
                    nev2 = nev2 & ~bad
                    q = _push_local(q, nev2, dl2, EventKind.TCP_RTX_TIMER,
                                    rw2, lane, seq_ctr)
                    seq_ctr = seq_ctr + nev2.astype(I32)
                    return tcp, q, seq_ctr, bad, why, fin2

                tcp, q, seq_ctr, bad, why, fin2 = _gate(
                    jnp.any(c2_mask), _c2_sec,
                    (tcp, q, seq_ctr, bad, why, zb))

                # ===== DACK fire ==========================================
                dgen = p.word(1)
                dslot = jnp.where(is_dk, p.word(0), 0)

                def _dack_fire_sec(ops):
                    tcp, _ = ops
                    live_dk = is_dk & (dgen == gather_hs(tcp.dack_gen,
                                                         dslot))
                    tcp = tcp.replace(dack_scheduled=set_hs(
                        tcp.dack_scheduled, live_dk, dslot, False))
                    fire = live_dk & (gather_hs(tcp.dack_counter, dslot) > 0)
                    tcp = tcp.replace(dack_counter=set_hs(
                        tcp.dack_counter, fire, dslot, jnp.zeros((H,), I32)))
                    return tcp, fire

                tcp, fire = _gate(jnp.any(is_dk), _dack_fire_sec, (tcp, zb))

                # ===== RTX timer fire (ref: handle_tcp_rtx) ===============
                # stale generations die; a disarmed deadline clears the
                # in-flight flag; a deadline that MOVED later re-emits the
                # covering event. A DUE deadline runs the full timeout
                # machinery (ref: tcp.py:1349-1401): collapse to slow
                # start, backoff, go-back-N retransmit of the snd_una
                # segment (wired in the wire stage below), re-arm.
                # Only the zero-window persist probe stays out of model.
                rslot = jnp.where(is_rtx, p.word(0), 0)

                def _rtx_fire_sec(ops):
                    tcp, q, seq_ctr, bad, why, _ = ops
                    rgen = p.word(1)
                    live_rtx = is_rtx & (rgen == gather_hs(tcp.rtx_gen,
                                                           rslot))
                    rdl = gather_hs(tcp.rtx_expire, rslot)
                    r_disarm = live_rtx & (rdl == simtime.INVALID)
                    r_pending = live_rtx & ~r_disarm & (t < rdl)
                    r_due = live_rtx & ~r_disarm & ~r_pending
                    tcp = tcp.replace(rtx_event=set_hs(
                        tcp.rtx_event, r_disarm, rslot, False))
                    r_emit = r_pending & ~bad
                    xw = jnp.zeros((H, W), I32)
                    xw = xw.at[:, 0].set(rslot.astype(I32))
                    xw = xw.at[:, 1].set(rgen)
                    free_x = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, r_emit & ~free_x, 1 << 41)
                    r_emit = r_emit & ~bad
                    q = _push_local(q, r_emit, rdl, EventKind.TCP_RTX_TIMER,
                                    xw, lane, seq_ctr)
                    seq_ctr = seq_ctr + r_emit.astype(I32)
                    tcp = tcp.replace(rtx_fire=set_hs(
                        tcp.rtx_fire, r_emit, rslot, rdl))

                    if lossless:
                        # a DUE deadline is a real RTO: out of the
                        # lossless model, stop the lane
                        bad, why = _flag(bad, why, r_due, 1 << 34)
                        return (tcp, q, seq_ctr, bad, why,
                                jnp.zeros((H,), bool))

                    # ---- timeout (ref: tcp.py:1349-1401) -----------------
                    r_una = gather_hs(tcp.snd_una, rslot)
                    r_nxt = gather_hs(tcp.snd_nxt, rslot)
                    r_live = r_due & (r_una < r_nxt)
                    r_probe = r_due & (r_una == r_nxt) \
                        & (gather_hs(tcp.snd_end, rslot) > r_nxt) \
                        & (gather_hs(tcp.snd_wnd, rslot) == 0)
                    bad, why = _flag(bad, why, r_probe, 1 << 40)
                    r_live = r_live & ~bad
                    r_cwnd = gather_hs(tcp.cwnd, rslot)
                    tcp = tcp.replace(
                        ssthresh=set_hs(tcp.ssthresh, r_live, rslot,
                                        cong.ssthresh_on_loss(alg, r_cwnd)),
                        cwnd=set_hs(tcp.cwnd, r_live, rslot,
                                    jnp.full((H,), RESTART_CWND, I32)))
                    wmax_t, ep_t = cong.on_loss_event(
                        alg, r_live, r_cwnd, gather_hs(tcp.cub_wmax, rslot),
                        gather_hs(tcp.cub_epoch_ms, rslot))
                    tcp = tcp.replace(
                        cub_wmax=set_hs(tcp.cub_wmax, r_live, rslot, wmax_t),
                        cub_epoch_ms=set_hs(tcp.cub_epoch_ms, r_live, rslot,
                                            ep_t),
                        ca_acc=set_hs(tcp.ca_acc, r_live, rslot,
                                      jnp.zeros((H,), I32)),
                        in_recovery=set_hs(tcp.in_recovery, r_live, rslot,
                                           False),
                        dup_acks=set_hs(tcp.dup_acks, r_live, rslot,
                                        jnp.zeros((H,), I32)),
                        backoff=set_hs(tcp.backoff, r_live, rslot,
                                       jnp.minimum(
                                           gather_hs(tcp.backoff, rslot) + 1,
                                           MAX_BACKOFF)))
                    tcp = tcp.replace(
                        rtx_event=set_hs(tcp.rtx_event, r_due, rslot, False),
                        rtx_expire=set_hs(tcp.rtx_expire, r_due, rslot,
                                          jnp.full((H,), simtime.INVALID,
                                                   I64)))
                    # re-arm with the bumped backoff (_arm_rtx for live;
                    # the retransmit segment itself wires below in
                    # serial order). After the due-fire cleared
                    # rtx_event, need_event is always true for r_live.
                    rto_r = (gather_hs(tcp.rto_ms, rslot).astype(I64)
                             << jnp.minimum(gather_hs(tcp.backoff, rslot),
                                            MAX_BACKOFF).astype(I64)) \
                        * simtime.ONE_MILLISECOND
                    rto_r = jnp.minimum(
                        rto_r, I64(RTO_MAX_MS) * simtime.ONE_MILLISECOND)
                    rdl_new = t + rto_r
                    tcp = tcp.replace(rtx_expire=set_hs(
                        tcp.rtx_expire, r_live, rslot, rdl_new))
                    gen_r = gather_hs(tcp.rtx_gen, rslot) + 1
                    tcp = tcp.replace(
                        rtx_gen=set_hs(tcp.rtx_gen, r_live, rslot, gen_r),
                        rtx_event=set_hs(tcp.rtx_event, r_live, rslot, True),
                        rtx_fire=set_hs(tcp.rtx_fire, r_live, rslot,
                                        rdl_new))
                    rw_r = (jnp.zeros((H, W), I32)
                            .at[:, 0].set(rslot.astype(I32))
                            .at[:, 1].set(gen_r))
                    free_r = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why, r_live & ~free_r, 8)
                    r_live = r_live & ~bad
                    q = _push_local(q, r_live, rdl_new,
                                    EventKind.TCP_RTX_TIMER, rw_r, lane,
                                    seq_ctr)
                    seq_ctr = seq_ctr + r_live.astype(I32)
                    return tcp, q, seq_ctr, bad, why, r_live

                tcp, q, seq_ctr, bad, why, retx_rto = _gate(
                    jnp.any(is_rtx), _rtx_fire_sec,
                    (tcp, q, seq_ctr, bad, why, zb))

                # ===== wire: out-ring cycle + stamps + outbox =============
                # Per-lane burst, in serial emission order: [retransmit
                # segment] -> [n_seg flush data (+ FIN tail)] -> [pure
                # ACK: dack fire OR loss-signalling immediate ACK] — all
                # on ONE wslot by construction (retx coexists with flush
                # only on a partial ACK, where both target the arrival
                # socket). A relay dual-close adds ONE secondary FIN on
                # c2_slot, wired last (FIFO priority order, exactly the
                # serial drain).
                if lossless:
                    # no retransmissions exist in the lossless model
                    retx_do = jnp.zeros((H,), bool)
                    retx_sent = retx_do
                    retx_data = retx_do
                    rt_len = jnp.zeros((H,), I32)
                    rt_una = jnp.zeros((H,), I32)
                    rt_flags = jnp.full((H,), pf.TCPF_ACK, I32)
                else:
                    retx_do = (retx_ack | retx_rto) & ~bad
                    rtslot = jnp.where(retx_rto, rslot, slot)
                    # handshake retransmits (SYN/SYN|ACK) are out of
                    # model
                    rt_st = gather_hs(tcp.st, rtslot)
                    bad, why = _flag(
                        bad, why,
                        retx_do & (rt_st < TcpSt.ESTABLISHED), 512)
                    retx_do = retx_do & ~bad
                    # regenerate the snd_una segment (ref:
                    # _retransmit_one, tcp.py:767-807): FIN from the
                    # state machine, data from the [snd_una, snd_end)
                    # byte range clipped at the first peer-sacked edge
                    # (sack_clip_len)
                    rt_una = gather_hs(tcp.snd_una, rtslot)
                    rt_end = gather_hs(tcp.snd_end, rtslot)
                    rt_nxt = gather_hs(tcp.snd_nxt, rtslot)
                    rt_fin_ever = gather_hs(tcp.fin_pending, rtslot) & (
                        gather_hs(tcp.snd_max, rtslot) == rt_end + 1)
                    retx_fin = retx_do & rt_fin_ever & (rt_una == rt_end)
                    retx_data = retx_do & ~retx_fin & (rt_una < rt_end)
                    rtsc = jnp.clip(rtslot, 0, S - 1)
                    rt_len = sack_clip_len(
                        rt_una, jnp.minimum(rt_end - rt_una, MSS),
                        tcp.sack_l[rows, rtsc], tcp.sack_r[rows, rtsc])
                    rt_len = jnp.where(retx_data, rt_len, 0).astype(I32)
                    retx_sent = retx_fin | retx_data
                    rt_flags = jnp.where(retx_fin,
                                         pf.TCPF_FIN | pf.TCPF_ACK,
                                         pf.TCPF_ACK)
                    tcp = tcp.replace(retx_segs=tcp.retx_segs
                                      + retx_sent.astype(I64))
                    # go-back-N: an RTO rewinds snd_nxt to just past
                    # the resent segment (ref: tcp.py:1394-1399)
                    resent_end = jnp.where(retx_data, rt_una + rt_len,
                                           rt_una + 1)
                    rewind = retx_rto & retx_sent & (resent_end < rt_nxt)
                    tcp = tcp.replace(snd_nxt=set_hs(
                        tcp.snd_nxt, rewind, rtslot, resent_end))

                pure_ack = (fire | imm_ack) & ~bad
                wslot = jnp.where(fire, dslot,
                                  jnp.where(retx_rto, rslot,
                                            jnp.where(imm_ack, slot,
                                                      fslot)))
                n_pkt = retx_sent.astype(I32) + n_seg + fin1.astype(I32) \
                    + pure_ack.astype(I32)
                sending = (retx_sent | pure_ack | (n_seg > 0) | fin1) & ~bad
                fin2 = fin2 & ~bad
                n_pkt = jnp.where(sending, n_pkt, 0)

                # refill the send bucket at t (drain-entry refill); the
                # arrival path refilled already (same quantum -> no-op)
                dq2 = jnp.maximum(t // simtime.ONE_MILLISECOND
                                  - net.tb_quantum, 0)
                refresh2 = (sending | fin2) & (dq2 > 0)
                send_tok = jnp.minimum(net.tb_send_refill + pf.MTU,
                                       net.tb_send_tokens
                                       + dq2 * net.tb_send_refill)
                recv_tok2 = jnp.minimum(net.tb_recv_refill + pf.MTU,
                                        net.tb_recv_tokens
                                        + dq2 * net.tb_recv_refill)
                net = net.replace(
                    tb_send_tokens=jnp.where(refresh2, send_tok,
                                             net.tb_send_tokens),
                    tb_recv_tokens=jnp.where(refresh2, recv_tok2,
                                             net.tb_recv_tokens),
                    tb_quantum=jnp.where(refresh2,
                                         t // simtime.ONE_MILLISECOND,
                                         net.tb_quantum))

                # ---- lane mode: fused fast path vs NIC ring path -----
                # The fused path (the pre-r5 wire_one sequence) models
                # enqueue + same-instant full drain — valid only when
                # the ring is empty, every burst packet clears the
                # per-packet token check, and the burst fits one serial
                # drain (nic_drain). Otherwise the lane takes the RING
                # path: enqueue to the real socket output ring, drain
                # through the token bucket, chain/wait NIC_SEND exactly
                # like handle_nic_send (nic.py:444-490) — the
                # token-limited (slow-link) sender regime.
                flush_len = []
                for j in range(FLUSH_SEGMENTS + 1):
                    pj_ = sending & (j < n_seg + fin1.astype(I32))
                    is_fin_j_ = fin1 & (j == n_seg)
                    flush_len.append((pj_, jnp.where(
                        is_fin_j_, 0,
                        jnp.clip(A_now - j * MSS, 0, MSS)).astype(I32)))
                afford = jnp.ones((H,), bool)
                cum_wl = jnp.zeros((H,), I64)
                for m_k, len_k in ([(retx_sent & sending, rt_len)]
                                   + flush_len
                                   + [(pure_ack & sending,
                                       jnp.zeros((H,), I32)),
                                      (fin2, jnp.zeros((H,), I32))]):
                    short_k = m_k & (net.tb_send_tokens - cum_wl < pf.MTU)
                    afford = afford & ~short_k
                    cum_wl = cum_wl + jnp.where(
                        m_k,
                        pf.wire_length(jnp.full((H,), pf.PROTO_TCP, I32),
                                       len_k).astype(I64), 0)
                backlog0 = jnp.sum(net.out_count, axis=1) > 0
                overbound = (n_pkt + fin2.astype(I32)) > cfg.nic_drain
                ring_lane = (sending | fin2) & (backlog0 | ~afford
                                                | overbound)
                fast = ~ring_lane
                fast_s = sending & fast
                drain_m = is_ns & ~bad

                # stamps shared by every packet of the burst (state does
                # not change between same-instant wires)
                stamp_ack = gather_hs(tcp.rcv_nxt, wslot)
                stamp_win = jnp.maximum(
                    gather_hs(net.sk_rcvbuf, wslot)
                    - gather_hs(tcp.app_rbytes, wslot), 0)
                stamp_tse = gather_hs(tcp.ts_recent, wslot)
                w_sport = gather_hs(net.sk_bound_port, wslot)
                w_dport = gather_hs(net.sk_peer_port, wslot)
                w_dip = gather_hs(net.sk_peer_ip, wslot)
                w_dsth = gather_hs(peer_h, wslot)
                bad, why = _flag(bad, why, (sending & (w_dsth < 0)), 268435456)
                # loopback connections route via PACKET_LOCAL +1ns in
                # the serial NIC — not modeled here
                bad, why = _flag(bad, why, (sending & (w_dsth == lane)),
                                 1 << 38)
                sending = sending & ~bad
                fast_s = fast_s & ~bad
                ring_lane = ring_lane & ~bad
                drain_m = drain_m & ~bad
                n_pkt = jnp.where(sending, n_pkt, 0)
                w_lat = gather_hs(lat_s, wslot)
                w_rel = gather_hs(rel_s, wslot)
                # the wired ACK cancels any pending delayed ACK on ITS
                # socket (ref: tcp.c:1105-1108 via nic wire_ack_departed);
                # ring-path packets cancel at their actual drain instead
                tcp = tcp.replace(dack_counter=set_hs(
                    tcp.dack_counter, fast_s, wslot, jnp.zeros((H,), I32)))

                out = sim.outbox
                M = out.capacity
                drops = jnp.zeros((H,), I32)
                last_drop = net.last_drop_status
                tx_wl = jnp.zeros((H,), I64)
                ring_head0 = gather_hs(net.out_head, wslot)
                rngc = net.rng_ctr
                emitted = jnp.zeros((H,), I32)
                ob_count = out.count
                ob_over = jnp.zeros((H,), bool)
                def wire_one(state, pj, lenj, seqj, flagsj, stamps, j_ctr,
                             extraj=0):
                    """Wire ONE packet per masked lane: token policing,
                    enqueue-time words + wire stamps (incl. the SACK
                    advertisement — stamp_at_wire parity), the
                    reliability draw at the running counter, the outbox
                    append. `state` = (out, bad, why, last_drop, drops,
                    tx_wl, emitted, ob_over); stamps = (ack, win, tse,
                    sport, dport, dip, dsth, lat, rel, sack3); extraj =
                    extra audit-status bits (retransmit stages)."""
                    (out, bad, why, last_drop, drops, tx_wl, emitted,
                     ob_over) = state
                    (s_ack, s_win, s_tse, s_sport, s_dport, s_dip, s_dsth,
                     s_lat, s_rel, s_sk) = stamps
                    wlj = pf.wire_length(jnp.full((H,), pf.PROTO_TCP, I32),
                                         lenj).astype(I64)
                    # token policing before EACH wire (serial `can` check)
                    bad, why = _flag(
                        bad, why,
                        (pj & (net.tb_send_tokens - tx_wl < pf.MTU)),
                        536870912)
                    pj = pj & ~bad
                    # out-ring plane contents below head are dead storage
                    # (tests/test_bulk.py DEAD convention); the wire copy
                    # carries the enqueue-time words + wire stamps
                    ring_w = jnp.zeros((H, W), I32)
                    ring_w = ring_w.at[:, pf.W_PROTO].set(
                        pf.PROTO_TCP | (flagsj << 8))
                    ring_w = ring_w.at[:, pf.W_LEN].set(lenj)
                    ring_w = ring_w.at[:, pf.W_PORTS].set(
                        pf.pack_ports(s_sport, s_dport))
                    ring_w = ring_w.at[:, pf.W_SEQ].set(seqj)
                    ring_w = ring_w.at[:, pf.W_PAYREF].set(pf.PAYREF_NONE)
                    ring_w = ring_w.at[:, pf.W_DSTIP].set(
                        s_dip.astype(jnp.uint32).astype(I32))
                    ring_w = ring_w.at[:, pf.W_STATUS].set(
                        pf.PDS_SND_CREATED | pf.PDS_SND_TCP_ENQUEUE_THROTTLED
                        | pf.PDS_SND_SOCKET_BUFFERED | extraj)
                    wire_w = ring_w.at[:, pf.W_ACK].set(s_ack)
                    wire_w = wire_w.at[:, pf.W_WIN].set(s_win)
                    wire_w = wire_w.at[:, pf.W_TSVAL].set(_ms(t))
                    wire_w = wire_w.at[:, pf.W_TSECHO].set(s_tse)
                    (sk1l, sk1r), (sk2l, sk2r), (sk3l, sk3r) = s_sk
                    wire_w = wire_w.at[:, pf.W_SACKL].set(sk1l)
                    wire_w = wire_w.at[:, pf.W_SACKR].set(sk1r)
                    wire_w = wire_w.at[:, pf.W_SACKL2].set(sk2l)
                    wire_w = wire_w.at[:, pf.W_SACKR2].set(sk2r)
                    wire_w = wire_w.at[:, pf.W_SACKL3].set(sk3l)
                    wire_w = wire_w.at[:, pf.W_SACKR3].set(sk3r)
                    wire_w = wire_w.at[:, pf.W_STATUS].set(
                        ring_w[:, pf.W_STATUS] | pf.PDS_SND_INTERFACE_SENT)
                    # reliability draw at the exact serial counter
                    if rel_dead:
                        dropj = jnp.zeros_like(pj)
                    else:
                        u = rng.uniform_at(net.rng_keys,
                                           rngc + jnp.asarray(j_ctr,
                                                              jnp.uint32))
                        dropj = pj & (lenj > 0) & (u > s_rel)
                    sendj = pj & ~dropj
                    wire_sent = wire_w.at[:, pf.W_STATUS].set(
                        wire_w[:, pf.W_STATUS] | pf.PDS_INET_SENT)
                    last_drop = jnp.where(
                        dropj, wire_w[:, pf.W_STATUS] | pf.PDS_INET_DROPPED,
                        last_drop)
                    drops = drops + dropj.astype(I32)
                    tx_wl = tx_wl + jnp.where(pj, wlj, 0)
                    col = ob_count + emitted
                    okb = sendj & (col < M)
                    ob_over = ob_over | (sendj & ~(col < M))
                    colc = jnp.clip(col, 0, M - 1)
                    out = out.replace(
                        dst=out.dst.at[rows, colc].set(
                            jnp.where(okb, s_dsth, out.dst[rows, colc])),
                        time=out.time.at[rows, colc].set(
                            jnp.where(okb, t + s_lat, out.time[rows, colc])),
                        kind=out.kind.at[rows, colc].set(
                            jnp.where(okb, EventKind.PACKET,
                                      out.kind[rows, colc])),
                        src=out.src.at[rows, colc].set(
                            jnp.where(okb, lane, out.src[rows, colc])),
                        seq=out.seq.at[rows, colc].set(
                            jnp.where(okb, seq_ctr + emitted,
                                      out.seq[rows, colc])),
                        words=out.words.at[rows, colc].set(
                            jnp.where(okb[:, None], wire_sent,
                                      out.words[rows, colc])),
                    )
                    emitted = emitted + sendj.astype(I32)
                    return (out, bad, why, last_drop, drops, tx_wl, emitted,
                            ob_over)

                stamps1 = (stamp_ack, stamp_win, stamp_tse, w_sport,
                           w_dport, w_dip, w_dsth, w_lat, w_rel,
                           _sack_stamps(tcp, wslot))
                state = (out, bad, why, last_drop, drops, tx_wl, emitted,
                         ob_over)
                retx_status = jnp.where(
                    retx_sent,
                    pf.PDS_SND_TCP_ENQUEUE_RETRANSMIT
                    | pf.PDS_SND_TCP_DEQUEUE_RETRANSMIT
                    | pf.PDS_SND_TCP_RETRANSMITTED, 0)
                # 1) the retransmitted snd_una segment (serial order:
                #    _retransmit_one precedes the flush)
                state = _gate(
                    jnp.any(retx_sent & fast_s),
                    lambda s: wire_one(s, retx_sent & fast_s, rt_len,
                                       rt_una, rt_flags, stamps1,
                                       jnp.zeros((H,), I32), retx_status),
                    state)
                rt_n = retx_sent.astype(I32)
                # 2) the flush burst: n_seg data segments + the FIN tail
                for j in range(FLUSH_SEGMENTS + 1):
                    pj = fast_s & (j < n_seg + fin1.astype(I32))
                    is_fin_j = fin1 & (j == n_seg)
                    lenj = jnp.where(
                        is_fin_j, 0,
                        jnp.clip(A_now - j * MSS, 0, MSS)).astype(I32)
                    seqj = jnp.where(is_fin_j, g_nxt + A_now,
                                     g_nxt + j * MSS)
                    flagsj = jnp.where(is_fin_j,
                                       pf.TCPF_FIN | pf.TCPF_ACK,
                                       pf.TCPF_ACK)
                    state = wire_one(state, pj, lenj, seqj, flagsj,
                                     stamps1, rt_n + j)
                # 3) the pure ACK: a fired delayed ACK, or the immediate
                #    loss-signalling ACK (old/out-of-order/dropped data)
                state = _gate(
                    jnp.any(pure_ack & fast_s),
                    lambda s: wire_one(s, pure_ack & fast_s,
                                       jnp.zeros((H,), I32),
                                       gather_hs(tcp.snd_nxt, wslot),
                                       jnp.full((H,), pf.TCPF_ACK, I32),
                                       stamps1,
                                       rt_n + n_seg + fin1.astype(I32)),
                    state)
                # secondary FIN (dual close) after the whole primary
                # burst — fast lanes only; ring lanes enqueue it below
                fin2f = fin2 & fast

                def _wire2_sec(ops):
                    state, tcp, fin2v = ops
                    stamps2 = (gather_hs(tcp.rcv_nxt, c2_slot),
                               jnp.maximum(
                                   gather_hs(net.sk_rcvbuf, c2_slot)
                                   - gather_hs(tcp.app_rbytes, c2_slot), 0),
                               gather_hs(tcp.ts_recent, c2_slot),
                               gather_hs(net.sk_bound_port, c2_slot),
                               gather_hs(net.sk_peer_port, c2_slot),
                               gather_hs(net.sk_peer_ip, c2_slot),
                               gather_hs(peer_h, c2_slot),
                               gather_hs(lat_s, c2_slot),
                               gather_hs(rel_s, c2_slot),
                               _sack_stamps(tcp, c2_slot))
                    (out, bad, why, last_drop, drops, tx_wl, emitted,
                     ob_over) = state
                    bad, why = _flag(
                        bad, why,
                        (fin2v & (gather_hs(peer_h, c2_slot) < 0)), 1 << 62)
                    fin2v = fin2v & ~bad
                    state = (out, bad, why, last_drop, drops, tx_wl,
                             emitted, ob_over)
                    state = wire_one(state, fin2v, jnp.zeros((H,), I32),
                                     g2_nxt,
                                     jnp.full((H,),
                                              pf.TCPF_FIN | pf.TCPF_ACK,
                                              I32),
                                     stamps2, n_pkt)
                    (out, bad, why, last_drop, drops, tx_wl, emitted,
                     ob_over) = state
                    fin2v = fin2v & ~bad
                    tcp = tcp.replace(dack_counter=set_hs(
                        tcp.dack_counter, fin2v, c2_slot,
                        jnp.zeros((H,), I32)))
                    return state, tcp, fin2v

                state, tcp, fin2f = _gate(jnp.any(fin2f), _wire2_sec,
                                          (state, tcp, fin2f))
                (out, bad, why, last_drop, drops, tx_wl, emitted,
                 ob_over) = state

                # ===== NIC ring path (r5): enqueue + token drain ==========
                # Ring-mode lanes put the burst on the REAL socket
                # output ring (sk_enqueue_out parity: plane words,
                # priority stamps, count/bytes) and then drain through
                # the token bucket exactly like handle_nic_send
                # (nic.py:444-604): FIFO head-priority selection,
                # wire-time stamping, per-packet token check, chain /
                # next-refill-wait NIC_SEND continuation events.
                def _mk_ring_w(lenj, seqj, flagsj, sportj, dportj, dipj,
                               extraj):
                    rw_ = jnp.zeros((H, W), I32)
                    rw_ = rw_.at[:, pf.W_PROTO].set(
                        pf.PROTO_TCP | (flagsj << 8))
                    rw_ = rw_.at[:, pf.W_LEN].set(lenj)
                    rw_ = rw_.at[:, pf.W_PORTS].set(
                        pf.pack_ports(sportj, dportj))
                    rw_ = rw_.at[:, pf.W_SEQ].set(seqj)
                    rw_ = rw_.at[:, pf.W_PAYREF].set(pf.PAYREF_NONE)
                    rw_ = rw_.at[:, pf.W_DSTIP].set(
                        dipj.astype(jnp.uint32).astype(I32))
                    return rw_.at[:, pf.W_STATUS].set(
                        pf.PDS_SND_CREATED
                        | pf.PDS_SND_TCP_ENQUEUE_THROTTLED
                        | pf.PDS_SND_SOCKET_BUFFERED | extraj)

                enq = jnp.zeros((H,), I32)

                def _enqueue_sec(ops):
                    net, tcp, bad, why, enq = ops
                    from shadow_tpu.net.rings import ring_push_at

                    c2_sport = gather_hs(net.sk_bound_port, c2_slot)
                    c2_dport = gather_hs(net.sk_peer_port, c2_slot)
                    c2_dip = gather_hs(net.sk_peer_ip, c2_slot)
                    c2_dsth = gather_hs(peer_h, c2_slot)
                    fin2r = fin2 & ring_lane
                    bad, why = _flag(bad, why, fin2r & (c2_dsth < 0),
                                     1 << 62)
                    bad, why = _flag(bad, why, fin2r & (c2_dsth == lane),
                                     1 << 38)
                    comps = [(retx_sent & ring_lane, rt_len, rt_una,
                              rt_flags, wslot, w_sport, w_dport, w_dip,
                              retx_status)]
                    for j, (pj_, len_j) in enumerate(flush_len):
                        is_fin_j = fin1 & (j == n_seg)
                        comps.append((pj_ & ring_lane, len_j,
                                      jnp.where(is_fin_j, g_nxt + A_now,
                                                g_nxt + j * MSS),
                                      jnp.where(is_fin_j,
                                                pf.TCPF_FIN | pf.TCPF_ACK,
                                                pf.TCPF_ACK),
                                      wslot, w_sport, w_dport, w_dip, 0))
                    comps.append((pure_ack & ring_lane,
                                  jnp.zeros((H,), I32),
                                  gather_hs(tcp.snd_nxt, wslot),
                                  jnp.full((H,), pf.TCPF_ACK, I32),
                                  wslot, w_sport, w_dport, w_dip, 0))
                    comps.append((fin2 & ring_lane, jnp.zeros((H,), I32),
                                  g2_nxt,
                                  jnp.full((H,), pf.TCPF_FIN | pf.TCPF_ACK,
                                           I32),
                                  c2_slot, c2_sport, c2_dport, c2_dip, 0))
                    for (m_k, len_k, seq_k, flags_k, slot_k, sport_k,
                         dport_k, dip_k, extra_k) in comps:
                        ek = m_k & ~bad
                        # sk_enqueue_out admission (sndbuf + ring room);
                        # a failed serial enqueue stalls the segment
                        # with snd_nxt already advanced here — out of
                        # model, stop the lane instead
                        sp_ok = (gather_hs(net.out_bytes, slot_k) + len_k
                                 <= gather_hs(net.sk_sndbuf, slot_k))
                        bad, why = _flag(bad, why, ek & ~sp_ok, 1 << 36)
                        ek = ek & ~bad
                        okp, pos = ring_push_at(net.out_head,
                                                net.out_count, BO, ek,
                                                slot_k)
                        bad, why = _flag(bad, why, ek & ~okp, 1 << 37)
                        ek = ek & okp & ~bad
                        rw_ = _mk_ring_w(len_k, seq_k, flags_k, sport_k,
                                         dport_k, dip_k, extra_k)
                        net = net.replace(
                            out_words=set_ring(net.out_words, ek, slot_k,
                                               pos, rw_),
                            out_priority=set_ring(
                                net.out_priority, ek, slot_k, pos,
                                (net.priority_ctr
                                 + enq.astype(I64)).astype(
                                     net.out_priority.dtype)),
                            out_count=set_hs(net.out_count, ek, slot_k,
                                             gather_hs(net.out_count,
                                                       slot_k) + 1),
                            out_bytes=set_hs(net.out_bytes, ek, slot_k,
                                             gather_hs(net.out_bytes,
                                                       slot_k) + len_k),
                        )
                        enq = enq + ek.astype(I32)
                    return net, tcp, bad, why, enq

                net, tcp, bad, why, enq = _gate(
                    jnp.any(ring_lane), _enqueue_sec,
                    (net, tcp, bad, why, enq))

                drain_m2 = (drain_m | (ring_lane & (enq > 0))) & ~bad
                # a popped NIC_SEND clears its pending flag at entry
                # (handle_nic_send, nic.py:464)
                net = net.replace(
                    nic_send_pending=net.nic_send_pending & ~is_ns)
                d_active = jnp.zeros((H,), I32)
                d_data = jnp.zeros((H,), I64)
                d_retxb = jnp.zeros((H,), I64)
                d_nosock = jnp.zeros((H,), I32)
                drawn = jnp.zeros((H,), I32)

                def _drain_sec(ops):
                    (net, tcp, out, bad, why, last_drop, drops, tx_wl,
                     emitted, ob_over, d_active, d_data, d_retxb,
                     d_nosock, drawn) = ops
                    big64 = jnp.iinfo(net.out_priority.dtype).max
                    for _k in range(cfg.nic_drain):
                        can = (net.tb_send_tokens - tx_wl) >= pf.MTU
                        nonempty = net.out_count > 0            # [H,S]
                        hp_all = net.out_head % BO
                        head_pri = jnp.take_along_axis(
                            net.out_priority, hp_all[..., None],
                            axis=2)[..., 0]
                        key = jnp.where(nonempty, head_pri, big64)
                        sel = jnp.argmin(key, axis=1).astype(I32)
                        found = jnp.any(nonempty, axis=1)
                        active = drain_m2 & can & found & ~bad
                        hp = net.out_head[rows, sel] % BO
                        wds = net.out_words[rows, sel, hp]      # [H,W]
                        lenk = wds[:, pf.W_LEN]
                        net = net.replace(
                            out_head=set_hs(net.out_head, active, sel,
                                            (net.out_head[rows, sel] + 1)
                                            % BO),
                            out_count=set_hs(net.out_count, active, sel,
                                             net.out_count[rows, sel] - 1),
                            out_bytes=set_hs(net.out_bytes, active, sel,
                                             net.out_bytes[rows, sel]
                                             - lenk),
                        )
                        # wire-time stamps (stamp_at_wire parity)
                        # the REAL serial wire-time stampers — one
                        # formula, zero drift (sack_advert rationale)
                        from shadow_tpu.net.tcp import (
                            stamp_at_wire, wire_ack_departed)

                        wds = stamp_at_wire(net, tcp, active, sel, wds, t)
                        wds = wds.at[:, pf.W_STATUS].set(jnp.where(
                            active,
                            wds[:, pf.W_STATUS]
                            | pf.PDS_SND_INTERFACE_SENT,
                            wds[:, pf.W_STATUS]))
                        # the departing ACK cancels the delayed ACK
                        tcp = wire_ack_departed(tcp, active, sel)
                        wlk = pf.wire_length(pf.proto_of(wds),
                                             lenk).astype(I64)
                        dipk = wds[:, pf.W_DSTIP].astype(
                            jnp.uint32).astype(I64)
                        dsth = host_of_ip(net, dipk)
                        bad, why = _flag(bad, why,
                                         active & (dsth == lane), 1 << 38)
                        active = active & ~bad
                        known = active & (dsth >= 0)
                        d_nosock = d_nosock + (active & ~known).astype(I32)
                        if not rel_dead:
                            u = rng.uniform_at(
                                net.rng_keys,
                                rngc + jnp.asarray(drawn, jnp.uint32))
                        drawn = drawn + active.astype(I32)
                        vdst_k = net.vertex_of_host[
                            jnp.clip(dsth, 0, GH - 1)]
                        vsrc_k = net.vertex_of_host[lane]
                        latk = net.latency_ns[vsrc_k, vdst_k]
                        if rel_dead:
                            dropk = jnp.zeros_like(known)
                        else:
                            relk = net.reliability[vsrc_k, vdst_k]
                            dropk = known & (lenk > 0) & (u > relk)
                        sendk = known & ~dropk
                        wire_sent = wds.at[:, pf.W_STATUS].set(
                            wds[:, pf.W_STATUS] | pf.PDS_INET_SENT)
                        last_drop = jnp.where(
                            dropk,
                            wds[:, pf.W_STATUS] | pf.PDS_INET_DROPPED,
                            last_drop)
                        drops = drops + dropk.astype(I32)
                        tx_wl = tx_wl + jnp.where(active, wlk, 0)
                        col = ob_count + emitted
                        okb = sendk & (col < M)
                        ob_over = ob_over | (sendk & ~(col < M))
                        colc = jnp.clip(col, 0, M - 1)
                        out = out.replace(
                            dst=out.dst.at[rows, colc].set(
                                jnp.where(okb, dsth,
                                          out.dst[rows, colc])),
                            time=out.time.at[rows, colc].set(
                                jnp.where(okb, t + latk,
                                          out.time[rows, colc])),
                            kind=out.kind.at[rows, colc].set(
                                jnp.where(okb, EventKind.PACKET,
                                          out.kind[rows, colc])),
                            src=out.src.at[rows, colc].set(
                                jnp.where(okb, lane,
                                          out.src[rows, colc])),
                            seq=out.seq.at[rows, colc].set(
                                jnp.where(okb, seq_ctr + emitted,
                                          out.seq[rows, colc])),
                            words=out.words.at[rows, colc].set(
                                jnp.where(okb[:, None], wire_sent,
                                          out.words[rows, colc])),
                        )
                        emitted = emitted + sendk.astype(I32)
                        is_rexk = (wds[:, pf.W_STATUS]
                                   & pf.PDS_SND_TCP_RETRANSMITTED) != 0
                        d_active = d_active + active.astype(I32)
                        d_data = d_data + jnp.where(active, lenk,
                                                    0).astype(I64)
                        d_retxb = d_retxb + jnp.where(
                            active & is_rexk, wlk, 0)
                    return (net, tcp, out, bad, why, last_drop, drops,
                            tx_wl, emitted, ob_over, d_active, d_data,
                            d_retxb, d_nosock, drawn)

                (net, tcp, out, bad, why, last_drop, drops, tx_wl,
                 emitted, ob_over, d_active, d_data, d_retxb, d_nosock,
                 drawn) = _gate(
                    jnp.any(drain_m2), _drain_sec,
                    (net, tcp, out, bad, why, last_drop, drops, tx_wl,
                     emitted, ob_over, d_active, d_data, d_retxb,
                     d_nosock, drawn))

                bad, why = _flag(bad, why, ob_over, 1073741824)
                fast_w = (fast_s | fin2f) & ~bad
                ring_w_lanes = (ring_lane | drain_m2) & ~bad
                wired_any = fast_w | ring_w_lanes
                out = out.replace(count=jnp.where(wired_any,
                                                  ob_count + emitted,
                                                  out.count))
                seq_ctr = seq_ctr + jnp.where(wired_any, emitted, 0)
                n_tot_f = jnp.where(fast_w,
                                    n_pkt + fin2f.astype(I32), 0)
                net = net.replace(
                    out_head=set_hs(net.out_head, fast_s & ~bad, wslot,
                                    (ring_head0 + n_pkt) % BO),
                    priority_ctr=net.priority_ctr
                    + n_tot_f.astype(I64)
                    + jnp.where(ring_lane & ~bad, enq, 0).astype(I64),
                    rng_ctr=rngc
                    + jnp.where(fast_w, n_tot_f, 0).astype(jnp.uint32)
                    + jnp.where(ring_w_lanes, drawn, 0).astype(
                        jnp.uint32),
                    tb_send_tokens=jnp.maximum(
                        net.tb_send_tokens
                        - jnp.where(wired_any, tx_wl, 0), 0),
                    ctr_tx_packets=net.ctr_tx_packets
                    + n_tot_f.astype(I64)
                    + jnp.where(ring_w_lanes, d_active, 0).astype(I64),
                    ctr_tx_bytes=net.ctr_tx_bytes
                    + jnp.where(wired_any, tx_wl, 0),
                    ctr_tx_data_bytes=net.ctr_tx_data_bytes
                    + jnp.where(fast_s & ~bad, A_now + rt_len,
                                0).astype(I64)
                    + jnp.where(ring_w_lanes, d_data, 0),
                    ctr_tx_retx_bytes=net.ctr_tx_retx_bytes
                    + jnp.where(fast_w & retx_sent,
                                pf.wire_length(
                                    jnp.full((H,), pf.PROTO_TCP, I32),
                                    rt_len).astype(I64), 0)
                    + jnp.where(ring_w_lanes, d_retxb, 0),
                    ctr_drop_nosocket=net.ctr_drop_nosocket
                    + jnp.where(ring_w_lanes, d_nosock, 0).astype(I64),
                    ctr_drop_reliability=net.ctr_drop_reliability
                    + drops.astype(I64),
                    last_drop_status=last_drop,
                    ctr_events_exec=net.ctr_events_exec + v.astype(I64),
                )
                net = net.replace(out_head=set_hs(
                    net.out_head, fin2f & ~bad, c2_slot,
                    (gather_hs(net.out_head, c2_slot) + 1) % BO))

                # chain / wait continuation (handle_nic_send tail,
                # nic.py:478-489) — emitted AFTER the drained packets,
                # matching the serial per-micro-step emission order
                def _chain_ns(ops):
                    net, q, seq_ctr, bad, why = ops
                    more = jnp.any(net.out_count > 0, axis=1)
                    can_next = net.tb_send_tokens >= pf.MTU
                    base = drain_m2 & ~bad & ~net.nic_send_pending
                    ch_now = base & more & can_next
                    ch_wait = base & more & ~can_next
                    free_n = jnp.any(q.time == simtime.INVALID, axis=1)
                    bad, why = _flag(bad, why,
                                     (ch_now | ch_wait) & ~free_n, 1 << 35)
                    ch_now = ch_now & ~bad
                    ch_wait = ch_wait & ~bad
                    zw = jnp.zeros((H, W), I32)
                    q = _push_local(q, ch_now, t, EventKind.NIC_SEND, zw,
                                    lane, seq_ctr)
                    seq_ctr = seq_ctr + ch_now.astype(I32)
                    from shadow_tpu.net.nic import next_refill_time

                    q = _push_local(q, ch_wait, next_refill_time(t),
                                    EventKind.NIC_SEND, zw, lane, seq_ctr)
                    seq_ctr = seq_ctr + ch_wait.astype(I32)
                    net = net.replace(
                        nic_send_pending=net.nic_send_pending | ch_now
                        | ch_wait)
                    return net, q, seq_ctr, bad, why

                net, q, seq_ctr, bad, why = _gate(
                    jnp.any(drain_m2), _chain_ns,
                    (net, q, seq_ctr, bad, why))

                sim = sim.replace(events=q, outbox=out, net=net, tcp=tcp,
                                  app=app)

                # ---- prefix-commit revert -----------------------------
                # lanes that hit an out-of-model boundary THIS iteration
                # roll their rows back to the iteration-start snapshot:
                # the offending event stays queued for the serial
                # fixpoint and everything before it stays committed.
                # Unmutated leaves are identical tracers (functional
                # updates), so the tree-map only selects on arrays the
                # body actually wrote (~a few MB), and only on
                # iterations where some lane stopped.
                stopped_now = bad & ~bad_prev
                # select only the leaves this iteration actually wrote
                # (unmutated leaves are the SAME tracer, `is`-testable
                # outside any cond) so the gated revert never touches
                # the big dead planes (out_words etc.)
                prev_leaves, treedef = jax.tree_util.tree_flatten(
                    (sim_prev, seq_prev))
                new_leaves, _ = jax.tree_util.tree_flatten((sim, seq_ctr))
                idx = [i for i, (a, b) in enumerate(
                    zip(prev_leaves, new_leaves))
                    if a is not b and b.ndim >= 1 and b.shape[0] == H]

                def _revert(pairs):
                    return tuple(
                        jnp.where(stopped_now.reshape(
                            (H,) + (1,) * (b.ndim - 1)), a, b)
                        for a, b in pairs)

                reverted = jax.lax.cond(
                    jnp.any(stopped_now), _revert,
                    lambda pairs: tuple(b for _, b in pairs),
                    tuple((prev_leaves[i], new_leaves[i]) for i in idx))
                for i, vnew in zip(idx, reverted):
                    new_leaves[i] = vnew
                sim, seq_ctr = jax.tree_util.tree_unflatten(
                    treedef, new_leaves)
                return _Carry(sim, bad, why, seq_ctr, it + 1)

            init = _Carry(sim, ~elig, why0,
                          q0.next_seq, jnp.zeros((), I32))
            final = jax.lax.while_loop(cond, body, init)
            sim_c, bad, why = final.sim, final.bad, final.why
            # prefix-commit: EVERY eligible lane merges its candidate
            # state — a stopped lane's rows hold the clean prefix with
            # the out-of-model event (and any later ones) still queued,
            # and the serial fixpoint continues from exactly there. The
            # debug `commit` mask reports lanes whose WHOLE window
            # stayed in model (leftovers = guard trip, counted bad).
            bad, why = _flag(bad, why,
                             jnp.any(sim_c.events.time < wend64, axis=1),
                             2147483648)
            commit = elig

            # ---- merge candidate state for committed hosts ----------------
            def merge(orig, cand):
                def m(a, b):
                    # global scalars (overflow) and replicated lookup
                    # tables ([V,V] latency etc.) are never touched by the
                    # scan — pass them through rather than broadcasting the
                    # per-host commit mask over a non-host leading dim
                    if a.ndim == 0 or a.shape[0] != H:
                        return a
                    cm = commit.reshape((H,) + (1,) * (a.ndim - 1))
                    return jnp.where(cm, b, a)

                return jax.tree_util.tree_map(m, orig, cand)

            q_m = merge(sim.events, sim_c.events)
            q_m = q_m.replace(next_seq=jnp.where(commit, final.seq_ctr,
                                                 sim.events.next_seq))
            out_m = merge(sim.outbox, sim_c.outbox)
            net_m = merge(sim.net, sim_c.net)
            tcp_m = merge(sim.tcp, sim_c.tcp)
            app_m = merge(sim.app, sim_c.app)
            n = jnp.sum(jnp.where(
                commit,
                sim_c.net.ctr_events_exec - sim.net.ctr_events_exec, 0),
                dtype=I64)
            sim = sim.replace(events=q_m, outbox=out_m, net=net_m, tcp=tcp_m,
                              app=app_m)
            return sim, n, bad, why, elig & ~bad, final.it

        def _skip_pass(sim):
            return (sim, jnp.zeros((), I64), ~elig, why0,
                    jnp.zeros((H,), bool), jnp.zeros((), I32))

        # a window with NO eligible host skips the whole pass —
        # prep (the ip->host lookup), the scan, and above all the
        # commit merge (a full state copy) cost nothing on sparse
        # or loss-dominated windows (the real-topology regime:
        # 5 ms min-jump => 200 windows per sim-second)
        sim, n, bad, why, commit, iters = jax.lax.cond(
            jnp.any(elig), _whole_pass, _skip_pass, sim)
        if debug:
            return sim, n, {"elig": elig, "bad": bad, "why": why,
                            "commit": commit, "iters": iters}
        return sim, n

    return bulk_fn
