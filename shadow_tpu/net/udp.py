"""UDP datagram socket semantics (ref: descriptor/udp.c).

Send wraps app data into packets of at most CONFIG_DATAGRAM_MAX_SIZE
(ref: udp.c send path, definitions.h:193) queued on the socket's
output ring for the NIC; receive buffers packets in arrival order in
the input ring, dropping when the receive buffer is full, and raises
the READABLE status (ref: udp.c:53-…, descriptor_adjustStatus)."""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core.events import NWORDS
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net.rings import (
    gather_hs,
    ring_advance_pop,
    ring_advance_push,
    ring_push_at,
    ring_peek_at,
    set_hs,
    set_ring,
)
from shadow_tpu.net.sockets import sk_enqueue_out
from shadow_tpu.net.state import NetState, SocketFlags

I32 = jnp.int32
DATAGRAM_MAX = 65507  # ref: definitions.h:193


def udp_enqueue_send(net: NetState, mask, slot, dst_ip, dst_port, length, payref):
    """Queue one datagram on (lane, slot)'s output ring. Returns
    (net, ok[H]) — ok False when the send buffer lacks space, the
    app-visible EWOULDBLOCK condition (ref: socket buffer accounting,
    socket.h:47-78)."""
    H = mask.shape[0]
    length = jnp.asarray(length, I32)
    src_port = gather_hs(net.sk_bound_port, slot)
    words = jnp.zeros((H, NWORDS), I32)
    words = words.at[:, pf.W_PROTO].set(pf.PROTO_UDP)
    words = words.at[:, pf.W_LEN].set(jnp.broadcast_to(length, (H,)))
    words = words.at[:, pf.W_PORTS].set(
        pf.pack_ports(src_port, jnp.asarray(dst_port, I32)))
    words = words.at[:, pf.W_PAYREF].set(
        jnp.broadcast_to(jnp.asarray(payref, I32), (H,)))
    words = words.at[:, pf.W_DSTIP].set(
        jnp.broadcast_to(
            jnp.asarray(dst_ip).astype(jnp.uint32).astype(I32), (H,)))
    words = words.at[:, pf.W_STATUS].set(
        pf.PDS_SND_CREATED | pf.PDS_SND_SOCKET_BUFFERED)
    return sk_enqueue_out(net, mask, slot, words)


def udp_deliver(net: NetState, mask, slot, src_ip, src_port, length, payref,
                status=None):
    """Push one received datagram into (lane, slot)'s input ring; drop
    (counted) when the receive buffer is full. Returns net. `status`
    is the packet's delivery-status trail word (audit, packet.h:18-40);
    buffered packets keep their trail in in_status."""
    H = mask.shape[0]
    length = jnp.asarray(length, I32)
    BI = net.in_src_ip.shape[2]
    if status is None:
        status = jnp.zeros((H,), I32)

    space_ok = (gather_hs(net.in_bytes, slot) + length) <= gather_hs(
        net.sk_rcvbuf, slot
    )
    ok, pos = ring_push_at(net.in_head, net.in_count, BI, mask & space_ok, slot)
    net = net.replace(
        in_src_ip=set_ring(net.in_src_ip, ok, slot, pos,
                           jnp.asarray(src_ip, net.in_src_ip.dtype)),
        in_src_port=set_ring(net.in_src_port, ok, slot, pos,
                             jnp.asarray(src_port, I32)),
        in_len=set_ring(net.in_len, ok, slot, pos, length),
        in_payref=set_ring(net.in_payref, ok, slot, pos,
                           jnp.asarray(payref, I32)),
        in_status=set_ring(net.in_status, ok, slot, pos,
                           status | pf.PDS_RCV_SOCKET_BUFFERED),
    )
    _, count = ring_advance_push(net.in_head, net.in_count, mask, slot, ok)
    net = net.replace(in_count=count)
    ib = gather_hs(net.in_bytes, slot)
    net = net.replace(in_bytes=set_hs(net.in_bytes, ok, slot, ib + length))
    # readable on data arrival (ref: descriptor_adjustStatus READABLE);
    # every arrival is an edge for ET epoll, even when already readable
    flags = gather_hs(net.sk_flags, slot)
    net = net.replace(
        sk_flags=set_hs(net.sk_flags, ok, slot, flags | SocketFlags.READABLE),
        sk_in_gen=set_hs(net.sk_in_gen, ok, slot,
                         gather_hs(net.sk_in_gen, slot) + 1),
    )
    dropped = mask & ~space_ok
    net = net.replace(
        ctr_drop_bufferfull=net.ctr_drop_bufferfull + dropped.astype(jnp.int64),
        last_drop_status=jnp.where(
            dropped, status | pf.PDS_RCV_SOCKET_DROPPED,
            net.last_drop_status),
    )
    return net


def udp_recv(net: NetState, mask, slot):
    """Pop one datagram per masked lane. Returns
    (net, got[H], src_ip, src_port, length, payref)."""
    H = mask.shape[0]
    lane = jnp.arange(H)
    BI = net.in_src_ip.shape[2]
    got, pos = ring_peek_at(net.in_head, net.in_count, mask, slot, BI)
    s = jnp.clip(slot, 0, net.in_src_ip.shape[1] - 1)
    posc = jnp.clip(pos, 0, BI - 1)
    src_ip = net.in_src_ip[lane, s, posc]
    src_port = net.in_src_port[lane, s, posc]
    length = jnp.where(got, net.in_len[lane, s, posc], 0)
    payref = net.in_payref[lane, s, posc]
    head, count = ring_advance_pop(net.in_head, net.in_count, got, slot, BI)
    net = net.replace(in_head=head, in_count=count)
    ib = gather_hs(net.in_bytes, slot)
    net = net.replace(in_bytes=set_hs(net.in_bytes, got, slot, ib - length))
    # clear READABLE when drained
    empty = gather_hs(net.in_count, slot) == 0
    flags = gather_hs(net.sk_flags, slot)
    net = net.replace(
        sk_flags=set_hs(net.sk_flags, got & empty, slot,
                        flags & ~SocketFlags.READABLE)
    )
    return net, got, src_ip, src_port, length, payref
