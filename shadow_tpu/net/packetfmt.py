"""Packet layout inside event payload words.

The reference's Packet object (ref: packet.c:22-37, packet.h:66-86)
carries protocol headers and a refcounted payload; on device a packet
in flight is just the event's NWORDS int32 words. Payload bytes are
never on device — `W_PAYREF` indexes the host-side payload pool
(mirrors Payload sharing, ref: payload.c:17-30); synthetic traffic
uses PAYREF_NONE and only lengths are modeled.

The event's `src` field is the source *host index*; source IP is
derived via the host IP table when needed.
"""

import jax.numpy as jnp

# word indices. Protocol-independent words come FIRST so UDP-only
# configs can carry narrow events (events.NWORDS_BASE = 6 words)
# instead of the full TCP-header width (events.NWORDS = 17) — the
# window cost is linear in bytes moved, so dead header words divide
# throughput directly. Code touching an index >= NWORDS_BASE must be
# gated on cfg.tcp (a static out-of-range index fails at trace time,
# never silently).
W_PROTO = 0    # protocol | tcp-flags<<8  (see below)
W_LEN = 1      # payload length in bytes
W_PORTS = 2    # src_port | dst_port<<16
W_PAYREF = 3   # host-side payload pool index, PAYREF_NONE = synthetic
W_DSTIP = 4    # destination IP (distinguishes loopback vs eth delivery)
# Delivery-status audit trail: a bitmask ORed at every pipeline stage
# the packet passes (the device form of the reference's append-only
# PacketDeliveryStatusFlags trail, packet.h:18-40 /
# packet_addDeliveryStatus). Decode host-side with pds_decode().
W_STATUS = 5
# --- TCP header words (indices >= events.NWORDS_BASE) ----------------
W_SEQ = 6      # TCP sequence number
W_ACK = 7      # TCP acknowledgment
W_WIN = 8      # TCP advertised window
W_TSVAL = 9    # TCP timestamp value (ms)
W_TSECHO = 10  # TCP timestamp echo (ms)
W_SACKL = 11   # TCP selective-ack range 1 left edge
W_SACKR = 12   # TCP selective-ack range 1 right edge
# full SACK list: ranges 2 and 3 (the reference carries a full
# selective-ack list in its TCP header, packet.h:52,77; three ranges
# cover Linux's practical SACK option limit)
W_SACKL2 = 13
W_SACKR2 = 14
W_SACKL3 = 15
W_SACKR3 = 16

PAYREF_NONE = -1

# --- delivery-status bits (ref: packet.h:18-40 PDS_* enum) -----------
PDS_SND_CREATED = 1 << 0
PDS_SND_TCP_ENQUEUE_THROTTLED = 1 << 1
PDS_SND_TCP_ENQUEUE_RETRANSMIT = 1 << 2
PDS_SND_TCP_DEQUEUE_RETRANSMIT = 1 << 3
PDS_SND_TCP_RETRANSMITTED = 1 << 4
PDS_SND_SOCKET_BUFFERED = 1 << 5
PDS_SND_INTERFACE_SENT = 1 << 6
PDS_INET_SENT = 1 << 7
PDS_INET_DROPPED = 1 << 8          # reliability (path loss) drop
PDS_ROUTER_ENQUEUED = 1 << 9
PDS_ROUTER_DEQUEUED = 1 << 10
PDS_ROUTER_DROPPED = 1 << 11       # CoDel AQM drop
PDS_RCV_INTERFACE_RECEIVED = 1 << 12
PDS_RCV_INTERFACE_DROPPED = 1 << 13
PDS_RCV_SOCKET_PROCESSED = 1 << 14
PDS_RCV_SOCKET_DROPPED = 1 << 15   # no bound socket / rcvbuf full
PDS_RCV_SOCKET_BUFFERED = 1 << 16
PDS_RCV_SOCKET_DELIVERED = 1 << 17

PDS_NAMES = {
    PDS_SND_CREATED: "SND_CREATED",
    PDS_SND_TCP_ENQUEUE_THROTTLED: "SND_TCP_ENQUEUE_THROTTLED",
    PDS_SND_TCP_ENQUEUE_RETRANSMIT: "SND_TCP_ENQUEUE_RETRANSMIT",
    PDS_SND_TCP_DEQUEUE_RETRANSMIT: "SND_TCP_DEQUEUE_RETRANSMIT",
    PDS_SND_TCP_RETRANSMITTED: "SND_TCP_RETRANSMITTED",
    PDS_SND_SOCKET_BUFFERED: "SND_SOCKET_BUFFERED",
    PDS_SND_INTERFACE_SENT: "SND_INTERFACE_SENT",
    PDS_INET_SENT: "INET_SENT",
    PDS_INET_DROPPED: "INET_DROPPED",
    PDS_ROUTER_ENQUEUED: "ROUTER_ENQUEUED",
    PDS_ROUTER_DEQUEUED: "ROUTER_DEQUEUED",
    PDS_ROUTER_DROPPED: "ROUTER_DROPPED",
    PDS_RCV_INTERFACE_RECEIVED: "RCV_INTERFACE_RECEIVED",
    PDS_RCV_INTERFACE_DROPPED: "RCV_INTERFACE_DROPPED",
    PDS_RCV_SOCKET_PROCESSED: "RCV_SOCKET_PROCESSED",
    PDS_RCV_SOCKET_DROPPED: "RCV_SOCKET_DROPPED",
    PDS_RCV_SOCKET_BUFFERED: "RCV_SOCKET_BUFFERED",
    PDS_RCV_SOCKET_DELIVERED: "RCV_SOCKET_DELIVERED",
}


def pds_decode(status: int) -> list:
    """Host-side decoder: status word -> ordered stage names (the
    analog of packet_toString's trail dump)."""
    return [name for bit, name in sorted(PDS_NAMES.items())
            if status & bit]

# protocols (ref: packet.h protocol enum {LOCAL, UDP, TCP})
PROTO_LOCAL = 0
PROTO_UDP = 1
PROTO_TCP = 2

# TCP header flags, stored shifted by 8 in W_PROTO
TCPF_SYN = 1
TCPF_ACK = 2
TCPF_FIN = 4
TCPF_RST = 8

# Header sizes added to payload length for bandwidth accounting
# (ref: definitions.h:176-183).
HDR_UDP = 42
HDR_TCP = 66
MTU = 1500  # ref: definitions.h:188


def proto_of(words):
    return words[:, W_PROTO] & 0xFF


def tcp_flags_of(words):
    return (words[:, W_PROTO] >> 8) & 0xFF


def pack_proto(proto, flags=0):
    return proto | (flags << 8)


def ports_of(words):
    w = words[:, W_PORTS]
    return w & 0xFFFF, (w >> 16) & 0xFFFF


def pack_ports(src_port, dst_port):
    return (src_port & 0xFFFF) | ((dst_port & 0xFFFF) << 16)


def wire_length(proto, payload_len):
    """Total on-wire bytes used for token-bucket accounting
    (ref: network_interface.c:443,545: payload + header size)."""
    hdr = jnp.where(proto == PROTO_TCP, HDR_TCP, HDR_UDP)
    return payload_len + hdr
