"""Masked per-lane ring-buffer helpers for [H,S,B] socket rings and
[H,R] router rings. Each micro-step touches at most one (host, slot)
per lane, so operations are [H]-vectorized scatters/gathers with
invalid lanes dropped via out-of-bounds indices."""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core.events import _onehot, _put

I32 = jnp.int32


def gather_hs(arr, slot):
    """arr[H,S] -> [H] value at (lane, slot); slot clipped for safety
    (callers mask invalid lanes)."""
    H = arr.shape[0]
    lane = jnp.arange(H)
    return arr[lane, jnp.clip(slot, 0, arr.shape[1] - 1)]


def set_hs(arr, mask, slot, value):
    """arr[H,S] masked write at (lane, slot). One-hot select, not a
    scatter: S is small, and XLA fuses selects where per-element
    scatters would each become a separate (slow-to-compile,
    slow-to-run) scatter op (shared core.events._onehot/_put)."""
    return _put(arr, _onehot(mask, slot, arr.shape[1]), value)


def set_ring(arr, mask, slot, pos, value):
    """arr[H,S,B] (or [H,S,B,W] with value [H,W]) masked write at
    (lane, slot, pos) via one-hot select — same rationale as set_hs:
    selects fuse, scatters don't."""
    H, S, B = arr.shape[:3]
    sel = (mask[:, None, None]
           & (jnp.arange(S)[None, :, None] == slot[:, None, None])
           & (jnp.arange(B)[None, None, :] == pos[:, None, None]))
    value = jnp.asarray(value, arr.dtype)
    if arr.ndim == 4:
        return jnp.where(sel[..., None], value[:, None, None, :], arr)
    v = value[:, None, None] if value.ndim == 1 else value
    return jnp.where(sel, v, arr)


def set_row(arr, mask, pos, value):
    """arr[H,R] (or [H,R,W] with value [H,W]) masked write at
    (lane, pos) via one-hot select."""
    return _put(arr, _onehot(mask, pos, arr.shape[1]), value)


def ring_push_at(head, count, capacity: int, mask, slot):
    """Compute the write position for pushing one element into ring
    (lane, slot). Returns (ok[H], pos[H]) with pos=capacity for
    dropped lanes (use mode='drop' scatters at [lane, slot, pos])."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c < capacity)
    pos = jnp.where(ok, (h + c) % capacity, capacity)
    return ok, pos


def ring_advance_push(head, count, mask, slot, ok):
    """Commit a push: count += 1 where ok."""
    c = gather_hs(count, slot)
    return head, set_hs(count, mask & ok, slot, c + 1)


def ring_peek_at(head, count, mask, slot, capacity: int):
    """Position of the ring head element; pos=capacity when empty or
    masked out."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c > 0)
    return ok, jnp.where(ok, h % capacity, capacity)


def ring_advance_pop(head, count, mask, slot, capacity: int):
    """Commit a pop: head = (head+1)%capacity, count -= 1."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c > 0)
    head = set_hs(head, ok, slot, (h + 1) % capacity)
    count = set_hs(count, ok, slot, c - 1)
    return head, count
