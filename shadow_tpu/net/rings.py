"""Masked per-lane ring-buffer helpers for [H,S,B] socket rings and
[H,R] router rings. Each micro-step touches at most one (host, slot)
per lane, so operations are [H]-vectorized scatters/gathers with
invalid lanes dropped via out-of-bounds indices."""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32


def gather_hs(arr, slot):
    """arr[H,S] -> [H] value at (lane, slot); slot clipped for safety
    (callers mask invalid lanes)."""
    H = arr.shape[0]
    lane = jnp.arange(H)
    return arr[lane, jnp.clip(slot, 0, arr.shape[1] - 1)]


def set_hs(arr, mask, slot, value):
    """arr[H,S] masked scatter at (lane, slot)."""
    H, S = arr.shape[:2]
    lane = jnp.arange(H)
    s = jnp.where(mask, slot, S)  # OOB -> drop
    return arr.at[lane, s].set(value, mode="drop")


def ring_push_at(head, count, capacity: int, mask, slot):
    """Compute the write position for pushing one element into ring
    (lane, slot). Returns (ok[H], pos[H]) with pos=capacity for
    dropped lanes (use mode='drop' scatters at [lane, slot, pos])."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c < capacity)
    pos = jnp.where(ok, (h + c) % capacity, capacity)
    return ok, pos


def ring_advance_push(head, count, mask, slot, ok):
    """Commit a push: count += 1 where ok."""
    c = gather_hs(count, slot)
    return head, set_hs(count, mask & ok, slot, c + 1)


def ring_peek_at(head, count, mask, slot, capacity: int):
    """Position of the ring head element; pos=capacity when empty or
    masked out."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c > 0)
    return ok, jnp.where(ok, h % capacity, capacity)


def ring_advance_pop(head, count, mask, slot, capacity: int):
    """Commit a pop: head = (head+1)%capacity, count -= 1."""
    c = gather_hs(count, slot)
    h = gather_hs(head, slot)
    ok = mask & (c > 0)
    head = set_hs(head, ok, slot, (h + 1) % capacity)
    count = set_hs(count, ok, slot, c - 1)
    return head, count
