"""timerfd-style timers (ref: descriptor/timer.c).

Each host owns T timer slots with absolute next-expiry + interval.
Setting a timer bumps a generation counter and schedules a TIMER event
carrying (slot, generation); stale events from earlier settings are
ignored on fire — the reference's expireID invalidation
(timer.c:23-42,201-…). Periodic timers reschedule themselves.

Apps observe expirations via tm_expirations (timerfd read semantics)
and may also register their own handler for EventKind.TIMER.
"""

from __future__ import annotations

import jax.numpy as jnp

from shadow_tpu.core import simtime
from shadow_tpu.core.events import EventKind, emit
from shadow_tpu.core.events import NWORDS
from shadow_tpu.net.rings import gather_hs, set_hs
from shadow_tpu.net.state import NetConfig, NetState

I32 = jnp.int32

# timer event words
TW_SLOT = 0
TW_GEN = 1


def timer_set(sim, buf, mask, slot, expire_time, interval=0):
    """Arm timer `slot` per masked lane to fire at expire_time (abs),
    then every `interval` ns if nonzero. Returns (sim, buf)."""
    net = sim.net
    H = net.tm_expire.shape[0]
    gen = gather_hs(net.tm_gen, slot) + 1
    net = net.replace(
        tm_expire=set_hs(net.tm_expire, mask, slot,
                         jnp.asarray(expire_time, simtime.DTYPE)),
        tm_interval=set_hs(net.tm_interval, mask, slot,
                           jnp.asarray(interval, simtime.DTYPE)),
        tm_gen=set_hs(net.tm_gen, mask, slot, gen),
    )
    words = jnp.zeros((H, NWORDS), I32)
    words = words.at[:, TW_SLOT].set(jnp.asarray(slot, I32))
    words = words.at[:, TW_GEN].set(gen)
    buf = emit(buf, mask, net.lane_id,
               jnp.asarray(expire_time, simtime.DTYPE), EventKind.TIMER, words)
    return sim.replace(net=net), buf


def timer_disarm(sim, mask, slot):
    """Disarm: bump generation so in-flight events become stale."""
    net = sim.net
    gen = gather_hs(net.tm_gen, slot) + 1
    net = net.replace(
        tm_expire=set_hs(net.tm_expire, mask, slot, simtime.INVALID),
        tm_gen=set_hs(net.tm_gen, mask, slot, gen),
    )
    return sim.replace(net=net)


def timer_read(sim, mask, slot):
    """timerfd read(): returns expirations since last read and clears
    the count. Returns (sim, count[H])."""
    net = sim.net
    n = gather_hs(net.tm_expirations, slot)
    n = jnp.where(mask, n, 0)
    net = net.replace(
        tm_expirations=set_hs(net.tm_expirations, mask, slot,
                              jnp.zeros_like(n)))
    return sim.replace(net=net), n


def handle_timer(cfg: NetConfig, sim, popped, buf):
    """kind=TIMER: count the expiration if the generation is current;
    reschedule periodic timers."""
    net = sim.net
    H = net.tm_expire.shape[0]
    mask = popped.valid & (popped.kind == EventKind.TIMER)
    slot = popped.words[:, TW_SLOT]
    gen = popped.words[:, TW_GEN]
    live = mask & (gather_hs(net.tm_gen, slot) == gen)

    exp = gather_hs(net.tm_expirations, slot)
    net = net.replace(
        tm_expirations=set_hs(net.tm_expirations, live, slot, exp + 1)
    )
    interval = gather_hs(net.tm_interval, slot)
    periodic = live & (interval > 0)
    nxt = popped.time + interval
    net = net.replace(
        tm_expire=set_hs(
            net.tm_expire, live, slot,
            jnp.where(periodic, nxt, simtime.INVALID),
        )
    )
    buf = emit(buf, periodic, net.lane_id, nxt,
               EventKind.TIMER, popped.words)
    return sim.replace(net=net), buf
