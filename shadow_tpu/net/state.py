"""Struct-of-arrays state for the virtual host network stack.

One NetState holds *all* hosts' kernel state as [H]- and [H,S]-shaped
device arrays (the reference's per-host heap objects — Host,
NetworkInterface, Router, Socket — ref: host.c:47-105,
network_interface.c, socket.h:47-78 — become rows). Sockets are laid
out [H, S] so "this host's sockets" is a row and qdisc selection is a
vectorized row scan.

Payload bytes are never device-resident; packets carry lengths and a
host-side pool reference (ref: payload.c).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.events import NWORDS, NWORDS_BASE, EventQueue, Outbox

I32 = jnp.int32
I64 = jnp.int64


class SocketType:
    NONE = 0
    UDP = 1
    TCP = 2
    # intra-host conduits (pipe/socketpair, ref: channel.c) are modeled
    # as socket pairs with local-only delivery
    PIPE = 3


class SocketFlags:
    """Descriptor status bits (ref: descriptor.h:19-31)."""

    ACTIVE = 1
    READABLE = 2
    WRITABLE = 4
    CLOSED = 8


class QDisc:
    """Interface queuing discipline (ref: options.h:31-34)."""

    FIFO = 0
    RR = 1


class RouterQ:
    """Upstream-router queue manager (ref: QueueManagerHooks vtable,
    router.c; CoDel is the reference default, host.c:205)."""

    CODEL = 0    # RFC-8289 AQM (ref: router_queue_codel.c)
    SINGLE = 1   # one-packet queue (ref: router_queue_single.c)
    STATIC = 2   # drop-tail at ring capacity (ref: router_queue_static.c)


# token-bucket refill interval (ref: network_interface.c:93-95)
TB_REFILL_INTERVAL = simtime.ONE_MILLISECOND

# default socket buffer byte limits (ref: definitions.h:153-159);
# a config that pins a different value disables that direction's TCP
# buffer autotuning (ref: master.c:355-364)
DEFAULT_SNDBUF = 131072
DEFAULT_RCVBUF = 174760


@dataclass(frozen=True)
class NetConfig:
    """Static build-time configuration (shapes are compile-time)."""

    num_hosts: int
    # When every host's eth IP is base + host_index (the common case:
    # the DNS registry allocates sequentially unless configs pin
    # addresses), IP lookups in the bulk passes become arithmetic
    # instead of [H*K]-element gathers, which TPU serializes at ~7 ns
    # per element (three such gathers were 10.5 of 28 ms/window at
    # 10k-host PHOLD, measured r4). -1 = not affine; set by build().
    ip_affine_base: int = -1
    sockets_per_host: int = 4
    in_ring: int = 16            # per-socket input packet ring slots
    out_ring: int = 16           # per-socket output packet ring slots
    router_ring: int = 32       # per-host upstream router queue slots
    timers_per_host: int = 4
    event_capacity: int = 32
    outbox_capacity: int = 32
    # --- virtual CPU model (ref: cpu.c:56-110, event.c:71-89) --------
    # threshold < 0 disables the model entirely (the reference's
    # default, options.c:81-82). The reference charges each event the
    # plugin's MEASURED wall time x frequency ratio — nondeterministic
    # across machines; here the charge is a configured deterministic
    # per-event cost, scaled per host by cpu_raw_freq_khz /
    # host cpufrequency and rounded to cpu_precision_ns (half-up).
    cpu_threshold_ns: int = -1
    cpu_precision_ns: int = 200_000   # 200 us (ref: options.c:82)
    cpu_event_cost_ns: int = 30_000   # deterministic per-event charge
    cpu_raw_freq_khz: int = 3_000_000  # the "physical" CPU baseline
    qdisc: int = QDisc.FIFO
    router_qdisc: int = RouterQ.CODEL  # upstream router queue manager
    # pcap capture (ref: <host logpcap> + pcap_writer.c): when on, the
    # NIC appends every sent/delivered packet to a per-host capture
    # ring the host side drains into libpcap files each window
    pcap: bool = False
    pcap_ring: int = 64          # capture ring slots per host
    autotune: bool = True        # TCP buffer autotuning (ref:
                                 # CONFIG_TCPAUTOTUNE, definitions.h:101).
                                 # Pinning sndbuf/rcvbuf away from the
                                 # defaults disables that direction's
                                 # autotuning (make_net_state), matching
                                 # the reference's user-override rule
                                 # (master.c:355-364)
    tcp_cong: int = 0            # congestion algorithm (tcp_cong.NAMES:
                                 # reno/aimd/cubic — the reference's
                                 # --tcp-congestion-control knob backed
                                 # by the tcp_cong.h vtable design)
    # --tcp-ssthresh (ref: options.c:137): initial slow-start
    # threshold in packets; 0 = discover via loss (the default)
    tcp_ssthresh: int = 0
    # --tcp-windows (ref: options.c:138): pin the initial congestion
    # window; 0 = the reference's effective behavior (reno init
    # resets to 1, tcp_cong_reno.c:176-180)
    tcp_windows: int = 0
    tcp: bool = True             # False skips building TcpState and
                                 # inlining the TCP machine into the
                                 # device program (UDP-only workloads
                                 # compile much faster)
    # Per-path packet counters (ref: topology.c:2053-2063 per-Path
    # packetCount, logged at cache clear): a [V,V] matrix counting
    # remote send attempts per (src vertex, dst vertex). Off by
    # default: the hot-path scatter-add costs real time on TPU, and
    # the reference pays ~nothing for its CPU counter. Sharded runs
    # accumulate shard-local partials into the replicated matrix and
    # psum the deltas at each window barrier (parallel/shard.py
    # _replicate_scalars), so the harvested matrix equals the serial
    # one bit-for-bit.
    track_paths: bool = False
    # Active-lane budget S for the sparse-window fast path
    # (core/engine.py): windows whose global census of rows holding
    # any event < wend fits S run the fixpoint over a compacted
    # [S]-lane Sim. None = engine default (DEFAULT_SPARSE_LANES);
    # 0 disables; values >= num_hosts are treated as disabled.
    sparse_lanes: int | None = None
    bootstrap_end: int = 0       # "unlimited bandwidth" period end
                                 # (ref: master.c:261-268)
    end_time: int = simtime.ONE_SECOND
    min_jump: int = 10 * simtime.ONE_MILLISECOND
    # Windows per device dispatch for the host-driven loops
    # (checkpoint.run_windows, and --supervise through it): K window
    # rounds run inside one jitted fori_loop between host barriers
    # (engine.make_chunk_body), amortizing dispatch overhead when
    # windows are small. 1 = one dispatch per window (legacy loop).
    # engine.run — the whole-run megakernel — is unaffected.
    windows_per_dispatch: int = 1
    # Adaptive time jump for the chunked loops: derive each window's
    # span from the CURRENT latency/reliability tables (after fault
    # rewrites) instead of the static boot-time minimum — fault plans
    # that raise latencies let windows grow (engine.make_wend_fn).
    # Off by default: window boundaries shift, so runs are only
    # window-for-window comparable with it off.
    adaptive_jump: bool = False
    # Open-system injection staging lanes (shadow_tpu/inject/): a
    # bounded device-resident buffer of host->device injected events
    # merged into the EventQueue at every window boundary. Power of
    # two (slot = trace position % lanes); 0 = off (Sim.inject stays
    # None and programs are byte-identical to pre-injection builds).
    inject_lanes: int = 0
    seed: int = 1
    # Packets drained per micro-step by the NIC send pass (the device
    # form of the reference's drain-while-sendable loop,
    # network_interface.c:519-579, as a lax.fori_loop). 1 = a separate
    # micro-step per wire packet (pre-r2 behavior); bursts longer than
    # nic_drain chain a same-time NIC_SEND event.
    nic_drain: int = 4
    # Max emissions per host per micro-step. None = derived: the wire
    # packets one drain pass can emit plus headroom for the chain /
    # timer / app / (TCP: rtx + dack + flush) emissions that can
    # coincide. Overflow is counted, never silent.
    emit_capacity: int | None = None

    def __post_init__(self):
        if self.emit_capacity is None:
            object.__setattr__(
                self, "emit_capacity",
                self.nic_drain + (6 if self.tcp else 4))
        elif self.emit_capacity < self.nic_drain + 2:
            # one drain pass alone can emit nic_drain wire packets
            # plus a chain/wait event; a pinned emit_capacity below
            # that would overflow (counted, but on configs that never
            # overflowed before this knob existed) — fail loudly at
            # build instead
            raise ValueError(
                f"emit_capacity={self.emit_capacity} < nic_drain"
                f"={self.nic_drain} + 2: raise emit_capacity or lower "
                f"nic_drain")
    # default socket buffer byte limits (ref: definitions.h:153-159)
    sndbuf: int = DEFAULT_SNDBUF
    rcvbuf: int = DEFAULT_RCVBUF
    # Packet-word width carried by events/rings. None = derive:
    # full TCP-header width when cfg.tcp, else the narrow
    # protocol-independent prefix (see core.events.NWORDS_BASE).
    nwords: int | None = None

    @property
    def words_width(self) -> int:
        if self.nwords is not None:
            # the TCP machine reads/writes header words up to index
            # NWORDS-1; a narrower override would be silently sliced
            # by fit_words at enqueue and then fail opaquely at trace
            if self.tcp and self.nwords < NWORDS:
                raise ValueError(
                    f"nwords={self.nwords} < {NWORDS} requires tcp=False "
                    f"(TCP packets carry header words up to index "
                    f"{NWORDS - 1})")
            if self.nwords < NWORDS_BASE:
                raise ValueError(
                    f"nwords={self.nwords} < NWORDS_BASE={NWORDS_BASE}: "
                    f"every packet needs the protocol-independent words")
            return self.nwords
        return NWORDS if self.tcp else NWORDS_BASE


# NetState fields that are *global lookup tables*: replicated across
# shards (any host may address any other). Everything else with a
# leading H dimension is per-host state, sharded over the mesh's host
# axis. (Consumed by shadow_tpu.parallel.shard when building
# PartitionSpecs.)
REPLICATED_FIELDS = frozenset({
    "host_ip", "ip_sorted", "host_of_ip_sorted", "vertex_of_host",
    "latency_ns", "reliability", "bw_up_kibps", "bw_down_kibps",
    # observability matrix: each shard scatter-adds into its replica;
    # the window barrier psums the deltas back to a global matrix
    # (parallel/shard.py _replicate_scalars)
    "ctr_path_packets",
})


@struct.dataclass
class NetState:
    # --- replicated global lookup tables -----------------------------
    host_ip: jax.Array           # [H] i64 eth IP per host (global table)
    ip_sorted: jax.Array         # [H] i64 sorted IPs (for ip->host lookup)
    host_of_ip_sorted: jax.Array  # [H] i32 host index aligned to ip_sorted
    vertex_of_host: jax.Array    # [H] i32 topology attachment (global)
    latency_ns: jax.Array        # [V,V] i64
    reliability: jax.Array       # [V,V] f32
    # per-host bandwidths, replicated: TCP buffer autotuning sizes
    # buffers from the *bottleneck* of local and peer bandwidth
    # (ref: _tcp_tuneInitialBufferSizes, tcp.c:441-533)
    bw_up_kibps: jax.Array       # [H] i64 (global table)
    bw_down_kibps: jax.Array     # [H] i64 (global table)
    # --- per-host (sharded) state -------------------------------------
    # Global host id of each local row. Single-shard: arange(H). Under
    # shard_map each shard sees its own slice — handlers use this (not
    # arange) wherever a host's *identity* matters: self-addressed
    # emissions, src-host comparisons, global-table gathers.
    lane_id: jax.Array           # [H] i32
    # --- per-host RNG (deterministic seed hierarchy) ------------------
    rng_keys: jax.Array          # [H, 2] u32 key data
    rng_ctr: jax.Array           # [H] u32 draw counters
    # --- NIC token buckets (ref: network_interface.c:93-226) ----------
    tb_send_refill: jax.Array    # [H] i64 bytes per interval
    tb_recv_refill: jax.Array    # [H] i64
    tb_send_tokens: jax.Array    # [H] i64
    tb_recv_tokens: jax.Array    # [H] i64
    tb_quantum: jax.Array        # [H] i64 last analytic refill quantum
    nic_send_pending: jax.Array  # [H] bool — a future NIC_SEND exists
    nic_recv_pending: jax.Array  # [H] bool
    # Transient intra-micro-step flag: data was enqueued on a socket
    # this micro-step and the send drain (which runs last in the
    # handler pipeline) should pick it up NOW — the device form of the
    # reference's synchronous networkinterface_wantsSend call
    # (network_interface.c:583-...) instead of a same-time event
    # round-trip. Always consumed by handle_nic_send in the same
    # micro-step; host-side syscall paths must flush it explicitly
    # (vproc flush_wants_send).
    nic_send_now: jax.Array      # [H] bool
    # TCP buffer autotuning enabled per host+direction (off when the
    # user pinned explicit buffer sizes — ref: master.c:355-364,
    # options --socket-send/recv-buffer)
    autotune_snd: jax.Array      # [H] bool
    autotune_rcv: jax.Array      # [H] bool
    # --- virtual CPU (ref: cpu.c timeCPUAvailable) -------------------
    cpu_avail: jax.Array         # [H] i64 absolute time the CPU frees up
    cpu_cost: jax.Array          # [H] i64 per-event charge, pre-scaled
                                 # by the host's frequency ratio and
                                 # pre-rounded to precision
    ctr_cpu_blocked: jax.Array   # [H] i64 events delayed by the CPU
    ctr_cpu_delay_ns: jax.Array  # [H] i64 total virtual processing delay
                                 # (ref: tracker_addVirtualProcessingDelay)
    # per-host executed-event count — the device-meaningful analog of
    # the reference's per-host execution GTimer logged at shutdown
    # (host.c:114-116,314-317; wall seconds make no sense for a host
    # that is one lane of a fused device step)
    ctr_events_exec: jax.Array   # [H] i64
    # [V,V] remote send attempts per vertex pair when
    # cfg.track_paths, else [1,1] (ref: topology.c:2053-2063)
    ctr_path_packets: jax.Array  # [Vp,Vp] i64
    # --- process lifetime (ref: process.c:1286-1360) ------------------
    # True once the host's PROC_STOP event fired: app handlers are
    # masked off from then on (the device analog of process_stop
    # aborting the plugin main thread). The netstack keeps running —
    # in-flight TCP state unwinds via its own timers, as the
    # reference's descriptors do after plugin death.
    proc_stopped: jax.Array      # [H] bool
    rr_ptr: jax.Array            # [H] i32 round-robin qdisc cursor
    port_ctr: jax.Array          # [H] i32 ephemeral port allocator
                                 # (counter analog of host.c:1058-1110)
    priority_ctr: jax.Array     # [H] i64 per-host packet priority
                                 # (ref: host.c packet priority counter)
    # --- upstream router ring + CoDel (ref: router_queue_codel.c) -----
    rq_src: jax.Array            # [H,R] i32 source host of queued packet
    rq_enq_ts: jax.Array         # [H,R] i64 enqueue time (sojourn calc)
    rq_words: jax.Array          # [H,R,NWORDS] i32 packet words
    rq_head: jax.Array           # [H] i32 ring head
    rq_count: jax.Array          # [H] i32 ring occupancy
    rq_bytes: jax.Array          # [H] i64 queued wire bytes
    codel_interval_expire: jax.Array  # [H] i64 (0 = good state)
    codel_next_drop: jax.Array   # [H] i64
    codel_dropping: jax.Array    # [H] bool drop mode
    codel_drop_count: jax.Array  # [H] i32
    codel_drop_count_last: jax.Array  # [H] i32
    # --- sockets [H,S] ------------------------------------------------
    sk_type: jax.Array           # [H,S] i32 SocketType
    sk_flags: jax.Array          # [H,S] i32 SocketFlags bits
    sk_bound_ip: jax.Array       # [H,S] i64 (0 = INADDR_ANY wildcard)
    sk_bound_port: jax.Array     # [H,S] i32 (0 = unbound)
    sk_peer_ip: jax.Array        # [H,S] i64 (0 = unconnected)
    sk_peer_port: jax.Array      # [H,S] i32
    sk_sndbuf: jax.Array         # [H,S] i32 byte limits
    sk_rcvbuf: jax.Array         # [H,S] i32
    # Monotonic readiness generations: bumped every time new input
    # data/EOF raises READABLE (in) or freed capacity raises WRITABLE
    # (out). Edge-triggered epoll watches key off these — a new
    # arrival on an already-readable socket is still an edge, exactly
    # like the reference's per-status-change notify
    # (descriptor_adjustStatus -> epoll.c:583).
    sk_in_gen: jax.Array         # [H,S] i32
    sk_out_gen: jax.Array        # [H,S] i32
    # input ring: packets delivered, waiting for app recv
    in_src_ip: jax.Array         # [H,S,BI] i64
    in_src_port: jax.Array       # [H,S,BI] i32
    in_len: jax.Array            # [H,S,BI] i32
    in_payref: jax.Array         # [H,S,BI] i32
    in_status: jax.Array         # [H,S,BI] i32 delivery-status trail
                                 # (ref: packet.h:18-40 audit)
    in_head: jax.Array           # [H,S] i32
    in_count: jax.Array          # [H,S] i32
    in_bytes: jax.Array          # [H,S] i32
    # output ring: fully-formed packets waiting for the NIC. Protocols
    # write complete packet words at enqueue time; volatile TCP header
    # fields (ack/window/ts) are re-stamped at wire time by the NIC
    # (ref: tcp_networkInterfaceIsAboutToSendPacket, tcp.c:1090-1120).
    out_words: jax.Array         # [H,S,BO,NWORDS] i32
    out_priority: jax.Array      # [H,S,BO] i64
    out_head: jax.Array          # [H,S] i32
    out_count: jax.Array         # [H,S] i32
    out_bytes: jax.Array         # [H,S] i32
    # --- timers (timerfd analog, ref: timer.c) ------------------------
    tm_expire: jax.Array         # [H,T] i64 next expiry (INVALID = off)
    tm_interval: jax.Array       # [H,T] i64 (0 = one-shot)
    tm_gen: jax.Array            # [H,T] i32 generation (stale-expiry guard)
    tm_expirations: jax.Array    # [H,T] i64 count since last read
    # --- counters (tracker-lite; full tracker in utils) ---------------
    ctr_drop_reliability: jax.Array  # [H] i64 packets dropped by path loss
    ctr_drop_codel: jax.Array    # [H] i64
    ctr_drop_nosocket: jax.Array  # [H] i64
    ctr_drop_bufferfull: jax.Array  # [H] i64
    ctr_rx_bytes: jax.Array      # [H] i64
    ctr_tx_bytes: jax.Array      # [H] i64
    ctr_rx_packets: jax.Array    # [H] i64
    ctr_tx_packets: jax.Array    # [H] i64
    # data/control/retransmit byte split (ref: tracker.c:51-99 — the
    # tracker accounts interface bytes by packet class): data = payload
    # bytes, control = wire - data (headers + 0-len control packets),
    # retransmit = wire bytes of segments whose audit trail carries
    # PDS_SND_TCP_RETRANSMITTED
    ctr_rx_data_bytes: jax.Array  # [H] i64
    ctr_tx_data_bytes: jax.Array  # [H] i64
    ctr_tx_retx_bytes: jax.Array  # [H] i64
    # object accounting (ref: object_counter.c — new/free counts
    # diffed at shutdown; a nonzero diff is a logical descriptor leak)
    ctr_sk_alloc: jax.Array      # [H] i64 sockets allocated
    ctr_sk_free: jax.Array       # [H] i64 sockets freed
    # trail word of the host's most recently dropped packet, with the
    # drop-stage bit set — the debugging hook the reference gets from
    # dumping a dropped packet's status list (packet_toString)
    last_drop_status: jax.Array  # [H] i32
    # --- pcap capture ring (ref: network_interface.c:337-373) ---------
    # Shapes are [H,1,...] when cfg.pcap is off (dead weight ~0).
    # cap_count is a monotonic write counter; slot = count % C. The
    # host drains between windows (utils/pcap.py); count jumping by
    # more than C since the last drain = dropped capture records.
    cap_time: jax.Array          # [H,C] i64 capture timestamp
    cap_words: jax.Array         # [H,C,NWORDS] i32 packet words
    cap_meta: jax.Array          # [H,C] i32: src_host | dir<<24 (1=in)
    cap_count: jax.Array         # [H] i32 monotonic
    rq_overflow: jax.Array       # [] i32 router ring overflow (grow R!)
    # Optional per-host attribution plane for rq_overflow ([H] i32),
    # attached by core/lanes.attach for lane-isolated ensemble runs —
    # None (the default) contributes no pytree leaves, so checkpoints
    # and compiled programs without lane isolation are byte-identical.
    # Invariant when attached: rq_overflow == sum(rq_overflow_h).
    rq_overflow_h: Any = None


@struct.dataclass
class Sim:
    """Top-level simulation state: engine queues + netstack + app."""

    events: EventQueue
    outbox: Outbox
    net: NetState
    app: Any = None
    tcp: Any = None  # TcpState when cfg.tcp (net/tcp.py), else None
    # TelemetryRing (telemetry/ring.py) when window telemetry is on.
    # None contributes no pytree leaves, so checkpoints and compiled
    # programs built without telemetry are byte-identical to pre-telem
    # builds; telemetry.attach() is the explicit opt-in.
    telem: Any = None
    # InjectStaging (inject/staging.py) when open-system injection is
    # on — same None-contributes-no-leaves contract as telem;
    # inject.attach() / NetConfig.inject_lanes is the opt-in.
    inject: Any = None
    # LaneHealth (core/lanes.py) when lane-isolated health latches are
    # on for packed ensemble runs — same None-contributes-no-leaves
    # contract; core.lanes.attach() is the opt-in.
    lanes: Any = None
    # FlowRing (telemetry/flows.py) when per-flow latency sampling is
    # on — same None-contributes-no-leaves contract;
    # telemetry.attach_flows() is the opt-in.
    flows: Any = None
    # LaneAdmission (core/lanes.py) when the program is RESIDENT — its
    # lane population changes at window barriers under tenant leases
    # (fleet/admission.py) — same None-contributes-no-leaves contract;
    # core.lanes.attach_admission() is the opt-in (requires lanes).
    admission: Any = None
    # CausalityState (telemetry/causality.py) when event-lineage /
    # window-advance attribution tracing is on — same
    # None-contributes-no-leaves contract;
    # telemetry.attach_causality() is the opt-in.
    causality: Any = None
    # GuardState (compile/specialize.py) when the program is a
    # capability-trimmed specialized variant — one sticky trip counter
    # per dropped capability, checked once per window — same
    # None-contributes-no-leaves contract; specialize.apply() is the
    # opt-in (attached only when something was actually dropped).
    guard: Any = None
    # SentinelState (parallel/elastic.py) when the cross-shard
    # integrity sentinel is on — a per-window-barrier digest of the
    # replicated leaves compared pmax-vs-pmin across shards, latching
    # a sticky SHARD_DIVERGENCE trip on mismatch — same
    # None-contributes-no-leaves contract; elastic.attach_sentinel()
    # is the opt-in.
    sentinel: Any = None


def drop_total(net: NetState) -> jax.Array:
    """[H] i64 total packets dropped per host, all drop classes. The
    single definition of "a drop" shared by the tracker heartbeat, the
    telemetry ring's per-window delta, and the manifest's final
    counters — so all three agree by construction."""
    return (net.ctr_drop_reliability + net.ctr_drop_codel
            + net.ctr_drop_nosocket + net.ctr_drop_bufferfull)


def ip_of_hosts(cfg: NetConfig, net: "NetState", idx) -> jax.Array:
    """eth IP of host index array `idx` (any shape). Junk indices on
    masked lanes are tolerated either way: arithmetic on them is
    harmless in the affine fast path (cfg.ip_affine_base), and the
    slow path clips before gathering."""
    if cfg.ip_affine_base >= 0:
        return cfg.ip_affine_base + idx.astype(I64)
    GH = net.host_ip.shape[0]
    return net.host_ip[jnp.clip(idx, 0, GH - 1)]


def make_net_state(
    cfg: NetConfig,
    host_ips: np.ndarray,       # [H] i64
    bw_up_kibps: np.ndarray,    # [H]
    bw_down_kibps: np.ndarray,  # [H]
    vertex_of_host: np.ndarray,  # [H] i32
    latency_ns: np.ndarray,     # [V,V] i64
    reliability: np.ndarray,    # [V,V] f32
    cpu_freq_khz: np.ndarray | None = None,  # [H] (0 = unspecified)
) -> NetState:
    H, S = cfg.num_hosts, cfg.sockets_per_host
    BI, BO, R, T = cfg.in_ring, cfg.out_ring, cfg.router_ring, cfg.timers_per_host
    num_vertices = int(np.asarray(latency_ns).shape[0])

    # bytes per refill interval (ref: network_interface.c:196-203)
    tf = simtime.ONE_SECOND // TB_REFILL_INTERVAL
    send_refill = np.asarray(bw_up_kibps, np.int64) * 1024 // tf
    recv_refill = np.asarray(bw_down_kibps, np.int64) * 1024 // tf
    from shadow_tpu.net.packetfmt import MTU

    z_h = jnp.zeros((H,), I64)
    zi_h = jnp.zeros((H,), I32)

    # per-event CPU charge: cost x (rawFreq / hostFreq), rounded
    # half-up to precision (ref: cpu.c:85-110 cpu_addDelay); constant
    # per host, so rounding once at build == rounding per event
    if cpu_freq_khz is None:
        freq = np.zeros(H, np.int64)
    else:
        freq = np.asarray(cpu_freq_khz, np.int64)
    freq = np.where(freq > 0, freq, cfg.cpu_raw_freq_khz)
    cost = np.asarray(cfg.cpu_event_cost_ns, np.int64) \
        * cfg.cpu_raw_freq_khz // np.maximum(freq, 1)
    p = cfg.cpu_precision_ns
    if p > 0:
        cost = (cost + p // 2) // p * p

    return NetState(
        host_ip=jnp.asarray(host_ips, I64),
        ip_sorted=jnp.asarray(np.sort(host_ips), I64),
        host_of_ip_sorted=jnp.asarray(np.argsort(host_ips), I32),
        vertex_of_host=jnp.asarray(vertex_of_host, I32),
        latency_ns=jnp.asarray(latency_ns, I64),
        reliability=jnp.asarray(reliability, jnp.float32),
        bw_up_kibps=jnp.asarray(bw_up_kibps, I64),
        bw_down_kibps=jnp.asarray(bw_down_kibps, I64),
        autotune_snd=jnp.full((H,), bool(
            cfg.autotune and cfg.sndbuf == DEFAULT_SNDBUF)),
        autotune_rcv=jnp.full((H,), bool(
            cfg.autotune and cfg.rcvbuf == DEFAULT_RCVBUF)),
        cpu_avail=z_h,
        cpu_cost=jnp.asarray(cost, I64),
        ctr_cpu_blocked=z_h,
        ctr_cpu_delay_ns=z_h,
        ctr_events_exec=z_h,
        ctr_path_packets=jnp.zeros(
            (num_vertices, num_vertices) if cfg.track_paths else (1, 1),
            I64),
        lane_id=jnp.arange(H, dtype=I32),
        rng_keys=rng.host_streams(cfg.seed, H),
        rng_ctr=jnp.zeros((H,), jnp.uint32),
        tb_send_refill=jnp.asarray(send_refill),
        tb_recv_refill=jnp.asarray(recv_refill),
        # buckets start at capacity = refill + MTU
        # (ref: network_interface.c:219-226)
        tb_send_tokens=jnp.asarray(send_refill + MTU),
        tb_recv_tokens=jnp.asarray(recv_refill + MTU),
        tb_quantum=z_h,
        nic_send_pending=jnp.zeros((H,), bool),
        nic_recv_pending=jnp.zeros((H,), bool),
        nic_send_now=jnp.zeros((H,), bool),
        proc_stopped=jnp.zeros((H,), bool),
        rr_ptr=zi_h,
        port_ctr=zi_h,
        priority_ctr=z_h,
        rq_src=jnp.zeros((H, R), I32),
        rq_enq_ts=jnp.zeros((H, R), I64),
        rq_words=jnp.zeros((H, R, cfg.words_width), I32),
        rq_head=zi_h,
        rq_count=zi_h,
        rq_bytes=z_h,
        codel_interval_expire=z_h,
        codel_next_drop=z_h,
        codel_dropping=jnp.zeros((H,), bool),
        codel_drop_count=zi_h,
        codel_drop_count_last=zi_h,
        sk_type=jnp.zeros((H, S), I32),
        sk_flags=jnp.zeros((H, S), I32),
        sk_bound_ip=jnp.zeros((H, S), I64),
        sk_bound_port=jnp.zeros((H, S), I32),
        sk_peer_ip=jnp.zeros((H, S), I64),
        sk_peer_port=jnp.zeros((H, S), I32),
        sk_sndbuf=jnp.full((H, S), cfg.sndbuf, I32),
        sk_rcvbuf=jnp.full((H, S), cfg.rcvbuf, I32),
        sk_in_gen=jnp.zeros((H, S), I32),
        sk_out_gen=jnp.zeros((H, S), I32),
        in_src_ip=jnp.zeros((H, S, BI), I64),
        in_src_port=jnp.zeros((H, S, BI), I32),
        in_len=jnp.zeros((H, S, BI), I32),
        in_payref=jnp.zeros((H, S, BI), I32),
        in_status=jnp.zeros((H, S, BI), I32),
        in_head=jnp.zeros((H, S), I32),
        in_count=jnp.zeros((H, S), I32),
        in_bytes=jnp.zeros((H, S), I32),
        out_words=jnp.zeros((H, S, BO, cfg.words_width), I32),
        out_priority=jnp.zeros((H, S, BO), I64),
        out_head=jnp.zeros((H, S), I32),
        out_count=jnp.zeros((H, S), I32),
        out_bytes=jnp.zeros((H, S), I32),
        tm_expire=jnp.full((H, T), simtime.INVALID, I64),
        tm_interval=jnp.zeros((H, T), I64),
        tm_gen=jnp.zeros((H, T), I32),
        tm_expirations=jnp.zeros((H, T), I64),
        ctr_drop_reliability=z_h,
        ctr_drop_codel=z_h,
        ctr_drop_nosocket=z_h,
        ctr_drop_bufferfull=z_h,
        ctr_rx_bytes=z_h,
        ctr_tx_bytes=z_h,
        ctr_rx_packets=z_h,
        ctr_tx_packets=z_h,
        ctr_rx_data_bytes=z_h,
        ctr_tx_data_bytes=z_h,
        ctr_tx_retx_bytes=z_h,
        ctr_sk_alloc=z_h,
        ctr_sk_free=z_h,
        last_drop_status=zi_h,
        cap_time=jnp.zeros((H, cfg.pcap_ring if cfg.pcap else 1), I64),
        cap_words=jnp.zeros(
            (H, cfg.pcap_ring if cfg.pcap else 1, cfg.words_width), I32),
        cap_meta=jnp.zeros((H, cfg.pcap_ring if cfg.pcap else 1), I32),
        cap_count=zi_h,
        rq_overflow=jnp.zeros((), I32),
    )


def make_sim(cfg: NetConfig, net: NetState, app: Any = None) -> Sim:
    tcp = None
    if cfg.tcp:
        from shadow_tpu.net.tcp import (
            TcpState, initial_cwnd, initial_ssthresh)

        tcp = TcpState.create(
            cfg.num_hosts, cfg.sockets_per_host,
            init_cwnd=initial_cwnd(cfg),
            init_ssthresh=initial_ssthresh(cfg))
    sim = Sim(
        events=EventQueue.create(cfg.num_hosts, cfg.event_capacity,
                                 cfg.words_width),
        outbox=Outbox.create(cfg.num_hosts, cfg.outbox_capacity,
                             cfg.words_width),
        net=net,
        app=app,
        tcp=tcp,
    )
    if getattr(cfg, "inject_lanes", 0):
        from shadow_tpu.inject import staging as _inject_staging
        sim = _inject_staging.attach(sim, cfg.inject_lanes)
    return sim


def host_of_ip(net: NetState, ip):
    """Device ip -> host-index lookup ([...] i64 -> [...] i32, -1 when
    unknown). Replaces worker_resolveIPToAddress (ref: worker.c:255)."""
    # scan_unrolled: the default 'scan' method is a lax.fori_loop whose
    # ~14 iterations each launch serial gathers on TPU (~100 ms at
    # [10k,48] queries, measured v5e); unrolled, the same binary search
    # fuses into the surrounding program
    idx = jnp.searchsorted(net.ip_sorted, ip, method="scan_unrolled")
    idx = jnp.clip(idx, 0, net.ip_sorted.shape[0] - 1)
    hit = net.ip_sorted[idx] == ip
    return jnp.where(hit, net.host_of_ip_sorted[idx], -1)
