"""Simulated TCP as vmapped struct-of-arrays state transitions.

This is the device-native re-design of the reference's tcp.c (2520
lines of per-socket heap objects + callbacks): all sockets' TCP state
lives in [H,S]-shaped tensors; packet processing, the state machine
(ref: tcp.c:1777-2100), Reno congestion control (ref:
tcp_cong_reno.c), RTO/RTT estimation (ref: tcp.c:991-1026), and flush
(ref: _tcp_flush, tcp.c:1121-...) are masked batch updates over one
(host, socket) pair per lane per micro-step.

Design choices vs the reference, called out explicitly:

- Sequence space is non-wrapping int32 starting at ISS=0 (the
  reference uses wrapping guint32). Streams are limited to 2^31 bytes
  per connection — far beyond any simulated workload here.
- Retransmission regenerates segments from the [snd_una, snd_end)
  byte range instead of keeping a retransmit queue of packet copies
  (ref: tcp.c:854-1027). Payload bytes are host-side pool references
  keyed by (socket, seq), so regeneration is lossless.
- The receiver's reassembly queue (ref: unorderedInput PQ,
  tcp.c:222-230) is a bounded set of OO_RANGES byte ranges; segments
  that would need a 5th disjoint range are dropped (the sender
  retransmits). SACK advertises the SACK_RANGES lowest parked ranges
  (the reference carries a full sack list, packet.h:52,77; three
  ranges is Linux's practical SACK-option budget); the sender stores
  the advertised list as its scoreboard (the receiver re-advertises
  its full parked set on every ACK, so replacing is equivalent to the
  reference's tally merge) and clips retransmissions at the first
  sacked edge.
- Server sockets multiplex children as separate socket slots with a
  peer-specific association instead of sub-objects keyed by
  hash(peerIP,peerPort) (ref: tcp.c:91-113,1822-1852); the accept
  queue holds child slot indices.
- cwnd/ssthresh count packets exactly like the reference
  (tcp_cong_reno.c), not bytes.
- Zero-window persist probes: when the peer's window closes with data
  still buffered and nothing in flight, the RTO timer doubles as a
  persist timer — each expiry sends one byte past the window (with
  the usual exponential backoff), whose ACK re-reveals the window.
  (The reference has NO probe; its senders rely on the drain-time
  window-update ACK alone and stall if that ACK is lost. The probe is
  a deliberate robustness improvement, not a parity deviation.)
- Delayed ACKs per the reference's scheme (tcp.c:2066-2091): plain
  ACKs for in-order data coalesce behind one scheduled send — 1 ms
  for the first 1000 "quick" ACKs of a connection, 5 ms after —
  while dup-ACKs, handshake ACKs, and anything with SYN/FIN send
  immediately; any departing ACK-carrying packet cancels the pending
  delayed ACK (tcp.c:1105-1108).
- Buffer autotuning per tcp.c:407-592: initial sizes from the
  topology bandwidth-delay product on the first RTT sample, the
  receive buffer grows with app-copy rate (Linux DRS), the send
  buffer with cwnd; pinning explicit buffer sizes disables it.

Volatile header fields (ack, advertised window, timestamps) are
stamped when the NIC actually emits the packet — stamp_at_wire() —
matching tcp_networkInterfaceIsAboutToSendPacket (tcp.c:1090-1120).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from shadow_tpu.core import simtime
from shadow_tpu.core.events import NWORDS, EventKind, emit
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net import tcp_cong as cong
from shadow_tpu.net.rings import gather_hs, set_hs, set_ring
from shadow_tpu.net.sockets import sk_bind, sk_enqueue_out, set_writable
from shadow_tpu.net.state import NetConfig, NetState, SocketFlags, SocketType

I32 = jnp.int32
I64 = jnp.int64

MSS = pf.MTU - pf.HDR_TCP          # 1434 payload bytes per segment
OO_RANGES = 4                      # receiver reassembly ranges
ACCEPT_QUEUE = 4                   # pending-children ring per listener
FLUSH_SEGMENTS = 4                 # max segments packetized per flush call
                                   # (a lax.fori_loop in tcp_flush, so the
                                   # program carries one body copy; paired
                                   # with cfg.nic_drain=4 so one micro-step
                                   # packetizes AND wires a 4-segment burst)
INIT_CWND = 1                      # packets: tcp_cong_reno_init overrides
                                   # its own IW10 to 1 (tcp_cong_reno.c:176-180)
RESTART_CWND = 10                  # after RTO the reference restarts at 10
                                   # (tcp_cong_reno_timeout_ev_)
INIT_SSTHRESH = 0x7FFFFFFF


def initial_cwnd(cfg):
    """Initial congestion window in packets (ref: --tcp-windows,
    options.c:138, default honored only until tcp_cong_reno_init
    resets to 1, tcp_cong_reno.c:176-180 — so 0 = keep that reference
    behavior; a nonzero config pins the initial window)."""
    return cfg.tcp_windows or INIT_CWND


def initial_ssthresh(cfg):
    """Initial slow-start threshold in packets (ref: --tcp-ssthresh,
    options.c:137: 0 = discover via loss)."""
    return cfg.tcp_ssthresh or INIT_SSTHRESH
RTO_MIN_MS = 200                   # Linux-like floor
RTO_MAX_MS = 60_000
RTO_INIT_MS = 1_000
MAX_BACKOFF = 8                    # cap exponential backoff shift
TIMEWAIT_NS = 60 * simtime.ONE_SECOND  # ref: definitions.h:198, tcp.c:604-699

SACK_RANGES = 3                    # advertised SACK list length

# delayed-ACK scheme (ref: tcp.c:2066-2091)
DACK_QUICK_LIMIT = 1000            # quick ACKs at connection start
DACK_QUICK_NS = 1 * simtime.ONE_MILLISECOND
DACK_SLOW_NS = 5 * simtime.ONE_MILLISECOND

# buffer autotuning bounds (ref: definitions.h:101-147)
TCP_WMEM_MAX = 4194304
TCP_RMEM_MAX = 6291456
SEND_BUFFER_MIN = 16384
RECV_BUFFER_MIN = 87380
SNDMEM_SKB = 2404                  # ref: _tcp_autotuneSendBuffer's
                                   # sampled per-skb memory constant


class TcpSt:
    """Connection states (ref: tcp.c:42-47)."""

    CLOSED = 0
    LISTEN = 1
    SYN_SENT = 2
    SYN_RCVD = 3
    ESTABLISHED = 4
    FIN_WAIT_1 = 5
    FIN_WAIT_2 = 6
    CLOSING = 7
    TIME_WAIT = 8
    CLOSE_WAIT = 9
    LAST_ACK = 10


@struct.dataclass
class TcpState:
    """All TCP sockets' protocol state, [H,S] per-socket columns."""

    st: jax.Array          # [H,S] i32 TcpSt
    # send side (absolute seq; SYN occupies 0, data starts at 1)
    snd_una: jax.Array     # [H,S] i32 oldest unacked
    snd_nxt: jax.Array     # [H,S] i32 next to send
    snd_max: jax.Array     # [H,S] i32 highest seq ever sent (ack
                           # validity bound; survives go-back-N rewinds)
    snd_end: jax.Array     # [H,S] i32 end of app-buffered stream data
    snd_wnd: jax.Array     # [H,S] i32 peer advertised window (bytes)
    fin_pending: jax.Array  # [H,S] bool app called close; cleared only
                            # on free. "FIN ever sent" is derived:
                            # fin_pending & (snd_max == snd_end + 1) —
                            # a flag would go stale across go-back-N
                            # rewinds + healing ACKs
    dup_acks: jax.Array    # [H,S] i32
    cwnd: jax.Array        # [H,S] i32 packets
    ssthresh: jax.Array    # [H,S] i32 packets
    ca_acc: jax.Array      # [H,S] i32 congestion-avoidance accumulator
    in_recovery: jax.Array  # [H,S] bool fast recovery
    recover: jax.Array     # [H,S] i32 recovery point
    # cubic curve state (tcp_cong.py; unused under reno/aimd —
    # the reference's per-algorithm `ca` blob, tcp_cong.h:28)
    cub_wmax: jax.Array     # [H,S] i32 window before last loss
    cub_epoch_ms: jax.Array  # [H,S] i32 epoch start (-1 = unset)
    # peer-sacked ranges (scoreboard = the advertised list; r<=l =
    # empty slot). Ref: tcp_retransmit_tally.cc interval sets.
    sack_l: jax.Array      # [H,S,SACK_RANGES] i32
    sack_r: jax.Array      # [H,S,SACK_RANGES] i32
    # receive side
    rcv_nxt: jax.Array     # [H,S] i32
    app_rbytes: jax.Array  # [H,S] i32 in-order bytes awaiting app recv
    fin_rcvd: jax.Array    # [H,S] bool
    fin_rseq: jax.Array    # [H,S] i32 seq of peer FIN
    oo_l: jax.Array        # [H,S,OO_RANGES] i32 out-of-order [l, r)
    oo_r: jax.Array        # [H,S,OO_RANGES] i32
    ts_recent: jax.Array   # [H,S] i32 last peer tsval (echoed back)
    # RTT / RTO (Karn/Jacobson via timestamps, ref: tcp.c:991-1026)
    srtt_ms: jax.Array     # [H,S] i32 (-1 = no sample yet)
    rttvar_ms: jax.Array   # [H,S] i32
    rto_ms: jax.Array      # [H,S] i32
    backoff: jax.Array     # [H,S] i32 exponential backoff shift
    # retransmission timer: one *canonical* in-flight event per socket,
    # identified by a generation counter (the reference's timer
    # invalidation pattern, timer.c:23-42). The event checks rtx_expire
    # on fire and re-arms if the deadline moved later; arming an
    # *earlier* deadline than the in-flight event's fire time emits a
    # replacement event with a bumped generation (stale events die
    # silently on gen mismatch) — so the earliest deadline always has
    # a covering event.
    rtx_expire: jax.Array  # [H,S] i64 deadline (INVALID = disarmed)
    rtx_event: jax.Array   # [H,S] bool a current-gen event is in flight
    rtx_fire: jax.Array    # [H,S] i64 fire time of that event
    rtx_gen: jax.Array     # [H,S] i32 current generation
    # listener / accept (ref: tcp server multiplexing, tcp.c:260-321)
    parent: jax.Array      # [H,S] i32 child -> listener slot (-1)
    aq: jax.Array          # [H,S,ACCEPT_QUEUE] i32 ready child slots
    aq_head: jax.Array     # [H,S] i32
    aq_count: jax.Array    # [H,S] i32
    # same-time flush continuation chain (see EventKind.TCP_FLUSH)
    flush_pending: jax.Array   # [H,S] bool a TCP_FLUSH event is queued
    # delayed ACK (ref: tcp.c:166-170,2066-2091)
    dack_scheduled: jax.Array  # [H,S] bool a DACK timer is in flight
    dack_counter: jax.Array    # [H,S] i32 ACK-worthy arrivals pending
    dack_gen: jax.Array        # [H,S] i32 stale-event guard (slot reuse)
    quick_acks: jax.Array      # [H,S] i32 quick ACKs sent so far
    # buffer autotuning (ref: tcp.c:407-592)
    at_init_done: jax.Array    # [H,S] bool initial BDP sizing done
    at_copied: jax.Array       # [H,S] i32 app bytes copied this RTT
    at_space: jax.Array        # [H,S] i32 DRS space watermark
    at_last: jax.Array         # [H,S] i64 last DRS reset time
    # counters (tracker parity: retransmission tally)
    retx_segs: jax.Array   # [H] i64 segments retransmitted
    fr_entries: jax.Array  # [H] i64 fast-recovery entries (3 dup ACKs)
    drop_oo_full: jax.Array  # [H] i64 segs dropped, reassembly full
    drop_rwin: jax.Array   # [H] i64 segs dropped, recv buffer full
    probes_sent: jax.Array  # [H] i64 zero-window persist probes

    @staticmethod
    def create(num_hosts: int, sockets_per_host: int,
           init_cwnd: int = INIT_CWND,
           init_ssthresh: int = INIT_SSTHRESH) -> "TcpState":
        H, S = num_hosts, sockets_per_host
        zi = jnp.zeros((H, S), I32)
        zb = jnp.zeros((H, S), bool)
        zh = jnp.zeros((H,), I64)
        return TcpState(
            st=zi, snd_una=zi, snd_nxt=zi, snd_max=zi, snd_end=zi,
            snd_wnd=jnp.full((H, S), MSS, I32),
            fin_pending=zb, dup_acks=zi,
            cwnd=jnp.full((H, S), init_cwnd, I32),
            ssthresh=jnp.full((H, S), init_ssthresh, I32),
            ca_acc=zi, in_recovery=zb, recover=zi,
            cub_wmax=zi, cub_epoch_ms=jnp.full((H, S), -1, I32),
            sack_l=jnp.zeros((H, S, SACK_RANGES), I32),
            sack_r=jnp.zeros((H, S, SACK_RANGES), I32),
            rcv_nxt=zi, app_rbytes=zi, fin_rcvd=zb, fin_rseq=zi,
            oo_l=jnp.zeros((H, S, OO_RANGES), I32),
            oo_r=jnp.zeros((H, S, OO_RANGES), I32),
            ts_recent=zi,
            srtt_ms=jnp.full((H, S), -1, I32),
            rttvar_ms=zi,
            rto_ms=jnp.full((H, S), RTO_INIT_MS, I32),
            backoff=zi,
            rtx_expire=jnp.full((H, S), simtime.INVALID, I64),
            rtx_event=zb,
            rtx_fire=jnp.full((H, S), simtime.INVALID, I64),
            rtx_gen=jnp.zeros((H, S), I32),
            parent=jnp.full((H, S), -1, I32),
            aq=jnp.zeros((H, S, ACCEPT_QUEUE), I32),
            aq_head=zi, aq_count=zi,
            flush_pending=zb,
            dack_scheduled=zb, dack_counter=zi, dack_gen=zi,
            quick_acks=zi,
            at_init_done=zb, at_copied=zi, at_space=zi,
            at_last=jnp.zeros((H, S), I64),
            retx_segs=zh, fr_entries=zh, drop_oo_full=zh, drop_rwin=zh,
            probes_sent=zh,
        )


# ---------------------------------------------------------------------
# small helpers
# ---------------------------------------------------------------------

def _ms(now):
    return (now // simtime.ONE_MILLISECOND).astype(I32)


def _set(tcp: TcpState, field: str, mask, slot, value):
    return tcp.replace(**{field: set_hs(getattr(tcp, field), mask, slot, value)})


def _seg_words(net: NetState, mask, slot, flags, seq, length, payref=None):
    """Build [H, NWORDS] TCP packet words addressed to (slot)'s peer.
    Volatile fields (ack/win/ts) are left zero for stamp_at_wire."""
    H = mask.shape[0]
    src_port = gather_hs(net.sk_bound_port, slot)
    dst_port = gather_hs(net.sk_peer_port, slot)
    dst_ip = gather_hs(net.sk_peer_ip, slot)
    words = jnp.zeros((H, NWORDS), I32)
    flags = jnp.broadcast_to(jnp.asarray(flags, I32), (H,))
    words = words.at[:, pf.W_PROTO].set(pf.PROTO_TCP | (flags << 8))
    words = words.at[:, pf.W_LEN].set(
        jnp.broadcast_to(jnp.asarray(length, I32), (H,)))
    words = words.at[:, pf.W_PORTS].set(pf.pack_ports(src_port, dst_port))
    words = words.at[:, pf.W_SEQ].set(
        jnp.broadcast_to(jnp.asarray(seq, I32), (H,)))
    if payref is None:
        payref = jnp.full((H,), pf.PAYREF_NONE, I32)
    words = words.at[:, pf.W_PAYREF].set(payref)
    words = words.at[:, pf.W_DSTIP].set(dst_ip.astype(jnp.uint32).astype(I32))
    # audit trail: every TCP segment is created and throttled-queued
    # (ref: packet.h PDS trail; throttledOutput, tcp.c:222-230)
    words = words.at[:, pf.W_STATUS].set(
        pf.PDS_SND_CREATED | pf.PDS_SND_TCP_ENQUEUE_THROTTLED
        | pf.PDS_SND_SOCKET_BUFFERED)
    return words


def _adv_window(net: NetState, tcp: TcpState, slot):
    """Receive window to advertise: buffer capacity minus bytes held
    for the app (ref: autotune-less branch of tcp.c:407-592).

    Out-of-order parked bytes deliberately do NOT shrink the window:
    they sit inside already-advertised sequence space, and subtracting
    them would make every dup-ACK generated after an OO arrival carry
    a smaller window than the last — defeating the sender's dup-ACK
    test (peer_win == wnd_prev) and disabling fast retransmit. This is
    Linux's monotonic-window-edge behavior; the data-path drop guard
    still accounts OO bytes for memory safety."""
    free = gather_hs(net.sk_rcvbuf, slot) - gather_hs(tcp.app_rbytes, slot)
    return jnp.maximum(free, 0)


def sack_advert(tcp: TcpState, slot):
    """The SACK list a departing packet on (lane, slot) advertises:
    the SACK_RANGES lowest parked reassembly ranges, ascending by left
    edge (the full sack list of packet.h:52,77 up to the 3-range
    budget). Returns ((l1,r1),(l2,r2),(l3,r3)), each [H] i32, zeros
    where absent. Shared by stamp_at_wire (serial NIC drain) and the
    TCP bulk pass's wire stage — one selection rule, one bit pattern.
    The slot's ranges are gathered FIRST so the selection runs over
    [H, NR] rows, not the full [H, S, NR] socket cube (the bulk scan
    calls this every while_loop iteration)."""
    H = slot.shape[0]
    rows = jnp.arange(H)
    S = tcp.oo_l.shape[1]
    sc = jnp.clip(slot, 0, S - 1)
    ool = tcp.oo_l[rows, sc]                            # [H, NR]
    oor = tcp.oo_r[rows, sc]
    big = jnp.iinfo(I32).max
    key = jnp.where(oor > ool, ool, big)
    out = []
    for _ in range(SACK_RANGES):
        pick = jnp.argmin(key, axis=1)                  # [H]
        have = key[rows, pick] != big
        out.append((jnp.where(have, ool[rows, pick], 0),
                    jnp.where(have, oor[rows, pick], 0)))
        # exclude the picked range from the next round
        key = jnp.where(jnp.arange(key.shape[1])[None, :]
                        == pick[:, None], big, key)
    return tuple(out)


def stamp_at_wire(net: NetState, tcp: TcpState, mask, slot, words, now):
    """Fill ack / advertised window / timestamps on a departing TCP
    packet (ref: tcp_networkInterfaceIsAboutToSendPacket,
    tcp.c:1090-1120)."""
    ack = gather_hs(tcp.rcv_nxt, slot)
    win = _adv_window(net, tcp, slot)
    tse = gather_hs(tcp.ts_recent, slot)

    def put(w, col, val):
        return w.at[:, col].set(jnp.where(mask, val, w[:, col]))

    words = put(words, pf.W_ACK, ack)
    words = put(words, pf.W_WIN, win)
    words = put(words, pf.W_TSVAL, _ms(now))
    words = put(words, pf.W_TSECHO, tse)
    cols = ((pf.W_SACKL, pf.W_SACKR), (pf.W_SACKL2, pf.W_SACKR2),
            (pf.W_SACKL3, pf.W_SACKR3))
    for (cl, cr), (sl, sr) in zip(cols, sack_advert(tcp, slot)):
        words = put(words, cl, sl)
        words = put(words, cr, sr)
    return words


def _enqueue_seg(sim, buf, mask, slot, flags, seq, length, now,
                 retransmit=False):
    """Push one segment on the socket output ring + kick the NIC.
    Returns (sim, buf, ok[H]); ok False when the ring/sndbuf was full
    (the segment was NOT queued — callers must not advance snd_nxt).
    `retransmit` marks the audit trail's retransmission stages
    (ref: PDS_SND_TCP_*RETRANSMIT*, packet.h:18-40)."""
    from shadow_tpu.net import nic

    words = _seg_words(sim.net, mask, slot, flags, seq, length)
    if retransmit:
        words = words.at[:, pf.W_STATUS].set(
            words[:, pf.W_STATUS] | pf.PDS_SND_TCP_ENQUEUE_RETRANSMIT
            | pf.PDS_SND_TCP_DEQUEUE_RETRANSMIT
            | pf.PDS_SND_TCP_RETRANSMITTED)
    net, ok = sk_enqueue_out(sim.net, mask, slot, words)
    sim = sim.replace(net=net)
    sim, buf = nic.notify_wants_send(sim, buf, ok, now)
    return sim, buf, ok


def _arm_rtx(sim, buf, mask, slot, now):
    """Ensure an RTO deadline + a covering timer event exist
    (ref: _tcp_setRetransmitTimer). If the new deadline is *earlier*
    than the in-flight event's fire time (backoff collapse after an
    ACK, or slot reuse with a far-future stale event), emit a
    replacement event under a bumped generation — the old event dies
    silently on gen mismatch."""
    tcp = sim.tcp
    H = mask.shape[0]
    rto_ns = (gather_hs(tcp.rto_ms, slot).astype(I64)
              << jnp.minimum(gather_hs(tcp.backoff, slot), MAX_BACKOFF).astype(I64)
              ) * simtime.ONE_MILLISECOND
    rto_ns = jnp.minimum(rto_ns, I64(RTO_MAX_MS) * simtime.ONE_MILLISECOND)
    deadline = now + rto_ns
    tcp = _set(tcp, "rtx_expire", mask, slot, deadline)
    in_flight = gather_hs(tcp.rtx_event, slot)
    earlier = mask & in_flight & (deadline < gather_hs(tcp.rtx_fire, slot))
    need_event = (mask & ~in_flight) | earlier
    gen = gather_hs(tcp.rtx_gen, slot) + 1
    tcp = _set(tcp, "rtx_gen", need_event, slot, gen)
    tcp = _set(tcp, "rtx_event", need_event, slot, True)
    tcp = _set(tcp, "rtx_fire", need_event, slot, deadline)
    sim = sim.replace(tcp=tcp)
    w = (jnp.zeros((H, NWORDS), I32)
         .at[:, 0].set(slot.astype(I32))
         .at[:, 1].set(gen))
    buf = emit(buf, need_event, sim.net.lane_id, deadline,
               EventKind.TCP_RTX_TIMER, w)
    return sim, buf


def _disarm_rtx(tcp: TcpState, mask, slot):
    """Clear the deadline; the in-flight event (if any) will see
    INVALID and die silently."""
    return _set(tcp, "rtx_expire", mask, slot,
                jnp.full(mask.shape, simtime.INVALID, I64))


# ---------------------------------------------------------------------
# app-facing API (the process_emu_* surface for TCP,
# ref: host.c:1111-1359, process.h:103-437)
# ---------------------------------------------------------------------

def tcp_connect(cfg: NetConfig, sim, mask, slot, dst_ip, dst_port, now, buf):
    """Active open: SYN_SENT + SYN on the wire (ref: tcp_connectToPeer,
    host.c:1193-1230)."""
    from shadow_tpu.net.sockets import sk_connect_peer

    net = sk_connect_peer(sim.net, mask, slot, dst_ip, dst_port)
    sim = sim.replace(net=net)
    tcp = sim.tcp
    tcp = _set(tcp, "st", mask, slot, jnp.full(mask.shape, TcpSt.SYN_SENT, I32))
    tcp = _set(tcp, "snd_una", mask, slot, jnp.zeros(mask.shape, I32))
    tcp = _set(tcp, "snd_nxt", mask, slot, jnp.ones(mask.shape, I32))
    tcp = _set(tcp, "snd_max", mask, slot, jnp.ones(mask.shape, I32))
    tcp = _set(tcp, "snd_end", mask, slot, jnp.ones(mask.shape, I32))
    sim = sim.replace(tcp=tcp)
    sim, buf, _ = _enqueue_seg(sim, buf, mask, slot, pf.TCPF_SYN,
                            jnp.zeros(mask.shape, I32), 0, now)
    return _arm_rtx(sim, buf, mask, slot, now)


def tcp_listen(sim, mask, slot):
    """Passive open on a bound socket (ref: host_listenForPeer)."""
    tcp = _set(sim.tcp, "st", mask, slot,
               jnp.full(mask.shape, TcpSt.LISTEN, I32))
    return sim.replace(tcp=tcp)


def tcp_accept(sim, mask, slot):
    """Pop one established child from the listener's accept queue.
    Returns (sim, got[H], child_slot[H])."""
    tcp = sim.tcp
    cnt = gather_hs(tcp.aq_count, slot)
    head = gather_hs(tcp.aq_head, slot)
    got = mask & (cnt > 0)
    H, S = tcp.aq_head.shape
    lane = jnp.arange(H)
    sc = jnp.clip(slot, 0, S - 1)
    child = tcp.aq[lane, sc, jnp.clip(head, 0, ACCEPT_QUEUE - 1)]
    child = jnp.where(got, child, -1)
    tcp = _set(tcp, "aq_head", got, slot, (head + 1) % ACCEPT_QUEUE)
    tcp = _set(tcp, "aq_count", got, slot, cnt - 1)
    # listener readable while children remain queued
    drained = got & (cnt - 1 == 0)
    flags = gather_hs(sim.net.sk_flags, slot)
    net = sim.net.replace(
        sk_flags=set_hs(sim.net.sk_flags, drained, slot,
                        flags & ~SocketFlags.READABLE))
    return sim.replace(net=net, tcp=tcp), got, child


def tcp_send(cfg: NetConfig, sim, mask, slot, nbytes, now, buf):
    """Append nbytes of stream data (ref: tcp_sendUserData,
    tcp.c:2126-2190). Accepts up to the send-buffer limit; returns
    (sim, buf, accepted[H] bytes)."""
    tcp = sim.tcp
    st = gather_hs(tcp.st, slot)
    can = mask & ((st == TcpSt.ESTABLISHED) | (st == TcpSt.CLOSE_WAIT)
                  | (st == TcpSt.SYN_SENT) | (st == TcpSt.SYN_RCVD))
    una = gather_hs(tcp.snd_una, slot)
    end = gather_hs(tcp.snd_end, slot)
    sndbuf = gather_hs(sim.net.sk_sndbuf, slot)
    room = jnp.maximum(sndbuf - (end - una), 0)
    accepted = jnp.where(can, jnp.minimum(jnp.asarray(nbytes, I32), room), 0)
    tcp = _set(tcp, "snd_end", can, slot, end + accepted)
    # stream buffer exhausted: drop WRITABLE until ACK progress frees
    # room (ref: descriptor_adjustStatus; drives epoll EPOLLOUT waits)
    bfull = can & (room - accepted <= 0)
    sim = sim.replace(tcp=tcp, net=set_writable(sim.net, bfull, slot, False))
    sim, buf = tcp_flush(cfg, sim, mask, slot, now, buf)
    return sim, buf, accepted


def tcp_recv(sim, mask, slot, maxbytes, now, buf):
    """Consume in-order received bytes (ref: tcp_receiveUserData,
    tcp.c:2192-...). Returns (sim, buf, nread[H], eof[H]).

    Window updates: an ACK is sent only when the read reopens a
    *constrained* window (was < 2 MSS, grew by >= 1 MSS) — receiver
    silly-window avoidance. A receiver that drains promptly never
    sends gratuitous ACKs, which matters because a pure ACK with an
    unchanged window is indistinguishable from a loss-signalling
    duplicate ACK at the sender."""
    tcp = sim.tcp
    net = sim.net
    win_before = _adv_window(net, tcp, slot)
    avail = gather_hs(tcp.app_rbytes, slot)
    nread = jnp.where(mask, jnp.minimum(jnp.asarray(maxbytes, I32), avail), 0)
    tcp = _set(tcp, "app_rbytes", mask, slot, avail - nread)

    # ---- receive-buffer autotuning (Linux DRS; ref:
    # _tcp_autotuneReceiveBuffer, tcp.c:535-564, called per app copy,
    # tcp.c:2303): track bytes copied per smoothed-RTT interval, grow
    # the buffer toward 2x the copy rate, capped by bw_down * srtt.
    at_on = mask & net.autotune_rcv & (nread > 0)
    copied = gather_hs(tcp.at_copied, slot) + nread
    space = jnp.maximum(2 * copied, gather_hs(tcp.at_space, slot))
    cur = gather_hs(net.sk_rcvbuf, slot)
    srtt = gather_hs(tcp.srtt_ms, slot)
    my_down = net.bw_down_kibps[net.lane_id]
    max_rmem = jnp.clip(my_down * 1024 * jnp.maximum(srtt, 0).astype(I64)
                        // 1000, TCP_RMEM_MAX, 10 * TCP_RMEM_MAX)
    growing = at_on & (space > cur)
    tcp = _set(tcp, "at_space", growing, slot, space)
    new_size = jnp.minimum(space.astype(I64), max_rmem).astype(I32)
    net = net.replace(sk_rcvbuf=set_hs(
        net.sk_rcvbuf, growing & (new_size > cur), slot, new_size))
    tcp = _set(tcp, "at_copied", at_on, slot, copied)
    last = gather_hs(tcp.at_last, slot)
    tcp = _set(tcp, "at_last", at_on & (last == 0), slot, now)
    rtt_ns = jnp.maximum(srtt, 0).astype(I64) * simtime.ONE_MILLISECOND
    reset = at_on & (last > 0) & (srtt > 0) & (now - last > rtt_ns)
    tcp = _set(tcp, "at_last", reset, slot, now)
    tcp = _set(tcp, "at_copied", reset, slot, jnp.zeros(mask.shape, I32))
    sim = sim.replace(net=net)
    eof = mask & gather_hs(tcp.fin_rcvd, slot) & (avail - nread == 0) & (
        gather_hs(tcp.rcv_nxt, slot) > gather_hs(tcp.fin_rseq, slot))
    drained = mask & (avail - nread == 0) & ~eof
    flags = gather_hs(sim.net.sk_flags, slot)
    net = sim.net.replace(
        sk_flags=set_hs(sim.net.sk_flags, drained, slot,
                        flags & ~SocketFlags.READABLE))
    sim = sim.replace(net=net, tcp=tcp)
    win_after = _adv_window(net, tcp, slot)
    update = mask & (win_before < 2 * MSS) & (win_after - win_before >= MSS)
    sim, buf, _ = _enqueue_seg(sim, buf, update, slot, pf.TCPF_ACK,
                               gather_hs(tcp.snd_nxt, slot), 0, now)
    return sim, buf, nread, eof


def tcp_close(cfg: NetConfig, sim, mask, slot, now, buf):
    """Active/passive close (ref: tcp_close, tcp.c:604-699): mark the
    FIN pending; flush emits it once all data is out."""
    tcp = sim.tcp
    st = gather_hs(tcp.st, slot)
    # buffered-but-unsent stream data exists iff snd_end advanced past
    # the SYN (data seq space starts at 1)
    has_data = gather_hs(tcp.snd_end, slot) > 1
    to_finwait = mask & ((st == TcpSt.ESTABLISHED) | (st == TcpSt.SYN_RCVD))
    to_lastack = mask & (st == TcpSt.CLOSE_WAIT)
    # close during active open with data already submitted: defer —
    # the FIN_WAIT_1 transition happens when the SYN|ACK establishes
    deferred = mask & (st == TcpSt.SYN_SENT) & has_data
    # closing a never-connected, listening, or empty-handshake socket
    # frees it directly
    direct = mask & ((st == TcpSt.CLOSED) | (st == TcpSt.LISTEN)
                     | ((st == TcpSt.SYN_SENT) & ~has_data))
    tcp = _set(tcp, "st", to_finwait, slot,
               jnp.full(mask.shape, TcpSt.FIN_WAIT_1, I32))
    tcp = _set(tcp, "st", to_lastack, slot,
               jnp.full(mask.shape, TcpSt.LAST_ACK, I32))
    tcp = _set(tcp, "fin_pending", to_finwait | to_lastack | deferred,
               slot, True)
    sim = sim.replace(tcp=tcp)
    sim = _free_socket(cfg, sim, direct, slot)
    return tcp_flush(cfg, sim, mask & ~direct, slot, now, buf)


def _free_socket(cfg, sim, mask, slot):
    """Release a socket slot for reuse (ref: descriptor close +
    handle recycling, host.c:696-767)."""
    net = sim.net
    zero = jnp.zeros(mask.shape, I32)
    net = net.replace(
        sk_type=set_hs(net.sk_type, mask, slot, zero),
        sk_flags=set_hs(net.sk_flags, mask, slot, zero),
        sk_bound_ip=set_hs(net.sk_bound_ip, mask, slot,
                           jnp.zeros(mask.shape, I64)),
        sk_bound_port=set_hs(net.sk_bound_port, mask, slot, zero),
        sk_peer_ip=set_hs(net.sk_peer_ip, mask, slot,
                          jnp.zeros(mask.shape, I64)),
        sk_peer_port=set_hs(net.sk_peer_port, mask, slot, zero),
        # autotune may have grown the buffers; a recycled slot starts
        # from the configured defaults again
        sk_sndbuf=set_hs(net.sk_sndbuf, mask, slot,
                         jnp.full(mask.shape, cfg.sndbuf, I32)),
        sk_rcvbuf=set_hs(net.sk_rcvbuf, mask, slot,
                         jnp.full(mask.shape, cfg.rcvbuf, I32)),
        # object accounting (ref: object_counter.c free counts)
        ctr_sk_free=net.ctr_sk_free + mask.astype(I64),
    )
    tcp = sim.tcp
    tcp = _set(tcp, "st", mask, slot, zero)
    tcp = _set(tcp, "snd_una", mask, slot, zero)
    tcp = _set(tcp, "snd_nxt", mask, slot, zero)
    tcp = _set(tcp, "snd_max", mask, slot, zero)
    tcp = _set(tcp, "snd_end", mask, slot, zero)
    tcp = _set(tcp, "snd_wnd", mask, slot, jnp.full(mask.shape, MSS, I32))
    tcp = _set(tcp, "fin_pending", mask, slot, False)
    tcp = _set(tcp, "dup_acks", mask, slot, zero)
    tcp = _set(tcp, "cwnd", mask, slot,
               jnp.full(mask.shape, initial_cwnd(cfg), I32))
    tcp = _set(tcp, "ssthresh", mask, slot,
               jnp.full(mask.shape, initial_ssthresh(cfg), I32))
    tcp = _set(tcp, "ca_acc", mask, slot, zero)
    tcp = _set(tcp, "in_recovery", mask, slot, False)
    tcp = _set(tcp, "cub_wmax", mask, slot, zero)
    tcp = _set(tcp, "cub_epoch_ms", mask, slot,
               jnp.full(mask.shape, -1, I32))
    tcp = _set(tcp, "rcv_nxt", mask, slot, zero)
    tcp = _set(tcp, "app_rbytes", mask, slot, zero)
    tcp = _set(tcp, "fin_rcvd", mask, slot, False)
    tcp = _set(tcp, "ts_recent", mask, slot, zero)
    tcp = _set(tcp, "srtt_ms", mask, slot, jnp.full(mask.shape, -1, I32))
    tcp = _set(tcp, "rttvar_ms", mask, slot, zero)
    tcp = _set(tcp, "rto_ms", mask, slot, jnp.full(mask.shape, RTO_INIT_MS, I32))
    tcp = _set(tcp, "backoff", mask, slot, zero)
    tcp = _disarm_rtx(tcp, mask, slot)
    tcp = _set(tcp, "parent", mask, slot, jnp.full(mask.shape, -1, I32))
    tcp = _set(tcp, "aq_head", mask, slot, zero)
    tcp = _set(tcp, "aq_count", mask, slot, zero)
    S = tcp.oo_l.shape[1]
    sel = mask[:, None] & (jnp.arange(S)[None, :] == slot[:, None])
    tcp = tcp.replace(
        oo_l=jnp.where(sel[..., None], 0, tcp.oo_l),
        oo_r=jnp.where(sel[..., None], 0, tcp.oo_r),
        sack_l=jnp.where(sel[..., None], 0, tcp.sack_l),
        sack_r=jnp.where(sel[..., None], 0, tcp.sack_r),
    )
    tcp = _set(tcp, "flush_pending", mask, slot, False)
    tcp = _set(tcp, "dack_scheduled", mask, slot, False)
    tcp = _set(tcp, "dack_counter", mask, slot, zero)
    # stale DACK events for a reused slot die on generation mismatch
    tcp = _set(tcp, "dack_gen", mask, slot,
               gather_hs(tcp.dack_gen, slot) + 1)
    tcp = _set(tcp, "quick_acks", mask, slot, zero)
    tcp = _set(tcp, "at_init_done", mask, slot, False)
    tcp = _set(tcp, "at_copied", mask, slot, zero)
    tcp = _set(tcp, "at_space", mask, slot, zero)
    tcp = _set(tcp, "at_last", mask, slot, jnp.zeros(mask.shape, I64))
    return sim.replace(net=net, tcp=tcp)


# ---------------------------------------------------------------------
# flush: packetize allowed stream bytes onto the output ring
# (ref: _tcp_flush, tcp.c:1121-...)
# ---------------------------------------------------------------------

def _flush_one_segment(cfg, sim, buf, mask, slot, now):
    """Packetize one admissible MSS-bounded segment per masked lane
    (one iteration of _tcp_flush's drain-while-sendable loop)."""
    tcp = sim.tcp
    st = gather_hs(tcp.st, slot)
    can_data = mask & (
        (st == TcpSt.ESTABLISHED) | (st == TcpSt.CLOSE_WAIT)
        | (st == TcpSt.FIN_WAIT_1) | (st == TcpSt.LAST_ACK))
    una = gather_hs(tcp.snd_una, slot)
    nxt = gather_hs(tcp.snd_nxt, slot)
    end = gather_hs(tcp.snd_end, slot)
    cwnd_b = gather_hs(tcp.cwnd, slot) * MSS
    wnd = jnp.minimum(cwnd_b, gather_hs(tcp.snd_wnd, slot))
    usable = una + wnd - nxt
    seg = jnp.minimum(jnp.minimum(end - nxt, MSS), usable)
    do = can_data & (seg > 0)
    sim, buf, sent = _enqueue_seg(sim, buf, do, slot, pf.TCPF_ACK, nxt,
                                  seg, now)
    tcp = _set(sim.tcp, "snd_nxt", sent, slot,
               nxt + jnp.where(sent, seg, 0))
    tcp = _set(tcp, "snd_max", sent, slot,
               jnp.maximum(gather_hs(tcp.snd_max, slot),
                           nxt + jnp.where(sent, seg, 0)))
    return sim.replace(tcp=tcp), buf


def tcp_flush(cfg: NetConfig, sim, mask, slot, now, buf):
    # fori_loop keeps ONE copy of the packetize body in the program
    # (compile time) while letting a single flush call emit several
    # segments (fewer same-time TCP_FLUSH continuation micro-steps)
    sim, buf = jax.lax.fori_loop(
        0, FLUSH_SEGMENTS,
        lambda i, c: _flush_one_segment(cfg, c[0], c[1], mask, slot, now),
        (sim, buf))
    # FIN rides once all data is packetized (FIN seq == snd_end)
    tcp = sim.tcp
    nxt = gather_hs(tcp.snd_nxt, slot)
    end = gather_hs(tcp.snd_end, slot)
    fin = mask & gather_hs(tcp.fin_pending, slot) & (nxt == end)
    sim, buf, fsent = _enqueue_seg(sim, buf, fin,
                                   slot, pf.TCPF_FIN | pf.TCPF_ACK,
                                   nxt, 0, now)
    tcp = sim.tcp
    tcp = _set(tcp, "snd_nxt", fsent, slot, nxt + 1)
    tcp = _set(tcp, "snd_max", fsent, slot,
               jnp.maximum(gather_hs(tcp.snd_max, slot), nxt + 1))
    sim = sim.replace(tcp=tcp)
    # outstanding data must be covered by a retransmission deadline;
    # a zero peer window with data waiting and nothing in flight arms
    # the same timer as a persist timer (zero-window probe — see
    # module docstring; the reference has no probe)
    tcp = sim.tcp
    una = gather_hs(tcp.snd_una, slot)
    nxt = gather_hs(tcp.snd_nxt, slot)
    outstanding = mask & (una < nxt)
    persist = mask & (una == nxt) & (gather_hs(tcp.snd_end, slot) > nxt) \
        & (gather_hs(tcp.snd_wnd, slot) == 0)
    need = (outstanding | persist) & (
        gather_hs(tcp.rtx_expire, slot) == simtime.INVALID)

    # more admissible data than this pass packetized (one coalesced
    # ACK can open many segments' worth of window): chain a same-time
    # TCP_FLUSH event, unwound by the window fixpoint — the device
    # form of _tcp_flush's drain-while-sendable loop (tcp.c:1121-...)
    st2 = gather_hs(tcp.st, slot)
    can2 = mask & (
        (st2 == TcpSt.ESTABLISHED) | (st2 == TcpSt.CLOSE_WAIT)
        | (st2 == TcpSt.FIN_WAIT_1) | (st2 == TcpSt.LAST_ACK))
    wnd2 = jnp.minimum(gather_hs(tcp.cwnd, slot) * MSS,
                       gather_hs(tcp.snd_wnd, slot))
    seg2 = jnp.minimum(
        jnp.minimum(gather_hs(tcp.snd_end, slot) - nxt, MSS),
        una + wnd2 - nxt)
    BO2 = sim.net.out_words.shape[2]
    room2 = (gather_hs(sim.net.out_count, slot) < BO2) & (
        gather_hs(sim.net.out_bytes, slot) + seg2
        <= gather_hs(sim.net.sk_sndbuf, slot))
    chain = can2 & (seg2 > 0) & room2 \
        & ~gather_hs(tcp.flush_pending, slot)
    tcp = _set(tcp, "flush_pending", chain, slot, True)
    sim = sim.replace(tcp=tcp)
    H2 = mask.shape[0]
    cw = jnp.zeros((H2, NWORDS), I32).at[:, 0].set(slot.astype(I32))
    buf = emit(buf, chain, sim.net.lane_id, now, EventKind.TCP_FLUSH, cw)
    return _arm_rtx(sim, buf, need, slot, now)


# ---------------------------------------------------------------------
# segment regeneration for retransmission
# ---------------------------------------------------------------------

def sack_clip_len(una, seg, sack_l, sack_r):
    """The device scoreboard's retransmit decision rule: clip a
    retransmission starting at snd_una so it ends at the first
    peer-sacked left edge above una — sacked bytes need no resend
    (ref: the reference tally's lost-range computation excludes sacked
    intervals, tcp_retransmit_tally.cc compute_lost). Because the
    receiver advertises its LOWEST parked ranges (stamp_at_wire), the
    first sacked edge above una is always in the advertised list, so
    this decision is bit-equal to the full interval-set tally's first
    lost range — differentially validated against the native tally
    under heavy random loss in tests/test_tally_oracle.py.

    una: [H] i32; seg: [H] i32 proposed length; sack_l/sack_r:
    [H, SACK_RANGES] i32 advertised scoreboard. Returns clipped [H]."""
    above = (sack_r > sack_l) & (sack_l > una[:, None])
    big = jnp.iinfo(I32).max
    first_sacked = jnp.min(jnp.where(above, sack_l, big), axis=1)
    return jnp.minimum(seg, jnp.maximum(first_sacked - una, 1))


def _retransmit_one(cfg, sim, mask, slot, now, buf):
    """Re-send the segment at snd_una (ref: _tcp_retransmitPacket).
    SYN / SYN|ACK / FIN are regenerated from the state machine; data
    segments from the [snd_una, snd_end) byte range."""
    tcp = sim.tcp
    st = gather_hs(tcp.st, slot)
    una = gather_hs(tcp.snd_una, slot)
    end = gather_hs(tcp.snd_end, slot)
    fin_ever = gather_hs(tcp.fin_pending, slot) & (
        gather_hs(tcp.snd_max, slot) == end + 1)

    is_syn = mask & (una == 0) & (st == TcpSt.SYN_SENT)
    is_synack = mask & (una == 0) & (st == TcpSt.SYN_RCVD)
    is_fin = mask & ~is_syn & ~is_synack & fin_ever & (una == end)
    is_data = mask & ~is_syn & ~is_synack & ~is_fin & (una < end)

    sim, buf, _ = _enqueue_seg(sim, buf, is_syn, slot, pf.TCPF_SYN,
                            jnp.zeros(mask.shape, I32), 0, now,
                            retransmit=True)
    sim, buf, _ = _enqueue_seg(sim, buf, is_synack, slot,
                            pf.TCPF_SYN | pf.TCPF_ACK,
                            jnp.zeros(mask.shape, I32), 0, now,
                            retransmit=True)
    sim, buf, _ = _enqueue_seg(sim, buf, is_fin, slot,
                            pf.TCPF_FIN | pf.TCPF_ACK, una, 0, now,
                            retransmit=True)
    seg = jnp.minimum(end - una, MSS)
    H = mask.shape[0]
    lane = jnp.arange(H)
    S = tcp.sack_l.shape[1]
    sc = jnp.clip(slot, 0, S - 1)
    sll = tcp.sack_l[lane, sc]                         # [H, SACK_RANGES]
    srr = tcp.sack_r[lane, sc]
    seg = sack_clip_len(una, seg, sll, srr)
    sim, buf, _ = _enqueue_seg(sim, buf, is_data, slot, pf.TCPF_ACK, una, seg,
                               now, retransmit=True)
    sent = is_syn | is_synack | is_fin | is_data
    resent_end = jnp.where(is_data, una + seg, una + 1)
    tcp = sim.tcp
    tcp = tcp.replace(retx_segs=tcp.retx_segs + sent.astype(I64))
    return sim.replace(tcp=tcp), buf, sent, resent_end


# ---------------------------------------------------------------------
# inbound packet processing (ref: tcp_processPacket, tcp.c:1777-2100)
# ---------------------------------------------------------------------

def tcp_packet_in(cfg: NetConfig, sim, mask, slot, words, src_ip, src_port,
                  now, buf):
    """Process one inbound TCP segment per masked lane, already matched
    to socket `slot` (child-specific association wins over the
    listener)."""
    tcp = sim.tcp
    net = sim.net
    H = mask.shape[0]
    slot = jnp.asarray(slot, I32)

    flags = pf.tcp_flags_of(words)
    seq = words[:, pf.W_SEQ]
    ack = words[:, pf.W_ACK]
    length = words[:, pf.W_LEN]
    peer_win = words[:, pf.W_WIN]
    tsval = words[:, pf.W_TSVAL]
    tsecho = words[:, pf.W_TSECHO]
    sackl = words[:, pf.W_SACKL]
    sackr = words[:, pf.W_SACKR]
    f_syn = (flags & pf.TCPF_SYN) != 0
    f_ack = (flags & pf.TCPF_ACK) != 0
    f_fin = (flags & pf.TCPF_FIN) != 0
    f_rst = (flags & pf.TCPF_RST) != 0
    st = gather_hs(tcp.st, slot)

    # ---- RST tears the connection down (ref: tcp.c RST handling) ----
    rst = mask & f_rst & (st != TcpSt.CLOSED) & (st != TcpSt.LISTEN)
    sim = sim.replace(tcp=tcp)
    sim = _free_socket(cfg, sim, rst, slot)
    tcp, net = sim.tcp, sim.net
    mask = mask & ~rst
    st = gather_hs(tcp.st, slot)

    # ---- LISTEN + SYN: spawn a child in SYN_RCVD ---------------------
    # (ref: server multiplexing, tcp.c:1822-1852). A full backlog —
    # queued children plus children still in handshake — refuses the
    # connection by dropping the SYN unanswered, so the client's SYN
    # retransmit retries later (the reference refuses at capacity
    # rather than orphaning an ESTABLISHED child no accept() can see).
    syn_to_listen = mask & (st == TcpSt.LISTEN) & f_syn
    in_handshake = jnp.sum(
        (tcp.parent == slot[:, None]) & (tcp.st == TcpSt.SYN_RCVD),
        axis=1, dtype=I32)
    backlog = gather_hs(tcp.aq_count, slot) + in_handshake
    syn_ok = syn_to_listen & (backlog < ACCEPT_QUEUE)
    from shadow_tpu.net.sockets import sk_create

    net, child = sk_create(net, syn_ok, SocketType.TCP)
    spawned = syn_to_listen & (child >= 0)
    net = net.replace(
        sk_bound_ip=set_hs(net.sk_bound_ip, spawned, child,
                           gather_hs(net.sk_bound_ip, slot)),
        sk_bound_port=set_hs(net.sk_bound_port, spawned, child,
                             gather_hs(net.sk_bound_port, slot)),
        sk_peer_ip=set_hs(net.sk_peer_ip, spawned, child, src_ip),
        sk_peer_port=set_hs(net.sk_peer_port, spawned, child, src_port),
    )
    tcp = _set(tcp, "st", spawned, child,
               jnp.full((H,), TcpSt.SYN_RCVD, I32))
    tcp = _set(tcp, "rcv_nxt", spawned, child, seq + 1)
    tcp = _set(tcp, "ts_recent", spawned, child, tsval)
    tcp = _set(tcp, "snd_una", spawned, child, jnp.zeros((H,), I32))
    tcp = _set(tcp, "snd_nxt", spawned, child, jnp.ones((H,), I32))
    tcp = _set(tcp, "snd_max", spawned, child, jnp.ones((H,), I32))
    tcp = _set(tcp, "snd_end", spawned, child, jnp.ones((H,), I32))
    tcp = _set(tcp, "snd_wnd", spawned, child, jnp.maximum(peer_win, MSS))
    tcp = _set(tcp, "parent", spawned, child, slot)
    sim = sim.replace(net=net, tcp=tcp)
    sim, buf, _ = _enqueue_seg(sim, buf, spawned, child,
                            pf.TCPF_SYN | pf.TCPF_ACK,
                            jnp.zeros((H,), I32), 0, now)
    sim, buf = _arm_rtx(sim, buf, spawned, child, now)
    tcp, net = sim.tcp, sim.net
    # everything below operates on the matched socket only
    mask = mask & ~syn_to_listen
    st = gather_hs(tcp.st, slot)

    # ---- repeat SYN to a SYN_RCVD child: re-offer SYN|ACK ------------
    resyn = mask & (st == TcpSt.SYN_RCVD) & f_syn & ~f_ack
    sim = sim.replace(net=net, tcp=tcp)
    sim, buf, _ = _enqueue_seg(sim, buf, resyn, slot, pf.TCPF_SYN | pf.TCPF_ACK,
                            jnp.zeros((H,), I32), 0, now)
    tcp, net = sim.tcp, sim.net
    mask = mask & ~resyn

    # ---- SYN_SENT + SYN|ACK: complete active open --------------------
    synack = mask & (st == TcpSt.SYN_SENT) & f_syn & f_ack & (ack == 1)
    # a deferred close (tcp_close during the handshake) lands the
    # connection straight in FIN_WAIT_1
    est_st = jnp.where(gather_hs(tcp.fin_pending, slot),
                       TcpSt.FIN_WAIT_1, TcpSt.ESTABLISHED).astype(I32)
    tcp = _set(tcp, "st", synack, slot, est_st)
    tcp = _set(tcp, "rcv_nxt", synack, slot, seq + 1)
    tcp = _set(tcp, "snd_una", synack, slot, jnp.ones((H,), I32))
    tcp = _set(tcp, "snd_wnd", synack, slot, jnp.maximum(peer_win, MSS))
    tcp = _set(tcp, "ts_recent", synack, slot, tsval)
    tcp = _set(tcp, "backoff", synack, slot, jnp.zeros((H,), I32))
    tcp = _disarm_rtx(tcp, synack, slot)
    # establish raises WRITABLE through the helper so the out-gen edge
    # fires for ET EPOLLOUT watches armed during the handshake
    net = set_writable(net, synack, slot, True)
    sim = sim.replace(net=net, tcp=tcp)
    # the handshake-completing ACK and any buffered data ride the
    # merged flush + pure-ACK paths at the end of this function (one
    # inlined copy instead of one per trigger — compile-time matters)
    st = gather_hs(tcp.st, slot)

    # ---- ts_recent update (in-window segments) -----------------------
    inwin = mask & (seq <= gather_hs(tcp.rcv_nxt, slot))
    tcp = _set(tcp, "ts_recent", inwin & (tsval >= gather_hs(tcp.ts_recent, slot)),
               slot, tsval)

    # ---- SYN_RCVD + final ACK: ESTABLISHED + accept queue ------------
    # If the completing ACK races a (transiently) full accept queue,
    # the ACK is ignored: the child stays SYN_RCVD and its SYN|ACK
    # retransmit re-offers — never an orphaned ESTABLISHED child that
    # no accept() can reach.
    est_cand = mask & (st == TcpSt.SYN_RCVD) & f_ack & ~f_syn & (ack == 1)
    parent = gather_hs(tcp.parent, slot)
    queue_ok = est_cand & (parent >= 0) & (
        gather_hs(tcp.aq_count, parent) < ACCEPT_QUEUE)
    est_child = est_cand & (queue_ok | (parent < 0))
    tcp = _set(tcp, "st", est_child, slot,
               jnp.full((H,), TcpSt.ESTABLISHED, I32))
    tcp = _set(tcp, "snd_una", est_child, slot, jnp.ones((H,), I32))
    tcp = _set(tcp, "backoff", est_child, slot, jnp.zeros((H,), I32))
    tcp = _disarm_rtx(tcp, est_child, slot)
    pos = (gather_hs(tcp.aq_head, parent)
           + gather_hs(tcp.aq_count, parent)) % ACCEPT_QUEUE
    tcp = tcp.replace(aq=set_ring(tcp.aq, queue_ok, parent, pos,
                                  slot.astype(I32)))
    tcp = _set(tcp, "aq_count", queue_ok, parent,
               gather_hs(tcp.aq_count, parent) + 1)
    pfl = gather_hs(net.sk_flags, parent)
    net = net.replace(
        sk_flags=set_hs(net.sk_flags, queue_ok, parent,
                        pfl | SocketFlags.READABLE),
        # each newly queued child is an IN edge on the listener
        sk_in_gen=set_hs(net.sk_in_gen, queue_ok, parent,
                         gather_hs(net.sk_in_gen, parent) + 1),
    )
    st = gather_hs(tcp.st, slot)

    # ---- ACK processing (ref: tcp.c ACK path + tcp_cong_reno.c) ------
    conn = mask & f_ack & (st >= TcpSt.ESTABLISHED)
    una = gather_hs(tcp.snd_una, slot)
    nxt = gather_hs(tcp.snd_nxt, slot)
    wnd_prev = gather_hs(tcp.snd_wnd, slot)
    tcp = _set(tcp, "snd_wnd", conn, slot, peer_win)
    # scoreboard = the advertised SACK list (the receiver re-sends its
    # full parked set each ACK, so replacement == the reference's
    # tally merge, tcp_retransmit_tally.cc); an empty list clears it
    sack_l3 = jnp.stack(
        [sackl, words[:, pf.W_SACKL2], words[:, pf.W_SACKL3]], axis=1)
    sack_r3 = jnp.stack(
        [sackr, words[:, pf.W_SACKR2], words[:, pf.W_SACKR3]], axis=1)
    S_ = tcp.sack_l.shape[1]
    sel_sk = conn[:, None] & (jnp.arange(S_)[None, :] == slot[:, None])
    tcp = tcp.replace(
        sack_l=jnp.where(sel_sk[..., None], sack_l3[:, None, :], tcp.sack_l),
        sack_r=jnp.where(sel_sk[..., None], sack_r3[:, None, :], tcp.sack_r),
    )

    smax = gather_hs(tcp.snd_max, slot)
    new_ack = conn & (ack > una) & (ack <= smax)
    # an ACK above a rewound snd_nxt means those bytes arrived from the
    # pre-rewind transmission: jump forward, nothing to resend below it
    heal = new_ack & (ack > nxt)
    tcp = _set(tcp, "snd_nxt", heal, slot, ack)
    nxt = jnp.where(heal, ack, nxt)
    # a true duplicate ACK carries no data, no SYN/FIN, AND no window
    # update — window updates from a draining receiver must not feed
    # the fast-retransmit counter (RFC 5681 §2 dup-ACK definition)
    dup_ack = conn & (ack == una) & (una < nxt) & (length == 0) \
        & ~f_syn & ~f_fin & (peer_win == wnd_prev)

    # RTT sample (Karn-safe via timestamps, ref: tcp.c:991-1026)
    rtt = jnp.maximum(_ms(now) - tsecho, 1)
    srtt = gather_hs(tcp.srtt_ms, slot)
    rttvar = gather_hs(tcp.rttvar_ms, slot)
    first = new_ack & (srtt < 0)
    srtt_n = jnp.where(first, rtt, srtt + (rtt - srtt) // 8)
    rttvar_n = jnp.where(first, rtt // 2,
                         (3 * rttvar + jnp.abs(srtt - rtt)) // 4)
    rto_n = jnp.clip(srtt_n + jnp.maximum(4 * rttvar_n, 1),
                     RTO_MIN_MS, RTO_MAX_MS)
    tcp = _set(tcp, "srtt_ms", new_ack & (tsecho > 0), slot, srtt_n)
    tcp = _set(tcp, "rttvar_ms", new_ack & (tsecho > 0), slot, rttvar_n)
    tcp = _set(tcp, "rto_ms", new_ack & (tsecho > 0), slot, rto_n)
    tcp = _set(tcp, "backoff", new_ack, slot, jnp.zeros((H,), I32))

    # New-ack congestion hooks (ref: tcp_cong.h vtable; reno in
    # tcp_cong_reno.c — algorithm chosen by cfg.tcp_cong at build
    # time, see net/tcp_cong.py). The hooks are fed the NUMBER OF
    # PACKETS the ACK covers (ref: tcp.c:1710-1717 nPacketsAcked) —
    # essential under delayed-ACK coalescing, where one ACK may cover
    # many segments.
    alg = cfg.tcp_cong
    in_rec = gather_hs(tcp.in_recovery, slot)
    recover = gather_hs(tcp.recover, slot)
    cwnd = gather_hs(tcp.cwnd, slot)
    ssth = gather_hs(tcp.ssthresh, slot)
    ca = gather_hs(tcp.ca_acc, slot)
    n_acked = jnp.where(new_ack, (ack - una + MSS - 1) // MSS, 0)

    full_rec = new_ack & in_rec & (ack >= recover)
    partial = new_ack & in_rec & (ack < recover)
    normal = new_ack & ~in_rec

    # slow start (common to all algorithms): cwnd += n, spilling
    # leftover acks into congestion avoidance at ssthresh
    # (ref: ca_reno_slow_start_new_ack_ev_)
    ss = normal & (cwnd < ssth)
    grown = cwnd + n_acked
    spill = ss & (grown >= ssth)
    cwnd1 = jnp.where(ss, jnp.minimum(grown, ssth), cwnd)
    # leaving fast recovery deflates to ssthresh and continues in CA
    # with this ACK's packet count (ref: ca_reno_fast_recovery_new_ack_ev_)
    cwnd1 = jnp.where(full_rec, ssth, cwnd1)
    ca_in = jnp.where(spill, grown - ssth,
                      jnp.where(full_rec | (normal & ~ss), n_acked, 0))
    in_ca = (normal & ~ss) | spill | full_rec
    # transitions reset the CA accumulator (transition_to_cong_avoid)
    ca_base = jnp.where(spill | full_rec, 0, ca)
    cwnd1, ca1, epoch1 = cong.ca_update(
        alg, in_ca, cwnd1, jnp.where(in_ca, ca_base, ca), ca_in,
        gather_hs(tcp.cub_wmax, slot),
        gather_hs(tcp.cub_epoch_ms, slot), _ms(now))
    tcp = _set(tcp, "cwnd", new_ack, slot, cwnd1)
    tcp = _set(tcp, "ca_acc", new_ack, slot, ca1)
    tcp = _set(tcp, "cub_epoch_ms", in_ca, slot, epoch1)
    tcp = _set(tcp, "in_recovery", full_rec, slot, False)
    tcp = _set(tcp, "dup_acks", new_ack, slot, jnp.zeros((H,), I32))
    tcp = _set(tcp, "snd_una", new_ack, slot, ack)

    # ---- buffer autotuning (ref: tcp.c:407-592) ----------------------
    # Initial sizing on the FIRST RTT sample (ref: tcp.c:1007-1009):
    # bandwidth-delay product from the topology's true latencies and
    # the bottleneck of local and peer interface bandwidth, x1.25.
    lane = jnp.arange(H)
    from shadow_tpu.net.state import host_of_ip

    sample = new_ack & (tsecho > 0)
    at_init = sample & first & ~gather_hs(tcp.at_init_done, slot)
    peer_ip = gather_hs(net.sk_peer_ip, slot)
    self_ip = net.host_ip[net.lane_id]
    is_loop = (peer_ip == self_ip) | ((peer_ip >> 24) == 127)
    peer_h = host_of_ip(net, peer_ip)
    GHn = net.host_ip.shape[0]
    ph = jnp.clip(peer_h, 0, GHn - 1)
    vsrc = net.vertex_of_host[net.lane_id]
    vdst = net.vertex_of_host[ph]
    rtt_topo_ms = jnp.maximum(
        (net.latency_ns[vsrc, vdst] + net.latency_ns[vdst, vsrc])
        // simtime.ONE_MILLISECOND, 1)
    my_up = net.bw_up_kibps[net.lane_id]
    my_down = net.bw_down_kibps[net.lane_id]
    peer_up = net.bw_up_kibps[ph]
    peer_down = net.bw_down_kibps[ph]
    # KiBps * ms * 1.25 / 1000 -> bytes (the delay-bandwidth product)
    bdp_snd = rtt_topo_ms * jnp.minimum(my_up, peer_down) * 1280 // 1000
    bdp_rcv = rtt_topo_ms * jnp.minimum(my_down, peer_up) * 1280 // 1000
    init_snd = jnp.where(
        is_loop, TCP_WMEM_MAX,
        jnp.clip(bdp_snd, SEND_BUFFER_MIN, TCP_WMEM_MAX)).astype(I32)
    init_rcv = jnp.where(
        is_loop, TCP_RMEM_MAX,
        jnp.clip(bdp_rcv, RECV_BUFFER_MIN, TCP_RMEM_MAX)).astype(I32)
    net = net.replace(
        sk_sndbuf=set_hs(net.sk_sndbuf, at_init & net.autotune_snd, slot,
                         init_snd),
        sk_rcvbuf=set_hs(net.sk_rcvbuf, at_init & net.autotune_rcv, slot,
                         init_rcv),
    )
    tcp = _set(tcp, "at_init_done", at_init, slot, True)
    # Runtime send-buffer growth with cwnd (ref: _tcp_autotuneSendBuffer
    # tcp.c:566-592, called per data ACK, tcp.c:1715-1723). Grow-only.
    srtt_now = jnp.maximum(jnp.where(sample, srtt_n, srtt), 0).astype(I64)
    max_wmem = jnp.clip(my_up * 1024 * srtt_now // 1000,
                        TCP_WMEM_MAX, 10 * TCP_WMEM_MAX)
    want_snd = jnp.minimum(
        I64(SNDMEM_SKB) * 2 * cwnd1.astype(I64), max_wmem).astype(I32)
    cur_snd = gather_hs(net.sk_sndbuf, slot)
    net = net.replace(sk_sndbuf=set_hs(
        net.sk_sndbuf, new_ack & net.autotune_snd & (want_snd > cur_snd),
        slot, want_snd))
    # ACK progress reopened stream-buffer room: restore WRITABLE
    # (ref: descriptor_adjustStatus on buffer drain -> epoll wakeup)
    wroom = new_ack & (
        gather_hs(net.sk_sndbuf, slot)
        - (gather_hs(tcp.snd_end, slot) - ack) > 0)
    net = set_writable(net, wroom, slot, True)

    # dup-ack counting / fast retransmit (ref: the dup-ack hook,
    # tcp_cong.h; reno dupack_ev — ssthresh/entry cwnd come from the
    # configured algorithm)
    da = gather_hs(tcp.dup_acks, slot) + 1
    tcp = _set(tcp, "dup_acks", dup_ack, slot, da)
    enter_fr = dup_ack & (da == 3) & ~in_rec
    ssth_fr = cong.ssthresh_on_loss(alg, cwnd)
    tcp = _set(tcp, "ssthresh", enter_fr, slot, ssth_fr)
    tcp = _set(tcp, "cwnd", enter_fr, slot,
               cong.cwnd_on_recovery_entry(alg, ssth_fr))
    wmax1, ep1 = cong.on_loss_event(
        alg, enter_fr, cwnd, gather_hs(tcp.cub_wmax, slot),
        gather_hs(tcp.cub_epoch_ms, slot))
    tcp = _set(tcp, "cub_wmax", enter_fr, slot, wmax1)
    tcp = _set(tcp, "cub_epoch_ms", enter_fr, slot, ep1)
    tcp = _set(tcp, "in_recovery", enter_fr, slot, True)
    tcp = _set(tcp, "recover", enter_fr, slot, nxt)
    tcp = tcp.replace(fr_entries=tcp.fr_entries + enter_fr.astype(I64))
    # window inflation while in recovery (classic AIMD forgoes it)
    if alg != cong.AIMD:
        inflate = dup_ack & in_rec
        tcp = _set(tcp, "cwnd", inflate, slot,
                   gather_hs(tcp.cwnd, slot) + 1)

    sim = sim.replace(net=net, tcp=tcp)
    sim, buf, _, _ = _retransmit_one(cfg, sim, enter_fr | partial, slot, now, buf)
    tcp = sim.tcp

    # re-arm / disarm the RTO deadline after progress
    still_out = new_ack & (ack < smax)
    done = new_ack & (ack >= smax)
    rto_ns = gather_hs(tcp.rto_ms, slot).astype(I64) * simtime.ONE_MILLISECOND
    tcp = _set(tcp, "rtx_expire", still_out, slot, now + rto_ns)
    tcp = _disarm_rtx(tcp, done, slot)
    sim = sim.replace(tcp=tcp)

    # window may have opened (new_ack), a pure window-update ACK may
    # have reopened a closed window (the receiver-drain ACK a stalled
    # sender is waiting for — without this, resumption would wait for
    # the backed-off persist timer), or the connection just
    # established with buffered data (synack): push more data
    reopened = conn & (wnd_prev == 0) & (peer_win > 0)
    sim, buf = tcp_flush(cfg, sim, new_ack | synack | reopened, slot, now,
                         buf)
    tcp, net = sim.tcp, sim.net
    st = gather_hs(tcp.st, slot)

    # ---- ACK of our FIN: teardown transitions ------------------------
    smax2 = gather_hs(tcp.snd_max, slot)
    fin_ever = gather_hs(tcp.fin_pending, slot) & (
        smax2 == gather_hs(tcp.snd_end, slot) + 1)
    fin_acked = mask & f_ack & fin_ever & (ack == smax2)
    tcp = _set(tcp, "st", fin_acked & (st == TcpSt.FIN_WAIT_1), slot,
               jnp.full((H,), TcpSt.FIN_WAIT_2, I32))
    tcp = _set(tcp, "st", fin_acked & (st == TcpSt.CLOSING), slot,
               jnp.full((H,), TcpSt.TIME_WAIT, I32))
    closed_now = fin_acked & (st == TcpSt.LAST_ACK)
    sim = sim.replace(net=net, tcp=tcp)
    sim = _free_socket(cfg, sim, closed_now, slot)
    tcp, net = sim.tcp, sim.net
    # TIME_WAIT entered via CLOSING: arm the 60 s reaper
    tw1 = fin_acked & (st == TcpSt.CLOSING)
    w = jnp.zeros((H, NWORDS), I32).at[:, 0].set(slot.astype(I32))
    buf = emit(buf, tw1, net.lane_id, now + TIMEWAIT_NS,
               EventKind.TCP_CLOSE_TIMER, w)
    st = gather_hs(tcp.st, slot)

    # ---- inbound data (ref: tcp.c data path + unordered input) -------
    has_data = mask & (length > 0) & (
        (st == TcpSt.ESTABLISHED) | (st == TcpSt.FIN_WAIT_1)
        | (st == TcpSt.FIN_WAIT_2))
    rcv_nxt = gather_hs(tcp.rcv_nxt, slot)
    seg_end = seq + length
    old = has_data & (seg_end <= rcv_nxt)
    fresh = has_data & ~old

    # receive-buffer guard: drop segments that cannot be stored
    oo_bytes = jnp.sum(tcp.oo_r - tcp.oo_l, axis=2, dtype=I32)
    freeb = gather_hs(net.sk_rcvbuf, slot) - gather_hs(tcp.app_rbytes, slot) \
        - gather_hs(oo_bytes, slot)
    fits = fresh & (length <= freeb)
    tcp = tcp.replace(drop_rwin=tcp.drop_rwin + (fresh & ~fits).astype(I64))

    inorder = fits & (seq <= rcv_nxt)
    adv = jnp.where(inorder, seg_end - rcv_nxt, 0)
    rcv1 = rcv_nxt + adv
    rbytes = gather_hs(tcp.app_rbytes, slot) + adv
    # merge any reassembly range now contiguous (unrolled bounded scan)
    lane = jnp.arange(H)
    S = tcp.oo_l.shape[1]
    sc = jnp.clip(slot, 0, S - 1)
    for _ in range(OO_RANGES):
        ool = tcp.oo_l[lane, sc]      # [H, NR]
        oor = tcp.oo_r[lane, sc]
        hit = (ool <= rcv1[:, None]) & (oor > ool)     # contiguous/overlap
        take = jnp.any(hit & inorder[:, None], axis=1)
        pick = jnp.argmax(hit, axis=1)
        new_r = oor[lane, pick]
        gain = jnp.where(take & (new_r > rcv1), new_r - rcv1, 0)
        rcv1 = rcv1 + gain
        rbytes = rbytes + gain
        # clear consumed range
        tcp = tcp.replace(
            oo_l=set_ring(tcp.oo_l, take & inorder, slot, pick, 0),
            oo_r=set_ring(tcp.oo_r, take & inorder, slot, pick, 0),
        )
    tcp = _set(tcp, "rcv_nxt", inorder, slot, rcv1)
    tcp = _set(tcp, "app_rbytes", inorder, slot, rbytes)

    # out-of-order: park [seq, seg_end) in a reassembly range
    ooseg = fits & (seq > rcv_nxt)
    ool = tcp.oo_l[lane, sc]
    oor = tcp.oo_r[lane, sc]
    overlap = (seq[:, None] <= oor) & (seg_end[:, None] >= ool) & (oor > ool)
    mergeable = jnp.any(overlap, axis=1)
    mpick = jnp.argmax(overlap, axis=1)
    empty_rng = oor <= ool
    has_empty = jnp.any(empty_rng, axis=1)
    epick = jnp.argmax(empty_rng, axis=1)
    do_merge = ooseg & mergeable
    do_new = ooseg & ~mergeable & has_empty
    dropped_oo = ooseg & ~mergeable & ~has_empty
    tcp = tcp.replace(drop_oo_full=tcp.drop_oo_full + dropped_oo.astype(I64))
    pick = jnp.where(do_merge, mpick, epick)
    nl = jnp.where(do_merge, jnp.minimum(ool[lane, pick], seq), seq)
    nr = jnp.where(do_merge, jnp.maximum(oor[lane, pick], seg_end), seg_end)
    tcp = tcp.replace(
        oo_l=set_ring(tcp.oo_l, do_merge | do_new, slot, pick, nl),
        oo_r=set_ring(tcp.oo_r, do_merge | do_new, slot, pick, nr),
    )

    # readable status for the app (epoll analog); each in-order
    # arrival is an edge for ET watches
    readable = inorder & (gather_hs(tcp.app_rbytes, slot) > 0)
    fl = gather_hs(net.sk_flags, slot)
    net = net.replace(
        sk_flags=set_hs(net.sk_flags, readable, slot,
                        fl | SocketFlags.READABLE),
        sk_in_gen=set_hs(net.sk_in_gen, readable, slot,
                         gather_hs(net.sk_in_gen, slot) + 1),
    )

    # ---- peer FIN (ref: tcp.c FIN processing) ------------------------
    fin_seen = mask & f_fin & (st >= TcpSt.ESTABLISHED) & (
        st != TcpSt.TIME_WAIT)
    tcp = _set(tcp, "fin_rcvd", fin_seen, slot, True)
    tcp = _set(tcp, "fin_rseq", fin_seen, slot, seg_end)
    # consume the FIN only when all data before it has arrived
    rn = gather_hs(tcp.rcv_nxt, slot)
    fin_now = mask & gather_hs(tcp.fin_rcvd, slot) & (
        rn == gather_hs(tcp.fin_rseq, slot)) & (
        st != TcpSt.TIME_WAIT) & (st >= TcpSt.ESTABLISHED)
    tcp = _set(tcp, "rcv_nxt", fin_now, slot, rn + 1)
    to_close_wait = fin_now & (st == TcpSt.ESTABLISHED)
    to_closing = fin_now & (st == TcpSt.FIN_WAIT_1)
    to_timewait = fin_now & (st == TcpSt.FIN_WAIT_2)
    tcp = _set(tcp, "st", to_close_wait, slot,
               jnp.full((H,), TcpSt.CLOSE_WAIT, I32))
    tcp = _set(tcp, "st", to_closing, slot, jnp.full((H,), TcpSt.CLOSING, I32))
    tcp = _set(tcp, "st", to_timewait, slot,
               jnp.full((H,), TcpSt.TIME_WAIT, I32))
    buf = emit(buf, to_timewait, net.lane_id, now + TIMEWAIT_NS,
               EventKind.TCP_CLOSE_TIMER, w)
    # EOF is app-visible readability (recv returns 0)
    fl = gather_hs(net.sk_flags, slot)
    net = net.replace(
        sk_flags=set_hs(net.sk_flags, fin_now, slot,
                        fl | SocketFlags.READABLE),
        sk_in_gen=set_hs(net.sk_in_gen, fin_now, slot,
                         gather_hs(net.sk_in_gen, slot) + 1),
    )

    # ---- ACK generation (ref: tcp.c:2050-2091) -----------------------
    # Loss-signalling ACKs (old/out-of-order/dropped data -> dup ACKs
    # with SACK) and handshake ACKs go out immediately; plain ACKs for
    # in-order data (and the FIN's ACK) coalesce behind one scheduled
    # delayed-ACK send — 1 ms while the connection's first
    # DACK_QUICK_LIMIT "quick ACKs" last, then 5 ms. resynack: a
    # SYN|ACK retransmitted to an already-ESTABLISHED peer (its
    # completing ACK was dropped by a then-full accept backlog)
    # elicits an immediate pure ACK — RFC 793 out-of-window behavior —
    # so the handshake retries even on a dataless connection.
    resynack = mask & f_syn & f_ack & (st >= TcpSt.ESTABLISHED)
    ooseg_ack = fits & (seq > rcv_nxt)
    dropped_ack = fresh & ~fits
    alive = st != TcpSt.CLOSED
    immediate = (old | ooseg_ack | dropped_ack | synack | resynack) & alive
    delayed = (inorder | fin_now) & ~immediate & alive
    sim = sim.replace(net=net, tcp=tcp)
    sim, buf, _ = _enqueue_seg(sim, buf, immediate, slot, pf.TCPF_ACK,
                            gather_hs(tcp.snd_nxt, slot), 0, now)
    tcp = sim.tcp
    cnt = gather_hs(tcp.dack_counter, slot) + 1
    tcp = _set(tcp, "dack_counter", delayed, slot, cnt)
    sched = delayed & ~gather_hs(tcp.dack_scheduled, slot)
    nq = gather_hs(tcp.quick_acks, slot)
    quick = nq < DACK_QUICK_LIMIT
    delay = jnp.where(quick, DACK_QUICK_NS, DACK_SLOW_NS)
    tcp = _set(tcp, "quick_acks", sched & quick, slot, nq + 1)
    tcp = _set(tcp, "dack_scheduled", sched, slot, True)
    dw = (jnp.zeros((H, NWORDS), I32)
          .at[:, 0].set(slot.astype(I32))
          .at[:, 1].set(gather_hs(tcp.dack_gen, slot)))
    buf = emit(buf, sched, sim.net.lane_id, now + delay,
               EventKind.TCP_DACK_TIMER, dw)
    return sim.replace(tcp=tcp), buf


# ---------------------------------------------------------------------
# timer event handlers
# ---------------------------------------------------------------------

def handle_tcp_rtx(cfg: NetConfig, sim, popped, buf):
    """kind=TCP_RTX_TIMER (ref: retransmit timer + exponential backoff,
    tcp.c:1280-...). The single in-flight event per socket re-arms
    itself while the deadline keeps moving."""
    if sim.tcp is None:
        return sim, buf
    mask = popped.valid & (popped.kind == EventKind.TCP_RTX_TIMER)
    slot = popped.word(0)
    egen = popped.word(1)
    now = popped.time
    tcp = sim.tcp
    H = mask.shape[0]

    # superseded events (generation mismatch) die silently — a newer
    # event with an earlier deadline has replaced them
    mask = mask & (egen == gather_hs(tcp.rtx_gen, slot))
    deadline = gather_hs(tcp.rtx_expire, slot)
    disarmed = mask & (deadline == simtime.INVALID)
    pending = mask & ~disarmed & (now < deadline)
    due = mask & ~disarmed & ~pending

    # the in-flight event dies unless re-emitted
    tcp = _set(tcp, "rtx_event", disarmed, slot, False)
    w = (jnp.zeros((H, NWORDS), I32)
         .at[:, 0].set(slot.astype(I32))
         .at[:, 1].set(egen))
    buf = emit(buf, pending, sim.net.lane_id, deadline,
               EventKind.TCP_RTX_TIMER, w)
    tcp = _set(tcp, "rtx_fire", pending, slot, deadline)

    # timeout: collapse to slow start and go back to snd_una
    # (ref: reno timeout_ev + _tcp_retransmitTimerExpired)
    una = gather_hs(tcp.snd_una, slot)
    nxt = gather_hs(tcp.snd_nxt, slot)
    live = due & (una < nxt)

    # persist expiry: zero window, data waiting, nothing in flight —
    # send one byte past the window; its (dup-)ACK re-reveals the
    # peer's window. Backoff caps the probe rate.
    probe = due & (una == nxt) & (gather_hs(tcp.snd_end, slot) > nxt) \
        & (gather_hs(tcp.snd_wnd, slot) == 0)
    sim2 = sim.replace(tcp=tcp)
    sim2, buf, psent = _enqueue_seg(sim2, buf, probe, slot, pf.TCPF_ACK,
                                    nxt, 1, now)
    tcp = sim2.tcp
    tcp = _set(tcp, "snd_nxt", psent, slot, nxt + 1)
    tcp = _set(tcp, "snd_max", psent, slot,
               jnp.maximum(gather_hs(tcp.snd_max, slot), nxt + 1))
    tcp = _set(tcp, "backoff", psent, slot,
               jnp.minimum(gather_hs(tcp.backoff, slot) + 1, MAX_BACKOFF))
    tcp = tcp.replace(probes_sent=tcp.probes_sent + psent.astype(I64))
    sim = sim2.replace(tcp=tcp)
    # (the due-lane disarm below clears this fire's event; the final
    # _arm_rtx re-arms both the loss retransmit and the probe)
    cwnd = gather_hs(tcp.cwnd, slot)
    # timeout hook (ref: reno timeout_ev): ssthresh from the
    # configured algorithm, restart from RESTART_CWND
    tcp = _set(tcp, "ssthresh", live, slot,
               cong.ssthresh_on_loss(cfg.tcp_cong, cwnd))
    tcp = _set(tcp, "cwnd", live, slot,
               jnp.full((H,), RESTART_CWND, I32))
    wmax_t, ep_t = cong.on_loss_event(
        cfg.tcp_cong, live, cwnd, gather_hs(tcp.cub_wmax, slot),
        gather_hs(tcp.cub_epoch_ms, slot))
    tcp = _set(tcp, "cub_wmax", live, slot, wmax_t)
    tcp = _set(tcp, "cub_epoch_ms", live, slot, ep_t)
    tcp = _set(tcp, "ca_acc", live, slot, jnp.zeros((H,), I32))
    tcp = _set(tcp, "in_recovery", live, slot, False)
    tcp = _set(tcp, "dup_acks", live, slot, jnp.zeros((H,), I32))
    tcp = _set(tcp, "backoff", live, slot,
               jnp.minimum(gather_hs(tcp.backoff, slot) + 1, MAX_BACKOFF))
    tcp = _set(tcp, "rtx_event", due, slot, False)
    tcp = _disarm_rtx(tcp, due, slot)
    sim = sim.replace(tcp=tcp)
    sim, buf, _, resent_end = _retransmit_one(cfg, sim, live, slot, now, buf)
    # go-back-N: snd_nxt rewinds to just past the retransmitted
    # segment (as actually sent, including any SACK clip); later ACK
    # arrivals flush the rest of the range again.
    tcp = sim.tcp
    rewind = live & (resent_end < nxt)
    tcp = _set(tcp, "snd_nxt", rewind, slot, resent_end)
    sim = sim.replace(tcp=tcp)
    sim, buf = _arm_rtx(sim, buf, live | probe, slot, now)
    return sim, buf


def handle_tcp_flush(cfg: NetConfig, sim, popped, buf):
    """kind=TCP_FLUSH: continue packetizing admissible stream data
    (the unwound remainder of one logical _tcp_flush call)."""
    if sim.tcp is None:
        return sim, buf
    mask = popped.valid & (popped.kind == EventKind.TCP_FLUSH)
    slot = popped.word(0)
    tcp = _set(sim.tcp, "flush_pending", mask, slot, False)
    sim = sim.replace(tcp=tcp)
    return tcp_flush(cfg, sim, mask, slot, popped.time, buf)


def handle_tcp_dack(cfg: NetConfig, sim, popped, buf):
    """kind=TCP_DACK_TIMER: the delayed-ACK send task (ref:
    _tcp_sendACKTaskCallback, tcp.c:1767-1775): clear the scheduled
    flag and send one pure ACK if any ACK-worthy arrival is still
    unacknowledged (a departing ACK-carrying packet zeroes the counter
    at wire time, cancelling us)."""
    if sim.tcp is None:
        return sim, buf
    mask = popped.valid & (popped.kind == EventKind.TCP_DACK_TIMER)
    slot = popped.word(0)
    egen = popped.word(1)
    now = popped.time
    tcp = sim.tcp
    # stale events for recycled slots die on generation mismatch
    mask = mask & (egen == gather_hs(tcp.dack_gen, slot))
    tcp = _set(tcp, "dack_scheduled", mask, slot, False)
    fire = mask & (gather_hs(tcp.dack_counter, slot) > 0)
    tcp = _set(tcp, "dack_counter", fire, slot, jnp.zeros(mask.shape, I32))
    sim = sim.replace(tcp=tcp)
    sim, buf, _ = _enqueue_seg(sim, buf, fire, slot, pf.TCPF_ACK,
                               gather_hs(tcp.snd_nxt, slot), 0, now)
    return sim, buf


def wire_ack_departed(tcp: TcpState, mask, slot):
    """A packet carrying an ACK just hit the wire for (lane, slot):
    cancel any pending delayed ACK (ref: tcp.c:1105-1108 resets
    delayedACKCounter whenever an outgoing header has ACK set).
    Called by the NIC send drain after stamp_at_wire."""
    return _set(tcp, "dack_counter", mask, slot,
                jnp.zeros(mask.shape, I32))


def handle_tcp_close(cfg: NetConfig, sim, popped, buf):
    """kind=TCP_CLOSE_TIMER: the TIME_WAIT reaper (ref: 60 s close
    timer, tcp.c:604-699)."""
    if sim.tcp is None:
        return sim, buf
    mask = popped.valid & (popped.kind == EventKind.TCP_CLOSE_TIMER)
    slot = popped.word(0)
    st = gather_hs(sim.tcp.st, slot)
    reap = mask & (st == TcpSt.TIME_WAIT)
    return _free_socket(cfg, sim, reap, slot), buf
