"""Composes the per-micro-step handler pipeline for the engine.

The reference dispatches events through arbitrary Task closures
(ref: task.c, event.c:65-93); here the dispatch is a fixed sequence of
masked batch handlers — every handler sees all H popped events and
acts only on lanes whose kind matches. Handlers touch disjoint state
per lane (one event per host per micro-step), so composition order
does not affect results; app handlers run after the netstack so they
observe updated socket state within the same micro-step.
"""

from __future__ import annotations

from typing import Callable, Sequence

from shadow_tpu.net import nic, tcp, timers
from shadow_tpu.net.state import NetConfig

AppHandler = Callable  # (cfg, sim, popped, buf) -> (sim, buf)

# Receive side runs first so app handlers observe freshly delivered
# data; the send drain runs LAST so packets enqueued anywhere in this
# micro-step (TCP ACKs, app replies) hit the wire without a same-time
# event round-trip (the nic_send_now fusion).
_PRE_APP = (
    nic.handle_nic_recv,       # PACKET + NIC_RECV + PACKET_LOCAL, fused
    timers.handle_timer,
    tcp.handle_tcp_rtx,
    tcp.handle_tcp_close,
)
_POST_APP = (
    nic.handle_nic_send,       # NIC_SEND + fused nic_send_now drain
)


def make_step_fn(cfg: NetConfig, app_handlers: Sequence[AppHandler] = ()):
    """Build the engine step_fn: netstack receive/timer handlers, then
    app handlers, then the send drain. TCP timer handlers are included
    only when the config carries TCP state (cfg.tcp) — UDP-only device
    programs stay small."""
    pre = _PRE_APP if cfg.tcp else tuple(
        h for h in _PRE_APP
        if h not in (tcp.handle_tcp_rtx, tcp.handle_tcp_close))

    def step(sim, popped, buf):
        for h in pre:
            sim, buf = h(cfg, sim, popped, buf)
        for h in app_handlers:
            sim, buf = h(cfg, sim, popped, buf)
        for h in _POST_APP:
            sim, buf = h(cfg, sim, popped, buf)
        return sim, buf

    return step
