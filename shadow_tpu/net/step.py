"""Composes the per-micro-step handler pipeline for the engine.

The reference dispatches events through arbitrary Task closures
(ref: task.c, event.c:65-93); here the dispatch is a fixed sequence of
masked batch handlers — every handler sees all H popped events and
acts only on lanes whose kind matches. Handlers touch disjoint state
per lane (one event per host per micro-step), so composition order
does not affect results; app handlers run after the netstack so they
observe updated socket state within the same micro-step.
"""

from __future__ import annotations

from typing import Callable, Sequence

from shadow_tpu.core.events import EventKind
from shadow_tpu.net import nic, tcp, timers
from shadow_tpu.net.state import NetConfig

AppHandler = Callable  # (cfg, sim, popped, buf) -> (sim, buf)

# Receive side runs first so app handlers observe freshly delivered
# data; the send drain runs LAST so packets enqueued anywhere in this
# micro-step (TCP ACKs, app replies) hit the wire without a same-time
# event round-trip (the nic_send_now fusion).
#
# Each netstack handler is paired with the event kinds it acts on:
# the pipeline wraps it in lax.cond so a micro-step where NO lane
# popped a matching kind skips the handler's whole subgraph (each
# handler is a masked batch update — all-false mask == identity — so
# skipping is value-identical and saves the execution cost; the TCP
# receive machine inside handle_nic_recv is by far the largest).
_PRE_APP = (
    (nic.handle_nic_recv, (EventKind.PACKET, EventKind.NIC_RECV,
                           EventKind.PACKET_LOCAL)),
    (timers.handle_timer, (EventKind.TIMER,)),
    (tcp.handle_tcp_rtx, (EventKind.TCP_RTX_TIMER,)),
    (tcp.handle_tcp_dack, (EventKind.TCP_DACK_TIMER,)),
    (tcp.handle_tcp_flush, (EventKind.TCP_FLUSH,)),
    (tcp.handle_tcp_close, (EventKind.TCP_CLOSE_TIMER,)),
)
_TCP_HANDLERS = (tcp.handle_tcp_rtx, tcp.handle_tcp_dack,
                 tcp.handle_tcp_flush, tcp.handle_tcp_close)


def _kind_pred(popped, kinds):
    import jax.numpy as jnp

    m = popped.valid & (popped.kind == kinds[0])
    for k in kinds[1:]:
        m = m | (popped.valid & (popped.kind == k))
    return jnp.any(m)


def _family_pred(census, popped, kinds):
    """Handler-family gate. With a window kind census (sparse-window
    layer 2) the scalar bit test short-circuits the whole family for
    every micro-step of a window whose census lacks the kinds — the
    per-micro-step popped-vector test only refines it within windows
    where the family is live. The census may over-approximate (bit 31
    is shared by kinds >= 31; emissions widen it), which is safe:
    handlers are masked batch updates, so a gate that opens onto an
    all-false mask is the identity."""
    import jax.numpy as jnp

    from shadow_tpu.core.events import census_mask

    p = _kind_pred(popped, kinds)
    if census is None:
        return p
    hot = (census & jnp.uint32(census_mask(kinds))) != 0
    return hot & p


def _cpu_gate(cfg: NetConfig, sim, popped, buf):
    """Virtual-CPU admission check (ref: event_execute, event.c:71-89
    + cpu.c:56-110): a host whose accumulated processing delay exceeds
    the threshold does not execute this event — it is rescheduled at
    now + delay with a fresh identity (the reference's
    worker_scheduleTask re-queue). Executed events charge the host's
    per-event cost against its CPU availability time."""
    import jax.numpy as jnp

    from shadow_tpu.core.events import push_rows

    net = sim.net
    # cpu_updateTime: availability never lags the present
    avail = jnp.maximum(net.cpu_avail, popped.time)
    delay = avail - popped.time
    blocked = popped.valid & (delay > cfg.cpu_threshold_ns)
    # re-queue the event at now + delay, PRESERVING its identity
    # (src/seq/words — the reference re-schedules the same task with
    # its original closure arguments)
    sim = sim.replace(events=push_rows(
        sim.events, blocked, popped.time + delay, popped.kind,
        popped.src, popped.seq, popped.words))
    executed = popped.valid & ~blocked
    net = net.replace(
        cpu_avail=jnp.where(executed, avail + net.cpu_cost,
                            jnp.where(popped.valid, avail, net.cpu_avail)),
        ctr_cpu_blocked=net.ctr_cpu_blocked
        + blocked.astype(jnp.int64),
        ctr_cpu_delay_ns=net.ctr_cpu_delay_ns
        + jnp.where(blocked, delay, 0),
    )
    return sim.replace(net=net), popped._replace(valid=executed), buf


def _handle_proc_stop(cfg: NetConfig, sim, popped, buf):
    """PROC_STOP enforcement (ref: _process_runStopTask -> process_stop,
    process.c:1286-1324): latch the host's stopped flag; app handlers
    are masked off for this and all later events."""
    import jax.numpy as jnp

    from shadow_tpu.core.events import EventKind

    stop = popped.valid & (popped.kind == EventKind.PROC_STOP)
    net = sim.net
    return sim.replace(net=net.replace(
        proc_stopped=net.proc_stopped | stop)), buf


def make_step_fn(cfg: NetConfig, app_handlers: Sequence[AppHandler] = (),
                 caps=None):
    """Build the engine step_fn: netstack receive/timer handlers, then
    app handlers, then the send drain. TCP timer handlers are included
    only when the config carries TCP state (cfg.tcp) — UDP-only device
    programs stay small. A non-negative cfg.cpu_threshold_ns inserts
    the virtual-CPU admission gate ahead of everything.

    `caps` (compile/specialize.py Capabilities, None = full program)
    statically trims provably-dead subgraphs instead of runtime-gating
    them: a dropped timers capability OMITS the timer handler family
    from the trace entirely, and the send drain skips the Bernoulli
    loss draw (see _drain_one). Bit-identical wherever the
    capabilities hold; the per-window guard latch (engine.step_window)
    converts a violation into a fatal health fault."""
    import jax
    import jax.numpy as jnp

    pre = _PRE_APP if cfg.tcp else tuple(
        (h, k) for h, k in _PRE_APP if h not in _TCP_HANDLERS)
    if caps is not None and not caps.timers:
        # statically-dead family: no handler can ever arm a host timer
        # (specialize.derive) — omitting it is the identity, and the
        # guard latch trips fatally if a TIMER appears anyway
        pre = tuple((h, k) for h, k in pre if h is not timers.handle_timer)
    cpu_on = cfg.cpu_threshold_ns >= 0

    def step(sim, popped, buf, census=None):
        if cpu_on:
            sim, popped, buf = _cpu_gate(cfg, sim, popped, buf)
        sim, buf = _handle_proc_stop(cfg, sim, popped, buf)
        for h, kinds in pre:
            sim, buf = jax.lax.cond(
                _family_pred(census, popped, kinds),
                lambda op, h=h: h(cfg, op[0], popped, op[1]),
                lambda op: op,
                (sim, buf))
        # a stopped host's app no longer sees events (the plugin is
        # dead); the netstack handlers above still ran for it
        app_popped = popped._replace(
            valid=popped.valid & ~sim.net.proc_stopped)
        if app_handlers:
            # app handlers are masked batch updates under the same
            # contract as the netstack (all-false == identity), so a
            # micro-step where every popped lane was CPU-deferred or
            # belongs to a stopped host skips the app subgraph whole
            def _apps(op):
                s, b = op
                for h in app_handlers:
                    s, b = h(cfg, s, app_popped, b)
                return s, b

            sim, buf = jax.lax.cond(
                jnp.any(app_popped.valid), _apps, lambda op: op,
                (sim, buf))
        # the send drain also serves lanes whose nic_send_now bit was
        # set by handlers above, not just popped NIC_SEND events
        send_pred = _kind_pred(popped, (EventKind.NIC_SEND,)) \
            | jnp.any(sim.net.nic_send_now)
        sim, buf = jax.lax.cond(
            send_pred,
            lambda op: nic.handle_nic_send(cfg, op[0], popped, op[1],
                                           caps=caps),
            lambda op: op,
            (sim, buf))
        # per-host executed-event accounting (the device analog of the
        # reference's per-host execution timer, host.c:314-317);
        # popped.valid is post-CPU-gate, so deferred events count once
        sim = sim.replace(net=sim.net.replace(
            ctr_events_exec=sim.net.ctr_events_exec
            + popped.valid.astype(jnp.int64)))
        return sim, buf

    return step
