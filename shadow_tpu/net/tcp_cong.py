"""Pluggable congestion control — the device form of the reference's
hook vtable (ref: tcp_cong.h:10-32 {duplicate_ack_ev, fast_recovery,
new_ack_ev, timeout_ev, ssthresh} + {cwnd, ca} state, designed for
aimd/reno/cubic with only reno implemented there; tcp_cong_reno.c).

Here an algorithm is a namespace of pure masked-update functions
chosen at build time by NetConfig.tcp_cong (one algorithm per run —
the vtable's per-socket indirection costs nothing to add later since
dispatch is a trace-time Python branch). The recovery MECHANICS
(dup-ack counting, recovery point, partial-ack retransmit, window
inflation) stay in tcp.py exactly as the reference keeps them in
tcp.c; the hooks only decide cwnd/ssthresh arithmetic:

- reno  (ref: tcp_cong_reno.c): slow start cwnd+=1/ACK; CA +1 per
  cwnd of acked packets; loss ssthresh = cwnd/2+1, enter recovery at
  ssthresh+3 with dup-ack inflation.
- aimd: classic AIMD — same slow start/CA, but recovery entry
  deflates straight to ssthresh (no +3 or inflation credit).
- cubic: concave/convex window curve W(t) = C*(t-K)^3 + W_max with
  C=0.4, beta=0.7 (RFC 9438 shapes, packet units; the TCP-friendly
  region and HyStart are omitted — documented deviation). Growth per
  ACK is clamped to the acked-packet count, so the curve is chased at
  most one packet per delivered packet.

All cubic arithmetic is f32-on-device; runs are deterministic per
platform (like the reference's doubles).
"""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32
F32 = jnp.float32

RENO = 0
AIMD = 1
CUBIC = 2

NAMES = {"reno": RENO, "aimd": AIMD, "cubic": CUBIC}

CUBIC_C = 0.4
CUBIC_BETA = 0.7


def ssthresh_on_loss(alg: int, cwnd):
    """New ssthresh when loss is detected (fast-recovery entry and
    RTO timeout; ref: reno ssthresh_halve = cwnd/2+1)."""
    if alg == CUBIC:
        return jnp.maximum((cwnd.astype(F32) * CUBIC_BETA).astype(I32), 2)
    return cwnd // 2 + 1


def cwnd_on_recovery_entry(alg: int, ssth):
    """cwnd on entering fast recovery (ref: reno fast_recovery:
    ssthresh + 3 dup-acked segments)."""
    if alg == AIMD:
        return ssth
    return ssth + 3


def ca_update(alg: int, mask, cwnd, ca_acc, n_acked, cub_wmax,
              cub_epoch_ms, now_ms):
    """Congestion-avoidance growth for ACKs covering n_acked packets.
    Returns (cwnd', ca_acc', cub_epoch_ms') — only `mask` lanes
    change. For reno/aimd this is the accumulator form of +1 cwnd per
    full window acked (ref: ca_reno_cong_avoid_new_ack_ev_); cubic
    chases its time-based curve instead."""
    if alg in (RENO, AIMD):
        ca1 = ca_acc + jnp.where(mask, n_acked, 0)
        cwnd1 = cwnd
        for _ in range(4):
            inc = mask & (ca1 >= cwnd1)
            ca1 = jnp.where(inc, ca1 - cwnd1, ca1)
            cwnd1 = jnp.where(inc, cwnd1 + 1, cwnd1)
        return cwnd1, ca1, cub_epoch_ms

    # ---- cubic ------------------------------------------------------
    # epoch starts at the first CA ack after a loss (epoch_ms < 0)
    fresh = mask & (cub_epoch_ms < 0)
    epoch = jnp.where(fresh, now_ms, cub_epoch_ms)
    wmax = jnp.maximum(cub_wmax, 2).astype(F32)
    # K = cbrt(W_max * (1-beta) / C) seconds
    k_s = jnp.cbrt(wmax * (1.0 - CUBIC_BETA) / CUBIC_C)
    t_s = jnp.maximum(now_ms - epoch, 0).astype(F32) / 1000.0
    target = CUBIC_C * (t_s - k_s) ** 3 + wmax
    target_i = jnp.maximum(target, 2.0).astype(I32)
    # chase the curve, at most one packet per acked packet, never shrink
    cwnd1 = jnp.clip(target_i, cwnd, cwnd + n_acked)
    cwnd1 = jnp.where(mask, cwnd1, cwnd)
    return cwnd1, ca_acc, jnp.where(mask, epoch, cub_epoch_ms)


def on_loss_event(alg: int, mask, cwnd, cub_wmax, cub_epoch_ms):
    """Algorithm state updates shared by fast-recovery entry and RTO
    (cubic records W_max and resets its epoch; reno/aimd keep no
    extra state). Returns (cub_wmax', cub_epoch_ms')."""
    if alg != CUBIC:
        return cub_wmax, cub_epoch_ms
    return (jnp.where(mask, cwnd, cub_wmax),
            jnp.where(mask, -1, cub_epoch_ms))
