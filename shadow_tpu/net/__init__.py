from shadow_tpu.net.state import NetState, NetConfig, SocketType, SocketFlags
from shadow_tpu.net.step import make_step_fn
