"""Bulk window pass: process a host's whole window of UDP packet
arrivals in ONE vectorized pass instead of one micro-step per event.

This is SURVEY.md §7.2's sort+segment design with a backend-adaptive
order representation (EventOrder): on accelerators, order-dependent
quantities are masked [H,K,K] compare-reduce sums (zero sorts, zero
gathers, zero scatters — on TPU those three lower to serial
element-at-a-time loops and dominated the pass; the fused cube
reduces run at HBM bandwidth); on the CPU fallback, one per-row
lexsort gives O(K log K) ranks/prefix-sums in [H,K] working memory
(the cube blows the cache at 100k hosts). Both are bit-identical.
The token-bucket evolution — a chain of refill-then-consume steps
f_i(x) = min(cap, x + dq_i*refill) - w_i — telescopes into the
closed form

    F(s0) = min(s0 + (q_K - q_0)*refill - sum(w),
                min_i [cap - w_i + (q_K - q_i)*refill - suffw_i])

because min-affine maps compose associatively (each f is
x -> min(m, x + c)).

Semantics contract: for every ELIGIBLE host, the final device state is
bit-identical to what the serial micro-step engine (engine.py +
nic.py's fused arrival->router->deliver->app->wire chain) would
produce — the golden test in tests/test_bulk.py runs both paths and
compares. Hosts that fail eligibility are left untouched; the serial
window fixpoint that runs right after naturally picks them up
(their in-window events are still queued).

Eligibility (per host) — the conditions under which the serial path's
per-event work is provably independent across the window:

- every in-window event is a remote UDP PACKET arrival
  (timers / process events / TCP / loopback -> serial path);
- the NIC is quiescent: router ring empty, no deferred NIC_RECV or
  NIC_SEND events in flight, socket rings empty;
- CoDel is in its idle good state (interval_expire == 0, not
  dropping) — then every dequeue has sojourn 0 and provably leaves
  the CoDel state untouched (ref: router_queue_codel.c:161-196);
- token buckets, projected by ONE analytic refill to the window's
  first in-window arrival (exactly the serial path's level at its
  first pull), cover the whole window's wire bytes without relying
  on further mid-window refills, so the serial drain never defers
  (ref: network_interface.c:421-455,519-579);
- the app's bulk handler accepts the host (precheck) and its sends
  fit the send buffer without tripping the transient-full WRITABLE
  clear in sk_enqueue_out.

Reference mapping: this is the device analog of running the per-host
pop loop (scheduler_policy_host_single.c:237-267) to completion for
the window with the event.c:110-153 order, exploiting that the
handlers the events reach (UDP deliver + app recv/send + NIC drain)
commute up to the state deltas reproduced here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.events import EventKind, EventQueue, _tie_key
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net.state import ip_of_hosts
from shadow_tpu.net.state import (
    TB_REFILL_INTERVAL,
    NetConfig,
    QDisc,
    RouterQ,
    SocketFlags,
    SocketType,
    host_of_ip,
)

I32 = jnp.int32
I64 = jnp.int64


@dataclass(frozen=True)
class EventOrder:
    """Per-row total order over the window's event slots under the
    deterministic comparator (time, then (src, seq) tie key — the
    reference's event.c:110-153; dst is the row), in one of two
    bit-identical representations chosen per backend:

    - "cube": prec[h, j, k] = slot j strictly precedes slot k. All
      order-dependent quantities become masked [H,K,K] compare-reduce
      sums — pure elementwise+reduction work that the TPU executes at
      HBM bandwidth. Measured on a v5e: the sort representation costs
      ~50 ms per window at H=10k (XLA row sort) plus ~5 ms per
      take_along_axis, because XLA lowers composed gathers to a
      serial element-at-a-time loop (~0.1 elem/ns); the K² cube costs
      ~0.2 ms per reduce with zero gathers.
    - "sort": perm[h, p] = slot at ascending position p, inv = its
      inverse; ranks/suffix sums via cumsum in sorted space + two
      take_along_axis. O(K log K) work — the right shape for the CPU
      fallback, where gathers are cheap and a K² cube at 100k hosts
      would blow the cache (this was this module's original form).

    Ties in (time, tie) occur only between INVALID/stale slots; the
    slot index breaks them (the "sort" path's stable lexsort does the
    same), keeping both representations exact permutations.
    """

    prec: Any = None   # [H,K,K] bool (cube) or None
    perm: Any = None   # [H,K] i32 (sort) or None
    inv: Any = None    # [H,K] i32 (sort) or None

    def _sorted(self, value):
        return jnp.take_along_axis(value, self.perm, axis=1)

    def _unsorted(self, value):
        return jnp.take_along_axis(value, self.inv, axis=1)


# Above this many prec-cube elements (H*K*K) fall back to the sort
# representation: at 100k hosts x K=64 the cube is ~410M entries —
# fine as a fused TPU reduce, hostile to a CPU cache. The budget is
# sized so every bench/scale shape up to 100k x K<=96 stays on the
# cube when on an accelerator. On CPU the sort form always wins
# (measured: the cube halved the 1024-host CPU bench).
CUBE_BUDGET_ACCEL = 1_000_000_000


def _default_impl(H: int, K: int) -> str:
    import jax

    if jax.default_backend() == "cpu":
        return "sort"
    return "cube" if H * K * K <= CUBE_BUDGET_ACCEL else "sort"


def make_order(t, tie, impl: str | None = None) -> EventOrder:
    H, K = t.shape
    if impl is None:
        impl = _default_impl(H, K)
    if impl == "sort":
        perm = jnp.lexsort((tie, t), axis=-1).astype(I32)
        inv = jnp.argsort(perm, axis=1).astype(I32)
        return EventOrder(perm=perm, inv=inv)
    tj, tk = t[:, :, None], t[:, None, :]
    ej, ek = tie[:, :, None], tie[:, None, :]
    jlt = jnp.arange(K)[:, None] < jnp.arange(K)[None, :]
    prec = (tj < tk) | ((tj == tk) & ((ej < ek) | ((ej == ek) & jlt)))
    return EventOrder(prec=prec)


def rank_in_order(order: EventOrder, weight):
    """[H,K] number of weighted events strictly preceding each slot
    under the total order (exclusive prefix count)."""
    if order.prec is not None:
        w = weight.astype(I32)
        return jnp.sum(jnp.where(order.prec, w[:, :, None], 0), axis=1,
                       dtype=I32)
    w = order._sorted(weight.astype(I32))
    pref = jnp.cumsum(w, axis=1) - w
    return order._unsorted(pref)


def suffix_sum(order: EventOrder, value):
    """[H,K] sum of value_i over events strictly AFTER each slot."""
    if order.prec is not None:
        return jnp.sum(jnp.where(order.prec, value[:, None, :],
                                 jnp.zeros((), value.dtype)), axis=2,
                       dtype=value.dtype)
    v = order._sorted(value)
    incl = jnp.cumsum(v, axis=1)
    total = incl[:, -1:]
    return order._unsorted(total - incl)


@dataclass(frozen=True)
class BulkDeliveries:
    """The window's UDP arrivals presented to the app bulk handler, in
    SLOT layout ([H,K] aligned with the event queue's slots; use
    `rank` helpers for time-order-dependent logic)."""

    mask: Any       # [H,K] bool — matched, delivered-to-app arrivals
    time: Any       # [H,K] i64
    tie: Any        # [H,K] i64 order tie key
    order: Any      # EventOrder over the row's slots (rank helpers)
    slot: Any       # [H,K] i32 receiving socket
    src_ip: Any     # [H,K] i64
    src_port: Any   # [H,K] i32
    length: Any     # [H,K] i32
    payref: Any     # [H,K] i32


@dataclass(frozen=True)
class BulkSends:
    """App's reply sends, one per delivered event at the event's time.
    Contract (v1): every send is remote (dst != self, not loopback)
    with length > 0; `nic_draw_ctr` is the absolute per-host RNG
    counter at which the NIC's reliability draw for this send must
    happen — the app owns the interleaved draw-stream layout (and
    must advance sim.net.rng_ctr past ALL of the window's draws,
    including these NIC draws, before returning) so the stream
    matches the serial path's execution order."""

    mask: Any           # [H,K] bool
    slot: Any           # [H,K] i32 sending socket
    dst_ip: Any         # [H,K] i64
    dst_host: Any       # [H,K] i32 (-1 = resolve from dst_ip)
    dst_port: Any       # [H,K] i32
    length: Any         # [H,K] i32
    payref: Any         # [H,K] i32
    nic_draw_ctr: Any   # [H,K] u32


class AppBulk:
    """Interface an on-device app exposes to opt into the bulk pass.

    max_send_len: static upper bound on reply payload length.
    resolves_dst: True = every masked send carries a valid dst_host
    (>= 0), so the pass skips the ip->host searchsorted entirely — on
    a TPU that lookup lowers to a ~14-iteration while loop of serial
    gathers costing ~100 ms per window at 10k hosts (measured v5e);
    apps that pick peers by index should always set it.
    precheck(cfg, sim) -> [H] bool — app-side eligibility (no mutation).
    run(cfg, sim, d: BulkDeliveries) -> (sim, BulkSends) — consume
    EVERY delivery in d.mask and stage at most one reply per event.
    """

    max_send_len: int = 0
    resolves_dst: bool = False

    def precheck(self, cfg, sim):
        raise NotImplementedError

    def run(self, cfg, sim, d):
        raise NotImplementedError


def _eligibility(cfg: NetConfig, sim, inwin, t, wl, nonboot, app_ok,
                 send_wire: int):
    net = sim.net
    q = sim.events
    kind_ok = jnp.all(~inwin | (q.kind == EventKind.PACKET), axis=1)
    # a stopped process's app is masked off in the serial path
    # (step.py PROC_STOP); stopped hosts must take that path
    kind_ok = kind_ok & ~net.proc_stopped
    proto = q.words[:, :, pf.W_PROTO] & 0xFF
    udp_ok = jnp.all(~inwin | (proto == pf.PROTO_UDP), axis=1)
    # remote arrivals only (loopback PACKET_LOCAL is a different kind;
    # a self-addressed PACKET cannot occur — sends to self go loopback)
    quiesced = (
        (net.rq_count == 0)
        & ~net.nic_recv_pending
        & ~net.nic_send_pending
        & (jnp.sum(net.out_count, axis=1) == 0)
        & (jnp.sum(net.in_count, axis=1) == 0)
    )
    codel_ok = ~net.codel_dropping & (net.codel_interval_expire == 0)
    # Token budgets with ONE projected refill (to the first arrival;
    # no reliance on further mid-window refills): the serial NIC polices
    # tokens >= MTU before EACH pull/send and consumes the packet's
    # actual wire bytes (nic.py; ref: network_interface.c:421-455,
    # 519-579). The worst prefix requirement for n transfers of sizes
    # w_i is (sum w_i) - w_last + MTU — the LAST transfer needs no
    # headroom after it. Bounding w_last below by the window's
    # smallest arrival (receive) / by send_wire (send) keeps the gate
    # exact enough for low-bandwidth vertices: the real topology's
    # buckets hold barely over one MTU, and the old "+ full MTU after
    # everything" form disqualified them permanently even at n=1.
    # Bucket levels are recorded AT LAST ACCESS (refill_tokens is
    # analytic-on-access), so a long-idle host's stored tokens are
    # stale-low. Project each bucket to the window's FIRST in-window
    # arrival time — exactly the serial path's level at its first
    # pull (refill is monotone in time, so this never overstates a
    # later transfer's budget). Without this, a host that drained its
    # bucket once read as broke forever and fell serial every window.
    from shadow_tpu.net.nic import projected_tokens

    t_first = jnp.min(
        jnp.where(inwin & nonboot, t, simtime.INVALID), axis=1)
    send_tok, recv_tok = projected_tokens(net, t_first)
    recv_w = jnp.where(inwin & nonboot, wl, 0)
    recv_need = jnp.sum(recv_w, axis=1)
    recv_min = jnp.min(
        jnp.where(inwin & nonboot, wl, jnp.iinfo(jnp.int32).max), axis=1)
    recv_ok = (recv_need == 0) | (
        recv_tok >= recv_need - recv_min + pf.MTU)
    # send_wire is the app's static reply bound — using MTU per send
    # would wrongly disqualify every low-bandwidth vertex even when
    # replies are tiny.
    n_nonboot = jnp.sum(inwin & nonboot, axis=1)
    send_ok = (n_nonboot == 0) | (
        send_tok >= (n_nonboot.astype(I64) - 1) * send_wire + pf.MTU)
    return (kind_ok & udp_ok & quiesced & codel_ok & recv_ok & send_ok
            & app_ok)


def _lookup_bulk(net, mask, dst_ip, dst_port, src_ip, src_port):
    """lookup_socket vectorized over [H,K] events (see
    sockets.lookup_socket for the precedence rules being reproduced:
    peer-specific association beats the general one,
    ref: network_interface.c:375-419)."""
    skt = net.sk_type[:, None, :]
    skf = net.sk_flags[:, None, :]
    bip = net.sk_bound_ip[:, None, :]
    bpt = net.sk_bound_port[:, None, :]
    pip = net.sk_peer_ip[:, None, :]
    ppt = net.sk_peer_port[:, None, :]
    base = (
        mask[:, :, None]
        & (skt == pf.PROTO_UDP)
        & ((skf & SocketFlags.CLOSED) == 0)
        & (bpt == dst_port[:, :, None])
        & ((bip == 0) | (bip == dst_ip[:, :, None]))
    )
    general = base & (ppt == 0)
    specific = base & (pip == src_ip[:, :, None]) & (
        ppt == src_port[:, :, None])

    def first(m):
        has = jnp.any(m, axis=2)
        return jnp.where(has, jnp.argmax(m, axis=2).astype(I32), -1)

    g = first(general)
    s = first(specific)
    return jnp.where(s >= 0, s, g)


def make_bulk_fn(cfg: NetConfig, app_bulk: AppBulk,
                 order_impl: str | None = None,
                 caps=None) -> Callable | None:
    """Build the per-window bulk pass, or None when the config cannot
    support it (static preconditions).

    `caps` (compile/specialize.py, None = full program) with a dropped
    loss capability trims the NIC-egress reliability draw out of the
    trace: uniform_at is a pure counter query (the app owns every
    window draw advance — BulkSends.nic_draw_ctr), so skipping it
    moves no RNG state, and with rel == 1.0 the drop mask it fed is
    constant-False."""
    if cfg.tcp:
        return None
    if cfg.qdisc != QDisc.FIFO:
        return None
    if cfg.router_qdisc != RouterQ.CODEL:
        # single/static managers drop at enqueue when occupied; the
        # bulk closed form assumes every window arrival is admitted
        return None
    if cfg.pcap:
        # capture-ring appends are per-event; keep the serial path
        return None
    if cfg.track_paths:
        # observability mode: the serial NIC pass carries the per-path
        # scatter-add; the bulk closed form does not reproduce it
        return None
    if cfg.out_ring < 2:
        return None
    if cfg.outbox_capacity < cfg.event_capacity:
        return None
    if cfg.cpu_threshold_ns >= 0:
        # the CPU admission gate serializes event execution per host;
        # the bulk pass has no equivalent yet
        return None
    # Replies must fit one MTU on the wire: then send_wire <= MTU, the
    # (n-1)*send_wire + MTU eligibility budget (_eligibility's
    # worst-prefix bound) is a true upper bound on the serial drain's
    # token need, and the serial path's max(tokens-w, 0) floor can
    # never engage mid-window (the closed form below doesn't model it).
    if app_bulk.max_send_len + pf.HDR_UDP > pf.MTU:
        return None

    def bulk_fn(sim, wend):
        net = sim.net
        q = sim.events
        H, K = q.time.shape
        GH = net.host_ip.shape[0]
        lane = net.lane_id

        t = q.time
        inwin = t < jnp.asarray(wend, simtime.DTYPE)
        tie = _tie_key(q.src, q.seq)
        length = q.words[:, :, pf.W_LEN]
        wl_all = pf.wire_length(
            jnp.full((H, K), pf.PROTO_UDP, I32), length).astype(I64)
        wl = jnp.where(inwin, wl_all, 0)
        nonboot = t >= cfg.bootstrap_end
        app_ok = app_bulk.precheck(cfg, sim)
        sndbuf_ok = jnp.min(net.sk_sndbuf, axis=1) > app_bulk.max_send_len

        # ---- receive side: router dequeue + socket delivery ----------
        src = q.src
        pw = q.words[:, :, pf.W_PORTS]
        src_port = pw & 0xFFFF
        dst_port = (pw >> 16) & 0xFFFF
        dst_ip = q.words[:, :, pf.W_DSTIP].astype(jnp.uint32).astype(I64)
        src_ip = ip_of_hosts(cfg, net, src)
        payref = q.words[:, :, pf.W_PAYREF]

        slot = _lookup_bulk(net, inwin, dst_ip, dst_port, src_ip, src_port)
        # Receive-buffer fit: with the input rings empty (quiescence)
        # and every delivery consumed in its own event, the serial
        # udp_deliver drops exactly the datagrams with
        # length > sk_rcvbuf (space check at in_bytes == 0,
        # ref: socket.h:47-78) — fall back rather than model the drop.
        rcvbuf_at = _gather_hs_bulk(net.sk_rcvbuf, slot)
        rcv_fit = jnp.all(
            ~inwin | (slot < 0) | (length <= rcvbuf_at), axis=1)

        elig = _eligibility(cfg, sim, inwin, t, wl, nonboot,
                            app_ok & sndbuf_ok & rcv_fit,
                            app_bulk.max_send_len + pf.HDR_UDP)

        ev = inwin & elig[:, None]                     # events we consume
        n_ev = jnp.sum(ev, axis=1, dtype=I32)          # [H]
        order = make_order(t, tie, impl=order_impl)

        matched = ev & (slot >= 0)
        nosock = ev & (slot < 0)

        # per-socket arrival counts (matched only reach the rings)
        S = net.sk_type.shape[1]
        arr_per_sock = jnp.sum(
            matched[:, :, None]
            & (slot[:, :, None] == jnp.arange(S)[None, None, :]),
            axis=1, dtype=I32)                         # [H,S]

        # ---- app: consume every matched delivery, stage replies ------
        d = BulkDeliveries(
            mask=matched, time=t, tie=tie, order=order, slot=slot,
            src_ip=src_ip, src_port=src_port, length=length, payref=payref,
        )
        sim2, sends = app_bulk.run(cfg, sim, d)
        net = sim2.net

        smask = sends.mask & elig[:, None]
        # source port stamped into reply words (udp_enqueue_send)
        sport = _gather_hs_bulk(net.sk_bound_port, sends.slot)

        # sends per socket -> out ring head advance + priority counter
        send_per_sock = jnp.sum(
            smask[:, :, None]
            & (sends.slot[:, :, None] == jnp.arange(S)[None, None, :]),
            axis=1, dtype=I32)                         # [H,S]
        n_send = jnp.sum(smask, axis=1, dtype=I32)

        # ---- NIC egress: reliability draw, latency, outbox entries ---
        if app_bulk.resolves_dst:
            dsth = sends.dst_host
        else:
            dsth = jnp.where(
                sends.dst_host >= 0, sends.dst_host,
                host_of_ip(net, sends.dst_ip))
        known = smask & (dsth >= 0)
        lossless = caps is not None and not caps.loss
        V = net.latency_ns.shape[0]
        if V == 1:
            lat = net.latency_ns[0, 0]
        else:
            vsrc = net.vertex_of_host[lane][:, None]
            vdst = net.vertex_of_host[jnp.clip(dsth, 0, GH - 1)]
            lat = net.latency_ns[vsrc, vdst]
        if lossless:
            drop = jnp.zeros_like(known)
            emit_ok = known
        else:
            u2 = rng.uniform_at(net.rng_keys, sends.nic_draw_ctr)
            rel = (net.reliability[0, 0] if V == 1
                   else net.reliability[vsrc, vdst])
            drop = known & nonboot & (sends.length > 0) & (u2 > rel)
            emit_ok = known & ~drop

        # ---- audit parity: last_drop_status (serial order) -----------
        # Per event column at most one drop occurs: a no-socket arrival
        # (which generates no reply) or a reliability-dropped reply.
        # The serial engine records the status of the LAST drop in
        # event order; reproduce by ranking drops in the total order.
        nosock_status = (
            q.words[:, :, pf.W_STATUS]
            | pf.PDS_ROUTER_ENQUEUED | pf.PDS_ROUTER_DEQUEUED
            | pf.PDS_RCV_INTERFACE_RECEIVED | pf.PDS_RCV_SOCKET_DROPPED)
        reply_drop_status = jnp.full(
            (H, K),
            pf.PDS_SND_CREATED | pf.PDS_SND_SOCKET_BUFFERED
            | pf.PDS_SND_INTERFACE_SENT | pf.PDS_INET_DROPPED, I32)
        drop_any = nosock | drop
        drop_status = jnp.where(nosock, nosock_status, reply_drop_status)
        n_drop = jnp.sum(drop_any, axis=1, dtype=I32)
        drop_rank = rank_in_order(order, drop_any)
        last_col = drop_any & (drop_rank == (n_drop[:, None] - 1))
        picked_drop = jnp.sum(jnp.where(last_col, drop_status, 0), axis=1,
                              dtype=I32)
        new_last_drop = jnp.where(elig & (n_drop > 0), picked_drop,
                                  net.last_drop_status)
        swl = jnp.where(smask, pf.wire_length(
            jnp.full((H, K), pf.PROTO_UDP, I32), sends.length), 0).astype(I64)

        # ---- token buckets: closed-form final values ------------------
        qq = jnp.where(ev, t // TB_REFILL_INTERVAL, 0)
        q_last = jnp.maximum(jnp.max(qq, axis=1), net.tb_quantum)
        q_last = jnp.where(n_ev > 0, q_last, net.tb_quantum)
        qv = jnp.where(ev, qq, q_last[:, None])  # inactive -> no clamp bite
        w_recv = jnp.where(nonboot, wl, 0)
        w_send = jnp.where(nonboot & smask, swl, 0)
        # suffix sums in time order
        suff_recv = suffix_sum(order, w_recv)
        suff_send = suffix_sum(order, w_send)
        cap_r = net.tb_recv_refill + pf.MTU
        cap_s = net.tb_send_refill + pf.MTU
        big = jnp.iinfo(jnp.int64).max // 2
        dq_total = (q_last - net.tb_quantum)

        def bucket_final(s0, cap, refill, w, suffw):
            straight = s0 + dq_total * refill - jnp.sum(w, axis=1)
            clamp = jnp.where(
                ev,
                cap[:, None] - w + (q_last[:, None] - qv) * refill[:, None]
                - suffw,
                big,
            )
            return jnp.minimum(straight, jnp.min(clamp, axis=1))

        new_recv_tok = bucket_final(net.tb_recv_tokens, cap_r,
                                    net.tb_recv_refill, w_recv, suff_recv)
        new_send_tok = bucket_final(net.tb_send_tokens, cap_s,
                                    net.tb_send_refill, w_send, suff_send)

        # ---- outbox entries at the event's time-order column ----------
        ord_col = rank_in_order(order, ev)             # [H,K] rank < K <= M
        send_rank = rank_in_order(order, emit_ok)
        seq = q.next_seq[:, None] + send_rank
        M = sim.outbox.capacity
        # each emitted reply lands at its time-order outbox column
        # (ranks are unique among emit_ok, so no column collides)
        out = sim.outbox
        if order.prec is not None:
            # one-hot reduce instead of scatter: XLA lowers composed
            # scatters on TPU to serial per-element loops (~5 ms each
            # at [10k,48] — 7 of them dominated the pass); the masked
            # [H,K,M] reduction is a fused bandwidth-bound sum
            onehot = emit_ok[:, :, None] & (
                ord_col[:, :, None] == jnp.arange(M)[None, None, :])
            got_col = jnp.any(onehot, axis=1)          # [H,M]

            def place(val, fill, dtype):
                v = jnp.asarray(val, dtype)
                s = jnp.sum(jnp.where(onehot, v[:, :, None],
                                      jnp.zeros((), dtype)), axis=1,
                            dtype=dtype)
                return jnp.where(got_col, s, jnp.asarray(fill, dtype))

            def place_words(wds):
                return jnp.sum(
                    jnp.where(onehot[:, :, :, None], wds[:, :, None, :],
                              0), axis=1, dtype=I32)
        else:
            lane_h = jnp.arange(H)[:, None]
            col = jnp.where(emit_ok, ord_col, M)

            def place(val, fill, dtype):
                base = jnp.full((H, M), fill, dtype)
                return base.at[lane_h, col].set(
                    jnp.asarray(val, dtype), mode="drop")

            def place_words(wds):
                return jnp.zeros((H, M, wds.shape[2]), I32).at[
                    lane_h, col].set(wds, mode="drop")

            got_col = jnp.zeros((H, M), bool).at[lane_h, col].set(
                True, mode="drop")
        o_dst = place(dsth, -1, I32)
        o_time = place(t + lat, simtime.INVALID, I64)
        o_src = place(jnp.broadcast_to(lane[:, None], (H, K)), 0, I32)
        o_seq = place(seq, 0, I32)
        o_kind = jnp.where(got_col, EventKind.PACKET, 0).astype(I32)
        # reply packet words (udp_enqueue_send layout)
        wds = jnp.zeros((H, K, q.words.shape[2]), I32)
        wds = wds.at[:, :, pf.W_PROTO].set(pf.PROTO_UDP)
        wds = wds.at[:, :, pf.W_LEN].set(sends.length)
        wds = wds.at[:, :, pf.W_PORTS].set(
            pf.pack_ports(sport, sends.dst_port))
        wds = wds.at[:, :, pf.W_PAYREF].set(sends.payref)
        wds = wds.at[:, :, pf.W_DSTIP].set(
            sends.dst_ip.astype(jnp.uint32).astype(I32))
        # same audit bits the micro-step path accumulates by wire time
        # (udp_enqueue_send + handle_nic_send) — bit-identity contract
        wds = wds.at[:, :, pf.W_STATUS].set(
            pf.PDS_SND_CREATED | pf.PDS_SND_SOCKET_BUFFERED
            | pf.PDS_SND_INTERFACE_SENT | pf.PDS_INET_SENT)
        o_words = place_words(wds)
        keep = ~got_col
        out = out.replace(
            dst=jnp.where(keep, out.dst, o_dst),
            time=jnp.where(keep, out.time, o_time),
            kind=jnp.where(keep, out.kind, o_kind),
            src=jnp.where(keep, out.src, o_src),
            seq=jnp.where(keep, out.seq, o_seq),
            words=jnp.where(keep[:, :, None], out.words, o_words),
            count=jnp.where(elig, jnp.sum(got_col, axis=1, dtype=I32),
                            out.count),
        )

        # ---- state deltas (bit-identical to the serial chain) ---------
        BI = net.in_src_ip.shape[2]
        BO = net.out_words.shape[2]
        R = net.rq_src.shape[1]
        any_arr = arr_per_sock > 0
        net = net.replace(
            tb_recv_tokens=jnp.where(elig, new_recv_tok, net.tb_recv_tokens),
            tb_send_tokens=jnp.where(elig, new_send_tok, net.tb_send_tokens),
            tb_quantum=jnp.where(elig, q_last, net.tb_quantum),
            # every arrival cycles through the router ring (enqueue at
            # head+count, dequeue advances head): head moves by the
            # arrival count, count/bytes return to zero
            rq_head=jnp.where(elig, (net.rq_head + n_ev) % R, net.rq_head),
            # input rings: k push/pop pairs advance head by k, leave
            # count/bytes unchanged; READABLE ends cleared, one in-gen
            # edge per arrival (udp.udp_deliver/udp_recv)
            in_head=jnp.where(any_arr, (net.in_head + arr_per_sock) % BI,
                              net.in_head),
            sk_in_gen=net.sk_in_gen + arr_per_sock,
            sk_flags=jnp.where(any_arr,
                               net.sk_flags & ~SocketFlags.READABLE,
                               net.sk_flags),
            # output rings: enqueue+drain pairs advance head, bump the
            # per-host packet priority counter (sk_enqueue_out)
            out_head=jnp.where(send_per_sock > 0,
                               (net.out_head + send_per_sock) % BO,
                               net.out_head),
            priority_ctr=net.priority_ctr + n_send.astype(I64),
            ctr_rx_packets=net.ctr_rx_packets
            + jnp.sum(matched, axis=1, dtype=I64),
            ctr_rx_bytes=net.ctr_rx_bytes
            + jnp.sum(jnp.where(matched, wl, 0), axis=1),
            ctr_rx_data_bytes=net.ctr_rx_data_bytes
            + jnp.sum(jnp.where(matched, length, 0), axis=1, dtype=I64),
            ctr_tx_data_bytes=net.ctr_tx_data_bytes
            + jnp.sum(jnp.where(smask, sends.length, 0), axis=1, dtype=I64),
            last_drop_status=new_last_drop,
            ctr_drop_nosocket=net.ctr_drop_nosocket
            + jnp.sum(nosock, axis=1, dtype=I64)
            + jnp.sum(smask & (dsth < 0), axis=1, dtype=I64),
            ctr_tx_packets=net.ctr_tx_packets
            + jnp.sum(smask, axis=1, dtype=I64),
            ctr_tx_bytes=net.ctr_tx_bytes
            + jnp.sum(jnp.where(smask, swl, 0), axis=1),
            ctr_drop_reliability=net.ctr_drop_reliability
            + jnp.sum(drop, axis=1, dtype=I64),
            ctr_events_exec=net.ctr_events_exec + n_ev.astype(I64),
        )

        # consume the window's events
        q = q.replace(
            time=jnp.where(ev, simtime.INVALID, q.time),
            next_seq=q.next_seq + jnp.sum(emit_ok, axis=1, dtype=I32),
        )
        sim2 = sim2.replace(events=q, outbox=out, net=net)
        return sim2, jnp.sum(n_ev, dtype=I64)

    return bulk_fn


def _gather_hs_bulk(arr, slot):
    """arr[H,S] -> [H,K] values at (h, slot[h,k]) via one-hot reduce
    (slot domain S is small)."""
    S = arr.shape[1]
    sel = slot[:, :, None] == jnp.arange(S)[None, None, :]
    return jnp.sum(jnp.where(sel, arr[:, None, :], 0), axis=2,
                   dtype=arr.dtype)
