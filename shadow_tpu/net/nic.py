"""NIC token buckets, qdisc, upstream-router CoDel, and the packet
send/receive event handlers.

Reference mechanics being reproduced (ref: network_interface.c,
router.c, router_queue_codel.c):

- Token buckets both directions, refilled every 1 ms, capacity =
  refill + MTU (ref: network_interface.c:93-100,192-226). Here refill
  is *analytic*: tokens are topped up on access from the number of
  whole 1 ms quanta elapsed — identical results without millions of
  refill events.
- Sending polices `wire bytes` while tokens >= MTU with FIFO-by-packet-
  priority or round-robin qdisc (ref: network_interface.c:465-579);
  the reference's per-activation drain loop becomes a chain of
  same-sim-time NIC_SEND events unwound by the window fixpoint (one
  packet per micro-step, all hosts in parallel).
- Loopback/self delivery is a +1 ns self event bypassing the router
  and consuming no tokens (ref: network_interface.c:546-561).
- Remote sends do the Bernoulli reliability drop (never for 0-length
  control packets, never during bootstrap) and deliver after the
  topology latency (ref: worker.c:243-304).
- Arrivals enqueue into the per-host upstream router queue under CoDel
  AQM (target 10 ms, interval 100 ms; ref: router_queue_codel.c:33-55)
  and are drained by the receive-side token bucket
  (ref: network_interface.c:421-455). NOTE: the reference's drop-time
  control law computes (prev + INTERVAL)/sqrt(count); this build uses
  the RFC-8289 form prev + INTERVAL/sqrt(count) — a deliberate
  deviation, the reference formula appears to be a transcription slip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.events import EventKind, emit
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net.rings import gather_hs, set_hs, set_row
from shadow_tpu.net.sockets import lookup_socket, set_writable
from shadow_tpu.net.state import (
    TB_REFILL_INTERVAL,
    NetConfig,
    NetState,
    QDisc,
    RouterQ,
    SocketFlags,
    SocketType,
)
from shadow_tpu.net.udp import udp_deliver

I32 = jnp.int32
I64 = jnp.int64

CODEL_TARGET = 10 * simtime.ONE_MILLISECOND
CODEL_INTERVAL = 100 * simtime.ONE_MILLISECOND


def ip_from_word(w):
    """i32 packet word -> i64 IP (bit-exact unsigned reinterpret)."""
    return w.astype(jnp.uint32).astype(I64)


def refill_tokens(net: NetState, mask, now):
    """Analytic token refill to the current 1 ms quantum."""
    q = now // TB_REFILL_INTERVAL
    dq = jnp.maximum(q - net.tb_quantum, 0)
    upd = mask & (dq > 0)
    send_cap = net.tb_send_refill + pf.MTU
    recv_cap = net.tb_recv_refill + pf.MTU
    new_send = jnp.minimum(send_cap, net.tb_send_tokens + dq * net.tb_send_refill)
    new_recv = jnp.minimum(recv_cap, net.tb_recv_tokens + dq * net.tb_recv_refill)
    return net.replace(
        tb_send_tokens=jnp.where(upd, new_send, net.tb_send_tokens),
        tb_recv_tokens=jnp.where(upd, new_recv, net.tb_recv_tokens),
        tb_quantum=jnp.where(upd, q, net.tb_quantum),
    )


def projected_tokens(net: NetState, at_time):
    """Bucket levels projected to `at_time` [H] — the value
    refill_tokens would produce on an access at that instant, without
    mutating state. Single source of the analytic-refill formula for
    read-only consumers (bulk._eligibility's token gate); keep in
    lockstep with refill_tokens above."""
    dq = jnp.maximum(at_time // TB_REFILL_INTERVAL - net.tb_quantum,
                     0).astype(jnp.int64)
    send_cap = net.tb_send_refill + pf.MTU
    recv_cap = net.tb_recv_refill + pf.MTU
    send = jnp.minimum(send_cap, net.tb_send_tokens + dq * net.tb_send_refill)
    recv = jnp.minimum(recv_cap, net.tb_recv_tokens + dq * net.tb_recv_refill)
    return send, recv


def next_refill_time(now):
    return (now // TB_REFILL_INTERVAL + 1) * TB_REFILL_INTERVAL


def _empty_words(H):
    from shadow_tpu.core.events import NWORDS

    return jnp.zeros((H, NWORDS), I32)


def _capture(cfg: NetConfig, net: NetState, mask, src_host, words, now,
             direction: int):
    """Append packets to the per-host pcap capture ring (ref: the
    sent/received pcap hooks, network_interface.c:337-373,414-415).
    No-op (and no device cost) unless cfg.pcap."""
    if not cfg.pcap:
        return net
    C = net.cap_time.shape[1]
    lane = jnp.arange(mask.shape[0])
    pos = net.cap_count % C
    meta = (jnp.clip(src_host, 0, (1 << 24) - 1).astype(I32)
            | I32(direction << 24))
    sel = mask
    return net.replace(
        cap_time=net.cap_time.at[lane, pos].set(
            jnp.where(sel, jnp.broadcast_to(now, sel.shape),
                      net.cap_time[lane, pos])),
        cap_words=net.cap_words.at[lane, pos].set(
            jnp.where(sel[:, None], words, net.cap_words[lane, pos])),
        cap_meta=net.cap_meta.at[lane, pos].set(
            jnp.where(sel, meta, net.cap_meta[lane, pos])),
        cap_count=net.cap_count + sel.astype(I32),
    )


def deliver_packet(cfg: NetConfig, sim, mask, src_host, words, now, buf):
    """Hand one arrived packet per masked lane to the bound socket
    (ref: _networkinterface_receivePacket, network_interface.c:375-419).
    UDP goes to the datagram ring; TCP enters the connection state
    machine (socket_pushInPacket -> protocol process, socket.h:84-87).
    Returns (sim, buf)."""
    net = sim.net
    GH = net.host_ip.shape[0]  # global host count (host_ip replicated)
    proto = pf.proto_of(words)
    src_port, dst_port = pf.ports_of(words)
    dst_ip = ip_from_word(words[:, pf.W_DSTIP])
    src_ip = jnp.where(
        src_host == net.lane_id, ip_from_word(words[:, pf.W_DSTIP]),
        net.host_ip[jnp.clip(src_host, 0, GH - 1)],
    )
    # loopback packets keep their loopback src address
    src_ip = jnp.where(dst_ip >> 24 == 127, dst_ip, src_ip)

    net = _capture(cfg, net, mask, src_host, words, now, direction=1)
    slot = lookup_socket(net, mask, proto, dst_ip, dst_port, src_ip, src_port)
    found = mask & (slot >= 0)
    words = words.at[:, pf.W_STATUS].set(jnp.where(
        found, words[:, pf.W_STATUS] | pf.PDS_RCV_SOCKET_PROCESSED,
        words[:, pf.W_STATUS]))
    is_udp = found & (proto == pf.PROTO_UDP)
    net = udp_deliver(
        net, is_udp, slot, src_ip, src_port, words[:, pf.W_LEN],
        words[:, pf.W_PAYREF], status=words[:, pf.W_STATUS],
    )
    nosock = mask & (slot < 0)
    net = net.replace(last_drop_status=jnp.where(
        nosock, words[:, pf.W_STATUS] | pf.PDS_RCV_SOCKET_DROPPED,
        net.last_drop_status))
    # TCP segment matching no socket: answer with RST so an active
    # open to a dead port fails promptly instead of retransmitting
    # SYNs forever (ref: the reference's RST-on-closed path in
    # tcp_processPacket; never RST a RST). The RST bypasses the NIC
    # rings — it belongs to no socket — and rides the event fabric
    # directly; 0-length control packets are exempt from reliability
    # drops either way. Gated on cfg.tcp: no TCP packets can exist in
    # a UDP-only config, and its narrow words carry no TCP header.
    if cfg.tcp:
        flags = pf.tcp_flags_of(words)
        need_rst = nosock & (proto == pf.PROTO_TCP) \
            & ((flags & pf.TCPF_RST) == 0)
        f_ack = (flags & pf.TCPF_ACK) != 0
        f_syn = (flags & pf.TCPF_SYN) != 0
        rseq = jnp.where(f_ack, words[:, pf.W_ACK], 0)
        rack = words[:, pf.W_SEQ] + words[:, pf.W_LEN] + f_syn.astype(I32)
        rst = jnp.zeros_like(words)
        rst = rst.at[:, pf.W_PROTO].set(
            pf.PROTO_TCP | ((pf.TCPF_RST | pf.TCPF_ACK) << 8))
        rst = rst.at[:, pf.W_PORTS].set(pf.pack_ports(dst_port, src_port))
        rst = rst.at[:, pf.W_SEQ].set(rseq)
        rst = rst.at[:, pf.W_ACK].set(rack)
        rst = rst.at[:, pf.W_PAYREF].set(pf.PAYREF_NONE)
        rst = rst.at[:, pf.W_DSTIP].set(src_ip.astype(jnp.uint32).astype(I32))
        srch = jnp.clip(src_host, 0, GH - 1)
        rst_local = need_rst & (src_host == net.lane_id)
        vme = net.vertex_of_host[net.lane_id]
        vsrc = net.vertex_of_host[srch]
        lat = net.latency_ns[vme, vsrc]
        buf = emit(buf, rst_local, net.lane_id, now + 1,
                   EventKind.PACKET_LOCAL, rst)
        buf = emit(buf, need_rst & ~rst_local & (src_host >= 0), src_host,
                   now + lat, EventKind.PACKET, rst)
    net = net.replace(
        ctr_drop_nosocket=net.ctr_drop_nosocket + nosock.astype(I64),
        ctr_rx_packets=net.ctr_rx_packets + found.astype(I64),
        ctr_rx_bytes=net.ctr_rx_bytes
        + jnp.where(found, pf.wire_length(proto, words[:, pf.W_LEN]), 0).astype(I64),
        ctr_rx_data_bytes=net.ctr_rx_data_bytes
        + jnp.where(found, words[:, pf.W_LEN], 0).astype(I64),
    )
    sim = sim.replace(net=net)
    if getattr(sim, "tcp", None) is not None:
        from shadow_tpu.net import tcp as tcp_mod

        is_tcp = found & (proto == pf.PROTO_TCP)
        sim, buf = tcp_mod.tcp_packet_in(
            cfg, sim, is_tcp, slot, words, src_ip, src_port, now, buf
        )
    return sim, buf


# ---------------------------------------------------------------------
# receive: packet arrival -> router ring -> CoDel dequeue -> delivery,
# fused into one handler pass
# ---------------------------------------------------------------------

def handle_nic_recv(cfg: NetConfig, sim, popped, buf):
    """kinds PACKET, NIC_RECV, PACKET_LOCAL, fused.

    An arriving packet (kind=PACKET) is enqueued into the router ring
    and — when the queue was idle — dequeued and delivered in the SAME
    micro-step, exactly like the reference's synchronous
    router_enqueue -> networkinterface_receivePackets call chain
    (router.c:104-125): no same-time event round-trip. kind=NIC_RECV
    events exist only for deferred drains (token-bucket refill waits,
    multi-packet chains). Chaining while packets and tokens remain
    mirrors the reference's while-loop (network_interface.c:432-455),
    unrolled across micro-steps."""
    net = sim.net
    H = net.rq_head.shape[0]
    lane = jnp.arange(H)
    now = popped.time
    R = cfg.router_ring

    # -- arrival enqueue (ref: router_enqueue, router.c:104-125) ------
    arr = popped.valid & (popped.kind == EventKind.PACKET)
    was_empty = net.rq_count == 0
    # queue-manager admission (ref: QueueManagerHooks enqueue):
    # CODEL admits to ring capacity (a full ring is an honest overflow
    # error — CoDel itself drops at dequeue); SINGLE holds one packet
    # (router_queue_single.c); STATIC drop-tails at capacity
    # (router_queue_static.c) — both drop the arrival, counted, with
    # the audit trail recorded.
    cap = {RouterQ.CODEL: R, RouterQ.SINGLE: 1,
           RouterQ.STATIC: R}[cfg.router_qdisc]
    aok = arr & (net.rq_count < cap)
    qdrop = arr & ~aok if cfg.router_qdisc != RouterQ.CODEL else (
        jnp.zeros_like(arr))
    apos = (net.rq_head + net.rq_count) % R
    awl = pf.wire_length(pf.proto_of(popped.words), popped.words[:, pf.W_LEN])
    arr_words = popped.words.at[:, pf.W_STATUS].set(jnp.where(
        aok, popped.words[:, pf.W_STATUS] | pf.PDS_ROUTER_ENQUEUED,
        popped.words[:, pf.W_STATUS]))
    net = net.replace(
        rq_src=set_row(net.rq_src, aok, apos, popped.src),
        rq_enq_ts=set_row(net.rq_enq_ts, aok, apos, popped.time),
        rq_words=set_row(net.rq_words, aok, apos, arr_words),
        rq_count=net.rq_count + aok.astype(I32),
        rq_bytes=net.rq_bytes + jnp.where(aok, awl, 0).astype(I64),
        rq_overflow=net.rq_overflow + jnp.sum(arr & ~aok & ~qdrop, dtype=I32),
        **({"rq_overflow_h": net.rq_overflow_h
            + (arr & ~aok & ~qdrop).astype(I32)}
           if net.rq_overflow_h is not None else {}),
        ctr_drop_codel=net.ctr_drop_codel + qdrop.astype(I64),
        last_drop_status=jnp.where(
            qdrop, popped.words[:, pf.W_STATUS] | pf.PDS_ROUTER_DROPPED,
            net.last_drop_status),
    )
    # fused drain: idle queue served immediately; a busy queue already
    # has a drain in flight (nic_recv_pending invariant)
    kick = aok & was_empty & ~net.nic_recv_pending

    # -- drain one packet (deferred NIC_RECV event or fused kick) -----
    ev = popped.valid & (popped.kind == EventKind.NIC_RECV)
    mask = ev | kick
    net = net.replace(nic_recv_pending=net.nic_recv_pending & ~ev)
    net = refill_tokens(net, mask, now)

    bootstrap = now < cfg.bootstrap_end
    have = net.rq_count > 0
    can = bootstrap | (net.tb_recv_tokens >= pf.MTU)
    active = mask & have & can

    # pop head entry
    pos = jnp.where(active, net.rq_head, R)
    posc = jnp.clip(pos, 0, R - 1)
    e_src = net.rq_src[lane, posc]
    e_ts = net.rq_enq_ts[lane, posc]
    e_words = net.rq_words[lane, posc]
    wl = pf.wire_length(pf.proto_of(e_words), e_words[:, pf.W_LEN]).astype(I64)
    bytes_after = net.rq_bytes - jnp.where(active, wl, 0)
    net = net.replace(
        rq_head=jnp.where(active, (net.rq_head + 1) % R, net.rq_head),
        rq_count=net.rq_count - active.astype(I32),
        rq_bytes=bytes_after,
    )

    if cfg.router_qdisc != RouterQ.CODEL:
        # single/static managers dequeue without AQM
        # (ref: router_queue_single.c / router_queue_static.c)
        drop_now = jnp.zeros_like(active)
        delivered = active
        return _finish_recv_common(
            cfg, sim.replace(net=net), popped, buf, mask, active,
            delivered, drop_now, e_src, e_words, wl, now, H)

    # CoDel good/bad state (ref: router_queue_codel.c:161-196)
    sojourn = now - e_ts
    below = (sojourn < CODEL_TARGET) | (bytes_after < pf.MTU)
    ie = net.codel_interval_expire
    ok_to_drop = active & ~below & (ie != 0) & (now >= ie)
    new_ie = jnp.where(
        active,
        jnp.where(below, 0, jnp.where(ie == 0, now + CODEL_INTERVAL, ie)),
        ie,
    )
    # empty queue resets the interval state (codel.c:161-166)
    new_ie = jnp.where(mask & ~have, 0, new_ie)

    dropping = net.codel_dropping
    # in DROP mode: leave it when delays are low again; drop while
    # now >= next_drop (codel.c:221-241)
    drop_in_dropmode = dropping & ok_to_drop & (now >= net.codel_next_drop)
    enter_drop = ~dropping & ok_to_drop
    drop_now = active & (drop_in_dropmode | enter_drop)

    sqrt_cnt = jnp.sqrt(jnp.maximum(net.codel_drop_count, 1).astype(jnp.float64))
    # control law (RFC 8289; see module docstring on the deviation)
    law_from_prev = (
        net.codel_next_drop
        + (CODEL_INTERVAL / sqrt_cnt).astype(I64)
    )
    delta = net.codel_drop_count - net.codel_drop_count_last
    recently = now < net.codel_next_drop + 16 * CODEL_INTERVAL
    restart_count = jnp.where(recently & (delta > 1), delta, 1)
    law_restart = now + (
        CODEL_INTERVAL / jnp.sqrt(jnp.maximum(restart_count, 1).astype(jnp.float64))
    ).astype(I64)

    new_dropping = jnp.where(
        active,
        jnp.where(dropping, dropping & ok_to_drop | drop_in_dropmode, enter_drop),
        dropping,
    )
    new_dropping = jnp.where(mask & ~have, False, new_dropping)
    net = net.replace(
        codel_interval_expire=new_ie,
        codel_dropping=new_dropping,
        codel_drop_count=jnp.where(
            drop_in_dropmode, net.codel_drop_count + 1,
            jnp.where(enter_drop & active, restart_count, net.codel_drop_count),
        ),
        codel_drop_count_last=jnp.where(
            enter_drop & active, restart_count, net.codel_drop_count_last
        ),
        codel_next_drop=jnp.where(
            drop_in_dropmode, law_from_prev,
            jnp.where(enter_drop & active, law_restart, net.codel_next_drop),
        ),
        ctr_drop_codel=net.ctr_drop_codel + drop_now.astype(I64),
    )

    delivered = active & ~drop_now
    return _finish_recv_common(
        cfg, sim.replace(net=net), popped, buf, mask, active,
        delivered, drop_now, e_src, e_words, wl, now, H)


def _finish_recv_common(cfg, sim, popped, buf, mask, active, delivered,
                        drop_now, e_src, e_words, wl, now, H):
    """Tail of the receive handler shared by all router queue
    managers: delivery merge, token consumption, drain chaining."""
    net = sim.net
    bootstrap = now < cfg.bootstrap_end
    net = net.replace(last_drop_status=jnp.where(
        drop_now, e_words[:, pf.W_STATUS] | pf.PDS_ROUTER_DROPPED,
        net.last_drop_status))
    # merge loopback deliveries (kind=PACKET_LOCAL, disjoint lanes —
    # one popped event per host) into one deliver_packet call so the
    # TCP state machine is materialized once per micro-step, not twice
    local = popped.valid & (popped.kind == EventKind.PACKET_LOCAL)
    d_mask = delivered | local
    d_src = jnp.where(local, popped.src, e_src)
    d_words = jnp.where(local[:, None], popped.words, e_words)
    # audit: dequeued from the router and received by the interface
    d_words = d_words.at[:, pf.W_STATUS].set(jnp.where(
        delivered,
        d_words[:, pf.W_STATUS] | pf.PDS_ROUTER_DEQUEUED
        | pf.PDS_RCV_INTERFACE_RECEIVED,
        d_words[:, pf.W_STATUS]))
    sim = sim.replace(net=net)
    sim, buf = deliver_packet(cfg, sim, d_mask, d_src, d_words, now, buf)
    net = sim.net

    # consume rx tokens for delivered packets only (CoDel drops happen
    # inside router_dequeue, before bandwidth accounting)
    consume = delivered & ~bootstrap
    net = net.replace(
        tb_recv_tokens=jnp.maximum(
            net.tb_recv_tokens - jnp.where(consume, wl, 0), 0
        )
    )

    # continue or re-arm
    more = net.rq_count > 0
    can_next = bootstrap | (net.tb_recv_tokens >= pf.MTU)
    chain = mask & more & can_next
    wait = mask & more & ~can_next
    buf = emit(buf, chain, net.lane_id, now, EventKind.NIC_RECV,
               _empty_words(H))
    buf = emit(buf, wait, net.lane_id, next_refill_time(now),
               EventKind.NIC_RECV, _empty_words(H))
    net = net.replace(nic_recv_pending=net.nic_recv_pending | chain | wait)
    return sim.replace(net=net), buf


# ---------------------------------------------------------------------
# send: drain socket output rings through the tx token bucket
# ---------------------------------------------------------------------

def _qdisc_select(cfg: NetConfig, net: NetState):
    """Pick the next socket slot to send from per host ([H] -> slot or
    -1). FIFO = lowest head-packet priority (app ordering,
    network_interface.c:484-517); RR = cyclic from the per-host cursor
    (network_interface.c:465-483)."""
    H, S = net.out_count.shape
    lane = jnp.arange(H)
    nonempty = net.out_count > 0
    BO = net.out_words.shape[2]
    head_pos = net.out_head % BO
    head_pri = jnp.take_along_axis(
        net.out_priority, head_pos[..., None], axis=2
    )[..., 0]
    if cfg.qdisc == QDisc.RR:
        key = (jnp.arange(S)[None, :] - net.rr_ptr[:, None]) % S
    else:
        key = head_pri
    key = jnp.where(nonempty, key, jnp.iinfo(key.dtype).max)
    sel = jnp.argmin(key, axis=1).astype(I32)
    found = jnp.any(nonempty, axis=1)
    return jnp.where(found, sel, -1)


def handle_nic_send(cfg: NetConfig, sim, popped, buf, caps=None):
    """Drain up to cfg.nic_drain packets chosen by the qdisc; chain a
    same-time NIC_SEND event if more remain sendable (ref:
    _networkinterface_sendPackets, network_interface.c:519-579 — the
    reference drains its ring in a while loop inside ONE event; the
    lax.fori_loop below is the device form, and the chained event only
    covers bursts longer than the loop bound).

    Runs LAST in the handler pipeline and acts on kind=NIC_SEND events
    *plus* lanes whose nic_send_now bit was set earlier in this
    micro-step (data enqueued by TCP/app handlers) — the fused form of
    the reference's synchronous networkinterface_wantsSend call.
    NIC_SEND events exist only for deferred sends (refill waits,
    over-long bursts)."""
    net = sim.net
    H = net.rq_head.shape[0]
    ev = popped.valid & (popped.kind == EventKind.NIC_SEND)
    mask = ev | net.nic_send_now
    now = popped.time

    net = net.replace(nic_send_pending=net.nic_send_pending & ~ev,
                      nic_send_now=jnp.zeros((H,), bool))
    net = refill_tokens(net, mask, now)
    sim = sim.replace(net=net)

    bootstrap = now < cfg.bootstrap_end
    if cfg.nic_drain <= 1:
        sim, buf = _drain_one(cfg, sim, buf, mask, now, bootstrap,
                              caps=caps)
    else:
        sim, buf = jax.lax.fori_loop(
            0, cfg.nic_drain,
            lambda i, c: _drain_one(cfg, c[0], c[1], mask, now, bootstrap,
                                    caps=caps),
            (sim, buf))

    # continue or re-arm (guard against lanes that already have a
    # deferred NIC_SEND in flight — fused fresh lanes can overlap one)
    net = sim.net
    more = jnp.any(net.out_count > 0, axis=1)
    can_next = bootstrap | (net.tb_send_tokens >= pf.MTU)
    chain = mask & more & can_next & ~net.nic_send_pending
    wait = mask & more & ~can_next & ~net.nic_send_pending
    buf = emit(buf, chain, net.lane_id, now, EventKind.NIC_SEND,
               _empty_words(H))
    buf = emit(buf, wait, net.lane_id, next_refill_time(now),
               EventKind.NIC_SEND, _empty_words(H))
    net = net.replace(nic_send_pending=net.nic_send_pending | chain | wait)
    return sim.replace(net=net), buf


def _drain_one(cfg: NetConfig, sim, buf, mask, now, bootstrap, caps=None):
    """One qdisc selection + wire transmission across all lanes (the
    loop body of the reference's send loop). Lanes with no sendable
    packet (or no tokens) are masked off and unchanged.

    A dropped loss capability (compile/specialize.py — reliability
    all-ones, no fault plan touching it) trims the Bernoulli draw and
    the drop bookkeeping out of the trace. Bit-identical: the RNG
    counter advance is data-independent (rng.uniform returns
    counters+1), so the trimmed path advances it arithmetically and
    every later draw lands on the same counter; with rel == 1.0 the
    drop mask is constant-False and the skipped updates are the
    identity."""
    net = sim.net
    H = net.rq_head.shape[0]
    lane = jnp.arange(H)
    can = bootstrap | (net.tb_send_tokens >= pf.MTU)
    sel = _qdisc_select(cfg, net)
    active = mask & can & (sel >= 0)

    # pop the head packet of the selected socket's output ring
    BO = net.out_words.shape[2]
    S = net.out_count.shape[1]
    selc = jnp.clip(sel, 0, S - 1)
    hpos = net.out_head[lane, selc] % BO
    words = net.out_words[lane, selc, hpos]              # [H, NWORDS]
    length = words[:, pf.W_LEN]
    proto = pf.proto_of(words)
    dst_ip = ip_from_word(words[:, pf.W_DSTIP])

    net = net.replace(
        out_head=set_hs(net.out_head, active, sel,
                        (net.out_head[lane, selc] + 1) % BO),
        out_count=set_hs(net.out_count, active, sel,
                         net.out_count[lane, selc] - 1),
        out_bytes=set_hs(net.out_bytes, active, sel,
                         net.out_bytes[lane, selc] - length),
    )
    # draining freed output capacity: restore WRITABLE for datagram
    # sockets (TCP writability is sndbuf-room-based; its ACK path
    # restores it). Ref: descriptor_adjustStatus -> epoll EPOLLOUT.
    is_dgram = active & (net.sk_type[lane, selc] == SocketType.UDP)
    net = set_writable(net, is_dgram, sel, True)
    if cfg.qdisc == QDisc.RR:
        net = net.replace(rr_ptr=jnp.where(active, (sel + 1) % S, net.rr_ptr))

    # volatile TCP header fields are stamped at wire time
    # (ref: tcp_networkInterfaceIsAboutToSendPacket, tcp.c:1090-1120)
    if getattr(sim, "tcp", None) is not None:
        from shadow_tpu.net import tcp as tcp_mod

        tmask = active & (proto == pf.PROTO_TCP)
        words = tcp_mod.stamp_at_wire(net, sim.tcp, tmask, sel, words, now)
        # a departing ACK cancels the pending delayed ACK
        acked = tmask & ((pf.tcp_flags_of(words) & pf.TCPF_ACK) != 0)
        sim = sim.replace(
            tcp=tcp_mod.wire_ack_departed(sim.tcp, acked, sel))

    wl = pf.wire_length(proto, length).astype(I64)
    GH = net.host_ip.shape[0]
    my_ip = net.host_ip[net.lane_id]
    local = active & ((dst_ip == my_ip) | (dst_ip >> 24 == 127))
    remote = active & ~local

    # audit: the packet left the interface (packet.h PDS trail)
    words = words.at[:, pf.W_STATUS].set(jnp.where(
        active, words[:, pf.W_STATUS] | pf.PDS_SND_INTERFACE_SENT,
        words[:, pf.W_STATUS]))
    net = _capture(cfg, net, active, net.lane_id, words, now, direction=0)

    # loopback: 1ns self delivery, no tokens
    # (network_interface.c:546-554)
    buf = emit(buf, local, net.lane_id, now + 1, EventKind.PACKET_LOCAL,
               words)

    # remote: reliability draw + latency lookup (worker.c:243-304)
    from shadow_tpu.net.state import host_of_ip

    dsth = host_of_ip(net, dst_ip)
    known = remote & (dsth >= 0)
    lossless = caps is not None and not caps.loss
    if lossless:
        net = net.replace(
            rng_ctr=net.rng_ctr + remote.astype(net.rng_ctr.dtype))
    else:
        u, ctr = rng.uniform(net.rng_keys, net.rng_ctr)
        net = net.replace(rng_ctr=jnp.where(remote, ctr, net.rng_ctr))
    vsrc = net.vertex_of_host[net.lane_id]
    vdst = net.vertex_of_host[jnp.clip(dsth, 0, GH - 1)]
    lat = net.latency_ns[vsrc, vdst]
    if lossless:
        drop = jnp.zeros_like(known)
        send = known
    else:
        rel = net.reliability[vsrc, vdst]
        drop = known & ~bootstrap & (length > 0) & (u > rel)
        send = known & ~drop
    words = words.at[:, pf.W_STATUS].set(jnp.where(
        send, words[:, pf.W_STATUS] | pf.PDS_INET_SENT,
        words[:, pf.W_STATUS]))
    buf = emit(buf, send, dsth, now + lat, EventKind.PACKET, words)

    if cfg.track_paths:
        # per-path packet counters (ref: topology.c:2053-2063 — the
        # reference bumps the Path's count on every routing lookup of
        # a send, dropped or not; loopback never reaches the topology)
        net = net.replace(ctr_path_packets=net.ctr_path_packets.at[
            vsrc, vdst].add(known.astype(I64), mode="drop"))

    # tracker byte split (ref: tracker.c:51-99): data vs retransmit,
    # classified by the packet's own audit trail. These cumulative
    # counters are the single source for every observability consumer:
    # the tracker heartbeat deltas them per interval, the telemetry
    # ring deltas drop_total per window (telemetry/ring.py), and the
    # run manifest reports the final totals — so a new counter only
    # needs to be bumped here (and mirrored in the tcp_bulk drain lane
    # for fields the bulk pass also advances, e.g. ctr_tx_retx_bytes)
    # to reach all three.
    is_retx = (words[:, pf.W_STATUS] & pf.PDS_SND_TCP_RETRANSMITTED) != 0
    net = net.replace(
        **({} if lossless else {
            "last_drop_status": jnp.where(
                drop, words[:, pf.W_STATUS] | pf.PDS_INET_DROPPED,
                net.last_drop_status),
            "ctr_drop_reliability":
                net.ctr_drop_reliability + drop.astype(I64),
        }),
        ctr_drop_nosocket=net.ctr_drop_nosocket + (remote & ~known).astype(I64),
        ctr_tx_packets=net.ctr_tx_packets + active.astype(I64),
        ctr_tx_bytes=net.ctr_tx_bytes + jnp.where(active, wl, 0),
        ctr_tx_data_bytes=net.ctr_tx_data_bytes
        + jnp.where(active, length, 0).astype(I64),
        ctr_tx_retx_bytes=net.ctr_tx_retx_bytes
        + jnp.where(active & is_retx, wl, 0),
        tb_send_tokens=jnp.maximum(
            net.tb_send_tokens - jnp.where(remote & ~bootstrap, wl, 0), 0
        ),
    )
    return sim.replace(net=net), buf


def notify_wants_send(sim, buf, mask, now):
    """App/TCP enqueued data on a socket: flag the lane so the send
    drain at the end of this micro-step's pipeline picks it up (the
    synchronous networkinterface_wantsSend, network_interface.c:583-…).
    Host-side syscall paths (vproc), which run outside the pipeline,
    must follow up with flush_wants_send()."""
    net = sim.net.replace(nic_send_now=sim.net.nic_send_now | mask)
    return sim.replace(net=net), buf


def flush_wants_send(sim, buf, now):
    """Convert lingering nic_send_now bits into NIC_SEND events — used
    by host-side syscall execution where no pipeline send drain will
    run this 'micro-step' (ProcessRuntime._apply)."""
    net = sim.net
    H = net.rq_head.shape[0]
    kick = net.nic_send_now & ~net.nic_send_pending
    buf = emit(buf, kick, net.lane_id, now, EventKind.NIC_SEND,
               _empty_words(H))
    net = net.replace(nic_send_pending=net.nic_send_pending | kick,
                      nic_send_now=jnp.zeros((H,), bool))
    return sim.replace(net=net), buf
