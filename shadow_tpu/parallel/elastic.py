"""Elastic degraded-mesh recovery: device-loss detection, the
cross-shard integrity sentinel, and shrink-to-survivors planning.

A sharded run is pinned to its launch mesh today: one lost chip (or
one shard silently corrupting its replica of the replicated state)
kills the whole run. Shard-count invariance — results are bit-
identical across {1,8} shards × {K=1,64} chunking, and checkpoints
store the GLOBAL layout — means device loss should cost a resume, not
a run. This module owns the three mechanisms:

1. **Device-loss classification** (`DeviceLossError`, `classify`,
   `guard_dispatch`): XLA surfaces a dead chip as a RuntimeError from
   the next dispatch (or as a dispatch that never completes). The
   guard wraps the chunk/window dispatch callables
   (checkpoint.run_windows `dispatch_wrap`) and converts matching
   errors into a typed `DEVICE_LOST` health fault carrying the failed
   shard/device identity — distinct from sim faults (faults/), which
   are *simulated*; this one is about the machine underneath.

2. **Cross-shard integrity sentinel** (`SentinelState`,
   `attach_sentinel`, `make_sentinel_fn`): inside the jitted window
   body, right after the route barrier restored the replication
   invariant, every shard folds the replicated leaves it carries into
   one u32 digest and compares pmax-vs-pmin across the mesh. Any
   disagreement is silent divergence (an SDC, a miscompiled
   collective, a flipped bit in a replicated table) and latches a
   sticky FATAL `SHARD_DIVERGENCE` trip with the offending shard id.
   None-default opt-in like telemetry: `Sim.sentinel is None` compiles
   to zero ops, so sentinel-off programs are byte-identical to
   pre-sentinel builds.

   What the digest covers — the replicated CONTROL state: exactly the
   leaves that are invariantly replicated at EVERY window barrier (not
   just at chunk exit, where `_replicate_scalars` additionally psums
   the per-shard scalar partials) AND that feed back into simulation
   state: the NetState replicated lookup tables minus the
   per-shard-delta path matrix, plus the replicated injection/
   causality cursors. Per-shard partials (scalar counters inside a
   chunk, lineage rows, `ctr_path_packets`) are legitimately different
   across shards mid-chunk and are excluded by construction. The bulk
   telemetry/flow ring PLANES are also excluded, deliberately: they
   are write-only accumulation buffers drained host-side — a diverged
   ring record corrupts observability output, never the simulation —
   and folding their DUS-updated planes into a per-window reduce sends
   the XLA CPU backend into a pathological multi-hour compile (the
   digest must stay a few fused reduces over lookup tables).

3. **Shrink planning** (`survivor_mesh`, `next_pow2_down`,
   `shard_digests`): given a mesh and a lost shard, build the
   next-pow2-down mesh over the surviving devices. The AOT program
   key includes the shard count and the bucket lattice is pow2, so
   the shrunk program is often already warm. `shard_digests` computes
   the per-shard sha256 the verified-state checkpoint ledger stamps
   (utils/checkpoint.py `save(..., elastic=...)`).

The degradation ladder itself — retry same mesh → shrink to
survivors → serial fallback, resuming from the last *verified*
checkpoint — lives in faults/supervisor.py (`ElasticPolicy` here is
its knob block); the fleet's device-set leases and no-attempt-burn
requeue live in fleet/.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax
from jax.tree_util import tree_map_with_path

from shadow_tpu.core import simtime

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32

# ---------------------------------------------------------------------
# device-loss classification
# ---------------------------------------------------------------------

# Substrings XLA/PJRT runtimes use when a device (or the transfer path
# to it) died underneath a dispatch. Deliberately broad: a false
# DEVICE_LOST costs one ladder step from a verified checkpoint; a
# missed one costs the run.
_LOSS_MARKERS = (
    "device_lost",
    "device lost",
    "device is lost",
    "device halted",
    "device unavailable",
    "failed to transfer",
    "transfer to device",
    "transfer from device",
    "data transfer failed",
    "device to host copy",
    "unable to enqueue",
    "failed to enqueue",
    "device failure",
    "chip unreachable",
    "ici link",
    "slice has been terminated",
    "core halted",
)


class DeviceLossError(RuntimeError):
    """A dispatch failed (or overran its deadline) because the machine
    underneath lost a device — NOT a simulation fault. Carries the
    failed shard index (-1 = unknown) and device repr for the health
    report and the fleet's elastic block."""

    def __init__(self, message: str, *, shard: int = -1,
                 device: str | None = None, cause: str = "xla_error"):
        super().__init__(message)
        self.shard = int(shard)
        self.device = device
        self.cause = cause

    def as_dict(self) -> dict:
        return {"fault": "DEVICE_LOST", "shard": self.shard,
                "device": self.device, "cause": self.cause,
                "message": str(self)}


def classify(exc: BaseException, *, shards: int = 1,
             elapsed_s: float | None = None,
             deadline_s: float | None = None) -> DeviceLossError | None:
    """Map an exception raised by (or a deadline measured around) a
    device dispatch to a DeviceLossError, or None when it is an
    ordinary error that should propagate as-is. The failed shard is
    parsed from the message when the runtime names a device ordinal;
    -1 (unknown) still drives the ladder — shrink decisions only need
    *that* a shard died, identity is for the report."""
    if isinstance(exc, DeviceLossError):
        return exc
    msg = str(exc).lower()
    hit = any(m in msg for m in _LOSS_MARKERS)
    if not hit and deadline_s is not None and elapsed_s is not None \
            and elapsed_s > deadline_s:
        return DeviceLossError(
            f"dispatch exceeded deadline ({elapsed_s:.1f}s > "
            f"{deadline_s:.1f}s): {exc}", cause="dispatch_deadline")
    if not hit:
        return None
    shard = -1
    for tok in ("device ordinal ", "device id ", "tpu_", "device "):
        i = msg.find(tok)
        if i >= 0:
            tail = msg[i + len(tok):]
            digits = ""
            for ch in tail:
                if ch.isdigit():
                    digits += ch
                else:
                    break
            if digits and int(digits) < max(shards, 1):
                shard = int(digits)
                break
    return DeviceLossError(str(exc), shard=shard, cause="xla_error")


def guard_dispatch(fn, *, shards: int = 1,
                   deadline_s: float | None = None):
    """Wrap a dispatch callable (the chunk/window fn run_windows
    drives): XLA errors matching the loss markers re-raise as
    DeviceLossError, and a *blocking* call that overran `deadline_s`
    raises one too (the dispatch itself is async; the overrun is
    measured when the runtime forces a sync inside the call — a hung
    device stalls exactly there). Ordinary errors propagate
    untouched."""
    def guarded(*args, **kwargs):
        t0 = time.monotonic()
        try:
            return fn(*args, **kwargs)
        except DeviceLossError:
            raise
        except Exception as e:           # noqa: BLE001 — classify-all
            loss = classify(e, shards=shards,
                            elapsed_s=time.monotonic() - t0,
                            deadline_s=deadline_s)
            if loss is not None:
                raise loss from e
            raise
    return guarded


def make_poisoned_dispatch(at_call, *, shard: int = 0,
                           message: str | None = None):
    """A dispatch_wrap that injects device losses: the global dispatch
    counter (shared across supervisor attempts — the wrap is re-applied
    per attempt but the counter persists) raises a DEVICE_LOST-shaped
    RuntimeError at each call index in `at_call` (int or collection),
    so the full classify path is exercised. Consecutive indices take
    the ladder past same-mesh retry into shrink territory. The chaos
    harness (tools/chaos_soak.py --device-loss) and the elastic tests
    use this as the software stand-in for pulling a chip."""
    kills = {int(at_call)} if isinstance(at_call, int) \
        else {int(c) for c in at_call}
    state = {"n": 0}

    def wrap(fn):
        def poisoned(*args, **kwargs):
            n = state["n"]
            state["n"] = n + 1
            if n in kills:
                raise RuntimeError(
                    message or f"INTERNAL: DEVICE_LOST: device ordinal "
                    f"{shard} halted mid-dispatch (injected)")
            return fn(*args, **kwargs)
        return poisoned
    return wrap


# ---------------------------------------------------------------------
# cross-shard integrity sentinel
# ---------------------------------------------------------------------

@struct.dataclass
class SentinelState:
    """Sticky divergence latch — every leaf is a REPLICATED scalar
    (all updates below are pure functions of collectives), so the
    whole subtree pins through _replicate_scalars like the telemetry
    ring (a delta-psum would multiply the counts by the shard
    count)."""

    checks: jax.Array            # [] i64 barrier comparisons performed
    trip: jax.Array              # [] i32 sticky mismatch count
    shard: jax.Array             # [] i32 offending shard of FIRST trip
    tripped_at: jax.Array        # [] i64 wend of first trip (0 before)
    verified_through: jax.Array  # [] i64 last wend verified divergence-free
    digest: jax.Array            # [] u32 last barrier digest (pmax'd)

    @staticmethod
    def create() -> "SentinelState":
        return SentinelState(
            checks=jnp.zeros((), I64),
            trip=jnp.zeros((), I32),
            shard=jnp.full((), -1, I32),
            tripped_at=jnp.zeros((), I64),
            verified_through=jnp.zeros((), I64),
            digest=jnp.zeros((), U32),
        )


def attach_sentinel(sim):
    """Return `sim` with the integrity sentinel attached (no-op if one
    already is). Same opt-in contract as telemetry.attach: Sim.sentinel
    defaults to None and contributes no pytree leaves, so sentinel-off
    checkpoints and compiled programs are byte-identical."""
    if getattr(sim, "sentinel", None) is not None:
        return sim
    return sim.replace(sentinel=SentinelState.create())


_GOLDEN = np.uint32(2654435761)      # Knuth multiplicative hash
_PRIME = np.uint32(16777619)         # FNV prime


def _fold_u32(acc, x):
    """Fold a u32 array into the running u32 digest: a position-
    weighted wraparound sum (so permutations change the digest), mixed
    multiplicatively. Pure vector ops — one fused reduce per leaf."""
    n = x.size
    w = (jnp.arange(n, dtype=U32) * _GOLDEN + U32(1)).reshape(x.shape)
    s = jnp.sum(x * w, dtype=U32)
    return (acc * _PRIME) ^ (s + acc)


def _leaf_u32(leaf):
    """View any leaf's bits as u32 words (i64 splits into lo/hi)."""
    x = jnp.asarray(leaf)
    if x.dtype == jnp.bool_:
        return [x.astype(U32)]
    if jnp.issubdtype(x.dtype, jnp.floating):
        return [lax.bitcast_convert_type(x.astype(jnp.float32), U32)]
    if x.dtype.itemsize == 8:
        return [(x & 0xFFFFFFFF).astype(U32),
                ((x >> 32) & 0xFFFFFFFF).astype(U32)]
    return [x.astype(U32)]


def _replicated_digest_leaves(sim):
    """The leaves the per-barrier digest covers (module docstring §2):
    invariantly replicated at every window barrier. Returns a flat
    list of arrays."""
    from shadow_tpu.net.state import REPLICATED_FIELDS

    out = []
    net = getattr(sim, "net", None)
    if net is not None:
        for name in sorted(REPLICATED_FIELDS):
            if name == "ctr_path_packets":
                continue  # per-shard scatter-add deltas mid-chunk
            out.append(getattr(net, name))
    # telemetry/flow rings are deliberately NOT covered: their planes
    # are write-only observability buffers (drained host-side, never
    # read back by the simulation), and reducing the DUS-updated
    # planes every window drives the XLA CPU backend into a
    # pathological compile (module docstring §2)
    inject = getattr(sim, "inject", None)
    if inject is not None:
        # only the replicated cursors — the cumulative counters are
        # per-shard partials inside a chunk (parallel/shard.py)
        out.extend([inject.seq_floor, inject.horizon])
    caus = getattr(sim, "causality", None)
    if caus is not None:
        out.append(caus.adv_count)
    return out


def digest_replicated(sim, wend) -> jax.Array:
    """One u32 digest over the replicated-at-barrier leaves + wend.

    All leaf words concatenate into ONE flat u32 vector folded by a
    single position-weighted reduce — the weight vector is a folded
    compile-time constant, so the whole digest lowers to the per-leaf
    word converts plus one concat and one fused multiply-reduce. The
    op count per window stays ~flat in the leaf count; a per-leaf
    fold chain (~5 ops x ~40 words) costs measurable dispatch
    overhead per window on small-host CPU shapes."""
    words = []
    for word in _leaf_u32(jnp.asarray(wend, simtime.DTYPE)):
        words.append(word.reshape(-1))
    for leaf in _replicated_digest_leaves(sim):
        for word in _leaf_u32(leaf):
            words.append(word.reshape(-1))
    flat = jnp.concatenate(words) if len(words) > 1 else words[0]
    acc = jnp.asarray(0x811C9DC5, U32)   # FNV offset basis
    return _fold_u32(acc, flat)


def make_sentinel_fn(axis: str | None = None):
    """Build the engine's sentinel_fn(sim, wend) -> sim barrier hook
    (core/engine.step_window runs it after route_fn + the lane
    barrier). `axis` names the shard_map mesh axis; None compiles the
    single-shard identity reductions — the digest is still computed
    and `verified_through` still advances (serial runs get the same
    verified-state ledger), but pmax == pmin by construction so a
    serial run can never trip.

    Replication: every SentinelState update below is a pure function
    of collectives (pmax/pmin/psum) and the replicated wend, so the
    new state is identical on every shard — _replicate_scalars pins
    the subtree rather than delta-psumming it.

    When sim.sentinel is None the hook is a trace-time no-op: zero ops
    in the compiled program (the byte-identity contract)."""

    def sentinel_fn(sim, wend):
        st = getattr(sim, "sentinel", None)
        if st is None:
            return sim
        d = digest_replicated(sim, wend)
        wend64 = jnp.asarray(wend, simtime.DTYPE)
        if axis is None:
            dmax = dmin = d
            offender = jnp.full((), -1, I32)
        else:
            dmax = lax.pmax(d, axis)
            dmin = lax.pmin(d, axis)
            n = lax.psum(jnp.ones((), I32), axis)
            n_max = lax.psum((d == dmax).astype(I32), axis)
            # suspects = the minority digest's holders (ties blame the
            # dmax holders, deterministically); offender = the lowest
            # suspect shard index — replicated via the pmin
            minority_is_max = n_max * 2 <= n
            suspect = jnp.where(minority_is_max, d == dmax, d != dmax)
            idx = lax.axis_index(axis).astype(I32)
            offender = lax.pmin(jnp.where(suspect, idx, n), axis)
        mismatch = dmax != dmin
        first = mismatch & (st.trip == 0)
        trip = st.trip + mismatch.astype(I32)
        st = st.replace(
            checks=st.checks + 1,
            trip=trip,
            shard=jnp.where(first, offender, st.shard),
            tripped_at=jnp.where(first, wend64, st.tripped_at),
            # a barrier only extends the verified prefix while the
            # latch is clean — everything after a trip is suspect
            verified_through=jnp.where(
                trip == 0, wend64, st.verified_through),
            digest=dmax,
        )
        return sim.replace(sentinel=st)

    return sentinel_fn


def make_divergence_fault_fn(axis: str, *, shard: int, at_ns: int,
                             inner=None):
    """TEST/CHAOS helper: a fault_fn that corrupts ONE shard's replica
    of a replicated table (latency_ns[0, 0] += 1) from `at_ns` on —
    the software stand-in for a replicated-memory bit flip. Composes
    over an existing fault_fn via `inner`."""
    def fault_fn(sim, wend):
        if inner is not None:
            sim = inner(sim, wend)
        idx = lax.axis_index(axis).astype(I32)
        hit = (idx == shard) & (jnp.asarray(wend, simtime.DTYPE)
                                >= at_ns)
        lat = sim.net.latency_ns
        bumped = lat.at[0, 0].add(1)
        return sim.replace(net=sim.net.replace(
            latency_ns=jnp.where(hit, bumped, lat)))
    return fault_fn


# ---------------------------------------------------------------------
# shrink planning
# ---------------------------------------------------------------------

def next_pow2_down(n: int) -> int:
    """Largest power of two <= n (>= 1)."""
    if n < 1:
        raise ValueError(f"no pow2 <= {n}")
    return 1 << (int(n).bit_length() - 1)


def survivor_mesh(mesh, axis: str, lost_shard: int):
    """Build the next-pow2-down mesh over the devices that survive
    losing `lost_shard` (-1 = unknown: drop the LAST shard — any
    pow2-down subset works, the layout is global). Returns
    (new_mesh, new_shards) or (None, 1) when the survivors can only
    carry a serial run."""
    from jax.sharding import Mesh

    devices = list(np.asarray(mesh.devices).reshape(-1))
    n = len(devices)
    drop = lost_shard if 0 <= lost_shard < n else n - 1
    survivors = [d for i, d in enumerate(devices) if i != drop]
    new_n = next_pow2_down(max(len(survivors), 1))
    if new_n < 2:
        return None, 1
    return Mesh(np.array(survivors[:new_n]), (axis,)), new_n


def shard_digests(sim, shards: int, axis: str = "hosts") -> list[str]:
    """Host-side per-shard sha256 over the checkpoint's leaves, split
    the way sim_specs shards them: leading-H leaves contribute shard
    s's row block to digest s; replicated leaves contribute whole to
    every shard's digest. Shard s's digest is therefore invariant
    under re-partitioning onto any mesh that still assigns it those
    rows — the verified-state ledger's integrity stamp
    (utils/checkpoint.py)."""
    from jax.sharding import PartitionSpec as P

    from shadow_tpu.parallel.shard import sim_specs

    shards = max(int(shards), 1)
    hashes = [hashlib.sha256() for _ in range(shards)]
    specs = sim_specs(sim, axis)
    flat_vals = jax.tree_util.tree_flatten_with_path(sim)[0]
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    for (path, leaf), spec in zip(flat_vals, flat_specs):
        arr = np.asarray(leaf)
        name = jax.tree_util.keystr(path).encode()
        sharded = (isinstance(spec, P) and len(spec) > 0
                   and spec[0] is not None and arr.ndim > 0
                   and arr.shape[0] % shards == 0)
        if sharded:
            per = arr.shape[0] // shards
            for s in range(shards):
                hashes[s].update(name)
                hashes[s].update(
                    np.ascontiguousarray(arr[s * per:(s + 1) * per])
                    .tobytes())
        else:
            blob = np.ascontiguousarray(arr).tobytes()
            for h in hashes:
                h.update(name)
                h.update(blob)
    return [h.hexdigest() for h in hashes]


# ---------------------------------------------------------------------
# the supervisor's ladder knobs
# ---------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ElasticPolicy:
    """Knobs for the device-loss degradation ladder
    (faults/supervisor.py): retry same mesh → shrink to survivors →
    serial fallback, resuming from the last VERIFIED checkpoint.
    Ladder steps do NOT burn the failure retry budget (like
    escalation heals: the sim did nothing wrong)."""

    same_mesh_retries: int = 1       # re-dispatch on the full mesh first
    allow_shrink: bool = True        # next-pow2-down onto survivors
    allow_serial: bool = True        # final rung: mesh=None
    min_shards: int = 1              # stop shrinking below this
    max_losses: int = 8              # total DEVICE_LOST budget per run
    dispatch_deadline_s: float | None = None  # hung-dispatch overrun

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def sentinel_report(sim) -> dict | None:
    """Host-side summary of the sentinel latch for manifests/health.
    None when the sentinel is not attached."""
    st = getattr(sim, "sentinel", None)
    if st is None:
        return None
    return {
        "checks": int(np.asarray(st.checks)),
        "trips": int(np.asarray(st.trip)),
        "shard": int(np.asarray(st.shard)),
        "tripped_at_ns": int(np.asarray(st.tripped_at)),
        "verified_through_ns": int(np.asarray(st.verified_through)),
        "digest": int(np.asarray(st.digest)),
    }
